"""Repo-root pytest bootstrap.

Two jobs:

- put ``tools/`` on ``sys.path`` so the dabtlint package (static analysis +
  runtime lock-order witness) imports without an install step;
- under ``DABT_LOCK_WITNESS=1``, register the lock-order witness plugin
  BEFORE any project module is imported, so every project
  ``threading.Lock``/``RLock`` creation is wrapped and the whole run's
  acquisition-order graph is recorded (the session fails on a cycle, on
  same-class nesting, or on a Future resolved under a non-allowlisted lock
  — see docs/STATIC_ANALYSIS.md and tools/dabtlint/witness.py).
"""

import os
import sys

_ROOT = os.path.dirname(os.path.abspath(__file__))
_TOOLS = os.path.join(_ROOT, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


def pytest_configure(config):
    if os.environ.get("DABT_LOCK_WITNESS") == "1":
        from dabtlint.witness import WitnessPlugin

        if config.pluginmanager.has_plugin("dabt-lock-witness"):
            return
        config.pluginmanager.register(
            WitnessPlugin(
                os.path.join(_ROOT, "django_assistant_bot_tpu"),
                baseline_path=os.path.join(_TOOLS, "dabtlint", "baseline.json"),
            ),
            "dabt-lock-witness",
        )
