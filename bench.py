"""Headline benchmark: embedding docs/sec/chip (BASELINE.md config 1).

Measures the jit-compiled TPU encoder (ruBert-base geometry, the reference
gpu_service's shipped embedder — reference: gpu_service/models.py:1-3) against the
reference's serving path re-created with torch/transformers on CPU, which loops one
text at a time exactly like ``TransformersEmbedder`` does (reference:
assistant/ai/embedders/transformers.py:15-29 — unbatched, O(n) forwards).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

BATCH = int(os.environ.get("BENCH_BATCH", "64"))
SEQ = int(os.environ.get("BENCH_SEQ", "128"))
ITERS = int(os.environ.get("BENCH_ITERS", "20"))
BASELINE_ITERS = int(os.environ.get("BENCH_BASELINE_ITERS", "2"))


def bench_tpu() -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from django_assistant_bot_tpu.models import EncoderConfig, encoder

    cfg = EncoderConfig(dtype=jnp.bfloat16)  # ruBert-base geometry: 12L/768E/12H
    params = encoder.init(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (BATCH, SEQ)), jnp.int32)
    mask = jnp.ones((BATCH, SEQ), jnp.int32)

    encode = jax.jit(lambda p, i, m: encoder.encode(p, cfg, i, m, normalize=True))
    np.asarray(encode(params, ids, mask))  # compile + warm (fetch forces completion)
    np.asarray(encode(params, ids, mask))

    def run(iters: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = encode(params, ids, mask)
        np.asarray(out)  # one fetch; device executed all iters serially before it
        return time.perf_counter() - t0

    # Two-run slope: under a remote-RPC device tunnel, a fixed round-trip latency
    # rides on every timed region; (t(2N) - t(N)) / N cancels it.
    t1 = run(ITERS)
    t2 = run(2 * ITERS)
    per_iter = max((t2 - t1) / ITERS, 1e-9)
    # encode is an unsharded single-device jit: exactly one chip does the work,
    # regardless of how many are visible.
    return BATCH / per_iter


def bench_torch_cpu() -> float:
    """Reference serving path: per-text torch forward loop (unbatched), CPU."""
    import torch
    from transformers import BertConfig, BertModel

    cfg = BertConfig(
        vocab_size=119_547,
        hidden_size=768,
        num_hidden_layers=12,
        num_attention_heads=12,
        intermediate_size=3072,
    )
    model = BertModel(cfg)
    model.eval()
    ids = torch.randint(1, cfg.vocab_size, (BATCH, SEQ))
    with torch.no_grad():
        model(input_ids=ids[:1])  # warm
        t0 = time.perf_counter()
        for _ in range(BASELINE_ITERS):
            for i in range(BATCH):
                out = model(input_ids=ids[i : i + 1])
                out.last_hidden_state.mean(dim=1)
        dt = time.perf_counter() - t0
    return (BATCH * BASELINE_ITERS) / dt


def main() -> None:
    value = bench_tpu()
    try:
        baseline = bench_torch_cpu()
    except Exception:
        baseline = None
    print(
        json.dumps(
            {
                "metric": "embedding_docs_per_sec_per_chip",
                "value": round(value, 2),
                "unit": "docs/s/chip",
                "vs_baseline": round(value / baseline, 2) if baseline else None,
            }
        )
    )


if __name__ == "__main__":
    main()
