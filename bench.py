"""Benchmarks for BASELINE.md configs 1-3 on the local accelerator.

Headline (the BASELINE.json north star): **end-to-end RAG req/s + p50 TTFT** —
query embedding over HTTP -> exact-KNN top-k -> chat generation over HTTP, i.e. the
full path the reference runs as embed (gpu_service) -> pgvector -> dialog
(gpu_service).  Also measured:

- config 1: embedding docs/s/chip (ruBert-base geometry, batched jit encode) vs the
  reference's unbatched per-text torch loop (assistant/ai/embedders/transformers.py:15-29)
- config 2: continuous-batching decode tokens/s/chip + p50/p99 TTFT under
  concurrency, vs the reference's single-stream torch generate
  (assistant/ai/providers/transformers.py:35-94)

The decoder uses a Llama-3-1B-class geometry (random weights — throughput is
weight-value independent) so the bench fits one chip; the serving path (engine,
chunked prefill, lookahead decode pipeline, HTTP contract) is the production path.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} for the headline,
with the other configs under "extras".
"""

from __future__ import annotations

import asyncio
import json
import math
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SMALL = bool(int(os.environ.get("BENCH_SMALL", "0")))  # CI/dev smoke mode

# Total wall-clock budget for the whole bench (real mode).  The r4 record was
# EMPTY (rc 124, no stdout) because the run assumed hours of headroom and
# printed its record only at the very end; the budget keeps the run comfortably
# inside the driver's cap, and the record-so-far is re-emitted after every
# section so even a hard kill leaves a parseable final line (VERDICT r4 #1).
BUDGET_S = int(os.environ.get("BENCH_BUDGET_S", "2400"))

# config 1 (embedding)
EMB_BATCH = int(os.environ.get("BENCH_BATCH", "64"))
EMB_SEQ = int(os.environ.get("BENCH_SEQ", "128"))
EMB_ITERS = int(os.environ.get("BENCH_ITERS", "20"))
BASELINE_ITERS = int(os.environ.get("BENCH_BASELINE_ITERS", "2"))

# config 2 (decode) / config 3 (RAG)
DECODE_REQUESTS = int(os.environ.get("BENCH_DECODE_REQUESTS", "32"))
DECODE_NEW_TOKENS = int(os.environ.get("BENCH_DECODE_NEW_TOKENS", "128"))
DECODE_PROMPT_LEN = int(os.environ.get("BENCH_DECODE_PROMPT_LEN", "120"))
# concurrency matches the engine slot count: 8 -> 16 measured 2.8 -> 5.8 req/s
# (r3); 16 -> 32 measured 5.7 -> 9.2 req/s same-session (r5 — the ledger's
# dispatch-floor amortization applied to the headline)
RAG_REQUESTS = int(os.environ.get("BENCH_RAG_REQUESTS", "64"))
RAG_CONCURRENCY = int(os.environ.get("BENCH_RAG_CONCURRENCY", "32"))
RAG_NEW_TOKENS = int(os.environ.get("BENCH_RAG_NEW_TOKENS", "32"))
# headline composes configs 3+4: the KNN hop runs at CORPUS SCALE (1M vectors,
# ~1.5 GB bf16 on device next to both models) through the real HTTP path
RAG_CORPUS = int(os.environ.get("BENCH_RAG_CORPUS", "1000000"))
# engine slot count for the core decode/RAG engine (the r5 ledger found a
# ~7.4 ms dispatch floor at 1B geometry — slots amortize it; 32 is the
# measured knee, 64 regresses)
SLOTS = int(os.environ.get("BENCH_SLOTS", "32"))
BASELINE_DECODE_TOKENS = int(os.environ.get("BENCH_BASELINE_DECODE_TOKENS", "6"))

# config 4 (bulk ingestion + KNN scale)
INGEST_DOCS = int(os.environ.get("BENCH_INGEST_DOCS", "10000"))
KNN_VECTORS = int(os.environ.get("BENCH_KNN_VECTORS", "1000000"))
KNN_QUERIES = int(os.environ.get("BENCH_KNN_QUERIES", "20"))


def _decoder_cfg():
    """Llama-3-1B-class geometry: full 128k vocab, GQA 32/8 heads, 16 layers."""
    import jax.numpy as jnp

    from django_assistant_bot_tpu.models import DecoderConfig

    if SMALL:
        return DecoderConfig.tiny()
    return DecoderConfig(
        vocab_size=128_256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        max_seq_len=1024,
        dtype=jnp.bfloat16,
    )


def _moe_cfg(num_layers=8):
    """Mixtral-class MoE on one chip: 2048 hidden / 8192 ffn x 8 experts,
    top-2 routing, int8 experts (weights synthesized on device).  Per-layer
    expert geometry is half Mixtral-8x7B's (4096/14336) — the largest that
    fits one 16 GB chip with 8 experts resident."""
    import jax.numpy as jnp

    from django_assistant_bot_tpu.models import DecoderConfig

    if SMALL:
        return DecoderConfig.tiny(num_experts=4)
    return DecoderConfig(
        vocab_size=32_000,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=num_layers,
        num_heads=16,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=1024,
        rope_theta=1e6,
        num_experts=8,
        experts_per_token=2,
        dtype=jnp.bfloat16,
    )


def _moe_cfg_mixtral(num_layers=4):
    """TRUE Mixtral-8x7B per-layer expert geometry (4096 hidden / 14336 ffn x 8
    experts, top-2), depth-truncated to fit one chip: ~1.4 GB int8 per layer of
    experts — 8 layers (~11.5 GB resident, a quarter of the full model's depth)
    is the deepest measured fit.  The honest config-5 attempt (VERDICT r4 weak
    #4) — `moe_geometry` in the record says exactly what ran."""
    import jax.numpy as jnp

    from django_assistant_bot_tpu.models import DecoderConfig

    return DecoderConfig(
        vocab_size=32_000,
        hidden_size=4096,
        intermediate_size=14_336,
        num_layers=num_layers,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=1024,
        rope_theta=1e6,
        num_experts=8,
        experts_per_token=2,
        dtype=jnp.bfloat16,
    )


def _encoder_cfg():
    import jax.numpy as jnp

    from django_assistant_bot_tpu.models import EncoderConfig

    if SMALL:
        return EncoderConfig.tiny()
    return EncoderConfig(dtype=jnp.bfloat16)  # ruBert-base geometry: 12L/768E/12H


def bench_embedding() -> float:
    """Config 1: batched jit encode, docs/s/chip (two-run slope cancels RPC cost)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from django_assistant_bot_tpu.models import encoder

    cfg = _encoder_cfg()
    params = encoder.init(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    seq = min(EMB_SEQ, cfg.max_position_embeddings)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (EMB_BATCH, seq)), jnp.int32)
    mask = jnp.ones((EMB_BATCH, seq), jnp.int32)

    encode = jax.jit(lambda p, i, m: encoder.encode(p, cfg, i, m, normalize=True))
    np.asarray(encode(params, ids, mask))  # compile + warm
    np.asarray(encode(params, ids, mask))

    def run(iters: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = encode(params, ids, mask)
        np.asarray(out)  # one fetch; device executed all iters serially before it
        return time.perf_counter() - t0

    t1 = run(EMB_ITERS)
    t2 = run(2 * EMB_ITERS)
    per_iter = max((t2 - t1) / EMB_ITERS, 1e-9)
    return EMB_BATCH / per_iter


def _decode_bucket() -> int:
    """The prefill bucket the decode benches actually exercise — computed with
    the engine's own bucket picker so it can't diverge from config 2."""
    from django_assistant_bot_tpu.serving.engine import pick_bucket

    return pick_bucket(DECODE_PROMPT_LEN, (128, 512), 512)


def _build_gen_engine(
    cfg=None,
    quantize=None,
    buckets=(128, 512),
    prefix_cache=0,
    kv_dtype=None,
    max_slots=None,
    speculative=0,
    scheduler=None,
    obs=True,
    decode_steps=None,
    chunk_size=None,
    prefill_piggyback=True,
    attn_fp8=False,
    spec_width=4,
    spec_probe_every=64,
):
    max_slots = max_slots or SLOTS
    import jax

    from django_assistant_bot_tpu.models import llama
    from django_assistant_bot_tpu.parallel import get_mesh, shard_pytree
    from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine

    cfg = cfg or _decoder_cfg()
    if quantize == "int8_device":
        # int8 weights synthesized directly in HBM — no host staging, no
        # host-side quantization pass (matters for multi-GB geometries)
        params = llama.init_int8(cfg, jax.random.PRNGKey(0))
    elif quantize == "int8_device_full":
        # embed/head int8 too: kills the 2-byte lm_head stream in decode
        params = llama.init_int8(cfg, jax.random.PRNGKey(0), quantize_embed=True)
    elif quantize == "int4_device":
        # grouped int4, packed two-per-byte, synthesized in HBM — 0.5
        # bytes/weight on the decode read path (ops/quant.py QTensor4)
        params = llama.init_int4(cfg, jax.random.PRNGKey(0))
    else:
        params = llama.init(cfg, jax.random.PRNGKey(0))
    if quantize in ("int8", "int4"):
        from django_assistant_bot_tpu.ops.quant import quantize_decoder_params

        params = quantize_decoder_params(params, fmt=quantize)
    mesh = get_mesh()
    with mesh:
        params = shard_pytree(params, llama.logical_axes(cfg), mesh)
    eng = GenerationEngine(
        cfg,
        params,
        ByteTokenizer(),
        max_slots=max_slots,  # default 16 = bench concurrency: one decode wave
        max_seq_len=min(1024, cfg.max_seq_len),
        prefill_buckets=buckets,
        chunk_size=chunk_size or buckets[-1],
        mesh=mesh,
        prefix_cache_size=prefix_cache,
        kv_cache_dtype=kv_dtype,
        speculative=speculative,
        spec_width=spec_width,
        spec_probe_every=spec_probe_every,
        scheduler=scheduler,
        obs=obs,
        decode_steps=decode_steps,
        prefill_piggyback=prefill_piggyback,
        attn_fp8=attn_fp8,
    )
    # compile every (batch, seq) prefill shape BEFORE measuring; the decode-only
    # engines are built with just the bucket their prompts hit (same bucket the
    # config-2 engine picks for the same prompts, so the configs stay comparable)
    eng.warmup()
    eng.start()
    return eng, cfg


def bench_decode(eng) -> dict:
    """Config 2: continuous-batching decode throughput + TTFT under concurrency.

    Also reports achieved HBM weight traffic (every decode step re-reads all
    weights once for the whole batch — a hard lower bound that excludes
    KV/activation traffic; v5e HBM peak ~819 GB/s) and decode MFU
    (~2 FLOPs/param/token against the v5e bf16 peak ~197 TFLOP/s).
    """
    import jax
    import numpy as np

    rng = np.random.default_rng(1)

    def fire(n_req, n_new):
        prompts = [
            rng.integers(1, 255, DECODE_PROMPT_LEN).tolist() for _ in range(n_req)
        ]
        t0 = time.perf_counter()
        futs = [
            eng.submit(p, max_tokens=n_new, temperature=0.8) for p in prompts
        ]
        results = [f.result(timeout=1200) for f in futs]
        wall = time.perf_counter() - t0
        return results, wall

    # shapes are pre-compiled by engine.warmup(); this warms the loop/sampling
    fire(2, 4)
    results, wall = fire(DECODE_REQUESTS, DECODE_NEW_TOKENS)
    total_new = sum(r.completion_tokens for r in results)
    ttfts = sorted(r.ttft_s for r in results)
    p99_idx = min(len(ttfts) - 1, max(0, math.ceil(0.99 * len(ttfts)) - 1))
    from django_assistant_bot_tpu.ops.quant import num_weights

    leaves = jax.tree.leaves(eng.params)
    param_bytes = sum(l.nbytes for l in leaves)
    # packed formats count UNPACKED weights (QTensor4 holds two per byte) and
    # scales are excluded — the honest MFU numerator (2 FLOPs/weight/token)
    n_params = num_weights(eng.params)
    tok_s = total_new / wall
    # Pure on-device step cost (no prefill wave, no host loop): the roofline
    # denominator.  steady tok/s = slots/step; HBM floor counts one full weight
    # read per step (KV/activation traffic excluded -> a hard lower bound).
    # fill_len pins the probe at this bench's own context fill — with the
    # length-bucketed decode read, an empty-cache probe would read almost no
    # KV and overstate the steady rate
    step_s = eng.probe_decode(iters=12, fill_len=DECODE_PROMPT_LEN + DECODE_NEW_TOKENS)
    steady_tok_s = eng.max_slots / step_s
    stats = eng.tick_stats()
    # Reference point: a chained convert+reduce stream over the SAME weight
    # set (serialized through the scalar carry — unchained dispatches overlap
    # server-side under the tunnel and report fiction).  NOT a ceiling: a
    # reduction is itself less bandwidth-efficient than the matmul pipeline
    # (measured runs have the decode step outrunning this probe), and the
    # shared chip's effective rate moves run to run — so it is recorded as a
    # probe alongside the achieved number, with no utilization% derived.
    import jax.numpy as jnp

    big = [l for l in leaves if l.nbytes >= (1 << 20)]
    big_bytes = sum(l.nbytes for l in big)
    stream = jax.jit(
        lambda c, ls: c + sum(jnp.sum(l.astype(jnp.float32)) for l in ls)
    )
    acc = jnp.zeros(())
    acc = stream(acc, big)
    jax.block_until_ready(acc)
    t0 = time.perf_counter()
    for _ in range(6):
        acc = stream(acc, big)
    jax.block_until_ready(acc)
    ceiling_gbps = big_bytes * 6 / (time.perf_counter() - t0) / 1e9
    return {
        "decode_tokens_per_s_per_chip": round(tok_s, 2),
        "decode_p50_ttft_s": round(statistics.median(ttfts), 4),
        "decode_p99_ttft_s": round(ttfts[p99_idx], 4),
        "decode_concurrency": DECODE_REQUESTS,
        "decode_new_tokens": DECODE_NEW_TOKENS,
        "decode_hbm_gbps_min": round(tok_s / DECODE_REQUESTS * param_bytes / 1e9, 1),
        "decode_mfu_pct": round(tok_s * 2 * n_params / 197e12 * 100, 2),
        "decode_pure_step_ms": round(step_s * 1e3, 3),
        "decode_steady_tokens_per_s": round(steady_tok_s, 2),
        "decode_steady_hbm_gbps": round(param_bytes / step_s / 1e9, 1),
        # byte-ledger roofline at the steady rate: MFU as a FRACTION (the
        # compact record's per-arm keys — prose percentages drift) and the
        # HBM GB/s the ledger's per-step bytes imply at the measured step
        # time (weights + head + the page/chunk-rounded KV read)
        "decode_mfu_frac": round(steady_tok_s * 2 * n_params / 197e12, 6),
        "decode_hbm_gbps": round(
            decode_byte_ledger(
                eng, fill_len=DECODE_PROMPT_LEN + DECODE_NEW_TOKENS
            )["total_gb_per_step"]
            / step_s,
            2,
        ),
        "decode_steps": eng.decode_steps,
        "decode_upload_overlap_frac": stats.get("upload_overlap_frac", 0.0),
        "decode_weight_bits": eng.weight_bits,
        "decode_hbm_stream_probe_gbps": round(ceiling_gbps, 1),
        "decode_tick_issue_ms": stats["issue_ms"],
        "decode_tick_block_ms": stats["block_ms"],
        # fraction of the allocated KV cache the decode attention actually
        # read (< 1 = the length-bucketed read is skipping invalid positions)
        "decode_kv_read_frac": stats["kv_read_frac"],
        "decode_kv_chunk": eng.decode_kv_chunk or 0,
    }


def bench_rag(gen_engine) -> dict:
    """Config 3 (headline): embed -> KNN -> generate over the real HTTP path."""
    import numpy as np

    from aiohttp.test_utils import TestClient, TestServer

    from django_assistant_bot_tpu.models import encoder
    from django_assistant_bot_tpu.serving import EmbeddingEngine, ByteTokenizer
    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec
    from django_assistant_bot_tpu.serving.server import create_app
    from django_assistant_bot_tpu.storage.knn import AsyncSearcher, VectorIndex

    import jax

    from django_assistant_bot_tpu.parallel import get_mesh, shard_pytree

    ecfg = _encoder_cfg()
    eparams = encoder.init(ecfg, jax.random.PRNGKey(1))
    mesh = get_mesh()
    with mesh:
        eparams = shard_pytree(eparams, encoder.logical_axes(ecfg), mesh)
    emb_eng = EmbeddingEngine(
        ecfg, eparams, ByteTokenizer(), max_batch=32, normalize=True, mesh=mesh
    ).start()

    registry = ModelRegistry(mesh=mesh)
    registry.specs = {
        "bench-emb": ModelSpec(name="bench-emb", kind="encoder"),
        "bench-chat": ModelSpec(name="bench-chat", kind="decoder"),
    }
    registry.embedders["bench-emb"] = emb_eng
    registry.generators["bench-chat"] = gen_engine

    # corpus: random docs, embeddings pre-computed (ingestion is config 4).
    # Built in slices to bound host RAM; doc text is generated on demand (a
    # materialized dict would hold RAG_CORPUS strings for 3 reads each).
    rng = np.random.default_rng(2)
    index = VectorIndex(ecfg.hidden_size)
    n = RAG_CORPUS if not SMALL else min(RAG_CORPUS, 10_000)
    step = 200_000
    for lo in range(0, n, step):
        hi = min(lo + step, n)
        index.add(
            range(lo, hi),
            rng.normal(size=(hi - lo, ecfg.hidden_size)).astype(np.float32),
        )

    def doc_text(i: int) -> str:
        return f"Document {i}: " + " ".join(f"fact{i}-{j}" for j in range(30))

    # pay the host->HBM corpus transfer + kernel compiles BEFORE timing starts
    # (blocks until resident — the serving-path warmup discipline, knn.py).
    # Only the shapes this bench's searches hit: k=3 and the coalesced query
    # batch sizes — every extra (q, k) bucket is another ~1-2 min kernel
    # compile at 1M x 768 through the remote compile service.
    t0 = time.perf_counter()
    index.warmup(ks=(3,), q_rows=(1, RAG_CONCURRENCY))
    rag_index_warmup_s = time.perf_counter() - t0

    searcher = AsyncSearcher(index)

    async def one_dialog(client, qid: int) -> list:
        """A 2-turn RAG dialog — the reference's real request shape: every turn
        re-sends system + packed context + history in full
        (assistant/bot/services/context_service/steps/final_prompt.py:14).
        Turn 2's prompt extends turn 1's, so the engine's prefix KV cache
        skips re-prefilling the context block."""
        q = f"benchmark question number {qid} about topic {qid % 7}?"
        r = await client.post(
            "/embeddings/", json={"model": "bench-emb", "texts": [q]}
        )
        emb = (await r.json())["embeddings"][0]
        # the real search service coalesces concurrent KNN queries into one
        # batched dispatch (rag/services/search_service.py) — same here
        top = await searcher.search(np.asarray(emb, np.float32), 3)
        context = "\n".join(doc_text(i)[:200] for i, _ in top)
        messages = [
            {"role": "system", "content": "Answer from context:\n" + context},
            {"role": "user", "content": q},
        ]
        usages = []
        for follow_up in (None, "what else does the context say?"):
            if follow_up is not None:
                messages.append({"role": "user", "content": follow_up})
            r = await client.post(
                "/dialog/",
                json={
                    "model": "bench-chat",
                    "messages": messages,
                    "max_tokens": RAG_NEW_TOKENS,
                    "json_format": False,
                },
            )
            data = await r.json()
            usages.append(data["response"]["usage"])
            messages.append(
                {"role": "assistant", "content": data["response"]["result"]}
            )
        return usages

    async def drive():
        loop = asyncio.get_event_loop()
        client = TestClient(TestServer(create_app(registry)), loop=loop)
        await client.start_server()
        try:
            # prefill shapes are pre-compiled by engine.warmup(); this warms the
            # HTTP/embed/KNN path end-to-end
            await one_dialog(client, 999)
            sem = asyncio.Semaphore(RAG_CONCURRENCY)

            async def guarded(i):
                async with sem:
                    return await one_dialog(client, i)

            n_dialogs = max(1, RAG_REQUESTS // 2)
            t0 = time.perf_counter()
            per_dialog = await asyncio.gather(
                *(guarded(i) for i in range(n_dialogs))
            )
            wall = time.perf_counter() - t0
        finally:
            await client.close()
        return per_dialog, wall

    try:
        per_dialog, wall = asyncio.new_event_loop().run_until_complete(drive())
    finally:
        emb_eng.stop()
    turn1 = sorted(d[0]["ttft_s"] for d in per_dialog)
    turn2 = sorted(d[1]["ttft_s"] for d in per_dialog)
    n_turns = sum(len(d) for d in per_dialog)
    return {
        "rag_req_per_s": round(n_turns / wall, 3),
        "rag_p50_ttft_s": round(statistics.median(turn1 + turn2), 4),
        # turn 2 re-sends turn 1's whole prompt + answer; the prefix KV cache
        # skips its recompute, so this TTFT isolates the prefix-cache win
        "rag_turn2_p50_ttft_s": round(statistics.median(turn2), 4),
        "rag_concurrency": RAG_CONCURRENCY,
        "rag_corpus_vectors": n,
        "rag_new_tokens": RAG_NEW_TOKENS,
        "rag_index_warmup_s": round(rag_index_warmup_s, 3),
        "rag_prefix_hits": gen_engine.prefix_hits,
        "rag_prefix_misses": gen_engine.prefix_misses,
    }


def _error_tail(stderr: str, max_chars: int = 400) -> str:
    """The diagnosis-bearing slice of a failed child's stderr.

    Root-cause markers (OOM, XLA runtime faults, timeouts) win over the
    generic wrapper the failure surfaces as ("generation engine failure" is
    the engine's _fail_all re-raise, not the diagnosis)."""
    lines = [l for l in (stderr or "").strip().splitlines() if l.strip()]
    for marker in ("RESOURCE_EXHAUSTED", "XlaRuntimeError", "DEADLINE", "INTERNAL:"):
        for line in reversed(lines):
            if marker in line:
                return line.strip()[:max_chars]
    for line in reversed(lines):
        if "Error" in line or "Exception" in line:
            return line.strip()[:max_chars]
    return " | ".join(lines[-3:])[:max_chars] if lines else "no stderr"


def _subprocess_bench(snippet: str, timeout_s: int = 1800):
    """Run a bench snippet in a FRESH python process and parse its final JSON
    line.  Multi-GB model builds on the shared chip can fail on fragmentation,
    and a failed build poisons the parent's device session (deallocation is
    async through the remote tunnel, so retries see the dead attempt's memory
    for minutes).  A child process's exit reliably frees its server-side
    allocations, so each geometry attempt gets a clean slate.

    Returns ``(result_dict_or_None, error_tail)`` — failures carry WHY (the
    child's terminal stderr line: OOM vs crash vs timeout), so the published
    bench record never says just "failed"."""
    import subprocess

    code = (
        "import sys, os\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        # every section child shares the persistent XLA compile cache: kernel
        # compiles (the dominant cold cost at 1M-KNN/8B scale) are paid once
        # across sections AND runs (VERDICT r5 #6)
        "from django_assistant_bot_tpu.utils.compile_cache import "
        "enable_persistent_compile_cache\n"
        "enable_persistent_compile_cache()\n"
        + snippet
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s}s"
    for line in reversed((p.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), ""
            except Exception:
                continue
    return None, f"rc={p.returncode}: {_error_tail(p.stderr)}"


def _flagship_8b_cfg(max_seq_len=512):
    """True Llama-3-8B geometry (32L/4096E/14336F/32H/8KV/128k vocab) — the
    model class the reference serves via Ollama llama3.1:8b (.env.example:12);
    int8 weight-only (~9 GB) fits one 16 GB chip."""
    import jax.numpy as jnp

    from django_assistant_bot_tpu.models import DecoderConfig

    return DecoderConfig(
        vocab_size=128_256,
        hidden_size=4096,
        intermediate_size=14_336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=max_seq_len,
        dtype=jnp.bfloat16,
    )


_8B_SNIPPET = """
import json, time
import numpy as np
import jax
import bench
from django_assistant_bot_tpu.models import llama
from django_assistant_bot_tpu.parallel import get_mesh, shard_pytree
from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine

slots = {slots}
tag = {tag!r}
cfg = bench._flagship_8b_cfg(max_seq_len={seq})
# int8 embed/head too: ~1 GB less HBM — headroom against other tenants'
# allocations on the shared chip (the r3/r4 OOMs struck MID-DECODE while a
# 12 GiB probe succeeded minutes earlier)
params = llama.init_int8(cfg, jax.random.PRNGKey(0), quantize_embed=True)
pb = sum(l.nbytes for l in jax.tree.leaves(params))
n_params = sum(l.size for l in jax.tree.leaves(params))
mesh = get_mesh()
with mesh:
    params = shard_pytree(params, llama.logical_axes(cfg), mesh)
eng = GenerationEngine(
    cfg, params, ByteTokenizer(), max_slots=slots, max_seq_len=cfg.max_seq_len,
    prefill_buckets=(bench._decode_bucket(),), chunk_size=bench._decode_bucket(),
    mesh=mesh, lookahead=2, burst=1, prefix_cache_size=0,
    kv_cache_dtype={kv!r},
)
eng.warmup()
eng.start()
try:
    rng = np.random.default_rng(5)

    def fire(n_req, n_new):
        prompts = [rng.integers(1, 255, bench.DECODE_PROMPT_LEN).tolist() for _ in range(n_req)]
        t0 = time.perf_counter()
        futs = [eng.submit(p, max_tokens=n_new, temperature=0.8) for p in prompts]
        results = [f.result(timeout=1500) for f in futs]
        return results, time.perf_counter() - t0

    fire(min(2, slots), 4)
    results, wall = fire(slots, bench.DECODE_NEW_TOKENS)
    fill = bench.DECODE_PROMPT_LEN + bench.DECODE_NEW_TOKENS
    # the ledger + a fill-pinned probe, pointed at THIS config (VERDICT r5 #2:
    # the 8B fp8-KV arm ran at 150 GB/s vs 227 without fp8 and no byte
    # accounting existed for it) — step time at the bench's own context fill,
    # bytes split into weights/head/KV-read-vs-allocated
    step_s = eng.probe_decode(iters=8, fill_len=fill)
    ledger = bench.decode_byte_ledger(eng, fill_len=fill)
    kv_frac = eng.tick_stats()["kv_read_frac"]
finally:
    eng.stop()
total_new = sum(r.completion_tokens for r in results)
ttfts = sorted(r.ttft_s for r in results)
tok_s = total_new / wall
print(json.dumps({{
    "decode_8b%s_tokens_per_s_per_chip" % tag: round(tok_s, 2),
    "decode_8b%s_p50_ttft_s" % tag: round(ttfts[len(ttfts) // 2], 4),
    "decode_8b%s_concurrency" % tag: slots,
    "decode_8b_param_gb": round(pb / 1e9, 2),
    "decode_8b%s_hbm_gbps_min" % tag: round(tok_s / slots * pb / 1e9, 1),
    "decode_8b%s_mfu_pct" % tag: round(tok_s * 2 * n_params / 197e12 * 100, 2),
    "decode_8b%s_pure_step_ms" % tag: round(step_s * 1e3, 3),
    "decode_8b%s_steady_tokens_per_s" % tag: round(slots / step_s, 2),
    "decode_8b%s_steady_gbps" % tag: round(
        ledger["total_gb_per_step"] / step_s, 1),
    "decode_8b%s_ledger" % tag: ledger,
    "decode_8b%s_kv_read_frac" % tag: kv_frac,
}}))
"""


_MOE_SNIPPET = """
import json
import bench

cfg = bench.{cfg_fn}(num_layers={layers})
eng, cfg = bench._build_gen_engine(cfg, quantize="int8_device",
                                   buckets=(bench._decode_bucket(),))
try:
    moe = bench.bench_decode(eng)
finally:
    eng.stop()
print(json.dumps({{
    "moe_decode_tokens_per_s_per_chip": moe["decode_tokens_per_s_per_chip"],
    "moe_decode_p50_ttft_s": moe["decode_p50_ttft_s"],
    "moe_decode_hbm_gbps_min": moe["decode_hbm_gbps_min"],
    "moe_geometry": "%dL/%dE/%dFx%dexperts-int8" % (
        cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.num_experts),
}}))
"""


# The continuous-batching serving math WITHOUT the engine wrapper: one wave of
# `slots` prompts prefills together, then chained (decode_step + sample)
# dispatches stream tokens with the dispatch queue as the lookahead pipeline.
# The engine's fused tick program set has OOM'd on the shared chip at 8B (its
# program-set load needs more headroom than the chip reliably has — recorded
# as decode_8b_engine_error); this path is the same per-token math as the
# engine steady state, one program per stage, and is what the number means.
_8B_MANUAL_SNIPPET = """
import json, time
import numpy as np
import jax, jax.numpy as jnp
import bench
from django_assistant_bot_tpu.models import llama
from django_assistant_bot_tpu.ops.sampling import sample_logits

slots = {slots}
cfg = bench._flagship_8b_cfg(max_seq_len={seq})
params = llama.init_int8(cfg, jax.random.PRNGKey(0), quantize_embed=True)
jax.block_until_ready(params)
pb = sum(l.nbytes for l in jax.tree.leaves(params))
n_params = sum(l.size for l in jax.tree.leaves(params))

B = slots
prompt_len = bench.DECODE_PROMPT_LEN
bucket = 128
rng = np.random.default_rng(5)
ids = np.zeros((B, bucket), np.int32)
ids[:, :prompt_len] = rng.integers(1, 255, (B, prompt_len))
lengths = np.full((B,), prompt_len, np.int32)
temps = jnp.full((B,), 0.8); tps = jnp.full((B,), 0.95)

pf = jax.jit(lambda p, i, l: llama.prefill(p, cfg, i, l))
ins = jax.jit(llama.insert_sequences, donate_argnums=(0,))
samp = jax.jit(lambda l, r: sample_logits(l, r, temperature=temps, top_k=50, top_p=tps))
step = jax.jit(lambda p, t, c: llama.decode_step(p, cfg, t, c), donate_argnums=(2,))

# build + compile everything once (warmup wave)
cache = llama.init_cache(cfg, B, cfg.max_seq_len)
logits, ks, vs = pf(params, jnp.asarray(ids), jnp.asarray(lengths))
cache = ins(cache, ks, vs, jnp.asarray(lengths), jnp.asarray(np.arange(B, dtype=np.int32)))
toks = samp(logits, jax.random.key(0))
lg, cache = step(params, toks, cache)
jax.block_until_ready(lg)

# measured wave: fresh prefill (TTFT) + n_new chained decode steps
n_new = bench.DECODE_NEW_TOKENS
t0 = time.perf_counter()
logits, ks, vs = pf(params, jnp.asarray(ids), jnp.asarray(lengths))
cache = ins(cache, ks, vs, jnp.asarray(lengths), jnp.asarray(np.arange(B, dtype=np.int32)))
toks = samp(logits, jax.random.key(1))
jax.block_until_ready(toks)
ttft = time.perf_counter() - t0
t1 = time.perf_counter()
for i in range(n_new - 1):
    lg, cache = step(params, toks, cache)
    toks = samp(lg, jax.random.key(i + 2))
jax.block_until_ready(toks)
decode_wall = time.perf_counter() - t1
step_s = decode_wall / (n_new - 1)
tok_s = B * n_new / (ttft + decode_wall)
print(json.dumps({{
    "decode_8b_int8_tokens_per_s_per_chip": round(tok_s, 2),
    "decode_8b_int8_steady_tokens_per_s": round(B / step_s, 2),
    "decode_8b_int8_p50_ttft_s": round(ttft, 4),
    "decode_8b_concurrency": B,
    "decode_8b_new_tokens": n_new,
    "decode_8b_param_gb": round(pb / 1e9, 2),
    "decode_8b_hbm_gbps_min": round(pb / step_s / 1e9, 1),
    "decode_8b_mfu_pct": round((B / step_s) * 2 * n_params / 197e12 * 100, 2),
    "decode_8b_path": "staged-dispatch (prefill/insert/sample/step as separate programs)",
}}))
"""


def bench_8b(time_left=None) -> dict:
    """Config 2 at true flagship geometry: 8B-class decode, int8 weight-only
    including embed/head (~8 GB total).

    Weights are synthesized directly on device (llama.init_int8) — staging a
    host-side 8B init through a remote tunnel would take minutes.  Each
    attempt runs in a fresh subprocess (_subprocess_bench) so an OOM on the
    shared chip can't poison the next attempt.  r4's unbounded walk-down
    (probe + 2 engine + 3 manual attempts + fp8, each with an hours-scale
    timeout) helped blow the driver cap; here every attempt is budget-capped
    via ``time_left`` (a seconds-remaining callable): the r4-proven primary
    (slots=8, seq=512 — PERF.md) runs once, the fp8 variant walks 64->32->16
    slots on OOM with SHRINKING per-attempt caps (fallbacks get 400 s, so a
    hang can't eat three full timeouts), and one manual-path fallback runs
    only if the primary failed and budget remains."""
    out: dict = {}

    def left() -> float:
        return float("inf") if time_left is None else time_left()

    if left() < 150:
        out["decode_8b_skipped"] = f"budget exhausted ({left():.0f}s left)"
        return out
    rem = lambda: max(60, left())  # noqa: E731 - shared floor for all attempts
    res, err = _run_with_transient_retry(
        _8B_SNIPPET.format(slots=8, seq=512, kv=None, tag="_int8"),
        900, rem, out, "decode_8b_primary",
    )
    engine_fit = bool(res)
    if res:
        out.update(res)
    else:
        out["decode_8b_engine_error_8x512"] = err
    if engine_fit and left() > 120:
        # fp8 KV variant: half-width cache multiplies the slots that fit, and
        # slots amortize the per-step cost (the r5 ledger) — measured 197 ->
        # 446 (8 bf16 -> 16 fp8, r4) -> 758 @ 32 -> 1158 tok/s @ 64 fp8
        # (r5 same-session; 128 OOMs: 4.2 GB KV next to 8 GB weights).
        # 64 first, smaller on OOM.
        for i, slots in enumerate((64, 32, 16)):
            # fallbacks get a smaller cap: a contention hang (timeout, not
            # fast OOM) must not eat three full attempt budgets
            cap = 900 if i == 0 else 400
            res, err = _run_with_transient_retry(
                _8B_SNIPPET.format(slots=slots, seq=512, kv="fp8", tag="_int8_fp8kv"),
                cap, rem, out, f"decode_8b_fp8kv_{slots}",
            )
            if res:
                out.update(res)
                break
            out[f"decode_8b_fp8kv_error_{slots}"] = err
            if left() < 150:
                break
    elif not engine_fit and left() > 120:
        # engine program set didn't fit — same serving math, staged dispatches
        res, err = _run_with_transient_retry(
            _8B_MANUAL_SNIPPET.format(slots=8, seq=512),
            900, rem, out, "decode_8b_manual",
        )
        if res:
            out.update(res)
        else:
            out["decode_8b_error_8x512"] = err
    return out


def bench_ingestion() -> dict:
    """Config 4: bulk-doc ingestion (10k-doc embedding batch -> KNN append) and
    KNN behavior at corpus scale (build / incremental-append / query latency).

    The reference runs this as a Celery task embedding texts one HTTP call per
    batch into pgvector (assistant/processing/tasks.py, pgvector HNSW insert);
    here it is batched jit encode feeding incremental device appends.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from django_assistant_bot_tpu.models import encoder
    from django_assistant_bot_tpu.storage.knn import VectorIndex

    out: dict = {}
    cfg = _encoder_cfg()
    out.update(bench_ingest_only())
    # KNN at corpus scale: SMALL runs a 20k-vector body in-process; the real
    # run's 1M walk-down lives in main()'s subprocess sequence
    out.update(_knn_scale_body(20_000, cfg.hidden_size, KNN_QUERIES))
    return out


def bench_ingest_only() -> dict:
    """The device-side half of config 4: batched jit encode -> device appends."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from django_assistant_bot_tpu.models import encoder
    from django_assistant_bot_tpu.storage.knn import VectorIndex

    out: dict = {}
    cfg = _encoder_cfg()
    params = encoder.init(cfg, jax.random.PRNGKey(3))
    encode = jax.jit(lambda p, i, m: encoder.encode(p, cfg, i, m, normalize=True))
    rng = np.random.default_rng(7)
    seq = min(EMB_SEQ, cfg.max_position_embeddings)
    n_docs = 512 if SMALL else INGEST_DOCS
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (EMB_BATCH, seq)), jnp.int32)
    mask = jnp.ones((EMB_BATCH, seq), jnp.int32)
    np.asarray(encode(params, ids, mask))  # compile

    # device-path ingestion: encoder outputs append on device (add_device), no
    # host round trip per batch — the d2h link (the slowest hop through a
    # remote tunnel) is off the hot path entirely
    index = VectorIndex(cfg.hidden_size)
    index.reserve(n_docs)
    t0 = time.perf_counter()
    done = 0
    while done < n_docs:
        index.add_device(range(done, done + EMB_BATCH), encode(params, ids, mask))
        done += EMB_BATCH
    index.warmup(ks=(16,), q_rows=(8,))  # blocks until every append landed
    wall = time.perf_counter() - t0
    out["ingest_docs_per_s_per_chip"] = round(done / wall, 2)
    out["ingest_docs"] = done
    return out


def _knn_scale_body(n_vec: int, dim: int, n_queries: int) -> dict:
    import numpy as np

    from django_assistant_bot_tpu.storage.knn import VectorIndex

    out: dict = {}
    rng = np.random.default_rng(17)
    big = rng.normal(size=(n_vec, dim)).astype(np.float32)
    scale_index = VectorIndex(dim)
    t0 = time.perf_counter()
    scale_index.add(range(n_vec), big)
    out["knn_build_host_s"] = round(time.perf_counter() - t0, 3)
    # warmup = the real cost of making the corpus serveable: bf16 host->HBM
    # transfer + normalize + query-bucket compiles, BLOCKED until resident
    # (dispatch is async; round 2 under-reported build and the first live
    # query silently paid the whole transfer).  Broken down (VERDICT r3 weak
    # #8): stage (h2d transfer + on-device normalize) vs kernel compiles, with
    # a raw device_put of the same bytes as the transfer floor.
    import jax as _jax
    import jax.numpy as _jnp

    raw = big[: min(n_vec, 100_000)].astype(np.dtype(_jnp.bfloat16))
    t0 = time.perf_counter()
    _jax.block_until_ready(_jax.device_put(raw))
    put_s = time.perf_counter() - t0
    out["knn_h2d_gbps"] = round(raw.nbytes / put_s / 1e9, 2)
    t0 = time.perf_counter()
    scale_index._ensure_device()
    # _ensure_device dispatches async; a real fetch is the only barrier
    _jax.block_until_ready(scale_index._device_index)
    out["knn_build_stage_s"] = round(time.perf_counter() - t0, 3)
    # cold vs warm COMPILE cost (VERDICT r5 #6): both sides time the kernel
    # warmup ONLY — staging (h2d + normalize) is re-paid by every boot whether
    # or not the compile cache hits, so including it in "cold" would credit
    # the cache with time it cannot save (it lives in knn_build_stage_s).
    # The pair runs against a FRESH on-disk cache dir: the section child
    # enables the persistent cache globally, so a prior run (or any `serve`
    # boot) would otherwise serve the "cold" compile from disk and collapse
    # the contrast these two keys exist to demonstrate.  "warm" re-runs the
    # same warmup after dropping the in-memory executables, so it must
    # round-trip the on-disk cache — the second-`serve`-boot compile number
    # the cache wiring buys.
    import shutil as _shutil
    import tempfile as _tempfile

    orig_cache_dir = getattr(_jax.config, "jax_compilation_cache_dir", None)

    def _set_cache_dir(d):
        # returns True when the CONFIG changed (the finally must then restore
        # it even if the private reset below is unavailable on this jax)
        try:
            _jax.config.update("jax_compilation_cache_dir", d)
        except Exception:
            return False
        try:
            # the persistent cache is a once-initialized singleton: if any
            # earlier compile latched it (the staging above did), a config
            # update alone never reaches it — reset so the new dir is live
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass
        return True

    fresh_cache = _tempfile.mkdtemp(prefix="dabt_cold_cache_")
    redirected = _set_cache_dir(fresh_cache)
    try:
        t0 = time.perf_counter()
        scale_index.warmup(ks=(16,), q_rows=(8, n_queries))
        out["knn_build_kernels_s"] = round(time.perf_counter() - t0, 3)
        out["knn_build_s"] = round(
            out["knn_build_stage_s"] + out["knn_build_kernels_s"], 3
        )
        out["knn_build_cold_s"] = out["knn_build_kernels_s"]
        _jax.clear_caches()
        t0 = time.perf_counter()
        scale_index.warmup(ks=(16,), q_rows=(8, n_queries))
        out["knn_build_warm_s"] = round(time.perf_counter() - t0, 3)
    finally:
        if redirected:
            _set_cache_dir(orig_cache_dir)
        _shutil.rmtree(fresh_cache, ignore_errors=True)
    out["knn_vectors"] = n_vec
    # post-warmup first query — the serving-path reality (no compile stall)
    t0 = time.perf_counter()
    scale_index.search(big[0], k=10)
    out["knn_first_query_ms"] = round((time.perf_counter() - t0) * 1e3, 3)

    lat = []
    q = rng.normal(size=(n_queries, dim)).astype(np.float32)
    for i in range(n_queries):
        t0 = time.perf_counter()
        scale_index.search(q[i], k=10)
        lat.append(time.perf_counter() - t0)
    # single-query p50 includes one full host<->device round trip per call —
    # through a remote-tunnel device that RTT dominates (device compute is
    # ~0.05 ms at 1M x 768); the batched number shows the amortized cost
    out["knn_query_p50_ms"] = round(statistics.median(lat) * 1e3, 3)
    t0 = time.perf_counter()
    scale_index.search_batch(q, k=10)
    out["knn_query_batched_ms_per_query"] = round(
        (time.perf_counter() - t0) / n_queries * 1e3, 3
    )

    # the SERVING-path single query: concurrent callers coalesce into one
    # batched dispatch (storage/knn.py AsyncSearcher — what the RAG search
    # service actually calls), so each single query pays ~1/N of the RTT
    from django_assistant_bot_tpu.storage.knn import AsyncSearcher

    async def _concurrent_singles():
        searcher = AsyncSearcher(scale_index)
        lats: list[float] = []

        async def one(i):
            t0 = time.perf_counter()
            await searcher.search(q[i], k=10)
            lats.append(time.perf_counter() - t0)

        await asyncio.gather(*(one(i) for i in range(n_queries)))
        return lats

    clat = asyncio.new_event_loop().run_until_complete(_concurrent_singles())
    out["knn_query_concurrent_p50_ms"] = round(statistics.median(clat) * 1e3, 3)

    extra = rng.normal(size=(10_000, dim)).astype(np.float32)
    t0 = time.perf_counter()
    scale_index.add(range(n_vec, n_vec + 10_000), extra)
    scale_index.search(extra[0], k=10)
    out["knn_append_10k_s"] = round(time.perf_counter() - t0, 3)
    return out


_KNN_SCALE_SNIPPET = """
import json
import bench

print(json.dumps(bench._knn_scale_body({n_vec}, {dim}, {nq})))
"""


def bench_ann() -> dict:
    """Config 4c (SMALL): the IVF-PQ body at smoke geometry, same code path
    as the real run's 1M subprocess."""
    return _ann_scale_body(20_000, _encoder_cfg().hidden_size, KNN_QUERIES)


def _ann_scale_body(n_vec: int, dim: int, n_queries: int) -> dict:
    """Config 4c: ANN (IVF-PQ, storage/ann.py) vs exact KNN on the SAME
    corpus, query batch, and k — the recall-accounted speedup.

    Every latency key is emitted alongside the recall the index was giving at
    that moment (a latency number without its recall is meaningless for an
    approximate index), plus build time, append latency, code bytes/vector,
    and the recall-vs-nprobe curve an operator tunes against (docs/ANN.md).
    The corpus is seeded CLUSTERED vectors — the geometry real embedding
    corpora have and the one IVF pruning is honest on; uniform-random vectors
    would understate recall and overstate pruning wins.
    """
    import numpy as np

    from django_assistant_bot_tpu.storage.ann import ANNIndex, make_clustered
    from django_assistant_bot_tpu.storage.knn import VectorIndex

    out: dict = {}
    rng = np.random.default_rng(17)
    rows = make_clustered(n_vec, dim, n_clusters=max(64, n_vec // 4000), seed=17)

    index = ANNIndex(dim, seed=17)
    t0 = time.perf_counter()
    index.add(range(n_vec), rows)
    index.train()
    # warmup blocks until code blocks + rerank tier are resident and the
    # query buckets are compiled — build_s is the full cost to serveable
    index.warmup(ks=(16,), q_rows=(8, 128))
    out["ann_build_s"] = round(time.perf_counter() - t0, 3)
    st = index.stats()
    out["ann_vectors"] = n_vec
    out["ann_nlist"] = st["nlist"]
    out["ann_nprobe_default"] = st["nprobe"]
    out["ann_codes_bytes_per_vec"] = round(st["codes_bytes_per_vector"], 2)

    # query batch: perturbed stored rows — the RAG near-duplicate shape,
    # matching what probe_recall scores so latency and recall line up
    qn = 128
    take = rng.choice(n_vec, size=qn, replace=False)
    q = rows[take] + 0.05 * rng.standard_normal((qn, dim)).astype(np.float32)

    rec = index.probe_recall(n_queries=64, k=10, seed=17)
    out["ann_recall_at10"] = round(rec["recall_at_k"], 4)
    index.search_batch(q, k=10)  # warm this exact shape
    t0 = time.perf_counter()
    index.search_batch(q, k=10)
    out["ann_query_batched_ms_per_query"] = round(
        (time.perf_counter() - t0) / qn * 1e3, 3
    )

    # the operator's tuning curve: recall AND latency per nprobe point
    curve: dict = {}
    p = 1
    while p <= min(64, index.nlist):
        r = index.probe_recall(n_queries=64, k=10, nprobe=p, seed=17)
        index.search_batch(q, k=10, nprobe=p)  # warm
        t0 = time.perf_counter()
        index.search_batch(q, k=10, nprobe=p)
        curve[str(p)] = {
            "recall_at10": round(r["recall_at_k"], 4),
            "ms_per_query": round((time.perf_counter() - t0) / qn * 1e3, 3),
        }
        p *= 4
    out["ann_recall_vs_nprobe"] = curve

    # exact baseline: same corpus, same query batch, same k — recall 1.0 by
    # construction (brute force IS the ground truth probe_recall scores against)
    exact = VectorIndex(dim)
    exact.add(range(n_vec), rows)
    exact.warmup(ks=(16,), q_rows=(8, 128))
    exact.search_batch(q, k=10)
    t0 = time.perf_counter()
    exact.search_batch(q, k=10)
    out["ann_exact_query_batched_ms_per_query"] = round(
        (time.perf_counter() - t0) / qn * 1e3, 3
    )
    out["ann_exact_recall_at10"] = 1.0
    out["ann_speedup_vs_exact"] = round(
        out["ann_exact_query_batched_ms_per_query"]
        / max(1e-9, out["ann_query_batched_ms_per_query"]),
        2,
    )
    del exact

    # live ingestion: 10k appended WITHOUT retrain, then recall re-probed —
    # the append latency key ships with the recall the index has after it
    extra = make_clustered(10_000, dim, seed=23)
    t0 = time.perf_counter()
    index.add(range(n_vec, n_vec + 10_000), extra)
    index.search(extra[0], k=10)  # barrier: appended rows are searchable
    out["ann_append_10k_s"] = round(time.perf_counter() - t0, 3)
    rec2 = index.probe_recall(n_queries=64, k=10, seed=29)
    out["ann_recall_at10_post_append"] = round(rec2["recall_at_k"], 4)
    return out


_ANN_SNIPPET = """
import json
import bench

print(json.dumps(bench._ann_scale_body({n_vec}, {dim}, {nq})))
"""


# kill-replay child: ingests ledgered documents one at a time into a durable
# index, logging each applied doc's top-k AFTER the WAL fsync — the parent
# SIGKILLs it mid-stream, so the last complete line is the pre-crash truth
# the recovered index must reproduce (storage/durable.py, docs/DURABILITY.md)
_DURABLE_CHILD = """
import json, os, sys, time
import numpy as np
from django_assistant_bot_tpu.storage.ann import make_clustered
from django_assistant_bot_tpu.storage.durable import DurableANN

dirp, progress, docs, rows_per, dim = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]), int(sys.argv[5])
)
rows = make_clustered(docs * rows_per, dim, seed=7)
q = rows[:: max(1, docs * rows_per // 8)][:8]
dur = DurableANN(dirp, dim=dim, fsync="always", snapshot_every_records=6, seed=7)
pf = open(progress, "a")
for d in range(docs):
    ids = list(range(d * rows_per, (d + 1) * rows_per))
    dur.ingest(ids, rows[ids], ledger_key=f"doc{d}")
    if d == 3:
        dur.train(nlist=8, seed=7)
    topk = [[int(i) for i, _ in dur.search(qq, k=10)] for qq in q]
    pf.write(json.dumps({"doc": d, "n": len(dur), "topk": topk}) + "\\n")
    pf.flush()
    os.fsync(pf.fileno())
    time.sleep(0.05)
"""


def bench_durable() -> dict:
    """Config 4d: durability kill-replay (storage/durable.py evidence).

    A child process live-ingests 24 ledgered documents into a WAL+snapshot
    backed index and is SIGKILLed mid-stream (>= 8 applied).  The parent then
    recovers the SAME directory — latest valid snapshot + WAL-tail replay —
    and asserts the three durability claims: (1) recovered top-k is identical
    to the child's last fsynced pre-crash answer on the pinned corpus, (2)
    zero duplicate vectors, (3) re-ingesting EVERY document with the original
    ledger keys no-ops exactly the already-applied ones and lands the rest,
    finishing at the full corpus.  Recovery wall time and replayed-record
    counts ride along as the operator-facing cost of the crash.
    """
    import json as _json
    import os
    import signal
    import subprocess
    import sys
    import tempfile

    import numpy as np

    from django_assistant_bot_tpu.storage.ann import make_clustered
    from django_assistant_bot_tpu.storage.durable import DurableANN

    docs, rows_per, dim = 24, 32, 64
    out: dict = {"durable_ingested_docs": docs}
    with tempfile.TemporaryDirectory(prefix="dabt-durable-") as tmp:
        dur_dir = os.path.join(tmp, "index")
        progress = os.path.join(tmp, "progress.jsonl")
        open(progress, "w").close()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        child = subprocess.Popen(
            [sys.executable, "-c", _DURABLE_CHILD, dur_dir, progress, str(docs), str(rows_per), str(dim)],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            lines = open(progress).read().splitlines()
            if len(lines) >= 8 or child.poll() is not None:
                break
            time.sleep(0.02)
        if child.poll() is None:
            child.send_signal(signal.SIGKILL)  # no atexit, no flush — a real crash
        else:
            err = (child.stderr.read() or b"").decode(errors="replace")
            raise RuntimeError(f"durable child exited early rc={child.returncode}: {err[-2000:]}")
        child.wait()
        pre_crash = [
            _json.loads(ln) for ln in open(progress).read().splitlines() if ln.strip()
        ]

        rows = make_clustered(docs * rows_per, dim, seed=7)
        q = rows[:: max(1, docs * rows_per // 8)][:8]
        t0 = time.perf_counter()
        dur = DurableANN(dur_dir, dim=dim, fsync="always", seed=7)
        st = dur.durability_stats()
        out["durable_recovery_s"] = round(time.perf_counter() - t0, 3)
        out["durable_replayed_records"] = st["replayed_records"]
        out["durable_wal_records"] = st["wal_records"]
        out["durable_snapshot_count"] = st["snapshot_count"]
        applied = sum(1 for d in range(docs) if dur.ledger_has(f"doc{d}"))
        out["durable_recovered_docs"] = applied

        live = dur.index.live_ids()
        expect = set(range(applied * rows_per))
        out["durable_duplicate_vectors"] = len(live) - len(set(live))
        assert set(live) == expect, "recovered id set != ledgered documents"

        topk = [[int(i) for i, _ in dur.search(qq, k=10)] for qq in q]
        truth = next((p["topk"] for p in pre_crash if p["doc"] == applied - 1), None)
        if truth is None:
            # crash landed between the WAL fsync and the progress fsync: the
            # last applied doc has no logged answer, so rebuild the pre-crash
            # index from scratch (same data/order/seed => same placement)
            ctl = DurableANN(os.path.join(tmp, "control"), dim=dim, fsync="never", snapshot_every_records=6, seed=7)
            for d in range(applied):
                ids = list(range(d * rows_per, (d + 1) * rows_per))
                ctl.ingest(ids, rows[ids], ledger_key=f"doc{d}")
                if d == 3:
                    ctl.train(nlist=8, seed=7)
            truth = [[int(i) for i, _ in ctl.search(qq, k=10)] for qq in q]
            ctl.close()
        out["durable_topk_identical"] = bool(topk == truth)

        # crash-resume: the worker re-runs its WHOLE ingest loop; applied
        # docs must no-op on the ledger, the rest must land exactly once
        deduped = 0
        for d in range(docs):
            ids = list(range(d * rows_per, (d + 1) * rows_per))
            n = dur.ingest(ids, rows[ids], ledger_key=f"doc{d}")
            deduped += int(n == 0)
        out["durable_resume_dedup_docs"] = deduped
        assert deduped == applied, "ledger dedup did not cover the applied docs"
        live = dur.index.live_ids()
        assert len(live) == docs * rows_per and len(set(live)) == len(live)
        out["durable_duplicate_vectors"] += len(live) - len(set(live))
        dur.close()
    return out


_DURABLE_SNIPPET = """
import json
import bench

print(json.dumps(bench.bench_durable()))
"""


def bench_core() -> dict:
    """Configs 1-3: embedding + bf16 decode + RAG, one engine build.  ONE body
    serves both the SMALL in-process run and the real run's subprocess — the
    measurement sequence can't drift between them."""
    out: dict = {}
    out["embedding_docs_per_sec_per_chip"] = round(bench_embedding(), 2)
    # LRU must hold every live dialog's prefix (each 2-turn dialog registers
    # up to 2 entries) or concurrent dialogs thrash each other's entries and
    # rag_turn2_p50_ttft_s stops measuring the prefix-cache win
    eng, _ = _build_gen_engine(prefix_cache=2 * RAG_CONCURRENCY + 2)
    try:
        out.update(bench_decode(eng))
        out.update(bench_rag(eng))
    finally:
        eng.stop()
    return out


def decode_byte_ledger(eng, fill_len=None) -> dict:
    """Per-decode-step HBM byte model for the engine's geometry (GB).

    Closes VERDICT r4 weak #3 (the int8 ledger): a decode step reads (a) the
    layer weights, (b) the lm_head, and (c) the KV cache.  Historically (c)
    used the engine's ALLOCATED shape — static-shape decode attention read all
    ``max_slots x max_seq_len`` rows regardless of live lengths; the
    length-bucketed decode read (``decode_kv_chunk``) now bounds it at the
    chunk-roundup of the batch's fill instead, so the ledger takes
    ``fill_len`` (the context the engine is serving) and reports both the
    allocated KV bytes and what the bucketed read actually streams.  At
    1B/512 ctx/16 slots the bf16 KV read (~2.1 GB) RIVALS the weights
    (~2.4 GB): int8 halves only (a)+(b) — fp8 KV and the bucketed read are
    what cut (c).
    """
    import jax
    import jax.numpy as jnp

    cfg = eng.cfg
    layer_b = sum(l.nbytes for l in jax.tree.leaves(eng.params["layers"]))
    head = eng.params.get("lm_head", eng.params["tok_embed"])
    head_b = sum(l.nbytes for l in jax.tree.leaves(head))
    kv_itemsize = jnp.dtype(eng.kv_cache_dtype or cfg.dtype).itemsize
    row_b = (
        eng.max_slots
        * cfg.num_layers
        * cfg.num_kv_heads
        * cfg.head_dim
        * 2  # K and V
        * kv_itemsize
    )
    if getattr(eng, "paged", False):
        # paged layout: the allocation is the page pool, not slots x max_seq
        kv_alloc_b = (
            eng._kv_pool.n_pages
            * cfg.num_layers
            * cfg.num_kv_heads
            * cfg.head_dim
            * eng.kv_page_size
            * 2
            * kv_itemsize
        )
    else:
        kv_alloc_b = row_b * eng.max_seq_len
    c = eng.decode_kv_chunk
    if c and fill_len is not None:
        covered = min(eng.max_seq_len, (min(fill_len, eng.max_seq_len - 1) // c + 1) * c)
    else:
        covered = eng.max_seq_len
    kv_b = row_b * covered
    total = layer_b + head_b + kv_b
    return {
        "weights_layers_gb": round(layer_b / 1e9, 3),
        "head_gb": round(head_b / 1e9, 3),
        "kv_read_gb": round(kv_b / 1e9, 3),
        "kv_alloc_gb": round(kv_alloc_b / 1e9, 3),
        "kv_read_frac": round(covered / eng.max_seq_len, 4),
        "total_gb_per_step": round(total / 1e9, 3),
    }


def bench_int8() -> dict:
    """Config 2b: int8 weight-only decode, WITH the bytes ledger.

    One full-traffic engine at the default (32-slot) size, then the 16-vs-32
    slot question settled with INTERLEAVED A/B/A probe trials
    (:func:`bench_slots_ab`) — a single A-then-B sample per run cannot carry
    the default on a shared chip whose effective rate swings ~2x between
    sessions (VERDICT r5 #3: the r5 artifact contradicted its own default)."""
    out: dict = {}
    fill = DECODE_PROMPT_LEN + DECODE_NEW_TOKENS
    eng, _ = _build_gen_engine(quantize="int8", buckets=(_decode_bucket(),))
    try:
        q8 = bench_decode(eng)
        out.update(
            {
                "decode_int8_tokens_per_s_per_chip": q8["decode_tokens_per_s_per_chip"],
                "decode_int8_p50_ttft_s": q8["decode_p50_ttft_s"],
                "decode_int8_hbm_gbps_min": q8["decode_hbm_gbps_min"],
                "decode_int8_pure_step_ms": q8["decode_pure_step_ms"],
                "decode_int8_steady_tokens_per_s": q8["decode_steady_tokens_per_s"],
                "decode_int8_kv_read_frac": q8["decode_kv_read_frac"],
                "decode_int8_mfu_frac": q8["decode_mfu_frac"],
                "decode_int8_hbm_gbps": q8["decode_hbm_gbps"],
                "decode_int8_ledger": decode_byte_ledger(eng, fill_len=fill),
            }
        )
    finally:
        eng.stop()
    # (the 1B int8+embed/head+fp8KV engine that closed the ledger lives in
    # PERF.md's table; re-measuring it every run bought ~200 s of budget for
    # no new information — the recorded fp8 evidence is the 8B config)
    out.update(bench_slots_ab())
    return out


def bench_slots_ab(trials: int = 3) -> dict:
    """Interleaved A/B/A slot-count trials on ONE shared int8 param set.

    Builds the SLOTS-slot (A) and SLOTS/2-slot (B) engines over the same
    weights (engines donate only their caches, never params), then alternates
    probe trials A,B,A,B,... inside one session so chip-rate drift hits both
    arms equally.  Records per-arm trial lists, medians, and spread; the
    winner key is what the canonical record cites for the default."""
    import jax

    from django_assistant_bot_tpu.models import llama
    from django_assistant_bot_tpu.parallel import get_mesh, shard_pytree
    from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine

    cfg = _decoder_cfg()
    params = llama.init_int8(cfg, jax.random.PRNGKey(0))
    mesh = get_mesh()
    with mesh:
        params = shard_pytree(params, llama.logical_axes(cfg), mesh)
    slots_a, slots_b = SLOTS, max(1, SLOTS // 2)
    if slots_a == slots_b:
        # BENCH_SLOTS=1: both arms collapse to the same geometry — the dict
        # key would collide (leaking the first engine) and the "contrast"
        # would probe one arm twice
        return {"slots_ab_winner": slots_a, "slots_ab_default": SLOTS}
    fill = DECODE_PROMPT_LEN + DECODE_NEW_TOKENS
    engines = {}
    out: dict = {}
    try:
        for slots in (slots_a, slots_b):
            eng = GenerationEngine(
                cfg,
                params,
                ByteTokenizer(),
                max_slots=slots,
                max_seq_len=min(1024, cfg.max_seq_len),
                prefill_buckets=(_decode_bucket(),),
                chunk_size=_decode_bucket(),
                mesh=mesh,
                prefix_cache_size=0,
            )
            eng.warmup()
            eng.start()
            engines[slots] = eng
        samples: dict = {slots_a: [], slots_b: []}
        for _ in range(trials):
            for slots in (slots_a, slots_b):  # interleaved: A B A B A B
                samples[slots].append(
                    engines[slots].probe_decode(iters=8, fill_len=fill)
                )
        for slots, ss in samples.items():
            ms = sorted(x * 1e3 for x in ss)
            med = statistics.median(ms)
            out[f"slots{slots}_step_ms_trials"] = [round(x, 3) for x in ms]
            out[f"slots{slots}_step_ms_median"] = round(med, 3)
            out[f"slots{slots}_step_ms_spread"] = round(ms[-1] - ms[0], 3)
            out[f"slots{slots}_steady_tokens_per_s"] = round(slots / (med / 1e3), 2)
        winner = max(
            (slots_a, slots_b), key=lambda s: out[f"slots{s}_steady_tokens_per_s"]
        )
        ledger_b = decode_byte_ledger(engines[slots_b], fill_len=fill)
    finally:
        for eng in engines.values():
            eng.stop()
    return {
        "decode_int8_slots_ab": out,
        "slots_ab_winner": winner,
        "slots_ab_default": SLOTS,
        # contrast keys the r5 record established under the "slots16" name —
        # the suffix tracks the ACTUAL B-arm geometry so a BENCH_SLOTS
        # override can't record a different slot count under the 16 label
        f"decode_int8_slots{slots_b}_steady_tokens_per_s": out[
            f"slots{slots_b}_steady_tokens_per_s"
        ],
        f"decode_int8_slots{slots_b}_pure_step_ms": out[
            f"slots{slots_b}_step_ms_median"
        ],
        f"decode_int8_slots{slots_b}_ledger": ledger_b,
        # geometry-stable alias for the compact record: the suffixed key's
        # name changes under a BENCH_SLOTS override, which would drop the
        # B-arm headline from the bounded last-line record
        "decode_int8_slots_b_steady_tokens_per_s": out[
            f"slots{slots_b}_steady_tokens_per_s"
        ],
        "decode_int8_slots_b": slots_b,
    }


def bench_fused_int4(trials: int = 3) -> dict:
    """fused_*/int4_* section (docs/QUANT.md): the roofline decode levers.

    Three INTERLEAVED probe arms at the same geometry / KV byte ledger, so
    chip-rate drift hits every arm equally (the bench_slots_ab discipline):

    - **unfused**  — int8 weights, decode_steps=1 (the baseline every claim
      is against);
    - **fused**    — int8 weights, decode_steps=N (one jit spans N chained
      decode steps: dispatch + host bookkeeping amortize over N tokens);
    - **int4**     — grouped int4 weights (0.5 bytes/weight packed),
      decode_steps=N (both levers together).

    Per arm: median-of-trials pure step time, steady tok/s, and the byte
    ledger's MFU fraction + achieved HBM GB/s — every throughput claim
    carries its bytes.  The accuracy cost is a NUMBER, not a vibe:
    ``int4_logit_err_rel`` quantizes one shared bf16 weight set at tiny
    geometry (the quantizer's error is a property of format x group size,
    not of the big arms' synthetic random weights) and reports max logit
    error vs the bf16 forward, alongside int8's, plus the in-dot vs
    dequantized-reference kernel-identity error (which must be ~0: the
    grouped dot IS the dequantized dot, reassociated).
    """
    import jax
    import numpy as np

    from django_assistant_bot_tpu.models import DecoderConfig, llama
    from django_assistant_bot_tpu.ops.quant import (
        INT4_GROUP_SIZE,
        deq,
        num_weights,
        quantize_decoder_params,
    )

    n_steps = int(os.environ.get("BENCH_DECODE_STEPS", "8"))
    fill = DECODE_PROMPT_LEN + DECODE_NEW_TOKENS
    arms = {
        "unfused": dict(quantize="int8_device", decode_steps=1),
        "fused": dict(quantize="int8_device", decode_steps=n_steps),
        "int4": dict(quantize="int4_device", decode_steps=n_steps),
    }
    engines: dict = {}
    out: dict = {"fused_decode_steps": n_steps}
    try:
        for arm, kw in arms.items():
            engines[arm], _ = _build_gen_engine(
                buckets=(_decode_bucket(),), prefix_cache=0, **kw
            )
        samples: dict = {arm: [] for arm in arms}
        for _ in range(trials):
            for arm in arms:  # interleaved: U F I U F I ...
                samples[arm].append(
                    engines[arm].probe_decode(iters=8, fill_len=fill)
                )
        for arm, ss in samples.items():
            eng = engines[arm]
            med = statistics.median(ss)
            steady = eng.max_slots / med
            ledger = decode_byte_ledger(eng, fill_len=fill)
            n_w = num_weights(eng.params)
            prefix = {"unfused": "decode_unfused", "fused": "fused", "int4": "int4"}[arm]
            out[f"{prefix}_step_ms"] = round(med * 1e3, 3)
            out[f"{prefix}_steady_tokens_per_s"] = round(steady, 2)
            out[f"{prefix}_mfu_frac"] = round(steady * 2 * n_w / 197e12, 6)
            out[f"{prefix}_hbm_gbps"] = round(
                ledger["total_gb_per_step"] / med, 2
            )
            out[f"{prefix}_ledger"] = ledger
        out["fused_vs_unfused_speedup"] = round(
            out["fused_steady_tokens_per_s"]
            / max(out["decode_unfused_steady_tokens_per_s"], 1e-9),
            3,
        )
        out["int4_vs_unfused_speedup"] = round(
            out["int4_steady_tokens_per_s"]
            / max(out["decode_unfused_steady_tokens_per_s"], 1e-9),
            3,
        )
        out["int4_vs_fused_speedup"] = round(
            out["int4_steady_tokens_per_s"]
            / max(out["fused_steady_tokens_per_s"], 1e-9),
            3,
        )
        # upload double-buffering evidence rides the fused arm's wall-clock
        # trace (the probe path bypasses the loop's prestage hook)
        rng = np.random.default_rng(3)
        futs = [
            engines["fused"].submit(
                rng.integers(1, 255, DECODE_PROMPT_LEN).tolist(),
                max_tokens=16 + 8 * (i % 3),
                temperature=0.8,
            )
            for i in range(engines["fused"].max_slots)
        ]
        for f in futs:
            f.result(timeout=600)
        out["fused_upload_overlap_frac"] = engines["fused"].upload_overlap_frac()
        out["fused_decode_steps_effective"] = engines[
            "fused"
        ].tick_stats()["decode_steps_effective"]
    finally:
        for eng in engines.values():
            eng.stop()
    # accuracy bound at tiny geometry from ONE shared bf16 weight set — the
    # quantizer-error methodology (docs/QUANT.md), cheap at any bench scale
    cfg_t = DecoderConfig.tiny()
    params_t = llama.init(cfg_t, jax.random.PRNGKey(7))
    ids = jax.numpy.asarray(
        np.random.default_rng(11).integers(1, 200, (2, 16)), jax.numpy.int32
    )
    ref = np.asarray(llama.forward(params_t, cfg_t, ids))
    denom = max(float(np.abs(ref).max()), 1e-6)
    q8_t = quantize_decoder_params(params_t, fmt="int8")
    q4_t = quantize_decoder_params(params_t, fmt="int4")
    l8 = np.asarray(llama.forward(q8_t, cfg_t, ids))
    l4 = np.asarray(llama.forward(q4_t, cfg_t, ids))
    dq4 = dict(q4_t)
    dq4["layers"] = {
        k: deq(v, cfg_t.dtype) for k, v in q4_t["layers"].items()
    }
    l4_ref = np.asarray(llama.forward(dq4, cfg_t, ids))
    out["int8_logit_err_rel"] = round(float(np.abs(l8 - ref).max()) / denom, 5)
    out["int4_logit_err_rel"] = round(float(np.abs(l4 - ref).max()) / denom, 5)
    out["int4_indot_vs_deq_err_rel"] = round(
        float(np.abs(l4 - l4_ref).max()) / max(float(np.abs(l4_ref).max()), 1e-6),
        6,
    )
    # the group size the arms and the accuracy probe ACTUALLY quantized at
    # (both use the quantizer default), so the recorded error bound can
    # never be attributed to a stale hardcoded number
    out["int4_group_size"] = INT4_GROUP_SIZE
    return out


def bench_contbatch(trials: int = 2) -> dict:
    """contbatch_* section (round 15, docs/QUANT.md + docs/SPECULATIVE.md):
    true continuous batching — three decode-plane levers, each behind its own
    ModelSpec knob, each measured as its own arm.

    (a) **Piggybacked chunked prefill** — decode p95 inter-token latency on
      resident chat streams while long-context prompts chunk-prefill through
      the same engine, piggyback ON vs OFF on the SAME greedy trace
      (interleaved trials, best arm each).  OFF runs every prefill chunk as
      its own dispatch that displaces the decode tick; ON folds chunk + N
      decode steps into ONE program, so the weights stream from HBM once per
      loop iteration instead of twice.  Outputs must be token-identical (the
      piggyback program is bit-identical by construction —
      tests/test_contbatch.py) and the displacement gauge records exactly
      what the fusion removed.

    (b) **Spec x fused** — single-stream greedy tok/s on the trained copy
      task (the spec section's methodology: acceptance is a property of a
      model that CAN quote): fused-only (decode_steps=N), spec-only
      (speculative=K, one verify pass per tick), and the composed
      spec x fused engine, interleaved.  The composition's claim is
      >= the better parent.

    (c) **fp8 in-dot attention** — pure decode step time at fp8 KV with the
      attention QK dot reading the cache operand as stored vs dequantizing
      to the compute dtype first, plus the ops-level max attention-output
      error vs the dequant reference (tests/test_contbatch.py bounds it at
      0.15; the number here is the measured value, not the bound).

    Every throughput arm carries its byte ledger (MFU frac + achieved HBM
    GB/s) — same discipline as bench_fused_int4.
    """
    import numpy as np
    import jax.numpy as jnp

    from django_assistant_bot_tpu.ops.attention import chunked_gqa_decode_attention
    from django_assistant_bot_tpu.ops.quant import num_weights
    from django_assistant_bot_tpu.serving import (
        ByteTokenizer,
        GenerationEngine,
        TokenStream,
    )
    from django_assistant_bot_tpu.training import copy_task_config, fit_copy_model

    out: dict = {}
    fill = DECODE_PROMPT_LEN + DECODE_NEW_TOKENS
    msl = min(1024, _decoder_cfg().max_seq_len)
    chunk = max(32, msl // 8)
    long_len = chunk * 3 + chunk // 2  # 3 piggybackable chunks + the final one
    n_chat, n_long = 4, 6
    n_new = min(96, msl - 24)
    rng = np.random.default_rng(15)
    chat_prompts = [rng.integers(1, 255, 16).tolist() for _ in range(n_chat)]
    long_prompts = [
        rng.integers(1, 255, long_len).tolist() for _ in range(n_long)
    ]

    # ---- (a) piggyback A/B: chat ITL under chunked-prefill pressure
    engines: dict = {}
    try:
        for arm, pig in (("on", True), ("off", False)):
            engines[arm], _ = _build_gen_engine(
                buckets=(chunk,),
                chunk_size=chunk,
                max_slots=8,
                prefill_piggyback=pig,
            )

        async def trace(eng):
            loop = asyncio.get_running_loop()
            streams = [
                TokenStream().bind(loop, capacity=n_new + 2)
                for _ in chat_prompts
            ]

            async def drain(st):
                times = []
                async for kind, _payload in st:
                    if kind == "token":
                        times.append(time.perf_counter())
                return times

            futs = [
                eng.submit(p, max_tokens=n_new, temperature=0.0, stream=st)
                for p, st in zip(chat_prompts, streams)
            ]
            drains = [asyncio.ensure_future(drain(st)) for st in streams]
            # the long-context pressure arrives while the chat slots decode:
            # each prompt chunk-prefills through the SAME engine loop
            futs += [
                eng.submit(p, max_tokens=8, temperature=0.0)
                for p in long_prompts
            ]
            results = [await asyncio.wrap_future(f) for f in futs]
            times = await asyncio.gather(*drains)
            gaps = [b - a for ts in times for a, b in zip(ts, ts[1:])]
            return gaps, [r.token_ids for r in results]

        def p95(gaps):
            return sorted(gaps)[max(0, int(len(gaps) * 0.95) - 1)]

        itl = {"on": [], "off": []}
        ids_first: dict = {}
        for t in range(trials):
            for arm in ("on", "off"):  # interleaved: on off on off
                gaps, ids = asyncio.run(trace(engines[arm]))
                itl[arm].append(p95(gaps) * 1e3)
                if t == 0:
                    ids_first[arm] = ids
        out["contbatch_itl_p95_on_ms"] = round(min(itl["on"]), 3)
        out["contbatch_itl_p95_off_ms"] = round(min(itl["off"]), 3)
        out["contbatch_itl_improvement_frac"] = round(
            1.0
            - out["contbatch_itl_p95_on_ms"]
            / max(out["contbatch_itl_p95_off_ms"], 1e-9),
            4,
        )
        out["contbatch_outputs_identical"] = ids_first["on"] == ids_first["off"]
        out["contbatch_chunk"] = chunk
        out["contbatch_long_prompt_len"] = long_len
        for arm in ("on", "off"):
            dec = engines[arm].decode_path_stats()
            out[f"contbatch_displacement_frac_{arm}"] = dec[
                "prefill_displacement_frac"
            ]
            out[f"contbatch_chunks_piggybacked_{arm}"] = dec[
                "prefill_chunks_piggybacked"
            ]
        # byte ledger on the shared pure-decode step (the decode program is
        # identical across arms — piggybacking changes dispatch count, not
        # the step), so the ITL claim above carries its bytes
        step_s = engines["on"].probe_decode(iters=8, fill_len=fill)
        ledger = decode_byte_ledger(engines["on"], fill_len=fill)
        n_w = num_weights(engines["on"].params)
        steady = engines["on"].max_slots / step_s
        out["contbatch_step_ms"] = round(step_s * 1e3, 3)
        out["contbatch_mfu_frac"] = round(steady * 2 * n_w / 197e12, 6)
        out["contbatch_hbm_gbps"] = round(
            ledger["total_gb_per_step"] / step_s, 2
        )
    finally:
        for eng in engines.values():
            eng.stop()

    # ---- (b) spec x fused vs its two parents, single stream, trained quoter
    ccfg = copy_task_config(hidden_size=128)
    cparams, ccfg, fit = fit_copy_model(ccfg, seq_len=128, batch=16, seed=0)
    crng = np.random.default_rng(1)
    M = 64  # trained copy span
    ctx = crng.integers(3, ccfg.vocab_size, M).tolist()
    prompt = ctx + ctx[:8]
    mt = M - 8
    n_steps = 4

    def spec_engine(**kw):
        eng = GenerationEngine(
            ccfg,
            cparams,
            ByteTokenizer(),
            max_slots=2,
            max_seq_len=ccfg.max_seq_len,
            prefill_buckets=(128,),
            prefix_cache_size=0,
            lookahead=3,
            **kw,
        )
        eng.warmup()
        eng.start()
        return eng

    sengines: dict = {}
    try:
        sengines["fused"] = spec_engine(decode_steps=n_steps)
        sengines["spec"] = spec_engine(
            speculative=6, spec_width=4, spec_probe_every=4
        )
        sengines["specfused"] = spec_engine(
            speculative=6, spec_width=4, spec_probe_every=4,
            decode_steps=n_steps,
        )
        for eng in sengines.values():  # warm every program shape
            eng.submit(prompt, max_tokens=mt, temperature=0.0).result(
                timeout=600
            )
        rates: dict = {a: [] for a in sengines}
        ids = {}
        for _ in range(trials):
            for arm, eng in sengines.items():  # interleaved F S X F S X
                t0 = time.perf_counter()
                tot = 0
                for _ in range(3):  # single stream
                    r = eng.submit(
                        prompt, max_tokens=mt, temperature=0.0
                    ).result(timeout=600)
                    tot += r.completion_tokens
                    ids[arm] = r.token_ids
                rates[arm].append(tot / (time.perf_counter() - t0))
        f_tok = max(rates["fused"])
        s_tok = max(rates["spec"])
        x_tok = max(rates["specfused"])
        st = sengines["specfused"].tick_stats()
        out["fusedonly_tokens_per_s"] = round(f_tok, 2)
        out["speconly_tokens_per_s"] = round(s_tok, 2)
        out["specfused_tokens_per_s"] = round(x_tok, 2)
        out["specfused_vs_fused_speedup"] = round(x_tok / max(f_tok, 1e-9), 3)
        out["specfused_vs_spec_speedup"] = round(x_tok / max(s_tok, 1e-9), 3)
        out["specfused_vs_best_parent_speedup"] = round(
            x_tok / max(f_tok, s_tok, 1e-9), 3
        )
        out["specfused_accept_rate"] = st.get("spec_accept_rate", 0.0)
        out["specfused_drafted"] = st.get("spec_drafted", 0)
        out["specfused_decode_steps"] = n_steps
        out["specfused_quote_accuracy"] = round(fit["quote_accuracy"], 4)
        out["specfused_outputs_identical"] = (
            ids["fused"] == ids["spec"] == ids["specfused"]
        )
    finally:
        for eng in sengines.values():
            eng.stop()

    # ---- (c) fp8 in-dot attention A/B at fp8 KV, interleaved probes
    fengines: dict = {}
    try:
        for arm, indot in (("attn_fp8_dequant", False), ("attn_fp8", True)):
            fengines[arm], _ = _build_gen_engine(
                buckets=(_decode_bucket(),),
                kv_dtype="fp8",
                attn_fp8=indot,
                max_slots=8,
            )
        samples: dict = {a: [] for a in fengines}
        for _ in range(trials + 1):
            for arm, eng in fengines.items():  # interleaved D I D I ...
                samples[arm].append(eng.probe_decode(iters=8, fill_len=fill))
        for arm, eng in fengines.items():
            med = statistics.median(samples[arm])
            steady = eng.max_slots / med
            ledger = decode_byte_ledger(eng, fill_len=fill)
            n_w = num_weights(eng.params)
            out[f"{arm}_step_ms"] = round(med * 1e3, 3)
            out[f"{arm}_steady_tokens_per_s"] = round(steady, 2)
            out[f"{arm}_mfu_frac"] = round(steady * 2 * n_w / 197e12, 6)
            out[f"{arm}_hbm_gbps"] = round(
                ledger["total_gb_per_step"] / med, 2
            )
        out["attn_fp8_step_speedup"] = round(
            out["attn_fp8_dequant_step_ms"]
            / max(out["attn_fp8_step_ms"], 1e-9),
            3,
        )
    finally:
        for eng in fengines.values():
            eng.stop()
    # ops-level accuracy number at tiny geometry (cheap at any bench scale,
    # the bench_fused_int4 quantizer-error methodology): in-dot vs the
    # dequant reference on unit-scale operands
    erng = np.random.default_rng(0)
    q = jnp.asarray(erng.standard_normal((2, 4, 1, 16)), jnp.bfloat16)
    k8 = jnp.asarray(
        erng.standard_normal((2, 2, 64, 16)) * 0.5, jnp.float32
    ).astype(jnp.float8_e4m3fn)
    v8 = jnp.asarray(
        erng.standard_normal((2, 2, 64, 16)) * 0.5, jnp.float32
    ).astype(jnp.float8_e4m3fn)
    positions = jnp.asarray([63, 21], jnp.int32)
    ref = chunked_gqa_decode_attention(q, k8, v8, positions, chunk=16)
    got = chunked_gqa_decode_attention(
        q, k8, v8, positions, chunk=16, fp8_dot=True
    )
    out["attn_fp8_indot_max_abs_err"] = round(
        float(
            jnp.max(jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32)))
        ),
        5,
    )
    out["attn_fp8_indot_err_bound"] = 0.15  # tests/test_contbatch.py contract
    return out


def bench_paged() -> dict:
    """paged_* section (docs/KV_PAGING.md): the paged KV plane's two claims.

    (a) Slots at fixed HBM: a legacy engine and a paged engine over the SAME
    KV byte ledger (the paged pool holds exactly the legacy arm's
    slots x max_seq_len pages).  Legacy concurrency is pinned at its slot
    count; paged admits by demand (ceil((prompt + max_tokens) / page) pages),
    so the same bytes serve more concurrent requests at bench prompt shapes —
    the capacity ratio is recorded alongside a measured burst (peak live
    slots + wall-clock tok/s) so the arithmetic is backed by a run.

    (b) Prefix-hit TTFT on a shared-system-prompt trace (the reference's
    per-bot prompt shape): page-sharing (COW boundary clone, zero prefix
    recompute) vs the r4 whole-prefix pinned LRU, p50/p95 client TTFT.
    """
    import jax
    import numpy as np

    from django_assistant_bot_tpu.models import llama
    from django_assistant_bot_tpu.parallel import get_mesh, shard_pytree
    from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine

    cfg = _decoder_cfg()
    params = llama.init_int8(cfg, jax.random.PRNGKey(0))
    mesh = get_mesh()
    with mesh:
        params = shard_pytree(params, llama.logical_axes(cfg), mesh)
    max_seq = min(1024, cfg.max_seq_len)
    bucket = _decode_bucket()
    new_tokens = 64
    legacy_slots = max(2, SLOTS // 2)

    def build(layout, slots, kv_pages=0, prefix_cache=0):
        eng = GenerationEngine(
            cfg, params, ByteTokenizer(),
            max_slots=slots, max_seq_len=max_seq,
            prefill_buckets=(bucket,), chunk_size=bucket, mesh=mesh,
            prefix_cache_size=prefix_cache, prefix_min_tokens=16,
            kv_layout=layout, kv_pages=kv_pages,
        )
        eng.warmup()
        eng.start()
        return eng

    rng = np.random.default_rng(5)
    out: dict = {}

    # ---- (a) slots at fixed HBM -----------------------------------------
    legacy = build("legacy", legacy_slots)
    page = legacy._resolve_kv_chunk(0) or 512
    pool_pages = legacy_slots * (max_seq // page)  # the legacy arm's exact bytes
    paged = build("paged", SLOTS, kv_pages=pool_pages)
    try:
        pages_per_req = -(-(DECODE_PROMPT_LEN + new_tokens) // paged.kv_page_size)
        paged_capacity = min(SLOTS, pool_pages // pages_per_req)
        n_req = min(2 * legacy_slots, paged_capacity)
        prompts = [
            rng.integers(1, 255, DECODE_PROMPT_LEN).tolist() for _ in range(n_req)
        ]

        def burst(eng):
            futs = [
                eng.submit(p, max_tokens=new_tokens, temperature=0.8)
                for p in prompts
            ]
            peak, t0 = 0, time.perf_counter()
            while not all(f.done() for f in futs):
                peak = max(peak, eng.num_active)
                time.sleep(0.002)
            wall = time.perf_counter() - t0
            toks = sum(f.result().completion_tokens for f in futs)
            return peak, toks / wall

        burst(legacy)  # warm both loops before the timed pass
        burst(paged)
        legacy_peak, legacy_tok_s = burst(legacy)
        paged_peak, paged_tok_s = burst(paged)
        out.update({
            "paged_page_size": paged.kv_page_size,
            "paged_pool_pages": pool_pages,
            "paged_pages_per_req": pages_per_req,
            # capacity at the SAME byte ledger: demand-based reservation vs
            # one max_seq_len row per slot
            "paged_slots_at_fixed_hbm": paged_capacity,
            "legacy_slots_at_fixed_hbm": legacy_slots,
            "paged_vs_legacy_slots": round(paged_capacity / legacy_slots, 2),
            "paged_kv_bytes_per_slot_frac": round(
                pages_per_req * page / max_seq, 4
            ),
            "paged_burst_peak_active": paged_peak,
            "legacy_burst_peak_active": legacy_peak,
            "paged_tokens_per_s": round(paged_tok_s, 2),
            "paged_legacy_tokens_per_s": round(legacy_tok_s, 2),
        })
    finally:
        legacy.stop()
        paged.stop()

    # ---- (b) prefix-hit TTFT on a shared-system-prompt trace -------------
    prefix = rng.integers(1, 255, min(300, bucket - 8)).tolist()
    turns = [
        prefix + rng.integers(1, 255, 40).tolist() for _ in range(12)
    ]

    def ttft_arm(layout):
        eng = build(layout, 4, prefix_cache=8)
        try:
            # first turn registers the prefix; it is excluded from the stats
            eng.submit(
                turns[0], max_tokens=8, temperature=0.0, prefix_len=len(prefix)
            ).result(timeout=1200)
            ttfts = []
            for t in turns[1:]:
                r = eng.submit(
                    t, max_tokens=8, temperature=0.0, prefix_len=len(prefix)
                ).result(timeout=1200)
                ttfts.append(r.ttft_s)
            hits = eng.prefix_hits
            ttfts.sort()
            return ttfts, hits
        finally:
            eng.stop()

    ttft_l, hits_l = ttft_arm("legacy")
    ttft_p, hits_p = ttft_arm("paged")

    def pctl(vals, frac):
        return vals[min(len(vals) - 1, max(0, round(frac * (len(vals) - 1))))]

    out.update({
        "paged_prefix_ttft_p50_s": round(pctl(ttft_p, 0.5), 4),
        "paged_prefix_ttft_p95_s": round(pctl(ttft_p, 0.95), 4),
        "legacy_prefix_ttft_p50_s": round(pctl(ttft_l, 0.5), 4),
        "legacy_prefix_ttft_p95_s": round(pctl(ttft_l, 0.95), 4),
        "paged_prefix_hits": hits_p,
        "legacy_prefix_hits": hits_l,
    })
    return out


# Each device-using config section runs in its OWN subprocess: the chip is
# shared across every live process on this host, so a parent that keeps model
# params resident starves the next section (r3's 8B bench failed exactly this
# way — the parent still held the 1B engines' HBM when the 9 GB child started).
_CORE_SNIPPET = """
import json
import bench

print(json.dumps(bench.bench_core()))
"""

_INT8_SNIPPET = """
import json
import bench

print(json.dumps(bench.bench_int8()))
"""

_INGEST_SNIPPET = """
import json
import bench

print(json.dumps(bench.bench_ingest_only()))
"""

_FUSED_INT4_SNIPPET = """
import json
import bench

print(json.dumps(bench.bench_fused_int4()))
"""

_PAGED_SNIPPET = """
import json
import bench

print(json.dumps(bench.bench_paged()))
"""

_CONTBATCH_SNIPPET = """
import json
import bench

print(json.dumps(bench.bench_contbatch()))
"""


# --------------------------------------------------------------------- baselines
def bench_overload() -> dict:
    """Overload section: arrival rate above decode capacity, mixed
    interactive/background traffic, FIFO vs the admission-controlled
    scheduler on the SAME trace (serving/scheduler.py).

    The trace floods the engine with background requests (the ingestion
    burst), then submits interactive dialog turns.  Measured per arm:
    interactive p50/p95 queue wait (TTFT — submit to first token).  The
    scheduler arm additionally demonstrates the overload contract: excess
    background load sheds with a Retry-After hint instead of queueing
    unboundedly, and an expired-deadline request frees its decode slot
    mid-decode (reclaim latency recorded next to the per-tick time)."""
    from django_assistant_bot_tpu.serving import (
        DeadlineExceeded,
        RequestScheduler,
        SchedulerConfig,
        SchedulerRejected,
    )

    import numpy as np

    n_bg, n_int = 20, 8
    bg_tokens, int_tokens = 48, 8
    rng = np.random.default_rng(7)
    bg_prompts = [rng.integers(1, 255, 24).tolist() for _ in range(n_bg)]
    int_prompts = [rng.integers(1, 255, 24).tolist() for _ in range(n_int)]

    def drive(eng) -> dict:
        # warm the loop (shapes are compiled by engine.warmup())
        eng.submit([1, 2, 3], max_tokens=4, temperature=0.0).result(timeout=600)
        arm: dict = {"shed": 0, "retry_after_s": None, "int_retries": 0}
        bg_futs = []
        for p in bg_prompts:
            try:
                bg_futs.append(
                    eng.submit(p, max_tokens=bg_tokens, temperature=0.8,
                               priority="background", tenant="ingest")
                )
            except SchedulerRejected as e:
                arm["shed"] += 1
                arm["retry_after_s"] = e.retry_after_s
        int_futs = []
        for p in int_prompts:
            # interactive clients honor Retry-After (the provider-layer retry
            # policy, ai/providers/http_service.py) — bounded re-submission
            for _ in range(100):
                try:
                    int_futs.append(
                        eng.submit(p, max_tokens=int_tokens, temperature=0.8,
                                   priority="interactive", tenant="dialog")
                    )
                    break
                except SchedulerRejected as e:
                    arm["int_retries"] += 1
                    time.sleep(min(0.2, e.retry_after_s))
            else:
                arm["int_never_admitted"] = arm.get("int_never_admitted", 0) + 1
        int_waits = sorted(f.result(timeout=1200).ttft_s for f in int_futs)
        for f in bg_futs:
            try:
                f.result(timeout=1200)
            except (SchedulerRejected, DeadlineExceeded):
                pass
        arm["bg_done"] = len(bg_futs)
        arm["p50"] = statistics.median(int_waits)
        arm["p95"] = int_waits[min(len(int_waits) - 1, math.ceil(0.95 * len(int_waits)) - 1)]
        return arm

    out: dict = {}
    # arm A: legacy unbounded FIFO (scheduler=None)
    eng, _ = _build_gen_engine(max_slots=4, buckets=(32,))
    try:
        fifo = drive(eng)
    finally:
        eng.stop()
    # arm B: admission-controlled scheduler, bounded queue.  Degradation and
    # the estimated-wait test are off so the contrast isolates ordering +
    # depth-bound shedding; the knobs get their own coverage in tests.
    sched = RequestScheduler(
        SchedulerConfig(max_queue=12, admit_max_wait_s=None, degrade_at=1.0)
    )
    eng, _ = _build_gen_engine(max_slots=4, buckets=(32,), scheduler=sched)
    try:
        s = drive(eng)
        # deadline reclaim: a deliberately-too-tight deadline on a warm
        # engine; the slot must come back within ~a decode tick
        t0 = time.perf_counter()
        fut = eng.submit([9] * 16, max_tokens=512, temperature=0.0, deadline_s=0.05)
        try:
            fut.result(timeout=600)
            out["overload_deadline_reclaimed"] = False
        except DeadlineExceeded:
            out["overload_deadline_reclaimed"] = True
            out["overload_deadline_reclaim_s"] = round(
                max(0.0, time.perf_counter() - t0 - 0.05), 4
            )
        stats = eng.tick_stats()
    finally:
        eng.stop()
    out.update(
        {
            "overload_fifo_interactive_p50_wait_s": round(fifo["p50"], 4),
            "overload_fifo_interactive_p95_wait_s": round(fifo["p95"], 4),
            "overload_sched_interactive_p50_wait_s": round(s["p50"], 4),
            "overload_sched_interactive_p95_wait_s": round(s["p95"], 4),
            "overload_interactive_p95_speedup": round(
                fifo["p95"] / max(1e-9, s["p95"]), 2
            ),
            "overload_shed": s["shed"],
            "overload_retry_after_s": round(s["retry_after_s"], 3)
            if s["retry_after_s"] is not None
            else None,
            "overload_interactive_retries": s["int_retries"],
            "overload_bg_requests": n_bg,
            "overload_interactive_requests": n_int,
            "overload_reclaimed_slots": stats.get("reclaimed_slots", 0),
            "overload_sched_wait_stats": stats.get("sched", {}).get("wait", {}),
        }
    )
    return out


_OVERLOAD_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_overload()))
"""


def bench_chaos() -> dict:
    """chaos_* section (serving/faults.py + engine supervision evidence):
    goodput and recovery-time-to-first-success under an injected engine-fatal
    fault vs the no-fault baseline on the SAME trace.

    The trace runs greedy requests through a small engine twice.  Baseline
    arm: no injector.  Chaos arm: ``tick_raise`` armed ONCE mid-trace (exact,
    not probabilistic) — the crash-only restart must complete the whole trace
    anyway (queued work preserved, token-less in-flight work re-submitted),
    and the time from the fault firing to the next successful completion is
    the recovery number."""
    import numpy as np

    from django_assistant_bot_tpu.serving.faults import FaultInjector

    n_req, n_new = 10, 24
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 255, 16).tolist() for _ in range(n_req)]

    def drive(eng, injector=None):
        # warm the loop (shapes are compiled by engine.warmup())
        eng.submit([1, 2, 3], max_tokens=4, temperature=0.0).result(timeout=600)
        done_ok: list = []  # time.monotonic() of each successful completion

        def note_done(f):
            if not f.cancelled() and f.exception() is None:
                done_ok.append(time.monotonic())

        t0 = time.perf_counter()
        futs = []
        for i, p in enumerate(prompts):
            if injector is not None and i == n_req // 2:
                # armed after half the trace is submitted: some requests are
                # in flight, some queued — the restart must preserve both
                injector.arm("tick_raise")
            f = eng.submit(p, max_tokens=n_new, temperature=0.0)
            f.add_done_callback(note_done)
            futs.append(f)
        ok = failed = 0
        for f in futs:
            try:
                f.result(timeout=1200)
                ok += 1
            except Exception:
                failed += 1
        wall = time.perf_counter() - t0
        recovery = None
        if injector is not None:
            fault_at = injector.last_fire_at("tick_raise")
            if fault_at is not None:
                after = [t for t in done_ok if t >= fault_at]
                if after:
                    recovery = min(after) - fault_at
        return ok, failed, wall, recovery

    out: dict = {}
    eng, _ = _build_gen_engine(max_slots=4, buckets=(32,))
    try:
        ok, failed, wall, _ = drive(eng)
        out["chaos_baseline_goodput_frac"] = round(ok / n_req, 4)
        out["chaos_baseline_wall_s"] = round(wall, 4)
    finally:
        eng.stop()
    inj = FaultInjector({})
    eng, _ = _build_gen_engine(max_slots=4, buckets=(32,))
    eng._faults = inj  # engine built fault-free; the injector rides along
    try:
        ok, failed, wall, recovery = drive(eng, injector=inj)
        sup = eng.supervision_stats()
        out.update(
            {
                "chaos_goodput_frac": round(ok / n_req, 4),
                "chaos_failed": failed,
                "chaos_wall_s": round(wall, 4),
                "chaos_recovery_s": round(recovery, 4) if recovery is not None else None,
                "chaos_restarts": sup["engine_restarts"],
                "chaos_resubmitted": sup["restarted_requests_resubmitted"],
                "chaos_poisoned": sup["poisoned_requests"],
                "chaos_injector_fires": inj.stats().get("tick_raise", {}).get("fires", 0),
            }
        )
    finally:
        eng.stop()
    return out


_CHAOS_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_chaos()))
"""


# Mesh-sliced fleet A/B (parallel/slicing.py; docs/MULTICHIP.md): 4 replicas
# x TP-2 on DISJOINT device slices of a forced-8-device CPU host vs the
# 1-slice arm, on one pinned greedy trace.  Runs in its own subprocess (the
# parent bench owns at most one device; the slice topology needs 8) in BOTH
# SMALL and real mode.  Aggregate = SUM of per-slice steady rates with each
# slice measured alone (interleaved A/B/A on slice 0): the slices' devices
# are disjoint by construction — asserted on the placement — so on real
# hardware they run physically in parallel, while on THIS forced host all 8
# "devices" share the machine's cores and a concurrent wall-clock run
# measures core contention, not slice scaling.  That concurrent number is
# recorded anyway (multichip_concurrent_frac, with multichip_host_cores) as
# the honesty key, same discipline as the stream section's GIL note.
_MULTICHIP_SNIPPET = """
import json, os, time
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")
if len(jax.devices()) < 8:
    # the compile-cache preamble (or a launch plugin) initialized the backend
    # before the flag landed: rebuild it as the 8-device CPU platform
    from jax.extend import backend as _jax_backend
    _jax_backend.clear_backends()
assert len(jax.devices()) == 8, len(jax.devices())
from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.parallel import (
    MeshPlanner, best_mesh_shape, make_mesh, shard_pytree)
from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine

SLICES, RD, MT = 4, 2, 32
cfg = DecoderConfig.tiny()
host_params = llama.init(cfg, jax.random.PRNGKey(0))  # ONE shared host copy
tok = ByteTokenizer()
planner = MeshPlanner(RD)
def build(sl):
    with sl.mesh:
        p = shard_pytree(host_params, llama.logical_axes(cfg), sl.mesh)
    e = GenerationEngine(cfg, p, tok, max_slots=4, max_seq_len=64,
                         lookahead=3, burst=4, prefix_cache_size=0,
                         mesh=sl.mesh)
    e.slice_id = sl.slice_id
    return e.start()
engines = [build(planner.acquire()) for _ in range(SLICES)]
# placement: every slice's weights live on its own disjoint device pair
placed = [set(e.slice_devices) for e in engines]
assert all(len(p) == RD for p in placed)
assert len(set().union(*placed)) == SLICES * RD

prompts = ["pinned trace prompt %d" % i for i in range(4)]
def drive(e, mt=MT):
    futs = [e.submit(tok.encode(p), max_tokens=mt, temperature=0.0)
            for p in prompts]
    t0 = time.perf_counter(); tot = 0
    for f in futs:
        tot += f.result(timeout=600).completion_tokens
    return tot / (time.perf_counter() - t0)
for e in engines:
    drive(e, 8)  # compiles out of the measurement
rates = [drive(e) for e in engines]
one = (rates[0] + drive(engines[0])) / 2  # A/B/A: slice 0 re-measured
agg = sum(rates)
# concurrent wall-clock honesty probe (all 4 slices driven at once)
t0 = time.perf_counter(); tot = 0
futs = [e.submit(tok.encode(p), max_tokens=MT, temperature=0.0)
        for e in engines for p in prompts]
for f in futs:
    tot += f.result(timeout=600).completion_tokens
conc = tot / (time.perf_counter() - t0)
# same weights, same trace -> every slice decodes the identical tokens,
# AND they match the GLOBAL-mesh engine (the acceptance bit-identity: a
# slices-only comparison could miss a divergence that hit every slice the
# same way)
outs = [e.submit(tok.encode("identity probe"), max_tokens=12,
                 temperature=0.0).result(timeout=600).token_ids
        for e in engines]
# per-slice HBM ledgers vs the single-global-mesh fleet's footprint
# (weights once on the global mesh + SLICES pools)
sl_hbm = [e.slice_stats()["hbm_bytes"] for e in engines]
gmesh = make_mesh(best_mesh_shape(8, want_model=RD))
with gmesh:
    gp = shard_pytree(host_params, llama.logical_axes(cfg), gmesh)
ge = GenerationEngine(cfg, gp, tok, max_slots=4, max_seq_len=64,
                      lookahead=3, burst=4, prefix_cache_size=0,
                      mesh=gmesh).start()
outs.append(ge.submit(tok.encode("identity probe"), max_tokens=12,
                      temperature=0.0).result(timeout=600).token_ids)
single_mesh = ge.hbm_weight_bytes + SLICES * ge.hbm_kv_bytes
for e in engines:
    e.stop()
ge.stop()
print(json.dumps({
    "multichip_slices": SLICES,
    "multichip_replica_devices": RD,
    "multichip_agg_tok_s": round(agg, 1),
    "multichip_tok_s_1slice": round(one, 1),
    "multichip_speedup": round(agg / one, 3),
    "multichip_scaling_frac": round(agg / (SLICES * one), 4),
    "multichip_per_slice_tok_s": [round(r, 1) for r in rates],
    "multichip_concurrent_agg_tok_s": round(conc, 1),
    "multichip_concurrent_frac": round(conc / (SLICES * one), 4),
    "multichip_host_cores": os.cpu_count(),
    "multichip_output_identical": all(o == outs[0] for o in outs),
    "multichip_slice_hbm_bytes": sl_hbm[0],
    "multichip_fleet_hbm_bytes": sum(sl_hbm),
    "multichip_single_mesh_hbm_bytes": single_mesh,
    "multichip_hbm_frac": round(sum(sl_hbm) / single_mesh, 4),
}))
"""


def bench_multichip() -> dict:
    """multichip_* section: the mesh-sliced fleet scaling A/B (see the
    snippet's header note for methodology and the honesty keys)."""
    res, err = _subprocess_bench(_MULTICHIP_SNIPPET, timeout_s=420)
    return res if res else {"multichip_error": err}


def bench_router() -> dict:
    """router_* section (serving/router.py evidence): fleet failover — one of
    two engine replicas is killed mid-trace via the ``replica_dead`` chaos
    site (armed exactly once, same discipline as ``chaos_*``); token-less
    requests on the dead replica must re-route to the survivor (goodput 1.0,
    no client-visible failure), and after an operator restart the recovery
    time from the kill to the restarted replica's first successful completion
    is recorded.  A rolling restart under a live trickle rides along as the
    zero-shed drain evidence.

    Both replicas' loops are stalled (``slow_tick``) through the kill window
    so in-flight work is still client-token-less when the replica dies — the
    re-routable regime the acceptance contract names; ``router_failed_past_
    first_token`` records any request that slipped past that window."""
    import numpy as np

    from django_assistant_bot_tpu.serving.faults import FaultInjector
    from django_assistant_bot_tpu.serving.router import EngineRouter

    n_req, n_new = 10, 24
    rng = np.random.default_rng(13)
    prompts = [rng.integers(1, 255, 16).tolist() for _ in range(n_req)]

    engines = []
    for _ in range(2):
        eng, _ = _build_gen_engine(max_slots=4, buckets=(32,))
        # a probability-0 spec pins the injected stall length; arm() below
        # makes the schedule exact
        eng._faults = FaultInjector({"slow_tick": {"p": 0.0, "delay_s": 0.2}})
        engines.append(eng)
    router_inj = FaultInjector({})
    router = EngineRouter(engines, faults=router_inj, breaker_reset_s=0.5)
    out: dict = {}
    try:
        for i in range(2):  # warm both replicas through the router
            router.submit([1, 2, 3 + i], max_tokens=4, temperature=0.0).result(
                timeout=600
            )
        for eng in engines:
            eng._faults.arm("slow_tick", 12)
        t0 = time.perf_counter()
        futs = []
        for i, p in enumerate(prompts):
            if i == n_req // 2:
                # the NEXT dispatch kills the replica it was about to pick —
                # its queued + in-flight (token-less) work must re-route
                router_inj.arm("replica_dead")
            futs.append(router.submit(p, max_tokens=n_new, temperature=0.0))
        ok = failed = 0
        for f in futs:
            try:
                f.result(timeout=1200)
                ok += 1
            except Exception:
                failed += 1
        wall = time.perf_counter() - t0
        kill_at = router_inj.last_fire_at("replica_dead")
        dead = [i for i, e in enumerate(engines) if not e._running]
        recovery = None
        if dead and kill_at is not None:
            idx = dead[0]
            router.restart_replica(idx)
            # pin one request onto the restarted replica: recovery is the
            # kill -> first-success-on-restarted-replica interval
            for j, rep in enumerate(router.replicas):
                rep.draining = j != idx
            try:
                router.submit(
                    [7, 7, 7], max_tokens=4, temperature=0.0
                ).result(timeout=600)
            finally:
                for rep in router.replicas:
                    rep.draining = False
            at = router.replicas[idx].last_success_at
            if at is not None:
                recovery = at - kill_at
        stats = router.router_stats()
        out.update(
            {
                "router_goodput_frac": round(ok / n_req, 4),
                "router_failed": failed,
                "router_wall_s": round(wall, 4),
                "router_reroutes": stats["reroutes"],
                "router_rerouted_failed": stats["rerouted_failed"],
                "router_failed_past_first_token": stats[
                    "failed_past_first_token"
                ],
                "router_recovery_s": round(recovery, 4)
                if recovery is not None
                else None,
                "router_replica_killed": bool(dead),
            }
        )
        # rolling restart under a live trickle: the zero-downtime drain path
        trickle = [
            router.submit([9, 9, 9 + i], max_tokens=4, temperature=0.0)
            for i in range(4)
        ]
        t0 = time.perf_counter()
        reports = router.rolling_restart(deadline_s=60.0)
        shed = sum(r["forced_failures"] for r in reports)
        ok2 = sum(
            1 for f in trickle if f.exception(timeout=600) is None
        )
        out.update(
            {
                "router_rolling_restart_s": round(time.perf_counter() - t0, 4),
                "router_drain_shed": shed,
                "router_drain_trickle_ok": ok2,
            }
        )
    finally:
        router.stop()
    return out


_ROUTER_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_router()))
"""


def _serve_app_thread(app):
    """Host an aiohttp app on its own thread's event loop; returns
    ``(base_url, stop)``.  The fleet arms need REAL localhost HTTP peers —
    the wire, the codec, and the re-route path are the things under test."""
    import asyncio
    import threading

    from aiohttp import web

    loop = asyncio.new_event_loop()
    started = threading.Event()
    state: dict = {}

    def _run():
        asyncio.set_event_loop(loop)

        async def _up():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            state["runner"] = runner
            state["port"] = runner.addresses[0][1]

        loop.run_until_complete(_up())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    started.wait(60)

    def _stop():
        async def _down():
            await state["runner"].cleanup()

        try:
            asyncio.run_coroutine_threadsafe(_down(), loop).result(30)
        except Exception:
            pass
        loop.call_soon_threadsafe(loop.stop)
        t.join(15)

    return f"http://127.0.0.1:{state['port']}", _stop


def _fleet_trace():
    """ONE pinned mixed chat/longctx trace shared by every fleet arm (seed
    pinned — same arrivals, shapes, and prefixes in each arm)."""
    from django_assistant_bot_tpu.workload.generator import (
        WorkloadConfig,
        WorkloadGenerator,
    )

    return WorkloadGenerator(
        WorkloadConfig(
            seed=7,
            duration_s=10.0,
            base_rps=2.0,
            shape="constant",
            tenants=2,
            background_frac=0.0,
            longctx_frac=0.25,
            chat_prompt_tokens=(8, 40),
            chat_max_tokens=(4, 10),
            longctx_prompt_tokens=(80, 160),
            longctx_max_tokens=(6, 12),
            prefix_frac=0.5,
            prefix_tokens=16,
        )
    ).generate()


# the identity probe: long enough that the disagg arm takes the
# prefill-pool handoff path (suffix >= 64)
_FLEET_IDENT_PROMPT = [11 + (i % 180) for i in range(100)]


def bench_fleet() -> dict:
    """fleet_* section (serving/fleet.py + docs/FLEET.md evidence): the
    cross-process fleet plane measured over REAL localhost HTTP peers —
    each peer a full serve stack (registry + engine + fleet plane + aiohttp
    app) with its own KV pools, exactly the cross-host shape minus the DCN.

    Three arms on the SAME pinned mixed chat/longctx trace:

    - **unified**: two unified peers behind the FleetRouter (the baseline);
    - **disagg**: one prefill-pool + one decode-pool peer — long prompts
      prefill in the prefill pool, pages ship over ``/fleet/kv/put``, and
      the decode pool serves the tokens; the identity probe asserts the
      disaggregated output matches the unified arm bit-for-bit;
    - **chaos**: two unified peers, one killed mid-trace — every token-less
      request must re-route to the survivor (goodput 1.0, reroutes > 0).
    """
    from django_assistant_bot_tpu.serving.fleet import (
        FleetPeer,
        FleetPlane,
        FleetRouter,
    )
    from django_assistant_bot_tpu.serving.registry import ModelRegistry
    from django_assistant_bot_tpu.serving.server import create_app
    from django_assistant_bot_tpu.workload.generator import prompt_ids_for

    def _peer(pool):
        reg = ModelRegistry.from_config(
            {
                "tiny-chat": {
                    "kind": "decoder",
                    "tiny": True,
                    "max_slots": 4,
                    "max_seq_len": 256,
                    "kv_host_bytes": 1 << 26,
                    "prefix_min_tokens": 16,
                }
            }
        )
        plane = FleetPlane(reg, name=f"bench-{pool}", pool=pool)
        reg.fleet_plane = plane
        url, stop = _serve_app_thread(create_app(reg))
        return {"reg": reg, "plane": plane, "url": url, "stop": stop}

    reqs = _fleet_trace()

    def _arm(pools, *, chaos=False):
        peers = [_peer(p) for p in pools]
        for i, p in enumerate(peers):
            p["plane"].peers = [
                (f"bench{j}", q["url"]) for j, q in enumerate(peers) if j != i
            ]
        router = FleetRouter(
            [
                FleetPeer(f"bench{i}", p["url"], pool=pool, timeout_s=600.0)
                for i, (p, pool) in enumerate(zip(peers, pools))
            ],
            model="tiny-chat",
            refresh_interval_s=1e9,  # the arm drives refresh itself
            request_timeout_s=600.0,
        )
        alive = [True] * len(peers)
        out: dict = {}
        try:
            router.refresh()
            router._last_refresh = router._clock()
            # warm every peer's prefill/decode buckets off the clock
            for p in peers:
                for rep in router.peers:
                    rep.draining = rep.base_url != p["url"]
                for warm in ([3] * 12, _FLEET_IDENT_PROMPT):
                    try:
                        router.submit(
                            list(warm), max_tokens=2, temperature=0.0
                        ).result(timeout=600)
                    except Exception:
                        pass  # pool-role peers reject half the warmups
            for rep in router.peers:
                rep.draining = False
            kill_at = len(reqs) // 3 if chaos else None
            t0 = time.perf_counter()
            futs = []
            for i, r in enumerate(reqs):
                if kill_at is not None and i == kill_at:
                    peers[0]["stop"]()
                    peers[0]["reg"].stop()
                    alive[0] = False
                futs.append(
                    router.submit(
                        prompt_ids_for(r),
                        max_tokens=r.max_tokens,
                        temperature=0.0,
                        prefix_len=r.prefix_len,
                        priority=r.priority,
                        tenant=r.tenant,
                    )
                )
            ok = failed = tokens = 0
            for f in futs:
                try:
                    tokens += f.result(timeout=900).completion_tokens
                    ok += 1
                except Exception:
                    failed += 1
            wall = time.perf_counter() - t0
            ident = None
            if not chaos:
                ident = router.submit(
                    list(_FLEET_IDENT_PROMPT), max_tokens=8, temperature=0.0
                ).result(timeout=600)
            ttft = max(
                p["reg"].generators["tiny-chat"].latency_stats()["ttft_p95_ms"]
                for p, up in zip(peers, alive)
                if up
            )
            out = {
                "goodput_frac": round(ok / len(reqs), 4),
                "failed": failed,
                "agg_tok_s": round(tokens / wall, 2) if wall > 0 else 0.0,
                "ttft_p95_ms": round(ttft, 2),
                "reroutes": router.reroutes,
                "handoffs": router.handoffs,
                "pages_shipped": router.pages_shipped,
                "handoff_fallbacks": router.handoff_fallbacks,
                "ident_token_ids": ident.token_ids if ident else None,
            }
        finally:
            router.close()
            for p, up in zip(peers, alive):
                if up:
                    p["stop"]()
                    p["reg"].stop()
        return out

    uni = _arm(("unified", "unified"))
    dis = _arm(("prefill", "decode"))
    cha = _arm(("unified", "unified"), chaos=True)
    return {
        "fleet_requests": len(reqs),
        "fleet_unified_agg_tok_s": uni["agg_tok_s"],
        "fleet_unified_ttft_p95_ms": uni["ttft_p95_ms"],
        "fleet_unified_goodput_frac": uni["goodput_frac"],
        "fleet_disagg_agg_tok_s": dis["agg_tok_s"],
        "fleet_disagg_ttft_p95_ms": dis["ttft_p95_ms"],
        "fleet_disagg_goodput_frac": dis["goodput_frac"],
        "fleet_handoffs": dis["handoffs"],
        "fleet_pages_shipped": dis["pages_shipped"],
        "fleet_handoff_fallbacks": dis["handoff_fallbacks"],
        "fleet_output_identical": bool(
            uni["ident_token_ids"]
            and uni["ident_token_ids"] == dis["ident_token_ids"]
        ),
        "fleet_chaos_goodput_frac": cha["goodput_frac"],
        "fleet_chaos_failed": cha["failed"],
        "fleet_reroutes": cha["reroutes"],
    }


_FLEET_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_fleet()))
"""


def bench_fleet_netchaos() -> dict:
    """fleet_chaos_net_* section (serving/fleet.py + serving/faults.py net
    sites; docs/FLEET.md "Failure modes" evidence): the pinned fleet trace
    replayed over two REAL localhost serve stacks under a seeded network
    chaos schedule — the messy middle the peer-kill arm can't reach (both
    peers alive, the wire misbehaving).

    Phases on the SAME trace as bench_fleet, driven by an offset clock the
    arm shares between the injector and the router (jumping the offset
    crosses window/TTL/breaker thresholds deterministically, no wall-clock
    sleeps):

    - **partition**: the ``netchaos->bench0`` edge alone drops at connect
      time (a seeded ``net_partition`` window); every affected request must
      re-route token-lessly to bench1, refresh failures are classified, and
      after ``registry_ttl_s`` of unreachability bench0's gossip-learned
      affinity claims age out of the prefix registry (TTL drop);
    - **heal**: the window closes; the next refresh forces the anti-entropy
      reset-snapshot resync and the convergence time lands in
      ``reconcile_last_s``;
    - **dedup probe**: ``net_drop`` armed once — the request is executed by
      the peer but the response is lost, the router retries the SAME peer
      under the idempotency key, and the ledger answers (criterion:
      duplicate executions == 0);
    - **corrupt probe**: ``net_corrupt`` armed for three ``/fleet/kv/put``
      transfers — the CRC32C envelope must reject all three (criterion:
      zero corrupt payloads absorbed).
    """
    from django_assistant_bot_tpu.serving.faults import FaultInjector
    from django_assistant_bot_tpu.serving.fleet import (
        FleetPlane,
        FleetRouter,
        PeerClient,
        PeerHTTPError,
    )
    from django_assistant_bot_tpu.serving.registry import ModelRegistry
    from django_assistant_bot_tpu.serving.server import create_app
    from django_assistant_bot_tpu.workload.generator import prompt_ids_for

    offset = [0.0]

    def clk():
        return time.monotonic() + offset[0]

    inj = FaultInjector(
        {
            "net_partition": {
                "start_after_s": 1000.0,
                "duration_s": 1000.0,
                "edges": ["netchaos->bench0"],
            }
        },
        seed=0,
        clock=clk,
    )

    def _peer(i):
        reg = ModelRegistry.from_config(
            {
                "tiny-chat": {
                    "kind": "decoder",
                    "tiny": True,
                    "max_slots": 4,
                    "max_seq_len": 256,
                    "kv_host_bytes": 1 << 26,
                    "prefix_min_tokens": 16,
                }
            }
        )
        plane = FleetPlane(reg, name=f"bench{i}", pool="unified")
        reg.fleet_plane = plane
        url, stop = _serve_app_thread(create_app(reg))
        return {"reg": reg, "plane": plane, "url": url, "stop": stop}

    reqs = _fleet_trace()
    peers = [_peer(0), _peer(1)]
    router = FleetRouter(
        [(f"bench{i}", p["url"]) for i, p in enumerate(peers)],
        model="tiny-chat",
        name="netchaos",
        refresh_interval_s=1e9,  # the arm drives refresh itself
        request_timeout_s=600.0,
        registry_ttl_s=5.0,
        timeout_retries=1,
        clock=clk,
        injector=inj,
    )
    out: dict = {}
    try:
        router.refresh()
        router._last_refresh = router._clock()
        # warm both peers' compile buckets off the clock
        for p in peers:
            for rep in router.peers:
                rep.draining = rep.base_url != p["url"]
            for warm in ([3] * 12, _FLEET_IDENT_PROMPT):
                try:
                    router.submit(
                        list(warm), max_tokens=2, temperature=0.0
                    ).result(timeout=600)
                except Exception:
                    pass
        for rep in router.peers:
            rep.draining = False
        idem0 = sum(p["plane"].stats()["idem_executions"] for p in peers)

        def _replay(chunk):
            futs = [
                router.submit(
                    prompt_ids_for(r),
                    max_tokens=r.max_tokens,
                    temperature=0.0,
                    prefix_len=r.prefix_len,
                    priority=r.priority,
                    tenant=r.tenant,
                )
                for r in chunk
            ]
            ok = failed = 0
            for f in futs:
                try:
                    f.result(timeout=900)
                    ok += 1
                except Exception:
                    failed += 1
            return ok, failed

        third = max(1, len(reqs) // 3)
        ok = failed = 0
        # phase A: clean wire
        a_ok, a_failed = _replay(reqs[:third])
        ok, failed = ok + a_ok, failed + a_failed
        # partition ON (jump into the seeded window): the first slice of
        # phase B dispatches while the router still believes bench0 is
        # healthy — those hops fail at connect and re-route token-lessly
        offset[0] += 1000.0
        half_b = reqs[third : third + max(1, third // 2)]
        b_ok, b_failed = _replay(half_b)
        ok, failed = ok + b_ok, failed + b_failed
        # TTL crossing: refresh stamps unreachable_since, the offset jump
        # ages it past the TTL, the second refresh drops bench0's
        # gossip-learned holdings from the prefix registry
        router.refresh()
        offset[0] += 10.0
        router.refresh()
        ttl_dropped_during = router.stats()["ttl_drops"]
        b2_ok, b2_failed = _replay(reqs[third + len(half_b) : 2 * third])
        ok, failed = ok + b2_ok, failed + b2_failed
        # HEAL (jump past the window's end): the next refresh reconciles the
        # diverged gossip view via the forced reset-snapshot exchange
        offset[0] += 1000.0
        router.refresh()
        c_ok, c_failed = _replay(reqs[2 * third :])
        ok, failed = ok + c_ok, failed + c_failed
        # dedup probe: the response is lost AFTER the peer executed — the
        # same-peer retry must be answered from the idempotency ledger
        for rep in router.peers:
            inj.arm("net_drop", 1, key=f"netchaos->{rep.name}")
        probe_ok = 0
        try:
            router.submit(
                list(_FLEET_IDENT_PROMPT), max_tokens=4, temperature=0.0
            ).result(timeout=600)
            probe_ok = 1
        except Exception:
            pass
        idem_execs = (
            sum(p["plane"].stats()["idem_executions"] for p in peers) - idem0
        )
        executed_unique = ok + probe_ok
        duplicates = max(0, idem_execs - executed_unique)
        dedup_hits = sum(
            p["plane"].stats()["idem_hits"] + p["plane"].stats()["idem_coalesced"]
            for p in peers
        )
        # corrupt probe: one wire entry (a real warm export when available,
        # else a locally encoded envelope — the CRC rejection under test
        # happens at decode, before any geometry check) re-put three times
        # through a corrupting edge — the checksum must reject every one
        wire = None
        for p in peers:
            wire = PeerClient(p["url"], timeout_s=60.0).post_for_bytes(
                "/fleet/kv/get",
                {
                    "model": "tiny-chat",
                    "prompt_ids": list(_FLEET_IDENT_PROMPT),
                    "prefix_len": len(_FLEET_IDENT_PROMPT) - 1,
                },
                timeout_s=60.0,
            )
            if wire is not None:
                break
        if wire is None:
            import numpy as np

            from django_assistant_bot_tpu.serving.fleet import encode_kv_entry
            from django_assistant_bot_tpu.serving.kv_pool import HostPrefixEntry

            k = np.arange(2 * 24 * 8, dtype=np.float16).reshape(2, 24, 1, 8, 1)
            wire = encode_kv_entry(
                HostPrefixEntry(
                    key=tuple(range(24)),
                    length=24,
                    k=k,
                    v=k + 1,
                    nbytes=2 * k.nbytes,
                    pages=3,
                )
            )
        probe_client = PeerClient(
            peers[1]["url"], timeout_s=60.0, injector=inj, fault_key="probe"
        )
        rejects0 = peers[1]["plane"].stats()["kv_integrity_rejects"]
        corrupt_injected = corrupt_rejected = corrupt_absorbed = 0
        for _ in range(3):
            inj.arm("net_corrupt", 1, key="probe")
            corrupt_injected += 1
            try:
                res = probe_client.post_bytes(
                    "/fleet/kv/put?model=tiny-chat", wire, timeout_s=60.0
                )
                if res.get("stored"):
                    corrupt_absorbed += 1
            except PeerHTTPError as e:
                if e.reason == "wire_integrity":
                    corrupt_rejected += 1
        server_rejects = (
            peers[1]["plane"].stats()["kv_integrity_rejects"] - rejects0
        )
        rs = router.stats()
        out = {
            "fleet_chaos_net_requests": len(reqs),
            "fleet_chaos_net_goodput_frac": round(ok / len(reqs), 4),
            "fleet_chaos_net_failed": failed,
            "fleet_chaos_net_reroutes": rs["reroutes"],
            "fleet_chaos_duplicate_execs": duplicates,
            "fleet_chaos_dedup_hits": dedup_hits,
            "fleet_chaos_dedup_probe_ok": probe_ok,
            "fleet_chaos_corrupt_injected": corrupt_injected,
            "fleet_chaos_corrupt_rejected": corrupt_rejected,
            "fleet_chaos_corrupt_absorbed": corrupt_absorbed,
            "fleet_chaos_corrupt_server_rejects": server_rejects,
            "fleet_chaos_ttl_drops": rs["ttl_drops"],
            "fleet_chaos_ttl_dropped_in_partition": ttl_dropped_during,
            "fleet_chaos_reconciles": rs["reconciles"],
            "fleet_chaos_reconcile_s": rs["reconcile_last_s"],
            "fleet_chaos_timeout_retries": rs["timeout_retries"],
            "fleet_chaos_refresh_reasons": dict(rs["refresh_failure_reasons"]),
        }
    finally:
        router.close()
        for p in peers:
            p["stop"]()
            p["reg"].stop()
    return out


_FLEET_NETCHAOS_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_fleet_netchaos()))
"""


def bench_autoscale() -> dict:
    """autoscale_* section (serving/autoscaler.py + workload/ evidence): the
    closed-loop A/B.  ONE seeded diurnal-ramp trace (workload/generator.py,
    seed pinned — deterministic arrivals, tenants, and token shapes) drives
    two fleets built from the same shared weights:

    - **off**: fixed at the minimum size (the reference's fixed-backend
      shape — overload is handled only by shedding);
    - **on**: starts at the minimum with the SLO autoscaler closing the loop
      (scale-up on TTFT burn/shed-rate/backlog, trough scale-down).

    Engine speed is pinned by a deterministic ``slow_tick`` injection (every
    tick pays a fixed floor), so "the peak overloads one replica, three
    hold it" is a property of the CONFIG, not of whichever host runs the
    bench.  Reported: p95 TTFT and client-visible sheds per arm, the on-arm's
    replica-seconds (the autoscaler's cost integral), and the fixed MAX-size
    fleet's replica-seconds as the budget bound the on-arm must beat."""
    import jax

    from django_assistant_bot_tpu.models import llama
    from django_assistant_bot_tpu.parallel import get_mesh, shard_pytree
    from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine
    from django_assistant_bot_tpu.serving.autoscaler import (
        AutoscalerConfig,
        SLOAutoscaler,
    )
    from django_assistant_bot_tpu.serving.engine import EngineUnavailable
    from django_assistant_bot_tpu.serving.faults import FaultInjector
    from django_assistant_bot_tpu.serving.router import EngineRouter
    from django_assistant_bot_tpu.serving.scheduler import (
        RequestScheduler,
        SchedulerConfig,
        SchedulerRejected,
    )
    from django_assistant_bot_tpu.workload import (
        WorkloadConfig,
        WorkloadGenerator,
        prompt_ids_for,
        replay,
    )

    MIN_R, MAX_R = 1, 3
    TICK_FLOOR_S = 0.03  # deterministic per-tick latency injection
    SLO_TTFT_S = 0.5
    cfg = _decoder_cfg()
    params = llama.init(cfg, jax.random.PRNGKey(0))
    mesh = get_mesh()
    with mesh:
        params = shard_pytree(params, llama.logical_axes(cfg), mesh)
    # the SAME trace for both arms: one diurnal period — trough, a peak that
    # overloads one 2-slot replica at the injected tick floor, trough again
    trace = WorkloadGenerator(
        WorkloadConfig(
            seed=11,
            duration_s=24.0,
            base_rps=24.0,
            shape="diurnal",
            diurnal_period_s=24.0,
            diurnal_min_frac=0.15,
            tenants=4,
            hot_tenant_frac=0.5,
            background_frac=0.1,
            longctx_frac=0.1,
            chat_prompt_tokens=(8, 24),
            chat_max_tokens=(4, 12),
            longctx_prompt_tokens=(32, 56),
            longctx_max_tokens=(8, 16),
            # no shared prefixes: prefix-suffix prefill programs aren't in
            # the factory's warmup set, and a mid-peak compile stall would
            # pollute the latency A/B with compile noise
            prefix_frac=0.0,
        )
    ).generate()

    def build_engine(i: int) -> GenerationEngine:
        eng = GenerationEngine(
            cfg,
            params,
            ByteTokenizer(),
            max_slots=2,
            max_seq_len=128,
            prefill_buckets=(64,),
            chunk_size=64,
            # one token per slot per tick: with the injected tick floor the
            # per-replica capacity is a CONFIG constant (~2 tok / 30 ms),
            # so "the peak overloads one replica, three hold" is
            # host-independent
            lookahead=1,
            burst=1,
            mesh=mesh,
            name=f"as/r{i}",
            scheduler=RequestScheduler(
                SchedulerConfig(
                    max_queue=8, admit_max_wait_s=2.0, admit_hist_min_samples=16
                )
            ),
            faults=FaultInjector({"slow_tick": {"p": 1.0, "delay_s": TICK_FLOOR_S}}),
        )
        eng.warmup()  # the compile cache makes replica 2..N's warmup a replay
        eng.start()
        return eng

    def run_arm(autoscale: bool) -> dict:
        engines = [build_engine(i) for i in range(MIN_R)]
        router = EngineRouter(engines, replica_factory=build_engine)
        asc = None
        if autoscale:
            asc = SLOAutoscaler(
                router,
                AutoscalerConfig(
                    min_replicas=MIN_R,
                    max_replicas=MAX_R,
                    interval_s=0.25,
                    slo_ttft_p95_s=SLO_TTFT_S,
                    up_consecutive=2,
                    up_cooldown_s=1.0,
                    down_consecutive=6,
                    down_cooldown_s=1.0,
                    drain_deadline_s=60.0,
                ),
                name="bench-autoscaler",
            ).start()
        futs = []
        shed = 0
        peak_fleet = len(router.replicas)

        def submit(ev):
            nonlocal shed, peak_fleet
            peak_fleet = max(peak_fleet, len(router.replicas))
            try:
                futs.append(
                    router.submit(
                        prompt_ids_for(ev),
                        max_tokens=ev.max_tokens,
                        temperature=0.0,
                        priority=ev.priority,
                        tenant=ev.tenant,
                        prefix_len=ev.prefix_len,
                    )
                )
            except (SchedulerRejected, EngineUnavailable):
                shed += 1

        try:
            router.submit([1, 2, 3], max_tokens=2, temperature=0.0).result(
                timeout=600
            )  # settle the first replica before the clock starts
            t0 = time.perf_counter()
            replay(trace, submit)
            ok = failed = 0
            for f in futs:
                try:
                    f.result(timeout=600)
                    ok += 1
                except Exception:
                    failed += 1
            wall = time.perf_counter() - t0
            lat = router.latency_stats()
            if asc is not None:
                asc.stop()  # also closes the replica-seconds integral
                replica_seconds = asc.replica_seconds
            else:
                replica_seconds = MIN_R * wall
            return {
                "wall_s": round(wall, 3),
                "requests": len(trace),
                "ok": ok,
                "failed": failed,
                "shed": shed,
                "ttft_p95_s": round(lat["ttft_p95_ms"] / 1e3, 4),
                "ttft_p50_s": round(lat["ttft_p50_ms"] / 1e3, 4),
                "replica_seconds": round(replica_seconds, 2),
                "peak_replicas": peak_fleet,
                "scale_ups": asc.scale_ups if asc else 0,
                "scale_downs": asc.scale_downs if asc else 0,
                "drain_shed": router.drain_shed,
            }
        finally:
            if asc is not None:
                asc.stop()
            router.stop()

    off = run_arm(False)
    on = run_arm(True)
    return {
        "autoscale_p95_ttft_off_s": off["ttft_p95_s"],
        "autoscale_p95_ttft_on_s": on["ttft_p95_s"],
        "autoscale_shed_off": off["shed"],
        "autoscale_shed_on": on["shed"],
        "autoscale_replica_seconds": on["replica_seconds"],
        # the cost bound the acceptance criterion names: a fixed fleet at the
        # MAX size pays max_replicas for the whole trace
        "autoscale_replica_seconds_fixed_max": round(MAX_R * off["wall_s"], 2),
        "autoscale_peak_replicas": on["peak_replicas"],
        "autoscale_scale_ups": on["scale_ups"],
        "autoscale_scale_downs": on["scale_downs"],
        "autoscale_drain_shed": on["drain_shed"],
        "autoscale_requests": len(trace),
        "autoscale_ok_on": on["ok"],
        "autoscale_ok_off": off["ok"],
        "autoscale_trace": "diurnal seed=11 24s peak=24rps tick_floor=30ms",
    }


_AUTOSCALE_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_autoscale()))
"""


def bench_kv_tier() -> dict:
    """kv_tier_* section (docs/KV_PAGING.md "Tiered KV" evidence): durable
    warm state on a many-session trace where live KV >> HBM.

    ONE pinned session-shaped trace (workload/generator.py sessions: per-
    session think-times, per-turn prompts extending the previous turn)
    drives two engines whose page pool is sized well BELOW the sessions'
    aggregate warm footprint, so LRU pressure evicts registered prefixes
    continuously:

    - **hbm_only** (kv_host_bytes=0): an evicted prefix is gone — the next
      turn re-prefills it cold (and the pre-tiering pool could only shed
      this shape as kv_pressure);
    - **tiered**: evictions spill to host DRAM and the next turn RESTORES
      (upload + suffix prefill, bit-identity-tested in
      tests/test_kv_tiering.py).

    Reported per arm: prefix-hit-eligible turn TTFT p50/p95, kv_pressure
    sheds, restore/spill counters.  Then two durability probes on the SAME
    warmed engines: (a) a tick_raise crash-only restart followed by one more
    turn per session — the tiered arm restores from the surviving host tier
    (goodput 1.0, warm TTFT), the hbm_only arm re-prefills; (b) a 2-replica
    fleet scale-down with migration on vs off — pages_lost_at_detach ~ 0
    with migration, > 0 without, and the migrated sessions' next turns stay
    warm-tier on the survivor."""
    import jax

    from django_assistant_bot_tpu.models import llama
    from django_assistant_bot_tpu.parallel import get_mesh, shard_pytree
    from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine
    from django_assistant_bot_tpu.serving.engine import EngineUnavailable
    from django_assistant_bot_tpu.serving.faults import FaultInjector
    from django_assistant_bot_tpu.serving.router import EngineRouter
    from django_assistant_bot_tpu.serving.scheduler import (
        RequestScheduler,
        SchedulerConfig,
        SchedulerRejected,
    )
    from django_assistant_bot_tpu.workload import (
        WorkloadConfig,
        WorkloadGenerator,
        WorkloadRequest,
        prompt_ids_for,
        replay,
    )

    cfg = _decoder_cfg()
    params = llama.init(cfg, jax.random.PRNGKey(0))
    mesh = get_mesh()
    with mesh:
        params = shard_pytree(params, llama.logical_axes(cfg), mesh)
    N_SESSIONS = 12 if not SMALL else 8
    POOL_PAGES = 10  # ~5 warm 2-page prefixes; the trace warms 2-3x that
    trace = WorkloadGenerator(
        WorkloadConfig(
            seed=13,
            duration_s=10.0,
            base_rps=0.0,  # sessions only: the many-idle-sessions shape
            sessions=N_SESSIONS,
            session_turns=(3, 4),
            session_think_s=(0.4, 1.5),
            session_prefix_tokens=(48, 80),
            session_body_tokens=(8, 24),
            session_max_tokens=(4, 8),
            session_start_frac=0.6,
        )
    ).generate()
    by_session: dict = {}
    for ev in trace:
        by_session.setdefault(ev.session, []).append(ev)

    def build(host_bytes, name):
        eng = GenerationEngine(
            cfg,
            params,
            ByteTokenizer(),
            max_slots=4,
            max_seq_len=256,
            prefill_buckets=(32, 64, 128),
            chunk_size=128,
            decode_kv_chunk=64,
            prefix_cache_size=32,  # entry bound is not the pressure: pages are
            prefix_min_tokens=16,
            kv_layout="paged",
            kv_pages=POOL_PAGES,
            kv_host_bytes=host_bytes,
            lookahead=1,
            burst=1,
            mesh=mesh,
            name=name,
            scheduler=RequestScheduler(
                SchedulerConfig(max_queue=64, admit_max_wait_s=8.0)
            ),
            faults=FaultInjector({}),
        )
        eng.warmup()
        eng.start()
        return eng

    def pctl(vals, frac):
        vals = sorted(vals)
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, max(0, round(frac * (len(vals) - 1))))]

    def next_turn(ev, extra=16):
        """One more turn of ev's session: the prompt extends ev's by
        `extra` tokens and declares ev's full prompt as its prefix."""
        return WorkloadRequest(
            t_s=0.0,
            tenant=ev.tenant,
            kind="session",
            prompt_tokens=ev.prompt_tokens + extra,
            max_tokens=4,
            prefix_len=ev.prompt_tokens,
            seed=ev.seed,
            session=ev.session,
            turn=ev.turn + 1,
        )

    def run_arm(host_bytes, name):
        eng = build(host_bytes, name)
        done = []
        shed = 0

        def submit(ev):
            nonlocal shed
            try:
                fut = eng.submit(
                    prompt_ids_for(ev),
                    max_tokens=ev.max_tokens,
                    temperature=0.0,
                    prefix_len=ev.prefix_len,
                )
            except (SchedulerRejected, EngineUnavailable):
                shed += 1
                return
            done.append((ev, fut))

        try:
            eng.submit([1, 2, 3], max_tokens=2, temperature=0.0).result(
                timeout=600
            )  # settle before the clock starts
            replay(trace, submit)
            hit_ttfts, ok = [], 0
            for ev, fut in done:
                try:
                    r = fut.result(timeout=600)
                    ok += 1
                    if ev.turn > 0:  # prefix-hit-eligible turns
                        hit_ttfts.append(r.ttft_s)
                except Exception:
                    pass
            st = eng.kv_stats()
            sched_shed = eng.scheduler.stats()["shed"]
            # ---- durability probe (a): crash-only restart mid-session ----
            eng._faults.arm("tick_raise")
            probes = [
                next_turn(evs[-1])
                for evs in by_session.values()
                if evs and evs[-1].turn > 0
            ]
            futs = [
                (p, eng.submit(
                    prompt_ids_for(p),
                    max_tokens=p.max_tokens,
                    temperature=0.0,
                    prefix_len=p.prefix_len,
                ))
                for p in probes
            ]
            restart_ttfts, restart_ok = [], 0
            for p, fut in futs:
                try:
                    r = fut.result(timeout=600)
                    restart_ok += 1
                    restart_ttfts.append(r.ttft_s)
                except Exception:
                    pass
            st_after = eng.kv_stats()
            return {
                "ok": ok,
                "shed_submit": shed,
                "kv_pressure_sheds": sched_shed.get("kv_pressure", 0),
                "hit_ttft_p50_s": round(pctl(hit_ttfts, 0.5), 4),
                "hit_ttft_p95_s": round(pctl(hit_ttfts, 0.95), 4),
                "hit_turns": len(hit_ttfts),
                "prefix_hits": st["prefix_hits"],
                "prefix_misses": st["prefix_misses"],
                "evictions": st["kv_evictions"],
                "restores": st.get("kv_restores", 0),
                "spills": st.get("kv_spills", 0),
                "restart_goodput_frac": round(
                    restart_ok / max(1, len(probes)), 4
                ),
                "restart_ttft_p50_s": round(pctl(restart_ttfts, 0.5), 4),
                "restarts": eng.engine_restarts,
                "restores_after_restart": st_after.get("kv_restores", 0)
                - st.get("kv_restores", 0),
            }
        finally:
            eng.stop()

    hbm = run_arm(0, "kvt/hbm")
    tiered = run_arm(1 << 30, "kvt/tiered")

    # ---- durability probe (b): scale-down migration on a 2-replica fleet --
    def scale_down_probe(migrate):
        engines = [build(1 << 30, f"kvt/sd{i}") for i in range(2)]
        router = EngineRouter(engines, names=["sd0", "sd1"])
        try:
            warm = [evs[0] for evs in list(by_session.values())[:4]]
            for ev in warm:
                router.submit(
                    prompt_ids_for(ev),
                    max_tokens=2,
                    temperature=0.0,
                    prefix_len=ev.prefix_len,
                ).result(timeout=600)
            # detach whichever replica holds warm state
            holder = 0
            for i, rep in enumerate(router.replicas):
                if rep.engine.kv_stats()["kv_shared_entries"] > 0:
                    holder = i
                    break
            router.remove_replica(holder, deadline_s=30.0, migrate=migrate)
            ttfts = []
            for ev in warm:
                r = router.submit(
                    prompt_ids_for(next_turn(ev)),
                    max_tokens=4,
                    temperature=0.0,
                    prefix_len=ev.prompt_tokens,
                ).result(timeout=600)
                ttfts.append(r.ttft_s)
            rs = router.router_stats()
            return {
                "pages_lost": rs["pages_lost_at_detach"],
                "entries_migrated": rs["entries_migrated"],
                "post_detach_ttft_p50_s": round(pctl(ttfts, 0.5), 4),
            }
        finally:
            router.stop()

    mig_on = scale_down_probe(True)
    mig_off = scale_down_probe(False)

    return {
        "kv_tier_hit_ttft_p50_s": tiered["hit_ttft_p50_s"],
        "kv_tier_hit_ttft_p95_s": tiered["hit_ttft_p95_s"],
        "kv_tier_hit_ttft_p50_hbm_only_s": hbm["hit_ttft_p50_s"],
        "kv_tier_hit_ttft_p95_hbm_only_s": hbm["hit_ttft_p95_s"],
        "kv_tier_pressure_sheds": tiered["kv_pressure_sheds"],
        "kv_tier_pressure_sheds_hbm_only": hbm["kv_pressure_sheds"],
        "kv_tier_prefix_hits": tiered["prefix_hits"],
        "kv_tier_prefix_hits_hbm_only": hbm["prefix_hits"],
        "kv_tier_prefix_misses": tiered["prefix_misses"],
        "kv_tier_prefix_misses_hbm_only": hbm["prefix_misses"],
        "kv_tier_restores": tiered["restores"],
        "kv_tier_spills": tiered["spills"],
        "kv_tier_evictions": tiered["evictions"],
        "kv_tier_ok": tiered["ok"],
        "kv_tier_ok_hbm_only": hbm["ok"],
        # restart survival: warm-tier TTFT + goodput through a tick_raise
        # crash (the host tier survives the allocator reset)
        "kv_tier_restart_goodput_frac": tiered["restart_goodput_frac"],
        "kv_tier_restart_goodput_frac_hbm_only": hbm["restart_goodput_frac"],
        "kv_tier_restart_ttft_p50_s": tiered["restart_ttft_p50_s"],
        "kv_tier_restart_ttft_p50_hbm_only_s": hbm["restart_ttft_p50_s"],
        "kv_tier_restores_after_restart": tiered["restores_after_restart"],
        # scale-down survival: migration keeps pages_lost_at_detach ~ 0 and
        # the migrated sessions' next turns warm on the survivor
        "kv_tier_detach_pages_lost_migrate_on": mig_on["pages_lost"],
        "kv_tier_detach_pages_lost_migrate_off": mig_off["pages_lost"],
        "kv_tier_detach_entries_migrated": mig_on["entries_migrated"],
        "kv_tier_detach_ttft_p50_migrate_on_s": mig_on["post_detach_ttft_p50_s"],
        "kv_tier_detach_ttft_p50_migrate_off_s": mig_off["post_detach_ttft_p50_s"],
        "kv_tier_trace": (
            f"sessions seed=13 n={N_SESSIONS} turns=3-4 "
            f"pool={POOL_PAGES}p page=64"
        ),
        # Honesty note (the stream-bench discipline): at CPU-tiny geometry a
        # full prefix re-prefill costs single-digit ms, so the wall-clock
        # TTFT arms measure mostly harness noise — the DETERMINISTIC tier
        # evidence here is hits/misses (warm turns served without prefix
        # recompute), restore/spill counts, restart goodput, and the
        # detach pages-lost A/B.  The TTFT criterion binds on real geometry,
        # where the avoided recompute is ~0.9 s (BENCH_r05 prefix numbers).
        "kv_tier_note": "toy-geometry TTFT ~ noise; hits/misses + counters are the tier evidence",
    }


_KV_TIER_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_kv_tier()))
"""


def bench_taskplane() -> dict:
    """taskplane_* section (tasks/queue.py + bot delivery ledger evidence):
    exactly-once-effect bot delivery under a mid-answer worker kill, A/B'd
    against the seed at-least-once plane on the SAME pinned update trace.

    The trace: 6 "dialogs", each answering with 4 parts through the REAL
    `_post_answer` delivery path into a recording platform.  Mid-trace the
    ``task_worker_lost`` chaos site kills the worker right after a part is
    delivered (exact fire-on-Nth schedule — deterministic, not flaky); lease
    expiry + reclaim re-dispatch the task.  Arm A (ledger ON, the shipped
    plane): every part must reach the user exactly once.  Arm B (ledger OFF —
    the seed behavior): the re-execution re-posts everything it already sent,
    which is the duplicate the ledger exists to kill.  Recovery time is
    kill → the killed task's completion (lease wait + re-run), and the DLQ
    must stay empty (worker loss is transient, not poison)."""
    import tempfile

    from django_assistant_bot_tpu.bot.domain import (
        BotPlatform,
        MultiPartAnswer,
        SingleAnswer,
    )
    from django_assistant_bot_tpu.bot.tasks import _post_answer
    from django_assistant_bot_tpu.serving.faults import (
        FaultInjector,
        reset_global_injector,
        set_global_injector,
    )
    from django_assistant_bot_tpu.storage import db as dbmod
    from django_assistant_bot_tpu.tasks.queue import TaskRecord, Worker, queue_stats, task

    N_DIALOGS, N_PARTS = 6, 4
    LEASE_S = 0.4

    class BenchPlatform(BotPlatform):
        def __init__(self):
            self.posted = []

        @property
        def codename(self):
            return "bench"

        async def get_update(self, request):
            raise NotImplementedError

        async def post_answer(self, chat_id, answer):
            self.posted.append((chat_id, answer.text))

        async def action_typing(self, chat_id):
            pass

    platform_box: dict = {}
    ledger_box = {"on": True}

    @task(queue="bench_tp", max_retries=3, retry_delay=0.05, name="bench.taskplane_deliver")
    def bench_deliver(scope, n_parts):
        answer = MultiPartAnswer(
            parts=[SingleAnswer(text=f"{scope}/part{i}") for i in range(n_parts)]
        )
        asyncio.run(
            _post_answer(
                platform_box["p"],
                scope,
                answer,
                ledger_scope=scope if ledger_box["on"] else None,
            )
        )

    def run_arm(use_ledger: bool) -> dict:
        """One fresh-DB replay of the pinned trace with a kill mid-answer."""
        tmp = tempfile.mkdtemp(prefix="dabt-bench-tp-")
        prev_db = os.environ.get("DABT_DB_PATH")
        os.environ["DABT_DB_PATH"] = os.path.join(tmp, "tasks.sqlite3")
        dbmod.reset_default_database()
        platform_box["p"] = BenchPlatform()
        ledger_box["on"] = use_ledger
        # the worker_lost site is consulted once pre-body + once per delivered
        # part (5/task): calls 1-10 are dialogs 0-1, call 11 is dialog 2's
        # pre-body, 12-13 its parts 0-1 — so call 13 kills the worker
        # MID-ANSWER with parts 0-1 already sent and dialogs 3-5 queued
        # behind; reclaim + re-dispatch must finish the whole trace
        inj = FaultInjector({"task_worker_lost": {"fire_on": [13]}})
        set_global_injector(inj)
        try:
            records = [
                bench_deliver.delay(f"dlg{i}", N_PARTS) for i in range(N_DIALOGS)
            ]
            w = Worker(
                ["bench_tp"], poll_s=0.01, lease_s=LEASE_S, concurrency=1
            ).start()
            try:
                deadline = time.time() + 60.0
                while time.time() < deadline:
                    statuses = {
                        r.refresh().status for r in records
                    }
                    if statuses <= {"done", "dead"}:
                        break
                    time.sleep(0.05)
            finally:
                w.stop(timeout_s=5.0)
            fault_at = inj.last_fire_at("task_worker_lost")
            recovery = None
            if fault_at is not None:
                recovery = time.monotonic() - fault_at  # bounded by the poll above
            posted = platform_box["p"].posted
            from collections import Counter

            counts = Counter(text for _, text in posted)
            expected = {f"dlg{i}/part{j}" for i in range(N_DIALOGS) for j in range(N_PARTS)}
            dup_posts = sum(n - 1 for n in counts.values() if n > 1)
            missing = len(expected - set(counts))
            exactly_once = sum(
                1 for k in expected if counts.get(k, 0) == 1
            ) / len(expected)
            stats = queue_stats()
            wstats = w.stats()
            return {
                "exactly_once_frac": round(exactly_once, 4),
                "duplicates": dup_posts,
                "missing": missing,
                "dlq": stats["dlq_size"],
                "reclaimed": wstats["reclaimed_leases"],
                "retries": wstats["retries"],
                "kills": wstats["worker_lost_aborts"],
                "recovery_s": round(recovery, 3) if recovery is not None else None,
                "done": TaskRecord.objects.filter(status="done").count(),
            }
        finally:
            reset_global_injector()
            if prev_db is None:
                os.environ.pop("DABT_DB_PATH", None)
            else:
                os.environ["DABT_DB_PATH"] = prev_db
            dbmod.reset_default_database()

    ledger = run_arm(use_ledger=True)
    seedlike = run_arm(use_ledger=False)
    # recovery_s from the arm loop is an upper bound (includes the final poll
    # interval); the dominant term is the lease wait, which is the honest cost
    # of a worker death — report it next to the lease so it is interpretable
    return {
        "taskplane_exactly_once_frac": ledger["exactly_once_frac"],
        "taskplane_duplicates": ledger["duplicates"],
        "taskplane_missing": ledger["missing"],
        "taskplane_dlq": ledger["dlq"],
        "taskplane_reclaimed": ledger["reclaimed"],
        "taskplane_kills": ledger["kills"],
        "taskplane_recovery_s": ledger["recovery_s"],
        "taskplane_lease_s": LEASE_S,
        "taskplane_done": ledger["done"],
        "taskplane_baseline_exactly_once_frac": seedlike["exactly_once_frac"],
        "taskplane_baseline_duplicates": seedlike["duplicates"],
        "taskplane_baseline_dlq": seedlike["dlq"],
        "taskplane_trace": f"{N_DIALOGS} dialogs x {N_PARTS} parts, 1 worker kill mid-answer",
    }


_TASKPLANE_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_taskplane()))
"""


def bench_obs() -> dict:
    """obs_* section (serving/obs.py evidence): the observability plane's two
    claims.  (1) Tracing + metric recording on the decode path costs within
    noise: interleaved off/on/off/on arms over the SAME compiled engine —
    the recorder is detached/attached between waves while the engine is
    idle, so the arms differ by exactly the hot-path `is None` branch the
    obs=False config ships (one engine build, no compile-noise between
    arms).  ``obs_overhead_frac`` is 1 - on/off decode tok/s, measured
    through the full engine loop (recording lives in ``_process_tick`` host
    bookkeeping, which device-only probes would miss).  (2) A ``/metrics``
    scrape is cheap and honest: ``obs_scrape_ms`` renders the full
    exposition, which must parse under the in-repo validator with
    TTFT/ITL/queue-wait histogram counts matching the known trace that was
    just run."""
    import numpy as np

    from django_assistant_bot_tpu.serving import (
        parse_prometheus_text,
        render_prometheus,
    )
    from django_assistant_bot_tpu.serving.obs import EngineObs

    n_req, n_new, waves_per_arm = 8, 64, 10
    rng = np.random.default_rng(17)
    prompts = [rng.integers(1, 255, 24).tolist() for _ in range(n_req)]

    def drive(eng) -> float:
        """tok/s over the whole wave (everything the arm pays rides inside)."""
        t0 = time.perf_counter()
        futs = [
            eng.submit(p, max_tokens=n_new, temperature=0.8) for p in prompts
        ]
        toks = sum(len(f.result(timeout=1200).token_ids) for f in futs)
        return toks / (time.perf_counter() - t0)

    out: dict = {}
    eng, _ = _build_gen_engine(max_slots=4, buckets=(32,), obs=False)
    recorder = EngineObs(name="bench")
    try:
        eng.submit([1, 2, 3], max_tokens=4, temperature=0.0).result(timeout=600)
        rates = {"off": [], "on": []}
        # strictly alternating waves, median per arm: single waves are short
        # enough (~hundreds of ms on small shapes) that scheduler jitter
        # swamps any one sample — the median over interleaved waves is what
        # makes the within-noise claim honest rather than lucky
        for i in range(2 * waves_per_arm):
            arm = ("off", "on")[i % 2]
            # the engine is idle between waves (every future resolved), so
            # swapping the recorder cannot race the loop mid-request
            eng.obs = recorder if arm == "on" else None
            rates[arm].append(drive(eng))
        eng.obs = recorder
        # scrape cost + validity against the trace the on-arms just ran:
        # the renderer walks a registry-shaped view, exactly like /metrics
        class _Shim:
            generators = {"bench": eng}
            embedders: dict = {}

        texts, t_scrape = [], []
        for _ in range(5):
            t0 = time.perf_counter()
            texts.append(render_prometheus(_Shim()))
            t_scrape.append(time.perf_counter() - t0)
        fams = parse_prometheus_text(texts[-1])
        done = waves_per_arm * n_req  # exactly the on-arm waves
        counts = {}
        for fam in ("dabt_ttft_seconds", "dabt_itl_seconds", "dabt_queue_wait_seconds"):
            counts[fam] = [
                v for name, _, v in fams[fam]["samples"] if name.endswith("_count")
            ][0]
        ok = (
            counts["dabt_ttft_seconds"] == done
            and counts["dabt_queue_wait_seconds"] == done
            and counts["dabt_itl_seconds"] > 0
        )
        out["obs_scrape_ms"] = round(statistics.median(t_scrape) * 1e3, 3)
        out["obs_scrape_bytes"] = len(texts[-1])
        out["obs_metrics_valid"] = bool(ok)
        out["obs_ttft_hist_count"] = int(counts["dabt_ttft_seconds"])
    finally:
        eng.stop()
    off_rate = statistics.median(rates["off"])
    on_rate = statistics.median(rates["on"])
    # the measured NOISE FLOOR of this A/B harness: the same statistic over
    # an off-vs-off split (even vs odd off waves).  Identical arms, so any
    # non-zero value is host jitter — the honest yardstick "within noise"
    # is judged against (on tiny CPU shapes this floor is several %, far
    # above the recording cost; on real device shapes both shrink)
    off_even = statistics.median(rates["off"][0::2])
    off_odd = statistics.median(rates["off"][1::2])
    noise = abs(1.0 - off_odd / max(1e-9, off_even))
    out.update(
        {
            "obs_off_tokens_per_s": round(off_rate, 2),
            "obs_on_tokens_per_s": round(on_rate, 2),
            # positive = recording costs throughput; the acceptance bar is
            # |frac| within max(2%, the measured off-vs-off noise floor)
            "obs_overhead_frac": round(1.0 - on_rate / max(1e-9, off_rate), 4),
            "obs_ab_noise_frac": round(noise, 4),
        }
    )
    return out


_OBS_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_obs()))
"""


def bench_stream() -> dict:
    """stream_* section (serving/streaming.py evidence): perceived latency —
    client-observed TTFT on the SAME concurrent trace, streaming (first delta
    of generate_stream) vs non-streaming (the full-response wait the reference
    contract imposes) — plus proof the token event queues don't throttle the
    engine: decode tok/s with N streaming consumers attached vs detached
    (futures only), interleaved A/B/A so drift on a shared chip can't fake a
    regression.  Also asserts the streamed text is byte-identical to the
    non-streaming greedy result (the detokenizer holdback contract).

    Caveat recorded with the numbers: at SMALL/toy geometry the engine tick
    is host-bound and shares the GIL with the consumer loop, so the
    attached-vs-detached ratio there measures Python thread scheduling
    (observed ±25% trial-to-trial on a shared host), not the event queues;
    the per-arm rates ship in the record so variance is visible.  The
    criterion binds on the real-geometry run, where ticks block in XLA with
    the GIL released."""
    import numpy as np

    eng, _ = _build_gen_engine(max_slots=4, buckets=(32,))
    # 4 admission waves of 4 slots, ~1s+ of wall per arm: short arms measure
    # host-scheduler noise, not the event queues (observed ±25% trial-to-trial
    # on a shared host at 8x48)
    n_req, n_new, plen = 16, 64, 24
    rng = np.random.default_rng(11)
    prompts = [
        "".join(chr(97 + int(c)) for c in rng.integers(0, 26, plen))
        for _ in range(n_req)
    ]
    try:
        eng.submit([1, 2, 3], max_tokens=4, temperature=0.0).result(timeout=600)

        async def detached_arm():
            # request/response path: the client sees NOTHING until the full
            # result lands, so its "time to first content" IS full latency
            t0 = time.perf_counter()
            futs = [
                eng.submit(
                    eng.tokenizer.encode(p), max_tokens=n_new, temperature=0.8
                )
                for p in prompts
            ]
            results = [await asyncio.wrap_future(f) for f in futs]
            wall = time.perf_counter() - t0
            first_content = sorted(r.latency_s for r in results)
            toks = sum(r.completion_tokens for r in results)
            return first_content, toks / wall

        async def attached_arm():
            # the SAME submit-based trace and the SAME completion measurement
            # (future resolution) as the detached arm — the ONLY difference
            # is a live TokenStream per request, drained concurrently by this
            # loop.  That isolates the question the acceptance criterion
            # asks: do the event queues throttle the ENGINE?  (Consumer-side
            # iteration wall time is a client cost, not an engine cost.)
            from django_assistant_bot_tpu.serving import TokenStream

            loop = asyncio.get_running_loop()
            streams = [
                TokenStream().bind(loop, capacity=n_new + 2) for _ in prompts
            ]

            async def drain(st, t_submit):
                first, n = None, 0
                async for kind, _payload in st:
                    if kind == "token":
                        if first is None:
                            first = time.perf_counter() - t_submit
                        n += 1
                return first, n

            t0 = time.perf_counter()
            futs, drains = [], []
            for p, st in zip(prompts, streams):
                futs.append(
                    eng.submit(
                        eng.tokenizer.encode(p),
                        max_tokens=n_new,
                        temperature=0.8,
                        stream=st,
                    )
                )
                drains.append(
                    asyncio.ensure_future(drain(st, time.perf_counter()))
                )
            results = [await asyncio.wrap_future(f) for f in futs]
            wall = time.perf_counter() - t0
            dr = await asyncio.gather(*drains)
            firsts = sorted(d[0] for d in dr if d[0] is not None)
            toks = sum(r.completion_tokens for r in results)
            # streams skip EOS and results strip it: counts must agree exactly
            assert sum(d[1] for d in dr) == toks, "streamed token count drifted"
            return firsts, toks / wall

        # interleaved A/B/A/B/A/B, best arm each: single-trial arm-to-arm
        # drift on a shared chip is the same order as the effect under test,
        # so one pair would report noise as throttling (or hide real
        # throttling); best-of-3 per arm bounds both directions
        nonstream_first: list = []
        att_first: list = []
        det_rates, att_rates = [], []
        for _ in range(3):
            f, r = asyncio.run(detached_arm())
            nonstream_first += f
            det_rates.append(r)
            f, r = asyncio.run(attached_arm())
            att_first += f
            att_rates.append(r)
        detached_tok_s = max(det_rates)
        att_tok_s = max(att_rates)
        att_first.sort()
        nonstream_first.sort()

        # byte identity: greedy (temperature 0) same prompt through both paths
        ref = eng.submit(
            eng.tokenizer.encode(prompts[0]), max_tokens=24, temperature=0.0
        ).result(timeout=600)

        async def collect():
            parts, final = [], None
            async for c in eng.generate_stream(
                prompts[0], max_tokens=24, temperature=0.0
            ):
                parts.append(c.text)
                if c.done:
                    final = c.result
            return "".join(parts), final

        streamed_text, streamed_final = asyncio.run(collect())
        stats = eng.tick_stats()
    finally:
        eng.stop()

    def pctl(vals, frac):
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, max(0, math.ceil(frac * len(vals)) - 1))]

    return {
        "stream_ttft_p50_s": round(pctl(att_first, 0.50), 4),
        "stream_ttft_p95_s": round(pctl(att_first, 0.95), 4),
        "stream_nonstream_ttft_p50_s": round(pctl(nonstream_first, 0.50), 4),
        "stream_nonstream_ttft_p95_s": round(pctl(nonstream_first, 0.95), 4),
        "stream_ttft_speedup_p50": round(
            pctl(nonstream_first, 0.50) / max(1e-9, pctl(att_first, 0.50)), 2
        ),
        "stream_attached_tokens_per_s": round(att_tok_s, 2),
        "stream_detached_tokens_per_s": round(detached_tok_s, 2),
        # ~1.0 = the event queues cost the engine nothing (acceptance: within
        # ~2% noise of the detached baseline on real geometry)
        "stream_attached_vs_detached": round(att_tok_s / max(1e-9, detached_tok_s), 4),
        # per-arm rates (interleaved run order): trial variance is the error
        # bar on the ratio above — judge the ratio against it
        "stream_detached_rates": [round(r, 1) for r in det_rates],
        "stream_attached_rates": [round(r, 1) for r in att_rates],
        "stream_final_byte_identical": bool(
            streamed_text == ref.text and streamed_final.text == ref.text
        ),
        "stream_concurrency": n_req,
        "stream_new_tokens": n_new,
        "stream_engine_ttft_p50_ms": stats.get("ttft_p50_ms"),
        "stream_engine_itl_p50_ms": stats.get("itl_p50_ms"),
    }


_STREAM_SNIPPET = """
import json
import bench
print(json.dumps(bench.bench_stream()))
"""


def baseline_embedding_torch_cpu() -> float:
    """Reference serving path: per-text torch forward loop (unbatched), CPU."""
    import torch
    from transformers import BertConfig, BertModel

    jcfg = _encoder_cfg()  # SMALL mode shrinks baseline and bench alike
    cfg = BertConfig(
        vocab_size=jcfg.vocab_size,
        hidden_size=jcfg.hidden_size,
        num_hidden_layers=jcfg.num_layers,
        num_attention_heads=jcfg.num_heads,
        intermediate_size=jcfg.intermediate_size,
    )
    model = BertModel(cfg)
    model.eval()
    seq = min(EMB_SEQ, jcfg.max_position_embeddings)  # same clamp as bench_embedding
    ids = torch.randint(1, cfg.vocab_size, (EMB_BATCH, seq))
    with torch.no_grad():
        model(input_ids=ids[:1])  # warm
        t0 = time.perf_counter()
        for _ in range(BASELINE_ITERS):
            for i in range(EMB_BATCH):
                out = model(input_ids=ids[i : i + 1])
                out.last_hidden_state.mean(dim=1)
        dt = time.perf_counter() - t0
    return (EMB_BATCH * BASELINE_ITERS) / dt


def baseline_decode_torch_cpu() -> float:
    """Reference generate path: single-stream torch decode, tokens/s (same 1B-class
    geometry).  The reference has no batching across requests
    (assistant/ai/providers/transformers.py:35-94)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    jcfg = _decoder_cfg()  # SMALL mode shrinks baseline and bench alike
    cfg = LlamaConfig(
        vocab_size=jcfg.vocab_size,
        hidden_size=jcfg.hidden_size,
        intermediate_size=jcfg.intermediate_size,
        num_hidden_layers=jcfg.num_layers,
        num_attention_heads=jcfg.num_heads,
        num_key_value_heads=jcfg.num_kv_heads,
        max_position_embeddings=jcfg.max_seq_len,
    )
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = torch.randint(1, 250, (1, DECODE_PROMPT_LEN))

    def gen(n_new: int) -> float:
        t0 = time.perf_counter()
        model.generate(
            ids,
            attention_mask=torch.ones_like(ids),
            max_new_tokens=n_new,
            # random weights sample EOS early; the two-point fit needs EXACT
            # lengths or the slope degenerates (the r3 1e9 sentinel)
            min_new_tokens=n_new,
            do_sample=True,
            top_p=0.95,
            top_k=50,
            pad_token_id=cfg.eos_token_id,
        )
        return time.perf_counter() - t0

    with torch.no_grad():
        gen(1)  # warm: first-call allocations/compile noise stays out of the rate
        n = max(2, BASELINE_DECODE_TOKENS)
        t_small, t_big = gen(n // 2), gen(n)
        # two-point fit separates prefill cost from the per-token decode rate so
        # neither pollutes the other when extrapolating to other request sizes
        per_token = (t_big - t_small) / (n - n // 2)
        if per_token <= 1e-4:
            # timing noise swallowed the decode slope (t_big <= t_small) — a
            # rate extrapolated from it would be fiction.  Raising makes main()
            # OMIT the torch-decode comparison instead of publishing a
            # sentinel (r3 shipped 1e9 tok/s; VERDICT r3 "what's weak" #3).
            raise RuntimeError(
                f"degenerate torch decode slope ({per_token:.2e}s/token at "
                f"n={n}); raise BENCH_BASELINE_DECODE_TOKENS"
            )
        prefill_s = max(t_small - (n // 2) * per_token, 0.0)
    return 1.0 / per_token, prefill_s


def baseline_embedding_torch_cpu_batched() -> float:
    """Stronger baseline than the reference's own loop: the same torch model
    batched (what a well-tuned torch-CPU deployment would do)."""
    import torch
    from transformers import BertConfig, BertModel

    jcfg = _encoder_cfg()
    cfg = BertConfig(
        vocab_size=jcfg.vocab_size,
        hidden_size=jcfg.hidden_size,
        num_hidden_layers=jcfg.num_layers,
        num_attention_heads=jcfg.num_heads,
        intermediate_size=jcfg.intermediate_size,
    )
    model = BertModel(cfg)
    model.eval()
    seq = min(EMB_SEQ, jcfg.max_position_embeddings)
    ids = torch.randint(1, cfg.vocab_size, (EMB_BATCH, seq))
    with torch.no_grad():
        model(input_ids=ids)  # warm
        t0 = time.perf_counter()
        for _ in range(BASELINE_ITERS):
            out = model(input_ids=ids)
            out.last_hidden_state.mean(dim=1)
        dt = time.perf_counter() - t0
    return (EMB_BATCH * BASELINE_ITERS) / dt


# Long-context prefill at 1B geometry through the chunked-KV pallas flash
# kernel (ops/attention.py): the whole-row kernel died at 16k (VMEM scoped
# stack); this records real-chip throughput at 8k/16k/32k — the long-context
# capability (ring/sequence parallelism covers multi-chip; this is the
# single-chip flash path the serving engine's prefill uses).
_LONGCTX_SNIPPET = """
import json, time
import numpy as np, jax, jax.numpy as jnp
from django_assistant_bot_tpu.models import DecoderConfig, llama

cfg = DecoderConfig(
    vocab_size=128_256, hidden_size=2048, intermediate_size=8192,
    num_layers=16, num_heads=32, num_kv_heads=8, head_dim=64,
    max_seq_len=32768, dtype=jnp.bfloat16)
params = llama.init(cfg, jax.random.PRNGKey(0))
jax.block_until_ready(params)
pf = jax.jit(lambda p, i, l: llama.prefill(p, cfg, i, l))
out = {}
for S in (8192, 16384, 32768):
    ids = jnp.ones((1, S), jnp.int32)
    lens = jnp.asarray([S], jnp.int32)
    lg, ks, vs = pf(params, ids, lens); np.asarray(lg)  # compile + warm
    t0 = time.perf_counter()
    lg, ks, vs = pf(params, ids, lens)
    lg2, ks, vs = pf(params, ids, lens)
    np.asarray(lg2)
    dt = (time.perf_counter() - t0) / 2
    out[f"longctx_prefill_{S}_tokens_per_s"] = round(S / dt, 1)
print(json.dumps(out))
"""


def bench_longctx_decode(ctx: int = 16384, slots: int = 8) -> dict:
    """Long-context DECODE (VERDICT r5 #7): tok/s and step cost at a 16k-token
    allocated cache, length-bucketed KV read vs the full-cache read.

    Two engines over ONE int8 1B param set (params are never donated), same
    session: ``bucketed`` (decode_kv_chunk auto) and ``full`` (disabled).
    Short traffic in the long-allocated cache is exactly the case the ledger
    flagged — the full read streams all ``slots x ctx`` KV rows per step while
    the valid context is ~200 tokens.  Probes are pinned at two fills (the
    bench's short fill and 12k) so the win is recorded where it is large AND
    where it tapers."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from django_assistant_bot_tpu.models import DecoderConfig, llama
    from django_assistant_bot_tpu.parallel import get_mesh, shard_pytree
    from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine

    if SMALL:
        cfg = DecoderConfig.tiny()
        ctx = min(ctx, cfg.max_seq_len)
    else:
        cfg = DecoderConfig(
            vocab_size=128_256,
            hidden_size=2048,
            intermediate_size=8192,
            num_layers=16,
            num_heads=32,
            num_kv_heads=8,
            head_dim=64,
            max_seq_len=ctx,
            dtype=jnp.bfloat16,
        )
        # int8 incl. embed/head: the 16k-ctx KV cache (~4.3 GB bf16 at 8
        # slots) needs the weight-side headroom on a shared 16 GB chip
    params = (
        llama.init(cfg, jax.random.PRNGKey(0))
        if SMALL
        else llama.init_int8(cfg, jax.random.PRNGKey(0), quantize_embed=True)
    )
    mesh = get_mesh()
    with mesh:
        params = shard_pytree(params, llama.logical_axes(cfg), mesh)
    rng = np.random.default_rng(9)
    out: dict = {"longctx_decode_ctx": ctx, "longctx_decode_slots": slots}
    fill_short = DECODE_PROMPT_LEN + DECODE_NEW_TOKENS
    prompt_len = min(DECODE_PROMPT_LEN, ctx // 4)
    for label, chunk in (("bucketed", 0), ("full", None)):
        eng = GenerationEngine(
            cfg,
            params,
            ByteTokenizer(),
            max_slots=slots,
            max_seq_len=ctx,
            prefill_buckets=(128,),
            chunk_size=128,
            mesh=mesh,
            prefix_cache_size=0,
            decode_kv_chunk=chunk,
        )
        eng.warmup()
        eng.start()
        try:
            prompts = [
                rng.integers(1, 255, prompt_len).tolist() for _ in range(slots)
            ]
            futs = [eng.submit(p, max_tokens=8, temperature=0.8) for p in prompts]
            [f.result(timeout=900) for f in futs]  # warm the loop/sampling
            t0 = time.perf_counter()
            futs = [
                eng.submit(p, max_tokens=DECODE_NEW_TOKENS, temperature=0.8)
                for p in prompts
            ]
            results = [f.result(timeout=900) for f in futs]
            wall = time.perf_counter() - t0
            out[f"longctx_decode_{label}_tokens_per_s"] = round(
                sum(r.completion_tokens for r in results) / wall, 2
            )
            out[f"longctx_decode_{label}_step_ms_short"] = round(
                eng.probe_decode(iters=6, fill_len=fill_short) * 1e3, 3
            )
            deep = min(12288, max(ctx // 2, ctx - 64))
            out[f"longctx_decode_{label}_step_ms_deep"] = round(
                eng.probe_decode(iters=6, fill_len=deep) * 1e3, 3
            )
            if label == "bucketed":
                out["longctx_decode_kv_read_frac"] = eng.tick_stats()["kv_read_frac"]
                out["longctx_decode_kv_chunk"] = eng.decode_kv_chunk or 0
                out["longctx_decode_ledger"] = decode_byte_ledger(
                    eng, fill_len=fill_short
                )
        finally:
            eng.stop()
    full_ms = out.get("longctx_decode_full_step_ms_short")
    buck_ms = out.get("longctx_decode_bucketed_step_ms_short")
    if full_ms and buck_ms:
        out["longctx_decode_step_speedup_short"] = round(full_ms / buck_ms, 3)
    return out


_LONGCTX_DECODE_SNIPPET = """
import json
import bench

print(json.dumps(bench.bench_longctx_decode()))
"""


# Tree-verified prompt-lookup speculative decoding (ops/speculative.py,
# docs/SPECULATIVE.md): single-stream greedy, spec-on vs spec-off.  Honest
# about the random-weights trap (the r5 regression measured 0.24x at ~5%
# acceptance and said nothing about the mechanism): the model is first FIT
# on the copy/quote task through the training plane until greedy decode
# actually quotes its prompt (training/copy_task.py, quote accuracy
# reported), so the measured speedup is the answer-from-context regime the
# reference actually serves.  Alongside the end-to-end A/B, a plain-vs-
# verify tick-cost sweep (engine.probe_spec) reports each tree rung's cost
# ratio and the breakeven accept rate — the controller's disable threshold.
_SPEC_SNIPPET = """
import json, time
import bench
from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine
from django_assistant_bot_tpu.training import copy_task_config, fit_copy_model

# hidden=128 keeps the device step large enough that host per-tick overhead
# doesn't drown the verify-vs-plain ratio (hidden=64 measured ~0.4 ms plain
# ticks — pure host noise territory); converges in ~100 Adam steps
cfg = copy_task_config(hidden_size=128)
params, cfg, fit = fit_copy_model(cfg, seq_len=128, batch=16, seed=0)
tok = ByteTokenizer()
import numpy as np
rng = np.random.default_rng(1)
M = 64  # trained copy span
ctx = rng.integers(3, cfg.vocab_size, M).tolist()
prompt = ctx + ctx[:8]  # context + the first quoted tokens; greedy continues
MT = M - 8

def run(spec):
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=cfg.max_seq_len,
        prefill_buckets=(128,), prefix_cache_size=0,
        speculative=spec, spec_width=4,
        spec_probe_every=4, spec_explore_every=8, lookahead=3, burst=4)
    eng.warmup()
    eng.start()
    try:
        eng.submit(prompt, max_tokens=MT, temperature=0.0).result(timeout=600)
        t0 = time.perf_counter()
        tot = 0
        ids = None
        for _ in range(6):  # single stream: one request in flight at a time
            r = eng.submit(prompt, max_tokens=MT, temperature=0.0).result(timeout=600)
            tot += r.completion_tokens
            ids = r.token_ids
        wall = time.perf_counter() - t0
        stats = eng.tick_stats()
        sweep = eng.probe_spec(iters=6) if spec else None
    finally:
        eng.stop()
    return tot / wall, stats, ids, sweep

plain_tok_s, _, plain_ids, _ = run(0)
spec_tok_s, stats, spec_ids, sweep = run(6)
# greedy equivalence is exact in exact arithmetic (token-identical on the
# f32 CPU mesh, tests/test_speculative.py); on the bf16 MXU near-tie argmax
# may break differently across program shapes — record the overlap instead
# of asserting across two differently-shaped programs
match = 0
for a, b in zip(spec_ids, plain_ids):
    if a != b:
        break
    match += 1
used = (stats["spec_tree_width"], stats["spec_tree_depth"])
rungs = sweep["rungs"]  # string-keyed "WxK", JSON-able as-is
best_be = min(v["breakeven_accept_rate"] for v in rungs.values())
print(json.dumps({
    "spec_decode_single_stream_tokens_per_s": round(spec_tok_s, 2),
    "spec_decode_plain_single_stream_tokens_per_s": round(plain_tok_s, 2),
    "spec_decode_speedup": round(spec_tok_s / plain_tok_s, 3),
    "spec_decode_accept_rate": stats.get("spec_accept_rate", 0.0),
    "spec_decode_drafted": stats.get("spec_drafted", 0),
    "spec_rung_accept_emas": stats.get("spec_rung_accept_emas", {}),
    "spec_tree_rung_used": f"{used[0]}x{used[1]}",
    "spec_auto_disabled": stats.get("spec_auto_disabled"),
    "spec_quote_accuracy": round(fit["quote_accuracy"], 4),
    "spec_train_steps": fit["train_steps"],
    "spec_plain_tick_ms": round(sweep["plain_tick_s"] * 1e3, 3),
    "spec_tick_cost_ratios": {
        r: round(v["cost_ratio"], 3) for r, v in rungs.items()
    },
    "spec_breakeven_accept_rates": {
        r: round(v["breakeven_accept_rate"], 4) for r, v in rungs.items()
    },
    "spec_breakeven_accept_rate": round(best_be, 4),
    "spec_decode_greedy_match_prefix": match,
    "spec_decode_tokens_compared": min(len(spec_ids), len(plain_ids)),
}))
"""


# The full real-weights path on chip (VERDICT r4 missing #1): a REAL-format
# checkpoint (safetensors + config.json + trained tokenizer.json, written
# locally — zero egress) through fetch -> convert(int8) -> serve -> /dialog
# over HTTP.  No `tiny: true`, no byte tokenizer anywhere in this section.
_REAL_CKPT_SNIPPET = """
import asyncio, json, os, tempfile, time
from types import SimpleNamespace
import bench
from aiohttp.test_utils import TestClient, TestServer
from django_assistant_bot_tpu.cli import fetch_models as fm
from django_assistant_bot_tpu.models import synth
from django_assistant_bot_tpu.serving import ModelRegistry
from django_assistant_bot_tpu.serving.server import create_app
from django_assistant_bot_tpu.serving.tokenizer import HFTokenizer

root = tempfile.mkdtemp(prefix="dabt-realckpt-")
src = synth.synth_decoder(os.path.join(root, "chat_ckpt"),
                          hidden_size=256, num_layers=4, vocab_size=512)
args = SimpleNamespace(models=[src], config=None, models_dir=root,
                       revision=None, convert=True, kind="decoder", quantize="int8")
assert fm.run(args) == 0
native = src + ".native.int8"
registry = ModelRegistry.from_config({"real-chat": {
    "kind": "decoder", "checkpoint": native, "max_slots": 4, "max_seq_len": 256}})
eng = registry.get_generator("real-chat")
assert isinstance(eng.tokenizer, HFTokenizer), "byte fallback leaked in"

async def drive():
    loop = asyncio.get_event_loop()
    client = TestClient(TestServer(create_app(registry)), loop=loop)
    await client.start_server()
    try:
        async def one(i):
            r = await client.post("/dialog/", json={
                "model": "real-chat",
                "messages": [
                    {"role": "system", "content": "answer from context"},
                    {"role": "user", "content": f"benchmark question {i}"},
                ],
                "max_tokens": 32, "json_format": False})
            assert r.status == 200, await r.text()
            return (await r.json())["response"]["usage"]
        await one(99)  # warm
        t0 = time.perf_counter()
        usages = await asyncio.gather(*(one(i) for i in range(8)))
        wall = time.perf_counter() - t0
        return sum(u["completion_tokens"] for u in usages) / wall
    finally:
        await client.close()

try:
    tok_s = asyncio.new_event_loop().run_until_complete(drive())
finally:
    registry.stop()
print(json.dumps({
    "real_ckpt_dialog_ok": True,
    "real_ckpt_tokenizer": "hf",
    "real_ckpt_path": "synth(safetensors+tokenizer.json) -> convert int8 -> serve -> /dialog",
    "real_ckpt_decode_tokens_per_s": round(tok_s, 2),
}))
"""


def _is_transient_compile_error(err: str) -> bool:
    """Connection-level drops from the tunnel's remote-compile helper — NOT
    deterministic compile failures (a bare 'remote_compile' match would retry
    e.g. a VMEM OOM for a guaranteed-identical failure, burning a section's
    whole budget twice)."""
    if "remote_compile" not in err:
        return False
    return any(
        sig in err for sig in ("read body", "closed", "Connection", "EOF", "timed out")
    )


def _run_with_transient_retry(snippet, cap_s, rem_fn, extras, name):
    """One section subprocess, with a single retry on transient compile-service
    failures.  The tunnel's remote-compile helper drops connections now and
    then (observed: "response body closed before all bytes were read"); the
    failure is environmental, a fresh subprocess usually lands, and both the
    transient and the final outcome end up in the record."""
    res, err = _subprocess_bench(snippet, timeout_s=int(min(cap_s, rem_fn())))
    if res is None and _is_transient_compile_error(err) and rem_fn() > 60:
        extras[f"{name}_transient"] = err
        res, err = _subprocess_bench(snippet, timeout_s=int(min(cap_s, rem_fn())))
    return res, err


def _run_baselines(box: dict) -> None:
    """Torch-CPU baselines — chip-free, so they run on a background thread
    while the device sections own the TPU (serial at r4 they cost minutes of
    the driver window for numbers that never change run to run)."""
    try:
        box["emb_base"] = baseline_embedding_torch_cpu()
    except Exception as e:  # pragma: no cover - depends on host load
        box["emb_err"] = repr(e)[:200]
    try:
        box["emb_base_batched"] = baseline_embedding_torch_cpu_batched()
    except Exception as e:  # pragma: no cover
        box["emb_batched_err"] = repr(e)[:200]
    try:
        dec_base, prefill_s = baseline_decode_torch_cpu()
        # prefill first: readers guard on dec_base, so both keys must be
        # visible once it is (emit() runs concurrently on the main thread)
        box["prefill_base_s"] = prefill_s
        box["dec_base"] = dec_base
    except Exception as e:  # pragma: no cover
        box["dec_err"] = repr(e)[:200]


def _finalize_vs_baseline(extras: dict, box: dict) -> None:
    """Fold the torch-CPU baselines into extras (ratios only when both sides ran)."""
    emb = extras.get("embedding_docs_per_sec_per_chip")
    emb_base = box.get("emb_base")
    if emb and emb_base:
        extras["embedding_vs_torch_cpu"] = round(emb / emb_base, 2)
    emb_bb = box.get("emb_base_batched")
    if emb and emb_bb:
        extras["embedding_vs_torch_cpu_batched"] = round(emb / emb_bb, 2)
    if emb_bb and extras.get("ingest_docs_per_s_per_chip"):
        extras["ingest_vs_torch_cpu_batched"] = round(
            extras["ingest_docs_per_s_per_chip"] / emb_bb, 2
        )
    dec_base = box.get("dec_base")
    if dec_base:
        extras["decode_baseline_tokens_per_s_torch_cpu"] = round(dec_base, 3)
        if extras.get("decode_tokens_per_s_per_chip"):
            extras["decode_vs_torch_cpu"] = round(
                extras["decode_tokens_per_s_per_chip"] / dec_base, 2
            )


def _build_record(extras: dict, box: dict) -> dict:
    """The ONE JSON record.  Called after every section with the extras
    accumulated so far — the driver parses the LAST JSON line on stdout, so
    re-emitting the record-so-far makes any truncation point yield the most
    complete evidence available (VERDICT r4 weak #1)."""
    # headline vs_baseline: the reference serves a RAG turn single-stream as
    # prefill + new_tokens decode, plus one unbatched embed call on the
    # retrieval turns only — our dialogs embed once per 2 turns, so the
    # baseline is charged the same 1/2 embed per turn (not one per turn)
    vs = None
    rag_req_s = extras.get("rag_req_per_s")
    dec_base, emb_base = box.get("dec_base"), box.get("emb_base")
    prefill_base_s = box.get("prefill_base_s")
    if dec_base and emb_base and rag_req_s and prefill_base_s is not None:
        ref_req_s = 1.0 / (
            prefill_base_s + RAG_NEW_TOKENS / dec_base + 0.5 / emb_base
        )
        extras["rag_baseline_req_per_s_torch_cpu"] = round(ref_req_s, 4)
        vs = round(rag_req_s / ref_req_s, 2)
    record = {
        "metric": "rag_req_per_s_plus_p50_ttft",
        "value": rag_req_s,
        "unit": "req/s (p50 TTFT %ss)" % extras.get("rag_p50_ttft_s")
        if rag_req_s
        else "req/s",
        "vs_baseline": vs,
        "extras": extras,
    }
    if rag_req_s is None:
        # the core child died — the failure IS the headline, not a buried extra
        record["error"] = extras.get(
            "core_error", "core section produced no result (yet)"
        )
    return record


# Headline keys for the bounded compact record, in PRIORITY order — when the
# line would exceed the budget, keys drop from the END of this list first.
# (VERDICT r5 #1: the full record outgrew the driver's 2,000-char tail window
# twice, so the canonical artifact lost `rag_req_per_s` — the compact record
# is what the driver's tail is guaranteed to capture.)
_COMPACT_KEYS = (
    "rag_req_per_s",
    "rag_p50_ttft_s",
    "embedding_docs_per_sec_per_chip",
    "decode_tokens_per_s_per_chip",
    "decode_steady_tokens_per_s",
    "decode_kv_read_frac",
    "decode_int8_steady_tokens_per_s",
    "decode_mfu_frac",
    "decode_hbm_gbps",
    "decode_int8_mfu_frac",
    "decode_int8_hbm_gbps",
    "decode_unfused_steady_tokens_per_s",
    "fused_steady_tokens_per_s",
    "int4_steady_tokens_per_s",
    "fused_decode_steps",
    "fused_vs_unfused_speedup",
    "int4_vs_unfused_speedup",
    "fused_mfu_frac",
    "int4_mfu_frac",
    "fused_hbm_gbps",
    "int4_hbm_gbps",
    "int4_logit_err_rel",
    "int8_logit_err_rel",
    "fused_upload_overlap_frac",
    "contbatch_itl_p95_on_ms",
    "contbatch_itl_p95_off_ms",
    "contbatch_itl_improvement_frac",
    "contbatch_outputs_identical",
    "contbatch_displacement_frac_off",
    "contbatch_displacement_frac_on",
    "contbatch_chunks_piggybacked_on",
    "specfused_tokens_per_s",
    "specfused_vs_best_parent_speedup",
    "specfused_vs_fused_speedup",
    "specfused_vs_spec_speedup",
    "specfused_accept_rate",
    "attn_fp8_step_ms",
    "attn_fp8_step_speedup",
    "attn_fp8_indot_max_abs_err",
    "contbatch_mfu_frac",
    "contbatch_hbm_gbps",
    "attn_fp8_mfu_frac",
    "attn_fp8_hbm_gbps",
    "decode_int8_slots_b_steady_tokens_per_s",
    "decode_int8_slots_b",
    "slots_ab_winner",
    "paged_vs_legacy_slots",
    "paged_slots_at_fixed_hbm",
    "paged_tokens_per_s",
    "paged_prefix_ttft_p50_s",
    "paged_prefix_ttft_p95_s",
    "legacy_prefix_ttft_p50_s",
    "decode_8b_int8_tokens_per_s_per_chip",
    "decode_8b_int8_fp8kv_tokens_per_s_per_chip",
    "longctx_decode_bucketed_tokens_per_s",
    "longctx_decode_full_tokens_per_s",
    "longctx_decode_kv_read_frac",
    "moe_decode_tokens_per_s_per_chip",
    "moe_geometry",
    "knn_build_cold_s",
    "knn_build_warm_s",
    "knn_query_batched_ms_per_query",
    "ann_recall_at10",
    "ann_query_batched_ms_per_query",
    "ann_exact_query_batched_ms_per_query",
    "ann_speedup_vs_exact",
    "ann_build_s",
    "ann_append_10k_s",
    "ann_recall_at10_post_append",
    "durable_recovery_s",
    "durable_replayed_records",
    "durable_topk_identical",
    "durable_duplicate_vectors",
    "durable_ingested_docs",
    "durable_recovered_docs",
    "durable_resume_dedup_docs",
    "durable_snapshot_count",
    "durable_wal_records",
    "ingest_docs_per_s_per_chip",
    "real_ckpt_decode_tokens_per_s",
    "longctx_prefill_32768_tokens_per_s",
    "spec_decode_speedup",
    "spec_decode_accept_rate",
    "spec_breakeven_accept_rate",
    "spec_rung_accept_emas",
    "spec_quote_accuracy",
    "overload_interactive_p95_speedup",
    "overload_fifo_interactive_p95_wait_s",
    "overload_sched_interactive_p95_wait_s",
    "overload_shed",
    "overload_deadline_reclaim_s",
    "chaos_goodput_frac",
    "chaos_recovery_s",
    "chaos_restarts",
    "chaos_baseline_goodput_frac",
    "router_goodput_frac",
    "router_recovery_s",
    "router_reroutes",
    "router_drain_shed",
    "fleet_unified_ttft_p95_ms",
    "fleet_disagg_ttft_p95_ms",
    "fleet_unified_agg_tok_s",
    "fleet_disagg_agg_tok_s",
    "fleet_chaos_goodput_frac",
    "fleet_reroutes",
    "fleet_output_identical",
    "fleet_handoffs",
    "fleet_pages_shipped",
    "fleet_chaos_net_goodput_frac",
    "fleet_chaos_duplicate_execs",
    "fleet_chaos_corrupt_injected",
    "fleet_chaos_corrupt_rejected",
    "fleet_chaos_corrupt_absorbed",
    "fleet_chaos_reconcile_s",
    "fleet_chaos_ttl_drops",
    "fleet_chaos_timeout_retries",
    "multichip_agg_tok_s",
    "multichip_tok_s_1slice",
    "multichip_scaling_frac",
    "multichip_slices",
    "multichip_concurrent_frac",
    "multichip_slice_hbm_bytes",
    "multichip_hbm_frac",
    "multichip_output_identical",
    "autoscale_p95_ttft_on_s",
    "autoscale_p95_ttft_off_s",
    "autoscale_shed_on",
    "autoscale_shed_off",
    "autoscale_replica_seconds",
    "autoscale_replica_seconds_fixed_max",
    "autoscale_peak_replicas",
    "kv_tier_hit_ttft_p95_s",
    "kv_tier_hit_ttft_p95_hbm_only_s",
    "kv_tier_pressure_sheds",
    "kv_tier_pressure_sheds_hbm_only",
    "kv_tier_restart_goodput_frac",
    "kv_tier_restart_ttft_p50_s",
    "kv_tier_restart_ttft_p50_hbm_only_s",
    "kv_tier_detach_pages_lost_migrate_on",
    "kv_tier_detach_pages_lost_migrate_off",
    "taskplane_exactly_once_frac",
    "taskplane_duplicates",
    "taskplane_baseline_exactly_once_frac",
    "taskplane_baseline_duplicates",
    "taskplane_recovery_s",
    "taskplane_dlq",
    "obs_overhead_frac",
    "obs_ab_noise_frac",
    "obs_scrape_ms",
    "obs_metrics_valid",
    "stream_ttft_p50_s",
    "stream_ttft_p95_s",
    "stream_nonstream_ttft_p50_s",
    "stream_ttft_speedup_p50",
    "stream_attached_vs_detached",
    "stream_final_byte_identical",
    "rag_turn2_p50_ttft_s",
    "bench_elapsed_s",
)

_COMPACT_BUDGET = 1450  # chars; hard driver tail is 2000, issue asks < 1500


def _sig4(v):
    """4 significant digits for floats; everything else passes through.

    Non-finite floats become None: json.dumps would emit bare ``NaN`` /
    ``Infinity``, which strict parsers reject — the exact failure the
    compact record exists to prevent."""
    if isinstance(v, bool):
        return v
    if isinstance(v, float):
        return float(f"{v:.4g}") if math.isfinite(v) else None
    return v


def _compact_record(record: dict) -> str:
    """The bounded-size summary line: headline + must-have keys, 4 sig figs.

    Always < ~1,500 chars (keys drop lowest-priority-first if ever needed), so
    the driver's 2,000-char stdout tail captures a parseable record whatever
    the full record grew to."""
    extras = record.get("extras", {})
    compact: dict = {
        "metric": record.get("metric"),
        "value": _sig4(record.get("value")),
        "vs_baseline": _sig4(record.get("vs_baseline")),
    }
    if record.get("error"):
        compact["error"] = str(record["error"])[:180]
    keys = [k for k in _COMPACT_KEYS if k in extras]
    for k in keys:
        compact[k] = _sig4(extras[k])
    line = json.dumps(compact)
    while len(line) > _COMPACT_BUDGET and keys:
        compact.pop(keys.pop())  # drop from the tail of the priority list
        line = json.dumps(compact)
    return line


def main() -> None:
    import threading

    from django_assistant_bot_tpu.utils.compile_cache import (
        enable_persistent_compile_cache,
    )

    extras: dict = {}
    t_start = time.monotonic()
    cache_dir = enable_persistent_compile_cache()
    if cache_dir:
        extras["compile_cache_dir"] = cache_dir

    def left() -> float:
        return BUDGET_S - (time.monotonic() - t_start)

    box: dict = {}
    baseline_thread = threading.Thread(
        target=_run_baselines, args=(box,), daemon=True
    )

    def emit() -> None:
        extras["bench_elapsed_s"] = round(time.monotonic() - t_start, 1)
        _finalize_vs_baseline(extras, box)
        record = _build_record(extras, box)
        # full record first, bounded compact record LAST: the driver tails
        # stdout, so whatever line the capture window ends on, the final one
        # is always the parseable <1,500-char summary (VERDICT r5 #1)
        print(json.dumps(record), flush=True)
        print(_compact_record(record), flush=True)

    if SMALL:
        # CI/dev smoke: tiny shapes, one process (the CPU device isn't shared)
        # — SAME bodies as the real run's subprocess snippets (bench_core /
        # bench_int8), only the process isolation differs
        baseline_thread.start()
        extras.update(bench_core())
        extras.update(bench_int8())
        extras.update(bench_fused_int4())
        extras.update(bench_paged())
        extras.update(bench_contbatch())
        extras.update(bench_longctx_decode(slots=4))
        moe_eng, _ = _build_gen_engine(_moe_cfg(), buckets=(_decode_bucket(),))
        try:
            moe = bench_decode(moe_eng)
            extras["moe_decode_tokens_per_s_per_chip"] = moe["decode_tokens_per_s_per_chip"]
            extras["moe_decode_p50_ttft_s"] = moe["decode_p50_ttft_s"]
        finally:
            moe_eng.stop()
        extras.update(bench_ingestion())
        extras.update(bench_ann())
        extras.update(bench_durable())
        extras.update(bench_overload())
        extras.update(bench_chaos())
        extras.update(bench_router())
        extras.update(bench_fleet())
        extras.update(bench_multichip())
        extras.update(bench_autoscale())
        extras.update(bench_kv_tier())
        extras.update(bench_taskplane())
        extras.update(bench_obs())
        extras.update(bench_stream())
        baseline_thread.join(timeout=600)
        emit()
        return

    # Real mode: one subprocess per device-using section (the parent holds
    # ZERO HBM, so every section gets the whole shared ~16 GB chip), ordered
    # by evidential priority — the record's must-haves first — under a hard
    # wall-clock budget; later sections are skipped (recorded as such) rather
    # than letting the whole run time out with nothing on stdout (r4).
    baseline_thread.start()

    def run(name: str, snippet: str, cap_s: int, reserve_s: int = 90) -> bool:
        rem = left() - reserve_s
        if rem < 60:
            extras[f"{name}_skipped"] = f"budget exhausted ({left():.0f}s left)"
            emit()
            return False
        t0 = time.monotonic()
        res, err = _run_with_transient_retry(
            snippet, cap_s, lambda: left() - reserve_s, extras, name
        )
        extras.setdefault("section_s", {})[name] = round(time.monotonic() - t0, 1)
        if res:
            extras.update(res)
        else:
            extras[f"{name}_error"] = err
        emit()
        return bool(res)

    # 1) configs 1-3 incl. the headline 1M-corpus RAG number
    run("core", _CORE_SNIPPET, cap_s=1500)
    # 2) config 2c: TRUE 8B flagship geometry + fp8-KV variant (r4 configs)
    t0 = time.monotonic()
    extras.update(bench_8b(time_left=lambda: left() - 90))
    extras.setdefault("section_s", {})["8b"] = round(time.monotonic() - t0, 1)
    emit()
    # 3) config 2b: int8 weight-only decode at 1B (halves decode HBM reads)
    #    + the interleaved 16-vs-32 slot A/B/A trials
    run("int8", _INT8_SNIPPET, cap_s=900)
    # 3a) roofline decode push: interleaved unfused-int8 / fused-int8 /
    #     fused-int4 probe arms with per-arm byte-ledger MFU + HBM GB/s and
    #     the int4 logit-error bound (docs/QUANT.md evidence)
    run("fused_int4", _FUSED_INT4_SNIPPET, cap_s=700)
    # 3a') paged KV plane: slots-at-fixed-HBM A/B (legacy vs paged on the
    #      same byte ledger) + prefix-hit TTFT vs the r4 prefix cache
    run("paged", _PAGED_SNIPPET, cap_s=600)
    # 3a'') continuous batching: piggybacked-chunked-prefill ITL A/B,
    #       spec x fused vs both parents, fp8 in-dot attention step + error
    #       (serving/engine.py round-15 evidence, tests/test_contbatch.py)
    run("contbatch", _CONTBATCH_SNIPPET, cap_s=600)
    # 3b) long-context DECODE: 16k-allocated cache at 8 slots, bucketed KV
    #     read vs full-cache read (the tentpole's canonical evidence)
    run("longctx_decode", _LONGCTX_DECODE_SNIPPET, cap_s=700)
    # 3c) overload: FIFO vs admission-controlled scheduler on the same
    #     above-capacity mixed trace (interactive p50/p95 wait, shed + 429
    #     contract, deadline slot reclaim — serving/scheduler.py evidence)
    run("overload", _OVERLOAD_SNIPPET, cap_s=400)
    # 3c') chaos: goodput + recovery-time-to-first-success with tick_raise
    #      fired once mid-trace vs the no-fault baseline on the same trace
    #      (serving/faults.py + crash-only restart evidence)
    run("chaos", _CHAOS_SNIPPET, cap_s=400)
    # 3c'') router: fleet failover — one of 2 replicas killed mid-trace
    #       (replica_dead armed once); token-less goodput, re-route counts,
    #       recovery-to-first-success on the restarted replica, and a
    #       rolling restart under live traffic (serving/router.py evidence)
    run("router", _ROUTER_SNIPPET, cap_s=400)
    # 3c''+) fleet: the cross-process plane — disagg (prefill-pool ->
    #        /fleet/kv/put -> decode-pool) vs unified over real localhost
    #        HTTP peers on the same pinned mixed trace, greedy outputs
    #        asserted identical, plus a peer-kill chaos arm (token-less
    #        re-route goodput — serving/fleet.py + docs/FLEET.md evidence;
    #        CPU-friendly tiny peers by design)
    run("fleet", _FLEET_SNIPPET, cap_s=420)
    # 3c''+n) fleet_netchaos: the fleet wire under seeded NETWORK chaos —
    #         a mid-trace single-edge partition + heal (TTL aging of the
    #         partitioned peer's affinity claims, classified refresh
    #         failures, post-heal anti-entropy reconcile), an armed
    #         net_drop dedup probe (idempotent dispatch: duplicate
    #         executions must be 0), and an armed net_corrupt KV probe
    #         (CRC32C envelope: zero corrupt payloads absorbed) —
    #         serving/fleet.py + serving/faults.py net-site evidence
    run("fleet_netchaos", _FLEET_NETCHAOS_SNIPPET, cap_s=420)
    # 3c''a) multichip: the mesh-sliced fleet A/B — 4 replicas x TP-2 on
    #        disjoint slices of a forced-8-device host vs the 1-slice arm
    #        (per-slice steady rates, placement-asserted disjointness,
    #        per-slice HBM ledger vs the single-mesh fleet footprint —
    #        parallel/slicing.py + docs/MULTICHIP.md evidence; CPU-pinned by
    #        design, like the MULTICHIP dryrun)
    run("multichip", _MULTICHIP_SNIPPET, cap_s=420)
    # 3c'''a) autoscale: the closed loop — fixed-min fleet vs SLO autoscaler
    #        on the SAME seeded diurnal trace (p95 TTFT, sheds,
    #        replica-seconds vs the fixed max-size budget —
    #        serving/autoscaler.py + workload/ evidence)
    run("autoscale", _AUTOSCALE_SNIPPET, cap_s=400)
    # 3c'''b) kv_tier: durable warm state — tiered vs HBM-only prefix-hit
    #        TTFT + kv_pressure sheds on the pinned many-session trace
    #        (live KV >> HBM), plus restart-survival and scale-down
    #        migration probes (serving/kv_pool.py host tier evidence)
    run("kv_tier", _KV_TIER_SNIPPET, cap_s=500)
    # 3c'''c) taskplane: exactly-once-effect bot delivery — ledger vs the seed
    #        at-least-once plane under a mid-answer worker kill on the same
    #        pinned trace (tasks/queue.py + bot delivery ledger evidence;
    #        CPU-only, no engine)
    run("taskplane", _TASKPLANE_SNIPPET, cap_s=200)
    # 3c''') obs: tracing+metrics decode-throughput A/B (must be within
    #        noise) + /metrics scrape cost and exposition validity against a
    #        known trace (serving/obs.py evidence)
    run("obs", _OBS_SNIPPET, cap_s=400)
    # 3d) streaming: client TTFT streaming-vs-nonstreaming on the same trace
    #     + attached/detached decode throughput (the token event queues must
    #     not throttle the engine — serving/streaming.py evidence)
    run("stream", _STREAM_SNIPPET, cap_s=400)
    # 4) config 4b: KNN at 1M-corpus scale (build/append/query latency)
    ecfg = _encoder_cfg()
    run(
        "knn_scale",
        _KNN_SCALE_SNIPPET.format(
            n_vec=KNN_VECTORS, dim=ecfg.hidden_size, nq=KNN_QUERIES
        ),
        cap_s=700,
    )
    # 4') config 4c: IVF-PQ ANN vs exact at the SAME 1M geometry — the
    #     recall-accounted speedup, recall-vs-nprobe curve, build/append cost
    #     (storage/ann.py + docs/ANN.md evidence)
    run(
        "ann_scale",
        _ANN_SNIPPET.format(n_vec=KNN_VECTORS, dim=ecfg.hidden_size, nq=KNN_QUERIES),
        cap_s=900,
    )
    # 4'') config 4d: durability kill-replay — SIGKILL mid-ingest, recover,
    #      recovered top-k identical + zero duplicates (docs/DURABILITY.md)
    run("durable", _DURABLE_SNIPPET, cap_s=400)
    # 5) config 5: MoE — true Mixtral per-layer expert shapes, deepest that
    #    fits first (8L ~ 11.5 GB int8 experts, measured 1057 tok/s), then 4L,
    #    then chip-scale geometry; the record carries `moe_geometry` saying
    #    which one ran (VERDICT r4 #7)
    #    caps sit close to each config's measured runtime (8L ~ 290 s, 4L
    #    ~ 130 s) so a worst-case walk through all three still leaves the
    #    later sections their budget
    if not run(
        "moe_mixtral8",
        _MOE_SNIPPET.format(cfg_fn="_moe_cfg_mixtral", layers=8),
        cap_s=450,
    ):
        if not run(
            "moe_mixtral4",
            _MOE_SNIPPET.format(cfg_fn="_moe_cfg_mixtral", layers=4),
            cap_s=350,
        ):
            run("moe", _MOE_SNIPPET.format(cfg_fn="_moe_cfg", layers=8), cap_s=400)
    # 6) config 4a: bulk ingestion (batched encode -> device appends)
    run("ingest", _INGEST_SNIPPET, cap_s=500)
    # 7) the real-weights path: real-format checkpoint -> convert -> /dialog
    run("real_ckpt", _REAL_CKPT_SNIPPET, cap_s=400)
    # 8) long-context prefill through the chunked-KV flash kernel
    run("longctx", _LONGCTX_SNIPPET, cap_s=450)
    # 9) tree speculative decoding: trained copy-task A/B + breakeven sweep
    run("spec", _SPEC_SNIPPET, cap_s=500)

    baseline_thread.join(timeout=max(30.0, min(600.0, left())))
    if baseline_thread.is_alive():
        extras["baseline_note"] = "torch-CPU baselines still running at emit"
    emit()


if __name__ == "__main__":
    main()
