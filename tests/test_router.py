"""Multi-replica serving plane (serving/router.py; docs/RESILIENCE.md "Fleet
topology"): health- and prefix-affinity-aware dispatch over N supervised
engine replicas, per-replica circuit breakers, token-less re-route on replica
death, graceful drain / rolling restart, and the SIGTERM whole-server drain.

Everything runs on CPU with tiny random models; chaos is exact (armed or
fire-on-Nth fault schedules, an injectable drain clock) — no sleep-and-hope
assertions on the failover paths.
"""

import asyncio
import threading
import time

import pytest

import jax

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.serving import (
    ByteTokenizer,
    EngineRouter,
    EngineUnavailable,
    FaultInjector,
    GenerationEngine,
    ModelRegistry,
    SchedulerRejected,
)
from django_assistant_bot_tpu.serving.server import DRAIN_KEY, create_app


def _params(seed=1):
    cfg = DecoderConfig.tiny()
    return cfg, llama.init(cfg, jax.random.key(seed))


def _engines(n=2, cfg=None, params=None, **kw):
    """N replicas over ONE shared weight tree (the registry's layout)."""
    if cfg is None:
        cfg, params = _params()
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 64)
    return cfg, [
        GenerationEngine(cfg, params, ByteTokenizer(), **kw).start()
        for _ in range(n)
    ]


class _FakeClock:
    """Deterministic drain clock: time advances ONLY through sleep(), which
    also yields a bounded slice of real time so engine threads progress."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, dt: float) -> None:
        self.t += dt
        time.sleep(min(dt, 0.005))


# ------------------------------------------------------------------ dispatch
def test_router_spreads_load_and_serves():
    _, engines = _engines(2)
    r = EngineRouter(engines)
    try:
        futs = [
            r.submit([1, 2, 3 + i], max_tokens=4, temperature=0.0)
            for i in range(8)
        ]
        for f in futs:
            assert len(f.result(timeout=120).token_ids) == 4
        stats = r.router_stats()
        # least-loaded + rotation: a healthy 2-replica fleet must not pin
        # every request onto one engine
        assert all(p["dispatched"] > 0 for p in stats["replicas"])
        assert stats["reroutes"] == 0
        assert r.supervision_stats()["healthy"] is True
    finally:
        r.stop()


def test_router_prefix_affinity_routes_to_registry_holder():
    """A prompt whose shared prefix is already registered in one replica's KV
    page pool must route there (docs/RESILIENCE.md: affinity below health) —
    and the affinity gauges must record it."""
    cfg, engines = _engines(2, prefix_min_tokens=8)
    r = EngineRouter(engines)
    try:
        prefix = list(range(1, 17))  # 16 tokens >= prefix_min_tokens
        first = r.submit(
            prefix + [40, 41, 42], max_tokens=2, temperature=0.0, prefix_len=16
        )
        first.result(timeout=120)
        holders = [
            i for i, e in enumerate(engines) if e.holds_prefix(prefix + [99], 16)
        ]
        assert len(holders) == 1  # registered exactly where it prefillled
        holder = holders[0]
        before = r.replicas[holder].dispatched
        for i in range(3):
            f = r.submit(
                prefix + [50 + i], max_tokens=2, temperature=0.0, prefix_len=16
            )
            f.result(timeout=120)
        assert r.replicas[holder].dispatched == before + 3
        assert r.affinity_hits >= 3
        # a holder skipped for drain/health reasons is a MISS: the request
        # re-prefills elsewhere and the gauge must say so, not claim a hit
        hits_before, misses_before = r.affinity_hits, r.affinity_misses
        r.replicas[holder].draining = True
        r.submit(
            prefix + [90], max_tokens=2, temperature=0.0, prefix_len=16
        ).result(timeout=120)
        r.replicas[holder].draining = False
        assert r.affinity_hits == hits_before
        assert r.affinity_misses == misses_before + 1
        # the in-process provider reads the context contract off the router
        assert r.max_seq_len == 64
    finally:
        r.stop()


def test_router_shed_propagates_when_every_replica_sheds():
    from django_assistant_bot_tpu.serving.scheduler import (
        RequestScheduler,
        SchedulerConfig,
    )

    cfg, params = _params()
    engines = [
        GenerationEngine(
            cfg,
            params,
            ByteTokenizer(),
            max_slots=2,
            max_seq_len=64,
            scheduler=RequestScheduler(SchedulerConfig(max_queue=0)),
        ).start()
        for _ in range(2)
    ]
    r = EngineRouter(engines)
    try:
        with pytest.raises(SchedulerRejected) as ei:
            r.submit([1, 2, 3], max_tokens=2)
        assert ei.value.retry_after_s > 0
        # shed is pressure, not a fault: no breaker opened
        assert all(p.breaker.state == "closed" for p in r.replicas)
    finally:
        r.stop()


def test_router_no_healthy_replica_raises_unavailable():
    _, engines = _engines(2)
    r = EngineRouter(engines)
    try:
        for e in engines:
            e._degraded_until = time.monotonic() + 30.0
        with pytest.raises(EngineUnavailable):
            r.submit([1, 2, 3], max_tokens=2)
        assert r.no_replica_available == 1
        for e in engines:
            e._degraded_until = None
        assert (
            len(r.submit([1, 2, 3], max_tokens=2, temperature=0.0)
                .result(timeout=120).token_ids)
            == 2
        )
    finally:
        r.stop()


# ------------------------------------------------------------- replica death
def _stall(engine, delay_s=0.1, fires=16):
    """Arm slow_tick so the engine's loop holds work in flight (lookahead
    keeps the sampled tokens on device, so requests stay client-token-less)."""
    inj = engine._faults
    assert inj is not None
    inj.arm("slow_tick", fires)
    with inj._lock:
        inj._sites["slow_tick"].delay_s = delay_s


def test_replica_kill_reroutes_tokenless_requests_goodput_one():
    """The acceptance contract: one of two replicas dies with queued and
    in-flight (token-less) work — every request completes on the survivor,
    the dead replica's breaker opens, and the fleet reports degraded."""
    cfg, params = _params()
    engines = [
        GenerationEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            faults=FaultInjector({}),
        ).start()
        for _ in range(2)
    ]
    # threshold 2, not the default 3: least-loaded dispatch sends ~3 of the
    # 6 requests to the doomed replica, but on a loaded CI host one can
    # finish inside the stall window before the kill — 2 re-routed failures
    # must still open the breaker or this test flakes under load
    r = EngineRouter(engines, breaker_threshold=2, breaker_reset_s=0.2)
    try:
        for i in range(2):  # warm both replicas (compiles out of the way)
            r.submit([1, 2, 3 + i], max_tokens=2, temperature=0.0).result(
                timeout=120
            )
        _stall(engines[0])
        _stall(engines[1])
        futs = [
            r.submit([5, 6, 7 + i], max_tokens=6, temperature=0.0)
            for i in range(6)
        ]
        time.sleep(0.05)  # inside the stalled first ticks: no host tokens yet
        r.kill_replica(0)
        for f in futs:
            assert len(f.result(timeout=120).token_ids) == 6  # goodput 1.0
        assert r.reroutes > 0
        assert r.rerouted_failed == 0
        assert r.failed_past_first_token == 0
        assert r.replicas[0].breaker.state in ("open", "half_open")
        sup = r.supervision_stats()
        assert sup["healthy"] is False  # one dead replica degrades the fleet
        assert sup["replicas"][0]["healthy"] is False
        # operator restart: the fleet heals
        r.restart_replica(0)
        assert r.supervision_stats()["healthy"] is True
        assert (
            len(
                r.submit([9, 9, 9], max_tokens=3, temperature=0.0)
                .result(timeout=120)
                .token_ids
            )
            == 3
        )
    finally:
        r.stop()


def test_router_stream_past_first_delta_fails_cleanly():
    """Mirror of the single-engine restart contract at fleet level: once a
    stream has emitted a delta, a replica death fails the request (no replay
    on another replica — the client would see divergent text)."""
    _, engines = _engines(2)
    r = EngineRouter(engines, breaker_reset_s=0.2)
    r.replicas[1].draining = True  # pin dispatch onto replica0

    async def go():
        agen = r.generate_stream("hello", max_tokens=48, temperature=0.0)
        first = await agen.__anext__()
        assert first.token_id is not None
        r.kill_replica(0)
        with pytest.raises(RuntimeError):
            async for _ in agen:
                pass

    try:
        asyncio.run(go())
        assert r.failed_past_first_token == 1
        assert r.reroutes == 0
        r.replicas[1].draining = False
        res = r.submit([1, 2, 3], max_tokens=3, temperature=0.0).result(
            timeout=120
        )
        assert len(res.token_ids) == 3
    finally:
        r.stop()


def test_replica_dead_fault_site_exercises_failover():
    """The replica_dead chaos site kills the replica the dispatcher is about
    to pick — the request lands on the survivor, nothing is lost."""
    cfg, params = _params()
    engines = [
        GenerationEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64
        ).start()
        for _ in range(2)
    ]
    inj = FaultInjector(
        {"replica_dead": {"fire_on": [3]}, "replica_slow": {"fire_on": [1], "delay_s": 0.01}}
    )
    r = EngineRouter(engines, faults=inj, breaker_reset_s=0.2)
    try:
        futs = [
            r.submit([1, 2, 3 + i], max_tokens=3, temperature=0.0)
            for i in range(4)
        ]
        for f in futs:
            assert len(f.result(timeout=120).token_ids) == 3
        assert inj.stats()["replica_dead"]["fires"] == 1
        assert inj.stats()["replica_slow"]["fires"] == 1
        assert sum(not e._running for e in engines) == 1
    finally:
        r.stop()


def test_reroute_preserves_remaining_deadline():
    """A re-routed request must carry its REMAINING deadline budget, not a
    fresh one per hop (the single-engine salvage keeps the original
    deadline_at — the fleet contract matches): an exhausted budget at
    re-route time is a DeadlineExceeded, and a live one is passed through
    shrunk."""
    from concurrent.futures import Future

    from django_assistant_bot_tpu.serving.router import _Routed, _StreamShim
    from django_assistant_bot_tpu.serving.scheduler import DeadlineExceeded

    _, engines = _engines(2)
    r = EngineRouter(engines)

    def routed(deadline_s, deadline_at):
        state = _Routed(
            [1, 2, 3],
            dict(
                max_tokens=2,
                temperature=0.0,
                top_p=0.9,
                json_format=False,
                prefix_len=0,
                priority="interactive",
                tenant="default",
                deadline_s=deadline_s,
            ),
            Future(),
            _StreamShim(None),
        )
        state.deadline_at = deadline_at
        failed = Future()
        failed.set_exception(RuntimeError("generation engine stopped"))
        return state, failed

    try:
        # budget already gone: no fresh attempt, the client gets its 504
        state, failed = routed(0.2, time.monotonic() - 1.0)
        r._on_inner_done(state, r.replicas[0], failed)
        assert isinstance(state.outer.exception(timeout=10), DeadlineExceeded)
        assert r.reroutes == 0
        # budget remaining: the hop happens with the SHRUNK deadline
        state, failed = routed(100.0, time.monotonic() + 30.0)
        r._on_inner_done(state, r.replicas[0], failed)
        assert state.outer.result(timeout=120).token_ids
        assert r.reroutes == 1
        assert state.kwargs["deadline_s"] <= 30.0
    finally:
        r.stop()


# -------------------------------------------------------------------- drain
def test_rolling_restart_under_live_traffic_sheds_nothing():
    """The zero-downtime acceptance contract: drain + restart every replica
    while requests keep flowing — every future completes, zero requests shed
    attributable to the drain, and both engine loops really restarted."""
    cfg, params = _params()
    engines = [
        GenerationEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            faults=FaultInjector({}),
        ).start()
        for _ in range(2)
    ]
    clock = _FakeClock()
    r = EngineRouter(engines, clock=clock, sleep=clock.sleep)
    try:
        for i in range(2):
            r.submit([1, 2, 3 + i], max_tokens=2, temperature=0.0).result(
                timeout=120
            )
        threads_before = [e._thread for e in engines]
        _stall(engines[0], delay_s=0.05, fires=8)
        _stall(engines[1], delay_s=0.05, fires=8)
        futs = [
            r.submit([5, 6, 7 + i], max_tokens=4, temperature=0.0)
            for i in range(6)
        ]
        reports = []
        rr = threading.Thread(
            target=lambda: reports.extend(r.rolling_restart(deadline_s=1e9))
        )
        rr.start()
        # live traffic THROUGH the rolling restart
        while rr.is_alive():
            futs.append(r.submit([8, 9], max_tokens=2, temperature=0.0))
            time.sleep(0.01)
        rr.join(timeout=120)
        for f in futs:
            assert f.exception(timeout=120) is None
        assert len(reports) == 2
        assert all(rep["drained"] for rep in reports)
        assert all(rep["forced_failures"] == 0 for rep in reports)
        assert r.drain_shed == 0
        assert r.drains == 2
        # both loops are NEW threads (a real restart, not a no-op)
        assert all(
            e._thread is not old for e, old in zip(engines, threads_before)
        )
        assert r.supervision_stats()["healthy"] is True
    finally:
        r.stop()


def test_drain_deadline_forces_and_counts_shed():
    """A deadline of zero with work in flight force-restarts: the drain
    reports the forced failures honestly, and every victim follows the
    fleet contract — token-less requests re-route to the survivor (no
    client-visible failure), requests past their first token fail cleanly."""
    cfg, params = _params()
    engines = [
        GenerationEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            faults=FaultInjector({}),
        ).start()
        for _ in range(2)
    ]
    clock = _FakeClock()
    r = EngineRouter(engines, clock=clock, sleep=clock.sleep, breaker_reset_s=0.2)
    try:
        for i in range(2):
            r.submit([1, 2, 3 + i], max_tokens=2, temperature=0.0).result(
                timeout=120
            )
        r.replicas[1].draining = True  # pin the trace onto replica0
        _stall(engines[0], delay_s=0.2, fires=8)
        futs = [
            r.submit([5, 6, 7 + i], max_tokens=4, temperature=0.0)
            for i in range(3)
        ]
        r.replicas[1].draining = False
        time.sleep(0.02)
        report = r.drain(0, deadline_s=0.0)
        assert report["drained"] is False
        assert report["forced_failures"] > 0
        assert r.drain_shed == report["forced_failures"]
        ok = failed = 0
        for f in futs:
            if f.exception(timeout=120) is None:
                ok += 1
            else:
                failed += 1
        # token-less victims survived via re-route; only requests already
        # past their first client-visible token may fail — and each such
        # failure is accounted for
        assert failed == r.failed_past_first_token
        assert r.rerouted_failed == 0
        assert ok + failed == len(futs)
        assert ok > 0  # at least the queued (token-less) work survived
    finally:
        r.stop()


def test_drain_rejects_concurrent_drain_of_same_replica():
    _, engines = _engines(1)
    r = EngineRouter(engines)
    try:
        r.replicas[0].draining = True
        with pytest.raises(RuntimeError, match="already draining"):
            r.drain(0)
        r.replicas[0].draining = False
    finally:
        r.stop()


# ----------------------------------------------------- registry + HTTP plane
@pytest.fixture()
def replica_registry():
    registry = ModelRegistry.from_config(
        {
            "tiny-chat": {
                "kind": "decoder",
                "tiny": True,
                "max_slots": 2,
                "max_seq_len": 64,
                "replicas": 2,
                "router_breaker_reset_s": 0.2,
            }
        }
    )
    yield registry
    registry.stop()


def test_registry_builds_router_only_past_one_replica():
    registry = ModelRegistry.from_config(
        {"tiny-chat": {"kind": "decoder", "tiny": True, "max_slots": 2,
                       "max_seq_len": 64}}
    )
    try:
        # replicas=1 (default): the plain engine, byte-identical serving path
        assert isinstance(registry.get_generator("tiny-chat"), GenerationEngine)
    finally:
        registry.stop()
    with pytest.raises(ValueError, match="replicas"):
        ModelRegistry.from_config(
            {"emb": {"kind": "encoder", "tiny": True, "replicas": 2}}
        )


def _run_with_client(registry, go, **app_kw):
    from aiohttp.test_utils import TestClient, TestServer

    async def main():
        client = TestClient(TestServer(create_app(registry, **app_kw)))
        await client.start_server()
        try:
            await go(client)
        finally:
            await client.close()

    asyncio.run(main())


def test_router_registry_serves_and_healthz_aggregates(replica_registry):
    router = replica_registry.get_generator("tiny-chat")
    assert isinstance(router, EngineRouter)
    assert len(router.replicas) == 2

    async def go(client):
        resp = await client.post(
            "/dialog/",
            json={
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 2,
            },
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["response"]["usage"]["completion_tokens"] >= 1

        resp = await client.get("/healthz")
        data = await resp.json()
        assert data["status"] == "ok"
        g = data["generators"]["tiny-chat"]
        assert g["router"]["n_replicas"] == 2
        assert len(g["router"]["replicas"]) == 2
        assert len(g["supervision"]["replicas"]) == 2
        assert g["kv"]["kv_layout_effective"] == "paged"

        # one dead replica of two: the fleet reports degraded with the dead
        # replica identifiable, but /dialog/ keeps serving from the survivor
        router.kill_replica(0)
        resp = await client.get("/healthz")
        data = await resp.json()
        assert data["status"] == "degraded"
        per = data["generators"]["tiny-chat"]["supervision"]["replicas"]
        assert [p["healthy"] for p in per].count(False) == 1
        resp = await client.post(
            "/dialog/",
            json={
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "still here?"}],
                "max_tokens": 2,
            },
        )
        assert resp.status == 200
        router.restart_replica(0)
        resp = await client.get("/healthz")
        assert (await resp.json())["status"] == "ok"

    _run_with_client(replica_registry, go)


def test_server_graceful_drain_finishes_inflight_then_503s():
    """The SIGTERM contract (cli serve --drain-deadline-s): once draining,
    admission 503s with Retry-After and /healthz says so; on shutdown the
    server waits for accepted work, so in-flight futures complete instead of
    dying with the process."""
    registry = ModelRegistry.from_config(
        {
            "tiny-chat": {
                "kind": "decoder",
                "tiny": True,
                "max_slots": 2,
                "max_seq_len": 64,
                "faults": {"slow_tick": {"every": 1, "delay_s": 0.05,
                                         "max_fires": 10}},
            }
        }
    )
    eng = registry.get_generator("tiny-chat")
    held = {}

    async def go(client):
        # work accepted BEFORE the drain begins
        held["fut"] = eng.submit([1, 2, 3], max_tokens=4, temperature=0.0)
        client.app[DRAIN_KEY]["draining"] = True
        resp = await client.post(
            "/dialog/",
            json={
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 2,
            },
        )
        assert resp.status == 503
        assert "Retry-After" in resp.headers
        resp = await client.post(
            "/embeddings/", json={"model": "x", "texts": ["a"]}
        )
        assert resp.status == 503
        resp = await client.get("/healthz")
        assert (await resp.json())["status"] == "draining"
        client.app[DRAIN_KEY]["draining"] = False
        # client.close() tears the server down: on_shutdown flips the drain
        # flag and waits for registry.idle() before on_cleanup stops engines

    try:
        _run_with_client(registry, go, drain_deadline_s=30.0)
        fut = held["fut"]
        assert fut.done()
        assert fut.exception() is None
        assert len(fut.result().token_ids) == 4
    finally:
        registry.stop()


# ------------------------------------------------- kv_layout_effective gauge
def test_kv_layout_effective_surfaces_requested_vs_effective():
    """The requested-vs-effective gauge still exists for genuinely
    non-pageable configs (a context no page size divides), and speculative
    engines — which used to be the silent-legacy case — now report the
    paged plane as effective."""
    cfg, params = _params()
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
        speculative=2,
    )
    ks = eng.kv_stats()
    assert ks["kv_layout_requested"] == "paged"
    assert ks["kv_layout_effective"] == "paged"
    assert eng.tick_stats()["kv"]["kv_layout_effective"] == "paged"

    # a prime-length context: no page size divides it -> legacy fallback,
    # and the gauge is how operators see it
    odd = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=61
    )
    ks = odd.kv_stats()
    assert ks["kv_layout_requested"] == "paged"
    assert ks["kv_layout_effective"] == "legacy"
