"""Answer task plane + Telegram adapter: the reference's test_answer_task shape —
the worker coroutine is driven in-process with a fake platform (SURVEY.md §4).

Exactly-once-effect delivery coverage: the per-part ledger (skip re-posts on
re-execution), the turn-complete replay skip, the mid-answer worker-kill chaos
case, inbound update_id dedup, flood-control → RetryLater mapping, and
send_answer_task's permanent/transient honesty."""

import asyncio

import pytest

from django_assistant_bot_tpu.bot.domain import (
    BotPlatform,
    Button,
    MultiPartAnswer,
    SingleAnswer,
    Update,
    User,
    UserUnavailableError,
)
from django_assistant_bot_tpu.bot.platforms.telegram.api import (
    TelegramBadRequest,
    TelegramForbidden,
    TelegramRetryAfter,
)
from django_assistant_bot_tpu.bot.platforms.telegram.platform import TelegramBotPlatform
from django_assistant_bot_tpu.bot.tasks import _answer_task, _post_answer, _send_answer_task
from django_assistant_bot_tpu.storage import models
from django_assistant_bot_tpu.tasks.queue import PermanentTaskError, RetryLater, TaskRecord, Worker


class RecordingPlatform(BotPlatform):
    def __init__(self, fail_with=None):
        self.posted = []
        self.fail_with = fail_with

    @property
    def codename(self):
        return "telegram"

    async def get_update(self, request):
        raise NotImplementedError

    async def post_answer(self, chat_id, answer):
        if self.fail_with:
            raise self.fail_with
        self.posted.append((chat_id, answer))

    async def action_typing(self, chat_id):
        pass


class FakeAPI:
    """Scripted TelegramAPI double."""

    def __init__(self, errors=None):
        self.calls = []
        self.errors = list(errors or [])

    async def send_message(self, chat_id, text, parse_mode=None, reply_markup=None, disable_web_page_preview=None):
        self.calls.append(("send_message", chat_id, text, parse_mode, reply_markup))
        if self.errors:
            raise self.errors.pop(0)
        return {"message_id": 1}

    async def send_audio(self, chat_id, audio, filename=None, reply_markup=None):
        self.calls.append(("send_audio", chat_id, filename))
        return {"message_id": 2}

    async def send_chat_action(self, chat_id, action):
        self.calls.append(("action", chat_id, action))

    async def get_file(self, file_id):
        return {"file_path": "photos/x.jpg", "file_id": file_id}

    async def download_file(self, file_path):
        return b"JPEGDATA"


@pytest.fixture()
def seeded(tmp_db, monkeypatch):
    from django_assistant_bot_tpu.bot.assistant_bot import AssistantBot

    bot = models.Bot.objects.create(codename="tb")
    user = models.BotUser.objects.create(user_id="u1", platform="telegram")
    instance = models.Instance.objects.create(bot=bot, user=user)
    dialog = models.Dialog.objects.create(instance=instance)

    async def fake_answer(self, messages, debug_info, do_interrupt):
        return SingleAnswer(text="task answer", usage=[{"model": "test"}])

    monkeypatch.setattr(AssistantBot, "get_answer_to_messages", fake_answer)
    return bot, instance, dialog


def _update_dict(message_id=1, text="hello", update_id=None):
    return Update(
        chat_id="u1", message_id=message_id, text=text, user=User(id="u1"),
        update_id=update_id,
    ).to_dict()


def test_answer_task_end_to_end(seeded):
    bot, instance, dialog = seeded
    from django_assistant_bot_tpu.bot.services.dialog_service import create_user_message

    create_user_message(dialog, 1, "hello")
    platform = RecordingPlatform()
    asyncio.run(_answer_task("tb", dialog.id, "telegram", _update_dict(), platform=platform))
    assert platform.posted and platform.posted[0][1].text == "task answer"
    # bot message persisted with cost rollup
    msgs = models.Message.objects.filter(dialog=dialog).order_by("id").all()
    assert msgs[-1].text == "task answer"


def test_answer_task_marks_unavailable_on_forbidden(seeded):
    bot, instance, dialog = seeded
    from django_assistant_bot_tpu.bot.services.dialog_service import create_user_message

    create_user_message(dialog, 1, "hello")
    platform = RecordingPlatform(fail_with=UserUnavailableError("u1"))
    asyncio.run(_answer_task("tb", dialog.id, "telegram", _update_dict(), platform=platform))
    assert models.Instance.objects.get(id=instance.id).is_unavailable


def test_send_answer_task_skips_unavailable(seeded):
    bot, instance, dialog = seeded
    instance.is_unavailable = True
    instance.save()
    platform = RecordingPlatform()
    asyncio.run(
        _send_answer_task(
            "tb", "telegram", "u1", SingleAnswer(text="bcast").to_dict(), platform=platform
        )
    )
    assert platform.posted == []


def test_send_answer_task_delivers(seeded):
    platform = RecordingPlatform()
    asyncio.run(
        _send_answer_task(
            "tb", "telegram", "u1", SingleAnswer(text="bcast").to_dict(), platform=platform
        )
    )
    assert platform.posted[0][1].text == "bcast"


# ----------------------------------------------------------- telegram adapter
def test_convert_message_update():
    platform = TelegramBotPlatform("tok", api=FakeAPI())
    data = {
        "message": {
            "message_id": 7,
            "chat": {"id": 123},
            "text": "hi there",
            "from": {"id": 42, "username": "alice", "first_name": "A", "language_code": "en"},
        }
    }
    upd = asyncio.run(platform.get_update(data))
    assert upd.chat_id == "123" and upd.message_id == 7 and upd.text == "hi there"
    assert upd.user.username == "alice"


def test_convert_callback_and_photo_updates():
    platform = TelegramBotPlatform("tok", api=FakeAPI())
    cb = {
        "callback_query": {
            "id": "cb1",
            "from": {"id": 42, "username": "alice"},
            "message": {"message_id": 9},
            "data": "/continue",
        }
    }
    upd = asyncio.run(platform.get_update(cb))
    assert upd.text == "/continue" and upd.message_id == 9

    photo = {
        "message": {
            "message_id": 10,
            "chat": {"id": 1},
            "from": {"id": 42},
            "photo": [{"file_id": "small"}, {"file_id": "big", "file_unique_id": "bu"}],
            "caption": "see this",
        }
    }
    upd = asyncio.run(platform.get_update(photo))
    assert upd.photo.content == b"JPEGDATA"
    assert upd.photo.extension == "jpg"
    assert upd.text == "see this"


def test_markdown_fallback_on_parse_error():
    api = FakeAPI(errors=[TelegramBadRequest(400, "Bad Request: can't parse entities")])
    platform = TelegramBotPlatform("tok", api=api)
    asyncio.run(platform.post_answer("1", SingleAnswer(text="broken *md")))
    # first MarkdownV2 attempt failed, second plain attempt went through
    assert len(api.calls) == 2
    assert api.calls[0][3] == "MarkdownV2" and api.calls[1][3] is None
    assert api.calls[1][2] == "broken *md"


def test_forbidden_raises_user_unavailable():
    api = FakeAPI(errors=[TelegramForbidden(403, "Forbidden: bot was blocked by the user")])
    platform = TelegramBotPlatform("tok", api=api)
    with pytest.raises(UserUnavailableError):
        asyncio.run(platform.post_answer("1", SingleAnswer(text="x")))


def test_forbidden_kicked_does_not_raise():
    api = FakeAPI(errors=[TelegramForbidden(403, "Forbidden: bot was kicked from the group chat")])
    platform = TelegramBotPlatform("tok", api=api)
    asyncio.run(platform.post_answer("1", SingleAnswer(text="x")))  # no raise


def test_inline_keyboard_markup():
    api = FakeAPI()
    platform = TelegramBotPlatform("tok", api=api)
    answer = SingleAnswer(text="pick", buttons=[[Button("Go", callback_data="/go")]])
    asyncio.run(platform.post_answer("1", answer))
    markup = api.calls[0][4]
    assert markup == {"inline_keyboard": [[{"text": "Go", "callback_data": "/go"}]]}


# ---------------------------------------------------- exactly-once delivery
def _three_parts():
    return MultiPartAnswer(parts=[SingleAnswer(text=f"part {i}") for i in range(3)])


def test_post_answer_ledger_skips_sent_parts(seeded):
    platform = RecordingPlatform()
    asyncio.run(_post_answer(platform, "u1", _three_parts(), ledger_scope="answer:1:9"))
    assert [a.text for _, a in platform.posted] == ["part 0", "part 1", "part 2"]
    # re-execution (worker loss replay): every part is already in the ledger
    asyncio.run(_post_answer(platform, "u1", _three_parts(), ledger_scope="answer:1:9"))
    assert len(platform.posted) == 3  # zero duplicates
    # a DIFFERENT scope posts fresh
    asyncio.run(_post_answer(platform, "u1", _three_parts(), ledger_scope="answer:1:10"))
    assert len(platform.posted) == 6


def test_post_answer_clean_failure_releases_ledger_claim(seeded):
    """A part whose POST fails in our frame must NOT stay claimed: the retry
    re-posts it (only a worker death mid-POST leaves an uncertain row)."""

    class FlakyPlatform(RecordingPlatform):
        def __init__(self):
            super().__init__()
            self.failures_left = 1

        async def post_answer(self, chat_id, answer):
            if answer.text == "part 1" and self.failures_left:
                self.failures_left -= 1
                raise ConnectionError("platform blip")
            await super().post_answer(chat_id, answer)

    platform = FlakyPlatform()
    with pytest.raises(ConnectionError):
        asyncio.run(_post_answer(platform, "u1", _three_parts(), ledger_scope="answer:2:1"))
    assert [a.text for _, a in platform.posted] == ["part 0"]
    # the retry: part 0 deduped, parts 1-2 delivered
    asyncio.run(_post_answer(platform, "u1", _three_parts(), ledger_scope="answer:2:1"))
    assert [a.text for _, a in platform.posted] == ["part 0", "part 1", "part 2"]


def test_flood_control_maps_to_retry_later(seeded):
    api = FakeAPI(
        errors=[TelegramRetryAfter(429, "Too Many Requests: retry after 17", 17.0)]
    )
    platform = TelegramBotPlatform("tok", api=api)
    with pytest.raises(RetryLater) as ei:
        asyncio.run(_post_answer(platform, "1", SingleAnswer(text="x")))
    assert ei.value.delay_s == 17.0


def test_answer_task_replay_skips_completed_turn(seeded):
    bot, instance, dialog = seeded
    from django_assistant_bot_tpu.bot.services.dialog_service import create_user_message

    create_user_message(dialog, 1, "hello")
    platform = RecordingPlatform()
    upd = _update_dict(update_id=501)
    asyncio.run(_answer_task("tb", dialog.id, "telegram", upd, platform=platform))
    assert len(platform.posted) == 1
    msgs_after_first = models.Message.objects.filter(dialog=dialog).count()
    # the at-least-once replay (worker died between delivery and done): the
    # turn-complete marker skips the WHOLE pipeline — no second LLM turn, no
    # duplicate post, no duplicate history row
    asyncio.run(_answer_task("tb", dialog.id, "telegram", upd, platform=platform))
    assert len(platform.posted) == 1
    assert models.Message.objects.filter(dialog=dialog).count() == msgs_after_first


def test_answer_task_reraises_transient_delivery_errors(seeded):
    """Transient delivery failures are the QUEUE's to retry — swallowing them
    into a log line (the seed behavior) silently dropped the user's answer."""
    bot, instance, dialog = seeded
    from django_assistant_bot_tpu.bot.services.dialog_service import create_user_message

    create_user_message(dialog, 1, "hello")
    platform = RecordingPlatform(fail_with=ConnectionError("telegram down"))
    with pytest.raises(ConnectionError):
        asyncio.run(
            _answer_task("tb", dialog.id, "telegram", _update_dict(update_id=502),
                         platform=platform)
        )


def test_answer_task_missing_dialog_is_permanent(seeded):
    with pytest.raises(PermanentTaskError):
        asyncio.run(_answer_task("tb", 999999, "telegram", _update_dict(update_id=503)))


class _FakeClock:
    def __init__(self, t=None):
        import time as _time

        # slightly ahead of wall time so real-clock delay() etas are due
        self.t = _time.time() + 60.0 if t is None else t

    def __call__(self):
        return self.t

    def advance(self, dt_s):
        self.t += dt_s


def test_worker_kill_mid_answer_delivers_exactly_once(seeded, monkeypatch):
    """THE chaos case (ISSUE 13 acceptance): a worker killed after delivering
    part 1 of 3; lease expiry re-dispatches the task; the re-execution must
    deliver the REMAINING parts only.  The seed plane re-posted everything."""
    from django_assistant_bot_tpu.bot.assistant_bot import AssistantBot
    from django_assistant_bot_tpu.serving.faults import (
        FaultInjector,
        reset_global_injector,
        set_global_injector,
    )

    bot, instance, dialog = seeded
    from django_assistant_bot_tpu.bot.services.dialog_service import create_user_message

    create_user_message(dialog, 1, "hello")
    generations = []

    async def fake_multi(self, messages, debug_info, do_interrupt):
        generations.append(1)
        return _three_parts()

    monkeypatch.setattr(AssistantBot, "get_answer_to_messages", fake_multi)
    platform = RecordingPlatform()
    monkeypatch.setattr(
        "django_assistant_bot_tpu.bot.tasks.get_bot_platform", lambda *a: platform
    )
    from django_assistant_bot_tpu.bot.tasks import answer_task

    # the worker_lost site is consulted once pre-body (Worker.execute) and
    # once per DELIVERED part (_post_answer): call 3 = right after "part 1"
    # went out
    inj = FaultInjector({"task_worker_lost": {"fire_on": [3]}})
    set_global_injector(inj)
    clk = _FakeClock()
    try:
        rec = answer_task.delay("tb", dialog.id, "telegram", _update_dict(update_id=601))
        w = Worker(["query"], lease_s=10.0, heartbeat_s=0, clock=clk)
        w.run_one()
        rec.refresh()
        assert rec.status == "running"  # the "dead" worker left its lease
        assert [a.text for _, a in platform.posted] == ["part 0", "part 1"]
        clk.advance(11.0)  # lease expires; reclaim re-dispatches
        w.run_one()
        rec.refresh()
        assert rec.status == "done"
        # every part delivered EXACTLY once — the re-execution skipped 0 and 1
        assert [a.text for _, a in platform.posted] == ["part 0", "part 1", "part 2"]
        # and it delivered from the persisted SNAPSHOT: one LLM generation
        # total, so the delivered parts all belong to one answer
        assert len(generations) == 1
        assert w.stats()["worker_lost_aborts"] == 1
    finally:
        reset_global_injector()


def test_partial_replay_redelivers_snapshot_not_a_fresh_generation(seeded, monkeypatch):
    """The answer is persisted before delivery starts: a replay after a
    partial delivery re-sends the SAME answer's remaining parts even when
    the model would now generate something different (no spliced answers)."""
    from django_assistant_bot_tpu.bot.assistant_bot import AssistantBot

    bot, instance, dialog = seeded
    from django_assistant_bot_tpu.bot.services.dialog_service import create_user_message

    create_user_message(dialog, 1, "hello")
    generations = []

    async def nondeterministic(self, messages, debug_info, do_interrupt):
        generations.append(1)
        n = len(generations)
        return MultiPartAnswer(
            parts=[SingleAnswer(text=f"gen{n} part {i}") for i in range(2)]
        )

    monkeypatch.setattr(AssistantBot, "get_answer_to_messages", nondeterministic)

    class DieOnPart1(RecordingPlatform):
        def __init__(self):
            super().__init__()
            self.deaths_left = 1

        async def post_answer(self, chat_id, answer):
            if answer.text.endswith("part 1") and self.deaths_left:
                self.deaths_left -= 1
                raise ConnectionError("blip before part 1 lands")
            await super().post_answer(chat_id, answer)

    platform = DieOnPart1()
    upd = _update_dict(update_id=602)
    with pytest.raises(ConnectionError):
        asyncio.run(_answer_task("tb", dialog.id, "telegram", upd, platform=platform))
    assert [a.text for _, a in platform.posted] == ["gen1 part 0"]
    # the retry: no second generation — the snapshot is re-delivered, so the
    # user gets gen1's part 1, not gen2's
    asyncio.run(_answer_task("tb", dialog.id, "telegram", upd, platform=platform))
    assert [a.text for _, a in platform.posted] == ["gen1 part 0", "gen1 part 1"]
    assert len(generations) == 1


def test_ledger_prune_removes_expired_rows(seeded):
    import datetime as dt

    from django_assistant_bot_tpu.bot import tasks as bot_tasks

    old = dt.datetime.now(dt.timezone.utc) - dt.timedelta(days=30)
    models.DeliveredPart.objects.create(scope="ancient:1", part=0, state="sent", created_at=old)
    models.SeenUpdate.objects.create(platform="telegram", bot_codename="tb", update_id=1, created_at=old)
    models.DeliveredPart.objects.create(scope="fresh:1", part=0, state="sent")
    bot_tasks._last_prune[0] = 0.0
    pruned = bot_tasks._maybe_prune_ledgers()
    assert pruned == 2
    assert models.DeliveredPart.objects.filter(scope="ancient:1").count() == 0
    assert models.DeliveredPart.objects.filter(scope="fresh:1").count() == 1
    # rate-gated: an immediate second call is a no-op
    models.DeliveredPart.objects.create(scope="ancient:2", part=0, created_at=old)
    assert bot_tasks._maybe_prune_ledgers() == 0
    # ...but the beat-scheduled maintenance task FORCES the sweep (it runs on
    # the worker's cadence, never the webhook request path)
    rec = bot_tasks.prune_ledgers_task.delay()
    Worker(["query"]).run_until_idle()
    rec.refresh()
    assert rec.status == "done" and rec.result == 1
    assert models.DeliveredPart.objects.filter(scope="ancient:2").count() == 0


def test_send_answer_task_bad_payload_dead_letters(seeded, monkeypatch):
    platform = RecordingPlatform()
    monkeypatch.setattr(
        "django_assistant_bot_tpu.bot.tasks.get_bot_platform", lambda *a: platform
    )
    from django_assistant_bot_tpu.bot.tasks import send_answer_task

    rec = send_answer_task.delay("tb", "telegram", "u1", {"audio": "not-base64!!", "text": None})
    Worker(["query"]).run_until_idle()
    rec.refresh()
    assert rec.status == "dead" and rec.error_kind == "permanent"
    assert "deserialize" in rec.error
    assert platform.posted == []


def test_send_answer_task_reraises_transient(seeded):
    platform = RecordingPlatform(fail_with=ConnectionError("telegram down"))
    with pytest.raises(ConnectionError):
        asyncio.run(
            _send_answer_task(
                "tb", "telegram", "u1", SingleAnswer(text="bcast").to_dict(),
                platform=platform,
            )
        )


def test_queued_send_answer_dedups_parts_across_retry(seeded, monkeypatch):
    """A broadcast send that dies mid-delivery dedups by its TaskRecord id."""

    class DieAfterFirst(RecordingPlatform):
        def __init__(self):
            super().__init__()
            self.deaths_left = 1

        async def post_answer(self, chat_id, answer):
            await super().post_answer(chat_id, answer)
            if answer.text == "part 0" and self.deaths_left:
                self.deaths_left -= 1
                err = RuntimeError("worker dies now")
                err.site = "task_worker_lost"
                raise err

    platform = DieAfterFirst()
    monkeypatch.setattr(
        "django_assistant_bot_tpu.bot.tasks.get_bot_platform", lambda *a: platform
    )
    from django_assistant_bot_tpu.bot.tasks import send_answer_task

    clk = _FakeClock()
    rec = send_answer_task.delay("tb", "telegram", "u1", _three_parts().to_dict())
    w = Worker(["query"], lease_s=10.0, heartbeat_s=0, clock=clk)
    w.run_one()
    clk.advance(11.0)
    w.run_one()
    rec.refresh()
    assert rec.status == "done"
    assert [a.text for _, a in platform.posted] == ["part 0", "part 1", "part 2"]


# ------------------------------------------------------------- inbound dedup
def test_ingest_dedups_platform_update_ids(seeded):
    from django_assistant_bot_tpu.bot.services.ingest_service import ingest_update

    upd = Update(chat_id="u1", message_id=5, text="hi", user=User(id="u1"), update_id=42)
    _, r1 = ingest_update("tb", "telegram", upd)
    _, r2 = ingest_update("tb", "telegram", upd)  # webhook redelivery
    assert r1 is not None and r2 is None
    assert TaskRecord.objects.filter(name__contains="answer_task").count() == 1
    # a NEW update enqueues normally
    upd2 = Update(chat_id="u1", message_id=6, text="more", user=User(id="u1"), update_id=43)
    _, r3 = ingest_update("tb", "telegram", upd2)
    assert r3 is not None
    # updates WITHOUT an update_id (API-driven, tests) never dedup
    upd3 = Update(chat_id="u1", message_id=7, text="again", user=User(id="u1"))
    _, r4 = ingest_update("tb", "telegram", upd3)
    _, r5 = ingest_update("tb", "telegram", upd3)
    assert r4 is not None and r5 is not None


def test_convert_update_carries_update_id():
    platform = TelegramBotPlatform("tok", api=FakeAPI())
    data = {
        "update_id": 990011,
        "message": {
            "message_id": 7,
            "chat": {"id": 123},
            "text": "hi",
            "from": {"id": 42},
        },
    }
    upd = asyncio.run(platform.get_update(data))
    assert upd.update_id == 990011
    # queue transport round-trip keeps it
    assert Update.from_dict(upd.to_dict()).update_id == 990011
    # pre-ledger payloads (no update_id key) still parse
    legacy = upd.to_dict()
    legacy.pop("update_id")
    assert Update.from_dict(legacy).update_id is None
