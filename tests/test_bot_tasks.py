"""Answer task plane + Telegram adapter: the reference's test_answer_task shape —
the worker coroutine is driven in-process with a fake platform (SURVEY.md §4)."""

import asyncio

import pytest

from django_assistant_bot_tpu.bot.domain import (
    BotPlatform,
    Button,
    SingleAnswer,
    Update,
    User,
    UserUnavailableError,
)
from django_assistant_bot_tpu.bot.platforms.telegram.api import (
    TelegramBadRequest,
    TelegramForbidden,
)
from django_assistant_bot_tpu.bot.platforms.telegram.platform import TelegramBotPlatform
from django_assistant_bot_tpu.bot.tasks import _answer_task, _send_answer_task
from django_assistant_bot_tpu.storage import models


class RecordingPlatform(BotPlatform):
    def __init__(self, fail_with=None):
        self.posted = []
        self.fail_with = fail_with

    @property
    def codename(self):
        return "telegram"

    async def get_update(self, request):
        raise NotImplementedError

    async def post_answer(self, chat_id, answer):
        if self.fail_with:
            raise self.fail_with
        self.posted.append((chat_id, answer))

    async def action_typing(self, chat_id):
        pass


class FakeAPI:
    """Scripted TelegramAPI double."""

    def __init__(self, errors=None):
        self.calls = []
        self.errors = list(errors or [])

    async def send_message(self, chat_id, text, parse_mode=None, reply_markup=None, disable_web_page_preview=None):
        self.calls.append(("send_message", chat_id, text, parse_mode, reply_markup))
        if self.errors:
            raise self.errors.pop(0)
        return {"message_id": 1}

    async def send_audio(self, chat_id, audio, filename=None, reply_markup=None):
        self.calls.append(("send_audio", chat_id, filename))
        return {"message_id": 2}

    async def send_chat_action(self, chat_id, action):
        self.calls.append(("action", chat_id, action))

    async def get_file(self, file_id):
        return {"file_path": "photos/x.jpg", "file_id": file_id}

    async def download_file(self, file_path):
        return b"JPEGDATA"


@pytest.fixture()
def seeded(tmp_db, monkeypatch):
    from django_assistant_bot_tpu.bot.assistant_bot import AssistantBot

    bot = models.Bot.objects.create(codename="tb")
    user = models.BotUser.objects.create(user_id="u1", platform="telegram")
    instance = models.Instance.objects.create(bot=bot, user=user)
    dialog = models.Dialog.objects.create(instance=instance)

    async def fake_answer(self, messages, debug_info, do_interrupt):
        return SingleAnswer(text="task answer", usage=[{"model": "test"}])

    monkeypatch.setattr(AssistantBot, "get_answer_to_messages", fake_answer)
    return bot, instance, dialog


def _update_dict(message_id=1, text="hello"):
    return Update(
        chat_id="u1", message_id=message_id, text=text, user=User(id="u1")
    ).to_dict()


def test_answer_task_end_to_end(seeded):
    bot, instance, dialog = seeded
    from django_assistant_bot_tpu.bot.services.dialog_service import create_user_message

    create_user_message(dialog, 1, "hello")
    platform = RecordingPlatform()
    asyncio.run(_answer_task("tb", dialog.id, "telegram", _update_dict(), platform=platform))
    assert platform.posted and platform.posted[0][1].text == "task answer"
    # bot message persisted with cost rollup
    msgs = models.Message.objects.filter(dialog=dialog).order_by("id").all()
    assert msgs[-1].text == "task answer"


def test_answer_task_marks_unavailable_on_forbidden(seeded):
    bot, instance, dialog = seeded
    from django_assistant_bot_tpu.bot.services.dialog_service import create_user_message

    create_user_message(dialog, 1, "hello")
    platform = RecordingPlatform(fail_with=UserUnavailableError("u1"))
    asyncio.run(_answer_task("tb", dialog.id, "telegram", _update_dict(), platform=platform))
    assert models.Instance.objects.get(id=instance.id).is_unavailable


def test_send_answer_task_skips_unavailable(seeded):
    bot, instance, dialog = seeded
    instance.is_unavailable = True
    instance.save()
    platform = RecordingPlatform()
    asyncio.run(
        _send_answer_task(
            "tb", "telegram", "u1", SingleAnswer(text="bcast").to_dict(), platform=platform
        )
    )
    assert platform.posted == []


def test_send_answer_task_delivers(seeded):
    platform = RecordingPlatform()
    asyncio.run(
        _send_answer_task(
            "tb", "telegram", "u1", SingleAnswer(text="bcast").to_dict(), platform=platform
        )
    )
    assert platform.posted[0][1].text == "bcast"


# ----------------------------------------------------------- telegram adapter
def test_convert_message_update():
    platform = TelegramBotPlatform("tok", api=FakeAPI())
    data = {
        "message": {
            "message_id": 7,
            "chat": {"id": 123},
            "text": "hi there",
            "from": {"id": 42, "username": "alice", "first_name": "A", "language_code": "en"},
        }
    }
    upd = asyncio.run(platform.get_update(data))
    assert upd.chat_id == "123" and upd.message_id == 7 and upd.text == "hi there"
    assert upd.user.username == "alice"


def test_convert_callback_and_photo_updates():
    platform = TelegramBotPlatform("tok", api=FakeAPI())
    cb = {
        "callback_query": {
            "id": "cb1",
            "from": {"id": 42, "username": "alice"},
            "message": {"message_id": 9},
            "data": "/continue",
        }
    }
    upd = asyncio.run(platform.get_update(cb))
    assert upd.text == "/continue" and upd.message_id == 9

    photo = {
        "message": {
            "message_id": 10,
            "chat": {"id": 1},
            "from": {"id": 42},
            "photo": [{"file_id": "small"}, {"file_id": "big", "file_unique_id": "bu"}],
            "caption": "see this",
        }
    }
    upd = asyncio.run(platform.get_update(photo))
    assert upd.photo.content == b"JPEGDATA"
    assert upd.photo.extension == "jpg"
    assert upd.text == "see this"


def test_markdown_fallback_on_parse_error():
    api = FakeAPI(errors=[TelegramBadRequest(400, "Bad Request: can't parse entities")])
    platform = TelegramBotPlatform("tok", api=api)
    asyncio.run(platform.post_answer("1", SingleAnswer(text="broken *md")))
    # first MarkdownV2 attempt failed, second plain attempt went through
    assert len(api.calls) == 2
    assert api.calls[0][3] == "MarkdownV2" and api.calls[1][3] is None
    assert api.calls[1][2] == "broken *md"


def test_forbidden_raises_user_unavailable():
    api = FakeAPI(errors=[TelegramForbidden(403, "Forbidden: bot was blocked by the user")])
    platform = TelegramBotPlatform("tok", api=api)
    with pytest.raises(UserUnavailableError):
        asyncio.run(platform.post_answer("1", SingleAnswer(text="x")))


def test_forbidden_kicked_does_not_raise():
    api = FakeAPI(errors=[TelegramForbidden(403, "Forbidden: bot was kicked from the group chat")])
    platform = TelegramBotPlatform("tok", api=api)
    asyncio.run(platform.post_answer("1", SingleAnswer(text="x")))  # no raise


def test_inline_keyboard_markup():
    api = FakeAPI()
    platform = TelegramBotPlatform("tok", api=api)
    answer = SingleAnswer(text="pick", buttons=[[Button("Go", callback_data="/go")]])
    asyncio.run(platform.post_answer("1", answer))
    markup = api.calls[0][4]
    assert markup == {"inline_keyboard": [[{"text": "Go", "callback_data": "/go"}]]}
