"""Admission-controlled request scheduler (serving/scheduler.py).

Policy units run against stub requests (no device); integration tests drive a
tiny CPU engine and the HTTP server: priority ordering, weighted fair share,
deadline expiry freeing a live decode slot, shed-threshold/429 mapping with
``Retry-After``, request validation (422), and /healthz queue stats.
"""

import asyncio
import dataclasses
import math
import time
from concurrent.futures import Future
from typing import Optional

import pytest

import jax

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.serving import (
    ByteTokenizer,
    DeadlineExceeded,
    GenerationEngine,
    ModelRegistry,
    RequestScheduler,
    SchedulerConfig,
    SchedulerRejected,
)
from django_assistant_bot_tpu.serving.server import create_app


@dataclasses.dataclass
class StubRequest:
    future: Future
    submitted_at: float
    priority: str = "interactive"
    tenant: str = "default"
    deadline_at: Optional[float] = None
    admitted: bool = False


def _stub(priority="interactive", tenant="default", deadline_at=None, admitted=False):
    """Direct-enqueue stub: admitted=False (depth counted at enqueue), matching
    requests that bypass try_admit."""
    return StubRequest(
        future=Future(),
        submitted_at=time.monotonic(),
        priority=priority,
        tenant=tenant,
        deadline_at=deadline_at,
        admitted=admitted,
    )


def _admit_and_enqueue(s, priority="interactive", tenant="default"):
    adm = s.try_admit(priority)
    assert adm.ok
    req = _stub(priority, tenant, admitted=True)
    s.enqueue(req)
    return req


# --------------------------------------------------------------- policy units
def test_priority_classes_share_by_weight():
    """interactive:background at 8:1 — under contention interactive takes ~8
    of every 9 pops, and background is never starved outright."""
    s = RequestScheduler(
        SchedulerConfig(class_weights={"interactive": 8, "background": 1})
    )
    for _ in range(18):
        _admit_and_enqueue(s, "background")
    for _ in range(18):
        _admit_and_enqueue(s, "interactive")
    order = [s.pop().priority for _ in range(18)]
    # the first 18 pops drain ~16 interactive vs ~2 background
    assert order.count("interactive") >= 16
    assert order.count("background") >= 1  # weighted share, not strict priority
    # everything eventually drains
    rest = [s.pop() for _ in range(18)]
    assert all(r is not None for r in rest)
    assert s.pop() is None


def test_tenant_weighted_fair_share_interleaves():
    """One chatty tenant cannot monopolize: with equal weights, pops alternate
    a:b:... regardless of arrival order; a 3x-weighted tenant gets ~3x slots."""
    s = RequestScheduler(SchedulerConfig(class_weights={"background": 1}))
    for _ in range(8):
        _admit_and_enqueue(s, "background", "a")
    for _ in range(8):
        _admit_and_enqueue(s, "background", "b")
    first_six = [s.pop().tenant for _ in range(6)]
    assert first_six.count("a") == 3 and first_six.count("b") == 3

    s = RequestScheduler(
        SchedulerConfig(
            class_weights={"background": 1}, tenant_weights={"big": 3.0, "small": 1.0}
        )
    )
    for _ in range(12):
        _admit_and_enqueue(s, "background", "big")
    for _ in range(12):
        _admit_and_enqueue(s, "background", "small")
    first_eight = [s.pop().tenant for _ in range(8)]
    assert first_eight.count("big") == 6 and first_eight.count("small") == 2


def test_queue_bound_sheds_with_retry_after():
    s = RequestScheduler(SchedulerConfig(max_queue=2, admit_max_wait_s=None))
    assert s.try_admit("background").ok
    assert s.try_admit("background").ok
    adm = s.try_admit("background")
    assert not adm.ok
    assert adm.reason == "queue_full"
    assert adm.retry_after_s > 0
    assert s.stats()["shed"] == {"queue_full": 1}
    # raising form carries the hint the server maps to Retry-After
    err = SchedulerRejected(adm.reason, adm.retry_after_s)
    assert err.retry_after_s == adm.retry_after_s


def test_estimated_wait_admission_test():
    s = RequestScheduler(
        SchedulerConfig(max_queue=100, admit_max_wait_s=1.0, service_time_init=2.0),
        slots=1,
    )
    _admit_and_enqueue(s, "interactive")  # empty queue: est wait 0, admitted
    # depth 1 * 2s EMA / 1 slot = 2s estimated wait > 1s ceiling
    adm = s.try_admit("interactive")
    assert not adm.ok and adm.reason == "estimated_wait"
    # an infeasible deadline sheds immediately rather than expiring later
    s.cfg.admit_max_wait_s = None
    adm = s.try_admit("interactive", deadline_s=0.5)
    assert not adm.ok and adm.reason == "deadline_infeasible"
    # service-time EMA folds real finishes in and un-sheds
    for _ in range(60):
        s.note_service(0.001)
    assert s.try_admit("interactive", deadline_s=0.5).ok


def test_per_token_service_model_drives_estimated_wait():
    """note_service with tokens engages the per-token model (rate EMA x
    tokens-per-request EMA) — fused N-step ticks deliver residency in
    tick-quantized quanta, and normalizing by the steps the slot actually
    sat through keeps predicted queue waits honest (docs/SCHEDULING.md)."""
    s = RequestScheduler(
        SchedulerConfig(max_queue=100, service_time_init=2.0), slots=1
    )
    # legacy calls keep the raw per-request EMA behavior byte-for-byte
    s.note_service(1.0)
    st = s.stats()
    assert st["service_per_token_ema_ms"] is None
    assert st["service_model_s"] == st["service_ema_s"]
    # token-fed calls: first sample seeds rate=0.1 s/tok, tokens=10
    s.note_service(1.0, tokens=10)
    st = s.stats()
    assert st["service_per_token_ema_ms"] == pytest.approx(100.0)
    assert st["service_tokens_ema"] == pytest.approx(10.0)
    assert st["service_model_s"] == pytest.approx(1.0)
    # the est-wait model consumes the per-token product, not the raw EMA:
    # depth 1 * model / 1 slot
    _admit_and_enqueue(s, "interactive")
    assert s.est_wait_s() == pytest.approx(st["service_model_s"], rel=1e-6)
    # a short request padded to a full fused tick (0.8 s residency for 8
    # charged steps) keeps the same per-token rate — the model stays ~1 s
    # while the raw per-request EMA is dragged toward the padded residency
    for _ in range(50):
        s.note_service(0.8, tokens=8)
    st = s.stats()
    assert st["service_per_token_ema_ms"] == pytest.approx(100.0, rel=0.02)
    assert st["service_model_s"] == pytest.approx(0.8, rel=0.05)


def test_deadline_expiry_reaped_at_queue_head():
    s = RequestScheduler(SchedulerConfig())
    dead = _stub(deadline_at=time.monotonic() - 0.01)
    live = _stub()
    s.enqueue(dead)
    s.enqueue(live)
    assert s.pop() is live
    with pytest.raises(DeadlineExceeded):
        dead.future.result(timeout=1)
    assert s.stats()["expired_queued"] == {"interactive": 1}
    assert s.queue_depth == 0


def test_reap_drops_dead_entries_mid_queue():
    """reap() (called every engine-loop iteration) fails expired/cancelled
    entries ANYWHERE in the queues — not only at the fair-share head when a
    slot frees — and releases their depth."""
    s = RequestScheduler(SchedulerConfig())
    live_a = _stub()
    dead = _stub(deadline_at=time.monotonic() - 0.01)
    gone = _stub()
    gone.future.cancel()
    live_b = _stub()
    for r in (live_a, dead, gone, live_b):  # dead entries sit BEHIND a live head
        s.enqueue(r)
    assert s.reap() == 2
    with pytest.raises(DeadlineExceeded):
        dead.future.result(timeout=1)
    assert s.queue_depth == 2
    assert s.pop() is live_a and s.pop() is live_b  # order preserved


def test_cancelled_entry_reaped_without_charge():
    s = RequestScheduler(SchedulerConfig())
    gone = _stub()
    gone.future.cancel()
    live = _stub()
    s.enqueue(gone)
    s.enqueue(live)
    assert s.pop() is live
    assert s.stats()["cancelled_queued"] == {"interactive": 1}


def test_degradation_band_clamps_max_tokens():
    s = RequestScheduler(
        SchedulerConfig(max_queue=4, degrade_at=0.5, degrade_max_tokens=16)
    )
    assert s.try_admit("background").clamp_max_tokens is None
    assert not s.degraded()
    adm = s.try_admit("background")  # depth hits 2 = 0.5 * 4
    assert adm.ok and adm.clamp_max_tokens == 16
    assert s.degraded()


def test_direct_enqueue_counts_depth():
    """Requests bypassing try_admit (internal paths writing the engine queue
    directly) must still be depth-accounted."""
    s = RequestScheduler(SchedulerConfig())
    s.enqueue(_stub(admitted=False))
    assert s.queue_depth == 1
    s.pop()
    assert s.queue_depth == 0


def test_wait_stats_percentiles():
    s = RequestScheduler(SchedulerConfig())
    now = time.monotonic()
    for age_s in (0.010, 0.020, 0.100):
        r = _stub()
        r.submitted_at = now - age_s
        s.enqueue(r)
        s.pop(now)
    w = s.wait_stats()["interactive"]
    assert w["n"] == 3
    assert 5 <= w["p50_ms"] <= 50
    assert w["p95_ms"] >= w["p50_ms"]


# --------------------------------------------------------- engine integration
@pytest.fixture(scope="module")
def sched_engine():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    sched = RequestScheduler(SchedulerConfig(max_queue=64, admit_max_wait_s=None))
    eng = GenerationEngine(
        cfg,
        params,
        ByteTokenizer(),
        max_slots=1,
        max_seq_len=256,
        scheduler=sched,
    ).start()
    yield eng
    eng.stop()


def test_engine_interactive_overtakes_background_queue(sched_engine):
    """With one busy slot and a queued background backlog, interactive
    requests jump the queue: they complete before all but the already-running
    background work."""
    eng = sched_engine
    done: list = []

    def tag(name):
        return lambda fut: done.append(name)

    bg = []
    for i in range(5):
        f = eng.submit(
            [1, 2, 3, i + 1], max_tokens=12, temperature=0.0,
            priority="background", tenant="ingest",
        )
        f.add_done_callback(tag(f"bg{i}"))
        bg.append(f)
    ia = []
    for i in range(2):
        f = eng.submit(
            [7, 8, 9, i + 1], max_tokens=6, temperature=0.0,
            priority="interactive", tenant="dialog",
        )
        f.add_done_callback(tag(f"int{i}"))
        ia.append(f)
    for f in bg + ia:
        f.result(timeout=120)
    # both interactive requests finish before the final two background ones
    # (only already-started bg work may precede them)
    assert max(done.index("int0"), done.index("int1")) < min(
        done.index("bg3"), done.index("bg4")
    )


def test_engine_deadline_frees_live_slot_mid_decode(sched_engine):
    """An expired deadline fails the future with DeadlineExceeded AND frees
    the slot promptly (within ~a decode tick) — the request stops burning
    decode work and the next request proceeds."""
    eng = sched_engine
    # warm: full greedy decode duration bounds the deadline we pick
    t0 = time.monotonic()
    eng.submit([1, 2, 3], max_tokens=200, temperature=0.0).result(timeout=120)
    warm_s = time.monotonic() - t0
    before = eng.reclaimed_slots
    fut = eng.submit(
        [1, 2, 3], max_tokens=200, temperature=0.0, deadline_s=max(0.02, warm_s / 4)
    )
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=120)
    deadline = time.monotonic() + 10
    while eng.num_active > 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert eng.num_active == 0
    assert eng.reclaimed_slots == before + 1
    # engine still healthy
    r = eng.submit([4, 5], max_tokens=3, temperature=0.0).result(timeout=120)
    assert len(r.token_ids) == 3
    stats = eng.tick_stats()
    assert stats["reclaimed_slots"] == before + 1
    assert stats["sched"]["expired_running"].get("interactive", 0) >= 1


def test_engine_queued_deadline_expires_while_slots_saturated(sched_engine):
    """A QUEUED request's deadline fires at ~the deadline even though every
    slot is busy — the engine reaps queue entries each loop iteration instead
    of waiting for a free slot to surface them."""
    eng = sched_engine
    # shrink the service-time EMA so the deadline passes the admission
    # feasibility test (the point here is queue-side expiry, not admission)
    for _ in range(100):
        eng.scheduler.note_service(0.001)
    # a warm jit cache can finish the 220-token blocker inside the 50ms
    # deadline, racing the expiry this test exists to observe — injected
    # per-tick latency (serving/faults.py slow_tick) pins the blocker's
    # residency deterministically past the queued request's deadline
    from django_assistant_bot_tpu.serving.faults import FaultInjector

    eng._faults = FaultInjector({"slow_tick": {"every": 1, "delay_s": 0.01}})
    try:
        blocker = eng.submit([1, 2, 3], max_tokens=220, temperature=0.0)
        queued = eng.submit(
            [4, 5, 6], max_tokens=10, temperature=0.0, deadline_s=0.05
        )
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            queued.result(timeout=30)
        # failed promptly (well before the blocker's full decode), not on dequeue
        assert time.monotonic() - t0 < 5.0
        blocker.result(timeout=120)  # the running request is unaffected
    finally:
        eng._faults = None


def test_engine_submit_sheds_past_bound():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(1))
    sched = RequestScheduler(SchedulerConfig(max_queue=1, admit_max_wait_s=None))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=1, max_seq_len=96, scheduler=sched
    )
    # NOT started: everything submitted stays queued, so the bound is exact
    try:
        eng._running = True  # let submit() enqueue without an engine thread
        eng.submit([1, 2], max_tokens=4)
        with pytest.raises(SchedulerRejected) as ei:
            eng.submit([1, 2], max_tokens=4)
        assert ei.value.retry_after_s > 0
    finally:
        eng._running = False
        eng.stop()


# ----------------------------------------------------------- HTTP integration
@pytest.fixture(scope="module")
def sched_registry():
    registry = ModelRegistry.from_config(
        {
            "sched-chat": {
                "kind": "decoder",
                "tiny": True,
                "dtype": "float32",
                "max_slots": 1,
                "max_seq_len": 128,
                "sched_max_queue": 1,
                "sched_admit_max_wait_s": None,
            },
            "tiny-emb": {"kind": "encoder", "tiny": True, "dtype": "float32"},
        }
    )
    yield registry
    registry.stop()


def _drive(registry, fn):
    async def runner():
        from aiohttp.test_utils import TestClient, TestServer

        app = create_app(registry)
        # the module fixture owns the registry; closing one test's client
        # must not stop the shared engines (create_app's on_cleanup would)
        app.on_cleanup.clear()
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()

    return asyncio.run(runner())


def test_dialog_validation_422(sched_registry):
    async def body(client):
        base = {"model": "sched-chat", "messages": [{"role": "user", "content": "x"}]}
        bad = [
            {"temperature": math.nan},
            {"temperature": -0.5},
            {"temperature": 99.0},
            {"temperature": "hot"},
            {"top_p": 0.0},
            {"top_p": 2.0},
            {"top_p": math.inf},
            {"max_tokens": 0},
            {"max_tokens": -5},
            {"max_tokens": 1 << 20},
            {"max_tokens": 3.7},
            {"max_tokens": True},
            {"priority": "vip"},
            {"tenant": ""},
            {"tenant": "x" * 200},
            {"deadline_s": -1},
            {"deadline_s": math.nan},
            {"deadline_s": 7200},
        ]
        for extra in bad:
            resp = await client.post("/dialog/", json={**base, **extra})
            assert resp.status == 422, (extra, await resp.text())
        # valid edge values still pass
        resp = await client.post(
            "/dialog/",
            json={
                **base,
                "temperature": 0.0,
                "top_p": 1.0,
                "max_tokens": 2,
                "priority": "background",
                "tenant": "ws1",
                "deadline_s": 30,
            },
        )
        assert resp.status == 200, await resp.text()

    _drive(sched_registry, body)


def test_dialog_shed_maps_to_429_with_retry_after_and_healthz(sched_registry):
    """Overload: 1 slot + queue bound 1 -> concurrent burst sheds with 429 +
    Retry-After; /healthz exposes depth/shed counters and per-class waits."""

    async def body(client):
        async def one(i):
            return await client.post(
                "/dialog/",
                json={
                    "model": "sched-chat",
                    "messages": [{"role": "user", "content": f"q{i}"}],
                    "max_tokens": 64,
                    "priority": "background",
                },
            )
        resps = await asyncio.gather(*(one(i) for i in range(10)))
        statuses = [r.status for r in resps]
        assert statuses.count(200) >= 1
        shed = [r for r in resps if r.status == 429]
        assert shed, statuses
        for r in shed:
            assert int(r.headers["Retry-After"]) >= 1
            data = await r.json()
            assert data["retry_after_s"] > 0 and data["reason"]
        health = await (await client.get("/healthz")).json()
        g = health["generators"]["sched-chat"]
        sched = g["sched"]
        assert sched["max_queue"] == 1
        assert sum(sched["shed"].values()) >= len(shed)
        assert "queue_depth" in sched and "wait" in sched
        assert any(w["n"] > 0 for w in sched["wait"].values())
        assert "reclaimed_slots" in g
        emb = health["embedders"]["tiny-emb"]
        assert {"queue_depth", "max_queue", "shed", "dropped_cancelled"} <= set(emb)

    _drive(sched_registry, body)


# ------------------------------------------------------- embedding coalescer
def test_embedding_queue_bound_sheds():
    from django_assistant_bot_tpu.models import EncoderConfig, encoder
    from django_assistant_bot_tpu.serving import EmbeddingEngine

    cfg = EncoderConfig.tiny()
    params = encoder.init(cfg, jax.random.key(0))
    eng = EmbeddingEngine(cfg, params, ByteTokenizer(), max_queue=1)
    eng._running = True  # no coalescer thread: the queue must fill
    try:
        async def drive():
            t1 = asyncio.ensure_future(eng.embed(["a"]))
            await asyncio.sleep(0.01)
            with pytest.raises(SchedulerRejected):
                await eng.embed(["b"])
            t1.cancel()
            return True

        assert asyncio.run(drive())
        assert eng.shed == 1
    finally:
        eng._running = False
        eng.stop()


def test_embedding_coalescer_drops_cancelled_futures():
    from django_assistant_bot_tpu.models import EncoderConfig, encoder
    from django_assistant_bot_tpu.serving import EmbeddingEngine

    cfg = EncoderConfig.tiny()
    params = encoder.init(cfg, jax.random.key(0))
    eng = EmbeddingEngine(cfg, params, ByteTokenizer())
    cancelled: Future = Future()
    cancelled.cancel()
    live: Future = Future()
    eng._queue.put((["dead text"], cancelled))
    eng._queue.put((["live text"], live))
    eng.start()
    try:
        embs = live.result(timeout=60)
        assert len(embs) == 1 and len(embs[0]) == cfg.hidden_size
        assert eng.dropped_cancelled == 1
    finally:
        eng.stop()


# ------------------------- predictive admission (queue-wait histogram, PR 11)
def test_histogram_quantile_interpolates_and_caps():
    from django_assistant_bot_tpu.serving import Histogram

    h = Histogram((0.1, 1.0, 10.0))
    assert h.quantile(0.95) == 0.0  # empty = cold, callers gate on .count
    for _ in range(90):
        h.observe(0.05)  # le 0.1 bucket
    for _ in range(10):
        h.observe(5.0)  # (1.0, 10.0] bucket
    q50 = h.quantile(0.5)
    assert 0.0 < q50 <= 0.1
    q95 = h.quantile(0.95)
    assert 1.0 < q95 <= 10.0
    # +Inf bucket values report the largest finite bound (a deliberate
    # under-estimate: predictions must stay actionable)
    h2 = Histogram((0.1, 1.0))
    h2.observe(99.0)
    assert h2.quantile(0.99) == 1.0


def test_warm_wait_histogram_floors_estimated_wait_and_retry():
    """The point-EMA model underestimates the tail; once the bound queue-wait
    histogram is warm, the estimated wait (and the 429 Retry-After derived
    from it) is floored by the configured quantile of realized waits."""
    from django_assistant_bot_tpu.serving import Histogram

    s = RequestScheduler(
        SchedulerConfig(
            max_queue=100,
            admit_max_wait_s=5.0,
            service_time_init=0.01,  # the EMA model predicts ~nothing
            admit_wait_quantile=0.95,
            admit_hist_min_samples=8,
        ),
        slots=1,
    )
    h = Histogram((0.1, 1.0, 10.0, 30.0))
    s.bind_wait_hist(h)
    # cold histogram: the EMA model alone drives the estimate
    _admit_and_enqueue(s)
    assert s.stats()["est_wait_source"] == "ema"
    assert s.est_wait_s() < 0.1
    # warm it with a heavy observed tail (queue waits ~8s)
    for _ in range(16):
        h.observe(8.0)
    st = s.stats()
    assert st["est_wait_source"] == "histogram"
    assert s.est_wait_s() > 1.0  # the measured tail floors the model
    # and the shed decision + Retry-After hint follow the SAME prediction:
    # est > admit_max_wait_s -> shed, with retry ~= the predicted wait
    adm = s.try_admit("interactive")
    assert not adm.ok and adm.reason == "estimated_wait"
    assert adm.retry_after_s == pytest.approx(s.est_wait_s(), rel=0.35)
    # empty queue: nothing ahead of the request, no histogram floor applies
    s2 = RequestScheduler(
        SchedulerConfig(admit_hist_min_samples=8), slots=1
    )
    s2.bind_wait_hist(h)
    assert s2.est_wait_s() == 0.0


def test_engine_binds_queue_wait_histogram_into_scheduler():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    sched = RequestScheduler(SchedulerConfig(admit_hist_min_samples=4))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
        scheduler=sched,
    )
    assert sched._wait_hist is eng.obs.queue_wait_s
    # obs=False: no histogram exists, the EMA path stays
    sched2 = RequestScheduler(SchedulerConfig())
    GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
        scheduler=sched2, obs=False,
    )
    assert sched2._wait_hist is None


def test_degrade_override_clamps_and_reports():
    """The autoscaler's load-shaping actuator: set_degrade forces the band on
    (max_tokens clamp at admission + degraded() True, which the engine reads
    as 'skip speculative verify forwards') independent of queue pressure."""
    s = RequestScheduler(SchedulerConfig(max_queue=100, degrade_at=1.0))
    assert not s.degraded()
    s.set_degrade(64)
    assert s.degraded()
    adm = s.try_admit("interactive")
    assert adm.ok and adm.clamp_max_tokens == 64
    st = s.stats()
    assert st["degraded"] is True and st["degrade_forced"] is True
    s.set_degrade(None)
    assert not s.degraded()
    assert s.try_admit("interactive").clamp_max_tokens is None
    # the band clamp and the override compose: the tighter one wins
    s3 = RequestScheduler(
        SchedulerConfig(max_queue=4, degrade_at=0.25, degrade_max_tokens=128)
    )
    s3.set_degrade(32)
    _admit_and_enqueue(s3)
    adm = s3.try_admit("interactive")
    assert adm.clamp_max_tokens == 32


def test_wait_histogram_floor_is_windowed_not_lifetime():
    """A past overload's tail must roll OUT of the prediction: after two
    window rotations of fast traffic, the quantile floor tracks the recent
    regime, not the process lifetime (a stale ~8s Retry-After at light load
    was the bug)."""
    from django_assistant_bot_tpu.serving import Histogram

    window = 32
    s = RequestScheduler(
        SchedulerConfig(
            service_time_init=0.01,
            admit_wait_quantile=0.95,
            admit_hist_min_samples=8,
            admit_hist_window=window,
        ),
        slots=1,
    )
    h = Histogram((0.1, 1.0, 10.0, 30.0))
    s.bind_wait_hist(h)
    _admit_and_enqueue(s)  # depth > 0 so the floor applies
    for _ in range(16):
        h.observe(8.0)  # the overload period
    assert s.est_wait_s() > 1.0
    # two full windows of fast traffic rotate the slow tail out entirely
    # (rotation happens inside the admission-path checks, so interleave the
    # reads the way live admissions would)
    for _ in range(2 * window):
        h.observe(0.05)
        s.est_wait_s()
    assert s.est_wait_s() < 0.2
    assert s.stats()["est_wait_source"] == "histogram"  # still warm
