"""Durable warm KV state: host-tier spill/restore + the fleet prefix
registry (docs/KV_PAGING.md "Tiered KV").

Evidence layers, all CPU so tier-1 gates the tentpole without hardware:

- host-tier unit tests (LRU byte ledger, disk demotion/promotion with raw
  byte views so fp8 round-trips, absorb/migration budgets);
- allocator integration: spill-on-evict + registration write-through via a
  fake fetch, tier-transition events firing OUTSIDE the locks;
- a pinned-seed THREE-tier fuzz extending the allocator fuzz to the
  hbm/host/disk state machine (refcount + byte-ledger invariants across
  tiers, restore racing eviction, register racing the host budget);
- engine-level: restore-then-suffix-prefill is BIT-identical to a cold full
  prefill, COW against a restored page, crash-only restart re-seeding warm
  sessions from the host tier (chaos: tick_raise mid-trace), restore racing
  a replica kill (token-less re-route, goodput 1.0);
- fleet-level: scale-down migration moves warm state to a survivor
  (pages_lost_at_detach ~ 0 with migration on, > 0 and flight-recorded
  without it), the registry re-points affinity, and migration survives the
  replica dying mid-drain (the export is host numpy, not device state).
"""

import os
import random
import time

import numpy as np

import jax

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine
from django_assistant_bot_tpu.serving.faults import FaultInjector
from django_assistant_bot_tpu.serving.kv_pool import (
    HostKVTier,
    PageAllocator,
)
from django_assistant_bot_tpu.serving.router import EngineRouter


# ----------------------------------------------------------------- helpers
def _fake_kv(n_pages: int, fill: float = 0.0, *, layers=2, kh=1, page=16, d=4):
    shape = (layers, n_pages, kh, page, d)
    return (
        np.full(shape, fill, np.float32),
        np.full(shape, -fill, np.float32),
    )


def _fake_fetch(pages):
    """Stand-in for the engine's device->host page gather: content encodes
    the page ids so a restore's bytes are checkable."""
    k, v = _fake_kv(len(pages))
    for i, p in enumerate(pages):
        k[:, i] = float(p)
        v[:, i] = -float(p)
    return k, v


_shared_params = {}


def _tiny_engine(**kw):
    cfg = DecoderConfig.tiny()
    if "params" not in _shared_params:
        _shared_params["cfg"] = cfg
        _shared_params["params"] = llama.init(cfg, jax.random.key(7))
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 256)
    kw.setdefault("decode_kv_chunk", 64)
    kw.setdefault("prefix_cache_size", 4)
    kw.setdefault("prefix_min_tokens", 16)
    kw.setdefault("kv_layout", "paged")
    return GenerationEngine(
        _shared_params["cfg"], _shared_params["params"], ByteTokenizer(), **kw
    )


# ---------------------------------------------------------- host tier units
def test_host_tier_put_get_lru_budget():
    host = HostKVTier(1000, page_size=16)
    k, v = _fake_kv(1)  # 2*1*1*16*4*4 = 512 bytes each array
    assert k.nbytes == 512
    # one entry (1024 B) exceeds the 1000 B budget -> refused, counted
    assert not host.put((1,) * 10, 10, k, v)
    assert host.stats()["kv_tier_dropped"] == 1
    host = HostKVTier(4096, page_size=16)
    assert host.put((1,) * 10, 10, k, v)
    assert host.put((2,) * 10, 10, k, v)
    assert not host.put((1,) * 10, 10, k, v)  # duplicate: touch, not store
    s = host.stats()
    assert s["kv_host_entries"] == 2 and s["kv_host_bytes"] == 2048
    assert host.put((3,) * 10, 10, k, v)
    assert host.put((4,) * 10, 10, k, v)
    # budget 4096 holds 4 x 1024; a 5th evicts the LRU — that is (2,): the
    # duplicate put of (1,) LRU-touched it
    assert host.put((5,) * 10, 10, k, v)
    s = host.stats()
    assert s["kv_host_entries"] == 4
    assert s["kv_host_evictions"] == 1
    assert host.lookup([2] * 20, 10) is None  # (2,) was the eviction victim
    assert host.lookup([1] * 20, 10) is not None


def test_host_tier_longest_match_and_restore_count():
    host = HostKVTier(1 << 20, page_size=16)
    k, v = _fake_kv(1)
    host.put((1, 2, 3), 3, k, v)
    k2, v2 = _fake_kv(1)
    host.put((1, 2, 3, 4, 5), 5, k2, v2)
    hit = host.lookup([1, 2, 3, 4, 5, 6], 5)
    assert hit is not None and hit.length == 5
    hit = host.lookup([1, 2, 3, 9], 3)
    assert hit is not None and hit.length == 3
    # lookup is repeatable and side-effect-free (a queued head re-runs it
    # every admission attempt): only note_restored counts a SERVED restore
    assert host.stats()["kv_host_restores"] == 0
    host.note_restored((1, 2, 3))
    assert host.stats()["kv_host_restores"] == 1
    # a peek is LRU-neutral and counts nothing
    assert host.holds([1, 2, 3, 9], 3)
    assert host.stats()["kv_host_restores"] == 1


def test_host_tier_disk_demotion_promotes_bit_exact(tmp_path):
    """Host budget of one entry + a spill dir: the second entry demotes the
    first to disk; a later lookup promotes it back BIT-exact (raw byte
    views, so any pool dtype — incl. fp8 — survives the round trip)."""
    import jax.numpy as jnp

    # each fp8 entry is 2*(2*1*1*8*4) = 128 bytes; a 150-byte budget holds
    # exactly one, so the second put demotes the first to disk
    host = HostKVTier(150, page_size=8, spill_dir=str(tmp_path))

    def mk(val):
        return np.asarray(jnp.full((2, 1, 1, 8, 4), val, jnp.float8_e4m3fn))
    a_k, a_v = mk(1.5), mk(-1.5)
    host.put((1,) * 6, 6, a_k, a_v)
    host.put((2,) * 6, 6, mk(2.5), mk(-2.5))
    s = host.stats()
    assert s["kv_host_entries"] == 1 and s["kv_disk_entries"] == 1
    assert s["kv_disk_spills"] == 1
    assert any(f.startswith("kvspill-") for f in os.listdir(tmp_path))
    hit = host.lookup([1] * 10, 6)
    assert hit is not None and hit.length == 6
    np.testing.assert_array_equal(
        hit.k.view(np.uint8), a_k.view(np.uint8)
    )
    np.testing.assert_array_equal(
        hit.v.view(np.uint8), a_v.view(np.uint8)
    )
    assert host.stats()["kv_disk_promotes"] == 1


def test_host_tier_absorb_respects_budget_and_counts():
    src = HostKVTier(1 << 20, page_size=16)
    k, v = _fake_kv(1)
    for i in range(4):
        src.put((i,) * 8, 8, k, v)
    dst = HostKVTier(2 * 1024, page_size=16)  # room for 2 of the 4
    retained = dst.absorb(src.snapshot())
    assert sorted(retained) == [(2,) * 8, (3,) * 8]
    s = dst.stats()
    assert s["kv_host_entries"] == 2 and s["kv_migrated_in"] == 2
    # LRU-order import: the source's MRU entries (2,), (3,) survive the
    # target's budget; the oldest fall out
    assert dst.lookup([3] * 10, 8) is not None
    assert dst.lookup([2] * 10, 8) is not None
    assert dst.lookup([0] * 10, 8) is None


# ------------------------------------------------- allocator spill/events
def test_allocator_spills_on_evict_and_restores_content():
    host = HostKVTier(1 << 20, page_size=16)
    al = PageAllocator(
        8, 16, max_shared_entries=1, min_prefix_tokens=1,
        host_tier=host, writethrough=False,
    )
    al.bind_spill_fetch(_fake_fetch)
    p = al.alloc(2)
    assert al.register([7] * 20, 20, p)
    assert host.stats()["kv_host_entries"] == 0  # writethrough off
    al.decref(p)
    q = al.alloc(1)
    assert al.register([8] * 10, 10, q)  # entry bound 1 -> evicts [7]*20
    assert al.evictions == 1
    ent = host.lookup([7] * 30, 20)
    assert ent is not None and ent.length == 20
    # spilled content is the page-id-encoded bytes the fake fetch produced
    assert ent.k[0, 0, 0, 0, 0] == float(p[0])
    assert ent.k[0, 1, 0, 0, 0] == float(p[1])
    al.decref(q)


def test_allocator_writethrough_copies_at_registration():
    host = HostKVTier(1 << 20, page_size=16)
    al = PageAllocator(
        8, 16, max_shared_entries=4, min_prefix_tokens=1, host_tier=host
    )
    al.bind_spill_fetch(_fake_fetch)
    p = al.alloc(2)
    assert al.register([3] * 20, 20, p)
    assert host.stats()["kv_host_entries"] == 1  # copied down immediately
    # reset() (crash-only restart) keeps the host copy and says so
    events = []
    al.on_event = lambda ev, key, length, pages: events.append(ev)
    al.reset()
    assert "evict_spilled" in events
    assert host.lookup([3] * 30, 20) is not None


def test_allocator_tier_events_fire_outside_locks():
    """Listener re-enters the allocator/tier stats paths — deadlock-free
    only because events fire after the locks release."""
    host = HostKVTier(1 << 20, page_size=16)
    al = PageAllocator(
        8, 16, max_shared_entries=1, min_prefix_tokens=1, host_tier=host
    )
    al.bind_spill_fetch(_fake_fetch)
    seen = []

    def listener(ev, key, length, pages):
        # taking the same component's lock again would deadlock if the
        # event fired under it
        al.stats()
        host.stats()
        seen.append((ev, length, pages))

    al.on_event = listener
    host.on_event = listener
    p = al.alloc(1)
    al.register([1] * 10, 10, p)
    al.decref(p)
    q = al.alloc(1)
    al.register([2] * 10, 10, q)
    al.decref(q)
    evs = [e for e, _, _ in seen]
    assert "register" in evs and "host_put" in evs and "evict_spilled" in evs


# --------------------------------------------------------- three-tier fuzz
def test_allocator_three_tier_fuzz_invariants(tmp_path):
    """Pinned-seed fuzz over the THREE-tier state machine: random
    alloc/decref/register/evict/host-lookup/disk traffic must keep (a) the
    device invariants the two-tier fuzz checks, (b) the host byte ledger
    exact and within budget, and (c) restores serving entries whose bytes
    match what was spilled.  Covers restore racing eviction (a lookup's
    winner can be evicted by the very next register) by construction.
    Seed pinned in CI via DABT_KV_FUZZ_SEED."""
    seed = int(os.environ.get("DABT_KV_FUZZ_SEED", "0"))
    rng = random.Random(f"tier:{seed}")
    host = HostKVTier(
        6 * 1024, page_size=16, spill_dir=str(tmp_path), max_disk_bytes=16 * 1024
    )
    al = PageAllocator(
        32, 16, page_bytes=7, max_shared_bytes=70, max_shared_entries=4,
        min_prefix_tokens=1, host_tier=host, writethrough=True,
    )
    al.bind_spill_fetch(_fake_fetch)
    held = []
    for _step in range(1500):
        op = rng.random()
        if op < 0.35:
            n = rng.randint(1, 6)
            got = al.alloc(n)
            if got is None:
                assert al.pages_free < n
            else:
                held.append(got)
        elif op < 0.6 and held:
            al.decref(held.pop(rng.randrange(len(held))))
        elif op < 0.8 and held:
            pages = held[rng.randrange(len(held))]
            toks = rng.randrange(64)
            length = len(pages) * al.page_size - rng.randint(0, al.page_size - 1)
            al.register([toks] * length, length, pages)
        else:
            # host-tier lookup: the restore side racing the eviction side
            toks = rng.randrange(64)
            ent = host.lookup([toks] * rng.randint(1, 80), rng.randint(1, 40))
            if ent is not None:
                # the spilled bytes encode their source page ids: every
                # page's K slab must be constant and equal to -V
                assert ent.k.shape[1] == ent.pages
                np.testing.assert_array_equal(ent.k, -ent.v)
        # ---- device invariants (the original fuzz's contract) ----------
        free = al.pages_free
        with al._lock:
            refd = set(al._refs)
            free_set = set(al._free)
        assert not (refd & free_set)
        assert len(free_set) == free
        assert len(refd) + free == al.n_pages
        for pages in held:
            for p in pages:
                assert p in refd
        # ---- host/disk ledger invariants -------------------------------
        with host._lock:
            assert host._bytes == sum(e.nbytes for e in host._entries.values())
            assert host._bytes <= host.max_bytes
            assert host._disk_bytes == sum(
                nb for (_, _, nb, _) in host._disk.values()
            )
            assert host._disk_bytes <= host.max_disk_bytes
            assert not (set(host._entries) & set(host._disk))
    for pages in held:
        al.decref(pages)


# ------------------------------------------------------ engine-level tests
def test_restore_then_suffix_prefill_bit_identical_to_cold():
    """Warm a prefix, evict it to the host tier (registry bound 1), then hit
    it again: the restore path's tokens must equal a host-tier-off engine's
    (which re-prefills cold) — restore-then-suffix-prefill is bit-identical
    to a cold full prefill."""
    rng = np.random.default_rng(21)
    pref1 = rng.integers(1, 255, 100).tolist()
    pref2 = rng.integers(1, 255, 100).tolist()
    turns = [
        (pref1 + rng.integers(1, 255, 30).tolist(), len(pref1)),
        (pref2 + rng.integers(1, 255, 30).tolist(), len(pref2)),
        (pref1 + rng.integers(1, 255, 40).tolist(), len(pref1)),
    ]

    def run(host_bytes):
        eng = _tiny_engine(
            prefix_cache_size=1, kv_host_bytes=host_bytes
        ).start()
        try:
            outs = [
                eng.submit(
                    t, max_tokens=8, temperature=0.0, prefix_len=pl
                ).result(timeout=300).token_ids
                for t, pl in turns
            ]
            return outs, eng.kv_stats()
        finally:
            eng.stop()

    ref, _ = run(0)
    got, st = run(1 << 26)
    assert got == ref
    assert st["kv_restores"] >= 1
    assert st["kv_host_hits"] >= 1
    assert st["kv_restores_inflight"] == 0
    assert st["kv_restore_p95_ms"] > 0


def test_cow_against_restored_page():
    """A restored prefix is re-registered: the NEXT sharer COW-clones its
    boundary page like any registry hit, and both outputs match the
    host-tier-off reference."""
    rng = np.random.default_rng(22)
    pref1 = rng.integers(1, 255, 90).tolist()  # 90 tokens: 1 full + 1 partial page
    pref2 = rng.integers(1, 255, 90).tolist()
    seq = [
        (pref1 + rng.integers(1, 255, 20).tolist(), len(pref1)),
        (pref2 + rng.integers(1, 255, 20).tolist(), len(pref2)),  # evicts pref1
        (pref1 + rng.integers(1, 255, 25).tolist(), len(pref1)),  # restore
        (pref1 + rng.integers(1, 255, 30).tolist(), len(pref1)),  # COW vs restored
    ]

    def run(host_bytes):
        eng = _tiny_engine(
            prefix_cache_size=1, kv_host_bytes=host_bytes
        ).start()
        try:
            outs = [
                eng.submit(
                    t, max_tokens=8, temperature=0.0, prefix_len=pl
                ).result(timeout=300).token_ids
                for t, pl in seq
            ]
            return outs, eng.kv_stats()
        finally:
            eng.stop()

    ref, _ = run(0)
    got, st = run(1 << 26)
    assert got == ref
    assert st["kv_restores"] >= 1
    # the 4th turn hit the RE-REGISTERED restored entry in HBM and cloned
    # its boundary page
    assert st["kv_cow_copies"] >= 1


def test_restore_when_pool_cannot_place_falls_back_cleanly():
    """Host hit whose page demand cannot be allocated: admission falls back
    (request completes as a full prefill or waits for pages) — no wedge, no
    wrong output.  Restore racing eviction, engine edition."""
    rng = np.random.default_rng(23)
    prefix = rng.integers(1, 255, 150).tolist()  # 3 pages of 64
    p_a = prefix + rng.integers(1, 255, 20).tolist()
    p_b = rng.integers(1, 255, 200).tolist()  # unrelated, hogs pages

    def run(host_bytes):
        eng = _tiny_engine(
            max_slots=2, prefix_cache_size=1, kv_pages=6,
            kv_host_bytes=host_bytes,
        ).start()
        try:
            outs = []
            outs.append(
                eng.submit(
                    p_a, max_tokens=8, temperature=0.0, prefix_len=len(prefix)
                ).result(timeout=300).token_ids
            )
            outs.append(
                eng.submit(p_b, max_tokens=8, temperature=0.0)
                .result(timeout=300).token_ids
            )
            outs.append(
                eng.submit(
                    p_a, max_tokens=8, temperature=0.0, prefix_len=len(prefix)
                ).result(timeout=300).token_ids
            )
            return outs
        finally:
            eng.stop()

    assert run(1 << 26) == run(0)


def test_crash_restart_preserves_warm_state_via_host_tier():
    """The durability acceptance shape: tick_raise mid-trace forces a
    crash-only restart (allocator reset, HBM registry gone) — but the host
    tier survives, the next prefix hit RESTORES instead of re-prefilling,
    and every future completes (goodput 1.0)."""
    inj = FaultInjector({})
    eng = _tiny_engine(
        faults=inj, prefix_cache_size=4, kv_host_bytes=1 << 26
    ).start()
    rng = np.random.default_rng(24)
    prefix = rng.integers(1, 255, 100).tolist()
    try:
        eng.submit(
            prefix + rng.integers(1, 255, 20).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        assert eng.kv_stats()["kv_host_entries"] == 1  # write-through
        inj.arm("tick_raise")
        futs = [
            eng.submit(
                prefix + rng.integers(1, 255, 20 + i).tolist(),
                max_tokens=4, temperature=0.0, prefix_len=len(prefix),
            )
            for i in range(3)
        ]
        results = [f.result(timeout=300) for f in futs]
        assert all(len(r.token_ids) == 4 for r in results)  # goodput 1.0
        assert eng.engine_restarts == 1
        st = eng.kv_stats()
        # the restart dropped HBM but not the host tier; post-restart
        # traffic restored (not re-prefilled) the warm prefix
        assert st["kv_host_entries"] >= 1
        assert st["kv_restores"] >= 1
        assert eng.supervision_stats()["healthy"] is True
        if eng.obs is not None:
            evs = [e["event"] for e in eng.obs.flight.events()]
            assert "kv_tier_survives_restart" in evs
            assert "kv_tier" in evs
    finally:
        eng.stop()


def test_scheduler_stats_carry_kv_tier_block():
    """bind_kv_tier (the bind_spec discipline): an engine with a host tier
    and a scheduler surfaces the tier's gauges inside scheduler.stats(), so
    pool pressure and warm-tier depth read side by side."""
    from django_assistant_bot_tpu.serving.scheduler import (
        RequestScheduler,
        SchedulerConfig,
    )

    sched = RequestScheduler(SchedulerConfig())
    eng = _tiny_engine(kv_host_bytes=1 << 26, scheduler=sched)
    st = sched.stats()
    assert "kv_tier" in st and st["kv_tier"]["kv_host_entries"] == 0
    plain = RequestScheduler(SchedulerConfig())
    eng2 = _tiny_engine(kv_host_bytes=0, scheduler=plain)
    assert "kv_tier" not in plain.stats()
    del eng, eng2


# ------------------------------------------------------------- fleet level
def _mk_fleet(n=2, host_bytes=1 << 26, **eng_kw):
    engines = [
        _tiny_engine(
            kv_host_bytes=host_bytes, name=f"r{i}", **eng_kw
        ).start()
        for i in range(n)
    ]
    return EngineRouter(engines, names=[f"r{i}" for i in range(n)])


def test_scale_down_migrates_warm_state_and_registry_repoints():
    router = _mk_fleet()
    rng = np.random.default_rng(31)
    prefix = rng.integers(1, 255, 100).tolist()
    try:
        router.submit(
            prefix + rng.integers(1, 255, 20).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        holders = router.prefix_registry.holders(prefix + [1], len(prefix))
        assert len(holders) == 1
        holder_name, tier = next(iter(holders.items()))
        assert tier == "hbm"
        idx = [rep.name for rep in router.replicas].index(holder_name)
        report = router.remove_replica(idx, deadline_s=10.0)
        assert report["migrated_entries"] == 1
        assert report["lost_pages"] == 0
        rs = router.router_stats()
        assert rs["pages_lost_at_detach"] == 0  # ~0 with migration on
        assert rs["entries_migrated"] == 1
        # the registry re-points at the survivor, at the host tier
        holders = router.prefix_registry.holders(prefix + [1], len(prefix))
        survivor = router.replicas[0].name
        assert holders == {survivor: "host"}
        # and the next hit restores on the survivor
        r = router.submit(
            prefix + rng.integers(1, 255, 30).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        assert len(r.token_ids) == 4
        surv = router.replicas[0].engine
        assert surv.kv_stats()["kv_restores"] >= 1
        assert surv.kv_stats()["kv_migrated_in"] == 1
    finally:
        router.stop()


def test_detach_without_host_tier_counts_lost_pages_and_flight_event():
    """The pre-migration satellite bugfix: a drain-then-detach that discards
    the replica's prefix registry must SAY so — pages_lost_at_detach counter
    + flight event — instead of silently wiping warm state."""
    router = _mk_fleet(host_bytes=0)  # tiering off: nothing to migrate into
    rng = np.random.default_rng(32)
    prefix = rng.integers(1, 255, 100).tolist()
    try:
        router.submit(
            prefix + rng.integers(1, 255, 20).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        holder = next(
            i
            for i, rep in enumerate(router.replicas)
            if rep.engine.kv_stats()["kv_shared_entries"] > 0
        )
        eng = router.replicas[holder].engine
        report = router.remove_replica(holder, deadline_s=10.0)
        assert report["lost_pages"] > 0
        assert report["lost_reason"]
        assert router.router_stats()["pages_lost_at_detach"] == report["lost_pages"]
        if eng.obs is not None:
            evs = [e["event"] for e in eng.obs.flight.events()]
            assert "pages_lost_at_detach" in evs
    finally:
        router.stop()


def test_detach_migrate_off_counts_each_prefix_once():
    """Union accounting: with write-through a warm prefix exists in BOTH the
    device registry and the host tier — a migrate=False detach must charge
    it once, not twice."""
    router = _mk_fleet()
    rng = np.random.default_rng(36)
    prefix = rng.integers(1, 255, 100).tolist()  # 2 pages of 64
    try:
        router.submit(
            prefix + rng.integers(1, 255, 20).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        holder = next(
            i
            for i, rep in enumerate(router.replicas)
            if rep.engine.kv_stats()["kv_shared_entries"] > 0
        )
        report = router.remove_replica(holder, deadline_s=10.0, migrate=False)
        assert report["lost_entries"] == 1
        assert report["lost_pages"] == 2  # NOT 4: hbm + host copies are one prefix
        assert report["lost_reason"] == "migration disabled"
    finally:
        router.stop()


def test_detach_with_dead_device_and_no_writethrough_counts_loss():
    """The silent-wipe shape pages_lost_at_detach exists to expose: with
    write-through OFF and the device unreadable at detach (spill fetch
    raises), the host snapshot comes back empty — the device-registry
    entries must STILL be charged as lost, with the flight event."""
    router = _mk_fleet(kv_host_writethrough=False)
    rng = np.random.default_rng(37)
    prefix = rng.integers(1, 255, 100).tolist()  # 2 pages
    try:
        router.submit(
            prefix + rng.integers(1, 255, 20).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        holder = next(
            i
            for i, rep in enumerate(router.replicas)
            if rep.engine.kv_stats()["kv_shared_entries"] > 0
        )
        eng = router.replicas[holder].engine
        assert eng.kv_stats()["kv_host_entries"] == 0  # writethrough off

        def dead_fetch(pages):
            raise RuntimeError("device unreadable (simulated death)")

        eng._fetch_pages_host = dead_fetch
        eng._kv_pool.bind_spill_fetch(dead_fetch)
        report = router.remove_replica(holder, deadline_s=10.0)
        assert report["migrated_entries"] == 0
        assert report["lost_entries"] == 1 and report["lost_pages"] == 2
        assert router.router_stats()["pages_lost_at_detach"] == 2
        if eng.obs is not None:
            evs = [e["event"] for e in eng.obs.flight.events()]
            assert "pages_lost_at_detach" in evs
    finally:
        router.stop()


def test_scale_down_migrates_disk_tier_entries(tmp_path):
    """A prefix demoted to the victim's DISK tier is warm state too: the
    migration export loads it back (HostKVTier.export_all) and moves it to
    the survivor — it is neither device-resident nor in host DRAM, so the
    host-only snapshot used to wipe it silently with pages_lost_at_detach
    staying 0."""
    router = _mk_fleet(kv_spill_dir=str(tmp_path))
    try:
        tier = router.replicas[1].engine.kv_host_tier
        k, v = _fake_kv(1)  # 1024 B per entry
        tier.put((7,) * 8, 8, k, v)
        # shrink the budget so the next put demotes the LRU entry to disk
        tier.max_bytes = 1024
        k2, v2 = _fake_kv(1, 2.0)
        tier.put((9,) * 8, 8, k2, v2)
        s = tier.stats()
        assert s["kv_disk_entries"] == 1 and s["kv_host_entries"] == 1
        report = router.remove_replica(1, deadline_s=10.0)
        assert report["migrated_entries"] == 2
        assert report["lost_entries"] == 0 and report["lost_pages"] == 0
        assert router.router_stats()["pages_lost_at_detach"] == 0
        # the demoted entry's BYTES made it to the survivor
        hit = router.replicas[0].engine.kv_host_tier.lookup([7] * 10, 8)
        assert hit is not None
        np.testing.assert_array_equal(hit.k, k)
    finally:
        router.stop()


def test_migration_charges_unreadable_disk_rows_lost(tmp_path):
    """A disk row whose file cannot be read back at export time is charged
    to pages_lost_at_detach instead of vanishing from the accounting."""
    router = _mk_fleet(kv_spill_dir=str(tmp_path))
    try:
        tier = router.replicas[1].engine.kv_host_tier
        k, v = _fake_kv(1)
        tier.put((7,) * 8, 8, k, v)
        tier.max_bytes = 1024
        tier.put((9,) * 8, 8, k, v)
        assert tier.stats()["kv_disk_entries"] == 1
        for f in os.listdir(tmp_path):  # corrupt the spill namespace
            os.unlink(os.path.join(tmp_path, f))
        report = router.remove_replica(1, deadline_s=10.0)
        assert report["migrated_entries"] == 1  # the host-DRAM entry
        assert report["lost_entries"] == 1 and report["lost_pages"] == 1
        assert router.router_stats()["pages_lost_at_detach"] == 1
    finally:
        router.stop()


def test_detach_migrate_off_counts_disk_entries(tmp_path):
    """migrate=False loss accounting spans host DRAM AND disk
    (HostKVTier.warm_keys) — a demoted prefix is warm state being
    discarded just the same."""
    router = _mk_fleet(kv_spill_dir=str(tmp_path))
    try:
        tier = router.replicas[1].engine.kv_host_tier
        k, v = _fake_kv(1)
        tier.put((7,) * 8, 8, k, v)
        tier.max_bytes = 1024
        tier.put((9,) * 8, 8, k, v)
        assert tier.stats()["kv_disk_entries"] == 1
        report = router.remove_replica(1, deadline_s=10.0, migrate=False)
        assert report["lost_entries"] == 2  # the host row AND the disk row
        assert report["lost_pages"] == 2
        assert report["lost_reason"] == "migration disabled"
    finally:
        router.stop()


def test_legacy_layout_warns_that_host_tier_is_inert(caplog):
    """kv_layout="legacy" is the documented one-flag paged rollback, so
    kv_host_bytes/kv_spill_dir stay VALID — but the host tier only runs on
    the paged plane, and losing durability on a rollback must be said out
    loud, not discovered from missing kv_host_* gauges."""
    import logging

    from django_assistant_bot_tpu.serving.registry import (
        ModelRegistry,
        ModelSpec,
    )

    reg = ModelRegistry()
    with caplog.at_level(
        logging.WARNING, logger="django_assistant_bot_tpu.serving.registry"
    ):
        reg.load(
            ModelSpec(
                name="legacy-rollback", kind="decoder", tiny=True,
                kv_layout="legacy", kv_host_bytes=1 << 20,
                max_slots=2, max_seq_len=64,
            )
        )
    try:
        assert any(
            "no effect with" in r.getMessage() for r in caplog.records
        )
        eng = reg.get_generator("legacy-rollback")
        assert getattr(eng, "kv_host_tier", None) is None
    finally:
        reg.stop()


def test_fallback_peek_covers_non_emitting_replica():
    """The per-replica holds_prefix peek must run for every candidate the
    fleet registry has NO answer for — not only when the registry is empty
    fleet-wide.  A non-event-emitting replica's HBM warm state beats an
    event-emitting replica's (worse-tier) registry holding of the same
    session."""
    router = _mk_fleet()
    rng = np.random.default_rng(41)
    prefix = rng.integers(1, 255, 100).tolist()
    try:
        # replica r1 stops emitting tier events (the stub/legacy shape the
        # fallback exists for), then warms the session HBM-directly
        b = router.replicas[1]
        b.engine.set_prefix_listener(None)
        b.engine.submit(
            prefix + rng.integers(1, 255, 20).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        assert b.engine.kv_stats()["kv_shared_entries"] == 1
        assert router.prefix_registry.holders(prefix + [1], len(prefix)) == {}
        # the registry knows only a (faked) host-tier holding on r0
        router.prefix_registry.on_event(
            "r0", "host_put", tuple(prefix), len(prefix)
        )
        r = router.submit(
            prefix + rng.integers(1, 255, 30).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        assert len(r.token_ids) == 4
        # the peeked HBM holder won over the registry's host-tier holder
        assert b.engine.kv_stats()["prefix_hits"] == 1
    finally:
        router.stop()


def test_fleet_registry_holders_aggregate_across_prefix_lengths():
    """A replica warm with a SHORTER prefix of the same session must keep
    its affinity preference even when another replica holds a longer one
    (the longest holder may be draining/unhealthy at dispatch time)."""
    from django_assistant_bot_tpu.serving.router import FleetPrefixRegistry

    reg = FleetPrefixRegistry()
    reg.on_event("r0", "register", (1, 2, 3), 3)
    reg.on_event("r1", "host_put", (1, 2, 3, 4, 5), 5)
    holders = reg.holders([1, 2, 3, 4, 5, 6, 7], 5)
    assert holders == {"r0": "hbm", "r1": "host"}


def test_host_tier_sweeps_its_stale_namespace_at_boot(tmp_path):
    """The disk index is in-memory: a previous process's files under THIS
    tier's namespace are unreachable and must be swept at construction —
    without touching other replicas' namespaces in a shared dir."""
    k, v = _fake_kv(1)
    a = HostKVTier(1100, page_size=16, spill_dir=str(tmp_path), name="repA")
    a.put((1,) * 8, 8, k, v)
    a.put((2,) * 8, 8, k, v)  # demotes (1,) to disk
    b = HostKVTier(1100, page_size=16, spill_dir=str(tmp_path), name="repB")
    b.put((3,) * 8, 8, k, v)
    b.put((4,) * 8, 8, k, v)
    files = sorted(os.listdir(tmp_path))
    assert any("repA" in f for f in files) and any("repB" in f for f in files)
    # a restarted repA process sweeps repA's orphan, leaves repB's file
    a2 = HostKVTier(1100, page_size=16, spill_dir=str(tmp_path), name="repA")
    files = sorted(os.listdir(tmp_path))
    assert not any("repA" in f for f in files)
    assert any("repB" in f for f in files)
    assert b.lookup([3] * 12, 8) is not None  # repB's disk entry still live
    del a2


def test_sweep_spares_live_sibling_process_files(tmp_path):
    """Spill filenames carry the writing pid: a boot sweep reclaims only
    files whose process is GONE (or recycled as ours), so two live serve
    processes sharing one DABT_KV_SPILL_DIR — even with the same replica
    name — cannot delete each other's warm state.  Pidless old-format
    files are always stale."""
    digest = "0" * 24
    live = f"kvspill-repA-p1-{digest}.npz"  # pid 1 is always alive
    dead_pid = next(
        p for p in range(400000, 500000) if not HostKVTier._pid_alive(p)
    )
    dead = f"kvspill-repA-p{dead_pid}-{digest}.npz"
    old = f"kvspill-repA-{digest}.npz"  # pre-pid format
    for f in (live, dead, old):
        with open(os.path.join(tmp_path, f), "wb") as fh:
            fh.write(b"x")
    HostKVTier(1100, page_size=16, spill_dir=str(tmp_path), name="repA")
    files = os.listdir(tmp_path)
    assert live in files
    assert dead not in files
    assert old not in files


def test_promote_racing_redemote_never_dangles_disk_index(tmp_path):
    """While a lookup holds a disk row reserved (file read outside the
    lock), a concurrent put-then-demote can re-write the SAME key's file at
    the same deterministic path and re-index it — the promote's cleanup
    must absorb that row instead of deleting a file the index points at."""
    host = HostKVTier(1100, page_size=16, spill_dir=str(tmp_path), name="r")
    k, v = _fake_kv(1)
    host.put((1,) * 8, 8, k, v)
    host.put((2,) * 8, 8, k, v)  # (1,) demoted to disk
    assert host.stats()["kv_disk_entries"] == 1
    orig = host._load_disk_file

    def racing_load(path, key, *a):
        ent = orig(path, key, *a)
        host._load_disk_file = orig  # the nested puts must not re-enter
        # the "concurrent" thread, deterministically: (1,) back into host
        # DRAM, then budget pressure demotes it straight back to disk at
        # the path the reserved promote is about to delete
        host.put((1,) * 8, 8, k, v)
        host.put((3,) * 8, 8, k, v)
        assert (1,) * 8 in host._disk
        return ent

    host._load_disk_file = racing_load
    hit = host.lookup([1] * 12, 8)
    assert hit is not None and hit.length == 8
    # no disk row may reference a deleted file
    for path, _ln, _nb, _pg in host._disk.values():
        assert os.path.exists(path), path
    # and every remaining disk entry still promotes cleanly
    assert host.lookup([3] * 12, 8) is not None or (3,) * 8 not in host._disk


def test_migration_survives_replica_dying_mid_drain():
    """THE race: the scale-down victim dies under the drain.  The warm-state
    export is a host-memory snapshot (numpy, not device state), so migration
    still lands on the survivor and the scale-down completes."""
    router = _mk_fleet()
    rng = np.random.default_rng(33)
    prefix = rng.integers(1, 255, 100).tolist()
    try:
        router.submit(
            prefix + rng.integers(1, 255, 20).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        holders = router.prefix_registry.holders(prefix + [1], len(prefix))
        holder_name = next(iter(holders))
        idx = [rep.name for rep in router.replicas].index(holder_name)
        # kill it the hard way, then scale it down: the drain sees a dead
        # engine (reads idle), the migration exports host numpy anyway
        router.kill_replica(idx)
        deadline = time.monotonic() + 10
        while router.replicas[idx].engine._thread.is_alive():
            assert time.monotonic() < deadline
            time.sleep(0.01)
        report = router.remove_replica(idx, deadline_s=5.0)
        assert report["died_mid_drain"] is True
        assert report["migrated_entries"] == 1
        assert report["lost_pages"] == 0
        survivor = router.replicas[0].engine
        assert survivor.kv_stats()["kv_migrated_in"] == 1
        # fleet keeps serving the warm prefix via restore
        r = router.submit(
            prefix + rng.integers(1, 255, 30).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(prefix),
        ).result(timeout=300)
        assert len(r.token_ids) == 4
        assert survivor.kv_stats()["kv_restores"] >= 1
    finally:
        router.stop()


def _stall(engine, delay_s=0.1, fires=16):
    """Arm slow_tick so the engine's loop holds work in flight token-less
    (the test_router discipline)."""
    inj = engine._faults
    inj.arm("slow_tick", fires)
    with inj._lock:
        inj._sites["slow_tick"].delay_s = delay_s


def test_restore_racing_replica_kill_reroutes_tokenless():
    """Chaos: a request whose prefix is HOST-tier-only on one replica is
    routed there (warm affinity) and the replica is killed inside the
    restore/admission window, before any client token.  The token-less
    re-route lands it on the survivor — goodput 1.0; the dead replica's
    restore is lost state, not a lost request."""
    engines = [
        _tiny_engine(
            kv_host_bytes=1 << 26, prefix_cache_size=1, name=f"r{i}",
            faults=FaultInjector({}),
        ).start()
        for i in range(2)
    ]
    router = EngineRouter(engines, names=["r0", "r1"], breaker_reset_s=0.2)
    rng = np.random.default_rng(34)
    pref1 = rng.integers(1, 255, 100).tolist()
    pref2 = rng.integers(1, 255, 100).tolist()
    try:
        router.replicas[1].draining = True  # pin warmup onto r0
        for pf in (pref1, pref2):  # pref2 evicts pref1 to r0's host tier
            router.submit(
                pf + rng.integers(1, 255, 20).tolist(),
                max_tokens=2, temperature=0.0, prefix_len=len(pf),
            ).result(timeout=300)
        router.replicas[1].draining = False
        assert router.prefix_registry.holders(pref1 + [1], len(pref1)) == {
            "r0": "host"
        }
        _stall(engines[0])
        _stall(engines[1])
        fut = router.submit(
            pref1 + rng.integers(1, 255, 30).tolist(),
            max_tokens=4, temperature=0.0, prefix_len=len(pref1),
        )
        time.sleep(0.05)  # inside the stalled window: no host tokens yet
        router.kill_replica(0)
        r = fut.result(timeout=300)
        assert len(r.token_ids) == 4  # goodput 1.0
        assert router.router_stats()["reroutes"] >= 1
        assert router.rerouted_failed == 0
    finally:
        router.stop()


def test_disk_tier_restore_through_engine(tmp_path):
    """A host budget of ~one entry + a spill dir: warming a second prefix
    demotes the first to disk; hitting it again promotes + restores, and
    the output matches the tiering-off reference."""
    rng = np.random.default_rng(35)
    pref1 = rng.integers(1, 255, 100).tolist()
    pref2 = rng.integers(1, 255, 100).tolist()
    seq = [
        (pref1 + rng.integers(1, 255, 20).tolist(), len(pref1)),
        (pref2 + rng.integers(1, 255, 20).tolist(), len(pref2)),
        (pref1 + rng.integers(1, 255, 25).tolist(), len(pref1)),
    ]

    def run(**kw):
        eng = _tiny_engine(prefix_cache_size=1, **kw).start()
        try:
            outs = [
                eng.submit(
                    t, max_tokens=8, temperature=0.0, prefix_len=pl
                ).result(timeout=300).token_ids
                for t, pl in seq
            ]
            return outs, eng.kv_stats()
        finally:
            eng.stop()

    ref, _ = run()
    # a 100-token prefix spans 2 pages, so one entry is 2 * page_bytes; a
    # 3-page budget holds exactly one entry and the second warm prefix
    # demotes the first to disk
    probe = _tiny_engine(kv_host_bytes=1 << 26)
    page_bytes = probe._kv_host.page_bytes
    del probe
    got, st = run(kv_host_bytes=3 * page_bytes, kv_spill_dir=str(tmp_path))
    assert got == ref
    assert st["kv_disk_spills"] >= 1
    assert st["kv_disk_promotes"] >= 1
    assert st["kv_restores"] >= 1
    assert any(f.startswith("kvspill-") for f in os.listdir(tmp_path))


def test_env_gate_dabt_kv_spill_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("DABT_KV_SPILL_DIR", str(tmp_path))
    eng = _tiny_engine()
    assert eng.kv_host_tier is not None
    assert eng.kv_host_tier.spill_dir == str(tmp_path)
    monkeypatch.delenv("DABT_KV_SPILL_DIR")
    eng2 = _tiny_engine()
    assert eng2.kv_host_tier is None
    del eng, eng2
