"""Bot runtime plane: domain round-trips, MarkdownV2 rendering, resources,
dialog services, AssistantBot engine, ContextService pipeline.

Test strategy mirrors the reference (SURVEY.md §4): the engine runs real against
sqlite; AI is cut at provider level (scripted EchoProvider) or at
`get_answer_to_messages` (reference tests/bot_tests/test_assistant_bot.py:83-107).
"""

import asyncio
import datetime as dt

import numpy as np
import pytest

from django_assistant_bot_tpu.ai.providers.echo import EchoProvider, HashEmbedder
from django_assistant_bot_tpu.bot import (
    Button,
    MultiPartAnswer,
    Photo,
    SingleAnswer,
    Update,
    User,
    answer_from_dict,
)
from django_assistant_bot_tpu.bot.assistant_bot import AssistantBot
from django_assistant_bot_tpu.bot.domain import BotPlatform
from django_assistant_bot_tpu.bot.platforms.telegram.format import (
    escape_markdown_v2,
    format_markdown_v2,
)
from django_assistant_bot_tpu.bot.resource_manager import ResourceManager
from django_assistant_bot_tpu.bot.services.dialog_service import (
    create_bot_message,
    create_user_message,
    get_dialog,
    get_gpt_messages,
    have_existing_answers,
)
from django_assistant_bot_tpu.conf import settings
from django_assistant_bot_tpu.storage import models


class StubPlatform(BotPlatform):
    def __init__(self):
        self.posted = []
        self.typing = 0

    @property
    def codename(self):
        return "stub"

    async def get_update(self, request):
        raise NotImplementedError

    async def post_answer(self, chat_id, answer):
        self.posted.append((chat_id, answer))

    async def action_typing(self, chat_id):
        self.typing += 1


@pytest.fixture()
def instance(tmp_db):
    bot = models.Bot.objects.create(codename="tb", system_text="You are helpful.")
    user = models.BotUser.objects.create(user_id="u1", platform="telegram", language="en")
    return models.Instance.objects.create(bot=bot, user=user)


@pytest.fixture()
def dialog(instance):
    return models.Dialog.objects.create(instance=instance)


@pytest.fixture()
def bot_engine(dialog):
    return AssistantBot(dialog, StubPlatform())


# --------------------------------------------------------------------- domain
def test_update_round_trip():
    upd = Update(
        chat_id="c1",
        message_id=5,
        text="hi",
        photo=Photo(file_id="f", extension="jpg", content=b"\x01\x02"),
        user=User(id="u", username="name"),
    )
    restored = Update.from_dict(upd.to_dict())
    assert restored.chat_id == "c1" and restored.message_id == 5
    assert restored.photo.content == b"\x01\x02"
    assert restored.user.username == "name"


def test_answer_round_trip():
    ans = SingleAnswer(
        text="hello",
        raw_text="#text hello",
        buttons=[[Button("Go", callback_data="/go")]],
        usage=[{"model": "m", "prompt_tokens": 1}],
    )
    restored = answer_from_dict(ans.to_dict())
    assert restored.text == "hello" and restored.raw_text == "#text hello"
    assert restored.buttons[0][0].callback_data == "/go"
    assert restored.final_model == "m"

    multi = MultiPartAnswer(parts=[ans, SingleAnswer(text="b")])
    restored = answer_from_dict(multi.to_dict())
    assert isinstance(restored, MultiPartAnswer) and len(restored.parts) == 2


# ------------------------------------------------------------------- markdown
def test_markdown_v2_escaping_and_structure():
    assert escape_markdown_v2("a.b!c") == "a\\.b\\!c"
    out = format_markdown_v2("**bold** and `code_x` plus plain. text")
    assert "*bold*" in out
    assert "`code_x`" in out
    assert "plain\\. text" in out
    fenced = format_markdown_v2("```python\nx = a.b\n```")
    assert "```python\nx = a.b\n```" in fenced


import pytest  # noqa: E402


@pytest.mark.parametrize(
    "src,expected",
    [
        # bullet lists -> \- items (reference ListItem, format.py:245-270)
        ("- one\n- two.", "\\- one\n\\- two\\."),
        ("* star\n+ plus", "\\- star\n\\- plus"),
        # nested bullets keep their indentation
        ("- a\n  - b\n    - c", "\\- a\n  \\- b\n    \\- c"),
        # numbered lists -> N\. items (reference NumberedListItem)
        ("1. first\n2. second", "1\\. first\n2\\. second"),
        ("1) alt style", "1\\. alt style"),
        # blockquotes -> native '>' quote lines
        ("> quoted text.", ">quoted text\\."),
        ("> line one\n> line two", ">line one\n>line two"),
        # nested inline styles survive (reference recursive formatter nodes)
        ("**bold with _italic_ inside**", "*bold with _italic_ inside*"),
        ("**bold ~~strike~~** tail.", "*bold ~strike~* tail\\."),
        ("- item with **bold** and [link](https://x.y/z)",
         "\\- item with *bold* and [link](https://x.y/z)"),
        # bold markers inside an already-bold context (a header) are elided —
        # doubled '*' would be rejected by Telegram's parser
        ("# Header with **bold**", "*Header with bold*"),
        ("**outer **inner** tail**", "*outer *inner* tail*"),
        ("***both***", "*_both_*"),
        # bold+italic inside a header: only the italic marker is new
        ("# H ***bi***", "*H _bi_*"),
    ],
)
def test_markdown_v2_structures_render_without_fallback(src, expected):
    """The reference's test-worthy structures (format.py:108-426) render as
    MarkdownV2 rather than degrading to fully-escaped literals."""
    assert format_markdown_v2(src) == expected


def test_markdown_v2_list_items_not_escaped_to_literals():
    out = format_markdown_v2("Intro:\n- **a**\n- b\n\n1. c\n2. d")
    assert "\\- *a*" in out and "1\\. c" in out
    # the old regex subset escaped bullets into literal '\-'-less text
    assert "\\*\\*" not in out


# ------------------------------------------------------------------ resources
def test_resource_manager_language_fallback(tmp_path):
    bot_dir = tmp_path / "mybot"
    (bot_dir / "messages" / "ru").mkdir(parents=True)
    (bot_dir / "phrases").mkdir()
    (bot_dir / "messages" / "ru" / "Hello.txt").write_text("privet")
    (bot_dir / "phrases" / "ru.json").write_text('{"Continue": "Prodolzhit"}')
    with settings.override(RESOURCES_DIR=str(tmp_path)):
        rm = ResourceManager("mybot", language="en")
        assert rm.get_message("Hello.txt") == "privet"  # en -> ru fallback
        assert rm.get_phrase("Continue") == "Prodolzhit"
        assert rm.get_phrase("Missing") == "Missing"  # literal fallback


# ------------------------------------------------------------- dialog service
def test_get_dialog_ttl_rollover(instance):
    d1 = get_dialog(instance, ttl=dt.timedelta(days=1))
    create_user_message(d1, 1, "hi")
    assert get_dialog(instance, ttl=dt.timedelta(days=1)).id == d1.id
    # age the message beyond the TTL -> new dialog, old completed
    old = (dt.datetime.now(dt.timezone.utc) - dt.timedelta(days=2)).isoformat()
    models.Message.objects.filter(dialog=d1).update(timestamp=old)
    d2 = get_dialog(instance, ttl=dt.timedelta(days=1))
    assert d2.id != d1.id
    assert models.Dialog.objects.get(id=d1.id).is_completed


def test_message_idempotence_and_answers(dialog):
    m1 = create_user_message(dialog, 10, "hello")
    m2 = create_user_message(dialog, 10, "hello again")
    assert m1.id == m2.id  # get_or_create on (dialog, message_id)
    assert not have_existing_answers(m1)
    create_bot_message(dialog, SingleAnswer(text="answer", usage=[{"model": "test"}]))
    assert have_existing_answers(m1)


def test_get_gpt_messages_continue_and_system(dialog):
    create_user_message(dialog, 1, "question")
    create_user_message(dialog, 2, "/continue")
    msgs = get_gpt_messages(dialog, "SYS")
    assert msgs[0] == {"role": "system", "content": "SYS"}
    assert msgs[1]["role"] == "user" and msgs[1]["content"] == "question"
    assert msgs[2] == {"role": "system", "content": "Continue"}


# ------------------------------------------------------------------- engine
def _run_update(bot, text, message_id=1):
    create_user_message(bot.dialog, message_id, text)
    upd = Update(chat_id="c", message_id=message_id, text=text, user=User(id="u1"))
    return asyncio.run(bot.handle_update(upd))


def test_handle_update_with_mocked_completion(bot_engine, monkeypatch):
    async def fake_answer(self, messages, debug_info, do_interrupt):
        return SingleAnswer(text="mocked!", usage=[{"model": "test"}])

    monkeypatch.setattr(AssistantBot, "get_answer_to_messages", fake_answer)
    answer = _run_update(bot_engine, "what is up?")
    assert answer.text == "mocked!"
    # debug checkpoint persisted into instance state
    state = models.Instance.objects.get(id=bot_engine.instance.id).state
    assert "debug_info" in state


def test_handle_update_unmarks_unavailable(bot_engine, monkeypatch):
    bot_engine.instance.is_unavailable = True
    bot_engine.instance.save()

    async def fake_answer(self, messages, debug_info, do_interrupt):
        return SingleAnswer(text="ok")

    monkeypatch.setattr(AssistantBot, "get_answer_to_messages", fake_answer)
    _run_update(bot_engine, "hello")
    assert models.Instance.objects.get(id=bot_engine.instance.id).is_unavailable is False


def test_whitelist_blocks_unknown_user(bot_engine):
    bot_engine.bot.is_whitelist_enabled = True
    bot_engine.bot.telegram_whitelist = "someoneelse"
    bot_engine.bot.save()
    answer = _run_update(bot_engine, "hi")
    assert "Authorization required" in answer.text
    assert answer.no_store


def test_command_new_dialog(bot_engine):
    answer = _run_update(bot_engine, "/new")
    assert "New dialog started" in answer.text
    assert models.Dialog.objects.get(id=bot_engine.dialog.id).is_completed


def test_command_model_selection(bot_engine):
    answer = _run_update(bot_engine, "/model tpu:llama-3-8b")
    assert "selected" in answer.text
    state = models.Instance.objects.get(id=bot_engine.instance.id).state
    assert state["model"] == "tpu:llama-3-8b"
    assert bot_engine._get_strong_ai_model() == "tpu:llama-3-8b"


def test_command_unknown(bot_engine):
    answer = _run_update(bot_engine, "/definitely_not_a_command")
    assert "Unknown command" in answer.text


def test_custom_command_decorator(dialog):
    class MyBot(AssistantBot):
        pass

    @MyBot.command(r"/task (\w+)")
    async def task_cmd(self, match, message_id):
        return SingleAnswer(text=f"task:{match.group(1)}", no_store=True)

    bot = MyBot(dialog, StubPlatform())
    answer = _run_update(bot, "/task build")
    assert answer.text == "task:build"
    # the base class table must not see the subclass command
    assert all(p.pattern != r"/task (\w+)" for p, _ in AssistantBot._command_handlers)


def test_think_and_text_tag_extraction(bot_engine):
    from django_assistant_bot_tpu.ai.domain import AIResponse

    bot_engine.resource_manager = ResourceManager("tb", "en")
    resp = AIResponse(
        result="<think>step by step</think>#text The answer is 42",
        usage={"model": "test"},
    )
    answer = bot_engine._ai_response_to_answer(resp)
    assert answer.text == "The answer is 42"
    assert answer.thinking == "step by step"
    assert answer.raw_text.startswith("<think>")


def test_idempotence_already_answered(bot_engine, monkeypatch):
    calls = []

    async def fake_answer(self, messages, debug_info, do_interrupt):
        calls.append(1)
        return SingleAnswer(text="a")

    monkeypatch.setattr(AssistantBot, "get_answer_to_messages", fake_answer)
    create_user_message(bot_engine.dialog, 1, "q")
    create_bot_message(bot_engine.dialog, SingleAnswer(text="already", usage=[]))
    upd = Update(chat_id="c", message_id=1, text="q", user=User(id="u1"))
    answer = asyncio.run(bot_engine.handle_update(upd))
    assert answer is None  # guarded: the question already has an answer
    assert not calls


# ------------------------------------------------------------ context service
def _seed_kb(bot):
    """Wiki root (completed processing) with one doc + clustered questions."""
    wiki = models.WikiDocument.objects.create(bot=bot, title="Billing")
    models.WikiDocumentProcessing.objects.create(
        wiki_document=wiki, status=models.WikiDocumentProcessing.COMPLETED
    )
    doc = models.Document.objects.create(
        wiki=wiki, name="Billing FAQ", content="Pay invoices in the portal."
    )
    emb = HashEmbedder(dim=768)
    for i, q in enumerate(["How to pay invoice?", "Where to update card?"] * 6):
        vec = np.asarray(asyncio.run(emb.embeddings([q]))[0], np.float32)
        models.Question.objects.create(document=doc, text=f"{q} #{i}", order=i, embedding=vec)
    return wiki, doc


def test_context_service_smalltalk_short_circuits(instance, monkeypatch):
    from django_assistant_bot_tpu.bot.services.context_service.service import ContextService
    from django_assistant_bot_tpu.bot.services.context_service.steps import base as steps_base
    from django_assistant_bot_tpu.rag.index_registry import reset_indexes

    reset_indexes()
    _seed_kb(instance.bot)
    scripted = EchoProvider(script=[{"topic": "Small talk"}])
    monkeypatch.setattr(steps_base, "get_ai_provider", lambda model: scripted)

    messages = [{"role": "user", "content": "hey there!"}]
    service = ContextService(
        bot=instance.bot,
        fast_ai_model="test",
        strong_ai_model="test",
        messages=list(messages),
        debug_info={},
    )
    enriched = asyncio.run(service.enrich())
    # small talk -> pipeline interrupted -> no system enrichment appended
    assert enriched == messages


def test_context_service_knowledge_path(instance, monkeypatch):
    from django_assistant_bot_tpu.bot.services.context_service.service import ContextService
    from django_assistant_bot_tpu.bot.services.context_service.steps import base as steps_base
    from django_assistant_bot_tpu.rag.index_registry import reset_indexes

    reset_indexes()
    wiki, doc = _seed_kb(instance.bot)
    # classify -> Billing topic; choose_known_question -> null (use doc search)
    scripted = EchoProvider(script=[{"topic": "Billing"}, {"question": None}])
    monkeypatch.setattr(steps_base, "get_ai_provider", lambda model: scripted)

    debug = {}
    service = ContextService(
        bot=instance.bot,
        fast_ai_model="test",
        strong_ai_model="test",
        messages=[{"role": "user", "content": "How to pay invoice? #3"}],
        debug_info=debug,
    )
    enriched = asyncio.run(service.enrich())
    final_system = enriched[-1]
    assert final_system["role"] == "system"
    assert "Pay invoices in the portal." in final_system["content"]
    assert debug["classify"]["topic"] == "Billing"
    assert debug["embedding_search"]["related_questions"]


def test_save_photo_unguessable_and_idempotent(tmp_path, monkeypatch):
    """Media serves auth-exempt, so names must be unguessable even to an
    attacker holding the content (HMAC over an install secret, not a bare
    content hash), contain no enumerable platform file_id, and stay stable
    across webhook redeliveries (VERDICT r4 weak #5)."""
    import hashlib
    import os as _os

    from django_assistant_bot_tpu.bot.services.dialog_service import _save_photo

    monkeypatch.setenv("DABT_MEDIA_DIR", str(tmp_path / "photos"))
    photo = Photo(file_id="enumerable-id-123", extension="jpg", content=b"known-bytes")
    p1 = _save_photo(photo)
    p2 = _save_photo(photo)
    assert p1 == p2  # redelivery rewrites the same path
    name = _os.path.basename(p1)
    assert "enumerable-id-123" not in p1
    assert hashlib.sha256(b"known-bytes").hexdigest()[:32] not in name
    # the secret must live OUTSIDE the served media tree (a sibling of the
    # media root — everything UNDER the root serves auth-exempt), mode 0600
    secret = tmp_path.parent / (tmp_path.name + ".secret")
    assert secret.exists() and (secret.stat().st_mode & 0o777) == 0o600
    assert len(secret.read_bytes()) == 32
    assert not (tmp_path / "photos" / ".media_secret").exists()
