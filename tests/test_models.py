"""Model correctness: parity vs HF transformers (torch CPU) + decode/forward agreement.

This is the test style SURVEY.md §4 prescribes adapted to the model plane: real
checkpoints are too big for CI, so tiny randomly-initialised HF models are saved to
disk and loaded through the production safetensors loader — the full load→convert→
forward path runs for real, only the scale is fake.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from django_assistant_bot_tpu.models import DecoderConfig, encoder, llama
from django_assistant_bot_tpu.models.hf_loader import load_decoder, load_encoder


@pytest.fixture(scope="module")
def tiny_bert_dir(tmp_path_factory):
    from transformers import BertConfig, BertModel

    cfg = BertConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=64,
    )
    model = BertModel(cfg)
    model.eval()
    d = tmp_path_factory.mktemp("tiny_bert")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


@pytest.fixture(scope="module")
def tiny_llama_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(cfg)
    model.eval()
    d = tmp_path_factory.mktemp("tiny_llama")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


@pytest.fixture(scope="module")
def tiny_qwen2_dir(tmp_path_factory):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    cfg = Qwen2Config(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = Qwen2ForCausalLM(cfg)
    model.eval()
    d = tmp_path_factory.mktemp("tiny_qwen2")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


@pytest.fixture(scope="module")
def tiny_gemma_dir(tmp_path_factory):
    from transformers import GemmaConfig, GemmaForCausalLM

    cfg = GemmaConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,  # gemma decouples head_dim from hidden/heads
        intermediate_size=64,
        max_position_embeddings=128,
        rope_theta=10000.0,
    )
    model = GemmaForCausalLM(cfg)
    model.eval()
    d = tmp_path_factory.mktemp("tiny_gemma")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


def test_encoder_matches_hf(tiny_bert_dir):
    import torch

    d, hf_model = tiny_bert_dir
    cfg, params = load_encoder(d, dtype=jnp.float32)
    ids = np.array([[5, 9, 17, 3, 0, 0], [8, 2, 0, 0, 0, 0]], np.int32)
    mask = np.array([[1, 1, 1, 1, 0, 0], [1, 1, 0, 0, 0, 0]], np.int32)

    with torch.no_grad():
        hf_out = hf_model(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).last_hidden_state.numpy()

    ours = np.asarray(encoder.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask)))
    # padding positions diverge (we don't mask them out of the residual stream) —
    # compare only real tokens
    for b in range(ids.shape[0]):
        n = mask[b].sum()
        np.testing.assert_allclose(ours[b, :n], hf_out[b, :n], atol=2e-4, rtol=1e-3)


def test_encoder_encode_pools_and_normalizes(tiny_bert_dir):
    d, _ = tiny_bert_dir
    cfg, params = load_encoder(d, dtype=jnp.float32)
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 100, (3, 8)), jnp.int32)
    mask = jnp.ones((3, 8), jnp.int32)
    out = encoder.encode(params, cfg, ids, mask, normalize=True)
    assert out.shape == (3, cfg.hidden_size)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(out), axis=-1), 1.0, atol=1e-5)


def test_llama_matches_hf(tiny_llama_dir):
    import torch

    d, hf_model = tiny_llama_dir
    cfg, params = load_decoder(d, dtype=jnp.float32)
    ids = np.array([[1, 5, 9, 17, 3, 25, 7, 2]], np.int32)
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, cfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=3e-4, rtol=1e-3)


def test_qwen2_matches_hf(tiny_qwen2_dir):
    """Qwen2 family = Llama geometry + q/k/v projection biases."""
    import torch

    d, hf_model = tiny_qwen2_dir
    cfg, params = load_decoder(d, dtype=jnp.float32)
    assert cfg.attn_bias
    # saved biases are random (HF init), so this exercises the bias path for real
    ids = np.array([[1, 5, 9, 17, 3, 25, 7, 2]], np.int32)
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, cfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=3e-4, rtol=1e-3)


def test_gemma_matches_hf(tiny_gemma_dir):
    """Gemma family: GeGLU MLP, (1+w) RMSNorm (folded at load), sqrt(E)-scaled
    embeddings, tied head, decoupled head_dim."""
    import torch

    d, hf_model = tiny_gemma_dir
    cfg, params = load_decoder(d, dtype=jnp.float32)
    assert cfg.hidden_act == "gelu_tanh"
    assert cfg.embed_multiplier == pytest.approx(32 ** 0.5)
    assert cfg.tie_embeddings and cfg.head_dim == 16
    ids = np.array([[1, 5, 9, 17, 3, 25, 7, 2]], np.int32)
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, cfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=3e-4, rtol=1e-3)


def test_llama31_rope_scaling_matches_hf(tmp_path):
    """Llama-3.1-style checkpoints carry rope_scaling type 'llama3'; the
    frequency remap must match HF's (silently ignoring it would misplace
    every position past the original context)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 64,
        },
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(cfg)
    model.eval()
    d = tmp_path / "llama31"
    model.save_pretrained(d, safe_serialization=True)
    jcfg, params = load_decoder(str(d), dtype=jnp.float32)
    assert jcfg.rope_scaling == (8.0, 1.0, 4.0, 64.0)
    # long enough that scaled and unscaled frequencies clearly diverge
    ids = np.asarray(
        np.random.default_rng(3).integers(1, 128, (1, 96)), np.int32
    )
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, jcfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=5e-4, rtol=1e-3)


def test_sliding_window_config_semantics():
    """Windowed attention runs natively now: full advertised context stays
    usable (no clamp) and HF's per-family gating flags map onto
    (sliding_window, window_layer_start)."""
    from django_assistant_bot_tpu.models.config import DecoderConfig

    base = dict(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4,
        max_position_embeddings=4096,
    )
    # Mistral/Phi-3 style: window active in every layer, context NOT clamped
    cfg = DecoderConfig.from_hf({**base, "sliding_window": 1024})
    assert cfg.max_seq_len == 4096
    assert cfg.sliding_window == 1024
    assert cfg.window_layer_start == 0
    # Qwen2 style: window present but disabled -> full attention
    cfg = DecoderConfig.from_hf(
        {**base, "sliding_window": 1024, "use_sliding_window": False}
    )
    assert cfg.sliding_window is None
    # qwen2 family omitting the flag: HF defaults it OFF for qwen2 only
    cfg = DecoderConfig.from_hf(
        {**base, "model_type": "qwen2", "sliding_window": 1024}
    )
    assert cfg.sliding_window is None
    # qwen2 with the flag on: layers [0, max_window_layers) stay full
    cfg = DecoderConfig.from_hf(
        {
            **base,
            "model_type": "qwen2",
            "sliding_window": 1024,
            "use_sliding_window": True,
            "max_window_layers": 2,
        }
    )
    assert cfg.sliding_window == 1024
    assert cfg.window_layer_start == 2
    # absent max_window_layers falls back to HF's default of 28 (not 0 — that
    # would window every layer HF keeps full)
    cfg = DecoderConfig.from_hf(
        {
            **base,
            "model_type": "qwen2",
            "sliding_window": 1024,
            "use_sliding_window": True,
        }
    )
    assert cfg.window_layer_start == 28


def test_mistral_sliding_window_matches_hf(tmp_path):
    """Prompt LONGER than the window — the parity case the round-2 clamp
    truncated (reference capability bar: 8k contexts via Ollama serve the
    full prompt, .env.example:12-19)."""
    import torch
    from transformers import MistralConfig, MistralForCausalLM

    cfg = MistralConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=128,
        rope_theta=10000.0,
        sliding_window=4,
        tie_word_embeddings=False,
    )
    model = MistralForCausalLM(cfg)
    model.eval()
    d = tmp_path / "mistral"
    model.save_pretrained(d, safe_serialization=True)
    jcfg, params = load_decoder(str(d), dtype=jnp.float32)
    assert jcfg.sliding_window == 4
    assert jcfg.max_seq_len == 128
    ids = np.array([[1, 5, 9, 17, 3, 25, 7, 2, 11, 4, 19, 6]], np.int32)  # 12 > 4
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, jcfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=3e-4, rtol=1e-3)
    # sanity: the window actually changes the result
    full = dataclasses.replace(jcfg, sliding_window=None)
    ours_full = np.asarray(llama.forward(params, full, jnp.asarray(ids)))
    assert np.abs(ours_full - ours).max() > 1e-3


def test_qwen2_window_layer_split_matches_hf(tmp_path):
    """Qwen2 max_window_layers: layer 0 full, layer 1 windowed — the split-scan
    path must agree with HF's per-layer layer_types masks."""
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM

    cfg = Qwen2Config(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=128,
        rope_theta=10000.0,
        use_sliding_window=True,
        sliding_window=4,
        max_window_layers=1,
        tie_word_embeddings=False,
    )
    model = Qwen2ForCausalLM(cfg)
    model.eval()
    d = tmp_path / "qwen2win"
    model.save_pretrained(d, safe_serialization=True)
    jcfg, params = load_decoder(str(d), dtype=jnp.float32)
    assert jcfg.sliding_window == 4
    assert jcfg.window_layer_start == 1
    ids = np.array([[1, 5, 9, 17, 3, 25, 7, 2, 11, 4, 19, 6]], np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, jcfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=3e-4, rtol=1e-3)


@pytest.mark.slow
def test_windowed_prefill_chunk_decode_matches_forward(tmp_path):
    """Windowed banded masks over the slot cache: prefill / chunked prefill /
    decode must all agree with the full windowed forward beyond the window."""
    import torch
    from transformers import MistralConfig, MistralForCausalLM

    hf_cfg = MistralConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=128,
        rope_theta=10000.0,
        sliding_window=4,
        tie_word_embeddings=False,
    )
    model = MistralForCausalLM(hf_cfg)
    model.eval()
    d = tmp_path / "mistral2"
    model.save_pretrained(d, safe_serialization=True)
    cfg, params = load_decoder(str(d), dtype=jnp.float32)
    prompt = np.array([[1, 5, 9, 17, 3, 25, 7, 2, 11, 4]], np.int32)  # 10 > 4
    n_new = 5

    seq = prompt.copy()
    for _ in range(n_new):
        logits = llama.forward(params, cfg, jnp.asarray(seq))
        seq = np.concatenate([seq, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
    expected = seq[0, prompt.shape[1]:].tolist()

    # monolithic prefill + decode
    cache = llama.init_cache(cfg, batch=1, max_len=32, dtype=jnp.float32)
    lengths = jnp.asarray([prompt.shape[1]], jnp.int32)
    logits, ks, vs = llama.prefill(params, cfg, jnp.asarray(prompt), lengths)
    cache = llama.insert_sequences(cache, ks, vs, lengths, jnp.asarray([0], jnp.int32))
    got = [int(jnp.argmax(logits[0]))]
    for _ in range(n_new - 1):
        logits, cache = llama.decode_step(
            params, cfg, jnp.asarray([got[-1]], jnp.int32), cache
        )
        got.append(int(jnp.argmax(logits[0])))
    assert got == expected

    # chunked prefill (two chunks of 5; the second spans the window boundary)
    cache = llama.init_cache(cfg, batch=1, max_len=32, dtype=jnp.float32)
    slot = jnp.asarray(0, jnp.int32)
    logits, cache = llama.prefill_chunk(
        params, cfg, jnp.asarray(prompt[:, :5]), cache, slot,
        jnp.asarray(0, jnp.int32), jnp.asarray(5, jnp.int32),
    )
    logits, cache = llama.prefill_chunk(
        params, cfg, jnp.asarray(prompt[:, 5:]), cache, slot,
        jnp.asarray(5, jnp.int32), jnp.asarray(5, jnp.int32),
    )
    got = [int(jnp.argmax(logits[0]))]
    for _ in range(n_new - 1):
        logits, cache = llama.decode_step(
            params, cfg, jnp.asarray([got[-1]], jnp.int32), cache
        )
        got.append(int(jnp.argmax(logits[0])))
    assert got == expected


def test_unsupported_rope_scaling_rejected(tiny_llama_dir, tmp_path):
    import json
    import shutil

    d, _ = tiny_llama_dir
    bad = tmp_path / "badrope"
    shutil.copytree(d, bad)
    cfg = json.loads((bad / "config.json").read_text())
    cfg["rope_scaling"] = {"rope_type": "dynamic", "factor": 4.0}
    (bad / "config.json").write_text(json.dumps(cfg))
    with pytest.raises(ValueError, match="unsupported rope_scaling"):
        load_decoder(str(bad))


def test_phi3_longrope_matches_hf(tmp_path):
    """Phi-3 128k longrope: short-factor regime (prompt within the pretrained
    context) AND long-factor regime (table built past it) both match HF.
    Round 2 rejected these checkpoints at load (hf_loader)."""
    import torch
    from transformers import Phi3Config, Phi3ForCausalLM

    common = dict(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        pad_token_id=0,
        original_max_position_embeddings=32,
        rope_scaling={
            "type": "longrope",
            "short_factor": [1.0, 1.1, 1.2, 1.3],
            "long_factor": [2.0, 2.5, 3.0, 4.0],
        },
    )
    rng = np.random.default_rng(4)

    # Our short/long choice is PER DEPLOYMENT (cfg.max_seq_len vs pretrained
    # original) — one factor list for prefill AND decode, where HF flips per
    # running sequence.  Each regime therefore gets its own checkpoint whose
    # deployed context selects the same list HF uses for the tested prompt.

    # short regime: deployed context == pretrained 32 -> short_factor;
    # HF also uses short_factor for every prompt <= 32
    model = Phi3ForCausalLM(Phi3Config(**common, max_position_embeddings=32))
    model.eval()
    d = tmp_path / "phi3lr_short"
    model.save_pretrained(d, safe_serialization=True)
    jcfg, params = load_decoder(str(d), dtype=jnp.float32)
    assert jcfg.rope_scaling[0] == "longrope"
    ids = np.asarray(rng.integers(1, 128, (1, 16)), np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, jcfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=5e-4, rtol=1e-3)

    # long regime: deployed context 128 > 32 -> long_factor;
    # HF flips the whole sequence to long_factor once the prompt passes 32
    model = Phi3ForCausalLM(Phi3Config(**common, max_position_embeddings=128))
    model.eval()
    d = tmp_path / "phi3lr_long"
    model.save_pretrained(d, safe_serialization=True)
    jcfg, params = load_decoder(str(d), dtype=jnp.float32)
    ids = np.asarray(rng.integers(1, 128, (1, 48)), np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, jcfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=5e-4, rtol=1e-3)
    # decode path consistency: chained prefill+decode equals repeated forward
    # (one factor list everywhere; mixed lists would corrupt cached K)
    prompt = np.asarray(rng.integers(1, 128, (1, 40)), np.int32)
    seq = prompt.copy()
    for _ in range(3):
        lg = llama.forward(params, jcfg, jnp.asarray(seq))
        seq = np.concatenate([seq, [[int(jnp.argmax(lg[0, -1]))]]], axis=1)
    expected = seq[0, prompt.shape[1]:].tolist()
    cache = llama.init_cache(jcfg, batch=1, max_len=64, dtype=jnp.float32)
    lengths = jnp.asarray([prompt.shape[1]], jnp.int32)
    lg, ks, vs = llama.prefill(params, jcfg, jnp.asarray(prompt), lengths)
    cache = llama.insert_sequences(cache, ks, vs, lengths, jnp.asarray([0], jnp.int32))
    got = [int(jnp.argmax(lg[0]))]
    for _ in range(2):
        lg, cache = llama.decode_step(params, jcfg, jnp.asarray([got[-1]], jnp.int32), cache)
        got.append(int(jnp.argmax(lg[0])))
    assert got == expected


def test_yarn_rope_scaling_matches_hf(tmp_path):
    """YaRN (Qwen2 long-context variants): NTK-by-parts interpolation with the
    mscale attention factor."""
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM

    cfg = Qwen2Config(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        rope_scaling={
            "rope_type": "yarn",
            "factor": 4.0,
            "original_max_position_embeddings": 32,
        },
    )
    model = Qwen2ForCausalLM(cfg)
    model.eval()
    d = tmp_path / "qwen2yarn"
    model.save_pretrained(d, safe_serialization=True)
    jcfg, params = load_decoder(str(d), dtype=jnp.float32)
    assert jcfg.rope_scaling[0] == "yarn"
    ids = np.asarray(np.random.default_rng(5).integers(1, 128, (1, 80)), np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, jcfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=5e-4, rtol=1e-3)


def test_phi3_matches_hf(tmp_path):
    """Phi-3: fused qkv_proj / gate_up_proj split at load time."""
    import torch
    from transformers import Phi3Config, Phi3ForCausalLM

    cfg = Phi3Config(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        pad_token_id=0,  # Phi3Config defaults to 32000, past this tiny vocab
    )
    model = Phi3ForCausalLM(cfg)
    model.eval()
    d = tmp_path / "phi3"
    model.save_pretrained(d, safe_serialization=True)
    jcfg, params = load_decoder(str(d), dtype=jnp.float32)
    ids = np.array([[1, 5, 9, 17, 3, 25, 7, 2]], np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, jcfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=3e-4, rtol=1e-3)


def test_unsupported_decoder_family_rejected(tiny_gemma_dir, tmp_path):
    """gemma-2 etc. would load without error but mis-compute; reject up front."""
    import json
    import shutil

    d, _ = tiny_gemma_dir
    bad = tmp_path / "fake_gemma2"
    shutil.copytree(d, bad)
    cfg = json.loads((bad / "config.json").read_text())
    cfg["model_type"] = "gemma2"
    (bad / "config.json").write_text(json.dumps(cfg))
    with pytest.raises(ValueError, match="unsupported decoder model_type"):
        load_decoder(str(bad))


def test_gemma_prefill_decode_matches_forward(tiny_gemma_dir):
    d, _ = tiny_gemma_dir
    cfg, params = load_decoder(d, dtype=jnp.float32)
    prompt = np.array([[1, 5, 9, 17, 3]], np.int32)
    seq = prompt.copy()
    for _ in range(4):
        logits = llama.forward(params, cfg, jnp.asarray(seq))
        seq = np.concatenate([seq, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
    expected = seq[0, prompt.shape[1]:].tolist()

    cache = llama.init_cache(cfg, batch=1, max_len=32, dtype=jnp.float32)
    lengths = jnp.asarray([prompt.shape[1]], jnp.int32)
    logits, ks, vs = llama.prefill(params, cfg, jnp.asarray(prompt), lengths)
    cache = llama.insert_sequences(cache, ks, vs, lengths, jnp.asarray([0], jnp.int32))
    got = [int(jnp.argmax(logits[0]))]
    for _ in range(3):
        logits, cache = llama.decode_step(
            params, cfg, jnp.asarray([got[-1]], jnp.int32), cache
        )
        got.append(int(jnp.argmax(logits[0])))
    assert got == expected


def test_qwen2_prefill_decode_matches_forward(tiny_qwen2_dir):
    """The decode_step bias path must agree with the full forward."""
    d, _ = tiny_qwen2_dir
    cfg, params = load_decoder(d, dtype=jnp.float32)
    prompt = np.array([[1, 5, 9, 17, 3]], np.int32)
    seq = prompt.copy()
    for _ in range(4):
        logits = llama.forward(params, cfg, jnp.asarray(seq))
        seq = np.concatenate([seq, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
    expected = seq[0, prompt.shape[1]:].tolist()

    cache = llama.init_cache(cfg, batch=1, max_len=32, dtype=jnp.float32)
    lengths = jnp.asarray([prompt.shape[1]], jnp.int32)
    logits, ks, vs = llama.prefill(params, cfg, jnp.asarray(prompt), lengths)
    cache = llama.insert_sequences(cache, ks, vs, lengths, jnp.asarray([0], jnp.int32))
    got = [int(jnp.argmax(logits[0]))]
    for _ in range(3):
        logits, cache = llama.decode_step(
            params, cfg, jnp.asarray([got[-1]], jnp.int32), cache
        )
        got.append(int(jnp.argmax(logits[0])))
    assert got == expected


def test_prefill_decode_matches_forward(tiny_llama_dir):
    """Greedy generation via prefill+decode must equal repeated full forwards."""
    d, _ = tiny_llama_dir
    cfg, params = load_decoder(d, dtype=jnp.float32)
    prompt = np.array([[1, 5, 9, 17, 3]], np.int32)
    n_new = 6

    # ground truth: repeated full forward, greedy
    seq = prompt.copy()
    for _ in range(n_new):
        logits = llama.forward(params, cfg, jnp.asarray(seq))
        nxt = int(jnp.argmax(logits[0, -1]))
        seq = np.concatenate([seq, [[nxt]]], axis=1)
    expected = seq[0, prompt.shape[1]:].tolist()

    # engine path: prefill into slot 0 of a 2-slot cache, then decode steps
    cache = llama.init_cache(cfg, batch=2, max_len=32, dtype=jnp.float32)
    lengths = jnp.asarray([prompt.shape[1]], jnp.int32)
    logits, ks, vs = llama.prefill(params, cfg, jnp.asarray(prompt), lengths)
    cache = llama.insert_sequences(cache, ks, vs, lengths, jnp.asarray([0], jnp.int32))
    got = []
    tok = int(jnp.argmax(logits[0]))
    got.append(tok)
    tokens = jnp.zeros((2,), jnp.int32)
    active = jnp.asarray([True, False])
    for _ in range(n_new - 1):
        tokens = tokens.at[0].set(tok)
        logits, cache = llama.decode_step(params, cfg, tokens, cache, active=active)
        tok = int(jnp.argmax(logits[0]))
        got.append(tok)
    assert got == expected


def test_sharded_forward_matches_single_device(tiny_llama_dir, mesh8):
    from django_assistant_bot_tpu.models.llama import logical_axes
    from django_assistant_bot_tpu.parallel import shard_pytree

    d, _ = tiny_llama_dir
    cfg, params = load_decoder(d, dtype=jnp.float32)
    ids = jnp.asarray(np.random.default_rng(1).integers(1, 100, (4, 16)), jnp.int32)
    ref = np.asarray(llama.forward(params, cfg, ids))

    with mesh8:
        sharded = shard_pytree(params, logical_axes(cfg), mesh8)
        out = jax.jit(lambda p, i: llama.forward(p, cfg, i))(sharded, ids)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=1e-3)


def test_moe_forward_matches_hf_mixtral(tmp_path):
    """Capacity set high enough that no token drops -> exact parity with HF."""
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    hf_cfg = MixtralConfig(
        vocab_size=128,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=64,
        num_local_experts=4,
        num_experts_per_tok=2,
        rope_theta=10000.0,
        max_position_embeddings=128,
    )
    model = MixtralForCausalLM(hf_cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, params = load_decoder(str(tmp_path), dtype=jnp.float32)
    # no-drop capacity: every token could route to the same expert
    cfg = DecoderConfig(**{**cfg.__dict__, "expert_capacity_factor": float(cfg.num_experts)})
    assert cfg.is_moe
    ids = np.array([[1, 5, 9, 17, 3, 25]], np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    ours = np.asarray(llama.forward(params, cfg, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, hf_logits, atol=5e-4, rtol=1e-3)
