"""Serving plane: continuous-batching engine semantics + HTTP contract parity.

The HTTP tests assert the exact reference gpu_service contract
(reference: gpu_service/main.py:75-107): request/response field names, 400 on
unknown model, trailing-slash paths.
"""

import asyncio
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.serving import (
    ByteTokenizer,
    EmbeddingEngine,
    GenerationEngine,
    ModelRegistry,
)
from django_assistant_bot_tpu.serving.server import create_app


@pytest.fixture(scope="module")
def tiny_gen_engine():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=96
    ).start()
    yield eng, cfg, params
    eng.stop()


def test_engine_greedy_matches_forward(tiny_gen_engine):
    """Greedy engine output == repeated full-forward argmax (continuous batching
    must not change the math)."""
    eng, cfg, params = tiny_gen_engine
    tok = ByteTokenizer()
    prompt = tok.encode("hello world")
    n_new = 5

    seq = np.asarray([prompt], np.int32)
    expected = []
    for _ in range(n_new):
        logits = llama.forward(params, cfg, jnp.asarray(seq))
        nxt = int(jnp.argmax(logits[0, -1]))
        expected.append(nxt)
        seq = np.concatenate([seq, [[nxt]]], axis=1)

    fut = eng.submit(prompt, max_tokens=n_new, temperature=0.0)
    result = fut.result(timeout=120)
    assert result.token_ids == expected
    assert result.prompt_tokens == len(prompt)
    assert result.completion_tokens == n_new
    assert result.length_limited  # no EOS in 5 greedy tokens of a random model


@pytest.mark.slow
def test_engine_concurrent_requests_batch(tiny_gen_engine):
    """Multiple in-flight requests share the decode loop and all complete; greedy
    determinism holds under batching (each request unaffected by slot-mates)."""
    eng, cfg, params = tiny_gen_engine
    tok = ByteTokenizer()
    prompts = [tok.encode(t) for t in ["aa", "bbbb", "cc dd ee", "f", "gg hh", "iii"]]
    futs = [eng.submit(p, max_tokens=6, temperature=0.0) for p in prompts]
    results = [f.result(timeout=120) for f in futs]

    for p, r in zip(prompts, results):
        seq = np.asarray([p], np.int32)
        for _ in range(6):
            logits = llama.forward(params, cfg, jnp.asarray(seq))
            seq = np.concatenate([seq, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
        assert r.token_ids == seq[0, len(p):].tolist()
    assert eng.num_active == 0


def test_engine_length_limit_on_full_cache(tiny_gen_engine):
    eng, cfg, params = tiny_gen_engine
    prompt = list(range(1, 90))  # near max_seq_len=96
    r = eng.submit(prompt, max_tokens=1000, temperature=0.0).result(timeout=120)
    assert r.length_limited
    assert len(prompt) + r.completion_tokens <= 96


def test_engine_long_prompt_truncated(tiny_gen_engine):
    eng, *_ = tiny_gen_engine
    r = eng.submit(list(range(1, 200)), max_tokens=2, temperature=0.0).result(timeout=120)
    assert r.prompt_tokens <= 95


def test_engine_fails_active_requests_and_recovers():
    """A device-step exception triggers a crash-only restart, and a request
    that had emitted NO tokens yet is transparently re-submitted: its future
    completes normally after the restart (docs/RESILIENCE.md).  The engine
    stays serviceable with a rebuilt cache."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(1))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64
    ).start()
    try:
        orig = eng._decode_tick
        state = {"armed": True}

        def boom(*args, **kwargs):
            if state.pop("armed", False):
                raise RuntimeError("injected device failure")
            return orig(*args, **kwargs)

        eng._decode_tick = boom
        # the fault fires on the FIRST decode tick — before any token reached
        # the host — so the request is salvageable and must survive the crash
        fut = eng.submit([1, 2, 3], max_tokens=5, temperature=0.0)
        res = fut.result(timeout=120)
        assert len(res.token_ids) == 5
        assert eng.engine_restarts == 1
        assert eng.supervision_stats()["restarted_requests_resubmitted"] == 1
        # engine healed itself (fresh cache, cleared slots): next request works
        res = eng.submit([1, 2, 3], max_tokens=5, temperature=0.0).result(timeout=120)
        assert len(res.token_ids) == 5
        assert eng.engine_restarts == 1  # no further restarts
    finally:
        eng.stop()


def test_wave_prefill_failure_salvages_every_unstarted_group():
    """A wave split into seq-bucket groups: if an early group's prefill raises,
    the later groups' requests must not hang unresolved — the crash-only
    restart re-submits every not-yet-slotted request (no tokens were emitted),
    so BOTH futures complete normally after one restart."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(2))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=96,
        prefill_buckets=(32, 64),
    )
    # enqueue directly (submit() pre-start intentionally fails fast) so both
    # requests land in ONE admission wave, split into two seq-bucket groups
    import time as _time
    from concurrent.futures import Future

    from django_assistant_bot_tpu.serving.engine import _Request

    fut_short: Future = Future()
    fut_long: Future = Future()
    for ids, fut in (([1, 2, 3], fut_short), (list(range(1, 41)), fut_long)):
        eng._queue.put(
            _Request(
                prompt_ids=ids,
                max_tokens=4,
                temperature=0.0,
                top_p=0.95,
                future=fut,
                submitted_at=_time.monotonic(),
            )
        )
    state = {"armed": True}
    orig = eng._prefill

    def boom(*args, **kwargs):
        if state.pop("armed", False):
            raise RuntimeError("injected prefill failure")
        return orig(*args, **kwargs)

    eng._prefill = boom
    eng.start()
    try:
        assert len(fut_short.result(timeout=120).token_ids) == 4
        assert len(fut_long.result(timeout=120).token_ids) == 4
        assert eng.engine_restarts == 1
        # engine recovered; new requests serve normally
        res = eng.submit([1, 2, 3], max_tokens=4, temperature=0.0).result(timeout=120)
        assert len(res.token_ids) == 4
    finally:
        eng.stop()


def test_serve_cli_warmup_flag(monkeypatch):
    """--warmup forces warmup=true onto every model spec before load."""
    import argparse

    from django_assistant_bot_tpu.cli import serve as serve_cli

    captured = {}

    class FakeRegistry:
        @classmethod
        def from_config(cls, config, mesh=None):
            captured.update(config)
            return cls()

    monkeypatch.setattr(
        "django_assistant_bot_tpu.serving.registry.ModelRegistry", FakeRegistry
    )
    monkeypatch.setattr(
        "django_assistant_bot_tpu.serving.server.run_server",
        lambda host, port, registry, drain_deadline_s=30.0: None,
    )
    args = argparse.Namespace(
        config=None, host="0.0.0.0", port=0, tiny=True, warmup=True
    )
    assert serve_cli.run(args) == 0
    assert captured and all(spec["warmup"] for spec in captured.values())


def test_embedding_engine_batches_and_coalesces():
    from django_assistant_bot_tpu.models import EncoderConfig, encoder

    cfg = EncoderConfig.tiny()
    params = encoder.init(cfg, jax.random.key(1))
    eng = EmbeddingEngine(cfg, params, ByteTokenizer(), max_batch=8, normalize=True).start()
    try:
        async def go():
            return await asyncio.gather(
                eng.embed(["alpha", "beta"]),
                eng.embed(["gamma"]),
                eng.embed(["delta", "epsilon", "zeta"]),
            )

        r1, r2, r3 = asyncio.run(go())
        assert len(r1) == 2 and len(r2) == 1 and len(r3) == 3
        for v in r1 + r2 + r3:
            assert len(v) == cfg.hidden_size
            assert abs(np.linalg.norm(v) - 1.0) < 1e-4
        # same text embeds identically regardless of batch-mates
        solo = eng.embed_sync(["beta"])[0]
        np.testing.assert_allclose(solo, r1[1], atol=1e-5)
    finally:
        eng.stop()


@pytest.mark.slow
def test_chunked_prefill_matches_forward():
    """Long prompts prefill chunk-by-chunk; greedy output must equal the
    full-forward reference exactly (disaggregation must not change the math)."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(3))
    tok = ByteTokenizer()
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=128, chunk_size=16
    ).start()
    try:
        prompt = tok.encode("the quick brown fox jumps over the lazy dog again")
        assert len(prompt) > 3 * 16  # several chunks + a ragged tail
        n_new = 5
        seq = np.asarray([prompt], np.int32)
        expected = []
        for _ in range(n_new):
            logits = llama.forward(params, cfg, jnp.asarray(seq))
            nxt = int(jnp.argmax(logits[0, -1]))
            expected.append(nxt)
            seq = np.concatenate([seq, [[nxt]]], axis=1)
        r = eng.submit(prompt, max_tokens=n_new, temperature=0.0).result(timeout=300)
        assert r.token_ids == expected
    finally:
        eng.stop()


@pytest.mark.slow
def test_chunked_prefill_ragged_tail_near_cache_end():
    """Prompt length not a multiple of chunk_size, close to max_seq_len: the final
    chunk slides left instead of writing past the cache end (which would silently
    clamp and corrupt earlier positions)."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(5))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=120, chunk_size=16
    ).start()
    try:
        prompt = [(i % 200) + 1 for i in range(99)]  # 6 full chunks + slid tail
        n_new = 5
        seq = np.asarray([prompt], np.int32)
        expected = []
        for _ in range(n_new):
            logits = llama.forward(params, cfg, jnp.asarray(seq))
            nxt = int(jnp.argmax(logits[0, -1]))
            expected.append(nxt)
            seq = np.concatenate([seq, [[nxt]]], axis=1)
        r = eng.submit(prompt, max_tokens=n_new, temperature=0.0).result(timeout=300)
        assert r.token_ids == expected
    finally:
        eng.stop()


def test_chunked_prefill_interleaves_with_decode():
    """Decode ticks keep running while a long prefill is in flight: a short
    request admitted alongside a many-chunk prompt finishes before the long
    request produces its first token."""
    import time as _time

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(4))
    tok = ByteTokenizer()
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=200, chunk_size=8
    ).start()
    try:
        # warm the compile caches so timing reflects steady-state interleaving
        eng.submit(tok.encode("warm"), max_tokens=2, temperature=0.0).result(timeout=300)
        eng.submit(list(range(1, 30)), max_tokens=2, temperature=0.0).result(timeout=300)

        t0 = _time.monotonic()
        f_short = eng.submit(tok.encode("hi"), max_tokens=4, temperature=0.0)
        t1 = _time.monotonic()
        f_long = eng.submit(list(range(1, 121)), max_tokens=2, temperature=0.0)  # 15 chunks
        rs = f_short.result(timeout=300)
        rl = f_long.result(timeout=300)
        short_end_abs = t0 + rs.latency_s
        long_first_tok_abs = t1 + rl.ttft_s
        assert short_end_abs < long_first_tok_abs, (rs, rl)
    finally:
        eng.stop()


@pytest.mark.slow
def test_sharded_engine_matches_single_device(tiny_gen_engine, mesh8):
    """North-star check (VERDICT r1 #1): the generation engine running under the
    mesh — sharded params AND sharded KV cache — produces the same greedy tokens
    as the single-device engine, token for token."""
    from django_assistant_bot_tpu.models.llama import logical_axes
    from django_assistant_bot_tpu.parallel import shard_pytree
    from django_assistant_bot_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    eng0, cfg, params = tiny_gen_engine
    tok = ByteTokenizer()
    prompts = [tok.encode(t) for t in ["hello world", "sharded serving", "x"]]
    ref = [
        eng0.submit(p, max_tokens=6, temperature=0.0).result(timeout=120).token_ids
        for p in prompts
    ]

    with mesh8:
        sharded = shard_pytree(params, logical_axes(cfg), mesh8)
    eng = GenerationEngine(
        cfg, sharded, tok, max_slots=4, max_seq_len=96, mesh=mesh8
    ).start()
    try:
        # the cache itself must be sharded: kv_heads over `model`, slots over `data`
        spec = eng._cache.k.sharding.spec
        assert MODEL_AXIS in spec and DATA_AXIS in spec
        futs = [eng.submit(p, max_tokens=6, temperature=0.0) for p in prompts]
        got = [f.result(timeout=300).token_ids for f in futs]
    finally:
        eng.stop()
    assert got == ref


@pytest.mark.slow
def test_moe_engine_sharded_generate_matches_single_device():
    """Config-5 path (Mixtral-style MoE continuous batching): the engine serving a
    MoE decoder under a (data, model, expert) mesh matches single-device greedy."""
    from django_assistant_bot_tpu.models.llama import logical_axes
    from django_assistant_bot_tpu.parallel import best_mesh_shape, make_mesh, shard_pytree
    from django_assistant_bot_tpu.parallel.mesh import EXPERT_AXIS

    cfg = DecoderConfig.tiny(num_experts=4)
    params = llama.init(cfg, jax.random.key(6))
    tok = ByteTokenizer()
    prompts = [tok.encode(t) for t in ["mixture of experts", "routing"]]

    eng0 = GenerationEngine(cfg, params, tok, max_slots=2, max_seq_len=96).start()
    try:
        ref = [
            eng0.submit(p, max_tokens=5, temperature=0.0).result(timeout=300).token_ids
            for p in prompts
        ]
    finally:
        eng0.stop()

    mesh = make_mesh(best_mesh_shape(8, want_model=2, want_expert=2))
    assert mesh.shape[EXPERT_AXIS] == 2
    with mesh:
        sharded = shard_pytree(params, logical_axes(cfg), mesh)
    eng = GenerationEngine(
        cfg, sharded, tok, max_slots=2, max_seq_len=96, mesh=mesh
    ).start()
    try:
        futs = [eng.submit(p, max_tokens=5, temperature=0.0) for p in prompts]
        got = [f.result(timeout=300).token_ids for f in futs]
    finally:
        eng.stop()
    assert got == ref


def test_sharded_embedding_engine_matches_single_device(mesh8):
    from django_assistant_bot_tpu.models import EncoderConfig, encoder
    from django_assistant_bot_tpu.parallel import shard_pytree

    cfg = EncoderConfig.tiny()
    params = encoder.init(cfg, jax.random.key(1))
    texts = ["alpha", "beta gamma", "delta"]

    eng0 = EmbeddingEngine(cfg, params, ByteTokenizer(), normalize=True).start()
    try:
        ref = eng0.embed_sync(texts)
    finally:
        eng0.stop()

    with mesh8:
        sharded = shard_pytree(params, encoder.logical_axes(cfg), mesh8)
    eng = EmbeddingEngine(
        cfg, sharded, ByteTokenizer(), normalize=True, mesh=mesh8
    ).start()
    try:
        got = eng.embed_sync(texts)
    finally:
        eng.stop()
    np.testing.assert_allclose(got, ref, atol=1e-5)


@pytest.fixture(scope="module")
def http_client():
    from aiohttp.test_utils import TestClient, TestServer

    loop = asyncio.new_event_loop()
    registry = ModelRegistry.from_config(
        {
            "tiny-emb": {"kind": "encoder", "tiny": True, "normalize": True},
            "tiny-chat": {"kind": "decoder", "tiny": True, "max_slots": 2, "max_seq_len": 64},
        }
    )
    client = TestClient(TestServer(create_app(registry)), loop=loop)
    loop.run_until_complete(client.start_server())
    yield loop, client
    loop.run_until_complete(client.close())
    loop.close()


def test_http_embeddings_contract(http_client):
    loop, client = http_client

    async def go():
        resp = await client.post(
            "/embeddings/", json={"model": "Tiny-EMB", "texts": ["hello", "world"]}
        )
        assert resp.status == 200
        data = await resp.json()
        assert set(data) == {"embeddings"}
        assert len(data["embeddings"]) == 2

        resp = await client.post("/embeddings/", json={"model": "nope", "texts": ["x"]})
        assert resp.status == 400
        assert (await resp.json())["detail"] == "Model is not supported"

        resp = await client.post("/embeddings/", json={"texts": ["x"]})
        assert resp.status == 422

    loop.run_until_complete(go())


@pytest.mark.slow
def test_http_dialog_contract(http_client):
    loop, client = http_client

    async def go():
        resp = await client.post(
            "/dialog/",
            json={
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4,
                "json_format": False,
            },
        )
        assert resp.status == 200
        data = await resp.json()
        r = data["response"]
        assert set(r) >= {"result", "usage", "length_limited"}
        assert isinstance(r["result"], str)
        assert r["usage"]["completion_tokens"] <= 4
        assert r["usage"]["total_tokens"] == (
            r["usage"]["prompt_tokens"] + r["usage"]["completion_tokens"]
        )

        resp = await client.post(
            "/dialog/", json={"model": "missing", "messages": [], "max_tokens": 1}
        )
        assert resp.status == 400

    loop.run_until_complete(go())


def test_http_healthz_and_models(http_client):
    loop, client = http_client

    async def go():
        resp = await client.get("/healthz")
        assert resp.status == 200
        data = await resp.json()
        assert data["status"] == "ok"
        assert "tiny-chat" in data["models"]

        resp = await client.get("/models")
        assert (await resp.json())["tiny-emb"]["kind"] == "encoder"

    loop.run_until_complete(go())


def _llama3_style_tokenizer():
    """A tiny tokenizer with the REAL Llama-3 chat template: char-level vocab,
    the four Llama-3 specials, and (like Meta's shipped fast tokenizer) a
    post-processor that prepends BOS on ordinary encode() calls — the exact
    setup where naive template encoding produces a double BOS."""
    from tokenizers import Regex, Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Split
    from tokenizers.processors import TemplateProcessing
    from transformers import PreTrainedTokenizerFast

    chars = (
        "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"
        " !?.,:'0123456789\n"
    )
    vocab = {"<unk>": 0}
    for c in chars:
        vocab[c] = len(vocab)
    t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = Split(Regex("[\\s\\S]"), behavior="isolated")
    from tokenizers.decoders import Fuse

    t.decoder = Fuse()
    bos = "<|begin_of_text|>"
    t.add_special_tokens([bos, "<|start_header_id|>", "<|end_header_id|>", "<|eot_id|>"])
    t.post_processor = TemplateProcessing(
        single=f"{bos} $A",
        pair=f"{bos} $A $B",
        special_tokens=[(bos, t.token_to_id(bos))],
    )
    hf = PreTrainedTokenizerFast(
        tokenizer_object=t,
        unk_token="<unk>",
        bos_token=bos,
        eos_token="<|eot_id|>",
        additional_special_tokens=["<|start_header_id|>", "<|end_header_id|>"],
    )
    # Meta's Llama-3/3.1 chat template (tokenizer_config.json of the family)
    hf.chat_template = (
        "{% set loop_messages = messages %}"
        "{% for message in loop_messages %}"
        "{% set content = '<|start_header_id|>' + message['role'] + "
        "'<|end_header_id|>\n\n' + message['content'] | trim + '<|eot_id|>' %}"
        "{% if loop.index0 == 0 %}{% set content = bos_token + content %}{% endif %}"
        "{{ content }}{% endfor %}"
        "{% if add_generation_prompt %}"
        "{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}{% endif %}"
    )
    return hf


def test_llama3_chat_template_golden_tokens():
    """encode_chat must produce EXACTLY the token sequence HF's own
    apply_chat_template(tokenize=True) yields for the Llama-3 template — and
    exactly one BOS.  The reference never chat-templates at all (it joins
    'role: content' lines, assistant/ai/providers/transformers.py:50); this
    pins the behavior that replaces that deficiency."""
    from django_assistant_bot_tpu.serving.tokenizer import HFTokenizer

    hf = _llama3_style_tokenizer()
    wrapped = HFTokenizer(hf)
    msgs = [
        {"role": "system", "content": "You are a bot."},
        {"role": "user", "content": "Hello there!"},
    ]
    golden = hf.apply_chat_template(msgs, tokenize=True, add_generation_prompt=True)
    ours = wrapped.encode_chat(msgs)
    assert ours == golden
    bos_id = hf.convert_tokens_to_ids("<|begin_of_text|>")
    assert ours[0] == bos_id
    assert ours.count(bos_id) == 1
    # the hazard is real: naive encode() of the rendered template doubles BOS
    naive = hf.encode(wrapped.apply_chat(msgs))
    assert naive[:2] == [bos_id, bos_id]
    # structure: exactly 3 headers (system, user, generation prompt), 2 eots
    sh = hf.convert_tokens_to_ids("<|start_header_id|>")
    eot = hf.convert_tokens_to_ids("<|eot_id|>")
    assert ours.count(sh) == 3
    assert ours.count(eot) == 2
    # round-trip sanity: specials drop, text survives
    assert "You are a bot." in wrapped.decode(ours)


def test_chat_template_absent_falls_back_to_plain_join():
    """No chat_template -> the reference's 'role: content' join semantics
    (assistant/ai/providers/transformers.py:50), BOS added normally."""
    from django_assistant_bot_tpu.serving.tokenizer import HFTokenizer, render_plain_chat

    hf = _llama3_style_tokenizer()
    hf.chat_template = None
    wrapped = HFTokenizer(hf)
    msgs = [{"role": "user", "content": "hi"}]
    assert wrapped.apply_chat(msgs) == "user: hi\nassistant:"
    assert wrapped.encode_chat(msgs) == hf.encode(render_plain_chat(msgs))


# ------------------------------------------------------------- prefix KV cache
@pytest.mark.slow
def test_prefill_suffix_matches_full_prefill():
    """insert_prefix + prefill_suffix must produce the same logits and cache
    state as one monolithic prefill of prefix+suffix (the prefix cache must
    not change the math)."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(7))
    rng = np.random.default_rng(11)
    P, C, S = 24, 8, 64
    prefix = rng.integers(1, 255, P).tolist()
    suffixes = [rng.integers(1, 255, C).tolist() for _ in range(2)]

    # reference: monolithic prefill of each full prompt
    full_ids = np.asarray([prefix + s for s in suffixes], np.int32)
    lengths = np.full((2,), P + C, np.int32)
    ref_logits, ref_ks, ref_vs = llama.prefill(
        params, cfg, jnp.asarray(full_ids), jnp.asarray(lengths)
    )

    # prefix path: prefill the prefix once, extract, insert into fresh slots,
    # then batched suffix prefill
    p_logits, p_ks, p_vs = llama.prefill(
        params, cfg, jnp.asarray([prefix], np.int32), jnp.asarray([P], np.int32)
    )
    cache = llama.init_cache(cfg, 3, S)
    cache = llama.insert_sequences(
        cache, p_ks, p_vs, jnp.asarray([P], np.int32), jnp.asarray([0], np.int32)
    )
    pk, pv = llama.extract_prefix(cache, jnp.asarray(0, jnp.int32), P)
    for slot in (1, 2):
        cache = llama.insert_prefix(cache, pk, pv, jnp.asarray(slot, jnp.int32))
    suffix_ids = jnp.asarray(suffixes, np.int32)
    logits, cache = llama.prefill_suffix(
        params,
        cfg,
        suffix_ids,
        cache,
        jnp.asarray([1, 2], np.int32),
        jnp.asarray([P, P], np.int32),
        jnp.asarray([C, C], np.int32),
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    assert np.asarray(cache.lengths)[1:3].tolist() == [P + C, P + C]
    # cache K/V of the suffix region must match the monolithic prefill's
    for slot, row in ((1, 0), (2, 1)):
        np.testing.assert_allclose(
            np.asarray(cache.k[:, slot, :, : P + C]),
            np.asarray(ref_ks[:, row, :, : P + C]),
            rtol=2e-4,
            atol=2e-4,
        )


@pytest.mark.slow
def test_engine_prefix_cache_hit_matches_uncached():
    """Greedy decode through the prefix cache == greedy decode without it,
    and the second same-prefix request is served from the cache."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(9))
    tok = ByteTokenizer()
    system = "You are a terse assistant who answers from provided context only. "
    prompts = [
        [{"role": "system", "content": system}, {"role": "user", "content": u}]
        for u in ("What is a TPU?", "Where do MXUs live?")
    ]
    n_new = 5

    def run(prefix_size):
        eng = GenerationEngine(
            cfg,
            params,
            tok,
            max_slots=2,
            max_seq_len=128,
            prefix_cache_size=prefix_size,
            prefix_min_tokens=8,
        ).start()
        try:
            outs = []
            for msgs in prompts:  # sequential: the 2nd request must hit
                r = asyncio.run(eng.generate(msgs, max_tokens=n_new, temperature=0.0))
                outs.append(r.token_ids)
            return outs, eng.prefix_hits, eng.prefix_misses
        finally:
            eng.stop()

    base, h0, m0 = run(0)
    cached, h1, m1 = run(8)
    assert cached == base
    assert h0 == 0 and m0 == 0  # disabled path keeps no stats
    assert m1 >= 1 and h1 >= 1  # first request registers, second hits


@pytest.mark.slow
def test_engine_prefix_cache_concurrent_wave():
    """A concurrent wave mixing cache hits and misses (suffix + full groups in
    one admission) stays correct under greedy decoding."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(9))
    tok = ByteTokenizer()
    system = "Answer from context: context-block-alpha beta gamma delta. "
    msgs = lambda u: [
        {"role": "system", "content": system},
        {"role": "user", "content": u},
    ]
    users = ["q one?", "q two?", "q three?", "q four?"]
    n_new = 4

    eng = GenerationEngine(
        cfg, params, tok, max_slots=4, max_seq_len=128,
        prefix_cache_size=8, prefix_min_tokens=8,
    ).start()
    try:
        # prime the cache so the wave below contains hits
        asyncio.run(eng.generate(msgs("prime"), max_tokens=2, temperature=0.0))

        async def fire_all():
            return await asyncio.gather(
                *(eng.generate(msgs(u), max_tokens=n_new, temperature=0.0) for u in users)
            )

        got = [r.token_ids for r in asyncio.run(fire_all())]
    finally:
        eng.stop()

    # reference: plain engine without prefix caching
    eng2 = GenerationEngine(
        cfg, params, tok, max_slots=4, max_seq_len=128, prefix_cache_size=0
    ).start()
    try:
        async def fire_all2():
            return await asyncio.gather(
                *(eng2.generate(msgs(u), max_tokens=n_new, temperature=0.0) for u in users)
            )

        want = [r.token_ids for r in asyncio.run(fire_all2())]
    finally:
        eng2.stop()
    assert got == want


def test_encode_chat_split_byte_tokenizer():
    from django_assistant_bot_tpu.serving.tokenizer import encode_chat_split

    tok = ByteTokenizer()
    msgs = [
        {"role": "system", "content": "sys prompt"},
        {"role": "user", "content": "hello"},
    ]
    ids, n = encode_chat_split(tok, msgs)
    assert ids == tok.encode_chat(msgs)
    assert 0 < n < len(ids)
    # the prefix must cover the system message but none of the user turn
    assert tok.decode(ids[:n]).endswith("sys prompt\n")
    # single message: nothing shareable
    ids1, n1 = encode_chat_split(tok, msgs[-1:])
    assert n1 == 0 and ids1 == tok.encode_chat(msgs[-1:])


@pytest.mark.slow
def test_probe_decode_and_tick_stats():
    """probe_decode measures idle-engine step time without corrupting state;
    tick_stats accumulates the per-tick breakdown after real traffic."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(3))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
        prefix_cache_size=0,
    ).start()
    try:
        step_s = eng.probe_decode(iters=2)
        assert step_s > 0
        # the probe must leave the engine fully serviceable
        r = asyncio.run(
            eng.generate([{"role": "user", "content": "hi"}], max_tokens=3,
                         temperature=0.0)
        )
        assert len(r.token_ids) == 3
        stats = eng.tick_stats()
        assert stats["ticks"] >= 1
        assert stats["issue_ms"] >= 0 and stats["block_ms"] >= 0
    finally:
        eng.stop()
    # probing with in-flight work must be refused (it would race the loop);
    # exercised on a stopped engine so the fake tick can't reach the loop
    eng._inflight.append(object())
    with pytest.raises(RuntimeError, match="idle"):
        eng.probe_decode(iters=1)


def test_prefix_cache_byte_cap_and_bucket():
    """Prefix device shape never falls back to max_seq_len (the ~1 GB/entry
    pinning at 8B geometry), and the byte budget LRU-evicts."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(5))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=512,
        prefill_buckets=(32, 64), chunk_size=64,
        prefix_cache_size=8, prefix_min_tokens=8,
        kv_layout="legacy",  # this test pins the legacy pinned-K/V LRU path
    )
    # bucket: fits a prefill bucket -> that bucket; else multiples of the
    # largest bucket, capped at the engine's (cfg-clamped) max_seq_len —
    # never the raw max_seq_len fallback for short prefixes
    assert eng._prefix_bucket(20) == 32
    assert eng._prefix_bucket(64) == 64
    assert eng._prefix_bucket(65) == 128
    assert eng._prefix_bucket(130) == 192
    assert eng._prefix_bucket(10_000) == eng.max_seq_len

    eng.start()
    try:
        sys_a = "context block alpha " * 4
        sys_b = "context block beta " * 4
        for s in (sys_a, sys_b):
            asyncio.run(eng.generate(
                [{"role": "system", "content": s}, {"role": "user", "content": "q"}],
                max_tokens=2, temperature=0.0,
            ))
        assert len(eng._prefix_lru) == 2
        assert eng._prefix_bytes == sum(
            e.pk.nbytes + e.pv.nbytes for e in eng._prefix_lru.values()
        )
        # shrink the budget below one entry: next registration evicts to fit
        one = next(iter(eng._prefix_lru.values()))
        eng.prefix_cache_max_bytes = one.pk.nbytes + one.pv.nbytes
        asyncio.run(eng.generate(
            [{"role": "system", "content": "context block gamma " * 4},
             {"role": "user", "content": "q"}],
            max_tokens=2, temperature=0.0,
        ))
        assert len(eng._prefix_lru) == 1
        assert eng._prefix_bytes <= eng.prefix_cache_max_bytes
    finally:
        eng.stop()


def test_encode_chat_split_memoizes_head_encoding():
    """The shared head's encode is cached on the tokenizer (the prefix-KV
    workload re-sends a near-identical multi-KB head every turn)."""
    from django_assistant_bot_tpu.serving.tokenizer import encode_chat_split

    class CountingTok(ByteTokenizer):
        def __init__(self):
            super().__init__()
            self.encodes = 0

        def encode(self, text):
            self.encodes += 1
            return super().encode(text)

    tok = CountingTok()
    msgs = [
        {"role": "system", "content": "ctx " * 50},
        {"role": "user", "content": "q1"},
    ]
    ids1, n1 = encode_chat_split(tok, msgs)
    first = tok.encodes
    msgs2 = [msgs[0], {"role": "user", "content": "q2"}]
    ids2, n2 = encode_chat_split(tok, msgs2)
    assert n1 == n2 > 0
    # second call re-encoded the full prompt but served the head from cache
    assert tok.encodes == first + 1


def test_engine_declares_dead_when_recovery_fails():
    """If the post-failure cache rebuild ALSO fails (e.g. the original fault
    was an OOM), the engine must die cleanly: queued futures fail, the loop
    exits, and later submits fail fast instead of enqueueing forever."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(1))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64
    ).start()
    try:
        def tick_boom(*a, **k):
            raise RuntimeError("injected device failure")

        def rebuild_boom(*a, **k):
            raise RuntimeError("injected rebuild failure")

        eng._decode_tick = tick_boom
        eng._fresh_cache = rebuild_boom
        fut = eng.submit([1, 2, 3], max_tokens=5, temperature=0.0)
        with pytest.raises(RuntimeError):
            fut.result(timeout=120)
        # loop exited via the dead-engine path; the thread drains and stops
        for _ in range(500):
            if not eng._running and not (eng._thread and eng._thread.is_alive()):
                break
            time.sleep(0.01)
        assert not eng._running
        # post-death submits fail fast (no eternal enqueue)
        fut2 = eng.submit([1, 2, 3], max_tokens=5, temperature=0.0)
        with pytest.raises(RuntimeError, match="stopped"):
            fut2.result(timeout=10)
    finally:
        eng.stop()


@pytest.mark.slow
def test_engine_fp8_kv_cache_serves():
    """fp8 slot cache: halves KV bytes, serves correctly (lossy but close —
    decode_step logits track the bf16-cache engine's), prefix cache included."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(13))
    tok = ByteTokenizer()
    msgs = [
        {"role": "system", "content": "shared system preamble for the cache"},
        {"role": "user", "content": "tell me about tpus"},
    ]

    def run(kv_dtype):
        eng = GenerationEngine(
            cfg, params, tok, max_slots=2, max_seq_len=128,
            prefix_cache_size=4, prefix_min_tokens=8, kv_cache_dtype=kv_dtype,
        ).start()
        try:
            outs = []
            for _ in range(2):  # second request exercises the fp8 prefix cache
                r = asyncio.run(eng.generate(msgs, max_tokens=6, temperature=0.0))
                outs.append(r.token_ids)
            return outs, eng._cache.k.dtype, eng.prefix_hits
        finally:
            eng.stop()

    base, dt_b, _ = run(None)
    got, dt_q, hits = run("fp8")
    assert dt_b == cfg.dtype and dt_q == jnp.float8_e4m3fn
    assert hits >= 1
    assert all(len(o) == 6 for o in got)
    # fp8 rounding may flip late greedy tokens; the first must survive
    assert [o[0] for o in got] == [b[0] for b in base]

    # logit-level closeness: one decode step from identical prefills
    ids = np.asarray([tok.encode("check fp8 kv cache closeness")], np.int32)
    lengths = np.asarray([ids.shape[1]], np.int32)
    lg, ks, vs = llama.prefill(params, cfg, jnp.asarray(ids), jnp.asarray(lengths))
    outs = {}
    for dt in (None, jnp.float8_e4m3fn):
        cache = llama.init_cache(cfg, 1, 64, dtype=dt)
        cache = llama.insert_sequences(
            cache, ks, vs, jnp.asarray(lengths), jnp.asarray([0], np.int32)
        )
        step_lg, _ = llama.decode_step(
            params, cfg, jnp.asarray([5], np.int32), cache
        )
        outs[dt] = np.asarray(step_lg[0])
    a, b = outs[None], outs[jnp.float8_e4m3fn]
    cos = float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b)))
    assert cos > 0.98, cos


def test_kv_cache_dtype_validation():
    """Bad kv_cache_dtype fails BEFORE any weight load; \"bf16\" is explicit
    bfloat16 even on f32 dev models (not an alias for the model dtype)."""
    from django_assistant_bot_tpu.serving.registry import ModelSpec

    reg = ModelRegistry()
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        reg.load(
            ModelSpec(name="bad", kind="decoder", tiny=True, kv_cache_dtype="fp16")
        )
    with pytest.raises(ValueError, match="decoder-only"):
        reg.load(
            ModelSpec(name="enc", kind="encoder", tiny=True, kv_cache_dtype="fp8")
        )

    cfg = DecoderConfig.tiny()  # tiny() is float32
    params = llama.init(cfg, jax.random.key(0))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
        kv_cache_dtype="bf16",
    )
    assert eng._cache.k.dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        GenerationEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            kv_cache_dtype="fp16",
        )
