"""CLI smoke tests: load_csv, search, emb_test, queue — each command's run()
drives the real stack (reference: the management commands in SURVEY §2.1 #21)."""

import argparse

import numpy as np
import pytest

from django_assistant_bot_tpu.cli import emb_test, load_csv, queue_cmd, search
from django_assistant_bot_tpu.conf import settings
from django_assistant_bot_tpu.rag.index_registry import reset_indexes
from django_assistant_bot_tpu.storage import models


@pytest.fixture(autouse=True)
def fresh_indexes():
    reset_indexes()
    yield
    reset_indexes()


@pytest.fixture()
def csv_loaded(tmp_db, tmp_path, capsys):
    path = tmp_path / "docs.csv"
    path.write_text(
        "topic,title,content\n"
        "Billing,Refunds,Refunds take three days.\n"
        "Billing,Invoices,Invoices are emailed monthly.\n"
        "Access,Login,Reset your password from the login page.\n"
    )
    args = argparse.Namespace(bot_codename="clibot", path=str(path), no_process=True)
    assert load_csv.run(args) == 0
    assert "Loaded 3 documents" in capsys.readouterr().out
    return models.Bot.objects.get(codename="clibot")


def test_load_csv_builds_wiki_tree(csv_loaded):
    bot = csv_loaded
    docs = models.WikiDocument.objects.filter(bot=bot).all()
    titles = {d.title for d in docs}
    assert {"Billing", "Access", "Refunds", "Invoices", "Login"} <= titles
    refunds = next(d for d in docs if d.title == "Refunds")
    assert refunds.parent_id is not None  # 2-level topic tree


def test_search_cli_finds_ingested_question(csv_loaded, capsys):
    bot = csv_loaded
    wiki = models.WikiDocument.objects.filter(bot=bot, title="Refunds").first()
    doc = models.Document.objects.create(wiki=wiki, name="Refunds")
    # embed via the SAME factory the search CLI uses, so dims always agree
    from django_assistant_bot_tpu.ai.services.ai_service import get_ai_embedder

    import asyncio

    emb = get_ai_embedder("test")
    vec = asyncio.run(emb.embeddings(["how long do refunds take?"]))[0]
    models.Question.objects.create(
        document=doc, text="how long do refunds take?", embedding=np.asarray(vec, np.float32)
    )
    with settings.override(EMBEDDING_AI_MODEL="test"):
        # a document only scores once it has >= max_scores_n hits (reference
        # aggregation semantics); one question in the corpus -> max_scores_n=1
        args = argparse.Namespace(
            query="how long do refunds take?", field="questions", max_scores_n=1, n=5
        )
        assert search.run(args) == 0
    out = capsys.readouterr().out
    assert "Refunds" in out  # the matching document is printed with its score


def test_emb_test_cli_prints_similarity(tmp_db, capsys):
    with settings.override(EMBEDDING_AI_MODEL="test"):
        args = argparse.Namespace(query1="hello", query2="hello", model=None)
        assert emb_test.run(args) == 0
    out = capsys.readouterr().out
    assert "Score: " in out
    score = float(out.split("Score:")[1].strip())
    assert score == pytest.approx(1.0, abs=1e-5)  # identical texts


def test_queue_cli_list_clear_remove(tmp_db, capsys):
    from django_assistant_bot_tpu.tasks.queue import TaskRecord

    for i in range(3):
        TaskRecord.objects.create(queue="query", name=f"tests.task{i}", args=[], kwargs={})
    assert queue_cmd.run(argparse.Namespace(action="list", queue=None, id=None, status=None)) == 0
    out = capsys.readouterr().out
    assert "tests.task0" in out and "tests.task2" in out

    first = TaskRecord.objects.all().order_by("id").first()
    assert (
        queue_cmd.run(argparse.Namespace(action="remove", queue=None, id=first.id, status=None))
        == 0
    )
    assert TaskRecord.objects.count() == 2
    assert queue_cmd.run(argparse.Namespace(action="clear", queue="query", id=None, status=None)) == 0
    assert TaskRecord.objects.count() == 0
    # remove without --id is a usage error
    assert queue_cmd.run(argparse.Namespace(action="remove", queue=None, id=None, status=None)) == 1


def test_fetch_models_skips_complete_and_reports_missing(tmp_path, capsys, monkeypatch):
    """fetch: an already-complete checkpoint dir is skipped (the reference's
    local_files_only probe, gpu_service/bin/fetch_models.py:10-30); an
    incomplete one without the hub client exits with guidance."""
    from django_assistant_bot_tpu.cli import fetch_models as fm

    models_dir = tmp_path / "models"
    done = models_dir / "org__done"
    done.mkdir(parents=True)
    (done / "config.json").write_text("{}")
    (done / "model.safetensors").write_text("x")
    assert fm.fetch_one("org/done", str(models_dir)) == str(done)
    assert "already fetched" in capsys.readouterr().out

    # force the no-hub-client path deterministically
    import builtins

    real_import = builtins.__import__

    def no_hub(name, *a, **k):
        if name == "huggingface_hub":
            raise ImportError("no hub in test")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_hub)
    with pytest.raises(SystemExit, match="manually"):
        fm.fetch_one("org/missing", str(models_dir))


def test_fetch_models_config_repo_ids(tmp_path):
    import json

    from django_assistant_bot_tpu.cli import fetch_models as fm

    cfg = tmp_path / "serving.json"
    local_dir = tmp_path / "local_ckpt"
    local_dir.mkdir()
    cfg.write_text(json.dumps({
        "chat": {"kind": "decoder", "path": "meta-llama/Llama-3.2-1B"},
        "tiny": {"kind": "decoder", "tiny": True},
        "local": {"kind": "decoder", "path": str(local_dir)},
    }))
    assert fm._config_repo_ids(str(cfg)) == ["meta-llama/Llama-3.2-1B"]


def test_fetch_models_config_skips_filesystem_paths(tmp_path, monkeypatch):
    """Filesystem-looking specs must never reach snapshot_download (r4 advisor:
    a not-yet-created local path like models/foo.native aborted the run)."""
    import json

    from django_assistant_bot_tpu.cli import fetch_models as fm

    monkeypatch.chdir(tmp_path)
    (tmp_path / "models").mkdir()
    cfg = tmp_path / "serving.json"
    cfg.write_text(json.dumps({
        "hub": {"kind": "decoder", "path": "org/real-repo"},
        "native": {"kind": "decoder", "path": "models/foo.native"},
        "native8": {"kind": "decoder", "path": "other/foo.native.int8"},
        "dot": {"kind": "decoder", "path": "./ckpt/dir"},
        "abs": {"kind": "decoder", "path": str(tmp_path / "nope")},
        "deep": {"kind": "decoder", "path": "a/b/c"},
    }))
    assert fm._config_repo_ids(str(cfg)) == ["org/real-repo"]


def test_fetch_models_continues_past_failures(tmp_path, monkeypatch, capsys):
    """One model failing must not abort the rest of the fetch run."""
    from types import SimpleNamespace

    from django_assistant_bot_tpu.cli import fetch_models as fm

    calls = []

    def fake_fetch(repo_id, models_dir, revision=None):
        calls.append(repo_id)
        if repo_id == "org/bad":
            raise SystemExit(f"{repo_id}: download failed")
        d = tmp_path / repo_id.replace("/", "__")
        d.mkdir(exist_ok=True)
        return str(d)

    monkeypatch.setattr(fm, "fetch_one", fake_fetch)
    args = SimpleNamespace(
        models=["org/bad", "org/good"], config=None, models_dir=str(tmp_path),
        revision=None, convert=False, kind="decoder", quantize=None,
    )
    rc = fm.run(args)
    assert calls == ["org/bad", "org/good"]  # kept going past the failure
    assert rc == 1  # but the run still reports it


def test_fetch_models_hub_id_not_swallowed_by_local_dir(tmp_path, monkeypatch, capsys):
    """A `google/` directory in CWD must not silently drop `google/gemma-2b`
    (ADVICE r5): only an EXISTING full path (or a .native convert target) is a
    local marker; the ambiguous case is logged and treated as a hub id."""
    from django_assistant_bot_tpu.cli import fetch_models as fm

    monkeypatch.chdir(tmp_path)
    (tmp_path / "google").mkdir()
    assert fm.looks_like_repo_id("google/gemma-2b")
    assert "treating it as a hub id" in capsys.readouterr().out
    # a not-yet-created converted checkpoint under an existing dir stays local
    (tmp_path / "models").mkdir()
    assert not fm.looks_like_repo_id("models/foo.native")
    # an existing full path stays local (no note)
    (tmp_path / "google" / "ckpt").mkdir()
    assert not fm.looks_like_repo_id("google/ckpt")
    assert "treating it as a hub id" not in capsys.readouterr().out


def test_persistent_compile_cache_wiring(tmp_path, monkeypatch):
    """enable_persistent_compile_cache points jax at the dir, creates it, and
    honors the opt-out env; failures must degrade to None, never raise."""
    import jax

    from django_assistant_bot_tpu.utils import compile_cache as cc

    prev = jax.config.jax_compilation_cache_dir
    target = tmp_path / "xla-cache"
    try:
        got = cc.enable_persistent_compile_cache(str(target))
        assert got == str(target)
        assert target.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(target)
        monkeypatch.setenv(cc.ENV_DISABLE, "1")
        assert cc.enable_persistent_compile_cache(str(target)) is None
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
