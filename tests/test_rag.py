"""RAG plane: object search annotation, doc-score aggregation, index invalidation."""

import asyncio

import numpy as np
import pytest

from django_assistant_bot_tpu.ai.providers.echo import HashEmbedder
from django_assistant_bot_tpu.rag import (
    embedding_search,
    embedding_search_questions,
    get_embedding,
    invalidate_index,
)
from django_assistant_bot_tpu.rag.index_registry import reset_indexes
from django_assistant_bot_tpu.storage import models


@pytest.fixture(autouse=True)
def fresh_indexes():
    reset_indexes()
    yield
    reset_indexes()


def _seed_questions(n_docs=3, per_doc=12):
    """Each doc's questions cluster around a distinct direction; returns the
    center texts so queries can target a known doc."""
    bot = models.Bot.objects.create(codename="rag-bot")
    wiki = models.WikiDocument.objects.create(bot=bot, title="wiki")
    emb = HashEmbedder(dim=768)
    docs, centers = [], []
    for d in range(n_docs):
        doc = models.Document.objects.create(wiki=wiki, name=f"doc{d}", content=f"content {d}")
        center_text = f"topic-{d}"
        center = np.asarray(asyncio.run(emb.embeddings([center_text]))[0])
        for i in range(per_doc):
            noise = np.random.default_rng(d * 100 + i).normal(size=768) * 0.05
            vec = center + noise
            models.Question.objects.create(
                document=doc, text=f"q{d}-{i}", order=i, embedding=vec.astype(np.float32)
            )
        docs.append(doc)
        centers.append(center_text)
    return docs, centers


def test_objects_search_sets_distance(tmp_db):
    _seed_questions()
    q_emb = asyncio.run(get_embedding("topic-1"))
    hits = asyncio.run(embedding_search_questions(q_emb, n=5))
    assert len(hits) == 5
    assert all(hasattr(h, "distance") for h in hits)
    assert hits[0].distance <= hits[-1].distance
    # nearest questions must come from doc index 1
    assert all(h.text.startswith("q1-") for h in hits[:3])


def test_embedding_search_doc_aggregation(tmp_db):
    docs, centers = _seed_questions()
    results = asyncio.run(embedding_search(centers[2], max_scores_n=5, top_n=3))
    assert results
    top_doc, score = results[0]
    assert top_doc.id == docs[2].id
    assert 0.0 < score <= 1.0
    scores = [s for _, s in results]
    assert scores == sorted(scores, reverse=True)


def test_index_invalidation_picks_up_new_rows(tmp_db):
    docs, centers = _seed_questions(n_docs=1, per_doc=12)
    q_emb = asyncio.run(get_embedding("brand-new-question"))
    hits = asyncio.run(embedding_search_questions(q_emb, n=1))
    assert hits and hits[0].distance > 0.1  # nothing similar yet

    new_q = models.Question.objects.create(
        document=docs[0],
        text="brand-new-question",
        embedding=np.asarray(q_emb, np.float32),
    )
    # without invalidation the cached index misses the new row
    hits_stale = asyncio.run(embedding_search_questions(q_emb, n=1))
    assert hits_stale[0].id != new_q.id
    invalidate_index(models.Question)
    hits_fresh = asyncio.run(embedding_search_questions(q_emb, n=1))
    assert hits_fresh[0].id == new_q.id
    assert hits_fresh[0].distance == pytest.approx(0.0, abs=2e-2)


def test_allowed_ids_restriction(tmp_db):
    _seed_questions(n_docs=2, per_doc=12)
    allowed = {
        q.id
        for q in models.Question.objects.all()
        if q.text.startswith("q0-")
    }
    q_emb = asyncio.run(get_embedding("topic-1"))
    hits = asyncio.run(embedding_search_questions(q_emb, n=5, allowed_ids=allowed))
    assert hits and all(h.id in allowed for h in hits)
