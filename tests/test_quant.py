"""Weight-only int8 quantization (ops/quant.py): accuracy, decode parity,
sharded serving integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.ops.quant import (
    QTensor,
    QTensor4,
    QUANTIZABLE,
    deq,
    num_weights,
    pack_int4,
    qeinsum,
    quantize_decoder_params,
    quantize_tensor,
    quantize_tensor_int4,
    unpack_int4,
    weight_bits,
)


def test_quantize_tensor_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(3, 64, 32)).astype(np.float32))
    qt = quantize_tensor(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (3, 1, 32)
    back = deq(qt, jnp.float32)
    # symmetric int8: error bounded by scale/2 per element
    max_err = float(jnp.max(jnp.abs(back - w)))
    assert max_err <= float(jnp.max(qt.scale)) * 0.51


# ------------------------------------------------------- int4 grouped format
def test_int4_pack_unpack_roundtrip_exact():
    rng = np.random.default_rng(1)
    vals = rng.integers(-8, 8, (5, 10, 7)).astype(np.int8)
    packed = pack_int4(vals)
    assert packed.dtype == np.uint8 and packed.shape == (5, 5, 7)
    np.testing.assert_array_equal(
        np.asarray(unpack_int4(jnp.asarray(packed))), vals
    )


def test_quantize_tensor_int4_roundtrip_error_bounded():
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(3, 64, 32)).astype(np.float32))
    qt = quantize_tensor_int4(w, group_size=16)
    assert qt.q.dtype == jnp.uint8 and qt.q.shape == (3, 32, 32)
    assert qt.scale.shape == (3, 4, 32) and qt.group_size == 16
    back = deq(qt, jnp.float32)
    # symmetric int4: error bounded by scale/2 per element
    max_err = float(jnp.max(jnp.abs(back - w)))
    assert max_err <= float(jnp.max(qt.scale)) * 0.51


def test_int4_group_size_clamps_to_even_divisor():
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.normal(size=(24, 8)).astype(np.float32))
    qt = quantize_tensor_int4(w, group_size=64)  # 64 > dim -> whole-dim group
    assert qt.group_size == 24
    qt = quantize_tensor_int4(w, group_size=10)  # 10 doesn't divide -> 8
    assert 24 % qt.group_size == 0 and qt.group_size % 2 == 0


def test_int4_qeinsum_matches_dequantized_reference():
    """The in-dot grouped contraction IS the dequantized dot, reassociated —
    the kernel-identity bound every int4 throughput claim rides on."""
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    qt = quantize_tensor_int4(w, group_size=16)
    x = jnp.asarray(rng.normal(size=(2, 5, 64)).astype(np.float32))
    got = qeinsum("bse,eo->bso", x, qt, jnp.float32)
    ref = jnp.einsum("bse,eo->bso", x, deq(qt, jnp.float32))
    assert float(jnp.max(jnp.abs(got - ref))) < 1e-4
    # ellipsis pattern (the lm_head shape) takes the same path
    got2 = qeinsum("...e,eo->...o", x, qt, jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(got2), rtol=1e-6)


def test_quantize_decoder_params_int4_and_weight_accounting():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(0))
    q4 = quantize_decoder_params(params, fmt="int4", group_size=16)
    for key in QUANTIZABLE:
        if key in q4["layers"]:
            assert isinstance(q4["layers"][key], QTensor4)
    # packed formats count UNPACKED weights, scales excluded
    assert num_weights(q4) == num_weights(params)
    assert weight_bits(q4) == 4
    assert weight_bits(quantize_decoder_params(params)) == 8
    assert weight_bits(params) == 16
    with pytest.raises(ValueError, match="format"):
        quantize_decoder_params(params, fmt="int2")


def test_int4_forward_error_bounded_vs_full_precision():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(0))
    q4 = quantize_decoder_params(params, fmt="int4", group_size=16)
    ids = jnp.asarray(
        np.random.default_rng(1).integers(1, 100, (2, 12)), jnp.int32
    )
    full = np.asarray(llama.forward(params, cfg, ids))
    quant = np.asarray(llama.forward(q4, cfg, ids))
    rel = np.abs(quant - full).max() / max(np.abs(full).max(), 1e-6)
    # 4-bit grouped on a RANDOM tiny model is the worst case (no outlier
    # structure); the bench records the measured bound per run
    assert rel < 0.5, rel


def test_init_int4_shapes_and_decode():
    cfg = DecoderConfig.tiny()
    p4 = llama.init_int4(cfg, jax.random.PRNGKey(0), group_size=16)
    wq = p4["layers"]["wq"]
    assert isinstance(wq, QTensor4) and wq.q.dtype == jnp.uint8
    assert wq.group_size == 16
    # prefill + decode run end to end on the packed weights
    prompt = jnp.asarray([[5, 6, 7, 8, 9]], jnp.int32)
    lengths = jnp.asarray([5], jnp.int32)
    cache = llama.init_cache(cfg, batch=1, max_len=32)
    logits, ks, vs = llama.prefill(p4, cfg, prompt, lengths)
    cache = llama.insert_sequences(
        cache, ks, vs, lengths, jnp.asarray([0], jnp.int32)
    )
    tok = int(jnp.argmax(logits[0]))
    logits2, cache = llama.decode_step(
        p4, cfg, jnp.asarray([tok], jnp.int32), cache
    )
    assert np.isfinite(np.asarray(logits2)).all()


@pytest.mark.slow
def test_quantized_forward_close_and_decode_consistent():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(0))
    qparams = quantize_decoder_params(params)
    for key in QUANTIZABLE:
        if key in qparams["layers"]:
            assert isinstance(qparams["layers"][key], QTensor)
    ids = jnp.asarray(np.random.default_rng(1).integers(1, 100, (2, 12)), jnp.int32)
    full = np.asarray(llama.forward(params, cfg, ids))
    quant = np.asarray(llama.forward(qparams, cfg, ids))
    # int8 per-channel error stays a small fraction of the logit scale
    rel = np.abs(quant - full).max() / max(np.abs(full).max(), 1e-6)
    assert rel < 0.05, rel

    # prefill+decode on the QUANTIZED params agrees with the quantized forward
    prompt = np.asarray(ids[:1, :5])
    seq = prompt.copy()
    for _ in range(4):
        logits = llama.forward(qparams, cfg, jnp.asarray(seq))
        seq = np.concatenate([seq, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
    expected = seq[0, prompt.shape[1]:].tolist()

    cache = llama.init_cache(cfg, batch=1, max_len=32)
    lengths = jnp.asarray([prompt.shape[1]], jnp.int32)
    logits, ks, vs = llama.prefill(qparams, cfg, jnp.asarray(prompt), lengths)
    cache = llama.insert_sequences(cache, ks, vs, lengths, jnp.asarray([0], jnp.int32))
    got = [int(jnp.argmax(logits[0]))]
    for _ in range(3):
        logits, cache = llama.decode_step(
            qparams, cfg, jnp.asarray([got[-1]], jnp.int32), cache
        )
        got.append(int(jnp.argmax(logits[0])))
    assert got == expected


def test_quantized_sharded_engine_generates(mesh8, tmp_db):
    """QTensor leaves ride shard_pytree's sharding tree as a prefix; the full
    registry->engine path serves a quantized model on the 8-device mesh."""
    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec

    registry = ModelRegistry(mesh=mesh8)
    spec = ModelSpec(
        name="tiny-q8", kind="decoder", tiny=True, quantize="int8",
        max_slots=2, max_seq_len=64,
    )
    registry.specs = {"tiny-q8": spec}
    registry.load(spec)
    eng = registry.get_generator("tiny-q8")
    try:
        fut = eng.submit([3, 7, 11], max_tokens=6, temperature=0.0)
        res = fut.result(timeout=600)
        assert len(res.token_ids) == 6
        # greedy determinism across a second request
        fut2 = eng.submit([3, 7, 11], max_tokens=6, temperature=0.0)
        assert fut2.result(timeout=600).token_ids == res.token_ids
    finally:
        registry.stop()


@pytest.mark.slow
def test_registry_warmup_knob(mesh8, tmp_db):
    """warmup=true compiles shapes at load; the engine then serves normally."""
    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec

    registry = ModelRegistry(mesh=mesh8)
    spec = ModelSpec(
        name="warm", kind="decoder", tiny=True, warmup=True, warmup_json=True,
        max_slots=2, max_seq_len=64,
    )
    registry.specs = {"warm": spec}
    registry.load(spec)
    eng = registry.get_generator("warm")
    try:
        res = eng.submit([5, 9], max_tokens=4, temperature=0.0).result(timeout=600)
        assert len(res.token_ids) == 4
        # json variants were compiled too (FSM exists before first json request)
        assert eng._fsm is not None and eng._decode_tick_json is not None
    finally:
        registry.stop()


def test_unknown_quantize_rejected(mesh8):
    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec

    registry = ModelRegistry(mesh=mesh8)
    # int4 became a supported format (docs/QUANT.md) — int2 stays unknown
    with pytest.raises(ValueError, match="unknown quantize"):
        registry.load(
            ModelSpec(name="bad", kind="decoder", tiny=True, quantize="int2")
        )


def test_init_int8_quantize_embed_serves():
    """int8 embedding/head tables (the 8B HBM-fit path): forward, prefill and
    decode all run, logits finite, and param bytes shrink accordingly."""
    import jax
    import numpy as np

    from django_assistant_bot_tpu.models import DecoderConfig, llama

    cfg = DecoderConfig.tiny()
    p_bf16 = llama.init_int8(cfg, jax.random.PRNGKey(0))
    p_q = llama.init_int8(cfg, jax.random.PRNGKey(0), quantize_embed=True)
    from django_assistant_bot_tpu.ops.quant import QTensor

    assert isinstance(p_q["tok_embed"], QTensor)
    assert sum(l.nbytes for l in jax.tree.leaves(p_q)) < sum(
        l.nbytes for l in jax.tree.leaves(p_bf16)
    )
    ids = np.arange(1, 9, dtype=np.int32)[None]
    logits = llama.forward(p_q, cfg, ids)
    assert np.isfinite(np.asarray(logits)).all()
    lg, ks, vs = llama.prefill(
        p_q, cfg, ids, np.asarray([ids.shape[1]], np.int32)
    )
    cache = llama.init_cache(cfg, 1, 32)
    cache = llama.insert_sequences(
        cache, ks, vs, np.asarray([8], np.int32), np.asarray([0], np.int32)
    )
    step_logits, cache = llama.decode_step(
        p_q, cfg, np.asarray([3], np.int32), cache
    )
    assert np.isfinite(np.asarray(step_logits)).all()


def test_init_int8_host_rng_same_structure_and_serves():
    """host_rng=True (the virtual-mesh fast path — numpy bytes instead of
    on-device threefry) must produce the identical pytree structure/shapes/
    dtypes as the device draw, and the model must run on it."""
    import jax
    import numpy as np

    from django_assistant_bot_tpu.models import DecoderConfig, llama

    for cfg in (DecoderConfig.tiny(), DecoderConfig.tiny(num_experts=4)):
        p_dev = llama.init_int8(cfg, jax.random.PRNGKey(1))
        p_host = llama.init_int8(cfg, jax.random.PRNGKey(1), host_rng=True)
        flat_d = jax.tree_util.tree_flatten_with_path(p_dev)[0]
        flat_h = jax.tree_util.tree_flatten_with_path(p_host)[0]
        assert [p for p, _ in flat_d] == [p for p, _ in flat_h]
        for (_, a), (_, b) in zip(flat_d, flat_h):
            assert a.shape == b.shape and a.dtype == b.dtype
        ids = np.arange(1, 9, dtype=np.int32)[None]
        logits = llama.forward(p_host, cfg, ids)
        assert np.isfinite(np.asarray(logits)).all()
