"""Multi-host bootstrap: two REAL OS processes form a jax.distributed cluster
over the CPU backend and run a cross-process collective — the closest a single
machine gets to proving the DCN path (SURVEY.md §2.3 collectives backend)."""

import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, os.environ["DABT_TEST_REPO"])
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    ).strip()
    import jax
    # the launch environment may force-register an accelerator plugin; pin CPU
    # before any backend touch (env vars alone are overridden by jax.config)
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:  # older jax: the XLA_FLAGS override above applies
        pass

    from django_assistant_bot_tpu.parallel.distributed import (
        initialize_cluster, is_primary, multihost_mesh,
    )

    initialize_cluster()  # reads DABT_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, len(jax.devices())
    assert len(jax.local_devices()) == 2

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = multihost_mesh()
    assert mesh.shape["data"] == 4, dict(mesh.shape)
    # cross-process collective: every process contributes its local shards of a
    # data-sharded array; the jit'd global sum must see all four devices' rows
    sharding = NamedSharding(mesh, P("data"))
    global_shape = (4,)
    local = [
        jax.device_put(jnp.asarray([float(d.id) + 1.0]), d)
        for d in mesh.local_devices
    ]
    arr = jax.make_array_from_single_device_arrays(global_shape, sharding, local)
    total = jax.jit(
        lambda x: jnp.sum(x), out_shardings=NamedSharding(mesh, P())
    )(arr)
    # every device (local on SOME process) contributed id+1; the global sum
    # proves rows from both processes met in one reduction
    expected = sum(d.id + 1.0 for d in jax.devices())
    assert float(total) == expected, (float(total), expected)
    print(f"rank={jax.process_index()} primary={is_primary()} ok")
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cluster_runs_cross_process_collective(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(
            os.environ,
            DABT_TEST_REPO=REPO,
            DABT_COORDINATOR=f"127.0.0.1:{port}",
            DABT_NUM_PROCESSES="2",
            DABT_PROCESS_ID=str(rank),
            JAX_PLATFORMS="cpu",
        )
        env.pop("XLA_FLAGS", None)  # worker pins its own device count
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    if any(
        "Multiprocess computations aren't implemented on the CPU backend" in o
        for o in outs
    ):
        # this jaxlib's CPU client predates cross-process collectives — the
        # cluster bootstrap itself worked (coordinator handshake, process
        # count); only the collective execution is unsupported here
        import pytest

        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"rank={rank}" in out and "ok" in out, out
    assert any("primary=True" in o for o in outs)
