"""Cross-process fleet plane (serving/fleet.py; docs/FLEET.md).

Evidence layers, all CPU:

- wire codec property tests: fp8/bf16/int8/f32 page snapshots encode→decode
  BIT-identical (including the boundary partial tail page) under the pinned
  DABT_KV_FUZZ_SEED; malformed and cross-build payloads fail loudly;
- the versioned-snapshot contract: HostKVTier.absorb refuses entries
  stamped by a different build (all-or-nothing), the disk tier refuses
  tampered/foreign .npz files;
- FleetRouter policy under stub peers (no sockets): precedence, token-less
  re-route + breaker feed, shed aggregation, the pool-role force retry,
  gossip application (delta + reset), prefix pull, the two-stage
  disaggregated handoff;
- live two-peer integration over REAL aiohttp servers (each hosted on its
  own thread's event loop): KV pages shipped over the wire land bit-exact
  on the receiver, a decode-pool peer serves a session whose prefill ran in
  the prefill pool with output identical to the unified arm, peer death
  re-routes token-lessly and degrades /fleet/healthz, and the dabt_fleet_*
  exposition parses;
- a @slow two-SUBPROCESS smoke (the CI step): boot two `serve --tiny`
  processes, route a dialog, kill one, assert re-route + fleet-degraded.
"""

import asyncio
import json
import os
import threading
import time

import numpy as np
import pytest

import jax

from django_assistant_bot_tpu.serving.engine import EngineUnavailable
from django_assistant_bot_tpu.serving.fleet import (
    FleetPeer,
    FleetPlane,
    FleetRouter,
    PeerHTTPError,
    PeerUnreachable,
    decode_kv_entry,
    encode_kv_entry,
)
from django_assistant_bot_tpu.serving.kv_pool import (
    KV_WIRE_VERSION,
    HostKVTier,
    HostPrefixEntry,
    WireVersionError,
)
from django_assistant_bot_tpu.serving.scheduler import SchedulerRejected

FUZZ_SEED = int(os.environ.get("DABT_KV_FUZZ_SEED", "0"))


# ---------------------------------------------------------------- wire codec
def _entry(dtype, *, length=37, page=16, layers=2, kh=1, d=4, seed=FUZZ_SEED):
    """A HostPrefixEntry with random page contents in `dtype`.  length=37
    with page=16 exercises the boundary shape: two full pages plus a
    partial COW tail page."""
    rng = np.random.default_rng(seed)
    n_pages = -(-length // page)
    shape = (layers, n_pages, kh, page, d)
    nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
    # draw raw bytes, then view as dtype: every bit pattern (NaNs, denormals,
    # fp8 codes) must survive the wire — value-space draws would miss them
    k = rng.integers(0, 256, nbytes, np.uint8).view(dtype).reshape(shape)
    v = rng.integers(0, 256, nbytes, np.uint8).view(dtype).reshape(shape)
    key = tuple(int(t) for t in rng.integers(1, 255, length))
    return HostPrefixEntry(
        key=key, length=length, k=k, v=v, nbytes=2 * nbytes, pages=n_pages
    )


def _wire_dtypes():
    import ml_dtypes

    return [
        np.float32,
        np.int8,
        np.dtype(ml_dtypes.bfloat16),
        np.dtype(ml_dtypes.float8_e4m3fn),
        np.dtype(ml_dtypes.float8_e5m2),
    ]


@pytest.mark.parametrize("dtype", _wire_dtypes(), ids=str)
def test_wire_roundtrip_bit_identical(dtype):
    ent = _entry(dtype)
    out = decode_kv_entry(encode_kv_entry(ent))
    assert out.key == ent.key and out.length == ent.length
    assert out.k.dtype == np.dtype(dtype) and out.v.dtype == np.dtype(dtype)
    assert out.k.shape == ent.k.shape and out.v.shape == ent.v.shape
    # BIT identity, not value identity: NaN payloads and fp8 codes included
    assert out.k.tobytes() == ent.k.tobytes()
    assert out.v.tobytes() == ent.v.tobytes()


def test_wire_roundtrip_fuzz_shapes():
    """Pinned-seed shape fuzz: page-aligned, single-page, and ragged-tail
    entries all round-trip bit-exactly."""
    rng = np.random.default_rng(1000 + FUZZ_SEED)
    for _ in range(10):
        length = int(rng.integers(1, 80))
        page = int(rng.choice([8, 16, 32]))
        ent = _entry(
            np.float32, length=length, page=page, seed=int(rng.integers(1 << 31))
        )
        out = decode_kv_entry(encode_kv_entry(ent))
        assert out.key == ent.key
        assert out.k.tobytes() == ent.k.tobytes()
        assert out.v.tobytes() == ent.v.tobytes()


def test_wire_rejects_malformed():
    ent = _entry(np.float32)
    data = encode_kv_entry(ent)
    with pytest.raises(ValueError):
        decode_kv_entry(b"NOTKV!" + data[6:])  # bad magic
    with pytest.raises(ValueError):
        decode_kv_entry(data[:-8])  # truncated body
    with pytest.raises(ValueError):
        decode_kv_entry(data[: len(data) // 4])  # truncated header/body


def test_wire_rejects_cross_build_version():
    ent = _entry(np.float32)
    data = bytearray(encode_kv_entry(ent))
    hlen = int.from_bytes(data[6:10], "little")
    header = json.loads(bytes(data[10 : 10 + hlen]).decode())
    header["wire_version"] = KV_WIRE_VERSION + 1
    hb = json.dumps(header, separators=(",", ":")).encode()
    tampered = data[:6] + len(hb).to_bytes(4, "little") + hb + data[10 + hlen :]
    with pytest.raises(WireVersionError):
        decode_kv_entry(bytes(tampered))


def test_absorb_rejects_unknown_wire_version_all_or_nothing():
    """A snapshot carrying even ONE cross-build entry must absorb NOTHING —
    failing loudly beats corrupting pages (the satellite contract)."""
    tier = HostKVTier(1 << 20, page_size=16)
    good = _entry(np.float32, length=16)
    bad = _entry(np.float32, length=32, seed=FUZZ_SEED + 1)
    bad.wire_version = KV_WIRE_VERSION + 1
    with pytest.raises(WireVersionError):
        tier.absorb([good, bad])
    assert tier.stats()["kv_host_entries"] == 0


def test_disk_file_rejects_cross_build_version(tmp_path):
    """A .npz written by a different build (tampered wire_version) loads as
    a MISS, never as reinterpreted pages."""
    tier = HostKVTier(
        1536, page_size=16, spill_dir=str(tmp_path), name="wire-test"
    )
    ent = _entry(np.float32, length=16, page=16)  # 1 page, 2*512B = 1024B
    assert tier.put(ent.key, ent.length, ent.k, ent.v)
    # a second entry evicts the first to disk (budget fits one)
    ent2 = _entry(np.float32, length=16, page=16, seed=FUZZ_SEED + 2)
    assert tier.put(ent2.key, ent2.length, ent2.k, ent2.v)
    files = [f for f in os.listdir(tmp_path) if f.endswith(".npz")]
    assert files, "expected a disk demotion"
    path = tmp_path / files[0]
    with np.load(path, allow_pickle=False) as z:
        blob = {name: z[name] for name in z.files}
    assert int(blob["wire_version"]) == KV_WIRE_VERSION
    blob["wire_version"] = np.asarray(KV_WIRE_VERSION + 1, np.int64)
    np.savez(path, **blob)
    # the demoted key must now MISS (and not crash): lookup promotes from
    # disk only after the version gate passes
    assert tier.lookup(list(ent.key) + [9], ent.length) is None


# ------------------------------------------------------- stub-peer policy
class _StubClient:
    """In-memory PeerClient: per-path handlers, call log, no sockets."""

    def __init__(self):
        self.calls = []
        self.generate = lambda body: {
            "token_ids": [1, 2],
            "result": "ok",
            "usage": {"prompt_tokens": 3, "completion_tokens": 2},
            "length_limited": False,
        }
        self.healthz = lambda: {
            "status": "ok",
            "load": {"queued": 0, "active": 0},
            "fleet": {"pool": "unified", "seq": 0},
        }
        self.prefix = lambda since: {"seq": 0, "events": []}
        self.kv_get = lambda body: None
        self.kv_put = lambda data: {"stored": True, "pages": 0}

    def get_json(self, path, timeout_s=None):
        self.calls.append(("GET", path))
        if path.startswith("/fleet/healthz"):
            return self.healthz()
        if path.startswith("/fleet/prefix"):
            return self.prefix(int(path.rsplit("=", 1)[1]))
        raise AssertionError(path)

    def post_json(self, path, body, timeout_s=None):
        self.calls.append(("POST", path, body))
        if path == "/fleet/generate":
            return self.generate(body)
        raise AssertionError(path)

    def post_for_bytes(self, path, body, timeout_s=None):
        self.calls.append(("POST", path, body))
        if path == "/fleet/kv/get":
            return self.kv_get(body)
        raise AssertionError(path)

    def post_bytes(self, path, data, timeout_s=None):
        self.calls.append(("POST-BYTES", path))
        if path.startswith("/fleet/kv/put"):
            return self.kv_put(data)
        raise AssertionError(path)


def _mk_router(n=2, pools=None, **kw):
    peers = [
        FleetPeer(
            f"p{i}",
            f"http://stub{i}",
            client=_StubClient(),
            pool=(pools[i] if pools else "unified"),
        )
        for i in range(n)
    ]
    kw.setdefault("refresh_interval_s", 1e9)  # tests drive refresh() directly
    kw.setdefault("breaker_reset_s", 1e9)
    router = FleetRouter(peers, model="tiny-chat", **kw)
    router._last_refresh = router._clock()  # suppress the lazy first refresh
    return router, peers


def test_fleet_router_dispatch_and_contract():
    router, peers = _mk_router()
    fut = router.submit([1, 2, 3], max_tokens=4, temperature=0.0)
    res = fut.result(timeout=10)
    assert res.token_ids == [1, 2] and res.text == "ok"
    assert res.peer in ("p0", "p1") and res.reroutes == 0
    assert res.trace_id
    body = next(
        c[2] for p in peers for c in p.client.calls if c[0] == "POST"
    )
    assert body["model"] == "tiny-chat" and body["trace_id"] == res.trace_id
    with pytest.raises(ValueError):
        router.submit([1, 2], stream=object())
    router.close()


def test_fleet_router_reroutes_token_less_on_peer_death():
    router, peers = _mk_router()
    peers[1].queued = 100  # p0 is least-loaded -> chosen first

    def _dead(body):
        raise PeerUnreachable("connection refused")

    peers[0].client.generate = _dead
    res = router.submit([1, 2, 3]).result(timeout=10)
    assert res.peer == "p1" and res.reroutes == 1
    assert router.reroutes == 1
    assert not peers[0].healthy
    # breaker fed: repeated failures open it so dispatch skips the corpse
    for _ in range(3):
        peers[0].breaker.record_failure()
    assert not peers[0].breaker.allow()
    router.close()


def test_fleet_router_exhausted_reroutes_raises():
    router, peers = _mk_router(n=2, max_reroutes=1)
    for p in peers:
        p.client.generate = lambda body: (_ for _ in ()).throw(
            PeerUnreachable("dead")
        )
    with pytest.raises(EngineUnavailable):
        router.submit([1, 2, 3]).result(timeout=10)
    assert router.rerouted_failed == 1
    router.close()


def test_fleet_router_shed_aggregation():
    router, peers = _mk_router()
    for i, p in enumerate(peers):
        p.client.generate = lambda body, _i=i: (_ for _ in ()).throw(
            PeerHTTPError(
                429, "queue full", retry_after_s=2.0 + _i, reason="queue_full"
            )
        )
    with pytest.raises(SchedulerRejected) as ei:
        router.submit([1, 2, 3]).result(timeout=10)
    # the hint is the MINIMUM across sheds: retry when the first peer might
    assert ei.value.retry_after_s == 2.0
    assert router.sheds == 1
    router.close()


def test_fleet_router_pool_role_force_retry():
    """When every reject is pool_role, availability beats role purity: one
    force retry, counted."""
    router, peers = _mk_router(pools=("decode", "decode"))

    def _guarded(body):
        if body.get("force"):
            return {
                "token_ids": [7],
                "result": "forced",
                "usage": {"prompt_tokens": 3, "completion_tokens": 1},
                "length_limited": False,
            }
        raise PeerHTTPError(
            429, "pool role", retry_after_s=1.0, reason="pool_role"
        )

    for p in peers:
        p.client.generate = _guarded
    res = router.submit([1, 2, 3]).result(timeout=10)
    assert res.token_ids == [7]
    assert router.pool_role_bypasses == 1
    router.close()


def test_fleet_router_gossip_affinity_and_reset():
    router, peers = _mk_router()
    key = tuple(range(1, 9))
    peers[1].client.prefix = lambda since: {
        "seq": 3,
        "events": [
            {
                "model": "tiny-chat",
                "replica": "tiny-chat/r0",
                "event": "host_put",
                "key": list(key),
                "length": len(key),
            },
            # other models' gossip must not leak into this router's registry
            {
                "model": "other",
                "replica": "other/r0",
                "event": "host_put",
                "key": [9, 9],
                "length": 2,
            },
        ],
    }
    router.refresh()
    assert peers[1].prefix_seq == 3
    holders = router._peer_holders(list(key) + [99], len(key))
    assert set(holders) == {"p1"}
    # affinity: p1 wins dispatch for the warm session despite equal load
    res = router.submit(list(key) + [50, 51], prefix_len=len(key)).result(10)
    assert res.peer == "p1"
    assert router.affinity_hits == 1
    # reset: the peer's log was trimmed/restarted -> drop and re-apply
    peers[1].client.prefix = lambda since: {
        "seq": 10,
        "reset": True,
        "holdings": [],
    }
    router.refresh()
    assert router._peer_holders(list(key) + [99], len(key)) == {}
    router.close()


def test_fleet_router_prefix_pull():
    router, peers = _mk_router()
    key = tuple(range(1, 9))
    ent = _entry(np.float32, length=len(key))
    ent = HostPrefixEntry(
        key=key, length=len(key), k=ent.k, v=ent.v, nbytes=ent.nbytes, pages=1
    )
    peers[1].client.prefix = lambda since: {
        "seq": 1,
        "events": [
            {
                "model": "tiny-chat",
                "replica": "tiny-chat/r0",
                "event": "host_put",
                "key": list(key),
                "length": len(key),
            }
        ],
    }
    router.refresh()
    # the holder sheds, so dispatch falls to p0 — which pulls the prefix
    # from p1 before the request lands
    peers[1].client.generate = lambda body: (_ for _ in ()).throw(
        PeerHTTPError(429, "busy", retry_after_s=1.0, reason="queue_full")
    )
    peers[1].client.kv_get = lambda body: encode_kv_entry(ent)
    peers[0].client.kv_put = lambda data: {"stored": True, "pages": 1}
    res = router.submit(list(key) + [50, 51], prefix_len=len(key)).result(10)
    assert res.peer == "p0"
    assert router.prefix_pulls == 1 and router.pages_shipped == 1
    assert any(
        c[1].startswith("/fleet/kv/put") for c in peers[0].client.calls
    )
    router.close()


def test_fleet_router_disagg_handoff_two_stage():
    router, peers = _mk_router(pools=("prefill", "decode"))
    prompt = list(range(1, 101))  # suffix 100 >= handoff threshold 64
    seen = {}

    def _prefill(body):
        seen["prefill"] = body
        assert body["prefill_only"] and body["max_tokens"] == 1
        assert body["priority"] == "background"
        assert body["push_to"] == peers[1].base_url
        return {
            "token_ids": [5],
            "result": "",
            "usage": {"prompt_tokens": 100, "completion_tokens": 1},
            "length_limited": False,
            "handoff": {"pushed": True, "pages": 7, "key_tokens": 99},
        }

    def _decode(body):
        seen["decode"] = body
        assert body["prefix_len"] == 99 and not body.get("prefill_only")
        return {
            "token_ids": [5, 6, 7],
            "result": "xyz",
            "usage": {"prompt_tokens": 100, "completion_tokens": 3},
            "length_limited": False,
        }

    peers[0].client.generate = _prefill
    peers[1].client.generate = _decode
    res = router.submit(prompt, max_tokens=3, temperature=0.0).result(10)
    assert res.peer == "p1" and res.token_ids == [5, 6, 7]
    assert router.handoffs == 1 and router.pages_shipped == 7
    assert "prefill" in seen and "decode" in seen
    router.close()


# ------------------------------------------------------ plane policy units
class _StubEngine:
    replicas = None
    num_active = 0

    def __init__(self, warm=False):
        self._warm = warm

    def queued_depth(self):
        return 0

    def holds_prefix(self, prompt_ids, prefix_len):
        return self._warm


class _StubRegistry:
    def __init__(self):
        self.generators = {}
        self.embedders = {}
        self.specs = {}

    def get_generator(self, model):
        return self.generators.get(model)


def test_plane_admission_guard_roles():
    reg = _StubRegistry()
    cold = _StubEngine(warm=False)
    reg.generators["m"] = cold
    plane = FleetPlane(reg, pool="prefill", decode_max_prefill_tokens=8)
    ids = list(range(40))
    rej = plane.admission_guard(
        "m", cold, ids, 0, prefill_only=False, force=False
    )
    assert rej is not None and rej.reason == "pool_role"
    assert (
        plane.admission_guard("m", cold, ids, 0, prefill_only=True, force=False)
        is None
    )
    plane.pool = "decode"
    # long cold suffix: shed
    assert (
        plane.admission_guard("m", cold, ids, 0, prefill_only=False, force=False)
        is not None
    )
    # prefill_only never runs in the decode pool
    assert (
        plane.admission_guard("m", cold, ids, 0, prefill_only=True, force=False)
        is not None
    )
    # warm prefix covering all but a short suffix: admitted via restore
    warm = _StubEngine(warm=True)
    assert (
        plane.admission_guard(
            "m", warm, ids, len(ids) - 4, prefill_only=False, force=False
        )
        is None
    )
    # force bypasses (counted): availability beats purity
    assert (
        plane.admission_guard("m", cold, ids, 0, prefill_only=False, force=True)
        is None
    )
    assert plane.pool_bypasses == 1 and plane.pool_rejects >= 3


def test_plane_gossip_log_delta_and_reset():
    plane = FleetPlane(_StubRegistry(), pool="unified", log_size=16)
    for i in range(3):
        plane.on_tier_event("m", "m/r0", "host_put", (1, 2, i), 3)
    out = plane.prefix_events(0)
    assert out["seq"] == 3 and len(out["events"]) == 3
    assert plane.prefix_events(2)["events"][0]["key"] == [1, 2, 2]
    assert plane.prefix_events(3)["events"] == []
    # overflow the bounded log: an ancient cursor gets a reset snapshot
    for i in range(40):
        plane.on_tier_event("m", "m/r0", "host_put", (9, i), 2)
    out = plane.prefix_events(1)
    assert out.get("reset") and out["seq"] == 43
    assert "holdings" in out


# ------------------------------------------------- live two-peer integration
def _serve_app_in_thread(app):
    """Host an aiohttp app on its OWN thread's event loop (TestClient can't
    serve cross-thread traffic — its loop isn't running between requests).
    Returns (base_url, stop)."""
    from aiohttp import web

    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def _run():
        asyncio.set_event_loop(loop)

        async def _up():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            state["runner"] = runner
            state["port"] = runner.addresses[0][1]

        loop.run_until_complete(_up())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    assert started.wait(30), "fleet peer server failed to start"

    def _stop():
        async def _down():
            await state["runner"].cleanup()

        try:
            asyncio.run_coroutine_threadsafe(_down(), loop).result(20)
        except Exception:
            pass
        loop.call_soon_threadsafe(loop.stop)
        t.join(10)

    return f"http://127.0.0.1:{state['port']}", _stop


def _tiny_fleet_config():
    return {
        "tiny-chat": {
            "kind": "decoder",
            "tiny": True,
            "max_slots": 2,
            "max_seq_len": 128,
            "kv_host_bytes": 1 << 26,
            "prefix_min_tokens": 4,
            "prefix_cache": 8,
        }
    }


@pytest.fixture(scope="module")
def fleet_pair():
    """Two REAL serve stacks (registry + engine + fleet plane + aiohttp app)
    on localhost — separate engines and KV pools, same tiny weights
    (llama.init is seed-deterministic), exactly the cross-process shape
    minus the fork."""
    from django_assistant_bot_tpu.serving.registry import ModelRegistry
    from django_assistant_bot_tpu.serving.server import create_app

    regs, planes, urls, stops = [], [], [], []
    for name in ("a", "b"):
        reg = ModelRegistry.from_config(_tiny_fleet_config())
        plane = FleetPlane(reg, name=name, pool="unified")
        reg.fleet_plane = plane
        url, stop = _serve_app_in_thread(create_app(reg))
        regs.append(reg)
        planes.append(plane)
        urls.append(url)
        stops.append(stop)
    planes[0].peers = [("b", urls[1])]
    planes[1].peers = [("a", urls[0])]
    yield regs, planes, urls
    for stop in stops:
        stop()
    for reg in regs:
        reg.stop()


def _fleet_generate(url, body, timeout=120.0):
    from django_assistant_bot_tpu.serving.fleet import PeerClient

    return PeerClient(url, timeout_s=timeout).post_json("/fleet/generate", body)


def test_fleet_kv_ships_bit_identical_across_processes(fleet_pair):
    """The acceptance bit-identity arm: register a prefix on peer A, ship it
    over /fleet/kv/get -> /fleet/kv/put to peer B, and assert B's host tier
    holds byte-identical pages — then B serves the same dialog with token
    ids identical to A's (restore across the process boundary)."""
    from django_assistant_bot_tpu.serving.fleet import PeerClient

    regs, planes, urls = fleet_pair
    prompt = [1 + (i % 250) for i in range(40)]
    plen = 16
    body = {
        "model": "tiny-chat",
        "prompt_ids": prompt,
        "max_tokens": 8,
        "temperature": 0.0,
        "prefix_len": plen,
    }
    ra = _fleet_generate(urls[0], body)
    assert ra["token_ids"], ra
    # A registered prompt[:16]; export it over the wire
    data = PeerClient(urls[0]).post_for_bytes(
        "/fleet/kv/get",
        {"model": "tiny-chat", "prompt_ids": prompt, "prefix_len": plen},
    )
    assert data is not None, "peer A should hold the registered prefix"
    ent = decode_kv_entry(data)
    assert ent.key == tuple(prompt[:plen])
    out = PeerClient(urls[1]).post_bytes(
        "/fleet/kv/put?model=tiny-chat", data
    )
    assert out["stored"], out
    # receiver-side bytes are BIT-identical to the wire payload
    tier_b = regs[1].generators["tiny-chat"].kv_host_tier
    got = tier_b.export_entry(ent.key)
    assert got is not None
    assert np.asarray(got.k).tobytes() == np.asarray(ent.k).tobytes()
    assert np.asarray(got.v).tobytes() == np.asarray(ent.v).tobytes()
    # and B serves the same dialog via restore with identical output
    restores_before = tier_b.stats()["kv_host_restores"]
    rb = _fleet_generate(urls[1], body)
    assert rb["token_ids"] == ra["token_ids"]
    assert tier_b.stats()["kv_host_restores"] > restores_before


def test_fleet_router_live_dispatch_and_gossip(fleet_pair):
    regs, planes, urls = fleet_pair
    router = FleetRouter(
        [("a", urls[0]), ("b", urls[1])],
        model="tiny-chat",
        refresh_interval_s=1e9,
        request_timeout_s=120.0,
    )
    try:
        router.refresh()
        assert all(p.healthy for p in router.peers)
        res = router.submit(
            [2 + (i % 200) for i in range(24)],
            max_tokens=6,
            temperature=0.0,
            prefix_len=8,
        ).result(timeout=120)
        assert res.completion_tokens > 0 and res.peer in ("a", "b")
        # the serving peer registered the prefix; gossip makes the router's
        # registry point affinity at it
        router.refresh()
        holders = router._peer_holders([2 + (i % 200) for i in range(24)], 8)
        assert res.peer in holders
    finally:
        router.close()


def test_fleet_peer_kill_reroute_and_degraded_healthz(fleet_pair):
    """The chaos arm: a dead peer re-routes token-lessly (goodput 1.0) and
    the survivor's /fleet/healthz reports the fleet degraded."""
    from django_assistant_bot_tpu.serving.fleet import PeerClient
    from django_assistant_bot_tpu.serving.registry import ModelRegistry
    from django_assistant_bot_tpu.serving.server import create_app

    regs, planes, urls = fleet_pair
    reg_c = ModelRegistry.from_config(_tiny_fleet_config())
    reg_c.fleet_plane = FleetPlane(reg_c, name="c", pool="unified")
    url_c, stop_c = _serve_app_in_thread(create_app(reg_c))
    router = FleetRouter(
        [("c", url_c), ("a", urls[0])],
        model="tiny-chat",
        refresh_interval_s=1e9,
        request_timeout_s=120.0,
        health_timeout_s=2.0,
    )
    old_peers = list(planes[0].peers)
    try:
        # warm path through c first (deterministic: a looks loaded; suppress
        # the lazy refresh so the fake load survives until dispatch)
        router._last_refresh = router._clock()
        router.peers[1].queued = 100
        res = router.submit([3] * 12, max_tokens=4, temperature=0.0).result(120)
        assert res.peer == "c"
        stop_c()
        reg_c.stop()
        # token-less re-route: every request still completes (goodput 1.0)
        done = [
            router.submit([4] * 12, max_tokens=4, temperature=0.0).result(120)
            for _ in range(2)
        ]
        assert all(r.peer == "a" for r in done)
        assert router.reroutes >= 1
        # the survivor's fleet healthz degrades on the unreachable peer
        planes[0].peers = [("c", url_c)]
        hz = PeerClient(urls[0]).get_json("/fleet/healthz")
        assert hz["fleet"]["status"] == "degraded"
        assert hz["fleet"]["peers_reachable"] == 0
    finally:
        planes[0].peers = old_peers
        router.close()


def test_fleet_disagg_prefill_decode_output_identity(fleet_pair):
    """The acceptance disaggregation arm: a decode-pool replica serves a
    session whose prefill ran in the prefill pool, output identical to the
    unified arm, with pages shipped over the wire and admitted via restore."""
    regs, planes, urls = fleet_pair
    # token alphabet disjoint from every other test in this module: a shared
    # first-token prefix would let B serve from its device prefix registry
    # (warmed by an earlier test) and skip the host-tier restore under test
    prompt = [11 + (i % 180) for i in range(80)]
    # unified reference first (pools still unified)
    ref = _fleet_generate(
        urls[0],
        {
            "model": "tiny-chat",
            "prompt_ids": prompt,
            "max_tokens": 8,
            "temperature": 0.0,
        },
    )
    assert ref["token_ids"]
    tier_b = regs[1].generators["tiny-chat"].kv_host_tier
    restores_before = tier_b.stats()["kv_host_restores"]
    planes[0].pool = "prefill"
    planes[1].pool = "decode"
    router = FleetRouter(
        [
            FleetPeer("a", urls[0], pool="prefill", timeout_s=120.0),
            FleetPeer("b", urls[1], pool="decode", timeout_s=120.0),
        ],
        model="tiny-chat",
        refresh_interval_s=1e9,
        request_timeout_s=120.0,
        handoff_suffix_tokens=64,
    )
    try:
        res = router.submit(prompt, max_tokens=8, temperature=0.0).result(120)
        assert res.token_ids == ref["token_ids"], (
            "disaggregated output must match the unified arm bit-for-bit"
        )
        assert res.peer == "b"
        assert router.handoffs == 1 and router.pages_shipped > 0
        assert planes[1].kv_puts >= 1
        assert tier_b.stats()["kv_host_restores"] > restores_before
    finally:
        planes[0].pool = "unified"
        planes[1].pool = "unified"
        router.close()


def test_fleet_metrics_exposition_parses(fleet_pair):
    from django_assistant_bot_tpu.serving.fleet import PeerClient
    from django_assistant_bot_tpu.serving.obs import (
        parse_prometheus_text,
        render_prometheus,
    )

    regs, planes, urls = fleet_pair
    # attach a fleet router so BOTH gauge families render
    router = FleetRouter(
        [("b", urls[1])], model="tiny-chat", refresh_interval_s=1e9
    )
    regs[0].fleet_router = router
    try:
        text = render_prometheus(regs[0])
    finally:
        del regs[0].fleet_router
        router.close()
    names = set(parse_prometheus_text(text))
    for want in (
        "dabt_fleet_pool_info",
        "dabt_fleet_kv_puts_total",
        "dabt_fleet_peers_total",
        "dabt_fleet_reroutes_total",
        "dabt_fleet_pages_shipped_total",
    ):
        assert want in names, (want, sorted(names)[:8])


def test_traces_endpoint_and_workload_export(fleet_pair, tmp_path):
    """Satellite: the obs trace ring exports to the workload JSONL format
    and replays structurally (sorted arrivals, positive budgets)."""
    import argparse

    from django_assistant_bot_tpu.cli import trace_export
    from django_assistant_bot_tpu.serving.fleet import PeerClient
    from django_assistant_bot_tpu.workload.generator import load_trace

    regs, planes, urls = fleet_pair
    # ensure at least two finished requests ride the ring
    for i in range(2):
        _fleet_generate(
            urls[0],
            {
                "model": "tiny-chat",
                "prompt_ids": [5 + i] * 10,
                "max_tokens": 3,
                "temperature": 0.0,
            },
        )
    body = PeerClient(urls[0]).get_json("/traces")
    assert body["traces"], "expected finished traces on the ring"
    src = tmp_path / "traces.json"
    src.write_text(json.dumps(body))
    out = tmp_path / "trace.jsonl"
    rc = trace_export.run(
        argparse.Namespace(
            url=None, input=str(src), output=str(out), longctx_threshold=None
        )
    )
    assert rc == 0
    reqs = load_trace(str(out))
    assert len(reqs) >= 2
    assert reqs[0].t_s == 0.0
    assert all(r.prompt_tokens > 0 and r.max_tokens >= 1 for r in reqs)
    ts = [r.t_s for r in reqs]
    assert ts == sorted(ts)


# --------------------------------------------------- two-subprocess smoke
@pytest.mark.slow
def test_fleet_two_subprocess_smoke(tmp_path):
    """The CI smoke: two REAL serve processes on localhost, a dialog routed
    through the FleetRouter, one peer killed mid-session — the request
    re-routes and the survivor's fleet healthz degrades."""
    import socket
    import subprocess
    import sys

    from django_assistant_bot_tpu.serving.fleet import PeerClient

    def _free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    ports = [_free_port(), _free_port()]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # CI sets DABT_FLIGHT_DIR so a red run uploads the subprocess dumps
    env.setdefault("DABT_FLIGHT_DIR", str(tmp_path / "flight"))
    procs = []
    try:
        for i, port in enumerate(ports):
            other = ports[1 - i]
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-m",
                        "django_assistant_bot_tpu.cli",
                        "serve",
                        "--tiny",
                        "--host",
                        "127.0.0.1",
                        "--port",
                        str(port),
                        "--fleet-name",
                        f"peer{i}",
                        "--fleet-peers",
                        f"peer{1 - i}=http://127.0.0.1:{other}",
                    ],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.STDOUT,
                )
            )
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        deadline = time.monotonic() + 300
        for url in urls:
            while True:
                try:
                    if PeerClient(url, timeout_s=5.0).get_json("/healthz")[
                        "status"
                    ] == "ok":
                        break
                except Exception:
                    pass
                assert time.monotonic() < deadline, "peers failed to boot"
                time.sleep(1.0)
        router = FleetRouter(
            [("peer0", urls[0]), ("peer1", urls[1])],
            model="tiny-chat",
            refresh_interval_s=1e9,
            request_timeout_s=120.0,
            health_timeout_s=3.0,
        )
        try:
            router.refresh()
            res = router.submit(
                [7] * 16, max_tokens=4, temperature=0.0
            ).result(timeout=180)
            assert res.completion_tokens > 0
            # chaos: kill peer0, keep serving through peer1
            procs[0].kill()
            procs[0].wait(30)
            router.peers[1].queued = 0
            router.peers[0].queued = 0
            done = router.submit(
                [8] * 16, max_tokens=4, temperature=0.0
            ).result(timeout=180)
            assert done.peer == "peer1"
            assert router.reroutes + router.refresh_failures >= 0
            hz = PeerClient(urls[1], timeout_s=10.0).get_json("/fleet/healthz")
            assert hz["fleet"]["status"] == "degraded"
        finally:
            router.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(30)
