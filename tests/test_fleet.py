"""Cross-process fleet plane (serving/fleet.py; docs/FLEET.md).

Evidence layers, all CPU:

- wire codec property tests: fp8/bf16/int8/f32 page snapshots encode→decode
  BIT-identical (including the boundary partial tail page) under the pinned
  DABT_KV_FUZZ_SEED; malformed and cross-build payloads fail loudly;
- the versioned-snapshot contract: HostKVTier.absorb refuses entries
  stamped by a different build (all-or-nothing), the disk tier refuses
  tampered/foreign .npz files;
- FleetRouter policy under stub peers (no sockets): precedence, token-less
  re-route + breaker feed, shed aggregation, the pool-role force retry,
  gossip application (delta + reset), prefix pull, the two-stage
  disaggregated handoff;
- live two-peer integration over REAL aiohttp servers (each hosted on its
  own thread's event loop): KV pages shipped over the wire land bit-exact
  on the receiver, a decode-pool peer serves a session whose prefill ran in
  the prefill pool with output identical to the unified arm, peer death
  re-routes token-lessly and degrades /fleet/healthz, and the dabt_fleet_*
  exposition parses;
- a @slow two-SUBPROCESS smoke (the CI step): boot two `serve --tiny`
  processes, route a dialog, kill one, assert re-route + fleet-degraded;
- fleet-wire hardening: CRC-32C integrity (truncation at every envelope
  boundary, flipped-byte rejection, v1<->v2 cross-version compat, disk
  tamper), PeerClient failure phases + injected net_* chaos, partition
  tolerance (TTL aging, digest-forced reconcile, refresh-failure reasons),
  the idempotency ledger, and live "netchaos" tests (CI's -k netchaos
  smoke): corrupt-put rejection, dedup, drop-retry, partition re-route,
  and the pull-miss -> cold-prefill fallthrough.
"""

import asyncio
import json
import os
import threading
import time

import numpy as np
import pytest

import jax

from django_assistant_bot_tpu.serving.engine import EngineUnavailable
from django_assistant_bot_tpu.serving.faults import FaultInjector
from django_assistant_bot_tpu.serving.fleet import (
    FleetPeer,
    FleetPlane,
    FleetRouter,
    PeerHTTPError,
    PeerUnreachable,
    _chain_digest,
    _flip_one_byte,
    decode_kv_entry,
    encode_kv_entry,
)
from django_assistant_bot_tpu.serving.kv_pool import (
    KV_WIRE_VERSION,
    TIER_HOST,
    HostKVTier,
    HostPrefixEntry,
    WireDecodeError,
    WireIntegrityError,
    WireVersionError,
    entry_crc32c,
)
from django_assistant_bot_tpu.serving.scheduler import SchedulerRejected

FUZZ_SEED = int(os.environ.get("DABT_KV_FUZZ_SEED", "0"))


# ---------------------------------------------------------------- wire codec
def _entry(dtype, *, length=37, page=16, layers=2, kh=1, d=4, seed=FUZZ_SEED):
    """A HostPrefixEntry with random page contents in `dtype`.  length=37
    with page=16 exercises the boundary shape: two full pages plus a
    partial COW tail page."""
    rng = np.random.default_rng(seed)
    n_pages = -(-length // page)
    shape = (layers, n_pages, kh, page, d)
    nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
    # draw raw bytes, then view as dtype: every bit pattern (NaNs, denormals,
    # fp8 codes) must survive the wire — value-space draws would miss them
    k = rng.integers(0, 256, nbytes, np.uint8).view(dtype).reshape(shape)
    v = rng.integers(0, 256, nbytes, np.uint8).view(dtype).reshape(shape)
    key = tuple(int(t) for t in rng.integers(1, 255, length))
    return HostPrefixEntry(
        key=key, length=length, k=k, v=v, nbytes=2 * nbytes, pages=n_pages
    )


def _wire_dtypes():
    import ml_dtypes

    return [
        np.float32,
        np.int8,
        np.dtype(ml_dtypes.bfloat16),
        np.dtype(ml_dtypes.float8_e4m3fn),
        np.dtype(ml_dtypes.float8_e5m2),
    ]


@pytest.mark.parametrize("dtype", _wire_dtypes(), ids=str)
def test_wire_roundtrip_bit_identical(dtype):
    ent = _entry(dtype)
    out = decode_kv_entry(encode_kv_entry(ent))
    assert out.key == ent.key and out.length == ent.length
    assert out.k.dtype == np.dtype(dtype) and out.v.dtype == np.dtype(dtype)
    assert out.k.shape == ent.k.shape and out.v.shape == ent.v.shape
    # BIT identity, not value identity: NaN payloads and fp8 codes included
    assert out.k.tobytes() == ent.k.tobytes()
    assert out.v.tobytes() == ent.v.tobytes()


def test_wire_roundtrip_fuzz_shapes():
    """Pinned-seed shape fuzz: page-aligned, single-page, and ragged-tail
    entries all round-trip bit-exactly."""
    rng = np.random.default_rng(1000 + FUZZ_SEED)
    for _ in range(10):
        length = int(rng.integers(1, 80))
        page = int(rng.choice([8, 16, 32]))
        ent = _entry(
            np.float32, length=length, page=page, seed=int(rng.integers(1 << 31))
        )
        out = decode_kv_entry(encode_kv_entry(ent))
        assert out.key == ent.key
        assert out.k.tobytes() == ent.k.tobytes()
        assert out.v.tobytes() == ent.v.tobytes()


def test_wire_rejects_malformed():
    ent = _entry(np.float32)
    data = encode_kv_entry(ent)
    with pytest.raises(ValueError):
        decode_kv_entry(b"NOTKV!" + data[6:])  # bad magic
    with pytest.raises(ValueError):
        decode_kv_entry(data[:-8])  # truncated body
    with pytest.raises(ValueError):
        decode_kv_entry(data[: len(data) // 4])  # truncated header/body


def test_wire_rejects_cross_build_version():
    ent = _entry(np.float32)
    data = bytearray(encode_kv_entry(ent))
    hlen = int.from_bytes(data[6:10], "little")
    header = json.loads(bytes(data[10 : 10 + hlen]).decode())
    header["wire_version"] = KV_WIRE_VERSION + 1
    hb = json.dumps(header, separators=(",", ":")).encode()
    tampered = data[:6] + len(hb).to_bytes(4, "little") + hb + data[10 + hlen :]
    with pytest.raises(WireVersionError):
        decode_kv_entry(bytes(tampered))


def test_absorb_rejects_unknown_wire_version_all_or_nothing():
    """A snapshot carrying even ONE cross-build entry must absorb NOTHING —
    failing loudly beats corrupting pages (the satellite contract)."""
    tier = HostKVTier(1 << 20, page_size=16)
    good = _entry(np.float32, length=16)
    bad = _entry(np.float32, length=32, seed=FUZZ_SEED + 1)
    bad.wire_version = KV_WIRE_VERSION + 1
    with pytest.raises(WireVersionError):
        tier.absorb([good, bad])
    assert tier.stats()["kv_host_entries"] == 0


def test_disk_file_rejects_cross_build_version(tmp_path):
    """A .npz written by a different build (tampered wire_version) loads as
    a MISS, never as reinterpreted pages."""
    tier = HostKVTier(
        1536, page_size=16, spill_dir=str(tmp_path), name="wire-test"
    )
    ent = _entry(np.float32, length=16, page=16)  # 1 page, 2*512B = 1024B
    assert tier.put(ent.key, ent.length, ent.k, ent.v)
    # a second entry evicts the first to disk (budget fits one)
    ent2 = _entry(np.float32, length=16, page=16, seed=FUZZ_SEED + 2)
    assert tier.put(ent2.key, ent2.length, ent2.k, ent2.v)
    files = [f for f in os.listdir(tmp_path) if f.endswith(".npz")]
    assert files, "expected a disk demotion"
    path = tmp_path / files[0]
    with np.load(path, allow_pickle=False) as z:
        blob = {name: z[name] for name in z.files}
    assert int(blob["wire_version"]) == KV_WIRE_VERSION
    blob["wire_version"] = np.asarray(KV_WIRE_VERSION + 1, np.int64)
    np.savez(path, **blob)
    # the demoted key must now MISS (and not crash): lookup promotes from
    # disk only after the version gate passes
    assert tier.lookup(list(ent.key) + [9], ent.length) is None


# ------------------------------------------- wire integrity (CRC) + versions
def _tamper_header(data: bytes, mutate) -> bytes:
    """Re-encode a wire payload with its JSON header passed through
    ``mutate`` (header-length field rewritten to match)."""
    hlen = int.from_bytes(data[6:10], "little")
    header = json.loads(bytes(data[10 : 10 + hlen]).decode())
    mutate(header)
    hb = json.dumps(header, separators=(",", ":")).encode()
    return data[:6] + len(hb).to_bytes(4, "little") + hb + data[10 + hlen :]


def test_wire_truncation_every_envelope_boundary():
    """Truncation at EVERY envelope boundary raises a clean WireDecodeError
    (a ValueError subclass — pre-CRC callers keep catching it), never an
    IndexError/struct garbage or a silently short array."""
    ent = _entry(np.float32)
    data = encode_kv_entry(ent)
    hlen = int.from_bytes(data[6:10], "little")
    k_nbytes = int(np.ascontiguousarray(ent.k).nbytes)
    cuts = [
        0,  # empty payload
        3,  # mid-magic
        6,  # magic only, header-length field missing
        8,  # mid header-length field
        10 + hlen // 2,  # mid-header JSON
        10 + hlen,  # header complete, body missing entirely
        10 + hlen + k_nbytes // 2,  # mid-K pages
        len(data) - 5,  # mid-V pages
    ]
    for cut in cuts:
        with pytest.raises(WireDecodeError):
            decode_kv_entry(data[:cut])
        with pytest.raises(ValueError):  # the hierarchy contract
            decode_kv_entry(data[:cut])


def test_wire_crc_rejects_flipped_body_byte():
    """A single flipped bit anywhere in the k/v body fails the CRC-32C and
    raises WireIntegrityError BEFORE any bytes become pages."""
    ent = _entry(np.float32)
    data = encode_kv_entry(ent)
    hlen = int.from_bytes(data[6:10], "little")
    for idx in (10 + hlen + 3, len(data) - 3):  # one in K, one in V
        bad = bytearray(data)
        bad[idx] ^= 0x01
        with pytest.raises(WireIntegrityError, match="CRC-32C"):
            decode_kv_entry(bytes(bad))
    # the injector's own mutation is exactly this failure class
    corrupted = (
        data[: 10 + hlen] + _flip_one_byte(data[10 + hlen :])
    )
    with pytest.raises(WireIntegrityError):
        decode_kv_entry(corrupted)
    # flip-of-flip restores the payload bit-exactly
    assert _flip_one_byte(_flip_one_byte(data)) == data
    assert decode_kv_entry(data).k.tobytes() == ent.k.tobytes()


def test_wire_v1_payload_accepted_by_new_decoder():
    """Cross-version compat, old->new: a v1 payload (no checksum) still
    decodes bit-identically — and, documenting the compat window's tradeoff,
    v1 corruption is NOT detectable."""
    ent = _entry(np.float32)
    v1 = _tamper_header(
        encode_kv_entry(ent),
        lambda h: (h.pop("crc32c"), h.update(wire_version=1)),
    )
    out = decode_kv_entry(v1)
    assert out.wire_version == 1 and out.crc32c is None
    assert out.k.tobytes() == ent.k.tobytes()
    assert out.v.tobytes() == ent.v.tobytes()
    # no checksum -> a flipped v1 body byte decodes silently (why v2 exists)
    hlen = int.from_bytes(v1[6:10], "little")
    flipped = v1[: 10 + hlen] + _flip_one_byte(v1[10 + hlen :])
    assert decode_kv_entry(flipped).key == ent.key


def test_wire_v2_payload_rejected_by_old_decoder(monkeypatch):
    """Cross-version compat, new->old: a decoder whose accept-set predates
    v2 refuses the CRC-stamped payload loudly (WireVersionError), never
    guesses at the header it half-understands."""
    import django_assistant_bot_tpu.serving.fleet as fleet_mod

    data = encode_kv_entry(_entry(np.float32))
    monkeypatch.setattr(fleet_mod, "WIRE_ACCEPT_VERSIONS", (1,))
    with pytest.raises(WireVersionError):
        decode_kv_entry(data)


def test_wire_v2_missing_crc_rejected():
    """A v2 header without its crc32c field is malformed, not 'optional
    integrity': WireDecodeError (a tampered header must not bypass the
    checksum by deleting it)."""
    data = _tamper_header(
        encode_kv_entry(_entry(np.float32)), lambda h: h.pop("crc32c")
    )
    with pytest.raises(WireDecodeError):
        decode_kv_entry(data)


def test_absorb_rejects_crc_mismatch_all_or_nothing():
    """A snapshot with one CRC-failed entry absorbs NOTHING, and the reject
    is counted where the bench reads it (kv_integrity_rejects)."""
    tier = HostKVTier(1 << 20, page_size=16)
    good = _entry(np.float32, length=16)
    bad = _entry(np.float32, length=32, seed=FUZZ_SEED + 1)
    bad.crc32c = entry_crc32c(bad.k, bad.v) ^ 1
    with pytest.raises(WireIntegrityError):
        tier.absorb([good, bad])
    assert tier.stats()["kv_host_entries"] == 0
    assert tier.stats()["kv_integrity_rejects"] == 1
    # honest entries (checksum intact, or none attached) absorb fine
    bad.crc32c = entry_crc32c(bad.k, bad.v)
    tier.absorb([good, bad])
    assert tier.stats()["kv_host_entries"] == 2


def _demote_one_to_disk(tmp_path, tier_name):
    """A tier sized for one entry, with a second put demoting the first to
    disk; returns (tier, demoted_entry, npz_path)."""
    tier = HostKVTier(
        1536, page_size=16, spill_dir=str(tmp_path), name=tier_name
    )
    ent = _entry(np.float32, length=16, page=16)
    assert tier.put(ent.key, ent.length, ent.k, ent.v)
    ent2 = _entry(np.float32, length=16, page=16, seed=FUZZ_SEED + 2)
    assert tier.put(ent2.key, ent2.length, ent2.k, ent2.v)
    files = [f for f in os.listdir(tmp_path) if f.endswith(".npz")]
    assert files, "expected a disk demotion"
    return tier, ent, tmp_path / files[0]


def test_disk_file_rejects_tampered_crc(tmp_path):
    """At-rest corruption: a .npz whose stored CRC no longer matches its
    bytes loads as a MISS, counted in kv_integrity_rejects."""
    tier, ent, path = _demote_one_to_disk(tmp_path, "crc-tamper")
    with np.load(path, allow_pickle=False) as z:
        blob = {name: z[name] for name in z.files}
    assert int(blob["crc32c"]) == entry_crc32c(ent.k, ent.v)
    blob["crc32c"] = np.asarray(int(blob["crc32c"]) ^ 1, np.int64)
    np.savez(path, **blob)
    assert tier.lookup(list(ent.key) + [9], ent.length) is None
    assert tier.stats()["kv_integrity_rejects"] == 1


def test_disk_file_pre_crc_layout_still_loads(tmp_path):
    """A spill file from the pre-CRC layout (no crc32c member) promotes as
    before — the integrity gate is additive, not a flag-day break."""
    tier, ent, path = _demote_one_to_disk(tmp_path, "crc-legacy")
    with np.load(path, allow_pickle=False) as z:
        blob = {name: z[name] for name in z.files}
    del blob["crc32c"]
    np.savez(path, **blob)
    got = tier.lookup(list(ent.key) + [9], ent.length)
    assert got is not None
    assert np.asarray(got.k).tobytes() == ent.k.tobytes()
    assert tier.stats()["kv_integrity_rejects"] == 0


# ------------------------------------------------------- stub-peer policy
class _StubClient:
    """In-memory PeerClient: per-path handlers, call log, no sockets."""

    def __init__(self):
        self.calls = []
        self.generate = lambda body: {
            "token_ids": [1, 2],
            "result": "ok",
            "usage": {"prompt_tokens": 3, "completion_tokens": 2},
            "length_limited": False,
        }
        self.healthz = lambda: {
            "status": "ok",
            "load": {"queued": 0, "active": 0},
            "fleet": {"pool": "unified", "seq": 0},
        }
        self.prefix = lambda since: {"seq": 0, "events": []}
        self.kv_get = lambda body: None
        self.kv_put = lambda data: {"stored": True, "pages": 0}

    def get_json(self, path, timeout_s=None):
        self.calls.append(("GET", path))
        if path.startswith("/fleet/healthz"):
            return self.healthz()
        if path.startswith("/fleet/prefix"):
            return self.prefix(int(path.rsplit("=", 1)[1]))
        raise AssertionError(path)

    def post_json(self, path, body, timeout_s=None):
        self.calls.append(("POST", path, body))
        if path == "/fleet/generate":
            return self.generate(body)
        raise AssertionError(path)

    def post_for_bytes(self, path, body, timeout_s=None):
        self.calls.append(("POST", path, body))
        if path == "/fleet/kv/get":
            return self.kv_get(body)
        raise AssertionError(path)

    def post_bytes(self, path, data, timeout_s=None):
        self.calls.append(("POST-BYTES", path))
        if path.startswith("/fleet/kv/put"):
            return self.kv_put(data)
        raise AssertionError(path)


def _mk_router(n=2, pools=None, **kw):
    peers = [
        FleetPeer(
            f"p{i}",
            f"http://stub{i}",
            client=_StubClient(),
            pool=(pools[i] if pools else "unified"),
        )
        for i in range(n)
    ]
    kw.setdefault("refresh_interval_s", 1e9)  # tests drive refresh() directly
    kw.setdefault("breaker_reset_s", 1e9)
    router = FleetRouter(peers, model="tiny-chat", **kw)
    router._last_refresh = router._clock()  # suppress the lazy first refresh
    return router, peers


def test_fleet_router_dispatch_and_contract():
    router, peers = _mk_router()
    fut = router.submit([1, 2, 3], max_tokens=4, temperature=0.0)
    res = fut.result(timeout=10)
    assert res.token_ids == [1, 2] and res.text == "ok"
    assert res.peer in ("p0", "p1") and res.reroutes == 0
    assert res.trace_id
    body = next(
        c[2] for p in peers for c in p.client.calls if c[0] == "POST"
    )
    assert body["model"] == "tiny-chat" and body["trace_id"] == res.trace_id
    with pytest.raises(ValueError):
        router.submit([1, 2], stream=object())
    router.close()


def test_fleet_router_reroutes_token_less_on_peer_death():
    router, peers = _mk_router()
    peers[1].queued = 100  # p0 is least-loaded -> chosen first

    def _dead(body):
        raise PeerUnreachable("connection refused")

    peers[0].client.generate = _dead
    res = router.submit([1, 2, 3]).result(timeout=10)
    assert res.peer == "p1" and res.reroutes == 1
    assert router.reroutes == 1
    assert not peers[0].healthy
    # breaker fed: repeated failures open it so dispatch skips the corpse
    for _ in range(3):
        peers[0].breaker.record_failure()
    assert not peers[0].breaker.allow()
    router.close()


def test_fleet_router_exhausted_reroutes_raises():
    router, peers = _mk_router(n=2, max_reroutes=1)
    for p in peers:
        p.client.generate = lambda body: (_ for _ in ()).throw(
            PeerUnreachable("dead")
        )
    with pytest.raises(EngineUnavailable):
        router.submit([1, 2, 3]).result(timeout=10)
    assert router.rerouted_failed == 1
    router.close()


def test_fleet_router_shed_aggregation():
    router, peers = _mk_router()
    for i, p in enumerate(peers):
        p.client.generate = lambda body, _i=i: (_ for _ in ()).throw(
            PeerHTTPError(
                429, "queue full", retry_after_s=2.0 + _i, reason="queue_full"
            )
        )
    with pytest.raises(SchedulerRejected) as ei:
        router.submit([1, 2, 3]).result(timeout=10)
    # the hint is the MINIMUM across sheds: retry when the first peer might
    assert ei.value.retry_after_s == 2.0
    assert router.sheds == 1
    router.close()


def test_fleet_router_pool_role_force_retry():
    """When every reject is pool_role, availability beats role purity: one
    force retry, counted."""
    router, peers = _mk_router(pools=("decode", "decode"))

    def _guarded(body):
        if body.get("force"):
            return {
                "token_ids": [7],
                "result": "forced",
                "usage": {"prompt_tokens": 3, "completion_tokens": 1},
                "length_limited": False,
            }
        raise PeerHTTPError(
            429, "pool role", retry_after_s=1.0, reason="pool_role"
        )

    for p in peers:
        p.client.generate = _guarded
    res = router.submit([1, 2, 3]).result(timeout=10)
    assert res.token_ids == [7]
    assert router.pool_role_bypasses == 1
    router.close()


def test_fleet_router_gossip_affinity_and_reset():
    router, peers = _mk_router()
    key = tuple(range(1, 9))
    peers[1].client.prefix = lambda since: {
        "seq": 3,
        "events": [
            {
                "model": "tiny-chat",
                "replica": "tiny-chat/r0",
                "event": "host_put",
                "key": list(key),
                "length": len(key),
            },
            # other models' gossip must not leak into this router's registry
            {
                "model": "other",
                "replica": "other/r0",
                "event": "host_put",
                "key": [9, 9],
                "length": 2,
            },
        ],
    }
    router.refresh()
    assert peers[1].prefix_seq == 3
    holders = router._peer_holders(list(key) + [99], len(key))
    assert set(holders) == {"p1"}
    # affinity: p1 wins dispatch for the warm session despite equal load
    res = router.submit(list(key) + [50, 51], prefix_len=len(key)).result(10)
    assert res.peer == "p1"
    assert router.affinity_hits == 1
    # reset: the peer's log was trimmed/restarted -> drop and re-apply
    peers[1].client.prefix = lambda since: {
        "seq": 10,
        "reset": True,
        "holdings": [],
    }
    router.refresh()
    assert router._peer_holders(list(key) + [99], len(key)) == {}
    router.close()


def test_fleet_router_prefix_pull():
    router, peers = _mk_router()
    key = tuple(range(1, 9))
    ent = _entry(np.float32, length=len(key))
    ent = HostPrefixEntry(
        key=key, length=len(key), k=ent.k, v=ent.v, nbytes=ent.nbytes, pages=1
    )
    peers[1].client.prefix = lambda since: {
        "seq": 1,
        "events": [
            {
                "model": "tiny-chat",
                "replica": "tiny-chat/r0",
                "event": "host_put",
                "key": list(key),
                "length": len(key),
            }
        ],
    }
    router.refresh()
    # the holder sheds, so dispatch falls to p0 — which pulls the prefix
    # from p1 before the request lands
    peers[1].client.generate = lambda body: (_ for _ in ()).throw(
        PeerHTTPError(429, "busy", retry_after_s=1.0, reason="queue_full")
    )
    peers[1].client.kv_get = lambda body: encode_kv_entry(ent)
    peers[0].client.kv_put = lambda data: {"stored": True, "pages": 1}
    res = router.submit(list(key) + [50, 51], prefix_len=len(key)).result(10)
    assert res.peer == "p0"
    assert router.prefix_pulls == 1 and router.pages_shipped == 1
    assert any(
        c[1].startswith("/fleet/kv/put") for c in peers[0].client.calls
    )
    router.close()


def test_fleet_router_disagg_handoff_two_stage():
    router, peers = _mk_router(pools=("prefill", "decode"))
    prompt = list(range(1, 101))  # suffix 100 >= handoff threshold 64
    seen = {}

    def _prefill(body):
        seen["prefill"] = body
        assert body["prefill_only"] and body["max_tokens"] == 1
        assert body["priority"] == "background"
        assert body["push_to"] == peers[1].base_url
        return {
            "token_ids": [5],
            "result": "",
            "usage": {"prompt_tokens": 100, "completion_tokens": 1},
            "length_limited": False,
            "handoff": {"pushed": True, "pages": 7, "key_tokens": 99},
        }

    def _decode(body):
        seen["decode"] = body
        assert body["prefix_len"] == 99 and not body.get("prefill_only")
        return {
            "token_ids": [5, 6, 7],
            "result": "xyz",
            "usage": {"prompt_tokens": 100, "completion_tokens": 3},
            "length_limited": False,
        }

    peers[0].client.generate = _prefill
    peers[1].client.generate = _decode
    res = router.submit(prompt, max_tokens=3, temperature=0.0).result(10)
    assert res.peer == "p1" and res.token_ids == [5, 6, 7]
    assert router.handoffs == 1 and router.pages_shipped == 7
    assert "prefill" in seen and "decode" in seen
    router.close()


# ------------------------------------------------------ plane policy units
class _StubEngine:
    replicas = None
    num_active = 0

    def __init__(self, warm=False):
        self._warm = warm

    def queued_depth(self):
        return 0

    def holds_prefix(self, prompt_ids, prefix_len):
        return self._warm


class _StubRegistry:
    def __init__(self):
        self.generators = {}
        self.embedders = {}
        self.specs = {}

    def get_generator(self, model):
        return self.generators.get(model)


def test_plane_admission_guard_roles():
    reg = _StubRegistry()
    cold = _StubEngine(warm=False)
    reg.generators["m"] = cold
    plane = FleetPlane(reg, pool="prefill", decode_max_prefill_tokens=8)
    ids = list(range(40))
    rej = plane.admission_guard(
        "m", cold, ids, 0, prefill_only=False, force=False
    )
    assert rej is not None and rej.reason == "pool_role"
    assert (
        plane.admission_guard("m", cold, ids, 0, prefill_only=True, force=False)
        is None
    )
    plane.pool = "decode"
    # long cold suffix: shed
    assert (
        plane.admission_guard("m", cold, ids, 0, prefill_only=False, force=False)
        is not None
    )
    # prefill_only never runs in the decode pool
    assert (
        plane.admission_guard("m", cold, ids, 0, prefill_only=True, force=False)
        is not None
    )
    # warm prefix covering all but a short suffix: admitted via restore
    warm = _StubEngine(warm=True)
    assert (
        plane.admission_guard(
            "m", warm, ids, len(ids) - 4, prefill_only=False, force=False
        )
        is None
    )
    # force bypasses (counted): availability beats purity
    assert (
        plane.admission_guard("m", cold, ids, 0, prefill_only=False, force=True)
        is None
    )
    assert plane.pool_bypasses == 1 and plane.pool_rejects >= 3


def test_plane_gossip_log_delta_and_reset():
    plane = FleetPlane(_StubRegistry(), pool="unified", log_size=16)
    for i in range(3):
        plane.on_tier_event("m", "m/r0", "host_put", (1, 2, i), 3)
    out = plane.prefix_events(0)
    assert out["seq"] == 3 and len(out["events"]) == 3
    assert plane.prefix_events(2)["events"][0]["key"] == [1, 2, 2]
    assert plane.prefix_events(3)["events"] == []
    # overflow the bounded log: an ancient cursor gets a reset snapshot
    for i in range(40):
        plane.on_tier_event("m", "m/r0", "host_put", (9, i), 2)
    out = plane.prefix_events(1)
    assert out.get("reset") and out["seq"] == 43
    assert "holdings" in out


# ------------------------------------------------- live two-peer integration
def _serve_app_in_thread(app):
    """Host an aiohttp app on its OWN thread's event loop (TestClient can't
    serve cross-thread traffic — its loop isn't running between requests).
    Returns (base_url, stop)."""
    from aiohttp import web

    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def _run():
        asyncio.set_event_loop(loop)

        async def _up():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            state["runner"] = runner
            state["port"] = runner.addresses[0][1]

        loop.run_until_complete(_up())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    assert started.wait(30), "fleet peer server failed to start"

    def _stop():
        async def _down():
            await state["runner"].cleanup()

        try:
            asyncio.run_coroutine_threadsafe(_down(), loop).result(20)
        except Exception:
            pass
        loop.call_soon_threadsafe(loop.stop)
        t.join(10)

    return f"http://127.0.0.1:{state['port']}", _stop


def _tiny_fleet_config():
    return {
        "tiny-chat": {
            "kind": "decoder",
            "tiny": True,
            "max_slots": 2,
            "max_seq_len": 128,
            "kv_host_bytes": 1 << 26,
            "prefix_min_tokens": 4,
            "prefix_cache": 8,
        }
    }


@pytest.fixture(scope="module")
def fleet_pair():
    """Two REAL serve stacks (registry + engine + fleet plane + aiohttp app)
    on localhost — separate engines and KV pools, same tiny weights
    (llama.init is seed-deterministic), exactly the cross-process shape
    minus the fork."""
    from django_assistant_bot_tpu.serving.registry import ModelRegistry
    from django_assistant_bot_tpu.serving.server import create_app

    regs, planes, urls, stops = [], [], [], []
    for name in ("a", "b"):
        reg = ModelRegistry.from_config(_tiny_fleet_config())
        plane = FleetPlane(reg, name=name, pool="unified")
        reg.fleet_plane = plane
        url, stop = _serve_app_in_thread(create_app(reg))
        regs.append(reg)
        planes.append(plane)
        urls.append(url)
        stops.append(stop)
    planes[0].peers = [("b", urls[1])]
    planes[1].peers = [("a", urls[0])]
    yield regs, planes, urls
    for stop in stops:
        stop()
    for reg in regs:
        reg.stop()


def _fleet_generate(url, body, timeout=120.0):
    from django_assistant_bot_tpu.serving.fleet import PeerClient

    return PeerClient(url, timeout_s=timeout).post_json("/fleet/generate", body)


def test_fleet_kv_ships_bit_identical_across_processes(fleet_pair):
    """The acceptance bit-identity arm: register a prefix on peer A, ship it
    over /fleet/kv/get -> /fleet/kv/put to peer B, and assert B's host tier
    holds byte-identical pages — then B serves the same dialog with token
    ids identical to A's (restore across the process boundary)."""
    from django_assistant_bot_tpu.serving.fleet import PeerClient

    regs, planes, urls = fleet_pair
    prompt = [1 + (i % 250) for i in range(40)]
    plen = 16
    body = {
        "model": "tiny-chat",
        "prompt_ids": prompt,
        "max_tokens": 8,
        "temperature": 0.0,
        "prefix_len": plen,
    }
    ra = _fleet_generate(urls[0], body)
    assert ra["token_ids"], ra
    # A registered prompt[:16]; export it over the wire
    data = PeerClient(urls[0]).post_for_bytes(
        "/fleet/kv/get",
        {"model": "tiny-chat", "prompt_ids": prompt, "prefix_len": plen},
    )
    assert data is not None, "peer A should hold the registered prefix"
    ent = decode_kv_entry(data)
    assert ent.key == tuple(prompt[:plen])
    out = PeerClient(urls[1]).post_bytes(
        "/fleet/kv/put?model=tiny-chat", data
    )
    assert out["stored"], out
    # receiver-side bytes are BIT-identical to the wire payload
    tier_b = regs[1].generators["tiny-chat"].kv_host_tier
    got = tier_b.export_entry(ent.key)
    assert got is not None
    assert np.asarray(got.k).tobytes() == np.asarray(ent.k).tobytes()
    assert np.asarray(got.v).tobytes() == np.asarray(ent.v).tobytes()
    # and B serves the same dialog via restore with identical output
    restores_before = tier_b.stats()["kv_host_restores"]
    rb = _fleet_generate(urls[1], body)
    assert rb["token_ids"] == ra["token_ids"]
    assert tier_b.stats()["kv_host_restores"] > restores_before


def test_fleet_router_live_dispatch_and_gossip(fleet_pair):
    regs, planes, urls = fleet_pair
    router = FleetRouter(
        [("a", urls[0]), ("b", urls[1])],
        model="tiny-chat",
        refresh_interval_s=1e9,
        request_timeout_s=120.0,
    )
    try:
        router.refresh()
        assert all(p.healthy for p in router.peers)
        res = router.submit(
            [2 + (i % 200) for i in range(24)],
            max_tokens=6,
            temperature=0.0,
            prefix_len=8,
        ).result(timeout=120)
        assert res.completion_tokens > 0 and res.peer in ("a", "b")
        # the serving peer registered the prefix; gossip makes the router's
        # registry point affinity at it
        router.refresh()
        holders = router._peer_holders([2 + (i % 200) for i in range(24)], 8)
        assert res.peer in holders
    finally:
        router.close()


def test_fleet_peer_kill_reroute_and_degraded_healthz(fleet_pair):
    """The chaos arm: a dead peer re-routes token-lessly (goodput 1.0) and
    the survivor's /fleet/healthz reports the fleet degraded."""
    from django_assistant_bot_tpu.serving.fleet import PeerClient
    from django_assistant_bot_tpu.serving.registry import ModelRegistry
    from django_assistant_bot_tpu.serving.server import create_app

    regs, planes, urls = fleet_pair
    reg_c = ModelRegistry.from_config(_tiny_fleet_config())
    reg_c.fleet_plane = FleetPlane(reg_c, name="c", pool="unified")
    url_c, stop_c = _serve_app_in_thread(create_app(reg_c))
    router = FleetRouter(
        [("c", url_c), ("a", urls[0])],
        model="tiny-chat",
        refresh_interval_s=1e9,
        request_timeout_s=120.0,
        health_timeout_s=2.0,
    )
    old_peers = list(planes[0].peers)
    try:
        # warm path through c first (deterministic: a looks loaded; suppress
        # the lazy refresh so the fake load survives until dispatch)
        router._last_refresh = router._clock()
        router.peers[1].queued = 100
        res = router.submit([3] * 12, max_tokens=4, temperature=0.0).result(120)
        assert res.peer == "c"
        stop_c()
        reg_c.stop()
        # token-less re-route: every request still completes (goodput 1.0)
        done = [
            router.submit([4] * 12, max_tokens=4, temperature=0.0).result(120)
            for _ in range(2)
        ]
        assert all(r.peer == "a" for r in done)
        assert router.reroutes >= 1
        # the survivor's fleet healthz degrades on the unreachable peer
        planes[0].peers = [("c", url_c)]
        hz = PeerClient(urls[0]).get_json("/fleet/healthz")
        assert hz["fleet"]["status"] == "degraded"
        assert hz["fleet"]["peers_reachable"] == 0
    finally:
        planes[0].peers = old_peers
        router.close()


def test_fleet_disagg_prefill_decode_output_identity(fleet_pair):
    """The acceptance disaggregation arm: a decode-pool replica serves a
    session whose prefill ran in the prefill pool, output identical to the
    unified arm, with pages shipped over the wire and admitted via restore."""
    regs, planes, urls = fleet_pair
    # token alphabet disjoint from every other test in this module: a shared
    # first-token prefix would let B serve from its device prefix registry
    # (warmed by an earlier test) and skip the host-tier restore under test
    prompt = [11 + (i % 180) for i in range(80)]
    # unified reference first (pools still unified)
    ref = _fleet_generate(
        urls[0],
        {
            "model": "tiny-chat",
            "prompt_ids": prompt,
            "max_tokens": 8,
            "temperature": 0.0,
        },
    )
    assert ref["token_ids"]
    tier_b = regs[1].generators["tiny-chat"].kv_host_tier
    restores_before = tier_b.stats()["kv_host_restores"]
    planes[0].pool = "prefill"
    planes[1].pool = "decode"
    router = FleetRouter(
        [
            FleetPeer("a", urls[0], pool="prefill", timeout_s=120.0),
            FleetPeer("b", urls[1], pool="decode", timeout_s=120.0),
        ],
        model="tiny-chat",
        refresh_interval_s=1e9,
        request_timeout_s=120.0,
        handoff_suffix_tokens=64,
    )
    try:
        res = router.submit(prompt, max_tokens=8, temperature=0.0).result(120)
        assert res.token_ids == ref["token_ids"], (
            "disaggregated output must match the unified arm bit-for-bit"
        )
        assert res.peer == "b"
        assert router.handoffs == 1 and router.pages_shipped > 0
        assert planes[1].kv_puts >= 1
        assert tier_b.stats()["kv_host_restores"] > restores_before
    finally:
        planes[0].pool = "unified"
        planes[1].pool = "unified"
        router.close()


def test_fleet_metrics_exposition_parses(fleet_pair):
    from django_assistant_bot_tpu.serving.fleet import PeerClient
    from django_assistant_bot_tpu.serving.obs import (
        parse_prometheus_text,
        render_prometheus,
    )

    regs, planes, urls = fleet_pair
    # attach a fleet router so BOTH gauge families render
    router = FleetRouter(
        [("b", urls[1])], model="tiny-chat", refresh_interval_s=1e9
    )
    regs[0].fleet_router = router
    try:
        text = render_prometheus(regs[0])
    finally:
        del regs[0].fleet_router
        router.close()
    names = set(parse_prometheus_text(text))
    for want in (
        "dabt_fleet_pool_info",
        "dabt_fleet_kv_puts_total",
        "dabt_fleet_peers_total",
        "dabt_fleet_reroutes_total",
        "dabt_fleet_pages_shipped_total",
    ):
        assert want in names, (want, sorted(names)[:8])


def test_traces_endpoint_and_workload_export(fleet_pair, tmp_path):
    """Satellite: the obs trace ring exports to the workload JSONL format
    and replays structurally (sorted arrivals, positive budgets)."""
    import argparse

    from django_assistant_bot_tpu.cli import trace_export
    from django_assistant_bot_tpu.serving.fleet import PeerClient
    from django_assistant_bot_tpu.workload.generator import load_trace

    regs, planes, urls = fleet_pair
    # ensure at least two finished requests ride the ring
    for i in range(2):
        _fleet_generate(
            urls[0],
            {
                "model": "tiny-chat",
                "prompt_ids": [5 + i] * 10,
                "max_tokens": 3,
                "temperature": 0.0,
            },
        )
    body = PeerClient(urls[0]).get_json("/traces")
    assert body["traces"], "expected finished traces on the ring"
    src = tmp_path / "traces.json"
    src.write_text(json.dumps(body))
    out = tmp_path / "trace.jsonl"
    rc = trace_export.run(
        argparse.Namespace(
            url=None, input=str(src), output=str(out), longctx_threshold=None
        )
    )
    assert rc == 0
    reqs = load_trace(str(out))
    assert len(reqs) >= 2
    assert reqs[0].t_s == 0.0
    assert all(r.prompt_tokens > 0 and r.max_tokens >= 1 for r in reqs)
    ts = [r.t_s for r in reqs]
    assert ts == sorted(ts)


# --------------------------------------------------- two-subprocess smoke
@pytest.mark.slow
def test_fleet_two_subprocess_smoke(tmp_path):
    """The CI smoke: two REAL serve processes on localhost, a dialog routed
    through the FleetRouter, one peer killed mid-session — the request
    re-routes and the survivor's fleet healthz degrades."""
    import socket
    import subprocess
    import sys

    from django_assistant_bot_tpu.serving.fleet import PeerClient

    def _free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    ports = [_free_port(), _free_port()]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # CI sets DABT_FLIGHT_DIR so a red run uploads the subprocess dumps
    env.setdefault("DABT_FLIGHT_DIR", str(tmp_path / "flight"))
    procs = []
    try:
        for i, port in enumerate(ports):
            other = ports[1 - i]
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-m",
                        "django_assistant_bot_tpu.cli",
                        "serve",
                        "--tiny",
                        "--host",
                        "127.0.0.1",
                        "--port",
                        str(port),
                        "--fleet-name",
                        f"peer{i}",
                        "--fleet-peers",
                        f"peer{1 - i}=http://127.0.0.1:{other}",
                    ],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.STDOUT,
                )
            )
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        deadline = time.monotonic() + 300
        for url in urls:
            while True:
                try:
                    if PeerClient(url, timeout_s=5.0).get_json("/healthz")[
                        "status"
                    ] == "ok":
                        break
                except Exception:
                    pass
                assert time.monotonic() < deadline, "peers failed to boot"
                time.sleep(1.0)
        router = FleetRouter(
            [("peer0", urls[0]), ("peer1", urls[1])],
            model="tiny-chat",
            refresh_interval_s=1e9,
            request_timeout_s=120.0,
            health_timeout_s=3.0,
        )
        try:
            router.refresh()
            res = router.submit(
                [7] * 16, max_tokens=4, temperature=0.0
            ).result(timeout=180)
            assert res.completion_tokens > 0
            # chaos: kill peer0, keep serving through peer1
            procs[0].kill()
            procs[0].wait(30)
            router.peers[1].queued = 0
            router.peers[0].queued = 0
            done = router.submit(
                [8] * 16, max_tokens=4, temperature=0.0
            ).result(timeout=180)
            assert done.peer == "peer1"
            assert router.reroutes + router.refresh_failures >= 0
            hz = PeerClient(urls[1], timeout_s=10.0).get_json("/fleet/healthz")
            assert hz["fleet"]["status"] == "degraded"
        finally:
            router.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(30)


# --------------------------------------------- peer client: phases + chaos
def _closed_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_peer_client_connect_refused_is_connect_phase():
    from django_assistant_bot_tpu.serving.fleet import PeerClient

    cli = PeerClient(
        f"http://127.0.0.1:{_closed_port()}", timeout_s=2.0,
        connect_timeout_s=1.0,
    )
    with pytest.raises(PeerUnreachable) as ei:
        cli.get_json("/fleet/healthz")
    assert ei.value.phase == "connect"


def test_peer_client_read_timeout_is_read_phase():
    """A peer that accepts the connection but never answers dies in the READ
    phase — the request may have executed, so the router must dedup, not
    re-route."""
    from aiohttp import web

    from django_assistant_bot_tpu.serving.fleet import PeerClient

    async def slow(request):
        await asyncio.sleep(5.0)
        return web.json_response({})

    app = web.Application()
    app.router.add_get("/slow", slow)
    url, stop = _serve_app_in_thread(app)
    try:
        cli = PeerClient(url, timeout_s=0.2, connect_timeout_s=2.0)
        with pytest.raises(PeerUnreachable) as ei:
            cli.get_json("/slow")
        assert ei.value.phase == "read"
    finally:
        stop()


def test_peer_client_retries_connect_phase_with_backoff():
    """Connect-phase retries back off exponentially through the INJECTABLE
    sleep; the injected partition consumes every attempt, so no socket is
    ever touched."""
    from django_assistant_bot_tpu.serving.fleet import PeerClient

    inj = FaultInjector({})
    inj.arm("net_partition", 3, key="r->p")
    sleeps = []
    cli = PeerClient(
        "http://127.0.0.1:1", timeout_s=1.0, sleep=sleeps.append,
        injector=inj, fault_key="r->p",
    )
    with pytest.raises(PeerUnreachable) as ei:
        cli._request("GET", "/x", retries=2)
    assert ei.value.phase == "connect"
    assert sleeps == [0.05, 0.1]
    assert inj.stats()["net_partition[r->p]"]["fires"] == 3


def test_peer_client_never_retries_read_phase():
    """Read-phase failures are NOT blindly re-sent by the client (the peer
    may have executed the request); recovery belongs to the router's
    idempotency-keyed same-peer retry."""
    from django_assistant_bot_tpu.serving.fleet import PeerClient

    sleeps = []
    cli = PeerClient("http://127.0.0.1:1", sleep=sleeps.append)
    cli._request_once = lambda *a, **k: (_ for _ in ()).throw(
        PeerUnreachable("connection reset mid-read", phase="read")
    )
    with pytest.raises(PeerUnreachable) as ei:
        cli._request("GET", "/x", retries=3)
    assert ei.value.phase == "read" and sleeps == []


def test_peer_client_net_delay_injected_through_sleep():
    from django_assistant_bot_tpu.serving.fleet import PeerClient

    inj = FaultInjector({"net_delay": {"fire_on": [1], "delay_s": 0.7}})
    sleeps = []
    cli = PeerClient(
        f"http://127.0.0.1:{_closed_port()}", timeout_s=1.0,
        connect_timeout_s=0.5, sleep=sleeps.append, injector=inj,
    )
    with pytest.raises(PeerUnreachable):
        cli.get_json("/x")
    assert sleeps == [0.7]


# ----------------------------------- router: partition tolerance (stubbed)
def test_fleet_router_refresh_failure_reasons_classified():
    """The operator triaging a partition needs WHY refresh failed — each
    failure shape lands under its own reason label and on the peer row."""
    router, peers = _mk_router(n=1)

    def _raiser(exc):
        def _f(path, timeout_s=None, retries=0):
            raise exc

        return _f

    cases = [
        (PeerUnreachable("connection refused"), "conn_refused"),
        (PeerUnreachable("read timed out", phase="read"), "timeout"),
        (PeerUnreachable("no route to host"), "unreachable"),
        (PeerHTTPError(503, "upstream sad"), "http_5xx"),
        (ValueError("bogus json"), "bad_payload"),
    ]
    for exc, want in cases:
        peers[0].client.get_json = _raiser(exc)
        router.refresh()
        assert peers[0].last_failure_reason == want
        assert not peers[0].healthy
    st = router.stats()
    assert st["refresh_failures"] == len(cases)
    assert st["refresh_failure_reasons"] == {
        "conn_refused": 1, "timeout": 1, "unreachable": 1,
        "http_5xx": 1, "bad_payload": 1,
    }
    assert st["peers"][0]["last_failure_reason"] == "bad_payload"
    assert any(
        r["event"] == "peer_unhealthy" and r.get("reason") == "conn_refused"
        for r in router.flight.events()
    )
    router.close()


def test_fleet_router_ttl_drop_and_heal_reconcile():
    """Partition tolerance end-to-end on a fake clock: gossip-learned
    affinity ages out once the holder is unreachable past registry_ttl_s,
    and the heal forces a reset-snapshot reconcile whose convergence time
    lands in reconcile_last_s."""
    t = [0.0]
    router, peers = _mk_router(registry_ttl_s=10.0, clock=lambda: t[0])
    key = tuple(range(1, 9))
    ev = {
        "model": "tiny-chat", "replica": "tiny-chat/r0",
        "event": "host_put", "key": list(key), "length": len(key),
    }
    peers[1].client.prefix = lambda since: {"seq": 1, "events": [ev]}
    router.refresh()
    assert set(router._peer_holders(list(key) + [99], len(key))) == {"p1"}

    healthz_ok = peers[1].client.get_json

    def _dead(path, timeout_s=None, retries=0):
        raise PeerUnreachable("connection refused")

    peers[1].client.get_json = _dead
    t[0] = 1.0
    router.refresh()  # failure starts the unreachable streak, no drop yet
    assert set(router._peer_holders(list(key) + [99], len(key))) == {"p1"}
    assert router.ttl_drops == 0 and peers[1].unreachable_since == 1.0
    t[0] = 11.0
    router.refresh()  # 10s unreachable: affinity claims age out, ONCE
    assert router._peer_holders(list(key) + [99], len(key)) == {}
    assert router.ttl_drops == 1 and peers[1].ttl_dropped
    t[0] = 12.0
    router.refresh()
    assert router.ttl_drops == 1  # not re-counted while still down
    assert any(
        r["event"] == "registry_ttl_drop" for r in router.flight.events()
    )

    # heal: the next successful refresh forces the anti-entropy reset
    def _reset_snapshot(since):
        assert since == -1, "heal after TTL drop must force the reset path"
        t[0] += 0.5  # the exchange itself takes measurable time
        return {
            "seq": 9, "digest": 4242, "reset": True,
            "holdings": [
                {
                    "model": "tiny-chat", "replica": "tiny-chat/r0",
                    "key": list(key), "length": len(key), "tier": TIER_HOST,
                }
            ],
        }

    peers[1].client.get_json = healthz_ok
    peers[1].client.prefix = _reset_snapshot
    t[0] = 20.0
    router.refresh()
    assert set(router._peer_holders(list(key) + [99], len(key))) == {"p1"}
    assert router.reconciles == 1
    assert router.reconcile_last_s == pytest.approx(0.5)
    assert peers[1].prefix_seq == 9 and peers[1].prefix_digest == 4242
    assert not peers[1].ttl_dropped and peers[1].unreachable_since is None
    assert any(
        r["event"] == "gossip_reconciled" for r in router.flight.events()
    )
    router.close()


def test_fleet_router_gossip_digest_mismatch_forces_reset():
    """A delta whose chained digest disagrees with the server's forces the
    reset-snapshot path in the SAME refresh — diverged logs never skew
    affinity silently."""
    router, peers = _mk_router()
    key = tuple(range(1, 9))
    ev = {
        "model": "tiny-chat", "replica": "tiny-chat/r0",
        "event": "host_put", "key": list(key), "length": len(key),
    }
    assert _chain_digest(0, ev) != 999999  # the advertised digest is wrong

    def _prefix(since):
        if since >= 0:
            return {"seq": 2, "digest": 999999, "events": [ev]}
        return {
            "seq": 5, "digest": 4242, "reset": True,
            "holdings": [
                {
                    "model": "tiny-chat", "replica": "tiny-chat/r0",
                    "key": list(key), "length": len(key), "tier": TIER_HOST,
                }
            ],
        }

    peers[1].client.prefix = _prefix
    router.refresh()
    assert router.gossip_digest_mismatches == 1
    assert router.reconciles == 1  # the forced reset IS a reconcile
    assert peers[1].prefix_seq == 5 and peers[1].prefix_digest == 4242
    assert set(router._peer_holders(list(key) + [99], len(key))) == {"p1"}
    assert any(
        r["event"] == "gossip_digest_mismatch"
        for r in router.flight.events()
    )
    router.close()


def test_plane_prefix_events_digest_matches_follower_chain():
    """Both delta and reset shapes carry the rolling digest, and a follower
    chaining _chain_digest over the delta events reproduces it exactly —
    the divergence check is sound, not a tautology."""
    plane = FleetPlane(_StubRegistry(), pool="unified", log_size=16)
    for i in range(3):
        plane.on_tier_event("m", "m/r0", "host_put", (1, 2, i), 3)
    out = plane.prefix_events(0)
    d = 0
    for ev in out["events"]:
        d = _chain_digest(d, ev)
    assert d == out["digest"] != 0
    for i in range(40):  # overflow the log -> reset shape
        plane.on_tier_event("m", "m/r0", "host_put", (9, i), 2)
    out2 = plane.prefix_events(1)
    assert out2.get("reset") and isinstance(out2["digest"], int)
    assert out2["digest"] != out["digest"]


# -------------------------------------- router: idempotent read-phase retry
def test_fleet_router_read_failure_retries_same_peer_same_key():
    """A read-phase death retries the SAME peer under the SAME idempotency
    key (the peer may have executed it — re-routing is what double-executes);
    no breaker failure, no reroute counted."""
    router, peers = _mk_router(timeout_retries=1)
    peers[1].queued = 100  # p0 is chosen first
    calls = {"n": 0}

    def _flaky(body):
        calls["n"] += 1
        if calls["n"] == 1:
            raise PeerUnreachable("connection reset by peer", phase="read")
        return {
            "token_ids": [1, 2], "result": "ok",
            "usage": {"prompt_tokens": 3, "completion_tokens": 2},
            "length_limited": False,
        }

    peers[0].client.generate = _flaky
    res = router.submit([1, 2, 3]).result(timeout=10)
    assert res.peer == "p0" and res.reroutes == 0
    assert router.timeout_retries_total == 1 and router.reroutes == 0
    bodies = [c[2] for c in peers[0].client.calls if c[0] == "POST"]
    assert len(bodies) == 2
    assert bodies[0]["idem_key"] == bodies[1]["idem_key"]
    assert bodies[0]["idem_key"] == f"{res.trace_id}:0"
    assert peers[0].healthy and peers[0].breaker.allow()
    assert any(
        r["event"] == "timeout_retry" for r in router.flight.events()
    )
    router.close()


def test_fleet_router_read_retries_exhausted_falls_to_reroute():
    router, peers = _mk_router(timeout_retries=0)
    peers[1].queued = 100
    peers[0].client.generate = lambda body: (_ for _ in ()).throw(
        PeerUnreachable("connection reset by peer", phase="read")
    )
    res = router.submit([1, 2, 3]).result(timeout=10)
    assert res.peer == "p1" and res.reroutes == 1
    assert router.timeout_retries_total == 0
    router.close()


def test_fleet_router_caller_attempt_feeds_idem_key():
    """submit(attempt=) is the CALLER's retry ordinal: bumping it asks for a
    fresh execution, reusing it dedups server-side."""
    router, peers = _mk_router()
    router.submit([1, 2, 3], trace_id="t-idem", attempt=0).result(10)
    router.submit([1, 2, 3], trace_id="t-idem", attempt=1).result(10)
    keys = {
        c[2]["idem_key"]
        for p in peers
        for c in p.client.calls
        if c[0] == "POST"
    }
    assert keys == {"t-idem:0", "t-idem:1"}
    router.close()


# ------------------------------------------ router: pull integrity re-fetch
def _pull_setup(router, peers):
    """Gossip p1 as holder of an 8-token prefix, p1 shedding, so dispatch
    lands on p0 which pulls from p1 first (mirrors the prefix-pull test)."""
    key = tuple(range(1, 9))
    ent = _entry(np.float32, length=len(key))
    ent = HostPrefixEntry(
        key=key, length=len(key), k=ent.k, v=ent.v, nbytes=ent.nbytes, pages=1
    )
    peers[1].client.prefix = lambda since: {
        "seq": 1,
        "events": [
            {
                "model": "tiny-chat", "replica": "tiny-chat/r0",
                "event": "host_put", "key": list(key), "length": len(key),
            }
        ],
    }
    router.refresh()
    peers[1].client.generate = lambda body: (_ for _ in ()).throw(
        PeerHTTPError(429, "busy", retry_after_s=1.0, reason="queue_full")
    )
    peers[1].client.kv_get = lambda body: encode_kv_entry(ent)
    return key


def test_fleet_router_pull_integrity_reject_refetches_once():
    """A pull whose payload rots in flight re-fetches ONCE from the holder
    (which still has the intact entry) before giving up — counted on both
    the reject and refetch gauges."""
    router, peers = _mk_router()
    key = _pull_setup(router, peers)
    puts = {"n": 0}

    def _put(data):
        puts["n"] += 1
        if puts["n"] == 1:
            raise PeerHTTPError(
                422, "CRC-32C mismatch", reason="wire_integrity"
            )
        return {"stored": True, "pages": 1}

    peers[0].client.kv_put = _put
    res = router.submit(list(key) + [50, 51], prefix_len=len(key)).result(10)
    assert res.peer == "p0"
    assert router.pull_integrity_rejects == 1 and router.pull_refetches == 1
    assert router.prefix_pulls == 1 and router.pages_shipped == 1
    assert router.pull_failures == 0
    fetches = [
        c for c in peers[1].client.calls if c[1] == "/fleet/kv/get"
    ]
    assert len(fetches) == 2
    router.close()


def test_fleet_router_pull_double_corruption_cold_prefills():
    """Two corrupt transfers in a row: give up on the pull (cold prefill on
    the target), NEVER absorb garbage — and the request still succeeds."""
    router, peers = _mk_router()
    key = _pull_setup(router, peers)
    peers[0].client.kv_put = lambda data: (_ for _ in ()).throw(
        PeerHTTPError(422, "CRC-32C mismatch", reason="wire_integrity")
    )
    res = router.submit(list(key) + [50, 51], prefix_len=len(key)).result(10)
    assert res.peer == "p0"
    assert router.pull_integrity_rejects == 2 and router.pull_refetches == 1
    assert router.prefix_pulls == 0 and router.pull_failures == 1
    router.close()


# ------------------------------------------------- plane: idempotency ledger
def test_plane_idem_claim_complete_hit_and_coalesce():
    plane = FleetPlane(_StubRegistry(), pool="unified")
    state, fut = plane.idem_claim("k1")
    assert state == "mine"
    # a dup arriving while in flight coalesces onto the SAME future
    state2, fut2 = plane.idem_claim("k1")
    assert state2 == "wait" and fut2 is fut
    assert plane.idem_coalesced == 1
    plane.idem_complete("k1", fut, {"result": "done"})
    assert fut.result(1) == {"result": "done"}
    # a dup arriving after completion is a hit on the recorded payload
    state3, fut3 = plane.idem_claim("k1")
    assert state3 == "wait" and fut3.result(1) == {"result": "done"}
    assert plane.idem_hits == 1 and plane.idem_executions == 1


def test_plane_idem_release_reexecutes():
    """A failed execution releases the key: waiters get None (their cue to
    claim afresh) and a retry re-executes instead of replaying a failure."""
    plane = FleetPlane(_StubRegistry(), pool="unified")
    _, fut = plane.idem_claim("k2")
    _, waiter = plane.idem_claim("k2")
    plane.idem_release("k2", fut)
    assert waiter.result(1) is None
    state, fut2 = plane.idem_claim("k2")
    assert state == "mine" and fut2 is not fut
    assert plane.idem_executions == 2


def test_plane_idem_ledger_bounded_done_first_eviction():
    """The ledger is bounded; COMPLETED entries evict before in-flight ones
    (an in-flight execution must never be forgotten while a dup could still
    arrive)."""
    plane = FleetPlane(_StubRegistry(), pool="unified", idem_ledger_size=8)
    _, done_fut = plane.idem_claim("done")
    plane.idem_complete("done", done_fut, {"ok": True})
    inflight = [plane.idem_claim(f"x{i}")[1] for i in range(9)]
    assert plane.idem_evictions == 2  # "done" first, then the oldest x
    assert "done" not in plane._idem and "x0" not in plane._idem
    assert all(f"x{i}" in plane._idem for i in range(1, 9))
    for i, f in enumerate(inflight):
        plane.idem_release(f"x{i}", f)


# ---------------------------------------- live network chaos (CI -k netchaos)
def test_fleet_netchaos_corrupt_kv_put_rejected_live(fleet_pair):
    """An in-flight bit flip on /fleet/kv/put fails the CRC on the RECEIVER:
    422 with reason=wire_integrity, counted, and nothing absorbed."""
    from django_assistant_bot_tpu.serving.fleet import PeerClient

    regs, planes, urls = fleet_pair
    inj = FaultInjector({})
    cli = PeerClient(urls[1], injector=inj, fault_key="probe")
    data = encode_kv_entry(_entry(np.float32, length=16))
    rejects_before = planes[1].kv_integrity_rejects
    puts_before = planes[1].kv_puts
    inj.arm("net_corrupt", 1, key="probe")
    with pytest.raises(PeerHTTPError) as ei:
        cli.post_bytes("/fleet/kv/put?model=tiny-chat", data)
    assert ei.value.status == 422 and ei.value.reason == "wire_integrity"
    assert planes[1].kv_integrity_rejects == rejects_before + 1
    assert planes[1].kv_puts == puts_before  # nothing absorbed
    # the same payload clean passes the CRC gate (geometry may still refuse
    # storage — that is a different, non-integrity verdict)
    try:
        cli.post_bytes("/fleet/kv/put?model=tiny-chat", data)
    except PeerHTTPError as e:
        assert e.reason != "wire_integrity"
    assert planes[1].kv_integrity_rejects == rejects_before + 1


def test_fleet_netchaos_idem_dedup_live(fleet_pair):
    """Two /fleet/generate POSTs under one idem_key execute ONCE: the second
    returns the recorded response marked deduped, under its own request id."""
    regs, planes, urls = fleet_pair
    body = {
        "model": "tiny-chat",
        "prompt_ids": [21 + (i % 160) for i in range(12)],
        "max_tokens": 3,
        "temperature": 0.0,
        "idem_key": "netchaos-dedup:0",
    }
    exec_before = planes[0].idem_executions
    r1 = _fleet_generate(urls[0], body)
    r2 = _fleet_generate(urls[0], body)
    assert r2.get("deduped") is True and not r1.get("deduped")
    assert r2["token_ids"] == r1["token_ids"]
    assert r2["request_id"] != r1["request_id"]
    assert planes[0].idem_executions == exec_before + 1
    assert planes[0].idem_hits >= 1


def test_fleet_netchaos_drop_read_retry_dedup_live(fleet_pair):
    """net_drop mid-request: the router retries the SAME peer under the same
    idem key; the peer (which DID execute the first send) dedups — goodput 1,
    duplicate executions 0."""
    regs, planes, urls = fleet_pair
    inj = FaultInjector({})
    router = FleetRouter(
        [("a", urls[0]), ("b", urls[1])],
        model="tiny-chat", name="netchaos", refresh_interval_s=1e9,
        request_timeout_s=120.0, injector=inj, timeout_retries=1,
    )
    exec_before = planes[0].idem_executions
    dups_before = planes[0].idem_hits + planes[0].idem_coalesced
    try:
        router._last_refresh = router._clock()
        router.peers[1].queued = 100  # a is chosen first
        inj.arm("net_drop", 1, key="netchaos->a")
        res = router.submit(
            [31 + (i % 140) for i in range(12)], max_tokens=4, temperature=0.0
        ).result(timeout=120)
        assert res.peer == "a" and res.reroutes == 0
        assert res.completion_tokens > 0
        assert router.timeout_retries_total == 1
        assert planes[0].idem_executions == exec_before + 1  # no double exec
        assert planes[0].idem_hits + planes[0].idem_coalesced >= dups_before + 1
    finally:
        router.close()


def test_fleet_netchaos_partition_reroute_live(fleet_pair):
    """An injected partition on one router edge re-routes token-lessly to
    the reachable peer: goodput stays 1.0."""
    regs, planes, urls = fleet_pair
    inj = FaultInjector({})
    router = FleetRouter(
        [("a", urls[0]), ("b", urls[1])],
        model="tiny-chat", name="netchaos", refresh_interval_s=1e9,
        request_timeout_s=120.0, injector=inj,
    )
    try:
        router._last_refresh = router._clock()
        router.peers[1].queued = 100  # a preferred... but partitioned
        inj.arm("net_partition", 1, key="netchaos->a")
        res = router.submit(
            [41] * 12, max_tokens=4, temperature=0.0
        ).result(timeout=120)
        assert res.peer == "b" and res.reroutes == 1
        assert router.reroutes == 1
    finally:
        router.close()


def test_fleet_netchaos_pull_miss_cold_prefill_live(fleet_pair):
    """Satellite: the /fleet/kv/get pull-miss path.  Gossip claims a holder
    whose entry is gone (evicted) — the 404 is an honest miss, the target
    falls through to cold prefill, and the CLIENT request never errors."""
    regs, planes, urls = fleet_pair
    router = FleetRouter(
        [("a", urls[0]), ("b", urls[1])],
        model="tiny-chat", name="netchaos", refresh_interval_s=1e9,
        request_timeout_s=120.0,
    )
    key = tuple(51 + (i % 100) for i in range(8))
    try:
        router._last_refresh = router._clock()
        # a STALE gossip claim: b never actually stored this prefix
        router.prefix_registry.apply_holding(
            "b/tiny-chat/r0", key, len(key), TIER_HOST
        )
        router._note_rep("b", "b/tiny-chat/r0")
        # open b's breaker so dispatch lands on a (the non-holder) while b
        # stays healthy enough to be pulled from
        for _ in range(3):
            router.peers[1].breaker.record_failure()
        assert not router.peers[1].breaker.allow()
        res = router.submit(
            list(key) + [60, 61, 62, 63],
            max_tokens=4, temperature=0.0, prefix_len=len(key),
        ).result(timeout=120)
        assert res.peer == "a" and res.completion_tokens > 0
        assert router.pull_misses == 1 and router.prefix_pulls == 0
        assert router.pull_failures == 0
    finally:
        router.close()
