"""Example app: the TaskManagerBot state machine end-to-end on the real engine."""

import asyncio

import pytest

from example.bot import TaskManagerBot

from django_assistant_bot_tpu.ai.providers.echo import EchoProvider
from django_assistant_bot_tpu.bot.domain import BotPlatform, MultiPartAnswer, Update, User
from django_assistant_bot_tpu.bot.services.dialog_service import create_user_message
from django_assistant_bot_tpu.storage import models


class StubPlatform(BotPlatform):
    @property
    def codename(self):
        return "console"

    async def get_update(self, request):
        raise NotImplementedError

    async def post_answer(self, chat_id, answer):
        pass

    async def action_typing(self, chat_id):
        pass


@pytest.fixture()
def bot(tmp_db, monkeypatch):
    bot_model = models.Bot.objects.create(codename="taskmanager")
    user = models.BotUser.objects.create(user_id="u1", platform="console", language="en")
    instance = models.Instance.objects.create(bot=bot_model, user=user)
    dialog = models.Dialog.objects.create(instance=instance)
    return TaskManagerBot(dialog, StubPlatform())


def _send(bot, text, message_id):
    async def turn():
        create_user_message(bot.dialog, message_id, text)
        upd = Update(chat_id="u1", message_id=message_id, text=text, user=User(id="u1"))
        answer = await bot.handle_update(upd)
        if answer is not None:
            await bot.on_answer_sent(answer)  # persist like the answer task does
        return answer

    return asyncio.run(turn())


def test_task_creation_state_machine(bot, monkeypatch):
    import example.bot as example_bot  # noqa: F401 — registers the bot

    scripted = EchoProvider(script=["#create_task"])
    monkeypatch.setattr(
        TaskManagerBot, "_fast_ai", property(lambda self: scripted)
    )

    # intent -> create task -> awaiting title
    answer = _send(bot, "I want to add a task", 1)
    assert "Enter task name" in answer.text
    assert bot.instance.state["awaiting_input"] == "task_title"

    # title input -> priority keyboard
    answer = _send(bot, "Ship the TPU framework", 2)
    assert "Priority" in answer.text
    assert any("/priority high" in b.callback_data for row in answer.buttons for b in row)

    # priority command -> confirm
    answer = _send(bot, "/priority high", 3)
    assert "Confirm task creation" in answer.text

    # confirm -> MultiPartAnswer + task stored in instance state
    answer = _send(bot, "/confirm_task", 4)
    assert isinstance(answer, MultiPartAnswer)
    assert "created" in answer.parts[0].text
    state = models.Instance.objects.get(id=bot.instance.id).state
    assert state["tasks"] == [{"title": "Ship the TPU framework", "priority": "high"}]

    # /list renders the stored task
    answer = _send(bot, "/list", 5)
    assert "Ship the TPU framework" in answer.text
    assert "🔴" in answer.text


def test_cancel_resets_state(bot, monkeypatch):
    scripted = EchoProvider(script=["#create_task"])
    monkeypatch.setattr(TaskManagerBot, "_fast_ai", property(lambda self: scripted))
    _send(bot, "new task please", 1)
    assert bot.instance.state["awaiting_input"] == "task_title"
    answer = _send(bot, "/cancel", 2)
    assert "cancelled" in answer.text.lower()
    assert not bot.instance.state["awaiting_input"]


def test_custom_commands_do_not_leak_to_base(bot):
    patterns = [p.pattern for p, _ in TaskManagerBot._command_handlers]
    assert r"/priority (high|medium|low)" in patterns
    from django_assistant_bot_tpu.bot.assistant_bot import AssistantBot

    base_patterns = [p.pattern for p, _ in AssistantBot._command_handlers]
    assert r"/priority (high|medium|low)" not in base_patterns


def test_start_and_help(bot):
    answer = _send(bot, "/start", 1)
    assert "task manager bot" in answer.text
    answer = _send(bot, "/help", 2)
    assert "/new_task" in answer.text


def test_example_resources_language_fallback(monkeypatch):
    """The shipped example resources exercise the full ResourceManager fallback
    chain (reference: example/bot/resources/task_manager/phrases/ru.json +
    assistant/bot/resource_manager.py:32-57)."""
    import example.settings as example_settings

    from django_assistant_bot_tpu.bot.resource_manager import ResourceManager
    from django_assistant_bot_tpu.conf import settings

    with settings.override(RESOURCES_DIR=example_settings.RESOURCES_DIR):
        # language present: en phrases served directly
        rm = ResourceManager("taskmanager", "en")
        assert rm.get_phrase("Continue") == "Continue"
        # phrase absent from en.json -> falls through to the default (ru) file
        assert (
            rm.get_phrase("`An error occurred while generating the response.`")
            == "`Произошла ошибка при формировании ответа.`"
        )
        # language with no phrase file at all -> default (ru) file
        rm_de = ResourceManager("taskmanager", "de")
        assert rm_de.get_phrase("Continue") == "Продолжить"
        # unknown phrase everywhere -> literal key (reference :57)
        assert rm_de.get_phrase("No such phrase") == "No such phrase"
        # messages fall back too: de has no messages/ dir, default_language=en
        rm_msg = ResourceManager("taskmanager", "de", default_language="en")
        assert "test message" in rm_msg.get_message("TestMessage.txt")
        # BOT_DEFAULT_LANGUAGE setting drives the implicit default
        with settings.override(BOT_DEFAULT_LANGUAGE="en"):
            rm_cfg = ResourceManager("taskmanager", "de")
            assert rm_cfg.default_language == "en"
            assert rm_cfg.get_phrase("Continue") == "Continue"


def test_example_bot_serves_continue_phrase(bot, monkeypatch):
    """End-to-end: a length-limited answer renders the Continue button through
    the example phrase files (ru user -> Продолжить)."""
    import example.settings as example_settings

    from django_assistant_bot_tpu.bot.resource_manager import ResourceManager
    from django_assistant_bot_tpu.conf import settings

    models.BotUser.objects.filter(user_id="u1").update(language="ru")
    bot.bot_user.language = "ru"
    with settings.override(RESOURCES_DIR=example_settings.RESOURCES_DIR):
        rm = ResourceManager(bot.bot.codename, bot.bot_user.language)
        assert rm.get_phrase("Continue") == "Продолжить"
