"""True continuous batching (ROADMAP item 2): piggybacked chunked prefill,
spec x fused unification, and fp8 in-dot attention.

The non-negotiable property is BIT-IDENTICAL output with piggybacked prefill
on vs off — folding a prefill chunk into the fused decode tick may only change
when decode tokens are dispatched, never which tokens come out.  The tests
crank :meth:`GenerationEngine._loop_iteration` directly (no engine thread) so
the admission/tick interleaving — and therefore the rng stream — is identical
across the A/B engines by construction.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.ops.attention import (
    chunked_gqa_decode_attention,
    paged_gqa_decode_attention,
)
from django_assistant_bot_tpu.ops.quant import quantize_decoder_params
from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine

# documented accuracy contract for the fp8 in-dot QK product (docs/QUANT.md):
# max abs attention-output error vs the bf16-dequant reference on unit-scale
# operands.  Measured ~0.05 on CPU; the bound leaves headroom for backend
# accumulation-order drift without ever hiding a broken scale.
FP8_INDOT_MAX_ABS_ERR = 0.15


@pytest.fixture(scope="module")
def tiny():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(7))
    return cfg, params


def _engine(cfg, params, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("prefix_cache_size", 0)
    kw.setdefault("chunk_size", 16)
    kw.setdefault("lookahead", 1)
    return GenerationEngine(cfg, params, ByteTokenizer(), **kw)


def _lockstep(eng):
    """Accept submissions without the engine thread: the test cranks
    ``_loop_iteration`` itself (submit() fast-fails when not 'running')."""
    eng._running = True
    return eng


def _crank(eng, futs, iters=600):
    """Drive the engine loop body deterministically until ``futs`` resolve."""
    for _ in range(iters):
        if all(f.done() for f in futs):
            return
        eng._loop_iteration()
    raise AssertionError("requests did not finish within the crank budget")


# -------------------------------------------------- piggyback bit-identity
LONG_PROMPT = list(range(1, 41))  # 40 ids > chunk_size=16 -> 3 prefill chunks


def _ab_run(cfg, params, piggyback, **kw):
    """Two ragged resident slots (one greedy, one sampled) decode while a
    40-token prompt admits through chunked prefill; returns every request's
    token ids plus the decode-path gauges."""
    eng = _lockstep(
        _engine(cfg, params, prefill_piggyback=piggyback, decode_steps=2, **kw)
    )
    futs = [
        eng.submit(list(range(3, 12)), max_tokens=20, temperature=0.0),
        eng.submit(list(range(5, 10)), max_tokens=18, temperature=0.8),
    ]
    for _ in range(3):  # fixed crank count: identical rng stream across A/B
        eng._loop_iteration()
    futs.append(eng.submit(LONG_PROMPT, max_tokens=6, temperature=0.7))
    _crank(eng, futs)
    out = [f.result(timeout=10).token_ids for f in futs]
    dec = eng.decode_path_stats()
    eng.stop(drain_timeout_s=10.0)
    return out, dec


@pytest.mark.parametrize(
    "kw",
    [
        {"kv_layout": "paged"},
        {"kv_layout": "legacy"},
        {"kv_layout": "paged", "quantize": "int8", "kv_cache_dtype": "fp8"},
        {"kv_layout": "paged", "quantize": "int4"},
    ],
    ids=["paged", "legacy", "paged-int8-fp8kv", "paged-int4"],
)
def test_piggybacked_prefill_bit_identical_to_sequential(tiny, kw):
    """Greedy AND sampled outputs must match bit-for-bit with the chunk
    folded into the decode tick vs the sequential chunk-then-tick path,
    across layouts and weight/KV formats — and the gauges must prove each
    path actually ran (piggybacked chunks on, displaced ticks off)."""
    cfg, params = tiny
    kw = dict(kw)
    q = kw.pop("quantize", None)
    if q:
        params = quantize_decoder_params(params, fmt=q)
    on, dec_on = _ab_run(cfg, params, True, **kw)
    off, dec_off = _ab_run(cfg, params, False, **kw)
    assert on == off
    assert dec_on["prefill_piggyback"] is True
    assert dec_on["prefill_chunks_piggybacked"] >= 2  # all but the final chunk
    assert dec_off["prefill_piggyback"] is False
    assert dec_off["prefill_chunks_piggybacked"] == 0
    # the sequential path displaced decode ticks; the piggybacked one
    # displaced strictly fewer (only the final, activation-feeding chunk)
    assert dec_off["prefill_displacement_frac"] > dec_on["prefill_displacement_frac"]


def test_piggyback_gauges_and_knob_defaults(tiny):
    cfg, params = tiny
    eng = _engine(cfg, params)
    assert eng._piggyback_tick is not None  # default-on
    dec = eng.decode_path_stats()
    assert dec["prefill_piggyback"] is True
    assert dec["prefill_chunks_piggybacked"] == 0
    assert dec["prefill_displacement_frac"] == 0.0
    assert dec["attn_fp8"] is False
    eng.stop(drain_timeout_s=5.0)
    # speculative engines never piggyback (the spec tick has its own shape)
    eng2 = _engine(cfg, params, speculative=3, spec_width=2)
    assert eng2._piggyback_tick is None
    eng2.stop(drain_timeout_s=5.0)


# ----------------------------------------------------- scheduler charging
def test_prefill_chunks_charged_to_service_model(tiny):
    """note_service must charge chunked-prefill dispatches as service units:
    an identical decode workload admitted through 3 prefill chunks must be
    charged exactly 3 more tokens than its single-shot-prefill twin —
    otherwise long-prompt traffic skews predicted queue waits optimistic."""
    from django_assistant_bot_tpu.serving.scheduler import (
        RequestScheduler,
        SchedulerConfig,
    )

    cfg, params = tiny

    def _charge(prompt):
        sched = RequestScheduler(SchedulerConfig())
        calls = []
        orig = sched.note_service
        sched.note_service = lambda seconds, tokens=0: (
            calls.append(tokens),
            orig(seconds, tokens),
        )[1]
        eng = _lockstep(_engine(cfg, params, scheduler=sched, decode_steps=1))
        fut = eng.submit(prompt, max_tokens=2, temperature=0.0)
        _crank(eng, [fut])
        fut.result(timeout=10)
        eng.stop(drain_timeout_s=10.0)
        assert len(calls) == 1
        return calls[0]

    short = _charge(list(range(1, 11)))  # 10 ids <= chunk_size: one prefill
    long_ = _charge(LONG_PROMPT)  # 3 chunks
    assert long_ == short + 3


# ------------------------------------------------------------ spec x fused
@pytest.mark.parametrize("steps", [2, 4])
def test_spec_fused_greedy_identity(tiny, steps):
    """decode_steps composes with speculation: N scanned verify passes per
    dispatch must still produce BIT-IDENTICAL greedy output to the plain
    engine, and the draft/accept counters must prove the fast path ran."""
    cfg, params = tiny
    tok = ByteTokenizer()
    jobs = [
        (tok.encode("ab ab ab ab ab ab"), 20, 0.0),
        (tok.encode("the cat sat on the cat sat on"), 16, 0.0),
        (tok.encode("xyz"), 8, 0.0),
    ]

    def run(**kw):
        eng = _engine(cfg, params, chunk_size=64, **kw).start()
        try:
            futs = [
                eng.submit(ids, max_tokens=mt, temperature=t)
                for ids, mt, t in jobs
            ]
            out = [f.result(timeout=600).token_ids for f in futs]
            stats = eng.tick_stats()
        finally:
            eng.stop(drain_timeout_s=60.0)
        return out, stats

    plain, _ = run(decode_steps=steps)
    spec, stats = run(
        decode_steps=steps, speculative=3, spec_width=2, spec_probe_every=1
    )
    assert spec == plain
    assert stats["spec_drafted"] > 0
    assert stats["decode_steps"] == steps


def test_spec_default_verify_depth_is_one(tiny):
    """Removing the old mutual exclusion must NOT silently multiply existing
    speculative deployments: without an explicit decode_steps a spec engine
    runs ONE verify pass per tick (burst is not inherited)."""
    cfg, params = tiny
    eng = _engine(cfg, params, burst=8, speculative=3, spec_width=2)
    assert eng.burst == 1
    eng.stop(drain_timeout_s=5.0)
    eng2 = _engine(cfg, params, decode_steps=2, speculative=3, spec_width=2)
    assert eng2.burst == 2
    eng2.stop(drain_timeout_s=5.0)


# ------------------------------------------------------------- fp8 in-dot
def _fp8_operands(seed=0, B=2, H=4, KH=2, S=64, D=16):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, KH, S, D)) * 0.5, jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KH, S, D)) * 0.5, jnp.float32)
    k8 = k.astype(jnp.float8_e4m3fn)
    v8 = v.astype(jnp.float8_e4m3fn)
    positions = jnp.asarray([S - 1, S // 3], jnp.int32)
    return q, k8, v8, positions


def test_fp8_indot_chunked_within_bound():
    q, k8, v8, positions = _fp8_operands()
    ref = chunked_gqa_decode_attention(q, k8, v8, positions, chunk=16)
    got = chunked_gqa_decode_attention(
        q, k8, v8, positions, chunk=16, fp8_dot=True
    )
    err = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32)))
    )
    assert 0.0 < err < FP8_INDOT_MAX_ABS_ERR, err


def test_fp8_indot_paged_within_bound():
    q, k8, v8, positions = _fp8_operands()
    B, KH, S, D = k8.shape
    page = 16
    nb = S // page
    # pool mirroring the contiguous cache: page j of row b at index b*nb+j
    k_pool = jnp.asarray(
        np.asarray(k8.astype(jnp.float32))
        .reshape(B, KH, nb, page, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B * nb, KH, page, D)
    ).astype(jnp.float8_e4m3fn)
    v_pool = jnp.asarray(
        np.asarray(v8.astype(jnp.float32))
        .reshape(B, KH, nb, page, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B * nb, KH, page, D)
    ).astype(jnp.float8_e4m3fn)
    bt = jnp.asarray(
        [[b * nb + j for j in range(nb)] for b in range(B)], jnp.int32
    )
    ref = paged_gqa_decode_attention(q, k_pool, v_pool, bt, positions)
    got = paged_gqa_decode_attention(
        q, k_pool, v_pool, bt, positions, fp8_dot=True
    )
    err = float(
        jnp.max(jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32)))
    )
    assert 0.0 < err < FP8_INDOT_MAX_ABS_ERR, err


def test_fp8_indot_rejects_non_fp8_kv():
    q, k8, v8, positions = _fp8_operands()
    with pytest.raises(ValueError, match="fp8"):
        chunked_gqa_decode_attention(
            q,
            k8.astype(jnp.bfloat16),
            v8.astype(jnp.bfloat16),
            positions,
            chunk=16,
            fp8_dot=True,
        )


def test_attn_fp8_engine_knob_validation(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="fp8"):
        _engine(cfg, params, attn_fp8=True)  # no fp8 KV cache
    from django_assistant_bot_tpu.serving.registry import ModelSpec

    with pytest.raises(ValueError, match="attn_fp8"):
        from django_assistant_bot_tpu.serving.registry import ModelRegistry

        ModelRegistry(
            specs={
                "m": ModelSpec(
                    name="m", kind="decoder", tiny=True, attn_fp8=True
                )
            }
        )


def test_attn_fp8_engine_end_to_end(tiny):
    """An fp8-in-dot engine serves a mixed batch and reports the knob; the
    lossy path must still be deterministic with itself (same seed, same
    lockstep crank -> same ids)."""
    cfg, params = tiny

    def run():
        eng = _lockstep(_engine(cfg, params, kv_cache_dtype="fp8", attn_fp8=True))
        futs = [
            eng.submit(list(range(2, 14)), max_tokens=12, temperature=0.0),
            eng.submit(LONG_PROMPT, max_tokens=6, temperature=0.9),
        ]
        _crank(eng, futs)
        out = [f.result(timeout=10).token_ids for f in futs]
        dec = eng.decode_path_stats()
        eng.stop(drain_timeout_s=10.0)
        return out, dec

    a, dec = run()
    b, _ = run()
    assert a == b
    assert dec["attn_fp8"] is True
    assert all(len(ids) >= 1 for ids in a)


# ------------------------------------------------------------------- chaos
def test_tick_raise_mid_piggyback_restart_leaves_page_pool_clean(tiny):
    """An engine-fatal fault fired inside a piggybacked dispatch (prefill
    chunk + decode tick in one program): crash-only restart must reset the
    page plane, salvage the token-less mid-prefill request, and fail the
    mid-decode one cleanly."""
    from django_assistant_bot_tpu.serving.faults import FaultInjected, FaultInjector

    cfg, params = tiny
    inj = FaultInjector({})
    eng = _lockstep(_engine(cfg, params, decode_steps=2, faults=inj, max_slots=2))
    assert eng.paged
    f0 = eng.submit(list(range(3, 12)), max_tokens=40, temperature=0.0)
    for _ in range(5):
        eng._loop_iteration()
    assert eng.num_active == 1
    f1 = eng.submit(LONG_PROMPT, max_tokens=4, temperature=0.0)
    for _ in range(50):
        st = eng._chunking
        if st is not None and st.step < len(st.starts) - 1:
            break  # mid-chunked-prefill with piggybacked steps remaining
        eng._loop_iteration()
    assert eng._chunking is not None
    assert eng._prefill_chunks_piggybacked >= 1
    inj.arm("tick_raise")
    # the next iteration's dispatch IS the piggybacked one — supervise it the
    # way _loop does (crash-only restart), minus the backoff sleep
    with pytest.raises(FaultInjected) as ei:
        eng._loop_iteration()
    with eng._iter_lock:
        eng._restart(ei.value)
    assert eng.engine_restarts == 1
    # pool clean immediately after the restart: every page freed, every
    # block table unallocated, no chunked-prefill state left behind
    assert eng._chunking is None
    assert all(not pages for pages in eng._slot_pages)
    kv = eng.kv_stats()
    assert kv["kv_pages_used"] == 0
    assert kv["kv_pages_free"] == eng._kv_pool.n_pages
    # token-less mid-prefill request was salvaged: it must complete on the
    # rebuilt pool; the mid-decode one fails cleanly with the fault
    for _ in range(600):
        if f0.done() and f1.done():
            break
        eng._loop_iteration()
    assert f1.result(timeout=10).token_ids
    with pytest.raises(Exception):
        f0.result(timeout=10)
    kv = eng.kv_stats()
    assert kv["kv_pages_used"] == 0
    eng.stop(drain_timeout_s=10.0)
