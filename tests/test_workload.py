"""Workload scenario engine (workload/generator.py; docs/AUTOSCALING.md):
seeded determinism (same seed -> byte-identical trace), the production
traffic shapes (diurnal / ramp / burst / constant, hot tenants, chat vs
long-context mixtures), JSONL trace round-trips, and clock-injectable
replay.  Everything here is pure host code — no device, no sleeps."""

import math

import pytest

from django_assistant_bot_tpu.workload import (
    WorkloadConfig,
    WorkloadGenerator,
    WorkloadRequest,
    load_trace,
    prompt_ids_for,
    replay,
    save_trace,
)


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, dt: float) -> None:
        self.t += dt


# --------------------------------------------------------------- determinism
def test_same_seed_identical_trace():
    cfg = WorkloadConfig(seed=7, duration_s=30, base_rps=4, shape="diurnal",
                         diurnal_period_s=30)
    a = WorkloadGenerator(cfg).generate()
    b = WorkloadGenerator(cfg).generate()
    assert a and a == b  # full structural equality, not just lengths
    c = WorkloadGenerator(WorkloadConfig(**{**cfg.__dict__, "seed": 8})).generate()
    assert a != c  # and the seed actually matters


def test_trace_timestamps_sorted_and_bounded():
    cfg = WorkloadConfig(seed=1, duration_s=12, base_rps=6, shape="burst",
                         burst_every_s=4, burst_len_s=1, burst_rps=20)
    ev = WorkloadGenerator(cfg).generate()
    assert all(0 <= e.t_s < cfg.duration_s for e in ev)
    assert all(a.t_s <= b.t_s for a, b in zip(ev, ev[1:]))


# --------------------------------------------------------------- the shapes
def test_diurnal_peak_denser_than_trough():
    cfg = WorkloadConfig(seed=3, duration_s=60, base_rps=8, shape="diurnal",
                         diurnal_period_s=60, diurnal_min_frac=0.1)
    g = WorkloadGenerator(cfg)
    # envelope: trough at the edges, peak at period/2
    assert g.rate_at(0.0) == pytest.approx(0.8, rel=1e-6)
    assert g.rate_at(30.0) == pytest.approx(8.0, rel=1e-6)
    ev = g.generate()
    trough = sum(1 for e in ev if e.t_s < 10 or e.t_s > 50)
    peak = sum(1 for e in ev if 20 <= e.t_s <= 40)
    assert peak > 2 * trough


def test_burst_windows_denser_than_base():
    cfg = WorkloadConfig(seed=5, duration_s=40, base_rps=2, shape="burst",
                         burst_every_s=10, burst_len_s=2, burst_rps=30)
    ev = WorkloadGenerator(cfg).generate()
    in_burst = sum(1 for e in ev if (e.t_s % 10) < 2)
    out_burst = len(ev) - in_burst
    # burst windows are 20% of the time but carry most of the traffic
    assert in_burst > out_burst


def test_ramp_monotonic_envelope():
    cfg = WorkloadConfig(seed=2, duration_s=20, base_rps=1, shape="ramp",
                         ramp_to_rps=9)
    g = WorkloadGenerator(cfg)
    rates = [g.rate_at(t) for t in (0, 5, 10, 15, 20)]
    assert rates == sorted(rates)
    ev = g.generate()
    first_half = sum(1 for e in ev if e.t_s < 10)
    assert len(ev) - first_half > first_half


def test_hot_tenant_and_mixture_fractions():
    cfg = WorkloadConfig(seed=11, duration_s=200, base_rps=10,
                         shape="constant", tenants=5, hot_tenant_frac=0.6,
                         background_frac=0.2, longctx_frac=0.25)
    ev = WorkloadGenerator(cfg).generate()
    n = len(ev)
    hot = sum(1 for e in ev if e.tenant == "tenant0") / n
    bg = sum(1 for e in ev if e.priority == "background") / n
    lc = sum(1 for e in ev if e.kind == "longctx") / n
    assert math.isclose(hot, 0.6, abs_tol=0.05)
    assert math.isclose(bg, 0.2, abs_tol=0.05)
    assert math.isclose(lc, 0.25, abs_tol=0.05)
    # long-context requests draw from the long token regime, chat from its own
    for e in ev:
        lo, hi = (cfg.longctx_prompt_tokens if e.kind == "longctx"
                  else cfg.chat_prompt_tokens)
        assert lo <= e.prompt_tokens <= hi


def test_config_validation_rejects_nonsense():
    with pytest.raises(ValueError, match="shape"):
        WorkloadGenerator(WorkloadConfig(shape="sinusoid"))
    with pytest.raises(ValueError, match="duration"):
        WorkloadGenerator(WorkloadConfig(duration_s=0))
    with pytest.raises(ValueError, match="hot_tenant_frac"):
        WorkloadGenerator(WorkloadConfig(hot_tenant_frac=1.5))


# ------------------------------------------------------------------- prompts
def test_prompt_ids_share_prefix_and_are_deterministic():
    a = WorkloadRequest(t_s=0.0, prompt_tokens=32, prefix_len=16, seed=42)
    b = WorkloadRequest(t_s=1.0, prompt_tokens=24, prefix_len=16, seed=43)
    ids_a, ids_b = prompt_ids_for(a), prompt_ids_for(b)
    assert ids_a == prompt_ids_for(a)  # same request -> same ids
    assert ids_a[:16] == ids_b[:16]  # shared prefix really is shared
    assert ids_a[16:] != ids_b[16:]  # bodies differ by seed
    assert len(ids_a) == 32
    assert all(1 <= t <= 255 for t in ids_a)  # byte-tokenizer-safe


# --------------------------------------------------------------------- JSONL
def test_jsonl_round_trip_identity(tmp_path):
    cfg = WorkloadConfig(seed=9, duration_s=15, base_rps=5, shape="diurnal",
                         diurnal_period_s=15)
    ev = WorkloadGenerator(cfg).generate()
    path = str(tmp_path / "trace.jsonl")
    assert save_trace(ev, path) == len(ev)
    assert load_trace(path) == ev


def test_jsonl_rejects_malformed_lines(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as fh:
        fh.write('{"t_s": 1.0}\nnot json\n')
    with pytest.raises(ValueError, match="bad.jsonl:2"):
        load_trace(path)


# -------------------------------------------------------------------- replay
def test_replay_paces_by_trace_time_and_speed():
    ev = [WorkloadRequest(t_s=t) for t in (0.0, 1.0, 3.0)]
    clock = _FakeClock()
    seen = []
    replay(ev, lambda e: seen.append((e.t_s, clock.t)),
           clock=clock, sleep=clock.sleep, speed=2.0)
    # each submit fires at trace-time / speed on the injected clock
    assert seen == [(0.0, 0.0), (1.0, 0.5), (3.0, 1.5)]


def test_replay_catches_submit_exceptions_as_outcomes():
    ev = [WorkloadRequest(t_s=0.0), WorkloadRequest(t_s=0.1)]
    clock = _FakeClock()

    def submit(e):
        if e.t_s == 0.0:
            raise RuntimeError("shed")
        return "ok"

    out = replay(ev, submit, clock=clock, sleep=clock.sleep)
    assert isinstance(out[0], RuntimeError) and out[1] == "ok"


def test_replay_honors_stop_predicate():
    ev = [WorkloadRequest(t_s=float(i)) for i in range(10)]
    clock = _FakeClock()
    n = []
    out = replay(ev, lambda e: n.append(1), clock=clock, sleep=clock.sleep,
                 stop=lambda: len(n) >= 3)
    assert len(out) == 3


# ------------------------------------------------- session-shaped traffic
def _session_cfg(**kw):
    kw.setdefault("seed", 11)
    kw.setdefault("duration_s", 120.0)
    kw.setdefault("base_rps", 0.0)  # sessions only, unless a test adds load
    kw.setdefault("sessions", 8)
    return WorkloadConfig(**kw)


def _by_session(trace):
    out = {}
    for e in trace:
        if e.kind == "session":
            out.setdefault(e.session, []).append(e)
    for evs in out.values():
        evs.sort(key=lambda e: e.turn)
    return out


def test_session_trace_deterministic_and_sorted():
    a = WorkloadGenerator(_session_cfg()).generate()
    b = WorkloadGenerator(_session_cfg()).generate()
    assert [e.to_dict() for e in a] == [e.to_dict() for e in b]
    assert a, "sessions must produce turns"
    assert all(a[i].t_s <= a[i + 1].t_s for i in range(len(a) - 1))


def test_session_turns_extend_previous_prompt_exactly():
    """The tiered-KV trace contract: turn k's prompt ids literally extend
    turn k-1's, and turn k declares the previous turn's FULL prompt as its
    cacheable prefix — the longest-match shape the prefix registry and the
    host tier restore serve."""
    trace = WorkloadGenerator(_session_cfg()).generate()
    sessions = _by_session(trace)
    assert sessions
    multi = [evs for evs in sessions.values() if len(evs) > 1]
    assert multi, "at least one session must have several turns"
    for evs in sessions.values():
        prev_ids = None
        for e in evs:
            ids = prompt_ids_for(e)
            assert len(ids) == e.prompt_tokens
            if prev_ids is None:
                # the opening turn declares its whole system prompt shareable
                assert e.prefix_len == e.prompt_tokens
            else:
                assert ids[: len(prev_ids)] == prev_ids
                assert e.prefix_len == len(prev_ids)
            prev_ids = ids


def test_session_think_times_within_config_range():
    cfg = _session_cfg(session_think_s=(2.0, 5.0), session_turns=(3, 3),
                       duration_s=1000.0)
    trace = WorkloadGenerator(cfg).generate()
    for evs in _by_session(trace).values():
        for a, b in zip(evs, evs[1:]):
            assert 2.0 <= b.t_s - a.t_s <= 5.0 + 1e-9


def test_session_trace_jsonl_round_trip(tmp_path):
    trace = WorkloadGenerator(_session_cfg(base_rps=1.0)).generate()
    path = str(tmp_path / "sessions.jsonl")
    save_trace(trace, path)
    loaded = load_trace(path)
    assert [e.to_dict() for e in loaded] == [e.to_dict() for e in trace]
    # session fields survive; non-session lines stay field-compatible
    kinds = {e.kind for e in loaded}
    assert "session" in kinds


def test_session_config_validation():
    with pytest.raises(ValueError):
        WorkloadConfig(sessions=-1).validate()
    with pytest.raises(ValueError):
        WorkloadConfig(sessions=2, session_think_s=(-1.0, 2.0)).validate()
    with pytest.raises(ValueError):
        WorkloadConfig(sessions=2, session_turns=(0, 2)).validate()
    with pytest.raises(ValueError):
        WorkloadConfig(sessions=1, session_start_frac=0.0).validate()
