"""AI plane: factory routing, JSON repair, tagged-text extraction, cost table,
retry combinators, language detection, TPU provider end-to-end on tiny models."""

import asyncio

import numpy as np
import pytest

from django_assistant_bot_tpu.ai import (
    AIDialog,
    AIResponse,
    calculate_ai_cost,
    extract_tagged_text,
    get_ai_embedder,
    get_ai_provider,
)
from django_assistant_bot_tpu.ai.providers.base import parse_json_response
from django_assistant_bot_tpu.ai.providers.echo import EchoProvider, HashEmbedder
from django_assistant_bot_tpu.ai.providers.ollama import merge_same_roles
from django_assistant_bot_tpu.utils import get_language, repeat_until, truncate_text
from django_assistant_bot_tpu.utils.repeat_until import RepeatUntilError


def test_factory_prefix_routing():
    from django_assistant_bot_tpu.ai.providers.http_service import (
        GPUServiceEmbedder,
        GPUServiceProvider,
    )
    from django_assistant_bot_tpu.ai.providers.openai_api import (
        ChatGPTAIProvider,
        GroqAIProvider,
        OpenAIEmbedder,
    )
    from django_assistant_bot_tpu.ai.providers.ollama import OllamaAIProvider, OllamaEmbedder

    assert isinstance(get_ai_provider("groq:llama3-70b"), GroqAIProvider)
    assert isinstance(get_ai_provider("gpu_service:x"), GPUServiceProvider)
    assert isinstance(get_ai_provider("ollama:mistral"), OllamaAIProvider)
    assert isinstance(get_ai_provider("llama3.1:8b"), OllamaAIProvider)
    assert isinstance(get_ai_provider("gpt-4o"), ChatGPTAIProvider)
    assert isinstance(get_ai_provider("test"), EchoProvider)
    assert isinstance(get_ai_embedder("text-embedding-3-small"), OpenAIEmbedder)
    assert isinstance(get_ai_embedder("gpu_service:rubert"), GPUServiceEmbedder)
    assert isinstance(get_ai_embedder("nomic-embed-text"), OllamaEmbedder)
    assert isinstance(get_ai_embedder("test"), HashEmbedder)


def test_parse_json_response_variants():
    assert parse_json_response('{"a": 1}')[0] == {"a": 1}
    assert parse_json_response('```json\n{"a": 1}\n```')[0] == {"a": 1}
    assert parse_json_response('prefix {"a": {"b": 2}} suffix')[0] == {"a": {"b": 2}}
    parsed, err = parse_json_response("not json at all")
    assert parsed is None and "no valid JSON" in err


def test_extract_tagged_text():
    out = extract_tagged_text("#THINK some reasoning #TEXT the answer")
    assert out == {"think": "some reasoning", "text": "the answer"}


def test_calculate_ai_cost():
    assert calculate_ai_cost(
        {"model": "gpt-4o-mini", "prompt_tokens": 1000, "completion_tokens": 1000}
    ) == pytest.approx(0.00075)
    assert calculate_ai_cost({"model": "llama3.1:8b", "prompt_tokens": 10}) == 0.0
    assert calculate_ai_cost({"model": "tpu:tiny", "prompt_tokens": 10}) == 0.0


def test_echo_provider_scripted():
    provider = EchoProvider(script=["first", {"intent": "greet"}])
    r1 = asyncio.run(provider.get_response([{"role": "user", "content": "hi"}]))
    assert r1.result == "first"
    r2 = asyncio.run(
        provider.get_response([{"role": "user", "content": "x"}], json_format=True)
    )
    assert r2.result == {"intent": "greet"}
    r3 = asyncio.run(provider.get_response([{"role": "user", "content": "ping"}]))
    assert r3.result == "echo: ping"


def test_ai_dialog_wraps_provider():
    dialog = AIDialog("test")
    resp = asyncio.run(dialog.prompt("hello"))
    assert isinstance(resp, AIResponse)
    assert resp.result == "echo: hello"
    assert resp.usage["model"] == "test"


def test_hash_embedder_deterministic():
    emb = HashEmbedder(dim=64)
    a1, a2, b = asyncio.run(emb.embeddings(["alpha", "alpha", "beta"]))
    np.testing.assert_array_equal(a1, a2)
    assert not np.allclose(a1, b)
    assert np.linalg.norm(a1) == pytest.approx(1.0, abs=1e-5)


def test_merge_same_roles():
    msgs = [
        {"role": "user", "content": "a"},
        {"role": "user", "content": "b"},
        {"role": "assistant", "content": "c"},
    ]
    merged = merge_same_roles(msgs)
    assert len(merged) == 2
    assert merged[0]["content"] == "a\nb"


def test_repeat_until_retries_then_succeeds():
    calls = []

    async def flaky():
        calls.append(1)
        return "ok" if len(calls) >= 3 else "bad"

    result = asyncio.run(
        repeat_until(flaky, condition=lambda r: r == "ok", max_attempts=5)
    )
    assert result == "ok" and len(calls) == 3

    async def always_bad():
        return "bad"

    with pytest.raises(RepeatUntilError):
        asyncio.run(repeat_until(always_bad, condition=lambda r: r == "ok", max_attempts=2))


def test_language_detection():
    assert get_language("hello world") == "en"
    assert get_language("привет мир") == "ru"
    assert get_language("你好世界") == "zh"
    assert get_language("こんにちは") == "ja"
    assert get_language("今日の天気はどうですか") == "ja"  # kanji-led, kana later
    assert get_language("안녕하세요") == "ko"
    assert get_language("") == "en"


def test_language_detection_latin_profiles():
    """Latin-script languages resolve by function-word/diacritic profiles —
    the round-2 heuristic returned 'en' for ALL of these, selecting the wrong
    phrase resources (reference bar: langid, assistant/utils/language.py:13)."""
    assert get_language("Quel est le temps? Je ne sais pas ce que vous voulez.") == "fr"
    assert get_language("Ich weiß nicht, was sie mit diesem Programm machen.") == "de"
    assert get_language("No sé qué es lo que quieres hacer con este programa.") == "es"
    assert get_language("Non so che cosa vuoi fare con questo programma, ma è bello.") == "it"
    assert get_language("Não sei o que você quer fazer com este programa.") == "pt"
    assert get_language("Ik weet niet wat je met dit programma wilt doen.") == "nl"
    # Ukrainian separates from Russian by its distinct letters
    assert get_language("Я не знаю, що ви хочете зробити з цією програмою.") == "uk"
    # weak evidence stays at the reference default
    assert get_language("ok") == "en"
    assert get_language("12345 !!") == "en"


def test_language_detector_pluggable():
    from django_assistant_bot_tpu.utils.language import set_language_detector

    set_language_detector(lambda text: "xx")
    try:
        assert get_language("anything at all") == "xx"
    finally:
        set_language_detector(None)
    assert get_language("hello world") == "en"


def test_truncate_text():
    assert truncate_text("abcdef", 10) == "abcdef"
    assert truncate_text("abcdefghij", 5) == "abcd…"


@pytest.mark.slow
def test_tpu_provider_tiny_end_to_end():
    """tpu: prefix loads a tiny random decoder and generates through the
    continuous-batching engine — the full in-process serving path."""
    from django_assistant_bot_tpu.ai.providers.tpu import reset_shared_registry

    reset_shared_registry()
    try:
        provider = get_ai_provider("tpu:tiny-chat")
        resp = asyncio.run(
            provider.get_response(
                [{"role": "user", "content": "hello"}], max_tokens=8
            )
        )
        assert isinstance(resp.result, str)
        assert resp.usage["completion_tokens"] >= 1
        assert provider.calculate_tokens("some text") > 0
        assert provider.context_size > 0

        embedder = get_ai_embedder("tpu:tiny-emb")
        vecs = asyncio.run(embedder.embeddings(["a", "b"]))
        assert len(vecs) == 2 and len(vecs[0]) > 0
    finally:
        reset_shared_registry()
