"""Tester harness: randomized personas, simulated dialogs, QA analysis + RICE
report (reference: assistant/bot/management/commands/tester.py:43-453)."""

import argparse
import json
import random

import pytest

from django_assistant_bot_tpu.ai.domain import AIResponse
from django_assistant_bot_tpu.bot.assistant_bot import AssistantBot
from django_assistant_bot_tpu.bot.domain import SingleAnswer
from django_assistant_bot_tpu.cli import tester


def test_generate_persona_randomized_and_reproducible():
    a = tester.generate_persona(random.Random(1))
    b = tester.generate_persona(random.Random(2))
    a2 = tester.generate_persona(random.Random(1))
    assert a == a2  # seeded -> reproducible
    assert a != b  # different seeds -> different profiles
    for dim in tester.TRAITS:
        assert f"- {dim}: " in a


class FakeAIDialog:
    """Stands in for simulator/control/analyzer/improvement models."""

    def __init__(self, model):
        self.model = model

    async def get_response(self, messages, max_tokens=1024, json_format=False):
        if json_format:  # analyzer verdict
            return AIResponse(
                result={"warnings": ["greeting is stiff"], "errors": []},
                usage={"model": self.model},
            )
        system = next((m["content"] for m in messages if m["role"] == "system"), "")
        if '"continue" or "end"' in system:  # control decision
            return AIResponse(result="end", usage={"model": self.model})
        return AIResponse(result="what can you do?", usage={"model": self.model})

    async def prompt(self, context, role="user", **kwargs):  # improvement model
        return AIResponse(result="Soften the greeting text.", usage={"model": self.model})


def _args(out, mode="run", dialogs=2, turns=6):
    return argparse.Namespace(
        bot_codename="tester-bot",
        mode=mode,
        dialogs=dialogs,
        turns=turns,
        model="test",
        out=str(out),
        seed=7,
    )


@pytest.fixture()
def patched(tmp_db, monkeypatch):
    async def fake_answer(self, messages, debug_info, do_interrupt):
        return SingleAnswer(text="bot reply", usage=[{"model": "test"}])

    monkeypatch.setattr(AssistantBot, "get_answer_to_messages", fake_answer)
    monkeypatch.setattr(tester, "AIDialog", FakeAIDialog)


def test_run_and_analyze_end_to_end(patched, tmp_path, capsys):
    out = tmp_path / "td"
    assert tester.run(_args(out)) == 0
    files = sorted(p.name for p in out.glob("dialog_*.json"))
    assert files == ["dialog_1.json", "dialog_2.json"]
    log = json.loads((out / "dialog_1.json").read_text())
    assert "persona" in log[0]
    user_turns = [e for e in log if e.get("role") == "user"]
    assert user_turns[0]["text"] == "/start"
    assert len(user_turns) >= 3  # control fires from turn 3, then says "end"
    assert any(e.get("role") == "assistant" for e in log)
    # personas differ between the two dialogs
    other = json.loads((out / "dialog_2.json").read_text())
    assert log[0]["persona"] != other[0]["persona"]
    # simulated dialogs are cleaned up (reference deletes them too), including
    # the synthetic user/instance rows
    from django_assistant_bot_tpu.storage import models

    assert models.Dialog.objects.count() == 0
    assert models.Instance.objects.count() == 0
    assert models.BotUser.objects.count() == 0

    assert tester.run(_args(out, mode="analyze")) == 0
    captured = capsys.readouterr().out
    assert "greeting is stiff" in captured
    assert "Proposed improvement:" in captured
    assert "Soften the greeting text." in captured
    lines = (out / "analysis_results.jsonl").read_text().strip().splitlines()
    assert len(lines) == 2
    rec = json.loads(lines[0])
    assert rec["warnings"] == ["greeting is stiff"]
    assert rec["crashes"] == 0


def test_crashes_are_captured_and_counted(patched, tmp_path, monkeypatch, capsys):
    async def boom(self, update):
        raise RuntimeError("engine exploded")

    monkeypatch.setattr(AssistantBot, "handle_update", boom)
    out = tmp_path / "td"
    assert tester.run(_args(out, dialogs=1, turns=4)) == 0
    log = json.loads((out / "dialog_1.json").read_text())
    crash_entries = [
        e for e in log if e.get("role") == "assistant" and tester.CRASH_MARKER in e["text"]
    ]
    assert crash_entries  # crash captured, dialog not aborted

    class CleanAnalyzer(FakeAIDialog):
        async def get_response(self, messages, max_tokens=1024, json_format=False):
            if json_format:
                return AIResponse(result={"warnings": [], "errors": []}, usage={})
            return await super().get_response(messages, max_tokens, json_format)

    monkeypatch.setattr(tester, "AIDialog", CleanAnalyzer)
    assert tester.run(_args(out, mode="analyze")) == 0
    rec = json.loads(
        (out / "analysis_results.jsonl").read_text().strip().splitlines()[0]
    )
    assert rec["crashes"] >= 1
    assert "crashes" in capsys.readouterr().out


def test_analyze_survives_stubborn_analyzer(patched, tmp_path, monkeypatch, capsys):
    """A dialog whose verdict never validates is recorded as failed; the run
    still completes and writes the other results."""
    out = tmp_path / "td"
    assert tester.run(_args(out, dialogs=2, turns=4)) == 0

    class BadAnalyzer(FakeAIDialog):
        async def get_response(self, messages, max_tokens=1024, json_format=False):
            if json_format:
                return AIResponse(result="not json at all", usage={})
            return await super().get_response(messages, max_tokens, json_format)

    monkeypatch.setattr(tester, "AIDialog", BadAnalyzer)
    assert tester.run(_args(out, mode="analyze")) == 0
    lines = (out / "analysis_results.jsonl").read_text().strip().splitlines()
    assert len(lines) == 2
    assert all(json.loads(l)["analysis_failed"] for l in lines)
