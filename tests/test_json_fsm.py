"""Grammar-constrained JSON decoding (SURVEY §7 hard part (d)).

The reference's JSON strategy is provider-side retry + repair
(assistant/ai/providers/ollama.py:49-107).  Here the decode tick itself masks
sampling through a JSON token-FSM, so every constrained generation parses —
asserted below at temperature 0.8 on a random-weights model, which without the
mask emits JSON approximately never.
"""

import json

import pytest

import jax

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.ops.json_fsm import (
    build_char_dfa,
    fsm_for_tokenizer,
)
from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine


def run_chars(dfa, text: str):
    state = dfa.initial
    for b in text.encode("utf-8"):
        state = int(dfa.table[state, b])
        if state == dfa.dead:
            return None
    return state


@pytest.mark.parametrize(
    "text",
    [
        '{}',
        '{"a": 1}',
        '{"a": -0.5e+3, "b": [true, false, null]}',
        '{"nested": {"x": [1, 2, {"y": "z"}]}}',
        '  {"ws" :\n[ 1 , 2 ]\t}',
        '{"esc": "a\\"b\\\\c\\u00e9", "utf8": "héllo"}',
        '[]',
        '[{"a": []}]',
        '{"num0": 0, "neg": -12.5}',
    ],
)
def test_dfa_accepts_valid_json(text):
    dfa = build_char_dfa(max_depth=4)
    state = run_chars(dfa, text)
    assert state is not None and dfa.accepting[state], text
    json.loads(text)  # sanity: python agrees it is valid


@pytest.mark.parametrize(
    "text",
    [
        '{',          # incomplete (not accepting — prefix is alive though)
        '{"a" 1}',    # missing colon
        '{"a": 1,}',  # trailing comma
        '{"a": 01}',  # leading zero
        '[1, ]',      # trailing comma in array
        '{"a": tru}', # bad literal — dead before completion
        '"bare"',     # top level must be object/array
        '{"a": 1}}',  # extra close
        "{'a': 1}",   # single quotes
    ],
)
def test_dfa_rejects_invalid_json(text):
    dfa = build_char_dfa(max_depth=4)
    state = run_chars(dfa, text)
    assert state is None or not dfa.accepting[state], text


def test_dfa_depth_limit():
    dfa = build_char_dfa(max_depth=3)
    assert run_chars(dfa, '{"a": {"b": [1]}}') is not None  # depth 3 ok
    assert run_chars(dfa, '{"a": {"b": [[1]]}}') is None  # depth 4 dies


def test_token_fsm_eos_only_when_complete():
    tok = ByteTokenizer()
    fsm = fsm_for_tokenizer(tok)
    # initial state: '{' and '[' and whitespace allowed, EOS not, 'x' not
    assert fsm.allowed[fsm.initial, ord("{")]
    assert fsm.allowed[fsm.initial, ord(" ")]
    assert not fsm.allowed[fsm.initial, tok.eos_id]
    assert not fsm.allowed[fsm.initial, ord("x")]
    # walk '{}' -> accepting -> only EOS allowed
    s = fsm.next_state[fsm.initial, ord("{")]
    s = fsm.next_state[s, ord("}")]
    assert fsm.accepting[s]
    assert fsm.allowed[s, tok.eos_id]
    assert fsm.allowed[s].sum() == 1


def test_hf_token_bytes_preserve_leading_space():
    """decode([i]) alone strips the SentencePiece leading-space marker; the
    anchor-prefix rendering must recover the true ' true' bytes, otherwise the
    FSM believes '1' + '▁2' yields '12' when the stream is really '1 2'."""
    from tokenizers import Tokenizer
    from tokenizers.decoders import Metaspace as DecMeta
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Metaspace as PreMeta
    from transformers import PreTrainedTokenizerFast

    from django_assistant_bot_tpu.ops.json_fsm import token_bytes_for
    from django_assistant_bot_tpu.serving.tokenizer import HFTokenizer

    vocab = {
        "<unk>": 0, "<s>": 1, "</s>": 2,
        "▁true": 3, "▁:": 4, "{": 5, "}": 6,
    }
    t = Tokenizer(WordLevel(vocab, unk_token="<unk>"))
    t.pre_tokenizer = PreMeta()
    t.decoder = DecMeta()
    hf = PreTrainedTokenizerFast(
        tokenizer_object=t, unk_token="<unk>", bos_token="<s>", eos_token="</s>"
    )
    wrapped = HFTokenizer(hf)
    assert wrapped.vocab_size == len(vocab)
    # the naive rendering loses the space; the anchor rendering must not
    assert hf.decode([3]) == "true"
    tb = token_bytes_for(wrapped)
    assert tb[3] == b" true"
    assert tb[wrapped.eos_id] == b""


@pytest.mark.slow
def test_engine_json_mode_always_parses_at_high_temperature():
    """20 constrained generations at temperature 0.8 on random weights: every
    output parses; unconstrained, none of them do (sanity of the premise)."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(11))
    tok = ByteTokenizer()
    eng = GenerationEngine(cfg, params, tok, max_slots=4, max_seq_len=160).start()
    try:
        futs = [
            eng.submit(
                tok.encode(f"reply with json #{i}"),
                max_tokens=96,
                temperature=0.8,
                json_format=True,
            )
            for i in range(20)
        ]
        results = [f.result(timeout=600) for f in futs]
        parsed = 0
        for r in results:
            if not r.length_limited:  # FSM forces EOS exactly at completion
                obj = json.loads(r.text)
                assert isinstance(obj, (dict, list))
                parsed += 1
            else:
                # ran out of budget mid-object — the only allowed failure mode;
                # the text must still be a valid *prefix* (never dead)
                dfa = build_char_dfa(max_depth=4)
                assert run_chars(dfa, r.text) is not None, r.text
        # with 96 tokens of budget the vast majority must complete
        assert parsed >= 15, (parsed, [r.text for r in results])

        # premise check: unconstrained sampling at 0.8 does not produce JSON
        loose = [
            eng.submit(tok.encode("reply with json"), max_tokens=48, temperature=0.8)
            for _ in range(3)
        ]
        bad = 0
        for f in loose:
            try:
                json.loads(f.result(timeout=600).text)
            except Exception:
                bad += 1
        assert bad == 3
    finally:
        eng.stop()


def test_engine_mixed_json_and_plain_batch():
    """JSON-constrained and plain greedy requests share the decode batch; the
    plain request's output is unaffected (token-for-token vs solo run)."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(12))
    tok = ByteTokenizer()
    eng = GenerationEngine(cfg, params, tok, max_slots=4, max_seq_len=128).start()
    try:
        solo = eng.submit(tok.encode("plain"), max_tokens=8, temperature=0.0).result(
            timeout=600
        )
        futs = [
            eng.submit(tok.encode("plain"), max_tokens=8, temperature=0.0),
            eng.submit(
                tok.encode("json"), max_tokens=64, temperature=0.5, json_format=True
            ),
        ]
        plain, constrained = futs[0].result(timeout=600), futs[1].result(timeout=600)
        assert plain.token_ids == solo.token_ids
        if not constrained.length_limited:
            json.loads(constrained.text)
    finally:
        eng.stop()
