"""Mesh-sliced fleet (parallel/slicing.py + serving/registry.py;
docs/MULTICHIP.md): each replica pinned to its OWN disjoint device slice —
weights placed per-slice from one shared host copy, KV pool and compiled
ticks living only on the slice, scale-up past the last free slice an honest
``no_capacity`` rejection, and slice-pinned decode bit-identical to the
global-mesh engine.

Everything runs on the suite's forced 8-device CPU mesh (tests/conftest.py)
with tiny random models; chaos is exact (armed fault schedules), no
sleep-and-hope.
"""

import time

import pytest

import jax

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.parallel import (
    MeshPlanner,
    NoCapacity,
    best_mesh_shape,
    make_mesh,
    shard_pytree,
)
from django_assistant_bot_tpu.serving import (
    AutoscalerConfig,
    ByteTokenizer,
    GenerationEngine,
    ModelRegistry,
    ModelSpec,
    SLOAutoscaler,
    parse_prometheus_text,
    render_prometheus,
)


def _leaf_device_ids(tree) -> set:
    out = set()
    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            out |= {d.id for d in sharding.device_set}
    return out


# ------------------------------------------------------------------ planner
def test_mesh_planner_partitions_disjoint_slices():
    pl = MeshPlanner(2)
    assert pl.n_slices == 4
    seen = set()
    for sl in pl.slices:
        ids = set(sl.device_ids)
        assert len(ids) == 2
        assert not (ids & seen)  # disjoint
        seen |= ids
        # TP inside the slice: `model` spans the whole slice by default
        assert dict(sl.mesh.shape)["model"] == 2
    assert seen == {d.id for d in jax.devices()}

    # acquire hands out the lowest free slice; exhausting raises NoCapacity
    got = [pl.acquire() for _ in range(4)]
    assert [s.slice_id for s in got] == [0, 1, 2, 3]
    assert pl.free_slices() == 0
    with pytest.raises(NoCapacity) as ei:
        pl.acquire()
    assert ei.value.slices_total == 4
    assert ei.value.replica_devices == 2
    # release is idempotent, and a freed slice is reused lowest-first
    pl.release(got[1])
    pl.release(got[1])
    assert pl.free_slices() == 1
    assert pl.acquire().slice_id == 1
    stats = pl.stats()
    assert stats["slices_total"] == 4 and stats["slices_free"] == 0
    assert stats["slice_axes"]["model"] == 2


def test_mesh_planner_validation_and_leftover_devices():
    with pytest.raises(ValueError):
        MeshPlanner(0)
    with pytest.raises(ValueError):
        MeshPlanner(16)  # more devices per replica than the host has
    # a non-dividing knob leaves devices idle (warned) but still plans
    pl = MeshPlanner(3)
    assert pl.n_slices == 2
    used = set()
    for sl in pl.slices:
        used |= set(sl.device_ids)
    assert len(used) == 6  # 2 of 8 devices unused


def test_registry_rejects_invalid_slicing_specs():
    with pytest.raises(ValueError, match="decoder-only"):
        ModelRegistry(
            {
                "e": ModelSpec(
                    name="e", kind="encoder", tiny=True, replica_devices=2
                )
            }
        )
    with pytest.raises(ValueError, match="replica_devices must be >= 0"):
        ModelRegistry(
            {
                "m": ModelSpec(
                    name="m", kind="decoder", tiny=True, replica_devices=-1
                )
            }
        )
    # more initial replicas than the host has slices is a load-time error,
    # not a surprise at first scale-up
    with pytest.raises(ValueError, match="device slices"):
        ModelRegistry(
            {
                "m": ModelSpec(
                    name="m",
                    kind="decoder",
                    tiny=True,
                    replicas=5,
                    replica_devices=2,
                )
            }
        )
    with pytest.raises(ValueError, match="exceeds"):
        ModelRegistry(
            {
                "m": ModelSpec(
                    name="m",
                    kind="decoder",
                    tiny=True,
                    replica_devices=9,
                )
            }
        )


# ------------------------------------------------------- placement + fleet
def test_sliced_fleet_placement_capacity_and_slice_reuse():
    """The tentpole acceptance walk on one registry: per-slice weight
    placement from the shared host copy, disjoint slices, per-slice HBM
    ledger, add_replica to the last slice, ``no_capacity`` past it (no
    same-chip cache clone), and a detach releasing its slice for reuse."""
    reg = ModelRegistry(
        {
            "m": ModelSpec(
                name="m",
                kind="decoder",
                tiny=True,
                replicas=2,
                max_replicas=4,
                replica_devices=2,
                max_slots=2,
                max_seq_len=64,
                lookahead=0,
                burst=1,
            )
        }
    )
    try:
        r = reg.get_generator("m")
        assert r.mesh_planner is not None
        assert r.mesh_planner.n_slices == 4
        # every replica's weights live ONLY on its own slice, slices disjoint
        slice_ids = []
        seen_devices: set = set()
        for rep in r.replicas:
            eng = rep.engine
            ids = set(eng.slice_devices)
            assert len(ids) == 2
            assert _leaf_device_ids(eng.params) <= ids
            assert _leaf_device_ids(eng._cache) <= ids
            assert not (ids & seen_devices)
            seen_devices |= ids
            slice_ids.append(eng.slice_id)
            sl = eng.slice_stats()
            assert sl["sliced"] is True
            assert sl["hbm_weight_bytes"] > 0
            assert sl["hbm_kv_bytes"] > 0
            assert sl["hbm_bytes"] == (
                sl["hbm_weight_bytes"] + sl["hbm_kv_bytes"]
            )
        assert slice_ids == [0, 1]
        # the fleet serves through the router surface unchanged
        tok = r.tokenizer
        futs = [
            r.submit(tok.encode(f"slice {i}"), max_tokens=4, temperature=0.0)
            for i in range(4)
        ]
        for f in futs:
            assert len(f.result(timeout=120).token_ids) == 4
        # per-slice ledgers are exclusive, so they SUM: fleet footprint ==
        # sum of slices (each replica's weights + pool on its own chips)
        per = [rep.engine.slice_stats()["hbm_bytes"] for rep in r.replicas]
        fleet_bytes = sum(per)
        assert fleet_bytes == pytest.approx(per[0] * len(per))
        # scale to the last free slice
        r.add_replica()
        r.add_replica()
        assert len(r.replicas) == 4
        assert {rep.engine.slice_id for rep in r.replicas} == {0, 1, 2, 3}
        assert r.mesh_planner.free_slices() == 0
        # past the last slice: an honest rejection, fleet size held, and no
        # replica ever lands on an already-pinned slice
        with pytest.raises(NoCapacity):
            r.add_replica()
        assert len(r.replicas) == 4
        rs = r.router_stats()
        assert rs["slices_total"] == 4 and rs["slices_free"] == 0
        assert {p["slice_id"] for p in rs["replicas"]} == {0, 1, 2, 3}
        # detach releases the slice; the next scale-up reuses it
        report = r.remove_replica(3, deadline_s=5.0)
        assert report["slice_id"] == 3
        assert r.mesh_planner.free_slices() == 1
        name = r.add_replica()
        assert name.endswith("r5")  # spawn indices never reuse names
        assert r.replicas[-1].engine.slice_id == 3  # ... but slices recycle
        # /metrics: per-replica slice gauges + fleet slice capacity
        fams = parse_prometheus_text(render_prometheus(reg))
        slice_bytes = fams["dabt_slice_hbm_bytes"]["samples"]
        assert len(slice_bytes) == 4  # one per live replica
        assert all(v > 0 for _, _, v in slice_bytes)
        assert [v for _, _, v in fams["dabt_router_slices_total"]["samples"]] == [4.0]
        assert [v for _, _, v in fams["dabt_router_slices_free"]["samples"]] == [0.0]
        assert sorted(
            v for _, _, v in fams["dabt_slice_id"]["samples"]
        ) == [0.0, 1.0, 2.0, 3.0]
        # fleet healthz surface: planner block + per-replica slice blocks
        ss = r.slice_stats()
        assert ss["planner"]["slices_total"] == 4
        assert {b["slice_id"] for b in ss["replicas"]} == {0, 1, 2, 3}
    finally:
        reg.stop()


def test_failed_replica_spawn_releases_its_slice(monkeypatch):
    """A scale-up whose engine fails to warm/start must NOT leak its slice:
    the half-built replica never joins the fleet (no detach epilogue), so
    the factory itself returns the slice — otherwise every failed spawn
    would shrink hardware capacity for the life of the process."""
    reg = ModelRegistry(
        {
            "m": ModelSpec(
                name="m",
                kind="decoder",
                tiny=True,
                replicas=1,
                max_replicas=4,
                replica_devices=2,
                max_slots=2,
                max_seq_len=64,
            )
        }
    )
    try:
        r = reg.get_generator("m")
        assert r.mesh_planner.free_slices() == 3

        def boom(self):
            raise RuntimeError("spawn failed")

        monkeypatch.setattr(GenerationEngine, "start", boom)
        with pytest.raises(RuntimeError, match="spawn failed"):
            r.add_replica()
        assert len(r.replicas) == 1
        assert r.mesh_planner.free_slices() == 3  # the slice came back
        monkeypatch.undo()
        r.add_replica()  # ... and is usable again
        assert len(r.replicas) == 2
        assert r.mesh_planner.free_slices() == 2
    finally:
        reg.stop()


def test_slice_pinned_engine_bit_identical_to_global_mesh():
    """Acceptance: greedy decode on a slice-pinned TP-2 engine is
    bit-identical to the same weights served on the 8-device global mesh —
    slicing changes placement, never output."""
    cfg = DecoderConfig.tiny()
    host = llama.init(cfg, jax.random.key(7))
    tok = ByteTokenizer()
    prompts = ["the quick brown fox", "hello world", "mesh sliced serving"]

    def run(mesh, params):
        eng = GenerationEngine(
            cfg,
            params,
            tok,
            max_slots=2,
            max_seq_len=64,
            lookahead=0,
            burst=1,
            prefix_cache_size=0,
            mesh=mesh,
        ).start()
        try:
            futs = [
                eng.submit(tok.encode(p), max_tokens=8, temperature=0.0)
                for p in prompts
            ]
            return [f.result(timeout=120).token_ids for f in futs]
        finally:
            eng.stop()

    gmesh = make_mesh(best_mesh_shape(8, want_model=2))
    with gmesh:
        gparams = shard_pytree(host, llama.logical_axes(cfg), gmesh)
    global_ids = run(gmesh, gparams)

    sl = MeshPlanner(2).acquire()
    with sl.mesh:
        sparams = shard_pytree(host, llama.logical_axes(cfg), sl.mesh)
    slice_ids = run(sl.mesh, sparams)
    assert slice_ids == global_ids


# ----------------------------------------------------------------- chaos
def _stall(engine, delay_s=0.1, fires=16):
    """Arm slow_tick so the engine's loop holds work in flight (requests
    stay client-token-less — the re-route eligibility window)."""
    inj = engine._faults
    assert inj is not None
    inj.arm("slow_tick", fires)
    with inj._lock:
        inj._sites["slow_tick"].delay_s = delay_s


def test_replica_death_on_sliced_fleet_reroutes_to_other_slice():
    """Chaos acceptance: a replica dies mid-trace on a 4-slice fleet — the
    re-route lands on a DIFFERENT slice, goodput is 1.0, and the restarted
    replica rebuilds only its own slice's pool (other slices' warm KV,
    registered prefixes included, is untouched)."""
    reg = ModelRegistry(
        {
            "m": ModelSpec(
                name="m",
                kind="decoder",
                tiny=True,
                replicas=4,
                replica_devices=1,
                max_slots=2,
                max_seq_len=64,
                prefix_min_tokens=8,
                # probability-0 site: never fires on its own, but gives every
                # replica an injector the test can arm (from_spec({}) is None)
                faults={"slow_tick": 0.0},
                router_breaker_threshold=2,
            )
        }
    )
    try:
        r = reg.get_generator("m")
        assert len(r.replicas) == 4
        assert len({rep.engine.slice_id for rep in r.replicas}) == 4
        # warm a DISTINCT prefix into each survivor's pool by pinning
        # dispatch (drain flags route around the others, like the affinity
        # suite does)
        prefixes = {}
        for i in range(1, 4):
            for j, rep in enumerate(r.replicas):
                rep.draining = j != i
            pfx = list(range(10 * i, 10 * i + 12))  # 12 >= prefix_min_tokens
            r.submit(
                pfx + [99], max_tokens=2, temperature=0.0, prefix_len=12
            ).result(timeout=120)
            prefixes[i] = pfx
            assert r.replicas[i].engine.holds_prefix(pfx + [1], 12)
        for rep in r.replicas:
            rep.draining = False
        # warm replica 0 too (compile out of the way), then kill it with
        # token-less work in flight
        for rep in r.replicas[1:]:
            rep.draining = True
        r.submit([1, 2, 3], max_tokens=2, temperature=0.0).result(timeout=120)
        for rep in r.replicas:
            rep.draining = False
        for rep in r.replicas:
            _stall(rep.engine)
        futs = [
            r.submit([5, 6, 7 + i], max_tokens=6, temperature=0.0)
            for i in range(8)
        ]
        time.sleep(0.05)  # inside the stalled first ticks: no host tokens
        dead_slice = r.replicas[0].engine.slice_id
        r.kill_replica(0)
        for f in futs:
            assert len(f.result(timeout=120).token_ids) == 6  # goodput 1.0
        assert r.reroutes > 0
        assert r.rerouted_failed == 0
        assert r.failed_past_first_token == 0
        # every survivor that finished work sits on a DIFFERENT slice
        for rep in r.replicas[1:]:
            assert rep.engine.slice_id != dead_slice
        # restart rebuilds ONLY the dead replica's pool: the survivors'
        # registered prefixes (their slices' warm KV) are untouched
        r.restart_replica(0)
        assert r.replicas[0].engine.slice_id == dead_slice  # slice kept
        for i in range(1, 4):
            assert r.replicas[i].engine.holds_prefix(prefixes[i] + [1], 12)
        assert (
            len(
                r.submit([9, 9, 9], max_tokens=3, temperature=0.0)
                .result(timeout=120)
                .token_ids
            )
            == 3
        )
        assert r.supervision_stats()["healthy"] is True
    finally:
        reg.stop()


# ------------------------------------------------------------- autoscaler
# minimal controller-facing fleet stub (the test_autoscaler discipline:
# exactly the read/actuate surface the controller touches, nothing more)
class _StubSched:
    def __init__(self):
        self.degrade_clamp = None

    def stats(self):
        return {"shed": {}, "est_wait_s": 0.0}

    def set_degrade(self, clamp):
        self.degrade_clamp = clamp


class _StubEngine:
    def __init__(self):
        self.scheduler = _StubSched()
        self.max_slots = 4
        self.active = 0

    def queued_depth(self):
        return 0

    @property
    def num_active(self):
        return self.active


class _StubRep:
    def __init__(self):
        self.engine = _StubEngine()
        self.draining = False


class _StubFleet:
    def __init__(self, n=1):
        self.replicas = [_StubRep() for _ in range(n)]
        self.ttft_p95_s = 0.0
        self.added = 0

    def latency_stats(self):
        return {"ttft_p95_ms": self.ttft_p95_s * 1e3, "ttft_n": 64}

    def kv_stats(self):
        return {"kv_pages_total": 100, "kv_pages_used": 0}

    def add_replica(self):
        self.replicas.append(_StubRep())
        self.added += 1
        return f"stub/r{len(self.replicas) - 1}"

    def remove_replica(self, idx, *, deadline_s=30.0):
        self.replicas.pop(idx)
        return {"replica": "stub", "drained": True, "forced_failures": 0,
                "died_mid_drain": False, "waited_s": 0.0}


class _NoCapacityFleet(_StubFleet):
    """A router whose device slices are exhausted: add_replica raises
    NoCapacity until ``no_capacity`` is cleared (a slice freed)."""

    def __init__(self, n=1):
        super().__init__(n)
        self.no_capacity = True

    def add_replica(self):
        if self.no_capacity:
            raise NoCapacity(
                "all 4 device slice(s) of 2 device(s) are pinned",
                slices_total=4,
                replica_devices=2,
            )
        return super().add_replica()


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_autoscaler_no_capacity_reason_distinct_from_cooldown_and_bounds():
    """Satellite: a scale-up skipped because the slices are exhausted is
    recorded as ``no_capacity`` — distinct from cooldown (flap damping) and
    bounds (the configured ceiling) — so operators can tell "at hardware
    limit" from "flap-damped"."""
    clock = _Clock()
    fleet = _NoCapacityFleet(1)
    asc = SLOAutoscaler(
        fleet,
        AutoscalerConfig(
            min_replicas=1,
            max_replicas=3,
            up_consecutive=2,
            up_cooldown_s=5.0,
        ),
        clock=clock,
    )
    fleet.replicas[0].engine.active = 1
    fleet.ttft_p95_s = 1.2  # over the SLO, below degrade_burn
    recs = []
    for _ in range(2):
        clock.advance(1.0)
        recs.append(asc.tick())
    # the refused spawn is not an actuation: the SAME tick falls through to
    # degradation (shaping load is the only actuator left at the hardware
    # limit, whatever the burn level — exactly the max_replicas behavior)
    assert recs[-1]["decision"] == "no_capacity+degrade_on"
    assert asc.degrade_active is True
    st = asc.stats()
    assert st["scale_up_skipped"]["no_capacity"] == 1
    assert st["last_skip_reason"] == "no_capacity"
    assert st["at_hardware_limit"] is True
    assert st["scale_up_failures"] == 0  # hardware limit is not a fault
    assert st["replicas"] == 1  # fleet size held
    # the flight ring carries the named event
    events = [e["event"] for e in asc.flight.events()]
    assert "scale_up_no_capacity" in events
    # while the limit is sticky, held-back ticks keep attributing to
    # no_capacity (the cooldown is incidental on a saturated host — calling
    # it "cooldown" would read as flap damping); the band stays armed (a
    # refusal never resets hysteresis)
    for _ in range(2):
        clock.advance(1.0)
        asc.tick()
    st = asc.stats()
    assert st["scale_up_skipped"]["no_capacity"] >= 2
    assert st["scale_up_skipped"]["cooldown"] == 0
    assert st["last_skip_reason"] == "no_capacity"
    # the limit transition rides the flight ring ONCE (repeat refusals are
    # counter evidence, not ring spam)
    events = [e["event"] for e in asc.flight.events()]
    assert events.count("scale_up_no_capacity") == 1
    # capacity frees (a slice released): the sticky flag clears on the next
    # successful scale event
    fleet.no_capacity = False
    fleet.ttft_p95_s = 1.2
    for _ in range(8):
        clock.advance(2.0)
        if asc.tick()["decision"] == "scale_up":
            break
    assert asc.stats()["at_hardware_limit"] is False
    assert asc.stats()["last_skip_reason"] is None
    # with capacity back, a held-back scale-up is honestly "cooldown" again
    for _ in range(3):
        clock.advance(1.0)
        asc.tick()
    assert asc.stats()["scale_up_skipped"]["cooldown"] >= 1
    assert asc.stats()["last_skip_reason"] == "cooldown"

    # bounds: a fleet AT max_replicas records "bounds", never "no_capacity"
    clock2 = _Clock()
    fleet2 = _StubFleet(3)
    asc2 = SLOAutoscaler(
        fleet2,
        AutoscalerConfig(min_replicas=1, max_replicas=3, up_consecutive=2),
        clock=clock2,
    )
    fleet2.replicas[0].engine.active = 1
    fleet2.ttft_p95_s = 1.2
    for _ in range(3):
        clock2.advance(1.0)
        asc2.tick()
    st2 = asc2.stats()
    assert st2["scale_up_skipped"]["bounds"] >= 1
    assert st2["scale_up_skipped"]["no_capacity"] == 0
    assert st2["last_skip_reason"] == "bounds"

    # the skip ledger is scrapeable next to the scale counters
    class _Reg:
        generators: dict = {}
        embedders: dict = {}
        autoscalers = {"m": asc}

    fams = parse_prometheus_text(render_prometheus(_Reg()))
    skipped = fams["dabt_autoscale_scale_up_skipped_total"]["samples"]
    nc = [
        v for _, labels, v in skipped if labels.get("reason") == "no_capacity"
    ]
    assert len(nc) == 1 and nc[0] >= 2.0
    assert [
        v for _, _, v in fams["dabt_autoscale_at_hardware_limit"]["samples"]
    ] == [0.0]


# --------------------------------------------------------------- autotune
def test_autotune_budget_is_slice_aware():
    """Satellite: --autotune's HBM budget covers ONE replica's devices — its
    slice on a sliced fleet — not the whole host, so the recommendation
    matches what a sliced replica can actually hold."""
    from django_assistant_bot_tpu.serving.autotune import recommend_for_spec

    cfg = DecoderConfig.tiny()
    sliced = ModelSpec(
        name="s", kind="decoder", tiny=True, replica_devices=2
    )
    out = recommend_for_spec(
        sliced, cfg, n_host_devices=8, hbm_gb_per_device=4.0
    )
    assert out["sliced"] is True
    assert out["slice_devices"] == 2
    assert out["assumptions"]["hbm_budget_gb"] == pytest.approx(8.0)
    # unsliced: the replica's mesh IS the whole host
    flat = ModelSpec(name="f", kind="decoder", tiny=True)
    out = recommend_for_spec(flat, cfg, n_host_devices=8, hbm_gb_per_device=4.0)
    assert out["sliced"] is False
    assert out["slice_devices"] == 8
    assert out["assumptions"]["hbm_budget_gb"] == pytest.approx(32.0)
    # no topology hints at all: the historical single-chip default
    out = recommend_for_spec(flat, cfg)
    assert out["assumptions"]["hbm_budget_gb"] == pytest.approx(16.0)
    # an explicit total budget override always wins
    out = recommend_for_spec(
        sliced, cfg, n_host_devices=8, hbm_gb_per_device=4.0, hbm_budget_gb=1.0
    )
    assert out["assumptions"]["hbm_budget_gb"] == pytest.approx(1.0)
