"""SLO autoscaler (serving/autoscaler.py; docs/AUTOSCALING.md).

Two layers, matching the chaos-suite discipline:

- a DETERMINISTIC fake-clock decision suite over a stub fleet — scale-up on
  SLO burn, trough scale-down, hysteresis/cooldown no-flap under an
  oscillating trace, degradation engage/release, min/max bounds — with zero
  sleeps and zero devices;
- real-engine integration: the router's dynamic-fleet surface
  (add_replica/remove_replica) under live traffic, and THE acceptance race —
  a scale-down drain racing ``replica_dead`` on the same replica: goodput
  1.0, no wedged drain, and a flight-recorder artifact carrying both the
  kill and the scale decision.  Runs under DABT_LOCK_WITNESS in CI.
"""

import json
import threading
import time

import pytest

import jax

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.serving import (
    AutoscalerConfig,
    ByteTokenizer,
    EngineRouter,
    FaultInjector,
    GenerationEngine,
    ModelRegistry,
    SLOAutoscaler,
    render_prometheus,
    parse_prometheus_text,
)


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt

    def sleep(self, dt: float) -> None:
        self.t += dt
        time.sleep(min(dt, 0.005))


# ---------------------------------------------------------------- stub fleet
class _StubSched:
    def __init__(self):
        self.shed_total = 0
        self.est_wait_s = 0.0
        self.degrade_clamp = None
        self.degrade_calls = []

    def stats(self):
        return {"shed": {"queue_full": self.shed_total},
                "est_wait_s": self.est_wait_s}

    def set_degrade(self, clamp):
        self.degrade_clamp = clamp
        self.degrade_calls.append(clamp)


class _StubEngine:
    def __init__(self):
        self.scheduler = _StubSched()
        self.max_slots = 4
        self.queued = 0
        self.active = 0

    def queued_depth(self):
        return self.queued

    @property
    def num_active(self):
        return self.active


class _StubRep:
    def __init__(self):
        self.engine = _StubEngine()
        self.draining = False


class _StubFleet:
    """The exact read/actuate surface the controller touches, nothing more."""

    def __init__(self, n=1):
        self.replicas = [_StubRep() for _ in range(n)]
        self.ttft_p95_s = 0.0
        self.kv_used = 0
        self.kv_total = 100
        self.added = 0
        self.removed = 0
        self.fail_add = False

    def latency_stats(self):
        return {"ttft_p95_ms": self.ttft_p95_s * 1e3, "ttft_n": 64}

    def kv_stats(self):
        return {"kv_pages_total": self.kv_total, "kv_pages_used": self.kv_used}

    def add_replica(self):
        if self.fail_add:
            raise RuntimeError("spawn failed")
        self.replicas.append(_StubRep())
        self.added += 1
        return f"stub/r{len(self.replicas) - 1}"

    def remove_replica(self, idx, *, deadline_s=30.0):
        rep = self.replicas.pop(idx)
        self.removed += 1
        return {"replica": "stub", "drained": True, "forced_failures": 0,
                "died_mid_drain": False, "waited_s": 0.0}


def _asc(fleet, clock, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("interval_s", 1.0)
    kw.setdefault("slo_ttft_p95_s", 1.0)
    kw.setdefault("up_consecutive", 2)
    kw.setdefault("down_consecutive", 3)
    kw.setdefault("up_cooldown_s", 5.0)
    kw.setdefault("down_cooldown_s", 10.0)
    return SLOAutoscaler(fleet, AutoscalerConfig(**kw), clock=clock)


def _ticks(asc, clock, n, dt=1.0):
    out = []
    for _ in range(n):
        clock.advance(dt)
        out.append(asc.tick())
    return out


# ------------------------------------------------------------ decision suite
def test_scale_up_on_slo_burn_after_hysteresis_and_cooldown():
    clock = _FakeClock()
    fleet = _StubFleet(1)
    asc = _asc(fleet, clock)
    # burn 1.2: over the SLO (up_burn 1.0) but below degrade_burn, so the
    # only actuator in play is the replica count.  Burn counts as evidence
    # only with work in flight (a stale rolling window must not scale an
    # idle fleet), so the stub carries one active request.
    fleet.replicas[0].engine.active = 1
    fleet.ttft_p95_s = 1.2
    recs = _ticks(asc, clock, 2)
    # tick 1 arms the band (hysteresis), tick 2 actuates
    assert recs[0]["decision"] == "hold"
    assert recs[1]["decision"] == "scale_up"
    assert fleet.added == 1 and len(fleet.replicas) == 2
    # still burning, but inside the up-cooldown: no second replica yet
    recs = _ticks(asc, clock, 2)
    assert all(r["decision"] == "hold" for r in recs)
    # cooldown expires (5s): the next sustained burn adds the third
    recs = _ticks(asc, clock, 3)
    assert "scale_up" in [r["decision"] for r in recs]
    assert len(fleet.replicas) == 3


def test_scale_up_on_shed_rate_and_kv_pressure_signals():
    clock = _FakeClock()
    fleet = _StubFleet(1)
    asc = _asc(fleet, clock)
    # shed-rate path: a SUSTAINED 5 sheds/s (the signal is the counter's
    # per-tick delta, so the sheds must keep landing across the hysteresis
    # window, not just once)
    _ticks(asc, clock, 1)
    fleet.replicas[0].engine.scheduler.shed_total = 5
    _ticks(asc, clock, 1)
    fleet.replicas[0].engine.scheduler.shed_total = 10
    recs = _ticks(asc, clock, 1)
    assert recs[-1]["decision"] == "scale_up"
    # kv-pressure path on a fresh controller
    clock2, fleet2 = _FakeClock(), _StubFleet(1)
    asc2 = _asc(fleet2, clock2)
    fleet2.kv_used = 95  # 0.95 >= up_kv_frac 0.9
    recs = _ticks(asc2, clock2, 2)
    assert recs[-1]["decision"] == "scale_up"


def test_scale_down_at_trough_requires_consecutive_calm_ticks():
    clock = _FakeClock()
    fleet = _StubFleet(3)
    asc = _asc(fleet, clock)
    # all signals calm, a smaller fleet trivially holds the (zero) load
    recs = _ticks(asc, clock, 3)
    assert [r["decision"] for r in recs] == ["hold", "hold", "scale_down"]
    assert fleet.removed == 1 and len(fleet.replicas) == 2
    # down-cooldown (10s) holds the second removal off
    recs = _ticks(asc, clock, 3)
    assert all(r["decision"] == "hold" for r in recs)
    recs = _ticks(asc, clock, 8)
    assert "scale_down" in [r["decision"] for r in recs]
    assert len(fleet.replicas) == 1  # and never below min_replicas
    recs = _ticks(asc, clock, 20)
    assert fleet.removed == 2 and len(fleet.replicas) == 1


def test_no_flap_under_oscillating_trace():
    """A trace that alternates hot/calm every tick must produce ZERO scale
    actions: the consecutive-tick bands reset on every flip (the classic
    flapping controller this config exists to rule out)."""
    clock = _FakeClock()
    fleet = _StubFleet(2)
    fleet.replicas[0].engine.active = 1  # burn needs in-flight work to count
    asc = _asc(fleet, clock)
    for i in range(20):
        fleet.ttft_p95_s = 2.0 if i % 2 == 0 else 0.1
        clock.advance(1.0)
        rec = asc.tick()
        assert rec["decision"] == "hold", (i, rec)
    assert fleet.added == 0 and fleet.removed == 0
    assert len(fleet.replicas) == 2


def test_scale_down_blocked_when_smaller_fleet_would_not_hold():
    """Calm latency but real load: (queued+active)/(slots of n-1 replicas)
    above down_util blocks the trough band — scaling down into a fleet that
    would immediately re-trigger scale-up is the flap we refuse."""
    clock = _FakeClock()
    fleet = _StubFleet(2)
    for rep in fleet.replicas:
        rep.engine.active = 3  # 6 active over 4 remaining slots >> down_util
    asc = _asc(fleet, clock)
    recs = _ticks(asc, clock, 6)
    assert all(r["decision"] == "hold" for r in recs)
    assert fleet.removed == 0


def test_degradation_band_engages_at_max_fleet_and_releases_with_hysteresis():
    clock = _FakeClock()
    fleet = _StubFleet(3)  # already at max_replicas
    fleet.replicas[0].engine.active = 1  # burn needs in-flight work to count
    asc = _asc(fleet, clock)
    fleet.ttft_p95_s = 2.0  # burn 2.0 >= degrade_burn 1.5
    recs = _ticks(asc, clock, 2)
    assert recs[-1]["decision"] == "degrade_on"
    assert asc.degrade_active
    # every replica's scheduler got the clamp (spec disable rides degraded())
    for rep in fleet.replicas:
        assert rep.engine.scheduler.degrade_clamp == asc.cfg.degrade_max_tokens
    # burn above the release threshold: the band HOLDS (hysteresis)
    fleet.ttft_p95_s = 1.0  # release needs <= 0.75
    recs = _ticks(asc, clock, 3)
    assert asc.degrade_active
    # burn below release: the band releases and the clamps lift
    fleet.ttft_p95_s = 0.2
    recs = _ticks(asc, clock, 1)
    assert recs[-1]["decision"] == "degrade_off"
    assert not asc.degrade_active
    for rep in fleet.replicas:
        assert rep.engine.scheduler.degrade_clamp is None


def test_degradation_precedes_nothing_below_max_fleet():
    """Below the ceiling a replica is the better actuator: sustained burn
    scales up first; degradation engages only once the fleet is maxed."""
    clock = _FakeClock()
    fleet = _StubFleet(2)
    fleet.replicas[0].engine.active = 1  # burn needs in-flight work to count
    asc = _asc(fleet, clock, up_cooldown_s=0.5)
    fleet.ttft_p95_s = 2.0
    decisions = [r["decision"] for r in _ticks(asc, clock, 6)]
    assert decisions.count("scale_up") == 1  # 2 -> 3 (max)
    assert "degrade_on" in decisions  # then shaping, at the ceiling
    assert fleet.added == 1


def test_scale_up_failure_counts_and_does_not_kill_the_loop():
    clock = _FakeClock()
    fleet = _StubFleet(1)
    fleet.fail_add = True
    fleet.replicas[0].engine.active = 1  # burn needs in-flight work to count
    asc = _asc(fleet, clock)
    fleet.ttft_p95_s = 3.0
    recs = _ticks(asc, clock, 3)
    assert "scale_up_failed" in [r["decision"] for r in recs]
    assert asc.stats()["scale_up_failures"] >= 1
    # the factory recovers; the controller retries without a cooldown penalty
    fleet.fail_add = False
    recs = _ticks(asc, clock, 2)
    assert "scale_up" in [r["decision"] for r in recs]


def test_replica_seconds_integrates_fleet_size_over_time():
    clock = _FakeClock()
    fleet = _StubFleet(2)
    asc = _asc(fleet, clock, down_consecutive=100)  # hold the fleet still
    # the first tick anchors the window (dt=0); the next four each cover 2s
    # at 2 replicas -> 16 replica-seconds
    _ticks(asc, clock, 5, dt=2.0)
    assert asc.replica_seconds == pytest.approx(16.0)
    st = asc.stats()
    assert st["replica_seconds"] == pytest.approx(16.0)
    assert st["ticks"] == 5


def test_autoscaler_config_validation():
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscalerConfig(min_replicas=3, max_replicas=1).validate()
    with pytest.raises(ValueError, match="hysteresis"):
        AutoscalerConfig(degrade_burn=1.0, degrade_release_burn=1.0).validate()
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalerConfig(min_replicas=0).validate()


def test_decisions_land_in_the_flight_ring():
    clock = _FakeClock()
    fleet = _StubFleet(1)
    fleet.replicas[0].engine.active = 1  # burn needs in-flight work to count
    asc = _asc(fleet, clock)
    fleet.ttft_p95_s = 2.0
    _ticks(asc, clock, 2)
    events = asc.flight.events()
    assert any(e["event"] == "autoscale" and e["decision"] == "scale_up"
               for e in events)
    # hold ticks do NOT flood the ring
    assert not any(e.get("decision") == "hold" for e in events)


# ------------------------------------------------------- real-engine plane
def _params(seed=1):
    cfg = DecoderConfig.tiny()
    return cfg, llama.init(cfg, jax.random.key(seed))


def _fleet(n=2, **kw):
    cfg, params = _params()
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 64)

    def factory(i):
        return GenerationEngine(
            cfg, params, ByteTokenizer(), name=f"t/r{i}",
            faults=FaultInjector({}), **kw
        ).start()

    engines = [factory(i) for i in range(n)]
    return engines, factory


def _stall(engine, delay_s=0.1, fires=16):
    inj = engine._faults
    inj.arm("slow_tick", fires)
    with inj._lock:
        inj._sites["slow_tick"].delay_s = delay_s


def test_add_replica_serves_and_names_never_reuse():
    engines, factory = _fleet(1)
    r = EngineRouter(engines, replica_factory=factory)
    try:
        name1 = r.add_replica()
        assert len(r.replicas) == 2 and r.replicas_added == 1
        f = r.submit([1, 2, 3], max_tokens=3, temperature=0.0)
        assert len(f.result(timeout=120).token_ids) == 3
        r.remove_replica(1, deadline_s=30.0)
        name2 = r.add_replica()
        assert name2 != name1  # spawn indices are monotonic, names unique
        assert r.router_stats()["replicas_added"] == 2
        assert r.router_stats()["replicas_removed"] == 1
    finally:
        r.stop()


def test_remove_replica_drains_cleanly_under_traffic_zero_shed():
    engines, factory = _fleet(2)
    r = EngineRouter(engines, replica_factory=factory)
    try:
        futs = [r.submit([1, 2, 3 + i], max_tokens=4, temperature=0.0)
                for i in range(6)]
        report = r.remove_replica(0, deadline_s=60.0)
        assert report["drained"] is True
        assert report["forced_failures"] == 0
        assert not report["died_mid_drain"]
        for f in futs:
            assert len(f.result(timeout=120).token_ids) == 4  # goodput 1.0
        assert r.drain_shed == 0
        assert len(r.replicas) == 1
        with pytest.raises(RuntimeError, match="last replica"):
            r.remove_replica(0)
    finally:
        r.stop()


def test_scale_down_drain_racing_replica_death(tmp_path, monkeypatch):
    """THE acceptance race (ISSUE 11): a scale-down drain and ``replica_dead``
    land on the SAME replica.  Contract: goodput 1.0 (every token-less victim
    re-routes to the survivor), the drain completes instead of wedging on a
    dead engine, and the flight-recorder artifact carries BOTH the kill and
    the scale decision.  Runs under DABT_LOCK_WITNESS in the CI smoke."""
    monkeypatch.setenv("DABT_FLIGHT_DIR", str(tmp_path))
    engines, factory = _fleet(2)
    r = EngineRouter(engines, replica_factory=factory, breaker_reset_s=0.2)
    try:
        for i in range(2):  # warm both replicas (compiles out of the way)
            r.submit([1, 2, 3 + i], max_tokens=2, temperature=0.0).result(
                timeout=120
            )
        # pin a batch of work onto replica0, stalled so it stays token-less
        r.replicas[1].draining = True
        _stall(engines[0], delay_s=0.2, fires=32)
        futs = [r.submit([5, 6, 7 + i], max_tokens=4, temperature=0.0)
                for i in range(4)]
        r.replicas[1].draining = False
        # scale-down drain on replica0 (blocked behind the stalled work)...
        reports = []
        t = threading.Thread(
            target=lambda: reports.append(
                r.remove_replica(0, deadline_s=1e9)
            )
        )
        t.start()
        time.sleep(0.05)
        # ...and the SAME replica dies mid-drain
        r.kill_replica(0)
        t.join(timeout=120)
        assert not t.is_alive(), "scale-down drain wedged on a dead replica"
        report = reports[0]
        assert report["died_mid_drain"] is True
        # goodput 1.0: every pinned (token-less) request re-routed and won
        for f in futs:
            assert len(f.result(timeout=120).token_ids) == 4
        assert r.rerouted_failed == 0
        assert r.failed_past_first_token == 0
        assert len(r.replicas) == 1
        # the artifact: one dump, holding the kill AND the scale decision
        dumps = sorted(tmp_path.glob("flight-*.json"))
        assert dumps, "scale_down race left no flight-recorder artifact"
        payload = json.loads(dumps[-1].read_text())
        assert payload["reason"] == "scale_down_interrupted"
        events = [e["event"] for e in payload["events"]]
        assert "scale_down" in events
        assert "replica_kill" in events
    finally:
        r.stop()


def test_autoscaler_scales_real_fleet_down_at_trough():
    """Closed loop on real engines: an idle 2-replica fleet under a
    min=1/max=3 controller drains back to one replica with zero shed —
    driven by tick() under the injected clock, no controller thread."""
    engines, factory = _fleet(2)
    clock = _FakeClock()
    r = EngineRouter(engines, replica_factory=factory,
                     clock=clock, sleep=clock.sleep)
    asc = SLOAutoscaler(
        r,
        AutoscalerConfig(min_replicas=1, max_replicas=3,
                         # the warm-up request's compile-inflated TTFT sample
                         # must not read as SLO burn on the CPU mesh
                         slo_ttft_p95_s=600.0,
                         down_consecutive=2, down_cooldown_s=0.1,
                         drain_deadline_s=1e9),
        clock=clock,
        sleep=clock.sleep,
    )
    try:
        r.submit([1, 2, 3], max_tokens=2, temperature=0.0).result(timeout=120)
        decisions = []
        for _ in range(4):
            clock.advance(1.0)
            decisions.append(asc.tick()["decision"])
        assert "scale_down" in decisions
        assert len(r.replicas) == 1
        assert r.drain_shed == 0
        st = asc.stats()
        assert st["scale_downs"] == 1 and st["replicas"] == 1
        # the fleet still serves after the scale-down
        f = r.submit([9, 9, 9], max_tokens=3, temperature=0.0)
        assert len(f.result(timeout=120).token_ids) == 3
    finally:
        r.stop()


# ----------------------------------------------------- registry + /metrics
def test_registry_dynamic_fleet_and_validation():
    # max_replicas above replicas builds a router even at replicas=1
    registry = ModelRegistry.from_config(
        {"tiny-chat": {"kind": "decoder", "tiny": True, "max_slots": 2,
                       "max_seq_len": 64, "replicas": 1, "max_replicas": 2}}
    )
    try:
        router = registry.get_generator("tiny-chat")
        assert isinstance(router, EngineRouter)
        assert len(router.replicas) == 1
        router.add_replica()  # the factory spawns from the shared weights
        assert len(router.replicas) == 2
        f = router.submit([1, 2, 3], max_tokens=2, temperature=0.0)
        assert len(f.result(timeout=120).token_ids) == 2
    finally:
        registry.stop()
    with pytest.raises(ValueError, match="max_replicas"):
        ModelRegistry.from_config(
            {"x": {"kind": "decoder", "tiny": True, "replicas": 2,
                   "max_replicas": 1}}
        )
    with pytest.raises(ValueError, match="decoder-only"):
        ModelRegistry.from_config(
            {"e": {"kind": "encoder", "tiny": True, "autoscale": True}}
        )


def test_registry_autoscaler_metrics_and_healthz_surface():
    registry = ModelRegistry.from_config(
        {"tiny-chat": {"kind": "decoder", "tiny": True, "max_slots": 2,
                       "max_seq_len": 64, "replicas": 1, "max_replicas": 2,
                       "autoscale": True, "autoscale_interval_s": 30.0}}
    )
    try:
        asc = registry.autoscalers["tiny-chat"]
        st = asc.stats()
        assert st["min_replicas"] == 1 and st["max_replicas"] == 2
        text = render_prometheus(registry)
        fams = parse_prometheus_text(text)
        for fam in ("dabt_autoscale_replicas", "dabt_autoscale_scale_ups_total",
                    "dabt_autoscale_degrade_active",
                    "dabt_router_replicas_added_total",
                    "dabt_router_replica_restarts_total"):
            assert fam in fams, fam
    finally:
        registry.stop()
    # stop() released any forced degradation and halted the control thread
    assert not asc.degrade_active


def test_workload_trace_drives_chaos_fleet_with_tokenless_goodput():
    """The scenario engine meets the chaos plane: a seeded burst trace
    replayed (fake-paced) against a 2-replica fleet whose dispatcher kills a
    replica mid-trace (``replica_dead``, armed exactly once).  Sheds are
    trace outcomes, token-less victims re-route, nothing is silently lost:
    ok + shed + failed-past-first-token == trace length."""
    from django_assistant_bot_tpu.serving import SchedulerRejected
    from django_assistant_bot_tpu.serving.engine import EngineUnavailable
    from django_assistant_bot_tpu.workload import (
        WorkloadConfig,
        WorkloadGenerator,
        prompt_ids_for,
        replay,
    )

    trace = WorkloadGenerator(
        WorkloadConfig(seed=5, duration_s=6.0, base_rps=4.0, shape="burst",
                       burst_every_s=3.0, burst_len_s=1.0, burst_rps=8.0,
                       chat_prompt_tokens=(4, 12), chat_max_tokens=(2, 4),
                       longctx_frac=0.0, prefix_frac=0.0)
    ).generate()
    assert len(trace) >= 10
    engines, factory = _fleet(2)
    inj = FaultInjector({"replica_dead": {"fire_on": [len(trace) // 2]}})
    r = EngineRouter(engines, faults=inj, breaker_reset_s=0.2)
    try:
        r.submit([1, 2, 3], max_tokens=2, temperature=0.0).result(timeout=120)
        futs, shed = [], 0

        def submit(ev):
            nonlocal shed
            try:
                futs.append(
                    r.submit(prompt_ids_for(ev), max_tokens=ev.max_tokens,
                             temperature=0.0, priority=ev.priority,
                             tenant=ev.tenant)
                )
            except (SchedulerRejected, EngineUnavailable):
                shed += 1

        replay(trace, submit, speed=8.0)  # paced, but compressed for CI
        ok = failed = 0
        for f in futs:
            try:
                f.result(timeout=120)
                ok += 1
            except Exception:
                failed += 1
        assert inj.stats()["replica_dead"]["fires"] == 1
        assert sum(not rep.engine._running for rep in r.replicas) == 1
        # accounting closes: every trace arrival is ok, shed, or an honest
        # past-first-token casualty of the kill
        assert ok + shed + failed == len(trace)
        assert ok > 0
        assert failed == r.failed_past_first_token
        assert r.rerouted_failed == 0
    finally:
        r.stop()
