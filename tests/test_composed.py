"""Composed planes on the 8-device mesh (VERDICT r4 #6).

ONE request flows the REAL production path end to end — webhook HTTP POST ->
persisted user message + queued answer task -> worker-side answer task ->
context pipeline (query embedding on the mesh-sharded TPU encoder ->
mesh-SHARDED exact-KNN over the bot's question vectors -> context packing) ->
TP-sharded continuous-batching generation engine -> platform reply — with
every device array (encoder params, corpus rows, decoder params, KV cache)
sharded over the virtual 8-device mesh.  The LLM *semantics* of the classify/
choose steps are scripted (their contracts are covered in test_bot.py); every
data plane is real.
"""

import asyncio
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from django_assistant_bot_tpu.ai.providers.echo import EchoProvider
from django_assistant_bot_tpu.bot.domain import BotPlatform, Update, User
from django_assistant_bot_tpu.conf import settings
from django_assistant_bot_tpu.storage import models


class RecordingPlatform(BotPlatform):
    def __init__(self):
        self.posted = []

    @property
    def codename(self):
        return "telegram"

    async def get_update(self, request):  # pragma: no cover - not driven here
        raise NotImplementedError

    async def post_answer(self, chat_id, answer):
        self.posted.append((chat_id, answer))

    async def action_typing(self, chat_id):
        pass


@pytest.mark.slow
def test_composed_planes_webhook_to_generation(tmp_db, monkeypatch):
    import jax

    from django_assistant_bot_tpu.ai.providers.tpu import (
        get_shared_registry,
        reset_shared_registry,
    )
    from django_assistant_bot_tpu.ai.services.ai_service import get_ai_embedder
    from django_assistant_bot_tpu.bot.services.context_service.steps import (
        base as steps_base,
    )
    from django_assistant_bot_tpu.bot.tasks import _answer_task
    from django_assistant_bot_tpu.rag.index_registry import get_index, reset_indexes
    from django_assistant_bot_tpu.tasks import TaskRecord

    with settings.override(
        EMBEDDING_DIM=64,  # tiny encoder hidden size
        KNN_MESH=True,  # corpus rows shard over the mesh `data` axis
        EMBEDDING_AI_MODEL="tpu:tiny-emb",
        DEFAULT_AI_MODEL="tpu:tiny-chat",
        DIALOG_FAST_AI_MODEL="tpu:tiny-chat",
        DIALOG_STRONG_AI_MODEL="tpu:tiny-chat",
    ):
        reset_shared_registry()
        reset_indexes()
        try:
            bot = models.Bot.objects.create(
                codename="composed-bot", telegram_token="1:composed"
            )
            user = models.BotUser.objects.create(user_id="c1", platform="telegram")
            models.Instance.objects.create(bot=bot, user=user)

            # KB embedded by the REAL mesh-sharded TPU encoder
            wiki = models.WikiDocument.objects.create(bot=bot, title="Billing")
            models.WikiDocumentProcessing.objects.create(
                wiki_document=wiki,
                status=models.WikiDocumentProcessing.COMPLETED,
            )
            doc = models.Document.objects.create(
                wiki=wiki, name="Billing FAQ", content="Pay invoices in the portal."
            )
            embedder = get_ai_embedder("tpu:tiny-emb")
            qs = [f"How to pay invoice? #{i}" for i in range(8)]
            vecs = asyncio.run(embedder.embeddings(qs))
            for i, (q, v) in enumerate(zip(qs, vecs)):
                models.Question.objects.create(
                    document=doc,
                    text=q,
                    order=i,
                    embedding=np.asarray(v, np.float32),
                )

            # 1) webhook ingress over HTTP: persists the user message and
            #    queues the answer task (the api plane)
            from aiohttp.test_utils import TestClient, TestServer

            from django_assistant_bot_tpu.api.app import create_api_app

            async def webhook():
                client = TestClient(TestServer(create_api_app()))
                await client.start_server()
                try:
                    resp = await client.post(
                        "/telegram/composed-bot/",
                        json={
                            "message": {
                                "message_id": 11,
                                "chat": {"id": "c1"},
                                "text": "How to pay invoice?",
                                "from": {"id": "c1", "username": "composer"},
                            }
                        },
                    )
                    assert resp.status == 200
                finally:
                    await client.close()

            asyncio.run(webhook())
            queued = [t for t in TaskRecord.objects.all().all() if "answer_task" in t.name]
            assert queued, "webhook must queue the answer task"
            saved = models.Message.objects.filter(message_id=11).all()
            assert len(saved) == 1
            dialog = models.Dialog.objects.get(id=saved[0].dialog_id)

            # 2) worker-side execution of that task: context pipeline with the
            #    real embedder + sharded KNN, then the real TP engine generates
            scripted = EchoProvider(script=[{"topic": "Billing"}, {"question": None}])
            monkeypatch.setattr(steps_base, "get_ai_provider", lambda model: scripted)
            platform = RecordingPlatform()
            upd = Update(
                chat_id="c1", message_id=11, text="How to pay invoice?",
                user=User(id="c1", username="composer"),
            ).to_dict()
            asyncio.run(
                _answer_task("composed-bot", dialog.id, "telegram", upd, platform=platform)
            )
            assert platform.posted, "the generated answer must reach the platform"
            answer = platform.posted[0][1]
            text = getattr(answer, "text", None) or getattr(answer, "parts", None)
            assert text, answer

            # 3) sharding evidence: every plane's arrays live on all 8 devices
            idx = get_index(models.Question)
            assert idx.mesh is not None and idx.mesh.shape["data"] > 1
            reg = get_shared_registry()
            gen = reg.get_generator("tiny-chat")
            emb = reg.get_embedder("tiny-emb")
            for eng in (gen, emb):
                leaves = jax.tree.leaves(eng.params)
                assert any(len(l.sharding.device_set) == 8 for l in leaves), (
                    "params must be mesh-sharded"
                )
            assert gen.mesh is not None  # KV cache shards via cache_shardings
        finally:
            reset_shared_registry()
            reset_indexes()
