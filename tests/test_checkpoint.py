"""Sharded checkpoint save/restore (SURVEY.md §5.4 — the orbax-analog).

The reference has no model state to checkpoint (inference-only; conversational
state lives in Postgres).  These tests cover the TPU build's obligation: params +
optimizer state survive process death, restore onto a mesh with identical
shardings, and the serving registry can boot from a native checkpoint.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from django_assistant_bot_tpu import checkpoint as ckpt
from django_assistant_bot_tpu.models import DecoderConfig, llama


def tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_sharded_params(tmp_path, mesh8):
    """Sharded save -> per-shard files -> restore with shardings == original."""
    from django_assistant_bot_tpu.parallel import shard_pytree

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    with mesh8:
        sharded = shard_pytree(params, llama.logical_axes(cfg), mesh8)

    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(path, sharded, step=7, meta={"note": "test"})

    # sharded leaves must have produced >1 shard file for TP-sharded weights
    manifest = ckpt.read_manifest(path)
    by_key = {e["key"]: e for e in manifest["leaves"]}
    wq = by_key["['layers']['wq']"]
    assert len(wq["shards"]) > 1  # heads axis sharded over model=2

    shardings = jax.tree.map(lambda x: x.sharding, sharded)
    restored, step, meta = ckpt.restore_checkpoint(path, shardings=shardings)
    assert step == 7 and meta["note"] == "test"
    tree_equal(restored, sharded)
    # restored leaves carry the requested shardings
    assert restored["layers"]["wq"].sharding == sharded["layers"]["wq"].sharding


def test_restore_without_template_rebuilds_dict_tree(tmp_path):
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(1))
    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(path, params)
    restored, _, _ = ckpt.restore_checkpoint(path)
    tree_equal(restored, params)


def test_bfloat16_leaves_roundtrip(tmp_path):
    tree = {"w": jnp.arange(8, dtype=jnp.bfloat16) / 3, "b": jnp.ones((3,), jnp.float32)}
    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(path, tree)
    restored, _, _ = ckpt.restore_checkpoint(path)
    assert np.asarray(restored["w"]).dtype == np.dtype("bfloat16")
    tree_equal(restored, tree)


def test_latest_and_prune(tmp_path):
    d = str(tmp_path)
    for s in (3, 10, 7):
        ckpt.save_checkpoint(ckpt.step_path(d, s), {"x": np.ones(2)}, step=s)
    assert ckpt.latest_checkpoint(d).endswith("step_000000010")
    ckpt.prune_checkpoints(d, keep=2)
    names = sorted(os.listdir(d))
    assert names == ["step_000000007", "step_000000010"]


def test_save_is_atomic_against_partial_state(tmp_path):
    """A leftover .tmp dir from a killed save is ignored and overwritten."""
    d = str(tmp_path)
    path = ckpt.step_path(d, 1)
    os.makedirs(path + ".tmp")  # simulate a crash mid-save
    with open(os.path.join(path + ".tmp", "garbage"), "w") as f:
        f.write("partial")
    assert ckpt.latest_checkpoint(d) is None  # incomplete tmp is not a checkpoint
    ckpt.save_checkpoint(path, {"x": np.arange(4)}, step=1)
    assert ckpt.latest_checkpoint(d) == path
    restored, _, _ = ckpt.restore_checkpoint(path)
    np.testing.assert_array_equal(restored["x"], np.arange(4))


@pytest.mark.slow
def test_kill_and_resume_training_matches_straight_run(tmp_path, mesh8):
    """Train 2 steps -> checkpoint -> 'die' -> restore into a FRESH state -> 1 more
    step == 3 straight steps, bit-for-bit on params."""
    import optax

    from django_assistant_bot_tpu.training import (
        init_train_state,
        make_train_step,
        restore_train_state,
        save_train_state,
    )
    from django_assistant_bot_tpu.training.train import TrainState, batch_sharding

    cfg = DecoderConfig.tiny()
    optimizer = optax.adamw(1e-3)
    step_fn = jax.jit(make_train_step(cfg, optimizer))
    rng = np.random.default_rng(0)
    batches = [
        rng.integers(1, cfg.vocab_size, (4, 32)).astype(np.int32) for _ in range(3)
    ]
    mask = np.ones((4, 32), np.float32)

    def run(state, data):
        with mesh8:
            for ids in data:
                ids_d = jax.device_put(ids, batch_sharding(mesh8))
                mask_d = jax.device_put(mask, batch_sharding(mesh8))
                p, o, _ = step_fn(state.params, state.opt_state, ids_d, mask_d)
                state = TrainState(params=p, opt_state=o, step=state.step + 1)
        return state

    def fresh_state():
        with mesh8:
            return init_train_state(cfg, optimizer, mesh=mesh8)

    # straight 3-step run
    straight = run(fresh_state(), batches)

    # interrupted run: 2 steps, save, restore fresh, 1 step
    d = str(tmp_path / "ckpts")
    s = run(fresh_state(), batches[:2])
    save_train_state(d, s, cfg)
    del s  # the process "dies"
    resumed = restore_train_state(d, cfg, optimizer, mesh=mesh8)
    assert resumed is not None and resumed.step == 2
    resumed = run(resumed, batches[2:])

    assert resumed.step == straight.step == 3
    tree_equal(resumed.params, straight.params)


def test_rope_scaling_config_roundtrips_as_tuple(tmp_path):
    """JSON turns the rope_scaling tuple into a list; the restore path must
    coerce it back or the frozen config becomes unhashable (it rides as a
    static jit argument in the training step)."""
    import dataclasses

    cfg = dataclasses.replace(
        DecoderConfig.tiny(), rope_scaling=(8.0, 1.0, 4.0, 64.0)
    )
    params = llama.init(cfg, jax.random.key(5))
    path = str(tmp_path / "rs-ck")
    ckpt.save_model(path, "decoder", cfg, params)
    kind, cfg2, _, _ = ckpt.load_model(path)
    assert cfg2.rope_scaling == (8.0, 1.0, 4.0, 64.0)
    assert isinstance(cfg2.rope_scaling, tuple)
    hash(cfg2)  # frozen dataclass must stay hashable
    assert cfg2 == cfg


def test_registry_loads_native_checkpoint(tmp_path):
    """cli serve can boot a model from a native checkpoint dir instead of HF."""
    from django_assistant_bot_tpu.serving import ModelRegistry

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(2))
    path = str(tmp_path / "model-ck")
    ckpt.save_model(path, "decoder", cfg, params)

    registry = ModelRegistry.from_config(
        {"native-chat": {"kind": "decoder", "checkpoint": path, "dtype": "float32",
                         "max_slots": 2, "max_seq_len": 64}}
    )
    try:
        eng = registry.get_generator("native-chat")
        assert eng is not None
        r = eng.submit([1, 2, 3], max_tokens=3, temperature=0.0).result(timeout=300)
        assert len(r.token_ids) == 3
        # weights really came from the checkpoint: greedy output matches forward
        seq = np.asarray([[1, 2, 3]], np.int32)
        logits = llama.forward(params, cfg, jnp.asarray(seq))
        assert r.token_ids[0] == int(jnp.argmax(logits[0, -1]))
    finally:
        registry.stop()
