"""dabtlint test suite: every checker on seeded fixture snippets (one
positive + one near-miss negative per code), suppression/baseline mechanics,
the CLI gate, and the runtime lock-order witness — including the contract
test that a deliberately introduced ABBA cycle is convicted by BOTH the
static DABT101 pass and the runtime witness.

No jax required: everything here is AST analysis and pure-Python threading.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import textwrap
import threading
from concurrent.futures import Future
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
TOOLS = REPO_ROOT / "tools"
if str(TOOLS) not in sys.path:  # repo-root conftest adds it; belt for direct runs
    sys.path.insert(0, str(TOOLS))

from dabtlint import Baseline, BaselineError, run_analysis  # noqa: E402
from dabtlint.cli import analyze_paths  # noqa: E402
from dabtlint.suppress import apply_suppressions  # noqa: E402
from dabtlint.witness import (  # noqa: E402
    LockOrderWitness,
    WitnessedLock,
    install,
    uninstall,
)
import dabtlint.witness as witness_mod  # noqa: E402


# --------------------------------------------------------------------- helpers
def _project(tmp_path: Path, files: dict) -> Path:
    root = tmp_path / "proj"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return root


def _findings(tmp_path: Path, files: dict, code: str | None = None):
    out = run_analysis([str(_project(tmp_path, files))])
    if code is not None:
        out = [f for f in out if f.code == code]
    return out


ABBA_SRC = """
    import threading

    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def ab():
        with lock_a:
            with lock_b:
                pass

    def ba():
        with lock_b:
            with lock_a:
                pass
"""


# --------------------------------------------------------------------- DABT101
def test_dabt101_direct_abba_cycle(tmp_path):
    found = _findings(tmp_path, {"locksmod.py": ABBA_SRC}, "DABT101")
    assert len(found) == 1
    f = found[0]
    assert f.module == "proj/locksmod.py"
    assert "lock_a" in f.detail and "lock_b" in f.detail
    assert "legs:" in f.detail


def test_dabt101_same_order_is_clean(tmp_path):
    src = """
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def one():
            with lock_a:
                with lock_b:
                    pass

        def two():
            with lock_a:
                with lock_b:
                    pass
    """
    assert _findings(tmp_path, {"locksmod.py": src}, "DABT101") == []


def test_dabt101_cycle_through_calls(tmp_path):
    src = """
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def takes_a():
            with lock_a:
                pass

        def takes_b():
            with lock_b:
                pass

        def f():
            with lock_a:
                takes_b()

        def g():
            with lock_b:
                takes_a()
    """
    found = _findings(tmp_path, {"calls.py": src}, "DABT101")
    assert len(found) == 1
    assert "call to takes_" in found[0].detail


def test_dabt101_cycle_through_done_callback(tmp_path):
    src = """
        import threading

        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def on_done(f):
            with lock_b:
                pass

        def resolver(fut):
            fut.add_done_callback(on_done)
            with lock_a:
                fut.set_result(1)

        def reverse():
            with lock_b:
                with lock_a:
                    pass
    """
    found = _findings(tmp_path, {"cb.py": src}, "DABT101")
    assert len(found) == 1
    assert "done-callback on_done()" in found[0].detail


# --------------------------------------------------------------------- DABT102
FUT_SRC = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()

        def bad(self, fut):
            with self._lock:
                fut.set_result(1)

        def good(self, fut):
            out = []
            with self._lock:
                out.append(fut)
            out[0].set_result(1)
"""


def test_dabt102_resolve_under_lock(tmp_path):
    found = _findings(tmp_path, {"futmod.py": FUT_SRC}, "DABT102")
    assert [f.symbol for f in found] == ["Box.bad"]
    assert "Box._lock" in found[0].detail


def test_dabt102_interprocedural_and_cancel_heuristic(tmp_path):
    src = """
        import threading

        def helper(f):
            f.set_exception(RuntimeError("x"))

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def via_helper(self, fut):
                with self._lock:
                    helper(fut)

            def cancels_future(self, fut):
                with self._lock:
                    fut.cancel()

            def cancels_timer(self, timer):
                with self._lock:
                    timer.cancel()
    """
    found = _findings(tmp_path, {"futmod.py": src}, "DABT102")
    symbols = sorted(f.symbol for f in found)
    # timer.cancel() is NOT future-shaped — the near-miss stays clean
    assert symbols == ["Box.cancels_future", "Box.via_helper"]
    via = next(f for f in found if f.symbol == "Box.via_helper")
    assert "helper()" in via.detail


# --------------------------------------------------------------------- DABT103
def test_dabt103_blocking_in_async(tmp_path):
    src = """
        import asyncio
        import subprocess
        import threading
        import time

        import requests

        _lk = threading.Lock()

        async def bad_sleep():
            time.sleep(0.1)

        async def bad_http():
            return requests.get("http://x")

        async def bad_subprocess():
            subprocess.run(["true"])

        async def bad_acquire():
            _lk.acquire()

        async def good():
            await asyncio.sleep(0.1)
            _lk.acquire(timeout=1.0)
            _lk.acquire(False)           # try-acquire: cannot block
            _lk.acquire(blocking=False)  # same, keyword form

            def sync_helper():
                time.sleep(1.0)  # nested sync def: not the loop's problem

            return sync_helper
    """
    found = _findings(tmp_path, {"amod.py": src}, "DABT103")
    assert sorted(f.symbol for f in found) == [
        "bad_acquire",
        "bad_http",
        "bad_sleep",
        "bad_subprocess",
    ]


# --------------------------------------------------------------------- DABT104
def test_dabt104_hot_path_reachability_and_taint(tmp_path):
    src = """
        import jax.numpy as jnp

        def _gather(y):
            return y.item()

        def decode_step(x):
            y = jnp.sum(x)
            return _gather(y)

        def cold_path(x):
            y = jnp.sum(x)
            return float(y)

        def decode_step_taint(x):
            y = jnp.sum(x)
            n = float(len([1]))
            return float(y), n
    """
    found = _findings(tmp_path, {"hot.py": src}, "DABT104")
    by_symbol = {f.symbol: f for f in found}
    # .item() flagged in the helper REACHED from decode_step, not at the root
    assert "_gather" in by_symbol
    assert "reachable from hot path decode_step" in by_symbol["_gather"].detail
    # float() fires on the tainted value only; float(len(...)) is clean
    assert "decode_step_taint" in by_symbol
    assert sum(f.symbol == "decode_step_taint" for f in found) == 1
    # cold_path is not in the registry: no finding
    assert "cold_path" not in by_symbol


def test_dabt104_aliased_numpy_import_still_caught(tmp_path):
    src = """
        import numpy as _np

        def decode_step(x):
            return _np.asarray(x)

        def unaliased_helper(x):
            return x
    """
    found = _findings(tmp_path, {"hot.py": src}, "DABT104")
    # the alias canonicalizes through the import table: still convicted
    assert [f.symbol for f in found] == ["decode_step"]
    assert "_np.asarray()" in found[0].detail


def test_dabt104_obs_recorder_entry_points_are_roots(tmp_path):
    """The observability recorders (serving/obs.py) are DABT104 roots in
    their own right: a device sync smuggled into metric recording — or into
    a helper only the recorder reaches — is convicted even when no engine
    hot path in the analyzed set calls it."""
    src = """
        import numpy as np

        def _leak(v):
            return v.item()

        class EngineObs:
            def on_tick(self, block_s, active):
                return np.asarray(block_s)

            def on_finish(self, req):  # NOT a hot-path root: lifecycle only
                return np.asarray(req)

        class Histogram:
            def observe(self, v):
                return _leak(v)

        class FlightRecorder:
            def record(self, event):
                return np.asarray(event)
    """
    found = _findings(tmp_path, {"obs_fixture.py": src}, "DABT104")
    by_symbol = {f.symbol for f in found}
    assert "EngineObs.on_tick" in by_symbol
    assert "FlightRecorder.record" in by_symbol
    # the sync reached THROUGH Histogram.observe is attributed to the helper
    assert "_leak" in by_symbol
    roots = {f.symbol: f.detail for f in found}
    assert "Histogram.observe" in roots["_leak"]
    # request-lifecycle methods are off the tick path and stay unflagged
    assert "EngineObs.on_finish" not in by_symbol


def test_real_obs_module_is_hot_path_clean_and_clock_disciplined():
    """The shipped serving/obs.py: its recorder entry points are in the
    hot-path registry and the module carries the DABT105 injectable-clock
    convention — so the gate (0 new findings) actively covers it."""
    import ast

    from dabtlint.checks import HOT_PATH_PATTERNS, _module_has_clock_convention
    from dabtlint.project import Project

    obs_path = REPO_ROOT / "django_assistant_bot_tpu" / "serving" / "obs.py"
    proj = Project.load([str(obs_path)])
    (mod,) = proj.modules
    # DABT105 scope: serving/ dir + the opt-in convention both hold
    assert _module_has_clock_convention(mod)
    # the registry names real entry points (a rename would silently un-root
    # the recorder; this pins pattern <-> method agreement)
    import fnmatch

    qualnames = set(mod.functions)
    for pat in (
        "*EngineObs.on_tick",
        "*Histogram.observe",
        "*FlightRecorder.record",
    ):
        assert any(fnmatch.fnmatch(q, pat) for q in qualnames), pat
    assert any(pat == "*EngineObs.on_tick" for pat in HOT_PATH_PATTERNS)
    # and the module itself contains no raw time.time()/monotonic() CALLS
    # (injectable defaults are attribute references, not calls)
    tree = ast.parse(obs_path.read_text())
    raw_calls = [
        n
        for n in ast.walk(tree)
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and isinstance(n.func.value, ast.Name)
        and n.func.value.id == "time"
    ]
    assert raw_calls == []


def test_real_fleet_module_is_clock_disciplined_for_dabt105():
    """The fleet wire (serving/fleet.py): PeerClient's connect-retry backoff
    and the router's TTL/reconcile timing are injectable — the module opts
    into the DABT105 convention and the real sweep convicts nothing in it,
    which is what lets the chaos bench drive partitions, backoff, and
    registry TTLs on an offset clock with zero wall sleeps."""
    import ast

    from dabtlint.checks import _module_has_clock_convention
    from dabtlint.project import Project

    fleet_path = REPO_ROOT / "django_assistant_bot_tpu" / "serving" / "fleet.py"
    proj = Project.load([str(fleet_path)])
    (mod,) = proj.modules
    assert _module_has_clock_convention(mod)
    # the retry/backoff and partition-tolerance surfaces under the sweep
    # really exist (a rename would silently un-cover them)
    qualnames = set(mod.functions)
    for want in (
        "PeerClient._request",
        "PeerClient._request_once",
        "FleetRouter._note_refresh_failure",
        "FleetRouter._poll_prefix",
    ):
        assert any(q.endswith(want) for q in qualnames), want
    # the REAL serving-dir DABT105 sweep: zero findings against fleet.py
    serving_dir = REPO_ROOT / "django_assistant_bot_tpu" / "serving"
    found = [
        f
        for f in run_analysis([str(serving_dir)], select={"DABT105"})
        if f.module.endswith("fleet.py")
    ]
    assert found == []
    # and no raw time.time()/monotonic()/sleep() CALLS anywhere in the
    # module — injectable defaults are attribute references, not calls
    tree = ast.parse(fleet_path.read_text())
    raw_calls = [
        n
        for n in ast.walk(tree)
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and isinstance(n.func.value, ast.Name)
        and n.func.value.id == "time"
    ]
    assert raw_calls == []


# --------------------------------------------------------------------- DABT105
def test_dabt105_convention_and_dir_scoping(tmp_path):
    files = {
        "serving/ticker.py": """
            import time

            class Ticker:
                def __init__(self, clock=time.monotonic):
                    self._clock = clock

                def stamp(self):
                    return time.monotonic()

                def good(self):
                    return self._clock()
        """,
        # serving module WITHOUT the convention: not yet disciplined, clean
        "serving/legacy.py": """
            import time

            def stamp():
                return time.time()
        """,
        # convention module OUTSIDE serving/: out of scope, clean
        "elsewhere.py": """
            import time

            def run(clock=time.monotonic):
                return time.monotonic()
        """,
    }
    found = _findings(tmp_path, files, "DABT105")
    assert [(f.module, f.symbol) for f in found] == [
        ("proj/serving/ticker.py", "Ticker.stamp")
    ]
    # the default-arg REFERENCE to time.monotonic is not a call: never flagged
    assert all("__init__" != f.symbol for f in found)


def test_dabt105_nested_function_reported_once(tmp_path):
    src = """
        import time

        class Engine:
            def __init__(self, clock=time.monotonic):
                self._clock = clock

            def outer(self):
                def inner():
                    return time.monotonic()

                return inner
    """
    found = _findings(tmp_path, {"serving/e.py": src}, "DABT105")
    # one site, one finding — attributed to the NESTED function that contains
    # it, not double-reported against the enclosing method too
    assert [f.symbol for f in found] == ["Engine.outer.<locals>.inner"]


def test_dabt105_bare_imported_sleep(tmp_path):
    src = """
        from time import sleep

        def pause(sleep=sleep):
            sleep(1.0)

        def raw_pause():
            sleep(1.0)
    """
    found = _findings(tmp_path, {"serving/p.py": src}, "DABT105")
    assert {f.symbol for f in found} == {"pause", "raw_pause"}


# ------------------------------------------------------- fixture-repo contract
def test_seeded_fixture_repo_exact_finding_set(tmp_path):
    """The acceptance-criteria fixture: one violation per checker, and the
    analyzer yields EXACTLY the expected (code, module, symbol) set."""
    files = {
        "locksmod.py": ABBA_SRC,
        "futmod.py": FUT_SRC,
        "amod.py": """
            import time

            async def leak():
                time.sleep(0.5)
        """,
        "hot.py": """
            import jax.numpy as jnp

            def decode_step(x):
                return jnp.sum(x).item()
        """,
        "serving/clockmod.py": """
            import time

            def wait(sleep=time.sleep):
                time.sleep(0.1)
        """,
    }
    found = run_analysis([str(_project(tmp_path, files))])
    assert {(f.code, f.module, f.symbol) for f in found} == {
        ("DABT101", "proj/locksmod.py", "ab"),
        ("DABT102", "proj/futmod.py", "Box.bad"),
        ("DABT103", "proj/amod.py", "leak"),
        ("DABT104", "proj/hot.py", "decode_step"),
        ("DABT105", "proj/serving/clockmod.py", "wait"),
    }


# ------------------------------------------------------------------ suppression
def test_suppression_requires_reason(tmp_path):
    files = {
        "serving/s.py": """
            import time

            def f(clock=time.monotonic):
                t0 = time.monotonic()  # dabtlint: ignore[DABT105] bench-only stamp
                t1 = time.monotonic()  # dabtlint: ignore[DABT105]
                return t0, t1
        """
    }
    _, findings, lines = analyze_paths([str(_project(tmp_path, files))])
    kept, suppressed, problems = apply_suppressions(findings, lines)
    assert len(suppressed) == 1  # the reasoned one
    assert len(kept) == 1  # the reasonless one stays a finding
    assert problems and "without a reason" in problems[0][2]


def test_suppression_on_preceding_comment_line(tmp_path):
    files = {
        "serving/s.py": """
            import time

            def f(clock=time.monotonic):
                # dabtlint: ignore[DABT105] wall-clock log line, not logic
                return time.monotonic()
        """
    }
    _, findings, lines = analyze_paths([str(_project(tmp_path, files))])
    kept, suppressed, _ = apply_suppressions(findings, lines)
    assert kept == [] and len(suppressed) == 1


# --------------------------------------------------------------------- baseline
def test_baseline_todo_stub_rejected_and_justified_accepted(tmp_path):
    proj = _project(tmp_path, {"futmod.py": FUT_SRC})
    findings = run_analysis([str(proj)])
    assert findings
    bl_path = tmp_path / "baseline.json"
    Baseline.write(str(bl_path), findings)
    with pytest.raises(BaselineError, match="justification"):
        Baseline.load(str(bl_path))
    data = json.loads(bl_path.read_text())
    for ent in data["findings"]:
        ent["justification"] = "fixture: accepted on purpose"
    bl_path.write_text(json.dumps(data))
    bl = Baseline.load(str(bl_path))
    new, accepted, stale = bl.split(findings)
    assert new == [] and len(accepted) == len(findings) and stale == []


def test_baseline_gates_new_findings_and_reports_stale(tmp_path):
    bl_path = tmp_path / "baseline.json"
    bl_path.write_text(
        json.dumps(
            {
                "findings": [
                    {
                        "code": "DABT102",
                        "module": "proj/other.py",
                        "symbol": "gone",
                        "detail": "no longer exists",
                        "justification": "stale on purpose",
                    }
                ],
                "witness": {},
            }
        )
    )
    proj = _project(tmp_path, {"futmod.py": FUT_SRC})
    findings = run_analysis([str(proj)])
    bl = Baseline.load(str(bl_path))
    new, accepted, stale = bl.split(findings)
    assert len(new) == len(findings) and accepted == []
    assert len(stale) == 1 and stale[0]["symbol"] == "gone"


def test_baseline_identity_survives_line_drift(tmp_path):
    proj = _project(tmp_path, {"futmod.py": FUT_SRC})
    key_before = run_analysis([str(proj)])[0].key
    shifted = "# a new header comment\n\n" + (proj / "futmod.py").read_text()
    (proj / "futmod.py").write_text(shifted)
    key_after = run_analysis([str(proj)])[0].key
    assert key_before == key_after  # (code, module, symbol, detail): no lines


# -------------------------------------------------------------------------- CLI
def test_cli_exit_codes(tmp_path):
    proj = _project(tmp_path, {"futmod.py": FUT_SRC})
    env = dict(os.environ, PYTHONPATH=str(TOOLS))
    r = subprocess.run(
        [sys.executable, "-m", "dabtlint", str(proj), "--no-baseline"],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )
    assert r.returncode == 1
    assert "DABT102" in r.stdout and "fix:" in r.stdout
    # write a baseline, justify it, and the gate goes green
    bl = tmp_path / "bl.json"
    subprocess.run(
        [sys.executable, "-m", "dabtlint", str(proj), "--baseline", str(bl), "--write-baseline"],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
        check=True,
    )
    data = json.loads(bl.read_text())
    for ent in data["findings"]:
        ent["justification"] = "cli fixture acceptance"
    bl.write_text(json.dumps(data))
    r2 = subprocess.run(
        [sys.executable, "-m", "dabtlint", str(proj), "--baseline", str(bl)],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "0 new findings" in r2.stdout


def test_real_tree_gate_is_green():
    """`dabtlint django_assistant_bot_tpu/` exits 0 on the committed tree —
    the same invocation CI gates on, with the checked-in baseline."""
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "dabtlint",
            str(REPO_ROOT / "django_assistant_bot_tpu"),
        ],
        capture_output=True,
        text=True,
        env=dict(os.environ, PYTHONPATH=str(TOOLS)),
        cwd=str(REPO_ROOT),
        timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new findings" in r.stdout


# ---------------------------------------------------------------- witness: unit
def _skip_if_witness_active():
    if witness_mod._installed is not None:
        pytest.skip("global lock-order witness active (DABT_LOCK_WITNESS=1)")


def test_witness_two_thread_abba_detected_deterministically(tmp_path):
    for _ in range(3):  # deterministic: same result every run
        w = LockOrderWitness(str(tmp_path))
        a = WitnessedLock(threading.Lock(), w, "A", reentrant=False)
        b = WitnessedLock(threading.Lock(), w, "B", reentrant=False)

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1)
        th1.start()
        th1.join()
        th2 = threading.Thread(target=t2)
        th2.start()
        th2.join()
        kinds = [v.kind for v in w.violations]
        assert kinds == ["lock-order-cycle"], kinds
        assert "A" in w.violations[0].description and "B" in w.violations[0].description


def test_witness_consistent_order_is_clean(tmp_path):
    w = LockOrderWitness(str(tmp_path))
    a = WitnessedLock(threading.Lock(), w, "A", reentrant=False)
    b = WitnessedLock(threading.Lock(), w, "B", reentrant=False)
    for _ in range(4):
        with a:
            with b:
                pass
    assert w.violations == []
    assert w.stats()["order_edges"] == 1


def test_witness_same_class_nesting_flagged(tmp_path):
    w = LockOrderWitness(str(tmp_path))
    s1 = WitnessedLock(threading.Lock(), w, "Sched._lock", reentrant=False)
    s2 = WitnessedLock(threading.Lock(), w, "Sched._lock", reentrant=False)
    with s1:
        with s2:
            pass
    assert [v.kind for v in w.violations] == ["same-class-nesting"]


def test_witness_rlock_reentry_is_clean(tmp_path):
    w = LockOrderWitness(str(tmp_path))
    r = WitnessedLock(threading.RLock(), w, "R", reentrant=True)
    with r:
        with r:
            pass
    with r:
        pass
    assert w.violations == [] and w.held_classes() == []


def test_witness_nonblocking_reacquire_not_a_self_deadlock(tmp_path):
    w = LockOrderWitness(str(tmp_path))
    lk = WitnessedLock(threading.Lock(), w, "L", reentrant=False)
    with lk:
        assert lk.acquire(False) is False  # try-acquire: legal, returns False
        assert lk.acquire(blocking=False) is False
    assert w.violations == [] and w.held_classes() == []
    # the BLOCKING re-acquire shape IS convicted (checked on a fresh witness
    # without actually deadlocking: note_acquire records before blocking)
    w2 = LockOrderWitness(str(tmp_path))
    w2.note_acquire("L", 1, reentrant=False)
    w2.note_acquire("L", 1, reentrant=False, blocking=True)
    assert [v.kind for v in w2.violations] == ["self-deadlock"]


def test_witness_failed_cancel_under_lock_not_convicted(tmp_path):
    _skip_if_witness_active()
    w = LockOrderWitness(str(tmp_path))
    install(w)
    try:
        lk = WitnessedLock(threading.Lock(), w, "L", reentrant=False)
        done = Future()
        done.set_result(1)
        with lk:
            assert done.cancel() is False  # runs no callbacks: hazard-free
        assert w.violations == []
        with lk:
            fresh = Future()
            assert fresh.cancel() is True  # this one DOES run callbacks
        assert [v.kind for v in w.violations] == ["future-under-lock"]
    finally:
        uninstall()


def test_witness_future_under_lock_and_allowlist(tmp_path):
    _skip_if_witness_active()
    w = LockOrderWitness(
        str(tmp_path), allowed_held={"Allowed._lock": "fixture: engine-thread lock"}
    )
    install(w)
    try:
        bad = WitnessedLock(threading.Lock(), w, "Bad._lock", reentrant=False)
        ok = WitnessedLock(threading.Lock(), w, "Allowed._lock", reentrant=False)
        with ok:
            Future().set_result(1)  # allowlisted class: clean
        assert w.violations == []
        with bad:
            Future().set_result(1)
        assert [v.kind for v in w.violations] == ["future-under-lock"]
        assert "Bad._lock" in w.violations[0].description
        # resolution with nothing held: clean
        n = len(w.violations)
        Future().set_result(2)
        assert len(w.violations) == n
    finally:
        uninstall()


# ---------------------------------------------- witness + static: same fixture
def test_abba_fixture_convicted_by_both_static_and_witness(tmp_path):
    """The acceptance contract: ONE deliberately introduced ABBA cycle, caught
    by the static DABT101 pass on the source AND by the runtime witness when
    the same module actually executes on two threads."""
    _skip_if_witness_active()
    proj = _project(tmp_path, {"abba_fixture.py": ABBA_SRC})
    static = [f for f in run_analysis([str(proj)]) if f.code == "DABT101"]
    assert len(static) == 1 and "lock_a" in static[0].detail

    w = install(LockOrderWitness(str(proj)))
    try:
        spec = importlib.util.spec_from_file_location(
            "abba_fixture_runtime", proj / "abba_fixture.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)  # module-level Lock() calls get wrapped
        th1 = threading.Thread(target=mod.ab)
        th1.start()
        th1.join()
        th2 = threading.Thread(target=mod.ba)
        th2.start()
        th2.join()
    finally:
        uninstall()
    kinds = [v.kind for v in w.violations]
    assert kinds == ["lock-order-cycle"], kinds
    # lock classes are named from their creation sites in the fixture file
    assert "abba_fixture.py::lock_a" in w.violations[0].description


# ------------------------------------------------------------- witness: plugin
def test_witness_plugin_fails_session_on_violation(tmp_path):
    """End-to-end pytest wiring: the test itself PASSES, but the witness
    plugin fails the session at sessionfinish with its summary."""
    proj = tmp_path / "wproj"
    proj.mkdir()
    (proj / "test_abba_plugin.py").write_text(
        textwrap.dedent(
            """
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def test_abba_order():
                def t1():
                    with lock_a:
                        with lock_b:
                            pass

                def t2():
                    with lock_b:
                        with lock_a:
                            pass

                a = threading.Thread(target=t1); a.start(); a.join()
                b = threading.Thread(target=t2); b.start(); b.join()
            """
        )
    )
    env = dict(
        os.environ,
        PYTHONPATH=str(TOOLS),
        DABT_LOCK_WITNESS="1",
        DABT_WITNESS_ROOT=str(proj),
    )
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            str(proj / "test_abba_plugin.py"),
            "-q",
            "-p",
            "dabtlint.witness",
            "-p",
            "no:cacheprovider",
            "-p",
            "no:xdist",
            "-p",
            "no:randomly",
        ],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(tmp_path),
        timeout=180,
    )
    assert "1 passed" in r.stdout  # the test itself is green...
    assert r.returncode != 0, r.stdout  # ...the witness fails the session
    assert "lock-order witness" in r.stdout
    assert "lock-order-cycle" in r.stdout


def test_witness_plugin_clean_session_stays_green(tmp_path):
    proj = tmp_path / "cproj"
    proj.mkdir()
    (proj / "test_clean_plugin.py").write_text(
        textwrap.dedent(
            """
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def test_single_order():
                with lock_a:
                    with lock_b:
                        pass
            """
        )
    )
    env = dict(
        os.environ,
        PYTHONPATH=str(TOOLS),
        DABT_LOCK_WITNESS="1",
        DABT_WITNESS_ROOT=str(proj),
    )
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            str(proj / "test_clean_plugin.py"),
            "-q",
            "-p",
            "dabtlint.witness",
            "-p",
            "no:cacheprovider",
            "-p",
            "no:xdist",
            "-p",
            "no:randomly",
        ],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(tmp_path),
        timeout=180,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "lock-order witness" in r.stdout
    assert "0 violation(s)" in r.stdout
