"""Storage plane: ORM CRUD/idempotence, schema parity, KNN exactness, locks.

Mirrors the reference's factory/fixture strategy (SURVEY.md §4) without Django:
fresh sqlite per test via the ``tmp_db`` fixture.
"""

import datetime as dt
import threading

import numpy as np
import pytest

from django_assistant_bot_tpu.storage import InstanceLock, VectorIndex, models
from django_assistant_bot_tpu.storage.orm import DoesNotExist, IntegrityError


@pytest.fixture()
def bot(tmp_db):
    return models.Bot.objects.create(codename="testbot", system_text="sys")


@pytest.fixture()
def instance(bot):
    user = models.BotUser.objects.create(user_id="u1", platform="telegram")
    return models.Instance.objects.create(bot=bot, user=user)


@pytest.fixture()
def dialog(instance):
    return models.Dialog.objects.create(instance=instance)


def test_crud_roundtrip(bot):
    got = models.Bot.objects.get(codename="testbot")
    assert got.id == bot.id and got.system_text == "sys"
    got.system_text = "updated"
    got.save()
    assert models.Bot.objects.get(id=bot.id).system_text == "updated"
    assert models.Bot.objects.count() == 1
    got.delete()
    assert models.Bot.objects.count() == 0


def test_unique_together_message_idempotence(dialog):
    role = models.Role.get_cached("user")
    m1, created1 = models.Message.objects.get_or_create(
        dialog=dialog, message_id=42, defaults={"role": role, "text": "hi"}
    )
    m2, created2 = models.Message.objects.get_or_create(
        dialog=dialog, message_id=42, defaults={"role": role, "text": "dupe"}
    )
    assert created1 and not created2
    assert m1.id == m2.id and m2.text == "hi"
    with pytest.raises(IntegrityError):
        models.Message.objects.create(dialog=dialog, message_id=42, role=role)


def test_filter_lookups_and_ordering(dialog):
    role = models.Role.get_cached("user")
    for i in range(5):
        models.Message.objects.create(dialog=dialog, message_id=i, role=role, text=f"m{i}")
    qs = models.Message.objects.filter(dialog=dialog, message_id__gte=2)
    assert qs.count() == 3
    ordered = qs.order_by("-message_id").all()
    assert [m.message_id for m in ordered] == [4, 3, 2]
    assert models.Message.objects.filter(message_id__in=[0, 4]).count() == 2
    assert models.Message.objects.filter(text__contains="m3").count() == 1
    first = models.Message.objects.filter(dialog=dialog).order_by("message_id").first()
    assert first.message_id == 0
    last = models.Message.objects.filter(dialog=dialog).order_by("message_id").last()
    assert last.message_id == 4


def test_fk_cascade_and_accessor(dialog):
    role = models.Role.get_cached("assistant")
    msg = models.Message.objects.create(dialog=dialog, message_id=1, role=role, text="x")
    assert msg.dialog.id == dialog.id  # lazy FK accessor
    assert msg.role.name == "assistant"
    dialog.instance.delete()  # cascades instance -> dialog -> message
    assert models.Message.objects.count() == 0
    assert models.Dialog.objects.count() == 0


def test_json_and_datetime_fields(instance):
    instance.state = {"mode": "chat", "debug_info": {"t": 1.5}}
    instance.save()
    fresh = models.Instance.objects.get(id=instance.id)
    assert fresh.state["debug_info"]["t"] == 1.5
    assert isinstance(fresh.created_at, dt.datetime)
    assert fresh.created_at.tzinfo is not None


def test_wiki_tree_path(tmp_db, bot=None):
    bot = models.Bot.objects.create(codename="b")
    root = models.WikiDocument.objects.create(bot=bot, title="Root")
    child = models.WikiDocument.objects.create(bot=bot, parent=root, title="Child")
    leaf = models.WikiDocument.objects.create(bot=bot, parent=child, title="Leaf")
    assert leaf.path == "Root / Child / Leaf"
    assert [d.id for d in root.descendants()] == [child.id, leaf.id]


def test_vector_field_roundtrip(tmp_db):
    bot = models.Bot.objects.create(codename="b")
    wiki = models.WikiDocument.objects.create(bot=bot, title="w")
    doc = models.Document.objects.create(wiki=wiki, name="d")
    vec = np.random.default_rng(0).normal(size=768).astype(np.float32)
    q = models.Question.objects.create(document=doc, text="q?", embedding=vec)
    got = models.Question.objects.get(id=q.id)
    np.testing.assert_array_equal(got.embedding, vec)
    with pytest.raises(ValueError):
        models.Question.objects.create(document=doc, text="bad", embedding=vec[:10])


def test_knn_exact_top1():
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(500, 64)).astype(np.float32)
    index = VectorIndex(64)
    index.add(list(range(1, 501)), vecs)
    # query = exact copy of row 123 (id 124) -> top-1 must be itself with sim ~1
    hits = index.search(vecs[123], k=5)
    assert hits[0][0] == 124
    assert hits[0][1] == pytest.approx(1.0, abs=2e-2)  # bf16 scoring
    # brute-force numpy agreement on the full top-5 id set
    normed = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    expected = set(np.argsort(-(normed @ normed[123]))[:5] + 1)
    assert {h[0] for h in hits} == expected


def test_knn_mutation_and_growth():
    index = VectorIndex(16)
    rng = np.random.default_rng(1)
    a = rng.normal(size=(200, 16)).astype(np.float32)
    index.add(list(range(200)), a)
    assert len(index) == 200
    index.remove([0, 1, 2])
    assert len(index) == 197
    hits = index.search(a[0], k=3)
    assert all(h[0] not in (0, 1, 2) for h in hits)
    # grow past the 128/256 pad boundary — results still exact for a fresh row
    b = rng.normal(size=(300, 16)).astype(np.float32)
    index.add(list(range(1000, 1300)), b)
    hits = index.search(b[50], k=1)
    assert hits[0][0] == 1050


def test_knn_incremental_append_avoids_full_restage():
    """Appends within the capacity bucket transfer only the new rows; the full
    re-stage (O(N) host->HBM) happens only on growth/overwrite/remove."""
    index = VectorIndex(16)
    rng = np.random.default_rng(3)
    a = rng.normal(size=(50, 16)).astype(np.float32)
    index.add(list(range(50)), a)
    index.search(a[0], k=1)  # materialize device copy (capacity 128)

    stages = []
    orig = index._stage_full
    index._stage_full = lambda n: (stages.append(n), orig(n))[1]

    b = rng.normal(size=(40, 16)).astype(np.float32)
    index.add(list(range(100, 140)), b)
    hits = index.search(b[7], k=1)
    assert hits[0][0] == 107
    assert stages == []  # appended in place
    # old rows still searchable after the in-place append
    assert index.search(a[10], k=1)[0][0] == 10
    # growth past capacity grows ON DEVICE — still no O(N) corpus re-transfer
    c = rng.normal(size=(60, 16)).astype(np.float32)
    index.add(list(range(200, 260)), c)
    assert index.search(c[5], k=1)[0][0] == 205
    assert index.search(a[10], k=1)[0][0] == 10  # pre-growth rows intact
    assert stages == []
    # overwriting an existing row re-stages (positions may be reused)
    index.add([10], rng.normal(size=(1, 16)).astype(np.float32))
    index.search(a[0], k=1)
    assert len(stages) == 1


def test_knn_allowed_ids_mask():
    """Allow-listed search masks row positions on the scoring kernel — exact
    filtered top-k without ranking the whole corpus (reference semantics:
    ``filter(id__in=...)`` + pgvector KNN)."""
    rng = np.random.default_rng(8)
    vecs = rng.normal(size=(300, 32)).astype(np.float32)
    index = VectorIndex(32)
    index.add(list(range(300)), vecs)
    q = vecs[42]
    allowed = {7, 99, 123, 250, 9999}  # 9999 not in the index: ignored
    hits = index.search(q, k=10, allowed_ids=allowed)
    assert [i for i, _ in hits[:1]] != [42]  # 42 itself is masked out
    assert {i for i, _ in hits} <= {7, 99, 123, 250}
    assert len(hits) == 4
    # agreement with brute force restricted to the allowlist
    normed = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    sims = normed @ normed[42]
    want = sorted([7, 99, 123, 250], key=lambda i: -sims[i])
    assert [i for i, _ in hits] == want
    # nothing allowed -> empty rows, no kernel call explosion
    assert index.search(q, k=5, allowed_ids={55555}) == []
    # unfiltered search unaffected
    assert index.search(q, k=1)[0][0] == 42


def test_knn_add_device_no_host_roundtrip():
    """Device-born rows append without a host round trip and stay searchable;
    the host copy materializes lazily when a re-stage needs it."""
    import jax.numpy as jnp

    rng = np.random.default_rng(9)
    a = rng.normal(size=(100, 16)).astype(np.float32)
    index = VectorIndex(16)
    index.add(list(range(100)), a)
    index.search(a[0], k=1)  # stage

    stages = []
    orig = index._stage_full
    index._stage_full = lambda n: (stages.append(n), orig(n))[1]

    b = rng.normal(size=(20, 16)).astype(np.float32)
    index.add_device(list(range(500, 520)), jnp.asarray(b))
    assert len(index) == 120
    assert index._pending_host  # host copy deferred
    assert index.search(b[3], k=1)[0][0] == 503
    assert stages == []  # no full re-stage, no host round trip
    # old rows still searchable
    assert index.search(a[10], k=1)[0][0] == 10
    # a remove forces host materialization + re-stage; device rows survive it
    index.remove([0])
    assert index.search(b[3], k=1)[0][0] == 503
    assert not index._pending_host
    assert len(stages) == 1
    # device append onto an unstaged/sharded/dirty index falls back to host add
    cold = VectorIndex(16)
    cold.add_device([1, 2], jnp.asarray(a[:2]))
    assert cold.search(a[1], k=1)[0][0] == 2


def test_knn_append_bucket_spanning_two_growths():
    """A padded append bucket must fit capacity entirely: dynamic_update_slice
    CLAMPS an out-of-range start, which would silently overwrite row 0 onward.
    Regression: start=50, m=70 -> bucket 256 needs capacity 512, not 256."""
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    a = rng.normal(size=(50, 16)).astype(np.float32)
    index = VectorIndex(16)
    index.add(list(range(50)), a)
    index.search(a[0], k=1)  # stage at capacity 128
    b = rng.normal(size=(70, 16)).astype(np.float32)
    index.add(list(range(100, 170)), b)  # host incremental path
    assert index.search(a[0], k=1)[0][0] == 0  # old rows intact
    assert index.search(b[3], k=1)[0][0] == 103
    # same shape stress through the device-append path
    index2 = VectorIndex(16)
    index2.add(list(range(50)), a)
    index2.search(a[0], k=1)
    index2.add_device(list(range(100, 170)), jnp.asarray(b))
    assert index2.search(a[0], k=1)[0][0] == 0
    assert index2.search(b[3], k=1)[0][0] == 103


def test_knn_warmup_precompiles_and_blocks():
    rng = np.random.default_rng(10)
    vecs = rng.normal(size=(200, 16)).astype(np.float32)
    index = VectorIndex(16)
    index.add(list(range(200)), vecs)
    assert index.warmup() is index  # stages + pre-executes query buckets
    assert index._device_count == 200
    assert index.search(vecs[5], k=3)[0][0] == 5
    # empty index: warmup is a no-op, not an error
    assert VectorIndex(8).warmup()._device_index is None


def test_knn_async_searcher_coalesces():
    """Concurrent async searches share ONE batched dispatch and still get
    per-caller k slicing; allowlist queries bypass coalescing."""
    import asyncio

    from django_assistant_bot_tpu.storage.knn import AsyncSearcher

    rng = np.random.default_rng(12)
    vecs = rng.normal(size=(100, 16)).astype(np.float32)
    index = VectorIndex(16)
    index.add(list(range(100)), vecs)

    calls = []
    orig = index.search_batch

    def spy(queries, k=10, allowed_ids=None):
        calls.append(len(queries))
        return orig(queries, k, allowed_ids=allowed_ids)

    index.search_batch = spy
    searcher = AsyncSearcher(index, window_s=0.01)

    async def drive():
        return await asyncio.gather(
            *(searcher.search(vecs[i], k=1 + i % 3) for i in range(6))
        )

    rows = asyncio.run(drive())
    assert [r[0][0] for r in rows] == list(range(6))  # each finds itself
    assert [len(r) for r in rows] == [1 + i % 3 for i in range(6)]
    assert calls == [6]  # one coalesced dispatch for all six

    async def drive_allowed():
        return await searcher.search(vecs[0], k=2, allowed_ids={5, 7})

    hits = asyncio.run(drive_allowed())
    assert {i for i, _ in hits} == {5, 7}


def test_knn_remove_then_add_same_count_keeps_ids_fresh():
    """Regression: a remove + add netting the same row count must refresh the
    position->id snapshot (it used to be refreshed only on length change)."""
    index = VectorIndex(8)
    rng = np.random.default_rng(4)
    vecs = rng.normal(size=(5, 8)).astype(np.float32)
    index.add([1, 2, 3, 4, 5], vecs)
    index.search(vecs[0], k=1)
    index.remove([3])
    fresh = rng.normal(size=(1, 8)).astype(np.float32)
    index.add([99], fresh)  # back to 5 rows
    assert index.search(fresh[0], k=1)[0][0] == 99


def test_knn_sharded_matches_single_device(mesh8):
    """Rows sharded over the mesh 'data' axis: local top-k + all-gather merge
    returns exactly the single-device result."""
    rng = np.random.default_rng(5)
    vecs = rng.normal(size=(700, 64)).astype(np.float32)
    plain = VectorIndex(64)
    plain.add(list(range(700)), vecs)
    sharded = VectorIndex(64, mesh=mesh8)
    sharded.add(list(range(700)), vecs)
    queries = rng.normal(size=(9, 64)).astype(np.float32)
    got = sharded.search_batch(queries, k=7)
    want = plain.search_batch(queries, k=7)
    for g, w in zip(got, want):
        assert [i for i, _ in g] == [i for i, _ in w]
        np.testing.assert_allclose(
            [s for _, s in g], [s for _, s in w], rtol=0, atol=1e-3
        )
    # incremental append works on the sharded path too
    extra = rng.normal(size=(30, 64)).astype(np.float32)
    sharded.add(list(range(1000, 1030)), extra)
    plain.add(list(range(1000, 1030)), extra)
    assert sharded.search(extra[3], k=1)[0][0] == 1003
    # k larger than one shard's rows still works (local top-k caps at n_local)
    big_k = sharded.search_batch(queries[:1], k=600)[0]
    want_k = plain.search_batch(queries[:1], k=600)[0]
    assert [i for i, _ in big_k] == [i for i, _ in want_k]


def test_knn_from_model(tmp_db):
    bot = models.Bot.objects.create(codename="b")
    wiki = models.WikiDocument.objects.create(bot=bot, title="w")
    doc = models.Document.objects.create(wiki=wiki, name="d")
    rng = np.random.default_rng(2)
    ids = []
    for i in range(10):
        q = models.Question.objects.create(
            document=doc, text=f"q{i}", embedding=rng.normal(size=768).astype(np.float32)
        )
        ids.append(q.id)
    models.Question.objects.create(document=doc, text="no-emb")  # must be skipped
    index = VectorIndex.from_model(models.Question)
    assert len(index) == 10
    target = models.Question.objects.get(id=ids[3])
    assert index.search(target.embedding, k=1)[0][0] == ids[3]


def test_instance_lock_mutual_exclusion(tmp_db):
    order = []

    def worker(name):
        with InstanceLock("conv:1", timeout=10):
            order.append(f"{name}-in")
            import time as _t

            _t.sleep(0.05)
            order.append(f"{name}-out")

    threads = [threading.Thread(target=worker, args=(f"t{i}",)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # critical sections never interleave: every -in is followed by its own -out
    for i in range(0, 6, 2):
        assert order[i].split("-")[0] == order[i + 1].split("-")[0]


def test_instance_lock_steals_stale(tmp_db):
    lock1 = InstanceLock("conv:2", stale_s=0.01)
    lock1.acquire()  # never released — simulates a dead holder
    lock2 = InstanceLock("conv:2", timeout=5, stale_s=0.01)
    import time as _t

    _t.sleep(0.05)
    lock2.acquire()
    lock2.release()


def test_get_returns_error_on_missing(tmp_db):
    with pytest.raises(DoesNotExist):
        models.Bot.objects.get(codename="nope")
    assert models.Bot.objects.get_or_none(codename="nope") is None


def test_knn_search_exact_at_hierarchical_topk_scale():
    """Corpora past the hierarchical-top-k threshold (16384 rows) still return
    exact top-k (the KNN kernel switches to the two-stage top-k there — the
    flat sort over 1M scores dominated the batched query latency)."""
    from django_assistant_bot_tpu.ops.sampling import _HIER_TOPK_MIN_VOCAB
    from django_assistant_bot_tpu.storage.knn import VectorIndex

    n, dim = _HIER_TOPK_MIN_VOCAB + 1000, 32
    rng = np.random.default_rng(7)
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    index = VectorIndex(dim)
    index.add(range(n), vecs)
    q = rng.normal(size=(dim,)).astype(np.float32)

    got = index.search(q, k=10)
    # numpy reference: bf16-rounded rows (the device path normalizes in bf16)
    import jax.numpy as jnp

    rows = np.asarray(vecs, dtype=jnp.bfloat16).astype(np.float32)
    rows /= np.maximum(np.linalg.norm(rows, axis=1, keepdims=True), 1e-12)
    # the device path rounds the normalized rows back to bf16 — mirror it
    rows = rows.astype(jnp.bfloat16).astype(np.float32)
    qn = q / max(np.linalg.norm(q), 1e-12)
    scores = rows @ np.asarray(qn, dtype=jnp.bfloat16).astype(np.float32)
    want = np.argsort(-scores)[:10]
    assert [i for i, _ in got] == want.tolist()
