"""ANN retrieval plane (storage/ann.py): IVF-PQ training, ADC correctness,
recall floors, live ingestion under queries, and registry routing.

Everything is seeded and CPU-sized — this file doubles as the CI "ANN smoke"
step, so the recall floors here are the regression net for the quantizer."""

import asyncio
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from django_assistant_bot_tpu.conf import settings
from django_assistant_bot_tpu.storage.ann import (
    ANNIndex,
    _adc_shortlist,
    _kmeans_step,
    _pq_step,
    _spill_assign,
    make_clustered,
)
from django_assistant_bot_tpu.storage.knn import VectorIndex, _normalize


# ----------------------------------------------------------------- training
def test_kmeans_step_separates_clusters_and_stays_normalized():
    rng = np.random.default_rng(0)
    a = np.array([1.0, 0.0, 0.0, 0.0], np.float32)
    b = np.array([0.0, 1.0, 0.0, 0.0], np.float32)
    batch = np.concatenate(
        [
            a + 0.05 * rng.standard_normal((64, 4)).astype(np.float32),
            b + 0.05 * rng.standard_normal((64, 4)).astype(np.float32),
        ]
    )
    batch = _normalize(batch)
    # seeded-from-data init, as _learn does (random init can collapse both
    # centroids into one cluster and the never-hit one keeps its old value)
    cents = jnp.asarray(batch[[0, 64]])
    counts = jnp.zeros((2,), jnp.float32)
    for _ in range(8):
        cents, counts = _kmeans_step(cents, counts, jnp.asarray(batch))
    cents = np.asarray(cents)
    np.testing.assert_allclose(np.linalg.norm(cents, axis=1), 1.0, atol=1e-5)
    # each true center must be close (cos > 0.98) to exactly one centroid
    sims = np.stack([a, b]) @ cents.T
    assert sims.max(axis=1).min() > 0.98
    assert set(sims.argmax(axis=1)) == {0, 1}


def test_pq_step_reduces_quantization_error():
    rng = np.random.default_rng(1)
    m, sub = 2, 4
    batch = rng.standard_normal((512, m, sub)).astype(np.float32) * 0.1
    cb = jnp.asarray(rng.standard_normal((m, 256, sub)).astype(np.float32))
    counts = jnp.zeros((m, 256), jnp.float32)

    def err(codebooks):
        c = np.asarray(codebooks)
        d = ((batch[:, :, None, :] - c[None]) ** 2).sum(-1)  # [B, m, 256]
        return d.min(axis=2).mean()

    e0 = err(cb)
    for _ in range(6):
        cb, counts = _pq_step(cb, counts, jnp.asarray(batch))
    assert err(cb) < e0 * 0.5


def test_spill_assign_respects_soft_cap():
    # 3 lists; every row's nearest is list 0, runner-up alternates 1/2
    n, cap = 90, 20
    lists2 = np.zeros((n, 2), np.int64)
    lists2[:, 1] = np.where(np.arange(n) % 2 == 0, 1, 2)
    fill = np.zeros(3, np.int64)
    out = _spill_assign(lists2, fill, cap)
    counts = np.bincount(out, minlength=3)
    # runners-up each absorb up to cap; the rest stay at the (soft) nearest
    assert counts[1] == cap and counts[2] == cap
    assert counts[0] == n - 2 * cap
    assert counts.sum() == n  # no row lost
    np.testing.assert_array_equal(counts, fill[:3])  # fill mutated in step


def test_spill_overflow_stays_at_nearest_when_runner_up_full():
    # both candidate lists below cap only for the first rows: the tail must
    # stay in its nearest list (soft cap) rather than being dropped
    n, cap = 50, 10
    lists2 = np.zeros((n, 2), np.int64)
    lists2[:, 1] = 1
    fill = np.zeros(2, np.int64)
    out = _spill_assign(lists2, fill, cap)
    counts = np.bincount(out, minlength=2)
    assert counts[0] == n - cap and counts[1] == cap
    assert counts.sum() == n  # no row lost


# ---------------------------------------------------------- ADC correctness
def test_adc_scores_match_dequantized_reference():
    dim, n = 32, 512
    rows = make_clustered(n, dim, n_clusters=8, seed=3)
    index = ANNIndex(dim, nlist=8, m=4, seed=3)
    index.add(range(n), rows)
    index.train()

    cent = np.asarray(index._centroids, np.float32)
    cb = np.asarray(index._codebooks, np.float32)
    codes = np.asarray(index._codes)
    lvalid = np.asarray(index._lvalid)
    rowpos = np.asarray(index._rowpos)
    nlist, list_cap, m = codes.shape
    sub = cb.shape[2]

    q = _normalize(make_clustered(4, dim, n_clusters=8, seed=7))
    sl = nlist * list_cap
    sl_scores, sl_pos = _adc_shortlist(
        index._centroids, index._codebooks, index._codes, index._lvalid,
        index._rowpos, jnp.asarray(q), nlist, sl,
    )
    sl_scores, sl_pos = np.asarray(sl_scores), np.asarray(sl_pos)

    # reference: score = q . c_list + sum_m lut[m, code_m], per occupied slot
    ref = {}
    q_sub = q.reshape(4, m, sub)
    for li in range(nlist):
        for si in range(list_cap):
            if not lvalid[li, si]:
                continue
            dec = cb[np.arange(m), codes[li, si]]  # [m, sub]
            for qi in range(4):
                ref[(qi, int(rowpos[li, si]))] = float(
                    q[qi] @ cent[li] + (q_sub[qi] * dec).sum()
                )
    checked = 0
    for qi in range(4):
        for j in range(sl):
            if not np.isfinite(sl_scores[qi, j]):
                continue
            assert ref[(qi, int(sl_pos[qi, j]))] == pytest.approx(
                float(sl_scores[qi, j]), abs=2e-3
            )
            checked += 1
    assert checked >= 4 * n  # every live slot scored for every query


# ------------------------------------------------------------ recall floors
def test_recall_floor_at_default_nprobe():
    dim, n = 64, 6000
    index = ANNIndex(dim, seed=0)
    index.add(range(n), make_clustered(n, dim, seed=0))
    index.train()
    rec = index.probe_recall(n_queries=64, k=10, seed=0)
    assert rec["recall_at_k"] >= 0.9
    assert index.stats()["last_recall"]["recall_at_k"] == rec["recall_at_k"]


def test_untrained_index_serves_exact_results():
    dim, n = 32, 300
    rows = make_clustered(n, dim, seed=5)
    ann = ANNIndex(dim)
    ann.add(range(n), rows)  # never trained -> exact fallback
    exact = VectorIndex(dim)
    exact.add(range(n), rows)
    q = rows[17] + 0.01
    a, e = ann.search(q, k=5), exact.search(q, k=5)
    assert [i for i, _ in a] == [i for i, _ in e]
    assert a[0][0] == 17
    assert ann.stats()["exact_fallback"] is True


def test_allowed_ids_uses_exact_tier_on_trained_index():
    dim, n = 32, 1000
    rows = make_clustered(n, dim, seed=6)
    index = ANNIndex(dim, seed=6)
    index.add(range(n), rows)
    index.train()
    allowed = set(range(0, n, 7))
    hits = index.search(rows[21], k=5, allowed_ids=allowed)
    assert hits and all(i in allowed for i, _ in hits)
    assert hits[0][0] == 21  # 21 is allowed; exact tier must find itself
    fenced = index.search(rows[22], k=5, allowed_ids=allowed)
    assert fenced and all(i in allowed and i != 22 for i, _ in fenced)


# --------------------------------------------------------------- liveness
def test_append_after_train_is_searchable_without_retrain():
    dim, n = 32, 2000
    index = ANNIndex(dim, seed=1)
    index.add(range(n), make_clustered(n, dim, seed=1))
    index.train()
    retrains0 = index.stats()["retrains"]
    extra = make_clustered(200, dim, seed=11)
    index.add(range(n, n + 200), extra)
    assert index.stats()["pending_appends"] == 200
    assert index.stats()["retrains"] == retrains0  # append, not retrain
    hits = index.search(extra[5], k=3)
    assert hits[0][0] == n + 5
    assert hits[0][1] == pytest.approx(1.0, abs=5e-3)


def test_append_under_concurrent_queries():
    dim, n = 32, 2000
    rows = make_clustered(n, dim, seed=2)
    index = ANNIndex(dim, seed=2)
    index.add(range(n), rows)
    index.train()

    stop = threading.Event()
    errors = []

    def hammer():
        qs = rows[:8] + 0.01
        while not stop.is_set():
            try:
                out = index.search_batch(qs, k=5)
                assert len(out) == 8 and all(r for r in out)
            except Exception as e:  # noqa: BLE001 - surface to the main thread
                errors.append(e)
                return

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for b in range(4):
            start = n + b * 100
            index.add(range(start, start + 100), make_clustered(100, dim, seed=20 + b))
        index.train()  # full retrain while queries are in flight
    finally:
        stop.set()
        t.join()
    assert not errors
    assert len(index) == n + 400
    hit = index.search(make_clustered(100, dim, seed=23)[7], k=1)[0]
    assert hit[0] == n + 307


def test_remove_tombstones_then_compaction():
    dim, n = 32, 1200
    rows = make_clustered(n, dim, seed=4)
    index = ANNIndex(dim, seed=4)
    index.add(range(n), rows)
    index.train()
    index.remove(range(0, 100))
    assert len(index) == n - 100
    assert index.stats()["tombstones"] == 100
    hits = index.search(rows[13], k=10)  # removed row must never come back
    assert all(i >= 100 for i, _ in hits)
    # crossing the dead fraction triggers automatic compaction
    index.remove(range(100, 400))
    st = index.stats()
    assert st["compactions"] >= 1
    assert st["tombstones"] == 0
    assert len(index) == n - 400
    hits = index.search(rows[500], k=3)
    assert hits[0][0] == 500


def test_add_same_id_overwrites_old_vector():
    dim = 32
    rows = make_clustered(64, dim, seed=8)
    index = ANNIndex(dim, nlist=8, m=4, seed=8)
    index.add(range(64), rows)
    index.train()
    new_vec = -rows[3]
    index.add([3], new_vec[None, :])
    assert len(index) == 64
    assert index.search(new_vec, k=1)[0][0] == 3
    # the stale encoding must not satisfy the old vector anymore
    top_old = index.search(rows[3], k=1)[0]
    assert top_old[0] != 3 or top_old[1] < 0.9


def test_clear_resets_to_empty_untrained():
    index = ANNIndex(16, nlist=8, m=4)
    index.add(range(128), make_clustered(128, 16, seed=9))
    index.train()
    index.clear()
    assert len(index) == 0
    assert index.search_batch(np.ones((1, 16), np.float32), k=3) == [[]]
    st = index.stats()
    assert not st["trained"] and st["rows"] == 0


# ---------------------------------------------------------------- sharding
def test_sharded_scan_matches_plain(mesh8):
    dim, n = 64, 4000
    rows = make_clustered(n, dim, seed=12)
    plain = ANNIndex(dim, seed=12)
    plain.add(range(n), rows)
    plain.train()
    sharded = ANNIndex(dim, mesh=mesh8, seed=12)
    sharded.add(range(n), rows)
    sharded.train()
    assert sharded.nlist % mesh8.shape["data"] == 0
    qs = rows[::500] + 0.01
    p_out = plain.search_batch(qs, k=10)
    s_out = sharded.search_batch(qs, k=10)
    for p_row, s_row in zip(p_out, s_out):
        assert p_row[0][0] == s_row[0][0]  # same top-1
        overlap = {i for i, _ in p_row} & {i for i, _ in s_row}
        assert len(overlap) >= 9  # overlap@10


# ------------------------------------------------------- registry + service
@pytest.fixture
def fresh_indexes():
    from django_assistant_bot_tpu.rag.index_registry import reset_indexes

    reset_indexes()
    yield
    reset_indexes()


def _seed_questions(n_docs=2, per_doc=12):
    from django_assistant_bot_tpu.ai.providers.echo import HashEmbedder
    from django_assistant_bot_tpu.storage import models

    bot = models.Bot.objects.create(codename="ann-bot")
    wiki = models.WikiDocument.objects.create(bot=bot, title="wiki")
    emb = HashEmbedder(dim=768)
    centers = []
    for d in range(n_docs):
        doc = models.Document.objects.create(
            wiki=wiki, name=f"doc{d}", content=f"content {d}"
        )
        center_text = f"topic-{d}"
        center = np.asarray(asyncio.run(emb.embeddings([center_text]))[0])
        for i in range(per_doc):
            noise = np.random.default_rng(d * 100 + i).normal(size=768) * 0.05
            models.Question.objects.create(
                document=doc,
                text=f"q{d}-{i}",
                order=i,
                embedding=(center + noise).astype(np.float32),
            )
        centers.append(center_text)
    return centers


def test_registry_routes_by_threshold_with_rollback(tmp_db, fresh_indexes):
    from django_assistant_bot_tpu.rag.index_registry import (
        get_index,
        rag_plane_stats,
        reset_indexes,
    )
    from django_assistant_bot_tpu.storage import models

    _seed_questions()
    # corpus below the (default) threshold -> exact index
    assert isinstance(get_index(models.Question), VectorIndex)
    reset_indexes()
    with settings.override(ANN_THRESHOLD=1):
        index = get_index(models.Question)
        assert isinstance(index, ANNIndex)
        st = rag_plane_stats()["indexes"]["Question.embedding"]
        assert st["kind"] == "ivfpq" and st["trained"]
    reset_indexes()
    # DABT_ANN=0 rollback beats the threshold
    with settings.override(ANN=False, ANN_THRESHOLD=1):
        assert isinstance(get_index(models.Question), VectorIndex)


def test_search_service_schema_parity_across_index_types(tmp_db, fresh_indexes):
    """The one shared test through BOTH engines: search_service must return
    identical result schemas (and the same top hit) whether the registry
    routed to VectorIndex or ANNIndex."""
    from django_assistant_bot_tpu.rag import embedding_search_questions, get_embedding
    from django_assistant_bot_tpu.rag.index_registry import get_index, reset_indexes
    from django_assistant_bot_tpu.storage import models

    centers = _seed_questions()
    q_emb = asyncio.run(get_embedding(centers[1]))

    def run_once():
        hits = asyncio.run(embedding_search_questions(q_emb, n=5))
        assert len(hits) == 5
        for h in hits:
            assert isinstance(h, models.Question)
            assert isinstance(h.distance, float) and 0.0 <= h.distance <= 2.0
        assert [h.distance for h in hits] == sorted(h.distance for h in hits)
        return [(h.id, h.text) for h in hits]

    exact_hits = run_once()
    assert isinstance(get_index(models.Question), VectorIndex)
    reset_indexes()
    with settings.override(ANN_THRESHOLD=1):
        ann_hits = run_once()
        assert isinstance(get_index(models.Question), ANNIndex)
    assert exact_hits[0] == ann_hits[0]
    assert {t for _, t in exact_hits} == {t for _, t in ann_hits}
    assert all(t.startswith("q1-") for _, t in ann_hits[:3])
