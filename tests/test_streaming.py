"""End-to-end token streaming: engine iterator, SSE wire, providers, delivery.

Covers the docs/STREAMING.md contracts:

- byte identity: the concatenation of streamed deltas equals the non-streaming
  decode of the same ids (engine iterator AND the SSE path);
- UTF-8 safety: incremental detokenization over random multi-byte (emoji/CJK)
  token splits never emits a replacement character for an incomplete fragment;
- cancellation: abandoning a stream (client disconnect) cancels the request
  and frees its decode slot within one tick, counted in ``tick_stats``;
- provider adapters: EchoProvider word-by-word, the buffered default adapter,
  GPUServiceProvider consuming the SSE wire;
- progressive bot delivery: first-chunk post + throttled edits + final edit,
  exercised with a fake clock.
"""

import asyncio
import json
import random
import time

import pytest

import jax

from django_assistant_bot_tpu.ai.domain import AIResponse
from django_assistant_bot_tpu.ai.providers.base import AIProvider, AIStreamChunk
from django_assistant_bot_tpu.ai.providers.echo import EchoProvider
from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.serving import (
    ByteTokenizer,
    GenerationEngine,
    IncrementalDetokenizer,
    ModelRegistry,
)
from django_assistant_bot_tpu.serving.server import create_app


# ------------------------------------------------------- incremental detok
MULTIBYTE_CORPUS = (
    "hello world! "
    "héllo café naïve "
    "👋🌍🤖🔥💡🧪 "
    "日本語のテキストです "
    "한국어 텍스트 "
    "привет мир "
    "🇺🇦🇯🇵 👩‍👩‍👧‍👦 "  # flags + ZWJ family: 4-byte clusters
)


class _NonByteTokenizer(ByteTokenizer):
    """Forces the general (full re-decode) path of the detokenizer."""

    byte_level = False


@pytest.mark.parametrize("tok_cls", [ByteTokenizer, _NonByteTokenizer])
def test_incremental_detok_property_random_splits(tok_cls):
    """Property: for random multi-byte strings fed ONE TOKEN AT A TIME (the
    worst-case split — every UTF-8 continuation byte lands in its own push),
    the concatenated deltas are byte-identical to the one-shot decode and no
    replacement character is ever fabricated."""
    tok = tok_cls()
    rng = random.Random(7)
    chars = MULTIBYTE_CORPUS
    for _ in range(40):
        s = "".join(rng.choice(chars) for _ in range(rng.randint(0, 30)))
        ids = tok.encode(s)  # includes BOS (renders to nothing)
        detok = IncrementalDetokenizer(tok)
        parts = [detok.push(i) for i in ids]
        parts.append(detok.flush())
        out = "".join(parts)
        assert out == tok.decode(ids) == s
        assert "�" not in out
        # every multi-byte character arrived whole in exactly one delta
        for p in parts:
            assert "�" not in p


def test_incremental_detok_flushes_truncated_tail():
    """A generation cut mid-character (length limit) still matches the
    one-shot decode: the replacement chars appear only at flush, exactly as
    the non-streaming decode would produce them."""
    tok = ByteTokenizer()
    ids = list("né".encode("utf-8"))[:-1]  # drop the é's continuation byte
    detok = IncrementalDetokenizer(tok)
    mid = "".join(detok.push(i) for i in ids)
    assert "�" not in mid  # never mid-stream
    assert mid + detok.flush() == tok.decode(ids)


# ----------------------------------------------------------- engine stream
@pytest.fixture(scope="module")
def stream_engine():
    import dataclasses as _dc

    # a LONG context so the disconnect test's abandoned generation would run
    # for thousands of ticks if the cancel didn't reap it
    cfg = _dc.replace(DecoderConfig.tiny(), max_seq_len=2048)
    params = llama.init(cfg, jax.random.key(0))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=2048
    ).start()
    yield eng
    eng.stop()


def _collect_stream(eng, prompt, **kw):
    async def go():
        parts, chunks, final = [], [], None
        async for c in eng.generate_stream(prompt, **kw):
            chunks.append(c)
            parts.append(c.text)
            if c.done:
                final = c
        return "".join(parts), chunks, final

    return asyncio.run(go())


def test_engine_stream_byte_identical_to_generate(stream_engine):
    """Greedy stream == greedy non-stream for the same request: same token
    ids, and the delta concatenation equals the non-streaming text byte for
    byte (acceptance criterion #1)."""
    eng = stream_engine
    prompt = "hello streaming world"
    ref = eng.submit(
        eng.tokenizer.encode(prompt), max_tokens=12, temperature=0.0
    ).result(timeout=300)
    text, chunks, final = _collect_stream(
        eng, prompt, max_tokens=12, temperature=0.0
    )
    assert final is not None and final.done
    assert final.result.token_ids == ref.token_ids
    assert text == ref.text == final.result.text
    token_chunks = [c for c in chunks if not c.done]
    assert [c.index for c in token_chunks] == list(range(len(token_chunks)))
    assert len(token_chunks) == len(ref.token_ids)
    assert final.finish_reason in ("stop", "length")


def test_engine_stream_disconnect_frees_slot_within_tick(stream_engine):
    """Abandoning the iterator mid-generation cancels the request; the
    per-iteration reap frees the slot almost immediately (one decode tick,
    not the ~2000 remaining tokens) and counts it in tick_stats."""
    eng = stream_engine
    before = eng.cancelled_slots

    async def go():
        agen = eng.generate_stream("x" * 16, max_tokens=2000, temperature=0.8)
        got = 0
        async for _c in agen:
            got += 1
            if got >= 2:
                break  # client gone; generator cleanup cancels the future
        await agen.aclose()
        return got

    assert asyncio.run(go()) >= 2
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if eng.num_active == 0 and eng.cancelled_slots > before:
            break
        time.sleep(0.005)
    assert eng.num_active == 0, "slot not reclaimed after stream abandonment"
    stats = eng.tick_stats()
    assert stats["cancelled_slots"] > before
    assert stats["reclaimed_slots"] >= stats["cancelled_slots"]


def test_engine_stream_latency_stats(stream_engine):
    """TTFT/ITL percentiles accumulate from streamed traffic."""
    eng = stream_engine
    _collect_stream(eng, "stats please", max_tokens=8, temperature=0.0)
    stats = eng.tick_stats()
    assert stats["ttft_n"] >= 1 and stats["ttft_p50_ms"] > 0
    assert stats["itl_n"] >= 1
    assert stats["itl_p95_ms"] >= stats["itl_p50_ms"] >= 0


# ---------------------------------------------------------------- SSE wire
@pytest.fixture(scope="module")
def sse_client():
    from aiohttp.test_utils import TestClient, TestServer

    loop = asyncio.new_event_loop()
    registry = ModelRegistry.from_config(
        {
            "tiny-chat": {
                "kind": "decoder", "tiny": True, "max_slots": 2,
                "max_seq_len": 1024,
            },
        }
    )
    client = TestClient(TestServer(create_app(registry)), loop=loop)
    loop.run_until_complete(client.start_server())
    yield loop, client, registry
    loop.run_until_complete(client.close())
    loop.close()


async def _read_sse(resp, limit=None):
    events = []
    async for raw in resp.content:
        line = raw.decode("utf-8").strip()
        if not line.startswith("data:"):
            continue
        data = line[len("data:"):].strip()
        if data == "[DONE]":
            break
        events.append(json.loads(data))
        if limit is not None and len(events) >= limit:
            break
    return events


def test_sse_dialog_happy_path(sse_client):
    """stream:true responds text/event-stream; delta concatenation equals the
    terminal event's full result (byte identity over the wire), usage rides
    the terminal event, and the non-streaming path is untouched."""
    loop, client, _ = sse_client
    body = {
        "model": "tiny-chat",
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 6,
        "temperature": 0.0,
        "stream": True,
    }

    async def go():
        resp = await client.post("/dialog/", json=body)
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        events = await _read_sse(resp)
        terminal = events[-1]
        assert terminal["done"] is True
        assert terminal["finish_reason"] in ("stop", "length")
        usage = terminal["usage"]
        assert usage["completion_tokens"] <= 6
        assert usage["total_tokens"] == (
            usage["prompt_tokens"] + usage["completion_tokens"]
        )
        deltas = "".join(e["delta"] for e in events if "delta" in e)
        assert deltas == terminal["result"]

        # same request non-streaming (greedy -> identical result text)
        plain = dict(body)
        del plain["stream"]
        resp2 = await client.post("/dialog/", json=plain)
        assert resp2.status == 200
        data = await resp2.json()
        assert data["response"]["result"] == terminal["result"]

    loop.run_until_complete(go())


def test_sse_rejects_json_format(sse_client):
    """Documented choice: stream + json_format is a 422, not buffered SSE."""
    loop, client, _ = sse_client

    async def go():
        resp = await client.post(
            "/dialog/",
            json={
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "hi"}],
                "json_format": True,
                "stream": True,
            },
        )
        assert resp.status == 422
        assert "json_format" in (await resp.json())["detail"]
        # non-bool stream flag is a 422 too, not a silent cast
        resp = await client.post(
            "/dialog/",
            json={
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "hi"}],
                "stream": "yes",
            },
        )
        assert resp.status == 422

    loop.run_until_complete(go())


def test_sse_unknown_model_is_400(sse_client):
    loop, client, _ = sse_client

    async def go():
        resp = await client.post(
            "/dialog/",
            json={"model": "nope", "messages": [], "stream": True},
        )
        assert resp.status == 400

    loop.run_until_complete(go())


def test_sse_disconnect_frees_slot(sse_client):
    """Closing the HTTP connection mid-stream cancels the engine request: the
    slot frees within ~a tick (not after the remaining ~900 tokens) and the
    disconnect lands in the cancelled counter /healthz exposes."""
    loop, client, registry = sse_client
    eng = registry.get_generator("tiny-chat")
    before = eng.cancelled_slots

    async def go():
        resp = await client.post(
            "/dialog/",
            json={
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "stream then vanish"}],
                "max_tokens": 900,
                "stream": True,
            },
        )
        assert resp.status == 200
        got = await _read_sse(resp, limit=2)
        assert got  # generation is live
        resp.close()  # client disconnects mid-stream
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if eng.num_active == 0 and eng.cancelled_slots > before:
                break
            await asyncio.sleep(0.01)
        assert eng.num_active == 0
        assert eng.cancelled_slots > before

        # the counter surfaces on /healthz
        health = await (await client.get("/healthz")).json()
        g = health["generators"]["tiny-chat"]
        assert g["stream"]["cancelled_slots"] >= eng.cancelled_slots - 1
        assert "ttft_p50_ms" in g["stream"]

    loop.run_until_complete(go())


# ------------------------------------------------------- provider adapters
def test_echo_provider_streams_word_by_word():
    prov = EchoProvider(script=["alpha  beta\ngamma 🤖 done"])

    async def go():
        return [
            c
            async for c in prov.stream_response(
                [{"role": "user", "content": "q"}]
            )
        ]

    chunks = asyncio.run(go())
    assert chunks[-1].done and chunks[-1].response is not None
    deltas = [c.delta for c in chunks if not c.done]
    assert len(deltas) >= 4  # genuinely word-by-word, not one blob
    assert "".join(deltas) == "alpha  beta\ngamma 🤖 done"
    assert chunks[-1].response.result == "alpha  beta\ngamma 🤖 done"


def test_default_stream_adapter_buffers_whole_response():
    """A provider that never heard of streaming still streams: the base
    adapter yields its whole get_response result once, then the terminal."""

    class Plain(AIProvider):
        calls_attempts = []

        @property
        def context_size(self):
            return 100

        def calculate_tokens(self, text):
            return 1

        async def get_response(self, messages, max_tokens=1024, json_format=False):
            if json_format:
                return AIResponse(result={"k": "v"}, usage=None)
            return AIResponse(result="whole thing", usage=None)

    async def go(json_format):
        return [
            c
            async for c in Plain().stream_response(
                [{"role": "user", "content": "q"}], json_format=json_format
            )
        ]

    chunks = asyncio.run(go(False))
    assert [c.delta for c in chunks if not c.done] == ["whole thing"]
    assert chunks[-1].done and chunks[-1].response.result == "whole thing"
    # dict results stream as their JSON text; the terminal keeps the dict
    jchunks = asyncio.run(go(True))
    assert json.loads(jchunks[0].delta) == {"k": "v"}
    assert jchunks[-1].response.result == {"k": "v"}


def test_gpu_service_provider_consumes_sse(sse_client):
    """GPUServiceProvider speaks the SSE wire format end-to-end against the
    real server: deltas arrive progressively and the terminal response carries
    the authoritative text + usage."""
    from django_assistant_bot_tpu.ai.providers.http_service import GPUServiceProvider

    loop, client, _ = sse_client
    base = str(client.server.make_url("")).rstrip("/")
    prov = GPUServiceProvider(base, "tiny-chat")

    async def go():
        return [
            c
            async for c in prov.stream_response(
                [{"role": "user", "content": "over the wire"}], max_tokens=5
            )
        ]

    chunks = loop.run_until_complete(go())
    assert chunks[-1].done
    resp = chunks[-1].response
    assert "".join(c.delta for c in chunks if not c.done) == resp.result
    assert resp.usage["completion_tokens"] <= 5


@pytest.mark.slow
def test_tpu_provider_stream_response():
    """tpu: provider streams in-process from the engine; json_format buffers
    through the base adapter (whole validated document, single delta)."""
    from django_assistant_bot_tpu.ai.providers.tpu import (
        TPUProvider,
        reset_shared_registry,
    )

    reset_shared_registry()
    try:
        prov = TPUProvider("stream-tiny")

        async def go():
            return [
                c
                async for c in prov.stream_response(
                    [{"role": "user", "content": "hello"}], max_tokens=6
                )
            ]

        chunks = asyncio.run(go())
        assert chunks[-1].done
        resp = chunks[-1].response
        assert "".join(c.delta for c in chunks if not c.done) == resp.result
        assert resp.usage["completion_tokens"] >= 1
    finally:
        reset_shared_registry()


# ---------------------------------------------------- progressive delivery
class FakePlatform:
    supports_partial = True

    def __init__(self, fail_post=False):
        self.posted = []
        self.edits = []
        self.finals = []
        self.fail_post = fail_post

    async def post_partial(self, chat_id, text):
        if self.fail_post:
            return None
        self.posted.append(text)
        return 42

    async def edit_partial(self, chat_id, message_id, text):
        assert message_id == 42
        self.edits.append(text)
        return True

    async def finalize_partial(self, chat_id, message_id, answer):
        assert message_id == 42
        self.finals.append(answer.text)
        return True


def _mk_stream(pieces, clk):
    """pieces: list of (time, delta) then a terminal AIResponse."""

    async def gen():
        full = []
        for t, delta in pieces:
            clk["t"] = t
            full.append(delta)
            yield AIStreamChunk(delta=delta)
        yield AIStreamChunk(
            done=True, response=AIResponse(result="".join(full), usage=None)
        )

    return gen()


def _builder(resp):
    from django_assistant_bot_tpu.bot.domain import SingleAnswer

    return SingleAnswer(text=resp.result, raw_text=resp.result)


def test_deliver_streamed_answer_throttles_edits():
    """Fake-clock cadence: first chunk posts immediately, edits inside the
    1 s window are coalesced (skipped, next edit carries the accumulation),
    and the final edit ALWAYS goes out even right after a throttled edit."""
    from django_assistant_bot_tpu.bot.services.dialog_service import (
        deliver_streamed_answer,
    )

    clk = {"t": 0.0}
    pieces = [
        (0.0, "Hello strea"),   # >= min_first_chars -> first post
        (0.3, "ming wor"),      # 0.3s since post -> throttled (no edit)
        (0.6, "ld, more "),     # still inside the window -> throttled
        (1.2, "text here "),    # window passed -> ONE edit with everything
        (1.4, "and the end."),  # throttled again
    ]
    p = FakePlatform()
    answer = asyncio.run(
        deliver_streamed_answer(
            p,
            "chat1",
            _mk_stream(pieces, clk),
            answer_builder=_builder,
            min_edit_interval_s=1.0,
            clock=lambda: clk["t"],
        )
    )
    full = "".join(d for _, d in pieces)
    assert p.posted == ["Hello strea"]
    # exactly one throttled edit, carrying the coalesced accumulation
    assert p.edits == ["Hello streaming world, more text here "]
    # final edit always sent, with the complete text
    assert p.finals == [full]
    assert answer.already_delivered is True
    assert answer.text == full


def test_deliver_streamed_answer_falls_back_without_edit_support():
    """No supports_partial (every non-Telegram platform today): nothing posts
    during the stream; the whole answer returns UNdelivered for the task
    plane's normal post_answer path."""
    from django_assistant_bot_tpu.bot.services.dialog_service import (
        deliver_streamed_answer,
    )

    class NoEdit:
        supports_partial = False

    clk = {"t": 0.0}
    answer = asyncio.run(
        deliver_streamed_answer(
            NoEdit(),
            "chat1",
            _mk_stream([(0.0, "hello "), (2.0, "world")], clk),
            answer_builder=_builder,
            min_edit_interval_s=1.0,
            clock=lambda: clk["t"],
        )
    )
    assert answer.text == "hello world"
    assert answer.already_delivered is False


def test_deliver_streamed_answer_failed_first_post_degrades():
    """post_partial returning None (send failure) degrades to whole-message
    delivery instead of losing the turn."""
    from django_assistant_bot_tpu.bot.services.dialog_service import (
        deliver_streamed_answer,
    )

    clk = {"t": 0.0}
    p = FakePlatform(fail_post=True)
    answer = asyncio.run(
        deliver_streamed_answer(
            p,
            "chat1",
            _mk_stream([(0.0, "long enough first"), (2.0, " tail")], clk),
            answer_builder=_builder,
            min_edit_interval_s=1.0,
            clock=lambda: clk["t"],
        )
    )
    assert p.edits == [] and p.finals == []
    assert answer.already_delivered is False
    assert answer.text == "long enough first tail"


def test_displayable_partial_hides_thinking_and_caps():
    """Partials never leak an open <think> block (internal reasoning) and
    stay under Telegram's message cap; a closed block strips exactly like the
    final answer's tag extraction."""
    from django_assistant_bot_tpu.bot.services.dialog_service import (
        PARTIAL_TEXT_CAP,
        _displayable_partial,
    )

    assert _displayable_partial("Hi <think>secret plan") == "Hi "
    assert _displayable_partial("<think>only reasoning so far") == ""
    assert _displayable_partial("<think>done</think>The answer") == "The answer"
    capped = _displayable_partial("x" * (PARTIAL_TEXT_CAP + 500))
    assert len(capped) == PARTIAL_TEXT_CAP + 1 and capped.endswith("…")


def test_deliver_streamed_answer_survives_raising_edits():
    """A platform edit raising (rate limit, network blip) must not abort the
    stream — the caller's fallback would re-generate and double-post.  The
    final answer still arrives, finalized if finalize works."""
    from django_assistant_bot_tpu.bot.services.dialog_service import (
        deliver_streamed_answer,
    )

    class FlakyPlatform(FakePlatform):
        async def edit_partial(self, chat_id, message_id, text):
            raise RuntimeError("telegram 429")

    clk = {"t": 0.0}
    p = FlakyPlatform()
    answer = asyncio.run(
        deliver_streamed_answer(
            p,
            "chat1",
            _mk_stream([(0.0, "first chunk long"), (2.0, " more"), (4.0, " end")], clk),
            answer_builder=_builder,
            min_edit_interval_s=1.0,
            clock=lambda: clk["t"],
        )
    )
    assert p.posted == ["first chunk long"]
    assert answer.text == "first chunk long more end"
    assert answer.already_delivered is True  # finalize still landed


def test_telegram_finalize_rejects_overlong_text():
    """Final text past Telegram's 4096-char cap can't be edited in: finalize
    returns False so the task plane posts the full answer whole."""
    from django_assistant_bot_tpu.bot.domain import SingleAnswer
    from django_assistant_bot_tpu.bot.platforms.telegram.platform import (
        TelegramBotPlatform,
    )

    api = _StubTelegramAPI()
    platform = TelegramBotPlatform("token", api=api)
    ok = asyncio.run(
        platform.finalize_partial("c", 7, SingleAnswer(text="y" * 5000))
    )
    assert ok is False and api.edited == []


class _StubTelegramAPI:
    def __init__(self):
        self.sent = []
        self.edited = []
        self.fail_parse_once = False

    async def send_message(self, chat_id, text, **kw):
        self.sent.append((text, kw.get("parse_mode")))
        return {"message_id": 7}

    async def edit_message_text(self, chat_id, message_id, text, *, parse_mode=None, reply_markup=None):
        from django_assistant_bot_tpu.bot.platforms.telegram.api import (
            TelegramBadRequest,
        )

        if self.fail_parse_once and parse_mode == "MarkdownV2":
            self.fail_parse_once = False
            raise TelegramBadRequest(400, "Bad Request: can't parse entities")
        self.edited.append((message_id, text, parse_mode))
        return {"message_id": message_id}


def test_telegram_partial_delivery_methods():
    """post_partial/edit_partial/finalize_partial against a stub API: plain
    partials, MarkdownV2 final edit with plain fallback, not-modified
    tolerated."""
    from django_assistant_bot_tpu.bot.domain import SingleAnswer
    from django_assistant_bot_tpu.bot.platforms.telegram.platform import (
        TelegramBotPlatform,
    )

    api = _StubTelegramAPI()
    platform = TelegramBotPlatform("token", api=api)
    assert platform.supports_partial

    async def go():
        mid = await platform.post_partial("c", "partial text")
        assert mid == 7
        assert api.sent == [("partial text", None)]  # plain, no parse mode
        assert await platform.edit_partial("c", mid, "partial text more")
        # final edit: MarkdownV2 parse failure falls back to plain text
        api.fail_parse_once = True
        ok = await platform.finalize_partial(
            "c", mid, SingleAnswer(text="final *text*")
        )
        assert ok

    asyncio.run(go())
    assert api.edited[0] == (7, "partial text more", None)
    assert api.edited[-1] == (7, "final *text*", None)  # plain fallback won


# ----------------------------------------------------- media secret (race)
def test_media_secret_loser_reads_winner(tmp_path, monkeypatch):
    """Two concurrent first-writers must converge on ONE secret: the loser of
    the exclusive create reads the winner's bytes instead of installing its
    own (the old replace pattern let both install different secrets)."""
    import os

    from django_assistant_bot_tpu.bot.services import dialog_service as ds

    root = tmp_path / "media"
    root.mkdir()
    path = str(root) + ".secret"
    winner = b"w" * 32
    real_link = os.link

    def racing_link(src, dst):
        if dst == path:
            # another process wins the race just before our link lands
            with open(path, "wb") as f:
                f.write(winner)
            raise FileExistsError(dst)
        return real_link(src, dst)

    monkeypatch.setattr(os, "link", racing_link)
    got = ds._media_secret(str(root))
    assert got == winner  # the loser adopted the winner's secret
    # no stale tmp files left behind
    assert not [p for p in root.parent.iterdir() if ".tmp" in p.name]


def test_media_secret_create_and_reuse(tmp_path):
    from django_assistant_bot_tpu.bot.services import dialog_service as ds

    root = tmp_path / "media"
    root.mkdir()
    s1 = ds._media_secret(str(root))
    s2 = ds._media_secret(str(root))
    assert s1 == s2 and len(s1) == 32
    import os

    assert (os.stat(str(root) + ".secret").st_mode & 0o777) == 0o600
