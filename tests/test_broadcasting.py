"""Broadcasting plane: status machine, targeting, batch fan-out, stats, finalize."""

import datetime as dt

import pytest

from django_assistant_bot_tpu.broadcasting import BroadcastCampaign
from django_assistant_bot_tpu.broadcasting.services import (
    record_batch_results,
    resolve_target_chat_ids,
    schedule_campaign_sending,
)
from django_assistant_bot_tpu.broadcasting.tasks import (
    check_scheduled_broadcasts,
)
from django_assistant_bot_tpu.bot.domain import BotPlatform, UserUnavailableError
from django_assistant_bot_tpu.conf import settings
from django_assistant_bot_tpu.storage import models
from django_assistant_bot_tpu.tasks import Worker


class FanoutPlatform(BotPlatform):
    def __init__(self, unavailable=()):
        self.sent = []
        self.unavailable = set(unavailable)

    @property
    def codename(self):
        return "telegram"

    async def get_update(self, request):
        raise NotImplementedError

    async def post_answer(self, chat_id, answer):
        if chat_id in self.unavailable:
            raise UserUnavailableError(chat_id)
        self.sent.append((chat_id, answer.text))

    async def action_typing(self, chat_id):
        pass


@pytest.fixture()
def campaign(tmp_db):
    bot = models.Bot.objects.create(codename="bc", telegram_token="t")
    for i in range(5):
        user = models.BotUser.objects.create(user_id=f"u{i}", platform="telegram")
        models.Instance.objects.create(bot=bot, user=user, is_unavailable=(i == 4))
    return BroadcastCampaign.objects.create(bot=bot, message_text="hello all")


def test_status_machine_schedule_sync(campaign):
    assert campaign.status == BroadcastCampaign.DRAFT
    campaign.scheduled_at = dt.datetime.now(dt.timezone.utc)
    campaign.save()
    assert campaign.status == BroadcastCampaign.SCHEDULED
    campaign.scheduled_at = None
    campaign.save()
    assert campaign.status == BroadcastCampaign.DRAFT


def test_resolve_targets_skips_unavailable(campaign):
    ids = resolve_target_chat_ids(campaign)
    assert sorted(ids) == ["u0", "u1", "u2", "u3"]  # u4 unavailable


def test_full_campaign_flow_with_partial_failure(campaign, monkeypatch):
    platform = FanoutPlatform(unavailable={"u2"})
    import django_assistant_bot_tpu.broadcasting.tasks as btasks

    monkeypatch.setattr(btasks, "get_bot_platform", lambda *a, **k: platform)

    schedule_campaign_sending(campaign)
    with settings.override(TASK_ALWAYS_EAGER=True):
        n = check_scheduled_broadcasts.apply()
    assert n == 1
    campaign.refresh()
    assert campaign.status == BroadcastCampaign.PARTIAL_FAILURE
    assert campaign.total_recipients == 4
    assert campaign.successful_sents == 3
    assert campaign.failed_sents == 1
    assert len(platform.sent) == 3
    # the failed user got marked unavailable
    user = models.BotUser.objects.get(user_id="u2", platform="telegram")
    inst = models.Instance.objects.get(bot=campaign.bot_id, user=user.id)
    assert inst.is_unavailable


def test_campaign_flow_through_worker(campaign, monkeypatch):
    platform = FanoutPlatform()
    import django_assistant_bot_tpu.broadcasting.tasks as btasks

    monkeypatch.setattr(btasks, "get_bot_platform", lambda *a, **k: platform)
    schedule_campaign_sending(campaign)
    check_scheduled_broadcasts.delay()
    w = Worker(["broadcasting"])
    for _ in range(6):
        w.run_until_idle()
    campaign.refresh()
    assert campaign.status == BroadcastCampaign.COMPLETED
    assert campaign.successful_sents == 4
    assert len(platform.sent) == 4


def test_record_batch_results_gates_on_sending(campaign):
    campaign.status = BroadcastCampaign.SENDING
    campaign.total_recipients = 10
    campaign.save()
    assert record_batch_results(campaign.id, 4, 0) is False  # not complete yet
    assert record_batch_results(campaign.id, 4, 2) is True  # now complete
    campaign.refresh()
    assert campaign.successful_sents == 8 and campaign.failed_sents == 2
    # wrong state ignored
    campaign.status = BroadcastCampaign.COMPLETED
    campaign.save()
    assert record_batch_results(campaign.id, 1, 0) is False
