"""Length-aware decode attention: the bucketed KV read must be a pure
optimization — identical outputs to the full-cache read across ragged per-slot
lengths, chunk-boundary transitions mid-decode, sliding windows, and the
fp8-KV per-chunk dequant path.  All CPU (f32 mesh), so tier-1 gates the
tentpole without hardware.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.ops.attention import (
    chunked_gqa_decode_attention,
    gqa_dot_product_attention,
)
from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine


def _random_cache(cfg, B, S, lengths, seed=0, dtype=None):
    rng = np.random.default_rng(seed)
    KH, D, L = cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    k = rng.normal(size=(L, B, KH, S, D)).astype(np.float32)
    v = rng.normal(size=(L, B, KH, S, D)).astype(np.float32)
    kd = jnp.asarray(k).astype(dtype) if dtype else jnp.asarray(k)
    vd = jnp.asarray(v).astype(dtype) if dtype else jnp.asarray(v)
    return llama.KVCache(k=kd, v=vd, lengths=jnp.asarray(lengths, jnp.int32))


def test_op_matches_masked_gqa_ragged():
    """Op level: chunked online-softmax == masked full softmax for ragged
    positions, including positions exactly on / either side of a boundary."""
    rng = np.random.default_rng(1)
    B, H, KH, S, D, chunk = 5, 8, 2, 128, 16, 32
    q = jnp.asarray(rng.normal(size=(B, H, 1, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, KH, S, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, KH, S, D)).astype(np.float32))
    positions = jnp.asarray([0, 31, 32, 33, 127], jnp.int32)

    kpos = jnp.arange(S)[None, :]
    mask = (kpos <= positions[:, None])[:, None, None, :]  # [B,1,1,S]
    full = gqa_dot_product_attention(q, k, v, mask=mask)
    chunked = chunked_gqa_decode_attention(q, k, v, positions, chunk=chunk)
    np.testing.assert_allclose(np.asarray(full), np.asarray(chunked), atol=2e-6)


def test_op_skips_tail_chunks():
    """Garbage (NaN) planted beyond the bucketed window must never be read —
    the proof the tail chunks are actually skipped, not just masked."""
    rng = np.random.default_rng(2)
    B, H, KH, S, D, chunk = 2, 4, 2, 128, 8, 32
    q = jnp.asarray(rng.normal(size=(B, H, 1, D)).astype(np.float32))
    k = rng.normal(size=(B, KH, S, D)).astype(np.float32)
    v = rng.normal(size=(B, KH, S, D)).astype(np.float32)
    positions = jnp.asarray([10, 40], jnp.int32)  # window = chunks [0, 2)
    k_nan, v_nan = k.copy(), v.copy()
    k_nan[:, :, 64:] = np.nan  # chunks [2, 4) — beyond every valid position
    v_nan[:, :, 64:] = np.nan
    clean = chunked_gqa_decode_attention(
        q, jnp.asarray(k), jnp.asarray(v), positions, chunk=chunk
    )
    poisoned = chunked_gqa_decode_attention(
        q, jnp.asarray(k_nan), jnp.asarray(v_nan), positions, chunk=chunk
    )
    assert not np.any(np.isnan(np.asarray(poisoned)))
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(poisoned))


def test_decode_step_bucketed_equivalence_ragged():
    """decode_step with kv_chunk == full-cache decode_step across a ragged
    batch whose lengths straddle chunk boundaries."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    B, S = 4, 256
    lengths = np.asarray([3, 63, 64, 200], np.int32)
    cache_a = _random_cache(cfg, B, S, lengths)
    cache_b = _random_cache(cfg, B, S, lengths)
    toks = jnp.asarray([7, 11, 13, 17], jnp.int32)
    lg_full, ca = llama.decode_step(params, cfg, toks, cache_a)
    lg_chunk, cb = llama.decode_step(params, cfg, toks, cache_b, kv_chunk=64)
    np.testing.assert_allclose(
        np.asarray(lg_full), np.asarray(lg_chunk), atol=1e-4, rtol=1e-4
    )
    np.testing.assert_array_equal(np.asarray(ca.lengths), np.asarray(cb.lengths))


def test_decode_step_boundary_transition_mid_decode():
    """Greedy decode across a chunk boundary: the bucketed path must track the
    full path token-for-token as the read window grows by a chunk mid-run."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(1))
    B, S, chunk = 2, 256, 64
    lengths = np.asarray([60, 61], np.int32)  # crosses 64 a few steps in
    cache_a = _random_cache(cfg, B, S, lengths, seed=3)
    cache_b = _random_cache(cfg, B, S, lengths, seed=3)
    ta = tb = jnp.asarray([5, 9], jnp.int32)
    for step in range(8):
        la, cache_a = llama.decode_step(params, cfg, ta, cache_a)
        lb, cache_b = llama.decode_step(params, cfg, tb, cache_b, kv_chunk=chunk)
        ta = jnp.argmax(la, -1).astype(jnp.int32)
        tb = jnp.argmax(lb, -1).astype(jnp.int32)
        assert np.array_equal(np.asarray(ta), np.asarray(tb)), f"diverged at {step}"
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), atol=1e-4, rtol=1e-4
        )


def test_decode_step_fp8_kv_per_chunk_dequant():
    """fp8 slot cache: the chunked path's per-chunk upcast must equal the full
    read's whole-cache upcast (same values, different dequant placement)."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(2))
    B, S = 3, 128
    lengths = np.asarray([5, 64, 100], np.int32)
    fp8 = jnp.float8_e4m3fn
    cache_a = _random_cache(cfg, B, S, lengths, seed=4, dtype=fp8)
    cache_b = _random_cache(cfg, B, S, lengths, seed=4, dtype=fp8)
    toks = jnp.asarray([3, 4, 5], jnp.int32)
    lg_full, ca = llama.decode_step(params, cfg, toks, cache_a)
    lg_chunk, cb = llama.decode_step(params, cfg, toks, cache_b, kv_chunk=32)
    assert ca.k.dtype == fp8 and cb.k.dtype == fp8
    np.testing.assert_allclose(
        np.asarray(lg_full), np.asarray(lg_chunk), atol=1e-4, rtol=1e-4
    )


def test_decode_step_windowed_chunked_equivalence():
    """Sliding-window layers through the chunked path: band masking inside the
    window chunks, leading chunks below the band skipped."""
    import dataclasses

    cfg = dataclasses.replace(
        DecoderConfig.tiny(), sliding_window=48, window_layer_start=1
    )
    params = llama.init(cfg, jax.random.key(3))
    B, S = 3, 256
    lengths = np.asarray([10, 120, 200], np.int32)
    cache_a = _random_cache(cfg, B, S, lengths, seed=5)
    cache_b = _random_cache(cfg, B, S, lengths, seed=5)
    toks = jnp.asarray([2, 3, 4], jnp.int32)
    lg_full, _ = llama.decode_step(params, cfg, toks, cache_a)
    lg_chunk, _ = llama.decode_step(params, cfg, toks, cache_b, kv_chunk=64)
    np.testing.assert_allclose(
        np.asarray(lg_full), np.asarray(lg_chunk), atol=1e-4, rtol=1e-4
    )


def test_engine_bucketed_greedy_matches_forward_and_reports_frac():
    """End-to-end: an engine with the bucketed read produces the same greedy
    tokens as the repeated full forward, and tick_stats reports
    kv_read_frac < 1 for a short-context batch (the acceptance criterion)."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(4))
    tok = ByteTokenizer()
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=256, decode_kv_chunk=64,
        prefix_cache_size=0,
    ).start()
    try:
        prompt = tok.encode("bucketed decode")
        n_new = 5
        seq = np.asarray([prompt], np.int32)
        expected = []
        for _ in range(n_new):
            logits = llama.forward(params, cfg, jnp.asarray(seq))
            nxt = int(jnp.argmax(logits[0, -1]))
            expected.append(nxt)
            seq = np.concatenate([seq, [[nxt]]], axis=1)
        result = eng.submit(prompt, max_tokens=n_new, temperature=0.0).result(
            timeout=120
        )
        assert result.token_ids == expected
        stats = eng.tick_stats()
        assert stats["ticks"] >= 1
        # prompt + 5 tokens ≈ 20 positions of a 256-slot cache in 64-wide
        # chunks -> 1 of 4 chunks read
        assert 0 < stats["kv_read_frac"] < 1
    finally:
        eng.stop()


def test_engine_kv_chunk_validation_and_auto():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(5))
    tok = ByteTokenizer()
    # auto at 256 ctx -> 128 (largest of 512/256/128 leaving >= 2 chunks)
    eng = GenerationEngine(cfg, params, tok, max_slots=1, max_seq_len=256)
    assert eng.decode_kv_chunk == 128
    # disabled -> full read, frac pinned at 1.0
    eng = GenerationEngine(
        cfg, params, tok, max_slots=1, max_seq_len=256, decode_kv_chunk=None
    )
    assert eng.decode_kv_chunk is None
    assert eng.tick_stats()["kv_read_frac"] == 1.0
    with pytest.raises(ValueError, match="decode_kv_chunk"):
        GenerationEngine(
            cfg, params, tok, max_slots=1, max_seq_len=256, decode_kv_chunk=100
        )
    with pytest.raises(ValueError, match="decode_kv_chunk"):
        GenerationEngine(
            cfg, params, tok, max_slots=1, max_seq_len=256, decode_kv_chunk=256
        )


def test_probe_decode_fill_len_leaves_engine_serviceable():
    """A fill-pinned probe (the representative-probe mode the bench uses) must
    reset lengths and leave the engine able to serve real traffic."""
    import asyncio

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(6))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=128,
        decode_kv_chunk=64, prefix_cache_size=0,
    ).start()
    try:
        step_s = eng.probe_decode(iters=2, fill_len=100)
        assert step_s > 0
        assert np.asarray(eng._cache.lengths).max() == 0  # reset after probe
        r = asyncio.run(
            eng.generate([{"role": "user", "content": "hi"}], max_tokens=3,
                         temperature=0.0)
        )
        assert len(r.token_ids) == 3
    finally:
        eng.stop()
