"""HTTP API plane: webhook enqueue, REST CRUD, synchronous message serve, auth.

Mirrors reference tests/bot_tests/test_api.py: the full view -> lock -> dialog
service -> persistence path runs real; the AI is cut at get_answer_to_messages.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from django_assistant_bot_tpu.api import create_api_app
from django_assistant_bot_tpu.bot.assistant_bot import AssistantBot
from django_assistant_bot_tpu.bot.domain import SingleAnswer
from django_assistant_bot_tpu.conf import settings
from django_assistant_bot_tpu.storage import models


def with_client(fn):
    """Run an async test body with a live aiohttp test client."""

    async def runner(*args, **kwargs):
        app = create_api_app()
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await fn(client, *args, **kwargs)
        finally:
            await client.close()

    return lambda *a, **k: asyncio.run(runner(*a, **k))


@pytest.fixture()
def seeded(tmp_db, monkeypatch):
    bot = models.Bot.objects.create(codename="api-bot", telegram_token="123:abc")
    user = models.BotUser.objects.create(user_id="u9", platform="console")
    instance = models.Instance.objects.create(bot=bot, user=user)
    dialog = models.Dialog.objects.create(instance=instance)

    async def fake_answer(self, messages, debug_info, do_interrupt):
        return SingleAnswer(text="api answer", usage=[{"model": "test"}])

    monkeypatch.setattr(AssistantBot, "get_answer_to_messages", fake_answer)
    return bot, instance, dialog


def test_bots_endpoints(seeded):
    @with_client
    async def body(client):
        resp = await client.get("/api/v1/bots/")
        data = await resp.json()
        assert resp.status == 200
        assert data["results"][0]["codename"] == "api-bot"
        resp = await client.get("/api/v1/bots/api-bot/")
        assert resp.status == 200
        resp = await client.get("/api/v1/bots/nope/")
        assert resp.status == 404

    body()


def test_dialog_crud(seeded):
    bot, instance, dialog = seeded

    @with_client
    async def body(client):
        resp = await client.post("/api/v1/dialogs/", json={"instance_id": instance.id})
        assert resp.status == 201
        new_id = (await resp.json())["id"]
        resp = await client.get(f"/api/v1/dialogs/{new_id}/")
        assert resp.status == 200
        resp = await client.get("/api/v1/dialogs/")
        assert len((await resp.json())["results"]) == 2
        resp = await client.delete(f"/api/v1/dialogs/{new_id}/")
        assert resp.status == 204
        assert models.Dialog.objects.get_or_none(id=new_id) is None

    body()


def test_message_create_runs_bot_synchronously(seeded):
    bot, instance, dialog = seeded

    @with_client
    async def body(client):
        resp = await client.post(
            f"/api/v1/dialogs/{dialog.id}/messages/", json={"text": "hello api"}
        )
        assert resp.status == 201
        data = await resp.json()
        assert data["message"]["text"] == "hello api"
        assert data["answers"][0]["text"] == "api answer"
        # both user message and assistant answer persisted
        resp = await client.get(f"/api/v1/dialogs/{dialog.id}/messages/")
        texts = [m["text"] for m in (await resp.json())["results"]]
        assert "hello api" in texts and "api answer" in texts

    body()


def test_wiki_endpoints(seeded):
    @with_client
    async def body(client):
        resp = await client.post(
            "/api/v1/wiki/", json={"bot": "api-bot", "title": "Root", "content": "c"}
        )
        assert resp.status == 201
        root_id = (await resp.json())["id"]
        resp = await client.post(
            "/api/v1/wiki/bulk/",
            json=[
                {"bot": "api-bot", "parent_id": root_id, "title": "A"},
                {"bot": "api-bot", "parent_id": root_id, "title": "B"},
            ],
        )
        assert resp.status == 201
        assert len((await resp.json())["created"]) == 2
        resp = await client.get("/api/v1/wiki/?bot=api-bot")
        data = await resp.json()
        assert data["count"] == 3
        child = [w for w in data["results"] if w["title"] == "A"][0]
        assert child["path"] == "Root / A"

    body()


def test_webhook_enqueues_answer_task(seeded):
    from django_assistant_bot_tpu.tasks import TaskRecord

    @with_client
    async def body(client):
        payload = {
            "message": {
                "message_id": 3,
                "chat": {"id": 555},
                "text": "webhook hi",
                "from": {"id": 555, "username": "web"},
            }
        }
        resp = await client.post("/telegram/api-bot/", json=payload)
        assert resp.status == 200
        tasks = TaskRecord.objects.all().all()
        assert any("answer_task" in t.name for t in tasks)
        # user message persisted before the task runs
        assert models.Message.objects.filter(message_id=3).count() == 1

    body()


def test_auth_token_enforced(seeded):
    @with_client
    async def body(client):
        with settings.override(API_AUTH_TOKEN="sekret"):
            resp = await client.get("/api/v1/bots/")
            assert resp.status == 401
            resp = await client.get(
                "/api/v1/bots/", headers={"Authorization": "Token sekret"}
            )
            assert resp.status == 200

    body()


def test_admin_pages_render(seeded):
    bot, instance, dialog = seeded
    from django_assistant_bot_tpu.bot.services.dialog_service import (
        create_bot_message,
        create_user_message,
    )
    from django_assistant_bot_tpu.broadcasting.models import BroadcastCampaign

    create_user_message(dialog, 1, "hi")
    create_bot_message(
        dialog,
        SingleAnswer(
            text="yo", usage=[{"model": "test", "prompt_tokens": 3, "completion_tokens": 5}]
        ),
    )
    wiki = models.WikiDocument.objects.create(bot=bot, title="W")
    campaign = BroadcastCampaign.objects.create(bot=bot, message_text="news")

    @with_client
    async def body(client):
        for path in (
            "/admin/",
            "/admin/bots",
            "/admin/instances",
            "/admin/dialogs",
            f"/admin/dialogs/{dialog.id}",
            "/admin/wiki",
            "/admin/campaigns",
            "/admin/tasks",
        ):
            resp = await client.get(path)
            assert resp.status == 200, path
            text = await resp.text()
            assert "<table>" in text, path
        # POST without the CSRF token is rejected
        resp = await client.post(f"/admin/wiki/{wiki.id}/process", allow_redirects=False)
        assert resp.status == 403
        # extract the per-process CSRF token from a rendered form
        import re

        page = await (await client.get("/admin/wiki")).text()
        csrf = re.search(r"name='csrf' value='([0-9a-f]+)'", page).group(1)
        # process action enqueues ingestion
        resp = await client.post(
            f"/admin/wiki/{wiki.id}/process", data={"csrf": csrf}, allow_redirects=False
        )
        assert resp.status == 302
        from django_assistant_bot_tpu.tasks.queue import TaskRecord

        assert any(
            "wiki_processing_task" in t.name for t in TaskRecord.objects.all()
        )
        # schedule action flips campaign status
        resp = await client.post(
            f"/admin/campaigns/{campaign.id}/schedule",
            data={"csrf": csrf},
            allow_redirects=False,
        )
        assert resp.status == 302
        campaign.refresh()
        assert campaign.status == BroadcastCampaign.SCHEDULED

    body()


def test_openapi_docs(seeded):
    @with_client
    async def body(client):
        resp = await client.get("/api/openapi.json")
        assert resp.status == 200
        spec = await resp.json()
        assert spec["openapi"].startswith("3.")
        # every REST route registered on the app appears in the spec
        for method, path in [
            ("post", "/telegram/{codename}/"),
            ("get", "/api/v1/bots/"),
            ("post", "/api/v1/dialogs/{id}/messages/"),
            ("post", "/api/v1/wiki/bulk/"),
        ]:
            assert method in spec["paths"][path], (method, path)
        assert "/admin/" not in spec["paths"]
        # docs page renders and is public even with an API token configured
        with settings.override(API_AUTH_TOKEN="sekret"):
            resp = await client.get("/api/docs")
            assert resp.status == 200
            text = await resp.text()
            assert "/api/v1/dialogs/" in text and "openapi.json" in text
            resp = await client.get("/api/openapi.json")
            assert resp.status == 200

    body()


def test_admin_basic_auth_enforced(seeded):
    import base64

    @with_client
    async def body(client):
        with settings.override(ADMIN_BASIC_AUTH="boss:hunter2"):
            resp = await client.get("/admin/")
            assert resp.status == 401
            assert resp.headers.get("WWW-Authenticate", "").startswith("Basic")
            cred = base64.b64encode(b"boss:hunter2").decode()
            resp = await client.get(
                "/admin/", headers={"Authorization": f"Basic {cred}"}
            )
            assert resp.status == 200
        # API token alone also locks the admin (admin:<token> fallback)
        with settings.override(API_AUTH_TOKEN="sekret", ADMIN_BASIC_AUTH=None):
            resp = await client.get("/admin/")
            assert resp.status == 401
            cred = base64.b64encode(b"admin:sekret").decode()
            resp = await client.get(
                "/admin/", headers={"Authorization": f"Basic {cred}"}
            )
            assert resp.status == 200

    body()


def test_webhook_secret_token_enforced(seeded):
    @with_client
    async def body(client):
        payload = {
            "message": {
                "message_id": 7,
                "chat": {"id": 556},
                "text": "secret hi",
                "from": {"id": 556, "username": "web"},
            }
        }
        with settings.override(TELEGRAM_WEBHOOK_SECRET="wh-secret"):
            resp = await client.post("/telegram/api-bot/", json=payload)
            assert resp.status == 403
            resp = await client.post(
                "/telegram/api-bot/",
                json=payload,
                headers={"X-Telegram-Bot-Api-Secret-Token": "wh-secret"},
            )
            assert resp.status == 200
        assert models.Message.objects.filter(message_id=7).count() == 1

    body()


def test_eager_task_delay_from_running_loop(seeded):
    """TASK_ALWAYS_EAGER .delay() of an async task from inside a running loop
    (the webhook path) must not raise 'asyncio.run() cannot be called...'."""
    from django_assistant_bot_tpu.tasks.queue import task

    calls = []

    @task(name="tests.eager_async_probe")
    async def probe(x):
        calls.append(x)
        return x * 2

    @with_client
    async def body(client):
        with settings.override(TASK_ALWAYS_EAGER=True):
            # directly from this running loop
            probe.delay(21)
        assert calls == [21]

    body()


def test_admin_auth_branch_bounded_to_admin_mount(seeded):
    """/adminfoo must take TOKEN auth (the API branch), not the interactive
    Basic branch — the old startswith('/admin') matched too broadly."""

    @with_client
    async def body(client):
        with settings.override(API_AUTH_TOKEN="sekret", ADMIN_BASIC_AUTH="boss:pw"):
            resp = await client.get("/adminfoo")
            # API branch: token-auth JSON 401, not an interactive Basic challenge
            assert resp.status == 401
            assert "WWW-Authenticate" not in resp.headers
            resp = await client.get(
                "/adminfoo", headers={"Authorization": "Token sekret"}
            )
            assert resp.status == 404  # authenticated, route simply absent

    body()


def test_media_url_middleware_and_static(tmp_path, seeded):
    """Reference parity for MediaURLMiddleware (assistant/assistant/
    middleware.py:4-15): media URLs become absolute per request host, and
    MEDIA_ROOT serves under MEDIA_URL."""
    (tmp_path / "pic.txt").write_text("media-bytes")

    @with_client
    async def body(client):
        resp = await client.get("/media/pic.txt")
        assert resp.status == 200
        assert await resp.text() == "media-bytes"

    with settings.override(MEDIA_ROOT=str(tmp_path)):
        body()

    # media stays public under token auth (platforms fetch sent photos by URL)
    @with_client
    async def body_tokened(client):
        resp = await client.get("/media/pic.txt")
        assert resp.status == 200
        resp = await client.get("/api/v1/bots/")
        assert resp.status == 401  # the API itself stays locked

    with settings.override(MEDIA_ROOT=str(tmp_path), API_AUTH_TOKEN="tok"):
        body_tokened()

    # stored photo paths under MEDIA_ROOT serialize as absolute media URLs
    photos = tmp_path / "photos"
    photos.mkdir()
    (photos / "p1.jpg").write_bytes(b"jpegish")
    bot, instance, dialog = seeded
    role = models.Role.get_cached("user")
    models.Message.objects.create(
        dialog=dialog, message_id=77, role=role, text="see photo",
        photo=str(photos / "p1.jpg"),
    )

    @with_client
    async def body_photo(client):
        resp = await client.get(f"/api/v1/dialogs/{dialog.id}/messages/")
        assert resp.status == 200
        rows = (await resp.json())["results"]
        by_id = {r["message_id"]: r for r in rows}
        url = by_id[77]["photo"]
        assert url and url.endswith("/media/photos/p1.jpg")
        assert url.startswith("http://")
        # and the URL actually serves the bytes
        from urllib.parse import urlparse

        got = await client.get(urlparse(url).path)
        assert got.status == 200
        assert await got.read() == b"jpegish"

    with settings.override(MEDIA_ROOT=str(tmp_path)):
        body_photo()

    # the absolute-URL computation itself (per request host/scheme)
    from aiohttp.test_utils import make_mocked_request

    from django_assistant_bot_tpu.api.app import media_url_middleware

    async def capture(request):
        import aiohttp.web as web

        return web.json_response({"media_url": request["media_url"]})

    async def drive():
        req = make_mocked_request("GET", "/healthz", headers={"Host": "bots.example.com"})
        resp = await media_url_middleware(req, capture)
        import json

        return json.loads(resp.body.decode())["media_url"]

    got = asyncio.run(drive())
    assert got == "http://bots.example.com/media/"
