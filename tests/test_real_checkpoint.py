"""The FULL weights path, real formats end to end, zero egress (VERDICT r4
missing #1): synthesize a true-HF-layout checkpoint (safetensors +
config.json + trained tokenizer.json with a chat template), run it through
``fetch_models --convert --quantize int8``, serve it from the converted
native checkpoint through the registry + HTTP server, and drive ``/dialog``
with the REAL tokenizer — no ``tiny: true``, no byte tokenizer, anywhere.

Reference parity: gpu_service/bin/fetch_models.py:10-30 (pre-download),
gpu_service/main.py:57-70 (load at boot), main.py:89-107 (/dialog).
"""

import asyncio
import os
import sys
from types import SimpleNamespace

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def real_ckpt(tmp_path_factory):
    """synth -> fetch(local no-op) -> convert(int8 native). Module-scoped:
    the torch save + int8 convert is the expensive half of the path."""
    from django_assistant_bot_tpu.cli import fetch_models as fm
    from django_assistant_bot_tpu.models import synth

    root = tmp_path_factory.mktemp("real_ckpt")
    src = synth.synth_decoder(str(root / "chat_ckpt"))
    args = SimpleNamespace(
        models=[src], config=None, models_dir=str(root), revision=None,
        convert=True, kind="decoder", quantize="int8",
    )
    assert fm.run(args) == 0
    native = src + ".native.int8"
    assert os.path.isdir(native)
    return src, native


def test_synth_checkpoint_is_real_hf_layout(real_ckpt):
    src, _ = real_ckpt
    files = set(os.listdir(src))
    assert "config.json" in files
    assert any(f.endswith(".safetensors") for f in files)
    assert "tokenizer.json" in files  # a real fast tokenizer, not bytes
    # loadable by stock transformers — the format IS the HF format
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(src)
    ids = tok.encode("the quick brown fox")
    assert len(ids) < len("the quick brown fox")  # BPE learned real merges
    assert tok.chat_template


def test_real_checkpoint_serves_dialog_over_http(real_ckpt):
    src, native = real_ckpt
    from aiohttp.test_utils import TestClient, TestServer

    from django_assistant_bot_tpu.serving import ModelRegistry
    from django_assistant_bot_tpu.serving.server import create_app
    from django_assistant_bot_tpu.serving.tokenizer import HFTokenizer

    registry = ModelRegistry.from_config(
        {
            "real-chat": {
                "kind": "decoder",
                "checkpoint": native,  # the converted int8 native checkpoint
                "max_slots": 2,
                "max_seq_len": 128,
                "lookahead": 0,
                "burst": 1,
            }
        }
    )
    try:
        eng = registry.get_generator("real-chat")
        # the real tokenizer came along via the checkpoint's tokenizer meta
        assert isinstance(eng.tokenizer, HFTokenizer)
        assert eng.cfg.vocab_size >= 300  # trained BPE vocab, not 259 bytes

        loop = asyncio.new_event_loop()
        client = TestClient(TestServer(create_app(registry)), loop=loop)

        async def go():
            await client.start_server()
            resp = await client.post(
                "/dialog/",
                json={
                    "model": "real-chat",
                    "messages": [
                        {"role": "system", "content": "answer from context"},
                        {"role": "user", "content": "what does the context say"},
                    ],
                    "max_tokens": 8,
                    "json_format": False,
                },
            )
            assert resp.status == 200
            data = await resp.json()
            r = data["response"]
            assert isinstance(r["result"], str)
            assert r["usage"]["completion_tokens"] > 0
            return r

        try:
            r = loop.run_until_complete(go())
        finally:
            loop.run_until_complete(client.close())
            loop.close()
        # the REAL tokenizer (chat template + trained BPE) did the encoding:
        # prompt_tokens equals the HF-side chat-template encoding exactly —
        # a byte tokenizer would count ~90 byte ids for this prompt instead
        from transformers import AutoTokenizer

        hf_tok = AutoTokenizer.from_pretrained(src)
        rendered = hf_tok.apply_chat_template(
            [
                {"role": "system", "content": "answer from context"},
                {"role": "user", "content": "what does the context say"},
            ],
            tokenize=False,
            add_generation_prompt=True,
        )
        expect = len(hf_tok.encode(rendered, add_special_tokens=False))
        assert r["usage"]["prompt_tokens"] == expect
    finally:
        registry.stop()


def test_real_encoder_checkpoint_embeds(tmp_path):
    """The encoder half (ruBert-class format): synth -> serve /embeddings."""
    from django_assistant_bot_tpu.models import synth
    from django_assistant_bot_tpu.serving import ModelRegistry
    from django_assistant_bot_tpu.serving.tokenizer import HFTokenizer

    src = synth.synth_encoder(str(tmp_path / "emb_ckpt"))
    registry = ModelRegistry.from_config(
        {"real-emb": {"kind": "encoder", "path": src, "normalize": True}}
    )
    try:
        eng = registry.get_embedder("real-emb")
        assert isinstance(eng.tokenizer, HFTokenizer)
        vecs = eng.embed_sync(["the quick brown fox", "привет как дела"])
        assert len(vecs) == 2 and len(vecs[0]) == 64
        import numpy as np

        assert abs(float(np.linalg.norm(np.asarray(vecs[0]))) - 1.0) < 1e-3
    finally:
        registry.stop()
