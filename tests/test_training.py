"""Training plane: loss decreases on overfit, sharded step matches single-device."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from django_assistant_bot_tpu.models.config import DecoderConfig
from django_assistant_bot_tpu.training import init_train_state, make_train_step
from django_assistant_bot_tpu.training.train import batch_sharding, lm_loss


def _batch(cfg, rng_seed=0, batch=4, seq=32):
    rng = np.random.default_rng(rng_seed)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.float32)
    return ids, mask


def test_overfit_loss_decreases():
    cfg = DecoderConfig.tiny()
    optimizer = optax.adamw(1e-2)
    state = init_train_state(cfg, optimizer, rng=jax.random.PRNGKey(0))
    ids, mask = _batch(cfg)
    step = jax.jit(make_train_step(cfg, optimizer))

    first = float(lm_loss(state.params, cfg, ids, mask))
    params, opt_state = state.params, state.opt_state
    for _ in range(10):
        params, opt_state, metrics = step(params, opt_state, ids, mask)
    last = float(metrics["loss"])
    assert np.isfinite(last)
    assert last < first * 0.8, (first, last)


def test_train_step_covers_family_variants(mesh8):
    """Qwen2 biases, Gemma GeGLU/scaled-embed, and llama3 rope scaling all
    flow through the sharded train step: gradients exist for every param
    (incl. the bias leaves) and the loss stays finite."""
    import dataclasses

    cfg = dataclasses.replace(
        DecoderConfig.tiny(),
        attn_bias=True,
        hidden_act="gelu_tanh",
        embed_multiplier=float(DecoderConfig.tiny().hidden_size) ** 0.5,
        rope_scaling=(8.0, 1.0, 4.0, 16.0),
    )
    optimizer = optax.adamw(1e-2)
    with mesh8:
        state = init_train_state(
            cfg, optimizer, rng=jax.random.PRNGKey(3), mesh=mesh8
        )
        ids, mask = _batch(cfg, rng_seed=3)
        ids = jax.device_put(ids, batch_sharding(mesh8))
        mask = jax.device_put(mask, batch_sharding(mesh8))
        step = jax.jit(make_train_step(cfg, optimizer))
        params = state.params
        before = np.asarray(params["layers"]["bq"])
        params, opt_state, metrics = step(params, state.opt_state, ids, mask)
        assert np.isfinite(float(metrics["loss"]))
        # the bias leaves actually trained (nonzero gradient flowed)
        after = np.asarray(params["layers"]["bq"])
        assert not np.allclose(before, after)
        # the variant features really change the math: the same weights under a
        # plain config produce a different loss (guards against silent no-ops)
        plain = dataclasses.replace(
            cfg,
            hidden_act="silu",
            embed_multiplier=1.0,
            rope_scaling=None,
        )
        plain_loss = float(lm_loss(state.params, plain, ids, mask))
        assert plain_loss != pytest.approx(float(metrics["loss"]), rel=1e-6)


@pytest.mark.slow
def test_sharded_step_matches_single_device(mesh8):
    cfg = DecoderConfig.tiny()
    optimizer = optax.adamw(1e-3)
    ids, mask = _batch(cfg, rng_seed=1)

    ref_state = init_train_state(cfg, optimizer, rng=jax.random.PRNGKey(7))
    ref_step = jax.jit(make_train_step(cfg, optimizer))
    _, _, ref_metrics = ref_step(ref_state.params, ref_state.opt_state, ids, mask)

    with mesh8:
        state = init_train_state(cfg, optimizer, rng=jax.random.PRNGKey(7), mesh=mesh8)
        s_ids = jax.device_put(np.asarray(ids), batch_sharding(mesh8))
        s_mask = jax.device_put(np.asarray(mask), batch_sharding(mesh8))
        step = jax.jit(make_train_step(cfg, optimizer))
        _, _, metrics = step(state.params, state.opt_state, s_ids, s_mask)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-4
    )


@pytest.mark.slow
def test_remat_step_matches_plain():
    cfg = DecoderConfig.tiny()
    optimizer = optax.sgd(1e-2)
    ids, mask = _batch(cfg, rng_seed=2)
    state = init_train_state(cfg, optimizer, rng=jax.random.PRNGKey(3))

    plain = jax.jit(make_train_step(cfg, optimizer))
    remat = jax.jit(make_train_step(cfg, optimizer, remat=True))
    p1, _, m1 = plain(state.params, state.opt_state, ids, mask)
    p2, _, m2 = remat(state.params, state.opt_state, ids, mask)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    l1 = jax.tree.leaves(p1)[0]
    l2 = jax.tree.leaves(p2)[0]
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_moe_train_step_runs():
    from django_assistant_bot_tpu.parallel import best_mesh_shape, make_mesh

    cfg = DecoderConfig.tiny(num_experts=4)
    optimizer = optax.adamw(1e-3)
    axes = best_mesh_shape(8, want_model=2, want_expert=2)
    mesh = make_mesh(axes)
    ids, mask = _batch(cfg, rng_seed=4)
    with mesh:
        state = init_train_state(cfg, optimizer, rng=jax.random.PRNGKey(5), mesh=mesh)
        s_ids = jax.device_put(np.asarray(ids), batch_sharding(mesh))
        s_mask = jax.device_put(np.asarray(mask), batch_sharding(mesh))
        step = jax.jit(make_train_step(cfg, optimizer))
        _, _, metrics = step(state.params, state.opt_state, s_ids, s_mask)
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_long_context_ring_step_matches_dense(mesh8):
    """Ring-attention (sequence-parallel) training step == dense step."""
    from django_assistant_bot_tpu.parallel import best_mesh_shape, make_mesh

    cfg = DecoderConfig.tiny()
    optimizer = optax.sgd(1e-2)
    ids, mask = _batch(cfg, rng_seed=9, batch=2, seq=64)

    ref_state = init_train_state(cfg, optimizer, rng=jax.random.PRNGKey(11))
    ref_step = jax.jit(make_train_step(cfg, optimizer))
    _, _, ref_metrics = ref_step(ref_state.params, ref_state.opt_state, ids, mask)

    mesh = make_mesh(best_mesh_shape(8, want_seq=4, want_model=2))
    with mesh:
        state = init_train_state(cfg, optimizer, rng=jax.random.PRNGKey(11), mesh=mesh)
        s_ids = jax.device_put(np.asarray(ids), batch_sharding(mesh))
        s_mask = jax.device_put(np.asarray(mask), batch_sharding(mesh))
        step = jax.jit(make_train_step(cfg, optimizer, long_context_mesh=mesh))
        _, _, metrics = step(state.params, state.opt_state, s_ids, s_mask)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-4
    )


def test_forward_long_matches_forward(mesh8):
    from django_assistant_bot_tpu.models import llama
    from django_assistant_bot_tpu.parallel import best_mesh_shape, make_mesh, shard_pytree

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(2))
    ids = jnp.asarray(np.random.default_rng(3).integers(1, cfg.vocab_size, (2, 64)), jnp.int32)
    ref = np.asarray(llama.forward(params, cfg, ids))
    mesh = make_mesh(best_mesh_shape(8, want_seq=4, want_model=2))
    with mesh:
        sharded = shard_pytree(params, llama.logical_axes(cfg), mesh)
        out = jax.jit(lambda p, i: llama.forward_long(p, cfg, i, mesh))(sharded, ids)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=2e-3)


# ---------------------------------------------------------- pipeline parallelism
@pytest.mark.parametrize("n_micro", [2, 4])
def test_pipeline_forward_matches_dense(n_micro):
    """GPipe schedule over a pipe>=2 mesh == monolithic forward (same params).

    n_micro=2 is the M == stages case; n_micro=4 > stages exercises the
    steady state where both stages work on different microbatches between
    inject and collect."""
    from django_assistant_bot_tpu.parallel import best_mesh_shape, make_mesh
    from django_assistant_bot_tpu.parallel.pipeline import (
        pipeline_forward,
        pipeline_param_specs,
    )
    from django_assistant_bot_tpu.models import llama
    from jax.sharding import NamedSharding

    cfg = DecoderConfig.tiny()  # 2 layers -> 1 per stage
    params = llama.init(cfg, jax.random.PRNGKey(21))
    ids = jnp.asarray(
        np.random.default_rng(22).integers(1, cfg.vocab_size, (16, 32)), jnp.int32
    )
    ref = np.asarray(llama.forward(params, cfg, ids))

    mesh = make_mesh(best_mesh_shape(8, want_pipe=2, want_model=1))
    assert mesh.shape["pipe"] == 2 and mesh.shape["data"] == 4
    with mesh:
        specs = pipeline_param_specs(cfg, params)
        sharded = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
        )
        out = jax.jit(
            lambda p, i: pipeline_forward(p, cfg, i, mesh, n_micro=n_micro)
        )(sharded, ids)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=2e-3)


@pytest.mark.slow
def test_pipeline_train_step_matches_dense():
    """PP x DP train step: loss and updated params == the single-device step."""
    from django_assistant_bot_tpu.parallel import best_mesh_shape, make_mesh
    from django_assistant_bot_tpu.parallel.pipeline import (
        init_pipeline_state,
        make_pipeline_train_step,
    )

    cfg = DecoderConfig.tiny()
    optimizer = optax.sgd(1e-2)
    ids, mask = _batch(cfg, rng_seed=23, batch=8, seq=32)

    ref_state = init_train_state(cfg, optimizer, rng=jax.random.PRNGKey(31))
    ref_step = jax.jit(make_train_step(cfg, optimizer))
    ref_params, _, ref_metrics = ref_step(
        ref_state.params, ref_state.opt_state, ids, mask
    )

    mesh = make_mesh(best_mesh_shape(8, want_pipe=2))
    assert mesh.shape["pipe"] == 2 and mesh.shape["data"] == 4
    with mesh:
        state = init_pipeline_state(
            cfg, optimizer, rng=jax.random.PRNGKey(31), mesh=mesh
        )
        step = jax.jit(make_pipeline_train_step(cfg, optimizer, mesh, n_micro=2))
        params, _, metrics = step(state.params, state.opt_state, ids, mask)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-4
    )
    # updated params match leaf-for-leaf (gradients flowed through every stage)
    for ref_leaf, leaf in zip(jax.tree.leaves(ref_params), jax.tree.leaves(params)):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=2e-3, atol=2e-5
        )


def test_pipeline_rejects_bad_shapes():
    from django_assistant_bot_tpu.parallel import best_mesh_shape, make_mesh
    from django_assistant_bot_tpu.parallel.pipeline import pipeline_forward
    from django_assistant_bot_tpu.models import llama

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(0))
    ids = jnp.zeros((4, 16), jnp.int32)
    no_pipe = make_mesh(best_mesh_shape(8))
    with pytest.raises(ValueError, match="pipe axis"):
        pipeline_forward(params, cfg, ids, no_pipe, n_micro=2)
    mesh = make_mesh(best_mesh_shape(8, want_pipe=2))
    with pytest.raises(ValueError, match="n_micro"):
        pipeline_forward(params, cfg, ids, mesh, n_micro=3)

def test_copy_task_batch_and_accuracy_gate():
    """The speculation bench's copy/quote harness: batch layout (second half
    repeats the first, loss masked to it) and the accuracy gate's teacher-
    forced semantics (a model that predicts the quoted token perfectly
    scores 1.0 on the masked region)."""
    from django_assistant_bot_tpu.training import (
        copy_task_config,
        make_copy_batch,
        quote_accuracy,
    )

    rng = np.random.default_rng(0)
    ids, mask = make_copy_batch(rng, 4, 64, 64)
    ids = np.asarray(ids)
    mask = np.asarray(mask)
    assert ids.shape == (4, 64) and mask.shape == (4, 64)
    assert (ids[:, :32] == ids[:, 32:]).all()  # the quote IS the context
    assert (mask[:, :32] == 0).all() and (mask[:, 32:] == 1).all()
    assert ids.min() >= 3  # special ids never appear in the copied span
    cfg = copy_task_config()
    from django_assistant_bot_tpu.models import llama

    params = llama.init(cfg, jax.random.PRNGKey(0))
    acc = quote_accuracy(params, cfg, jnp.asarray(ids), jnp.asarray(mask))
    assert 0.0 <= acc <= 1.0  # random weights: defined, bounded, not asserted


def test_fit_copy_model_single_step_smoke():
    """fit_copy_model wires the training plane end to end (one step, tiny
    geometry) and reports its convergence evidence — the bench relies on
    that report to keep the random-weights trap out of spec_* numbers."""
    from django_assistant_bot_tpu.training import copy_task_config, fit_copy_model

    cfg = copy_task_config(vocab_size=32, hidden_size=16, max_seq_len=64)
    params, cfg2, info = fit_copy_model(
        cfg, seq_len=32, batch=4, max_steps=2, eval_every=1, seed=0
    )
    assert cfg2 is cfg
    assert info["train_steps"] >= 1
    assert 0.0 <= info["quote_accuracy"] <= 1.0
    assert params is not None
