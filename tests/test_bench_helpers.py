"""Host-side bench helpers (no device): failure diagnosis + record hygiene.

The bench record is the round's canonical evidence (BENCH_r*.json) — these
lock the helpers that keep failures diagnosable (VERDICT r3 weak #1: failures
were recorded blind) and the headline well-formed.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def test_error_tail_prefers_root_cause_over_wrapper():
    stderr = "\n".join(
        [
            "Traceback (most recent call last):",
            '  File "x.py", line 1, in <module>',
            "jax.errors.JaxRuntimeError: RESOURCE_EXHAUSTED: TPU backend error.",
            "During handling of the above exception, another exception occurred:",
            "RuntimeError: generation engine failure",
        ]
    )
    tail = bench._error_tail(stderr)
    assert "RESOURCE_EXHAUSTED" in tail
    assert "generation engine failure" not in tail


def test_error_tail_falls_back_to_last_exception_line():
    assert "ValueError: boom" in bench._error_tail("ValueError: boom")
    assert bench._error_tail("") == "no stderr"
    out = bench._error_tail("line1\nline2\nline3\nline4")
    assert "line4" in out


def test_subprocess_bench_returns_error_tail():
    res, err = bench._subprocess_bench(
        "raise RuntimeError('intentional-test-failure')", timeout_s=120
    )
    assert res is None
    assert "intentional-test-failure" in err


def test_subprocess_bench_parses_final_json_line():
    res, err = bench._subprocess_bench(
        "import json\nprint('noise'); print(json.dumps({'ok': 1}))", timeout_s=120
    )
    assert res == {"ok": 1} and err == ""


def test_bench_8b_budget_walk_semantics(monkeypatch):
    """The 8B section's budget discipline (what blew the r4 driver cap):
    exhausted budget records a skip without spawning anything; the fp8
    walk-down uses SHRINKING per-attempt caps (900 then 400) so a hang can't
    eat three full timeouts; per-slot error keys never overwrite each other."""
    out = bench.bench_8b(time_left=lambda: 100)
    assert out == {"decode_8b_skipped": "budget exhausted (100s left)"}

    calls = []

    def fake(snippet, timeout_s=1800):
        calls.append(timeout_s)
        if len(calls) == 1:
            return {"decode_8b_int8_tokens_per_s_per_chip": 1.0}, ""
        return None, "simulated OOM"

    monkeypatch.setattr(bench, "_subprocess_bench", fake)
    out = bench.bench_8b(time_left=lambda: 10**6)
    assert calls == [900, 900, 400, 400]
    assert {"decode_8b_fp8kv_error_64", "decode_8b_fp8kv_error_32",
            "decode_8b_fp8kv_error_16"} <= set(out)


def test_transient_compile_failure_retries_once(monkeypatch):
    """A remote-compile-service connection drop (environmental) earns exactly
    one fresh-subprocess retry, with the transient recorded; real failures
    and exhausted budgets do not retry."""
    calls = []

    def flaky(snippet, timeout_s=1800):
        calls.append(timeout_s)
        if len(calls) == 1:
            return None, "rc=1: INTERNAL: remote_compile: read body: closed"
        return {"ok": 1}, ""

    monkeypatch.setattr(bench, "_subprocess_bench", flaky)
    extras = {}
    res, err = bench._run_with_transient_retry("x", 300, lambda: 1000, extras, "s")
    assert res == {"ok": 1} and len(calls) == 2
    assert "remote_compile" in extras["s_transient"]

    calls.clear()
    monkeypatch.setattr(
        bench, "_subprocess_bench", lambda s, timeout_s=0: (None, "real OOM")
    )
    extras = {}
    res, err = bench._run_with_transient_retry("x", 300, lambda: 1000, extras, "s")
    assert res is None and "s_transient" not in extras  # non-transient: no retry


def test_compact_record_is_bounded_and_parseable():
    """The LAST stdout line must always fit the driver's 2,000-char tail and
    carry the headline (VERDICT r5 #1: two rounds of `parsed: null`)."""
    import json

    extras = {k: 1234.5678 for k in bench._COMPACT_KEYS}
    extras["rag_req_per_s"] = 9.87654
    record = {
        "metric": "rag_req_per_s_plus_p50_ttft",
        "value": 9.87654,
        "unit": "req/s",
        "vs_baseline": 171.959,
        "extras": extras,
    }
    line = bench._compact_record(record)
    assert len(line) < 1500
    parsed = json.loads(line)
    assert parsed["rag_req_per_s"] == 9.877  # 4 sig figs
    assert parsed["value"] == 9.877
    # a pathologically bloated extras set still fits: low-priority keys drop,
    # the headline survives
    extras["moe_geometry"] = "x" * 4000
    line = bench._compact_record(record)
    assert len(line) < 1500
    assert "rag_req_per_s" in json.loads(line)


def test_compact_record_carries_error_headline():
    import json

    record = {"metric": "m", "value": None, "vs_baseline": None,
              "error": "core section produced no result (yet)", "extras": {}}
    parsed = json.loads(bench._compact_record(record))
    assert "core section" in parsed["error"]


def test_sig4_rounding():
    assert bench._sig4(1234.5678) == 1235.0
    assert bench._sig4(0.0123456) == 0.01235
    assert bench._sig4(12) == 12  # ints pass through
    assert bench._sig4("str") == "str"
    assert bench._sig4(True) is True


def test_transient_predicate_excludes_deterministic_compile_failures():
    """Only connection-drop signatures retry; a deterministic remote-compile
    failure (e.g. VMEM OOM) must not burn a second full attempt."""
    assert bench._is_transient_compile_error(
        "INTERNAL: http://x/remote_compile: read body: response body closed"
    )
    assert not bench._is_transient_compile_error(
        "INTERNAL: http://x/remote_compile: AOT PJRT error: Ran out of memory"
    )
    assert not bench._is_transient_compile_error("RESOURCE_EXHAUSTED: plain OOM")
