"""Ingestion plane: split, per-document pipeline, chord finalize, CSV loader.

AI is cut at the provider (scripted EchoProvider); storage, task dispatch, KNN
invalidation, and status machine all run real (SURVEY.md §4 strategy).
"""

import asyncio

import numpy as np
import pytest

from django_assistant_bot_tpu.ai.providers.echo import EchoProvider
from django_assistant_bot_tpu.conf import settings
from django_assistant_bot_tpu.loading import CSVLoader
from django_assistant_bot_tpu.processing import signals  # noqa: F401 — activates post_save
from django_assistant_bot_tpu.processing.tasks import (
    finalize_document_processing_task,
    wiki_processing_task,
)
from django_assistant_bot_tpu.rag.index_registry import reset_indexes
from django_assistant_bot_tpu.storage import models
from django_assistant_bot_tpu.storage.orm import disable_signals
from django_assistant_bot_tpu.tasks import TaskRecord, Worker

CONTENT = "Pay invoices in the billing portal. Refunds take five business days."
FORMATTED = "## Billing\nPay invoices in the billing portal. Refunds take five business days."
SENTENCES = [
    "Pay invoices in the billing portal and check status there regularly.",
    "Refunds take five business days to process after the request is filed.",
]
QUESTIONS = [
    "How do I pay my invoices in the billing portal system?",
    "How long do refunds take to process after filing the request?",
]


@pytest.fixture(autouse=True)
def _fresh(tmp_db):
    reset_indexes()
    yield
    reset_indexes()


def _scripted(monkeypatch, script):
    from django_assistant_bot_tpu.ai import dialog as dialog_mod

    provider = EchoProvider(script=list(script))
    monkeypatch.setattr(
        dialog_mod, "get_ai_provider", lambda model, **kwargs: provider
    )
    return provider


def _pipeline_script():
    return [
        {"text": FORMATTED},        # DocumentFormatStep
        {"sentences": SENTENCES},   # ExtractSentencesStep
        {"questions": QUESTIONS},   # GenerateQuestionsStep
    ]


def test_wiki_processing_eager_end_to_end(monkeypatch):
    _scripted(monkeypatch, _pipeline_script())
    bot = models.Bot.objects.create(codename="ing")
    with settings.override(TASK_ALWAYS_EAGER=True):
        wiki = models.WikiDocument.objects.create(bot=bot, title="Billing", content=CONTENT)

    # signal fired -> split (single section, short content) -> full pipeline -> finalize
    processing = models.WikiDocumentProcessing.objects.get(wiki_document=wiki)
    assert processing.status == models.WikiDocumentProcessing.COMPLETED
    doc = models.Document.objects.get(processing=processing)
    assert doc.name == "Billing" and doc.content == FORMATTED
    sentences = models.Sentence.objects.filter(document=doc).all()
    questions = models.Question.objects.filter(document=doc).all()
    assert [s.text for s in sentences] == SENTENCES
    assert [q.text for q in questions] == QUESTIONS
    assert all(s.embedding is not None for s in sentences)
    assert all(q.embedding is not None for q in questions)


def test_wiki_processing_via_worker_chord(monkeypatch):
    _scripted(monkeypatch, _pipeline_script())
    bot = models.Bot.objects.create(codename="ing2")
    wiki = models.WikiDocument.objects.create(bot=bot, title="Docs", content=CONTENT)
    # signal enqueued the wiki task; drain: wiki -> group member -> chord finalize
    w = Worker(["processing"])
    for _ in range(4):
        w.run_until_idle()
    processing = models.WikiDocumentProcessing.objects.get(wiki_document=wiki)
    assert processing.status == models.WikiDocumentProcessing.COMPLETED
    assert models.Question.objects.count() == len(QUESTIONS)
    names = [t.name for t in TaskRecord.objects.all()]
    assert any("wiki_processing_task" in n for n in names)
    assert any("finalize_document_processing_task" in n for n in names)
    assert all(t.status == "done" for t in TaskRecord.objects.all())


def test_finalize_deletes_stale_processings(monkeypatch):
    bot = models.Bot.objects.create(codename="ing3")
    with disable_signals():
        wiki = models.WikiDocument.objects.create(bot=bot, title="W", content="short")
    old = models.WikiDocumentProcessing.objects.create(wiki_document=wiki)
    new = models.WikiDocumentProcessing.objects.create(wiki_document=wiki)
    finalize_document_processing_task.apply(new.id)
    assert models.WikiDocumentProcessing.objects.get(id=new.id).status == "completed"
    assert models.WikiDocumentProcessing.objects.get_or_none(id=old.id) is None


def test_merge_questions_dedup(monkeypatch):
    """A near-duplicate question triggers LLM same-meaning + doc-choice; the
    loser's question is deleted (reference: steps/questions.py:104-203)."""
    from django_assistant_bot_tpu.processing.documents.steps.questions import (
        MergeQuestionsStep,
    )

    bot = models.Bot.objects.create(codename="ing4")
    with disable_signals():
        wiki = models.WikiDocument.objects.create(bot=bot, title="W", content="x")
    d1 = models.Document.objects.create(wiki=wiki, name="old", content="old doc")
    d2 = models.Document.objects.create(wiki=wiki, name="new", content="new doc")
    vec = np.random.default_rng(0).normal(size=768).astype(np.float32)
    q_old = models.Question.objects.create(document=d1, text="How to pay?", embedding=vec)
    q_new = models.Question.objects.create(document=d2, text="How to pay??", embedding=vec)

    # similarity -> true; doc choice -> 1 (the asking doc d2 wins, old question deleted)
    _scripted(monkeypatch, [{"result": True}, {"result": 1}])
    asyncio.run(MergeQuestionsStep(d2).run())
    assert models.Question.objects.get_or_none(id=q_old.id) is None
    assert models.Question.objects.get_or_none(id=q_new.id) is not None


def test_split_long_document(monkeypatch):
    from django_assistant_bot_tpu.processing.wiki import split_wiki_document

    long_content = "\n".join(f"Line {i} of the long document body." for i in range(60))
    bot = models.Bot.objects.create(codename="ing5")
    with disable_signals():
        wiki = models.WikiDocument.objects.create(bot=bot, title="Long", content=long_content)
    _scripted(
        monkeypatch,
        [
            {"names": ["Part One", "Part Two"]},
            {"text": "First half of the text."},
            {"text": "Second half of the text."},
        ],
    )
    processing = asyncio.run(split_wiki_document(wiki))
    docs = models.Document.objects.filter(processing=processing).order_by("id").all()
    assert [d.name for d in docs] == ["Part One", "Part Two"]
    assert docs[0].content == "First half of the text."


def test_csv_loader_builds_tree(tmp_path):
    bot = models.Bot.objects.create(codename="csv")
    p = tmp_path / "data.csv"
    p.write_text(
        "topic,title,content\n"
        "Billing,Pay,How to pay\n"
        "Billing,Refund,How to refund\n"
        "Shipping,Track,How to track\n"
    )
    with disable_signals():
        n = CSVLoader(bot).load(str(p))
    assert n == 3
    roots = models.WikiDocument.objects.filter(bot=bot, parent=None).all()
    assert sorted(r.title for r in roots) == ["Billing", "Shipping"]
    billing = next(r for r in roots if r.title == "Billing")
    assert sorted(c.title for c in billing.children()) == ["Pay", "Refund"]


def test_language_matches_only_known_jitter_pairs(monkeypatch):
    """Equivalence is limited to detector-jitter pairs (ru<->uk; latin 'en'
    default; symmetric latin pairs on SHORT chunks only) — a full-length
    German answer to an English document must FAIL (r4 advisor: whole-script
    equivalence was too broad; r5: one-way en acceptance spun repeat_until)."""
    from django_assistant_bot_tpu.processing import utils as pu

    # detected code = first token of the text, so length is controllable
    monkeypatch.setattr(pu, "get_language", lambda t: t.split()[0])
    pu.language_jitter_counts.clear()
    long_pad = " x" * pu.LATIN_JITTER_MAX_CHARS  # pushes past the threshold
    assert pu.language_matches("ru", "uk") and pu.language_matches("uk", "ru")
    assert pu.language_matches("fr", "en")  # short latin chunks read as en
    assert pu.language_matches("fr", "en" + long_pad)  # en default: any length
    assert pu.language_matches(None, "anything")
    # the r5 asymmetry fix: expected en + detected fr/nl on a SHORT chunk is
    # detector jitter, not a wrong-language answer
    assert pu.language_matches("en", "fr")
    assert pu.language_matches("en", "nl")
    # ...but a long answer in the wrong language still fails
    assert not pu.language_matches("en", "de" + long_pad)
    assert not pu.language_matches("en", "es" + long_pad)
    assert not pu.language_matches("ru", "en")
    assert not pu.language_matches("en", "ru")
    # jitter direction is observable
    assert pu.language_jitter_counts["en->fr"] == 1
    assert pu.language_jitter_counts["fr->en"] == 2
