"""Durability plane tests (storage/durable.py + storage/integrity.py).

The WAL/snapshot/recovery contract, exercised the way crashes actually land:
torn tails healed at open (not trusted), corrupt snapshots detected by digest
walk and FALLEN BACK from (never loaded), tombstones that cannot resurrect
across a snapshot boundary, idempotency-ledger dedup across restarts, and the
headline SIGKILL-mid-ingest kill-replay (slow-marked; also CI's smoke step).
Fault schedules are armed/exact (serving/faults.py), fuzz seeds pinned — all
deterministic.
"""

import argparse
import json
import os
import struct

import numpy as np
import pytest

from django_assistant_bot_tpu.serving.faults import (
    ALL_SITES,
    FaultInjected,
    FaultInjector,
    reset_global_injector,
    set_global_injector,
)
from django_assistant_bot_tpu.storage.ann import make_clustered
from django_assistant_bot_tpu.storage.durable import (
    _HDR,
    REC_APPEND,
    REC_INSTALL,
    REC_TOMBSTONE,
    DurableANN,
    MmapRowStore,
    SnapshotStore,
    WriteAheadLog,
    verify_dir,
)
from django_assistant_bot_tpu.storage.integrity import crc32c, entry_crc32c, file_crc32c

DIM = 32


@pytest.fixture(autouse=True)
def _clean_injector():
    reset_global_injector()
    yield
    reset_global_injector()


def _corpus(n, seed=7):
    return make_clustered(n, DIM, seed=seed)


def _topk(index, queries, k=10):
    return [[int(i) for i, _ in index.search(q, k=k)] for q in queries]


# ------------------------------------------------------------------ CRC-32C
def test_crc32c_known_vector_and_chaining():
    # RFC 3720 check value for "123456789"
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    whole = crc32c(b"hello world")
    assert crc32c(b" world", crc32c(b"hello")) == whole
    assert entry_crc32c(b"k", b"v") == crc32c(b"v", crc32c(b"k"))


def test_crc32c_unified_across_planes():
    """Satellite 1: one implementation — the KV-pool and fleet-wire checksums
    ARE storage.integrity's, not copies that could drift."""
    from django_assistant_bot_tpu.serving import fleet, kv_pool
    from django_assistant_bot_tpu.storage import integrity

    assert kv_pool.crc32c is integrity.crc32c
    assert kv_pool.entry_crc32c is integrity.entry_crc32c
    assert fleet.crc32c is integrity.crc32c


def test_file_crc32c_matches_buffer(tmp_path):
    p = tmp_path / "blob"
    data = bytes(range(256)) * 77
    p.write_bytes(data)
    assert file_crc32c(str(p), chunk_bytes=1000) == crc32c(data)
    assert file_crc32c(str(tmp_path / "missing")) is None


# ---------------------------------------------------------------------- WAL
def test_wal_roundtrip_property_fuzz(tmp_path):
    """Pinned-seed property test: random record types/sizes through tiny
    segments (forced rotation), reopened, must replay byte-identically."""
    rng = np.random.default_rng(int(os.environ.get("DABT_FAULT_SEED", "0")))
    wal = WriteAheadLog(str(tmp_path / "wal"), segment_bytes=256, fsync="never")
    written = []
    for _ in range(120):
        rtype = int(rng.integers(1, 4))
        payload = rng.bytes(int(rng.integers(0, 200)))
        seq = wal.append(rtype, payload)
        written.append((seq, rtype, payload))
    assert wal.segment_count > 1  # rotation actually exercised
    assert wal.last_seq == 120
    wal.close()

    back = WriteAheadLog(str(tmp_path / "wal"), segment_bytes=256, fsync="never")
    assert back.torn_tail_truncations == 0
    assert list(back.replay()) == written
    # replay(after_seq) resumes mid-stream
    assert list(back.replay(after_seq=100)) == written[100:]
    assert back.append(REC_APPEND, b"after-reopen") == 121
    back.close()


@pytest.mark.parametrize("cut", ["mid_header", "mid_payload", "garbage_tail"])
def test_wal_torn_tail_truncated_on_open(tmp_path, cut):
    wal = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    for i in range(5):
        wal.append(REC_APPEND, f"rec-{i}".encode() * 10)
    path = wal._segments[-1]["path"]
    size = os.path.getsize(path)
    wal.close()
    with open(path, "r+b") as f:
        if cut == "mid_header":
            f.seek(0, os.SEEK_END)
            f.write(_HDR.pack(0x4C415744, 6, REC_APPEND, 50, 0)[:7])
        elif cut == "mid_payload":
            f.seek(0, os.SEEK_END)
            f.write(_HDR.pack(0x4C415744, 6, REC_APPEND, 50, 0) + b"x" * 20)
        else:
            f.seek(0, os.SEEK_END)
            f.write(b"\xde\xad\xbe\xef" * 8)

    healed = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    assert healed.torn_tail_truncations == 1
    assert os.path.getsize(path) == size  # truncated back to the good bytes
    assert [seq for seq, _, _ in healed.replay()] == [1, 2, 3, 4, 5]
    assert healed.append(REC_APPEND, b"resumes") == 6  # seq continues, no gap
    healed.close()


def test_wal_mid_stream_corruption_fails_replay_loudly(tmp_path):
    """Corruption BEFORE the tail is new damage, not a torn write — replay
    must surface it, never silently skip records."""
    wal = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    for i in range(10):
        wal.append(REC_APPEND, f"payload-{i}".encode() * 5)
    path = wal._segments[-1]["path"]
    wal.close()
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 3)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    # the healing open truncates at the first bad record; everything after
    # the flipped byte is unreachable, so the heal drops it
    healed = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    assert healed.torn_tail_truncations == 1
    seqs = [seq for seq, _, _ in healed.replay()]
    assert seqs == list(range(1, len(seqs) + 1)) and len(seqs) < 10
    healed.close()


def test_wal_single_writer_flock_reader_semantics(tmp_path):
    writer = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    writer.append(REC_APPEND, b"one")
    writer.append(REC_TOMBSTONE, b"two")
    reader = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    assert writer.writable and not reader.writable
    # readers replay the committed records but may not mutate anything
    assert [p for _, _, p in reader.replay()] == [b"one", b"two"]
    with pytest.raises(OSError):
        reader.append(REC_APPEND, b"nope")
    assert reader.prune_through(2) == 0
    reader.close()
    writer.close()
    # the writer's close released the flock: next opener owns the log
    heir = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    assert heir.writable
    heir.close()


def test_wal_fsync_interval_policy_uses_injected_clock(tmp_path, monkeypatch):
    """DABT104 discipline: the interval policy reads the injected clock, so a
    fake clock drives the sync schedule deterministically."""
    now = [0.0]
    real_fsync, calls = os.fsync, []
    monkeypatch.setattr(os, "fsync", lambda fd: (calls.append(fd), real_fsync(fd)))
    wal = WriteAheadLog(
        str(tmp_path / "wal"),
        fsync="interval",
        sync_every=1000,
        sync_interval_s=5.0,
        clock=lambda: now[0],
    )
    wal.append(REC_APPEND, b"a")  # first append opens the segment (dir fsync)
    base = len(calls)
    wal.append(REC_APPEND, b"b")
    wal.append(REC_APPEND, b"c")
    assert len(calls) == base  # clock never moved: no fsync yet
    now[0] = 6.0
    wal.append(REC_APPEND, b"d")
    assert len(calls) == base + 1  # interval elapsed on the fake clock
    wal.close()


def test_wal_prune_keeps_active_segment(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"), segment_bytes=200, fsync="never")
    for i in range(30):
        wal.append(REC_APPEND, b"x" * 64)
    segs = wal.segment_count
    assert segs > 2
    removed = wal.prune_through(wal.last_seq)
    assert removed == segs - 1 and wal.segment_count == 1
    assert wal.append(REC_APPEND, b"still-appendable") == 31
    wal.close()


# -------------------------------------------------------------- fault sites
def test_storage_fault_sites_registered():
    for site in ("disk_write_fail", "disk_torn_write", "snapshot_corrupt"):
        assert site in ALL_SITES


def test_disk_write_fail_fault(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    set_global_injector(FaultInjector({"disk_write_fail": {"fire_on": [1]}}))
    with pytest.raises(OSError):
        wal.append(REC_APPEND, b"doomed")
    # the failed append logged NOTHING; the next one lands at seq 1
    assert wal.append(REC_APPEND, b"fine") == 1
    assert [p for _, _, p in wal.replay()] == [b"fine"]
    wal.close()


def test_disk_torn_write_fault_poisons_then_heals(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    wal.append(REC_APPEND, b"committed")
    set_global_injector(FaultInjector({"disk_torn_write": {"fire_on": [1]}}))
    with pytest.raises(FaultInjected):
        wal.append(REC_APPEND, b"torn-in-half" * 10)
    reset_global_injector()
    with pytest.raises(OSError):  # poisoned: this writer is "dead"
        wal.append(REC_APPEND, b"refused")
    wal.close()
    healed = WriteAheadLog(str(tmp_path / "wal"), fsync="always")
    assert healed.torn_tail_truncations == 1
    assert [p for _, _, p in healed.replay()] == [b"committed"]
    assert healed.append(REC_APPEND, b"recovered") == 2
    healed.close()


def test_snapshot_corrupt_fault_detected_not_trusted(tmp_path):
    store = SnapshotStore(str(tmp_path / "snaps"))
    arrays = {"ids": np.arange(10, dtype=np.int64)}
    store.write(arrays, {"wal_seq": 1})
    set_global_injector(FaultInjector({"snapshot_corrupt": {"fire_on": [1]}}))
    store.write(arrays, {"wal_seq": 2})
    reset_global_injector()
    assert store.verify(os.path.join(store.dir, store.list_snapshots()[0])) != []
    best, fallbacks = store.latest_valid()
    assert fallbacks == 1 and best is not None and best.endswith("snap-000000000001")
    # the corrupt dir was quarantined, not deleted: evidence survives
    assert any(n.endswith(".corrupt") for n in os.listdir(store.dir))


# ---------------------------------------------------------------- snapshots
def test_snapshot_atomicity_tmp_dir_ignored(tmp_path):
    store = SnapshotStore(str(tmp_path / "snaps"))
    store.write({"ids": np.arange(4, dtype=np.int64)}, {"wal_seq": 3})
    # a crashed writer's leftover tmp dir must be invisible to recovery
    os.makedirs(os.path.join(store.dir, ".tmp-snap-000000000009-1234"))
    assert store.list_snapshots() == ["snap-000000000003"]
    best, fallbacks = store.latest_valid()
    assert best is not None and fallbacks == 0


def test_snapshot_manifest_digests_cover_every_artifact(tmp_path):
    store = SnapshotStore(str(tmp_path / "snaps"))
    arrays = {
        "ids": np.arange(6, dtype=np.int64),
        "vectors": np.ones((6, DIM), np.float32),
    }
    path = store.write(arrays, {"wal_seq": 5})
    manifest = json.load(open(os.path.join(path, "manifest.json")))
    assert set(manifest["artifacts"]) == {"ids.npy", "vectors.npy"}
    for fname, spec in manifest["artifacts"].items():
        assert spec["crc32c"] == file_crc32c(os.path.join(path, fname))
    assert store.verify(path) == []


# --------------------------------------------------------------- DurableANN
def test_durable_crash_reopen_search_identity(tmp_path):
    rows = _corpus(300)
    q = rows[::40][:6]
    dur = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    dur.ingest(range(200), rows[:200], ledger_key="doc0")
    dur.train(nlist=8, seed=7)
    dur.ingest(range(200, 300), rows[200:], ledger_key="doc1")
    before = _topk(dur, q)
    dur.close()  # close WITHOUT snapshot: recovery is pure WAL replay

    back = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    st = back.durability_stats()
    assert back.recovered and st["replayed_records"] == 3
    assert len(back) == 300 and back.ledger_has("doc0") and back.ledger_has("doc1")
    assert _topk(back, q) == before
    back.close()


def test_durable_snapshot_restore_identity_and_drift_reset(tmp_path):
    """Satellite 3: a restore resets the drift gauge — advisory retrain
    starts from a clean slate on the recovered placement."""
    rows = _corpus(300)
    q = rows[::40][:6]
    dur = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    dur.ingest(range(300), rows, ledger_key="doc0")
    dur.train(nlist=8, seed=7)
    before = _topk(dur, q)
    assert dur.snapshot() is not None
    dur.close()

    back = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    st = back.durability_stats()
    assert back.recovered and st["replayed_records"] == 0  # all from snapshot
    assert st["snapshot_count"] == 1 and st["snapshot_age_s"] is not None
    assert _topk(back, q) == before
    ist = back.index.stats()
    assert ist["trained"] and not ist["retrain_advised"]
    assert float(ist["drift_frac"] or 0.0) == 0.0
    back.close()


def test_durable_tombstone_no_resurrection_across_snapshot(tmp_path):
    """Satellite 4: removed rows stay removed when the remove preceded the
    snapshot (compaction point: only live rows are written) AND when it
    landed after it (tombstone replayed from the WAL tail)."""
    rows = _corpus(300)
    dur = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    dur.ingest(range(300), rows, ledger_key="doc0")
    dur.train(nlist=8, seed=7)
    dur.remove(list(range(0, 40)))  # before the snapshot boundary
    dur.snapshot()
    dur.remove(list(range(40, 60)))  # after it, lives only in the WAL tail
    dur.close()

    back = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    live = set(back.index.live_ids())
    assert live == set(range(60, 300))
    assert len(back) == 240
    # a broad search never returns a resurrected id
    for q in rows[:60:7]:
        assert not {int(i) for i, _ in back.search(q, k=50)} & set(range(60))
    # the snapshot itself holds only live rows: compaction, not tombstone-list
    snaps = back.snapshots.list_snapshots()
    arrays, _ = back.snapshots.load(os.path.join(back.snapshots.dir, snaps[0]))
    assert set(arrays["ids"].tolist()) == set(range(40, 300))
    back.close()


def test_durable_corrupt_snapshot_falls_back_to_previous(tmp_path):
    rows = _corpus(300)
    q = rows[::40][:6]
    dur = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7, snapshot_keep=4)
    dur.ingest(range(200), rows[:200], ledger_key="doc0")
    dur.train(nlist=8, seed=7)
    dur.snapshot()  # good snapshot
    dur.ingest(range(200, 300), rows[200:], ledger_key="doc1")
    set_global_injector(FaultInjector({"snapshot_corrupt": {"fire_on": [1]}}))
    dur.snapshot()  # newest snapshot is silently rotten
    reset_global_injector()
    before = _topk(dur, q)
    dur.close()

    back = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7, snapshot_keep=4)
    st = back.durability_stats()
    assert st["snapshot_fallbacks"] == 1  # detected by digest walk, skipped
    assert len(back) == 300 and _topk(back, q) == before
    back.close()


def test_durable_ledger_dedup_survives_restart(tmp_path):
    rows = _corpus(120)
    dur = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    assert dur.ingest(range(60), rows[:60], ledger_key="doc:1:v1") == 60
    assert dur.ingest(range(60), rows[:60], ledger_key="doc:1:v1") == 0
    dur.snapshot()
    dur.close()
    back = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    assert back.ingest(range(60), rows[:60], ledger_key="doc:1:v1") == 0
    assert back.durability_stats()["ledger_dedup_hits"] == 1
    assert back.ingest(range(60, 120), rows[60:], ledger_key="doc:1:v2") == 60
    live = back.index.live_ids()
    assert len(live) == len(set(live)) == 120  # zero duplicate vectors
    back.close()


def test_durable_untrained_roundtrip_exact_tier(tmp_path):
    rows = _corpus(50)
    q = rows[::9][:4]
    dur = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    dur.ingest(range(50), rows)
    before = _topk(dur, q, k=5)
    dur.snapshot()
    dur.close()
    back = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    assert not back.index.stats()["trained"]
    assert _topk(back, q, k=5) == before
    back.close()


def test_durable_read_only_opener_serves_without_mutating(tmp_path):
    rows = _corpus(80)
    writer = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    writer.ingest(range(80), rows, ledger_key="doc0")
    reader = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    assert writer.writable and not reader.writable
    assert len(reader) == 80  # recovered the committed state
    with pytest.raises(OSError):
        reader.ingest(range(80, 90), rows[:10])
    with pytest.raises(OSError):
        reader.snapshot()
    reader.close()
    writer.close()


# -------------------------------------------------------------- mmap tier
def test_mmap_row_store_grow_preserves_rows(tmp_path):
    store = MmapRowStore(str(tmp_path / "rows.mmap"))
    a = store.alloc((4, 8))
    a[:] = np.arange(32, dtype=np.float32).reshape(4, 8)
    a.flush()
    b = store.alloc((16, 8))
    assert isinstance(b, np.memmap)
    np.testing.assert_array_equal(b[:4], np.arange(32, dtype=np.float32).reshape(4, 8))


def test_durable_mmap_rows_roundtrip_and_restage(tmp_path):
    rows = _corpus(200)
    q = rows[::40][:4]
    dur = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7, mmap_rows=True)
    dur.ingest(range(200), rows, ledger_key="doc0")
    dur.train(nlist=8, seed=7)
    # the disk tier must survive the retrain's restage, not revert to RAM
    assert isinstance(dur.index._mat, np.memmap)
    before = _topk(dur, q)
    dur.snapshot()
    dur.close()
    back = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7, mmap_rows=True)
    assert isinstance(back.index._mat, np.memmap)
    assert _topk(back, q) == before
    back.close()


# ------------------------------------------------------------------ verify
def test_verify_dir_clean_and_corrupt(tmp_path):
    rows = _corpus(100)
    dur = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    dur.ingest(range(100), rows, ledger_key="doc0")
    dur.snapshot()
    dur.ingest(range(100, 110), _corpus(10, seed=9), ledger_key="doc1")
    dur.close()
    report = verify_dir(str(tmp_path / "d"))
    assert report["ok"] and report["wal_records"] >= 1 and report["snapshots"]

    # flip one byte inside a snapshot artifact: the digest walk must object
    snap = os.path.join(str(tmp_path / "d"), "snapshots", report["snapshots"][0]["name"])
    victim = next(
        os.path.join(snap, n) for n in sorted(os.listdir(snap)) if n.endswith(".npy")
    )
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    report = verify_dir(str(tmp_path / "d"))
    assert not report["ok"] and report["problems"]


def test_verify_dir_flags_wal_crc_damage(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "d" / "wal"), fsync="always")
    for i in range(6):
        wal.append(REC_APPEND, f"record-{i}".encode() * 8)
    path = wal._segments[-1]["path"]
    wal.close()
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    report = verify_dir(str(tmp_path / "d"))
    assert not report["ok"] and any("wal-" in p for p in report["problems"])


# --------------------------------------------------------------------- CLI
def _cli_args(argv):
    from django_assistant_bot_tpu.cli import ann as ann_cli

    p = argparse.ArgumentParser()
    ann_cli.add_parser(p.add_subparsers(dest="command"))
    return p.parse_args(["ann", *argv])


def test_cli_snapshot_restore_verify_roundtrip(tmp_path, capsys):
    from django_assistant_bot_tpu.cli import ann as ann_cli

    rows = _corpus(150)
    d = str(tmp_path / "d")
    dur = DurableANN(d, dim=DIM, fsync="always", seed=7)
    dur.ingest(range(150), rows, ledger_key="doc0")
    dur.train(nlist=8, seed=7)
    dur.close()

    assert ann_cli.run(_cli_args(["verify", "--dir", d])) == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True

    assert ann_cli.run(_cli_args(["snapshot", "--dir", d, "--dim", str(DIM)])) == 0
    st = json.loads(capsys.readouterr().out)
    assert st["snapshot_count"] == 1 and st["rows"] == 150

    assert ann_cli.run(_cli_args(["restore", "--dir", d, "--dim", str(DIM)])) == 0
    st = json.loads(capsys.readouterr().out)
    assert st["recovered"] and st["rows"] == 150 and st["retrain_advised"] is False

    # corrupt an artifact: verify must exit non-zero (satellite 2's contract)
    snaps = os.listdir(os.path.join(d, "snapshots"))
    snap = os.path.join(d, "snapshots", sorted(snaps)[0])
    victim = next(
        os.path.join(snap, n) for n in sorted(os.listdir(snap)) if n.endswith(".npy")
    )
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    assert ann_cli.run(_cli_args(["verify", "--dir", d])) == 1
    assert json.loads(capsys.readouterr().out)["ok"] is False


# ----------------------------------------------------------------- metrics
def test_durability_gauges_rendered(tmp_path):
    from django_assistant_bot_tpu.rag import index_registry
    from django_assistant_bot_tpu.serving.obs import (
        _Exposition,
        _render_rag_plane,
        parse_prometheus_text,
    )

    rows = _corpus(150)
    dur = DurableANN(str(tmp_path / "d"), dim=DIM, fsync="always", seed=7)
    dur.ingest(range(150), rows, ledger_key="doc0")
    dur.train(nlist=8, seed=7)
    dur.snapshot()
    index_registry.reset_indexes()
    try:
        with index_registry._lock:
            index_registry._indexes[("Question", "embedding")] = dur
        x = _Exposition()
        _render_rag_plane(x)
        fams = parse_prometheus_text(x.render())
        lab = {"index": "Question.embedding"}
        assert fams["dabt_ann_wal_records"]["samples"][0][1:] == (lab, 2.0)
        assert fams["dabt_ann_snapshot_age_s"]["samples"][0][1] == lab
        assert fams["dabt_ann_writable"]["samples"][0][2] == 1.0
        assert fams["dabt_ann_snapshot_count"]["samples"][0][2] == 1.0
        assert fams["dabt_ann_snapshot_fallbacks_total"]["samples"][0][2] == 0.0
        assert fams["dabt_ann_ledger_entries"]["samples"][0][2] == 1.0
    finally:
        index_registry.reset_indexes()
        dur.close()


# ---------------------------------------------------------------- registry
def test_registry_routes_durable_and_ingest_document(tmp_db, tmp_path):
    import asyncio

    from django_assistant_bot_tpu.ai.providers.echo import HashEmbedder
    from django_assistant_bot_tpu.conf import settings
    from django_assistant_bot_tpu.rag.index_registry import (
        get_index,
        ingest_document,
        invalidate_index,
        remove_rows,
        reset_indexes,
    )
    from django_assistant_bot_tpu.storage import models

    reset_indexes()
    bot = models.Bot.objects.create(codename="dur-bot")
    wiki = models.WikiDocument.objects.create(bot=bot, title="w")
    doc = models.Document.objects.create(wiki=wiki, name="d0", content="c")
    emb = HashEmbedder(dim=settings.EMBEDDING_DIM)
    center = np.asarray(asyncio.run(emb.embeddings(["topic"]))[0])
    rng = np.random.default_rng(0)
    for i in range(24):
        models.Question.objects.create(
            document=doc, text=f"q{i}", order=i,
            embedding=(center + rng.normal(size=center.shape) * 0.05).astype(np.float32),
        )
    try:
        with settings.override(
            ANN_THRESHOLD=1, ANN_DURABLE_DIR=str(tmp_path / "durable")
        ):
            idx = get_index(models.Question)
            assert isinstance(idx, DurableANN) and idx.writable and len(idx) == 24

            doc2 = models.Document.objects.create(wiki=wiki, name="d1", content="c")
            ids2, vecs2 = [], []
            for i in range(6):
                q = models.Question.objects.create(
                    document=doc2, text=f"r{i}", order=i,
                    embedding=(center + rng.normal(size=center.shape) * 0.05).astype(np.float32),
                )
                ids2.append(q.id)
                vecs2.append(q.embedding)
            key = f"Question:{doc2.id}:{max(ids2)}:{len(ids2)}"
            assert ingest_document(models.Question, "embedding", key, ids2, np.stack(vecs2))
            # a worker re-run after crash: same key no-ops on the ledger
            assert not ingest_document(models.Question, "embedding", key, ids2, np.stack(vecs2))
            # the in-place ingest adopted its own generation: NO rebuild
            assert get_index(models.Question) is idx and len(idx) == 30

            drop = ids2[:2]
            for q in models.Question.objects.filter(id__in=drop):
                q.delete()
            remove_rows(models.Question, "embedding", drop)
            assert get_index(models.Question) is idx and len(idx) == 28

            # an EXTERNAL invalidation (another worker moved the DB): this
            # process owns the flock, so refresh reconciles in place rather
            # than deadlocking into a read-only second instance
            invalidate_index(models.Question)
            assert get_index(models.Question) is idx
    finally:
        reset_indexes()
        idx.close()


# -------------------------------------------------------------- kill-replay
@pytest.mark.slow
def test_durable_kill_replay_subprocess():
    """The headline chaos bench, as CI's smoke: a child process is SIGKILLed
    mid-ingest, the parent recovers the directory and must reproduce the
    child's last fsynced pre-crash top-k exactly, with zero duplicate
    vectors, and a full re-run of the ingest loop must dedup every
    already-applied document (bench.bench_durable is the single
    implementation the bench record and this test share)."""
    import bench

    out = bench.bench_durable()
    assert out["durable_recovered_docs"] >= 8
    assert out["durable_recovered_docs"] < out["durable_ingested_docs"]
    assert out["durable_topk_identical"] is True
    assert out["durable_duplicate_vectors"] == 0
    assert out["durable_resume_dedup_docs"] == out["durable_recovered_docs"]
    assert out["durable_recovery_s"] < 60


def test_wal_record_header_layout_pinned():
    """The on-disk header is a contract (docs/DURABILITY.md): magic u32, seq
    u64, type u8, payload-len u32, crc u32 — little-endian, 21 bytes."""
    assert _HDR.size == struct.calcsize("<IQBII") == 21
    assert (REC_APPEND, REC_TOMBSTONE, REC_INSTALL) == (1, 2, 3)
