"""Test bootstrap: force an 8-device fake CPU mesh BEFORE jax is imported anywhere.

This is the multi-chip test strategy SURVEY.md §4 calls for: the reference tests its
distributed (Celery) path by direct function invocation; we do better — every sharding
test runs against a real 8-device mesh with XLA collectives, on CPU.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon sitecustomize force-registers the TPU plugin and overrides jax_platforms
# via jax.config — env vars alone are not enough; override the config back before any
# backend initialisation.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: no such option — the XLA_FLAGS device-count override above
    # (read at backend init) provides the 8-device CPU mesh on its own
    pass

# The suite's wall-clock is dominated by XLA compiles of the SAME tiny shapes
# repeated across modules and runs; the persistent compile cache (the same
# wiring bench.py and a production `serve` boot use) makes warm runs fit the
# tier-1 time budget.  Tests assert on numerics and behavior, never on
# compile-time, so cached executables change nothing observable; set
# DABT_COMPILE_CACHE_OFF=1 for a cold-compile measurement run.
from django_assistant_bot_tpu.utils.compile_cache import (  # noqa: E402
    enable_persistent_compile_cache,
)

enable_persistent_compile_cache()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from django_assistant_bot_tpu.parallel import best_mesh_shape, make_mesh

    n = len(jax.devices())
    return make_mesh(best_mesh_shape(n, want_model=2, want_seq=2))


@pytest.fixture()
def tmp_db(tmp_path, monkeypatch):
    """Fresh sqlite database per test."""
    db_path = tmp_path / "dabt.sqlite3"
    monkeypatch.setenv("DABT_DB_PATH", str(db_path))
    from django_assistant_bot_tpu.storage import db

    db.reset_default_database()
    yield db.get_database()
    db.reset_default_database()
