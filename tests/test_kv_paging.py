"""Paged, prefix-shared KV memory plane (docs/KV_PAGING.md).

Four layers of evidence, all CPU so tier-1 gates the tentpole without
hardware:

- allocator unit + property tests (host-side page bookkeeping: alloc/free,
  COW refcounts, LRU eviction under the byte budget, out-of-pages behavior);
- op-level: the block-table gather decode attention is BIT-identical to the
  contiguous chunked read when pages mirror chunks, including fp8 pools and
  shuffled page placement;
- engine-level byte-identity: paged vs legacy engines over the same params
  and seed produce identical token ids for greedy + sampled traffic, ragged
  lengths, fp8 KV, and the chunked-prefill path;
- the serving contract: prefix sharing survives a sharer freeing mid-decode,
  crash-only restart rebuilds a clean pool, and the scheduler sheds on KV
  pressure with its own 429 reason.
"""

import asyncio
import os
import random
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.ops.attention import (
    chunked_gqa_decode_attention,
    paged_gqa_decode_attention,
)
from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine
from django_assistant_bot_tpu.serving.kv_pool import PageAllocator
from django_assistant_bot_tpu.serving.scheduler import (
    RequestScheduler,
    SchedulerConfig,
    SchedulerRejected,
)


# --------------------------------------------------------------- allocator
def test_allocator_alloc_free_roundtrip():
    al = PageAllocator(8, 64)
    a = al.alloc(3)
    b = al.alloc(5)
    assert sorted(a + b) == list(range(8))
    assert al.alloc(1) is None  # exhausted -> None, nothing allocated
    al.decref(a)
    c = al.alloc(3)
    assert sorted(c) == sorted(a)  # freed pages come back
    assert al.pages_free == 0 + (8 - 5 - 3)


def test_allocator_refcounts_shared_pages_survive_owner():
    al = PageAllocator(8, 64, max_shared_entries=4)
    pages = al.alloc(2)
    assert al.register([1] * 100, 100, pages)  # 100 tokens -> 2 pages of 64
    al.decref(pages)  # owner frees; registry still holds its refs
    assert al.pages_free == 6
    hit = al.lookup([1] * 120, 100)
    assert hit is not None and hit.length == 100 and hit.full_pages == 1
    # evicting the entry releases the last refs
    al.reset()
    assert al.pages_free == 8


def test_allocator_lru_eviction_under_byte_budget():
    # page_bytes=10, budget 25 -> at most 2 single-page entries fit
    al = PageAllocator(
        8, 64, page_bytes=10, max_shared_bytes=25, max_shared_entries=8,
        min_prefix_tokens=1,
    )
    owners = []
    for i in range(3):
        p = al.alloc(1)
        owners.append(p)
        assert al.register([i] * 40, 40, p)
    assert al.evictions == 1  # the first entry LRU-evicted past the budget
    assert al.lookup([0] * 50, 40) is None
    assert al.lookup([2] * 50, 40) is not None


def test_allocator_on_demand_eviction_feeds_alloc():
    al = PageAllocator(4, 64, max_shared_entries=8, min_prefix_tokens=1)
    p = al.alloc(2)
    assert al.register([9] * 80, 80, p)
    al.decref(p)  # only the registry holds them now
    assert al.pages_free == 2
    assert al.available() == 4  # 2 free + 2 evictable
    got = al.alloc(4)  # forces the entry out
    assert got is not None and len(got) == 4
    assert al.evictions == 1
    assert al.lookup([9] * 90, 80) is None


def test_allocator_eviction_during_alloc_spares_pinned_pages():
    """The admit sequence pins a hit's pages (incref) BEFORE alloc: alloc's
    on-demand eviction may then drop the entry, but the pinned pages must
    neither free nor be handed back as 'fresh' pages of the same request."""
    al = PageAllocator(6, 64, max_shared_entries=4, min_prefix_tokens=1)
    p = al.alloc(2)
    al.register([7] * 80, 80, p)
    al.decref(p)  # registry-only-held now
    held = al.alloc(3)  # free list down to 1
    hit = al.lookup([7] * 100, 80)
    al.incref(hit.pages)  # the pin
    # needs 2, free holds 1: eviction fires but the PINNED pages survive it —
    # they are neither freed nor handed back, so the alloc correctly fails
    # (the engine then falls back to a full prefill without the hit)
    assert al.alloc(2) is None
    assert al.evictions == 1
    with al._lock:
        assert all(page in al._refs for page in hit.pages)
    al.decref(list(hit.pages))  # unpin: NOW the pages free
    got = al.alloc(2)
    assert got is not None and set(got) >= set(hit.pages) - set(held)
    al.decref(got)
    al.decref(held)
    assert al.pages_free == 6


def test_engine_falls_back_to_full_prefill_when_hit_blocks_alloc():
    """Engine corner: the hit's pinned pages are exactly what eviction would
    need — admission must drop the hit and run a full prefill (correct
    output, no wedged queue head) instead of waiting forever."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(13))
    rng = np.random.default_rng(14)
    prefix = rng.integers(1, 255, 150).tolist()  # 3 pages of 64 (2 full + 1)
    p_a = prefix + rng.integers(1, 255, 20).tolist()  # 178-token demand: 3 pages
    p_b = prefix + rng.integers(1, 255, 60).tolist()  # 218-token demand: 4 pages

    def run(prefix_cache):
        eng = GenerationEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=256,
            decode_kv_chunk=64, prefix_cache_size=prefix_cache,
            prefix_min_tokens=16, kv_layout="paged", kv_pages=4,
        ).start()
        try:
            ra = eng.submit(
                p_a, max_tokens=8, temperature=0.0, prefix_len=len(prefix)
            ).result(timeout=300)
            rb = eng.submit(
                p_b, max_tokens=8, temperature=0.0, prefix_len=len(prefix)
            ).result(timeout=300)
            return (ra.token_ids, rb.token_ids), eng.kv_stats()
        finally:
            eng.stop()

    ref, _ = run(0)
    got, stats = run(8)
    assert got == ref
    # the hit could not be used (4-page demand vs 1 free + its own pinned
    # pages): the registry entry was evicted to make room for a full prefill
    assert stats["kv_evictions"] >= 1


def test_allocator_out_of_pages_is_atomic():
    al = PageAllocator(4, 64)
    held = al.alloc(3)
    assert al.alloc(2) is None
    assert al.pages_free == 1  # the failed alloc took nothing
    al.decref(held)


def test_allocator_longest_prefix_match_and_lru_touch():
    al = PageAllocator(16, 4, max_shared_entries=8, min_prefix_tokens=1)
    short = al.alloc(1)
    al.register([1, 2, 3], 3, short)
    long_pages = al.alloc(2)
    al.register([1, 2, 3, 4, 5], 5, long_pages)
    hit = al.lookup([1, 2, 3, 4, 5, 6, 7], 5)
    assert hit.length == 5  # longest match wins
    hit = al.lookup([1, 2, 3, 9, 9], 3)
    assert hit.length == 3


def test_allocator_property_fuzz_invariants():
    """Pinned-seed fuzz: random alloc/decref/register/lookup/evict traffic
    must keep the bookkeeping invariants — no page both free and referenced,
    free + used == total, failed allocs change nothing.  The seed is
    overridable (DABT_KV_FUZZ_SEED) so CI can pin it."""
    seed = int(os.environ.get("DABT_KV_FUZZ_SEED", "0"))
    rng = random.Random(seed)
    al = PageAllocator(
        32, 16, page_bytes=7, max_shared_bytes=70, max_shared_entries=5,
        min_prefix_tokens=1,
    )
    held = []  # lists of pages we hold refs on
    for _step in range(2000):
        op = rng.random()
        if op < 0.4:
            n = rng.randint(1, 6)
            before = al.pages_free
            got = al.alloc(n)
            if got is None:
                assert al.pages_free < n  # truly couldn't satisfy; took nothing
            else:
                held.append(got)
        elif op < 0.7 and held:
            al.decref(held.pop(rng.randrange(len(held))))
        elif op < 0.85 and held:
            pages = held[rng.randrange(len(held))]
            toks = rng.randrange(1 << 20)
            length = len(pages) * al.page_size - rng.randint(0, al.page_size - 1)
            al.register([toks] * length, length, pages)
        else:
            al.lookup([rng.randrange(4)] * rng.randint(1, 40), 8)
        # invariants
        free = al.pages_free
        with al._lock:
            refd = set(al._refs)
            free_set = set(al._free)
        assert not (refd & free_set)
        assert len(free_set) == free
        assert len(refd) + free == al.n_pages
        for pages in held:
            for p in pages:
                assert p in refd
    for pages in held:
        al.decref(pages)


# ---------------------------------------------------------------- op level
@pytest.mark.parametrize("dtype", [None, jnp.float8_e4m3fn])
def test_paged_attention_bit_identical_to_chunked(dtype):
    """Pages mirroring a contiguous cache's chunks (shuffled physical
    placement) -> bit-identical output to the contiguous chunked read."""
    rng = np.random.default_rng(1)
    B, H, KH, S, D, page = 5, 8, 2, 256, 16, 64
    NB = S // page
    q = jnp.asarray(rng.normal(size=(B, H, 1, D)).astype(np.float32))
    k = rng.normal(size=(B, KH, S, D)).astype(np.float32)
    v = rng.normal(size=(B, KH, S, D)).astype(np.float32)
    positions = jnp.asarray([0, 63, 64, 130, 255], jnp.int32)
    kd = jnp.asarray(k).astype(dtype) if dtype else jnp.asarray(k)
    vd = jnp.asarray(v).astype(dtype) if dtype else jnp.asarray(v)
    contiguous = chunked_gqa_decode_attention(q, kd, vd, positions, chunk=page)

    # scatter the rows' chunks into a shuffled pool; extra pages hold garbage
    P = B * NB + 3
    perm = rng.permutation(B * NB)
    pool_k = rng.normal(size=(P, KH, page, D)).astype(np.float32)
    pool_v = rng.normal(size=(P, KH, page, D)).astype(np.float32)
    bt = np.full((B, NB), P, np.int32)
    for b in range(B):
        for j in range(NB):
            phys = int(perm[b * NB + j])
            pool_k[phys] = k[b, :, j * page : (j + 1) * page].transpose(0, 1, 2)
            pool_v[phys] = v[b, :, j * page : (j + 1) * page]
            bt[b, j] = phys
    pk = jnp.asarray(pool_k).astype(dtype) if dtype else jnp.asarray(pool_k)
    pv = jnp.asarray(pool_v).astype(dtype) if dtype else jnp.asarray(pool_v)
    paged = paged_gqa_decode_attention(
        q, pk, pv, jnp.asarray(bt), positions
    )
    np.testing.assert_array_equal(np.asarray(contiguous), np.asarray(paged))


def test_paged_attention_masks_unallocated_blocks():
    """Logical blocks past a row's allocation gather garbage (clamped page 0)
    — NaN poison there must never reach the output."""
    rng = np.random.default_rng(2)
    B, H, KH, page, NB, D = 2, 4, 2, 32, 4, 8
    P = 4
    q = jnp.asarray(rng.normal(size=(B, H, 1, D)).astype(np.float32))
    pool_k = rng.normal(size=(P, KH, page, D)).astype(np.float32)
    pool_v = rng.normal(size=(P, KH, page, D)).astype(np.float32)
    pool_k[0] = np.nan  # page 0 is what sentinel gathers clamp onto
    pool_v[0] = np.nan
    bt = np.full((B, NB), P, np.int32)  # everything unallocated...
    bt[0, 0], bt[1, 0] = 1, 2  # ...except each row's first block
    positions = jnp.asarray([10, 20], jnp.int32)
    out = paged_gqa_decode_attention(
        q, jnp.asarray(pool_k), jnp.asarray(pool_v), jnp.asarray(bt), positions
    )
    assert not np.any(np.isnan(np.asarray(out)))


def test_decode_step_paged_matches_chunked_ragged():
    """Model level: decode_step_paged == decode_step(kv_chunk=page) for a
    ragged batch, bit-exact, and lengths advance identically."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    rng = np.random.default_rng(3)
    B, S, page = 4, 256, 64
    NB = S // page
    lengths = np.asarray([3, 63, 64, 200], np.int32)
    KH, D, L = cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    k = rng.normal(size=(L, B, KH, S, D)).astype(np.float32)
    v = rng.normal(size=(L, B, KH, S, D)).astype(np.float32)
    cache = llama.KVCache(
        k=jnp.asarray(k), v=jnp.asarray(v), lengths=jnp.asarray(lengths)
    )
    # identical content as a paged pool with identity-ish block tables
    P = B * NB
    pool_k = k.transpose(1, 0, 2, 3, 4).reshape(B, L, KH, NB, page, D)
    pool_k = pool_k.transpose(1, 0, 3, 2, 4, 5).reshape(L, P, KH, page, D)
    pool_v = v.transpose(1, 0, 2, 3, 4).reshape(B, L, KH, NB, page, D)
    pool_v = pool_v.transpose(1, 0, 3, 2, 4, 5).reshape(L, P, KH, page, D)
    bt = np.arange(P, dtype=np.int32).reshape(B, NB)
    paged = llama.PagedKVCache(
        k=jnp.asarray(pool_k), v=jnp.asarray(pool_v), lengths=jnp.asarray(lengths)
    )
    toks = jnp.asarray([7, 11, 13, 17], jnp.int32)
    lg_a, ca = llama.decode_step(params, cfg, toks, cache, kv_chunk=page)
    lg_b, cb = llama.decode_step_paged(params, cfg, toks, paged, jnp.asarray(bt))
    np.testing.assert_array_equal(np.asarray(lg_a), np.asarray(lg_b))
    np.testing.assert_array_equal(np.asarray(ca.lengths), np.asarray(cb.lengths))


# ------------------------------------------------------- engine byte-identity
def _drive(eng, futs, limit=4000):
    """Single-threaded deterministic engine loop (no engine thread): every
    request is queued before the first admission, so both layouts see the
    identical wave structure and tick schedule."""
    steps = 0
    while not all(f.done() for f in futs):
        eng._reap_dead_slots()
        eng._admit()
        if eng._chunking is not None:
            eng._chunk_step()
        if eng.num_active > 0:
            eng._issue_tick()
        while eng._inflight and (
            len(eng._inflight) > eng.lookahead or eng.num_active == 0
        ):
            eng._process_tick()
        steps += 1
        assert steps < limit, "engine made no progress"


def _run_layout(cfg, params, prompts, layout, *, kv_dtype=None, chunk_size=512):
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=256,
        chunk_size=chunk_size, decode_kv_chunk=64, prefix_cache_size=0,
        kv_layout=layout, kv_cache_dtype=kv_dtype,
    )
    assert eng.paged == (layout == "paged")
    eng._running = True
    futs = [
        eng.submit(
            p, max_tokens=12, temperature=(0.9 if i % 2 else 0.0), top_p=0.9
        )
        for i, p in enumerate(prompts)
    ]
    _drive(eng, futs)
    eng._running = False
    return [f.result(timeout=0).token_ids for f in futs]


@pytest.mark.parametrize("quantize", [False, True])
@pytest.mark.parametrize("kv_dtype", [None, "fp8"])
def test_engine_paged_byte_identical_to_legacy(quantize, kv_dtype):
    """The acceptance criterion: greedy + sampled traffic over ragged prompt
    lengths, int8 and bf16 weights, bf16 and fp8 KV — identical token ids."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    if quantize:
        from django_assistant_bot_tpu.ops.quant import quantize_decoder_params

        params = quantize_decoder_params(params)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 255, n).tolist() for n in (9, 33, 65, 100)]
    legacy = _run_layout(cfg, params, prompts, "legacy", kv_dtype=kv_dtype)
    paged = _run_layout(cfg, params, prompts, "paged", kv_dtype=kv_dtype)
    assert legacy == paged


def test_engine_paged_chunked_prefill_byte_identical():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(1))
    rng = np.random.default_rng(8)
    prompts = [rng.integers(1, 255, 200).tolist()]
    legacy = _run_layout(cfg, params, prompts, "legacy", chunk_size=64)
    paged = _run_layout(cfg, params, prompts, "paged", chunk_size=64)
    assert legacy == paged


# --------------------------------------------------------- prefix sharing
def _prefix_engine(cfg, params, prefix_cache, **kw):
    return GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=256,
        decode_kv_chunk=64, prefix_cache_size=prefix_cache,
        prefix_min_tokens=16, kv_layout="paged", **kw,
    )


def test_paged_prefix_share_matches_uncached_reference():
    """Shared-prefix traffic (the reference's per-bot system prompt shape):
    cached pages + COW boundary clone must reproduce the no-cache outputs,
    with hits and COW clones actually recorded."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(2))
    rng = np.random.default_rng(9)
    prefix = rng.integers(1, 255, 70).tolist()  # 1 full 64-page + a partial
    prompts = [prefix + rng.integers(1, 255, 20).tolist() for _ in range(3)]

    def run(prefix_cache):
        eng = _prefix_engine(cfg, params, prefix_cache).start()
        try:
            out = [
                eng.submit(
                    p, max_tokens=8, temperature=0.0, prefix_len=len(prefix)
                ).result(timeout=300).token_ids
                for p in prompts  # serial: first registers, later ones hit
            ]
            return out, eng.kv_stats()
        finally:
            eng.stop()

    ref, _ = run(0)
    got, stats = run(8)
    assert got == ref
    assert stats["prefix_hits"] == 2
    assert stats["kv_cow_copies"] == 2  # the 70-token prefix has a partial page
    assert stats["kv_shared_pages"] == 2
    assert stats["kv_shared_page_frac"] > 0


def test_paged_prefix_sharer_survives_other_freeing():
    """One sharer finishes (and releases its refs) while another keeps
    decoding over the same shared pages — the survivor's output must stay on
    the uncached reference path, and the registry keeps the pages alive."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(2))
    rng = np.random.default_rng(10)
    prefix = rng.integers(1, 255, 70).tolist()
    p_long = prefix + rng.integers(1, 255, 20).tolist()
    p_short = prefix + rng.integers(1, 255, 20).tolist()

    ref_eng = _prefix_engine(cfg, params, 0).start()
    try:
        ref = ref_eng.submit(
            p_long, max_tokens=24, temperature=0.0, prefix_len=len(prefix)
        ).result(timeout=300).token_ids
    finally:
        ref_eng.stop()

    eng = _prefix_engine(cfg, params, 8).start()
    try:
        eng.submit(
            p_long[: len(prefix) + 1], max_tokens=2, temperature=0.0,
            prefix_len=len(prefix),
        ).result(timeout=300)  # registers the prefix
        f_long = eng.submit(
            p_long, max_tokens=24, temperature=0.0, prefix_len=len(prefix)
        )
        f_short = eng.submit(
            p_short, max_tokens=2, temperature=0.0, prefix_len=len(prefix)
        )
        f_short.result(timeout=300)  # finishes first, decrefs its pages
        assert f_long.result(timeout=300).token_ids == ref
        free_after = eng.kv_stats()["kv_pages_free"]
        assert free_after > 0  # the short sharer's private pages came back
    finally:
        eng.stop()


def test_paged_pool_accounting_returns_to_empty():
    """After every request finishes, only registry-held pages stay out of the
    free list — no leaks from the admit/finish/reap paths."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(2))
    rng = np.random.default_rng(11)
    eng = _prefix_engine(cfg, params, 0).start()
    try:
        futs = [
            eng.submit(rng.integers(1, 255, 30).tolist(), max_tokens=5,
                       temperature=0.0)
            for _ in range(6)
        ]
        for f in futs:
            f.result(timeout=300)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = eng.kv_stats()
            if st["kv_pages_used"] == 0:
                break
            time.sleep(0.02)
        assert eng.kv_stats()["kv_pages_used"] == 0
    finally:
        eng.stop()


# ------------------------------------------------- restart + KV admission
def test_restart_rebuilds_clean_pool():
    """Crash-only _restart: allocator reset (every page free, registry
    emptied), block tables unallocated — and the engine still serves."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(3))
    rng = np.random.default_rng(12)
    prefix = rng.integers(1, 255, 70).tolist()
    eng = _prefix_engine(cfg, params, 8).start()
    try:
        eng.submit(
            prefix + [5, 6, 7], max_tokens=3, temperature=0.0,
            prefix_len=len(prefix),
        ).result(timeout=300)
        assert eng.kv_stats()["kv_shared_pages"] > 0
        with eng._iter_lock:
            eng._restart(RuntimeError("injected"))
        st = eng.kv_stats()
        assert st["kv_pages_used"] == 0
        assert st["kv_shared_pages"] == 0
        assert np.all(eng._block_tables == eng._kv_sentinel)
        r = eng.submit([1, 2, 3], max_tokens=3, temperature=0.0).result(
            timeout=300
        )
        assert len(r.token_ids) == 3
    finally:
        eng.stop()


def test_scheduler_kv_pressure_policy_deterministic():
    """Policy level, no engine/timing: a request that cannot start now
    (demand > obtainable pages minus queued reservations) and whose projected
    KV wait exceeds admit_max_wait_s sheds with reason=kv_pressure, counted
    separately from queue_full; either condition alone admits."""
    sched = RequestScheduler(
        SchedulerConfig(max_queue=64, admit_max_wait_s=1.0), slots=2
    )
    avail = {"pages": 0}
    sched.bind_kv(lambda: avail["pages"], 4)
    for _ in range(100):
        sched.note_service(5.0)  # one pool drain ~ 5 s >> the 1 s ceiling
    adm = sched.try_admit("interactive", None, kv_pages=2)
    assert not adm.ok
    assert adm.reason == "kv_pressure" and adm.retry_after_s > 0
    assert sched.shed["kv_pressure"] == 1
    assert sched.shed.get("queue_full", 0) == 0
    # pages obtainable -> admitted despite the projected wait
    avail["pages"] = 4
    adm = sched.try_admit("interactive", None, kv_pages=2)
    assert adm.ok
    assert sched.stats()["queued_kv_pages"] == 2
    # zero-demand (legacy layout) requests never consult the KV test
    avail["pages"] = 0
    adm = sched.try_admit("interactive", None, kv_pages=0)
    assert adm.reason != "kv_pressure"  # (may still shed on depth est-wait)


def test_engine_sheds_on_kv_pressure_end_to_end():
    """Engine level: pool-sized requests in flight (pinned slow via the fault
    injector so they cannot finish under the test), the next submit sheds
    synchronously with reason=kv_pressure."""
    from django_assistant_bot_tpu.serving.faults import FaultInjector

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(4))
    sched = RequestScheduler(
        SchedulerConfig(max_queue=64, admit_max_wait_s=1.0)
    )
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=256,
        decode_kv_chunk=128, prefix_cache_size=0, kv_layout="paged",
        scheduler=sched,
        faults=FaultInjector({"slow_tick": {"every": 1, "delay_s": 0.02}}),
    ).start()
    try:
        holds = [
            eng.submit([b] * 100, max_tokens=200, temperature=0.0)
            for b in (1, 2)
        ]  # 2 pages each -> the whole 4-page pool
        deadline = time.monotonic() + 30
        while eng.kv_stats()["kv_pages_free"] > 0:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # pump the service EMA only now (the holds are slotted, the queue is
        # empty) so the depth-based est-wait test stays quiet and the shed
        # below is attributable to KV pressure alone
        for _ in range(100):
            sched.note_service(5.0)
        with pytest.raises(SchedulerRejected) as ei:
            eng.submit([3] * 100, max_tokens=200, temperature=0.0)
        assert ei.value.reason == "kv_pressure"
        assert sched.shed["kv_pressure"] == 1
        for f in holds:
            f.cancel()
    finally:
        eng.stop()


def test_scheduler_kv_pressure_still_queues_modest_backlog():
    """The KV test must NOT shed ordinary queueing: small-demand requests
    behind a busy engine queue as before (the default factor allows one full
    pool drain of backlog)."""
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(4))
    sched = RequestScheduler(SchedulerConfig(max_queue=64))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=1, max_seq_len=256,
        decode_kv_chunk=64, prefix_cache_size=0, scheduler=sched,
    ).start()
    try:
        futs = [
            eng.submit([1, 2, 3, i], max_tokens=8, temperature=0.0)
            for i in range(4)
        ]
        for f in futs:
            f.result(timeout=300)
        assert sched.shed.get("kv_pressure", 0) == 0
    finally:
        eng.stop()


def test_kv_pressure_429_reason_on_the_wire():
    """The shed reason reaches the HTTP 429 body (the operator-visible
    contract)."""
    from aiohttp.test_utils import TestClient, TestServer

    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec
    from django_assistant_bot_tpu.serving.server import create_app

    registry = ModelRegistry(
        {
            "tiny-chat": ModelSpec(
                name="tiny-chat", kind="decoder", tiny=True, max_slots=2,
                max_seq_len=256, sched_admit_max_wait_s=1.0,
                faults={"slow_tick": {"every": 1, "delay_s": 0.02}},
            )
        }
    )

    async def drive():
        eng = registry.get_generator("tiny-chat")
        client = TestClient(TestServer(create_app(registry)))
        await client.start_server()
        try:
            holds = [
                eng.submit([b] * 100, max_tokens=200, temperature=0.0)
                for b in (1, 2)
            ]
            deadline = time.monotonic() + 30
            while eng.kv_stats()["kv_pages_free"] > 0:
                assert time.monotonic() < deadline
                await asyncio.sleep(0.01)
            for _ in range(100):
                eng.scheduler.note_service(5.0)
            r = await client.post(
                "/dialog/",
                json={
                    "model": "tiny-chat",
                    "messages": "x" * 120,
                    "max_tokens": 200,
                },
            )
            assert r.status == 429
            body = await r.json()
            assert body["reason"] == "kv_pressure"
            assert "Retry-After" in r.headers
            for f in holds:
                f.cancel()
        finally:
            await client.close()

    try:
        asyncio.new_event_loop().run_until_complete(drive())
    finally:
        registry.stop()


# ------------------------------------------------------------- knobs/shims
def test_engine_kv_knob_validation_and_fallbacks():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(6))
    tok = ByteTokenizer()
    with pytest.raises(ValueError, match="kv_layout"):
        GenerationEngine(cfg, params, tok, max_slots=1, kv_layout="huh")
    # page size aligns with the decode chunk by default
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=256, decode_kv_chunk=64
    )
    assert eng.paged and eng.kv_page_size == 64
    assert eng._kv_pool.n_pages == 2 * (256 // 64)  # byte parity default
    # decode_kv_chunk=None still pages (its own auto size)
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=256, decode_kv_chunk=None
    )
    assert eng.paged and eng.kv_page_size == 128
    # speculative engines run the paged plane natively (the tree verify
    # commits the accepted path through the block table) — no fallback,
    # requested == effective
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=256, speculative=2
    )
    assert eng.paged
    ks = eng.kv_stats()
    assert ks["kv_layout_requested"] == "paged"
    assert ks["kv_layout_effective"] == "paged"
    with pytest.raises(ValueError, match="kv_pages"):
        GenerationEngine(
            cfg, params, tok, max_slots=2, max_seq_len=256,
            decode_kv_chunk=64, kv_pages=2,  # < one max-length request
        )


def test_modelspec_prefix_cache_size_shim():
    from django_assistant_bot_tpu.serving.registry import ModelSpec

    spec = ModelSpec.from_dict(
        "m", {"kind": "decoder", "tiny": True, "prefix_cache_size": 3}
    )
    assert spec.prefix_cache == 3
    # explicit new-name knob wins over the deprecated alias
    spec = ModelSpec.from_dict(
        "m",
        {"kind": "decoder", "tiny": True, "prefix_cache_size": 3,
         "prefix_cache": 5},
    )
    assert spec.prefix_cache == 5


def test_tick_stats_and_healthz_carry_kv_gauges():
    from aiohttp.test_utils import TestClient, TestServer

    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec
    from django_assistant_bot_tpu.serving.server import create_app

    registry = ModelRegistry(
        {
            "tiny-chat": ModelSpec(
                name="tiny-chat", kind="decoder", tiny=True, max_slots=2,
                max_seq_len=256,
            )
        }
    )

    async def drive():
        client = TestClient(TestServer(create_app(registry)))
        await client.start_server()
        try:
            r = await client.get("/healthz")
            body = await r.json()
            kv = body["generators"]["tiny-chat"]["kv"]
            assert kv["kv_layout"] == "paged"
            for key in ("kv_pages_used", "kv_pages_free", "kv_shared_page_frac",
                        "kv_evictions", "kv_cow_copies"):
                assert key in kv
        finally:
            await client.close()

    try:
        asyncio.new_event_loop().run_until_complete(drive())
        eng = registry.get_generator("tiny-chat")
        assert eng.tick_stats()["kv"]["kv_layout"] == "paged"
    finally:
        registry.stop()
