"""Ops correctness: flash kernel vs reference, ring attention on the 8-device mesh,
sampling semantics, norms/rope vs straightforward numpy."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from django_assistant_bot_tpu.ops import (
    dot_product_attention,
    flash_attention,
    layer_norm,
    ring_attention,
    rms_norm,
    sample_logits,
)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    B, H, S, D = 2, 4, 256, 64
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(qkv, causal):
    q, k, v = qkv
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("window", [64, 100, 256])
def test_flash_sliding_window_matches_reference(qkv, window):
    """Windowed flash (block-skip + in-block band) vs the jnp banded path."""
    q, k, v = qkv
    ref = dot_product_attention(q, k, v, causal=True, window=window)
    out = flash_attention(q, k, v, causal=True, window=window, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("window", [64, 150])
def test_flash_window_block_skip(window):
    """S >> window: late q-blocks start their kv loop past block 0
    (first_iter > 0) — exercises the skip arithmetic, not just the in-block
    band (S=512, block_kv=128: q-block 3 skips >= 1 kv block for W<=257)."""
    rng = np.random.default_rng(3)
    B, H, S, D = 1, 2, 512, 64
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True, window=window)
    out = flash_attention(q, k, v, causal=True, window=window, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_window_mask_semantics():
    """keep iff kpos > qpos - W (HF sliding_window_overlay): with W=1 every
    query sees only itself, so softmax returns exactly its own value row."""
    rng = np.random.default_rng(2)
    B, H, S, D = 1, 1, 8, 4
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    out = dot_product_attention(q, k, v, causal=True, window=1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(v), atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(qkv, mesh8, causal):
    q, k, v = qkv
    ref = dot_product_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh8, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_decode_attention_with_offset():
    """q_offset makes single-token decode equal the last row of full attention."""
    rng = np.random.default_rng(1)
    B, H, S, D = 1, 2, 16, 8
    q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
    full = dot_product_attention(q, k, v, causal=True)
    last = dot_product_attention(q[:, :, -1:], k, v, causal=True, q_offset=S - 1)
    np.testing.assert_allclose(np.asarray(last[:, :, 0]), np.asarray(full[:, :, -1]), atol=1e-5)


def test_sample_greedy_and_temperature():
    logits = jnp.asarray([[0.0, 5.0, 1.0], [9.0, 0.0, 0.0]], jnp.float32)
    toks = sample_logits(logits, jax.random.key(0), temperature=0.0, top_k=0, top_p=1.0)
    assert toks.tolist() == [1, 0]
    # mixed greedy/sampled batch compiles as one call
    toks = sample_logits(
        logits, jax.random.key(0), temperature=jnp.asarray([0.0, 1.0]), top_k=2, top_p=0.9
    )
    assert toks[0] == 1


def test_top_p_restricts_support():
    # one dominant token, p small -> always that token even at high temperature
    logits = jnp.asarray([[10.0, 0.0, 0.0, 0.0]], jnp.float32)
    for i in range(5):
        t = sample_logits(logits, jax.random.key(i), temperature=2.0, top_k=0, top_p=0.5)
        assert t.tolist() == [0]


def test_norms_match_numpy():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(3, 7, 16)).astype(np.float32)
    w = rng.normal(size=(16,)).astype(np.float32)
    b = rng.normal(size=(16,)).astype(np.float32)

    rms = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-5) * w
    np.testing.assert_allclose(np.asarray(rms_norm(jnp.asarray(x), jnp.asarray(w))), rms, atol=1e-5)

    mu, var = x.mean(-1, keepdims=True), x.var(-1, keepdims=True)
    ln = (x - mu) / np.sqrt(var + 1e-12) * w + b
    np.testing.assert_allclose(
        np.asarray(layer_norm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))), ln, atol=1e-4
    )


def test_top_k_hierarchical_matches_lax_top_k():
    """Exact at large vocab (the decode hot path): same values, and ids agree
    wherever values are unique; padding lanes never leak in."""
    from django_assistant_bot_tpu.ops.sampling import top_k_hierarchical

    rng = np.random.default_rng(0)
    for V in (16_384, 128_256, 5000):  # aligned, unaligned (pad), small
        x = jnp.asarray(rng.normal(size=(4, V)).astype(np.float32))
        vals, idx = jax.jit(lambda a: top_k_hierarchical(a, 50))(x)
        ref_vals, ref_idx = jax.lax.top_k(x, 50)
        np.testing.assert_allclose(np.asarray(vals), np.asarray(ref_vals))
        np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref_idx))
        assert int(idx.max()) < V  # no padded-lane index escapes


def test_top_k_hierarchical_adversarial_clusters():
    """All top-k values packed into ONE group must still all be found (the
    pigeonhole argument the implementation relies on)."""
    from django_assistant_bot_tpu.ops.sampling import top_k_hierarchical

    V, k = 32_768, 50
    x = np.zeros((2, V), np.float32)
    x[0, 256 : 256 + k] = np.arange(k, 0, -1)  # contiguous block in one group
    x[1, ::701] = np.arange(len(x[1, ::701]), 0, -1)  # scattered
    vals, idx = top_k_hierarchical(jnp.asarray(x), k)
    ref_vals, ref_idx = jax.lax.top_k(jnp.asarray(x), k)
    np.testing.assert_allclose(np.asarray(vals), np.asarray(ref_vals))


def test_top_k_hierarchical_degenerate_rows_stay_in_vocab():
    """A row with fewer than k entries above the finite NEG_INF pad value (a
    fully-masked FSM state at an unaligned vocab) must never return an index
    >= V — a uniform draw over the all-NEG_INF candidates would otherwise
    emit an out-of-vocab token id (r4 advisor finding)."""
    from django_assistant_bot_tpu.ops.attention import NEG_INF
    from django_assistant_bot_tpu.ops.sampling import top_k_hierarchical

    V, k = 130, 50  # unaligned: 126 pad lanes tie with the masked row
    x = np.full((2, V), NEG_INF, np.float32)
    x[1, 7] = 1.0  # one live candidate; row 0 fully masked
    vals, idx = top_k_hierarchical(jnp.asarray(x), k)
    assert int(np.asarray(idx).max()) < V
    assert int(np.asarray(idx)[1, 0]) == 7


def test_sample_logits_large_vocab_greedy_matches_argmax():
    from django_assistant_bot_tpu.ops.sampling import sample_logits

    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(3, 128_256)).astype(np.float32))
    out = sample_logits(
        logits, jax.random.key(0), temperature=jnp.zeros((3,)), top_k=50, top_p=0.95
    )
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, axis=-1))
    )


def test_longrope_long_regime_warns_short_does_not():
    """A deployment past the pretrained context commits to the LONG factor
    list for all sequences — diverging from HF on short prompts.  That choice
    must be visible at load time (VERDICT r4 missing #2)."""
    import warnings

    from django_assistant_bot_tpu.ops.rope import rope_frequencies

    scaling = ("longrope", [1.0, 1.1, 1.2, 1.3], [2.0, 2.5, 3.0, 4.0], 32, 1.5)
    with pytest.warns(UserWarning, match="LONG factor list"):
        rope_frequencies(8, 64, theta=1e4, scaling=scaling, deployed_len=128)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # short regime: silent
        rope_frequencies(8, 16, theta=1e4, scaling=scaling, deployed_len=32)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("window", [None, 100])
def test_flash_multi_chunk_kv_matches_reference(qkv, causal, window):
    """The chunked-KV pipeline path (num_chunks > 1 — what long contexts use;
    a whole-row resident block dies at 16k VMEM) must match the reference
    exactly, incl. the online-softmax state carried across chunk programs and
    the dead-chunk index clamping in every causal/window combination."""
    if window is not None and not causal:
        pytest.skip("window implies causal in the model paths")
    q, k, v = qkv  # S=256 -> 4 chunks of 64
    ref = dot_product_attention(q, k, v, causal=causal, window=window)
    out = flash_attention(
        q, k, v, causal=causal, window=window, interpret=True,
        block_q=64, block_kv=64, chunk_kv=64,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)
