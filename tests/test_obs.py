"""Observability plane tests (serving/obs.py + wiring; docs/OBSERVABILITY.md).

Four groups:

- unit: the fixed-bucket Histogram, the exposition renderer and the small
  in-repo Prometheus parser/validator (the one CI's chaos smoke uses);
- tracing: trace_id propagation end to end (submit kwarg, generated ids,
  span structure from the host timestamps the tick path already stamps);
- HTTP: ``X-Request-Id`` accepted and echoed on EVERY ``/dialog/`` response
  shape (JSON, SSE terminal event, 422/429/503/504 error bodies), plus the
  ``GET /metrics`` endpoint — including the scrape-under-duress regression
  net: /metrics and /healthz must answer promptly and parse while one
  replica is dead, mid-drain, and mid-restart (the router-lock/scheduler-
  lock deadlock family from PR 7);
- flight recorder: a chaos ``tick_raise`` restart must dump a well-formed
  JSON artifact containing the injected-fault event and the resubmitted
  request's trace_id.
"""

from __future__ import annotations

import asyncio
import glob
import io
import json
import logging
import time
from types import SimpleNamespace

import jax
import pytest

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.serving import (
    ByteTokenizer,
    EngineUnavailable,
    FaultInjector,
    GenerationEngine,
    GenerationResult,
    Histogram,
    ModelRegistry,
    SchedulerRejected,
    new_trace_id,
    parse_prometheus_text,
    render_prometheus,
)
from django_assistant_bot_tpu.serving.obs import (
    JsonLogFormatter,
    setup_json_logging,
)
from django_assistant_bot_tpu.serving.scheduler import DeadlineExceeded
from django_assistant_bot_tpu.serving.server import create_app


def _engine(tmp_path=None, **kw):
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 64)
    if tmp_path is not None:
        kw.setdefault("obs_dump_dir", str(tmp_path))
    return GenerationEngine(cfg, params, ByteTokenizer(), **kw)


# ---------------------------------------------------------------------- units
def test_histogram_buckets_cumulative_and_sum():
    h = Histogram((0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    buckets, total, n = h.snapshot()
    assert n == 5 and abs(total - 56.05) < 1e-9
    assert buckets == [(0.1, 1), (1.0, 3), (10.0, 4), (float("inf"), 5)]
    # boundary values land in their own bucket (le is inclusive)
    h2 = Histogram((1.0,))
    h2.observe(1.0)
    assert h2.snapshot()[0][0] == (1.0, 1)


def test_parser_roundtrips_renderer_output():
    h = Histogram((0.5, 2.0))
    h.observe(0.1)
    h.observe(1.0)
    from django_assistant_bot_tpu.serving.obs import _Exposition

    x = _Exposition()
    x.add("t_total", "counter", "a counter", 7, {"model": "m"})
    x.add("g", "gauge", 'label with "quotes" and \\', 1.5, {"k": 'v"w\\x'})
    x.add_histogram("lat_seconds", "a histogram", h, {"model": "m"})
    fams = parse_prometheus_text(x.render())
    assert fams["t_total"]["samples"] == [("t_total", {"model": "m"}, 7.0)]
    # label escaping survives the roundtrip
    assert fams["g"]["samples"][0][1] == {"k": 'v"w\\x'}
    lat = fams["lat_seconds"]
    assert lat["type"] == "histogram"
    counts = {n: v for n, _, v in lat["samples"] if n.endswith("_count")}
    assert counts == {"lat_seconds_count": 2.0}


def test_parser_rejects_malformed_exposition():
    with pytest.raises(ValueError, match="no preceding TYPE"):
        parse_prometheus_text("orphan_metric 1\n")
    bad_noncumulative = (
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\nh_bucket{le="+Inf"} 5\n'
        "h_sum 1\nh_count 5\n"
    )
    with pytest.raises(ValueError, match="non-cumulative"):
        parse_prometheus_text(bad_noncumulative)
    bad_no_inf = "# TYPE h histogram\n" 'h_bucket{le="1"} 5\nh_sum 1\nh_count 5\n'
    with pytest.raises(ValueError, match="\\+Inf"):
        parse_prometheus_text(bad_no_inf)
    bad_count = (
        "# TYPE h histogram\n"
        'h_bucket{le="+Inf"} 4\nh_sum 1\nh_count 5\n'
    )
    with pytest.raises(ValueError, match="_count"):
        parse_prometheus_text(bad_count)
    with pytest.raises(ValueError, match="unparseable"):
        parse_prometheus_text("# TYPE g gauge\ng not-a-number\n")


def test_json_log_formatter_line_shape():
    fmt = JsonLogFormatter()
    rec = logging.LogRecord(
        "serving", logging.INFO, __file__, 1, "request finished", (), None
    )
    rec.trace_id = "abc123"
    rec.model = "tiny-chat"
    rec.replica = "tiny-chat/r0"
    line = json.loads(fmt.format(rec))
    assert line["event"] == "request finished"
    assert line["trace_id"] == "abc123"
    assert line["model"] == "tiny-chat"
    assert line["replica"] == "tiny-chat/r0"
    assert line["level"] == "info" and "ts" in line


def test_setup_json_logging_gate(monkeypatch):
    monkeypatch.delenv("DABT_LOG_JSON", raising=False)
    assert setup_json_logging() is False  # plain-text default untouched
    stream = io.StringIO()
    root = logging.getLogger()
    handler = logging.StreamHandler(stream)
    old_formatters = [(h, h.formatter) for h in root.handlers]
    root.addHandler(handler)
    try:
        monkeypatch.setenv("DABT_LOG_JSON", "1")
        assert setup_json_logging() is True
        logging.getLogger("obs-test").warning(
            "shed", extra={"trace_id": "t1", "reason": "queue_full"}
        )
        line = json.loads(stream.getvalue().strip().splitlines()[-1])
        assert line == {
            "ts": line["ts"],
            "level": "warning",
            "logger": "obs-test",
            "event": "shed",
            "trace_id": "t1",
            "reason": "queue_full",
        }
    finally:
        root.removeHandler(handler)
        for h, f in old_formatters:
            h.setFormatter(f)


# -------------------------------------------------------------------- tracing
def test_trace_id_propagates_and_spans_close(tmp_path):
    eng = _engine(tmp_path, name="traced").start()
    try:
        r = eng.submit(
            [1, 2, 3], max_tokens=4, temperature=0.0, trace_id="req-1"
        ).result(timeout=300)
        tr = eng.obs.trace("req-1")
        assert tr is not None and tr["engine"] == "traced"
        names = [s["name"] for s in tr["spans"]]
        assert names == ["admit", "queue_wait", "prefill", "decode", "detok", "deliver"]
        assert tr["completion_tokens"] == len(r.token_ids)
        # span arithmetic: queue_wait + prefill + decode + detok == total
        spans = {s["name"]: s for s in tr["spans"]}
        parts = sum(
            spans[n].get("dur_s", 0.0)
            for n in ("queue_wait", "prefill", "decode", "detok")
        )
        assert abs(parts - tr["total_s"]) < 1e-3
        assert spans["decode"]["tokens"] == tr["completion_tokens"]
        # generated ids when the caller sends none; unique per request
        f1 = eng.submit([4, 5], max_tokens=2, temperature=0.0)
        f2 = eng.submit([6, 7], max_tokens=2, temperature=0.0)
        f1.result(timeout=300), f2.result(timeout=300)
        ids = [t["trace_id"] for t in eng.obs.traces()]
        assert len(ids) == len(set(ids)) == 3
        assert all(ids)
    finally:
        eng.stop()


def test_obs_off_engine_serves_without_recorder(tmp_path):
    eng = _engine(tmp_path, obs=False).start()
    try:
        assert eng.obs is None
        r = eng.submit([1, 2, 3], max_tokens=3, temperature=0.0).result(timeout=300)
        assert len(r.token_ids) == 3
    finally:
        eng.stop()


def test_metrics_histogram_counts_match_known_trace(tmp_path):
    """The acceptance-criteria count check: N finished requests -> exactly N
    TTFT and N queue-wait observations in the scraped exposition."""
    eng = _engine(tmp_path, name="counted").start()
    try:
        n = 5
        futs = [
            eng.submit([1 + i, 2, 3], max_tokens=3, temperature=0.0)
            for i in range(n)
        ]
        for f in futs:
            f.result(timeout=300)
        reg = SimpleNamespace(generators={"counted": eng}, embedders={})
        fams = parse_prometheus_text(render_prometheus(reg))
        for fam in ("dabt_ttft_seconds", "dabt_queue_wait_seconds"):
            counts = [
                v for name, _, v in fams[fam]["samples"] if name.endswith("_count")
            ]
            assert counts == [float(n)], (fam, counts)
        # tick histogram saw at least one tick per generated token wave
        tick_counts = [
            v
            for name, _, v in fams["dabt_tick_seconds"]["samples"]
            if name.endswith("_count")
        ]
        assert tick_counts[0] >= 1
        assert fams["dabt_traces_total"]["samples"][0][2] == float(n)
    finally:
        eng.stop()


# ------------------------------------------------------------ flight recorder
def test_chaos_restart_dumps_wellformed_artifact(tmp_path, monkeypatch):
    """A chaos tick_raise restart must leave a parseable JSON artifact whose
    event ring contains the injected-fault event AND the resubmitted
    request's trace_id — diagnosable from the artifact alone."""
    # pin the dump location: DABT_FLIGHT_DIR (set by CI's chaos smoke step)
    # takes precedence over obs_dump_dir, and this test globs tmp_path
    monkeypatch.setenv("DABT_FLIGHT_DIR", str(tmp_path))
    eng = _engine(tmp_path, name="chaos").start()
    inj = FaultInjector({})
    eng._faults = inj
    try:
        eng.submit([1, 2, 3], max_tokens=2, temperature=0.0).result(timeout=300)
        inj.arm("tick_raise")
        r = eng.submit(
            [4, 5, 6], max_tokens=3, temperature=0.0, trace_id="chaos-req"
        ).result(timeout=300)
        assert len(r.token_ids) == 3  # crash-only restart completed the trace
        assert eng.engine_restarts == 1
    finally:
        eng.stop()
    dumps = sorted(glob.glob(str(tmp_path / "flight-chaos-*.json")))
    assert dumps, "restart produced no flight-recorder dump"
    with open(dumps[0]) as fh:
        artifact = json.load(fh)
    assert artifact["reason"] == "restart"
    assert artifact["recorder"] == "chaos"
    events = artifact["events"]
    fault = [e for e in events if e["event"] == "fault_fire"]
    assert fault and fault[0]["site"] == "tick_raise"
    resub = [e for e in events if e["event"] == "resubmit"]
    assert any(e["trace_id"] == "chaos-req" for e in resub)
    restart = [e for e in events if e["event"] == "restart"]
    assert restart and "FaultInjected" in restart[0]["error"]
    # every event is stamped and ordered
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)


# ----------------------------------------------------------------------- HTTP
class _StubEngine:
    """Engine-shaped stub for deterministic HTTP response-shape tests."""

    def __init__(self):
        self.raise_exc = None
        self.seen_trace_ids = []
        self.tokenizer = ByteTokenizer()
        self.max_seq_len = 64
        self.num_active = 0
        self.steps = 0
        self.reclaimed_slots = 0

    async def generate(self, messages, **kw):
        self.seen_trace_ids.append(kw.get("trace_id"))
        if self.raise_exc is not None:
            raise self.raise_exc
        return GenerationResult(
            token_ids=[1, 2],
            text="ok",
            prompt_tokens=3,
            completion_tokens=2,
            length_limited=False,
        )

    async def generate_stream(self, messages, **kw):
        from django_assistant_bot_tpu.serving.streaming import StreamChunk

        self.seen_trace_ids.append(kw.get("trace_id"))
        if self.raise_exc is not None:
            raise self.raise_exc
        yield StreamChunk(index=0, token_id=1, text="o")
        yield StreamChunk(
            index=1,
            token_id=None,
            text="k",
            done=True,
            finish_reason="stop",
            result=GenerationResult(
                token_ids=[1, 2],
                text="ok",
                prompt_tokens=3,
                completion_tokens=2,
                length_limited=False,
            ),
        )


class _StubRegistry:
    def __init__(self, eng):
        self.eng = eng
        self.generators = {}
        self.embedders = {}
        self.specs = {}

    def get_generator(self, model):
        return self.eng if model == "stub" else None

    def get_embedder(self, model):
        return None

    def idle(self):
        return True

    def stop(self):
        pass


@pytest.fixture()
def stub_client():
    from aiohttp.test_utils import TestClient, TestServer

    loop = asyncio.new_event_loop()
    eng = _StubEngine()
    app = create_app(_StubRegistry(eng))
    client = TestClient(TestServer(app), loop=loop)
    loop.run_until_complete(client.start_server())
    yield loop, client, eng, app
    loop.run_until_complete(client.close())
    loop.close()


def _dialog_body(**kw):
    body = {
        "model": "stub",
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 4,
    }
    body.update(kw)
    return body


def test_request_id_echoed_on_every_dialog_shape(stub_client):
    loop, client, eng, app = stub_client

    async def go():
        hdr = {"X-Request-Id": "client-id-1"}
        # 200 JSON: header + body echo, and the id IS the engine trace_id
        resp = await client.post("/dialog/", json=_dialog_body(), headers=hdr)
        assert resp.status == 200
        assert resp.headers["X-Request-Id"] == "client-id-1"
        assert (await resp.json())["request_id"] == "client-id-1"
        assert eng.seen_trace_ids[-1] == "client-id-1"

        # no client id -> server generates one (and still echoes it)
        resp = await client.post("/dialog/", json=_dialog_body())
        rid = resp.headers["X-Request-Id"]
        assert rid and (await resp.json())["request_id"] == rid
        assert eng.seen_trace_ids[-1] == rid

        # hostile header shapes are replaced, never echoed verbatim
        resp = await client.post(
            "/dialog/", json=_dialog_body(), headers={"X-Request-Id": "x" * 500}
        )
        assert resp.headers["X-Request-Id"] != "x" * 500

        # 422 (bad body)
        resp = await client.post(
            "/dialog/", json={"model": "stub"}, headers=hdr
        )
        assert resp.status == 422
        assert resp.headers["X-Request-Id"] == "client-id-1"
        assert (await resp.json())["request_id"] == "client-id-1"

        # 400 (unknown model)
        resp = await client.post(
            "/dialog/", json=_dialog_body(model="nope"), headers=hdr
        )
        assert resp.status == 400
        assert (await resp.json())["request_id"] == "client-id-1"

        # 429 (shed): the formerly-uncorrelatable case
        eng.raise_exc = SchedulerRejected("queue_full", 1.5)
        resp = await client.post("/dialog/", json=_dialog_body(), headers=hdr)
        assert resp.status == 429
        assert resp.headers["X-Request-Id"] == "client-id-1"
        body = await resp.json()
        assert body["request_id"] == "client-id-1"
        assert body["reason"] == "queue_full"

        # 503 (engine degraded)
        eng.raise_exc = EngineUnavailable("degraded", retry_after_s=2.0)
        resp = await client.post("/dialog/", json=_dialog_body(), headers=hdr)
        assert resp.status == 503
        assert (await resp.json())["request_id"] == "client-id-1"

        # 504 (deadline)
        eng.raise_exc = DeadlineExceeded("too slow")
        resp = await client.post("/dialog/", json=_dialog_body(), headers=hdr)
        assert resp.status == 504
        assert (await resp.json())["request_id"] == "client-id-1"

        # SSE: header + terminal event carry the id
        eng.raise_exc = None
        resp = await client.post(
            "/dialog/", json=_dialog_body(stream=True), headers=hdr
        )
        assert resp.status == 200
        assert resp.headers["X-Request-Id"] == "client-id-1"
        text = (await resp.read()).decode()
        terminal = [
            json.loads(line[len("data: "):])
            for line in text.splitlines()
            if line.startswith("data: {")
        ][-1]
        assert terminal["done"] is True
        assert terminal["request_id"] == "client-id-1"

        # draining 503 echoes too
        from django_assistant_bot_tpu.serving.server import DRAIN_KEY

        app[DRAIN_KEY]["draining"] = True
        try:
            resp = await client.post("/dialog/", json=_dialog_body(), headers=hdr)
            assert resp.status == 503
            assert (await resp.json())["request_id"] == "client-id-1"
        finally:
            app[DRAIN_KEY]["draining"] = False

    loop.run_until_complete(go())


def test_provider_sends_request_id_and_server_echoes(stub_client):
    loop, client, eng, app = stub_client

    async def go():
        from django_assistant_bot_tpu.ai.providers.http_service import (
            GPUServiceProvider,
        )

        base = str(client.make_url(""))
        prov = GPUServiceProvider(base, "stub")
        resp = await prov.get_response([{"role": "user", "content": "hi"}])
        assert resp.result == "ok"
        assert prov.last_request_id
        # the provider's generated id reached the engine as the trace_id
        assert eng.seen_trace_ids[-1] == prov.last_request_id

    loop.run_until_complete(go())


# ------------------------------------------------- scrape under duress (slow)
@pytest.fixture(scope="module")
def duress_fleet(tmp_path_factory):
    """2-replica tiny fleet behind the real server app (module-scoped: the
    engines compile once and every duress scenario reuses them)."""
    from aiohttp.test_utils import TestClient, TestServer

    tmp = tmp_path_factory.mktemp("flight")
    loop = asyncio.new_event_loop()
    registry = ModelRegistry.from_config(
        {
            "tiny-chat": {
                "kind": "decoder",
                "tiny": True,
                "max_slots": 2,
                "max_seq_len": 64,
                "replicas": 2,
                "obs_dump_dir": str(tmp),
                "router_breaker_reset_s": 0.2,
            }
        }
    )
    client = TestClient(TestServer(create_app(registry)), loop=loop)
    loop.run_until_complete(client.start_server())
    yield loop, client, registry
    loop.run_until_complete(client.close())
    loop.close()


def _scrape_promptly(loop, client, budget_s=10.0):
    """GET /metrics and /healthz; both must answer within the budget and the
    exposition must parse.  Returns the parsed families."""
    t0 = time.monotonic()

    async def go():
        m = await client.get("/metrics")
        assert m.status == 200
        text = await m.text()
        h = await client.get("/healthz")
        assert h.status == 200
        return text, await h.json()

    text, health = loop.run_until_complete(asyncio.wait_for(go(), budget_s))
    assert time.monotonic() - t0 < budget_s
    return parse_prometheus_text(text), health


def test_metrics_scrape_under_duress(duress_fleet):
    loop, client, registry = duress_fleet
    router = registry.get_generator("tiny-chat")

    async def warm():
        resp = await client.post(
            "/dialog/",
            json={
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 2,
            },
        )
        assert resp.status == 200

    loop.run_until_complete(asyncio.wait_for(warm(), 300))

    # healthy: both replicas up, per-replica labels present
    fams, health = _scrape_promptly(loop, client)
    healthy = {
        labels["replica"]: v
        for _, labels, v in fams["dabt_engine_healthy"]["samples"]
    }
    assert set(healthy) == {"tiny-chat/r0", "tiny-chat/r1"}
    assert all(v == 1.0 for v in healthy.values())
    assert health["status"] == "ok"
    assert "dabt_router_reroutes_total" in fams

    # one replica DEAD: scrape still prompt + parseable, health degrades
    router.kill_replica(0)
    deadline = time.monotonic() + 30
    while router.replicas[0].engine._thread.is_alive():
        assert time.monotonic() < deadline
        time.sleep(0.01)
    fams, health = _scrape_promptly(loop, client)
    healthy = {
        labels["replica"]: v
        for _, labels, v in fams["dabt_engine_healthy"]["samples"]
    }
    assert healthy["tiny-chat/r0"] == 0.0 and healthy["tiny-chat/r1"] == 1.0
    assert health["status"] == "degraded"

    # MID-RESTART of the dead replica (on a worker thread, scraping racing it)
    import threading

    t = threading.Thread(target=router.restart_replica, args=(0,))
    t.start()
    try:
        fams, _ = _scrape_promptly(loop, client)
        assert "dabt_engine_healthy" in fams
    finally:
        t.join(timeout=60)
    assert not t.is_alive()
    fams, health = _scrape_promptly(loop, client)
    assert health["status"] == "ok"

    # MID-DRAIN: replica marked draining; scrape sees the flag and stays prompt
    router.replicas[1].draining = True
    try:
        fams, _ = _scrape_promptly(loop, client)
        draining = {
            labels["replica"]: v
            for _, labels, v in fams["dabt_replica_draining"]["samples"]
        }
        assert draining["tiny-chat/r1"] == 1.0
    finally:
        router.replicas[1].draining = False

    # traffic still serves after the duress tour
    loop.run_until_complete(asyncio.wait_for(warm(), 300))


def test_new_trace_id_shape():
    a, b = new_trace_id(), new_trace_id()
    assert a != b
    assert len(a) == 16 and all(c in "0123456789abcdef" for c in a)
