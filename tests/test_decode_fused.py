"""Roofline decode push (docs/QUANT.md): fused multi-token decode tick
(`decode_steps`), int4 grouped-quant serving, double-buffered uploads, the
decode-path operator gauges, and the byte-ledger autotune sweep."""

import jax
import numpy as np
import pytest
from types import SimpleNamespace

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.ops.quant import quantize_decoder_params
from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine


def _tiny():
    cfg = DecoderConfig.tiny()
    return cfg, llama.init(cfg, jax.random.PRNGKey(0))


def _drive(eng, futs, limit=4000):
    """Single-threaded deterministic engine loop (no engine thread) — the
    test_kv_paging discipline: every request queued before the first
    admission, so both arms see the identical wave structure."""
    steps = 0
    while not all(f.done() for f in futs):
        eng._reap_dead_slots()
        eng._admit()
        if eng._chunking is not None:
            eng._chunk_step()
        if eng.num_active > 0:
            eng._issue_tick()
        while eng._inflight and (
            len(eng._inflight) > eng.lookahead or eng.num_active == 0
        ):
            eng._process_tick()
        eng._prestage_uploads()
        steps += 1
        assert steps < limit, "engine made no progress"


def _run(cfg, params, prompts, *, kv_layout="paged", temps=None, **kw):
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=256,
        prefix_cache_size=0, kv_layout=kv_layout, **kw,
    )
    eng._running = True
    temps = temps or [0.0] * len(prompts)
    futs = [
        eng.submit(p, max_tokens=12, temperature=t, top_p=0.9)
        for p, t in zip(prompts, temps)
    ]
    _drive(eng, futs)
    eng._running = False
    return [f.result(timeout=0).token_ids for f in futs], eng


def _ragged_prompts(seed=5, n=4):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 200, ln).tolist() for ln in (3, 17, 40, 9)][:n]


# --------------------------------------------------------------- bit identity
def test_decode_steps_one_byte_identical_to_unfused_burst():
    """The rollback contract: decode_steps=1 IS the unfused tick — greedy AND
    sampled traffic byte-identical to the historical burst=1 alias."""
    cfg, params = _tiny()
    prompts = _ragged_prompts()
    temps = [0.0, 0.9, 0.0, 0.7]
    a, _ = _run(cfg, params, prompts, temps=temps, decode_steps=1)
    b, _ = _run(cfg, params, prompts, temps=temps, burst=1)
    assert a == b


@pytest.mark.parametrize("kv_layout", ["paged", "legacy"])
@pytest.mark.parametrize("quantize", [None, "int8", "int4"])
def test_fused_greedy_token_identical(kv_layout, quantize):
    """N>1 fused ticks are greedy token-identical to N=1 across layouts and
    weight formats over ragged prompt fills — the acceptance criterion's
    bit-identity subset."""
    cfg, params = _tiny()
    if quantize:
        params = quantize_decoder_params(params, fmt=quantize)
    prompts = _ragged_prompts()
    a, ea = _run(cfg, params, prompts, kv_layout=kv_layout, decode_steps=1)
    b, eb = _run(cfg, params, prompts, kv_layout=kv_layout, decode_steps=3)
    assert a == b
    assert ea.decode_steps == 1 and eb.decode_steps == 3
    if quantize == "int4":
        assert eb.weight_bits == 4
    elif quantize == "int8":
        assert eb.weight_bits == 8


def test_fused_sampled_token_identical_across_n():
    """Sampled rows too: the fused scan splits the chained rng once per STEP,
    exactly like N=1 tick-per-step — same split chain, same ids."""
    cfg, params = _tiny()
    prompts = _ragged_prompts(seed=9)
    temps = [0.8, 0.9, 0.7, 1.0]
    a, _ = _run(cfg, params, prompts, temps=temps, decode_steps=1)
    b, _ = _run(cfg, params, prompts, temps=temps, decode_steps=4)
    assert a == b


# ------------------------------------------------------------- int4 serving
def test_int4_engine_serves_threaded():
    """Grouped-int4 weights through the real threaded engine: decode works,
    the weight_bits gauge reports 4, and the fused tick stays engaged."""
    cfg = DecoderConfig.tiny()
    params = llama.init_int4(cfg, jax.random.PRNGKey(2))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=128,
        decode_steps=4, prefix_cache_size=0,
    ).start()
    try:
        futs = [
            eng.submit(list(range(1, 10)), max_tokens=8, temperature=0.0)
            for _ in range(3)
        ]
        for f in futs:
            r = f.result(timeout=120)
            assert len(r.token_ids) >= 1
        st = eng.tick_stats()
        assert st["weight_bits"] == 4
        assert st["decode_steps"] == 4
        assert st["decode_steps_effective"] == 4
    finally:
        eng.stop()


# ------------------------------------------------------- json downgrade path
def test_json_slots_downgrade_fused_tick_to_single_step():
    cfg, params = _tiny()
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=128,
        decode_steps=4, prefix_cache_size=0,
    )
    eng.warmup(json=True)
    eng.start()
    try:
        f = eng.submit([1, 2, 3], max_tokens=8, temperature=0.0, json_format=True)
        f.result(timeout=120)
        st = eng.tick_stats()
        assert st["json_downgraded_ticks"] > 0
        assert st["decode_steps_effective"] == 1
        assert st["decode_steps"] == 4
        # plain traffic afterwards re-engages the fused tick
        eng.submit([1, 2, 3], max_tokens=6, temperature=0.0).result(timeout=120)
        assert eng.tick_stats()["decode_steps_effective"] == 4
    finally:
        eng.stop()


def test_speculative_composes_with_decode_steps():
    """Spec x fused: decode_steps now scans N verify passes per dispatch
    instead of being rejected; the engine reports both knobs and the
    oversized product still fails loudly at construction."""
    cfg, params = _tiny()
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=128,
        decode_steps=4, speculative=3,
    )
    assert eng.burst == 4 and eng.speculative == 3
    # a spec engine WITHOUT an explicit decode_steps stays at one verify
    # pass per tick — `burst` must not silently multiply existing deploys
    eng1 = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=128,
        burst=8, speculative=3,
    )
    assert eng1.burst == 1
    with pytest.raises(ValueError, match="too large"):
        GenerationEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=128,
            decode_steps=8, speculative=7,
        )


# -------------------------------------------------- double-buffered uploads
def test_upload_overlap_reported_and_positive():
    """Staggered finishes dirty the sampling arrays while ticks are still in
    flight — the prestage path must absorb some upload cycles and the gauge
    must ride tick_stats."""
    cfg, params = _tiny()
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=4, max_seq_len=128,
        decode_steps=2, prefix_cache_size=0,
    ).start()
    try:
        futs = [
            eng.submit(list(range(1, 6)), max_tokens=4 + 10 * i, temperature=0.7)
            for i in range(4)
        ]
        for f in futs:
            f.result(timeout=120)
        st = eng.tick_stats()
        assert 0.0 <= st["upload_overlap_frac"] <= 1.0
        assert eng._uploads_prestaged > 0
    finally:
        eng.stop()


# ------------------------------------------------------------ chaos restart
def test_tick_raise_mid_fused_tick_restart_leaves_page_pool_clean():
    """tick_raise armed mid-fused-tick (decode_steps=4, paged): crash-only
    restart resets the page plane — every page back on the free list, block
    tables unallocated — and salvaged requests complete on the rebuilt pool
    (the speculative chaos test's contract, now on the fused plain tick)."""
    from django_assistant_bot_tpu.serving.faults import FaultInjector

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(9))
    tok = ByteTokenizer()
    inj = FaultInjector({})
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=96, decode_steps=4,
        prefix_cache_size=0, faults=inj,
    )
    assert eng.paged
    eng.start()
    try:
        f0 = eng.submit(tok.encode("ab ab ab ab"), max_tokens=6, temperature=0.0)
        f0.result(timeout=120)
        inj.arm("tick_raise")
        futs = [
            eng.submit(tok.encode("cd cd cd cd"), max_tokens=6, temperature=0.0)
            for _ in range(2)
        ]
        done = 0
        for f in futs:
            try:
                r = f.result(timeout=120)
                assert len(r.token_ids) >= 1
                done += 1
            except RuntimeError:
                pass  # past-first-token requests fail cleanly on restart
        assert done >= 1
        assert eng.engine_restarts == 1
        assert eng.healthy()
        kv = eng.kv_stats()
        assert kv["kv_pages_used"] == 0
        assert kv["kv_pages_free"] == eng._kv_pool.n_pages
        assert all(not pages for pages in eng._slot_pages)
    finally:
        eng.stop(drain_timeout_s=60.0)


# ------------------------------------------------------------ operator plane
def test_decode_path_gauges_in_metrics_exposition():
    from django_assistant_bot_tpu.serving.obs import (
        parse_prometheus_text,
        render_prometheus,
    )

    cfg = DecoderConfig.tiny()
    params = llama.init_int4(cfg, jax.random.PRNGKey(3))
    eng = GenerationEngine(
        cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=128,
        decode_steps=2, prefix_cache_size=0, name="q4",
    )
    reg = SimpleNamespace(generators={"q4": eng}, embedders={})
    fams = parse_prometheus_text(render_prometheus(reg))
    assert fams["dabt_weight_bits"]["samples"][0][2] == 4
    assert fams["dabt_decode_steps"]["samples"][0][2] == 2
    assert "dabt_decode_steps_effective" in fams
    assert "dabt_upload_overlap_frac" in fams


def test_registry_accepts_decode_steps_with_speculative():
    """The registry-level mutual exclusion is gone: a spec x fused entry
    loads and threads both knobs into the engine."""
    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec

    spec = ModelSpec(
        name="m", kind="decoder", tiny=True, decode_steps=2, speculative=3,
        max_seq_len=128, scheduler=False,
    )
    reg = ModelRegistry(specs={"m": spec})
    try:
        eng = reg.generators["m"]
        assert eng.burst == 2 and eng.speculative == 3
    finally:
        reg.stop()


def test_registry_rejects_bad_quant_knobs():
    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec

    with pytest.raises(ValueError, match="quantize"):
        ModelRegistry(
            specs={"m": ModelSpec(name="m", kind="decoder", tiny=True, quantize="int2")}
        )
    with pytest.raises(ValueError, match="quant_group_size"):
        ModelRegistry(
            specs={
                "m": ModelSpec(
                    name="m", kind="decoder", tiny=True,
                    quantize="int4", quant_group_size=3,
                )
            }
        )


# ----------------------------------------------------- quantized checkpoints
@pytest.mark.parametrize("fmt", ["int8", "int4"])
def test_quantized_checkpoint_roundtrip_preserves_qtensor(fmt, tmp_path):
    """Regression: the checkpoint loader used to collapse a QTensor onto
    whichever field restored LAST (keystr attr paths weren't parsed), so a
    `fetch_models --convert --quantize int8` checkpoint restored with wq ==
    its SCALE array — unservable.  Both formats must round-trip exactly,
    with scales kept f32 through the dtype cast."""
    import jax.numpy as jnp

    from django_assistant_bot_tpu.checkpoint import load_model, save_model
    from django_assistant_bot_tpu.ops.quant import QTensor, QTensor4

    cfg, params = _tiny()
    qp = quantize_decoder_params(params, fmt=fmt)
    save_model(str(tmp_path / "m"), "decoder", cfg, qp)
    kind, cfg2, back, _meta = load_model(str(tmp_path / "m"), dtype=jnp.bfloat16)
    assert kind == "decoder"
    cls = QTensor4 if fmt == "int4" else QTensor
    wq = back["layers"]["wq"]
    assert isinstance(wq, cls)
    np.testing.assert_array_equal(
        np.asarray(wq.q), np.asarray(qp["layers"]["wq"].q)
    )
    assert np.asarray(wq.scale).dtype == np.float32
    ids = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
    np.testing.assert_allclose(
        np.asarray(llama.forward(qp, cfg, ids)),
        np.asarray(llama.forward(back, cfg, ids)),
        atol=2e-2,
    )


def test_prequantized_checkpoint_guard(tmp_path):
    """A converted checkpoint arrives pre-quantized: a MATCHING quantize knob
    is a logged no-op, a MISMATCHED one is a named config error — not the
    opaque numpy shape crash double-quantization used to die with."""
    from django_assistant_bot_tpu.checkpoint import save_model
    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec

    cfg, params = _tiny()
    qp = quantize_decoder_params(params, fmt="int4")
    save_model(str(tmp_path / "m"), "decoder", cfg, qp)
    reg = ModelRegistry(
        specs={
            "a": ModelSpec(
                name="a", kind="decoder",
                checkpoint=str(tmp_path / "m"), quantize="int4",
            )
        }
    )
    try:
        assert reg.get_generator("a").weight_bits == 4
    finally:
        reg.stop()
    with pytest.raises(ValueError, match="already quantized"):
        ModelRegistry(
            specs={
                "b": ModelSpec(
                    name="b", kind="decoder",
                    checkpoint=str(tmp_path / "m"), quantize="int8",
                )
            }
        )


# ------------------------------------------------------------------ autotune
def test_autotune_sweep_ranks_and_respects_budget():
    from django_assistant_bot_tpu.serving.autotune import Geometry, recommend, sweep

    geom = Geometry(
        num_layers=16, hidden_size=2048, intermediate_size=8192,
        num_heads=32, num_kv_heads=8, head_dim=64, vocab_size=128256,
    )
    cands = sweep(geom, max_seq_len=1024, weight_bits=8, hbm_budget_gb=8.0)
    assert cands, "no feasible geometry"
    # ranked by modeled tok/s, every candidate inside the budget
    rates = [c.est_tokens_per_s for c in cands]
    assert rates == sorted(rates, reverse=True)
    assert all(c.hbm_total_gb <= 8.0 for c in cands)
    rec = recommend(geom, max_seq_len=1024, weight_bits=4, hbm_budget_gb=8.0)
    assert set(rec["recommended"]) == {"kv_page_size", "max_slots", "decode_steps"}
    assert rec["assumptions"]["weight_bits"] == 4


def test_autotune_int4_reads_fewer_bytes_and_steps_amortize_overhead():
    from django_assistant_bot_tpu.serving.autotune import Geometry, sweep

    geom = Geometry(
        num_layers=16, hidden_size=2048, intermediate_size=8192,
        num_heads=32, num_kv_heads=8, head_dim=64, vocab_size=128256,
    )
    assert geom.weight_read_bytes(4) < geom.weight_read_bytes(8)
    assert geom.weight_read_bytes(8) < geom.weight_read_bytes(16)
    # untied models hold a second embedding table decode never streams:
    # the feasibility side must charge it, the read side must not
    emb_bytes = geom.head_weights() * geom.dtype_bytes
    assert geom.resident_weight_bytes(16) == geom.weight_read_bytes(16) + emb_bytes
    import dataclasses

    tied = dataclasses.replace(geom, tie_embeddings=True)
    assert tied.resident_weight_bytes(16) == tied.weight_read_bytes(16)
    # with a large host overhead the sweep must prefer deeper fused ticks
    # at fixed page/slots: tok/s strictly rises with decode_steps
    cands = sweep(
        geom, max_seq_len=1024, weight_bits=8, hbm_budget_gb=8.0,
        host_overhead_us=10_000.0, page_sizes=(256,), slots=(8,),
        decode_steps=(1, 4, 16),
    )
    by_steps = {c.decode_steps: c.est_tokens_per_s for c in cands}
    assert by_steps[16] > by_steps[4] > by_steps[1]


def test_measure_report_reranks_by_probe():
    """`--measure` discipline: probe the top-k, keep BOTH rankings, make
    ledger-vs-measured disagreement a visible artifact, and never let one
    failed probe abort the sweep."""
    from django_assistant_bot_tpu.serving.autotune import measure_report

    class FakeEng:
        def __init__(self, step_s):
            self._s = step_s
            self.stopped = False

        def probe_decode(self, iters=16, fill_len=None):
            if self._s is None:
                raise RuntimeError("compile exploded")
            return self._s

        def stop(self, drain_timeout_s=None):
            self.stopped = True

    # ledger rank 0 probes SLOWER than rank 1, rank 2's probe dies
    step_by_depth = {2: 0.010, 4: 0.004, 8: None}
    built = []

    def factory(cand):
        eng = FakeEng(step_by_depth[cand["decode_steps"]])
        built.append(eng)
        return eng

    report = {
        "top": [
            {"kv_page_size": 32, "max_slots": 8, "decode_steps": d}
            for d in (2, 4, 8)
        ],
        "recommended": {"kv_page_size": 32, "max_slots": 8, "decode_steps": 2},
    }
    measure_report(report, factory, top_k=3)
    assert report["ledger_recommended"]["decode_steps"] == 2
    assert report["recommended"]["decode_steps"] == 4
    assert report["measured_agrees_with_ledger"] is False
    assert report["measured"][0]["measured_tokens_per_s"] == 8 / 0.004
    errs = [r for r in report["measured"] if "probe_error" in r]
    assert len(errs) == 1 and errs[0]["decode_steps"] == 8
    assert all(e.stopped for e in built), "a probed engine leaked"


def test_autotune_recommend_for_spec_tiny():
    import dataclasses

    from django_assistant_bot_tpu.serving.autotune import recommend_for_spec
    from django_assistant_bot_tpu.serving.registry import ModelSpec

    spec = ModelSpec(
        name="t", kind="decoder", tiny=True, quantize="int4", max_seq_len=256
    )
    cfg = DecoderConfig.tiny()
    cfg = dataclasses.replace(cfg, max_seq_len=256)
    out = recommend_for_spec(spec, cfg)
    assert out["model"] == "t"
    assert out["assumptions"]["weight_bits"] == 4
    assert out["recommended"]["kv_page_size"] in (32, 64, 128)
    # spec x fused composition (round 15): the sweep covers every verify
    # depth inside the construction bound n*(K+1) <= max_seq_len/4 instead
    # of clamping a speculative decoder to decode_steps=1
    spec_s = ModelSpec(
        name="s", kind="decoder", tiny=True, speculative=3, max_seq_len=256
    )
    out_s = recommend_for_spec(spec_s, cfg)
    steps = {c["decode_steps"] for c in out_s["top"]}
    assert steps - {1}, "spec sweep still clamped to decode_steps=1"
    assert all(n * (3 + 1) <= 256 // 4 for n in steps)


def test_shard_pytree_keeps_fail_loudly_for_plain_weights():
    """The non-dividing-dim replication fallback applies ONLY to quantized
    subtrees (int4 packing/grouping can stop dividing a TP axis the
    full-width weight divided) — a mis-annotated plain weight still fails
    loudly instead of silently replicating N-fold."""
    from django_assistant_bot_tpu.parallel.sharding import _is_quantized
    from django_assistant_bot_tpu.ops.quant import (
        QTensor4,
        quantize_tensor_int4,
    )
    import jax.numpy as jnp

    w = jnp.asarray(np.random.default_rng(0).normal(size=(24, 8)), jnp.float32)
    assert _is_quantized(quantize_tensor_int4(w, group_size=8))
    assert isinstance(quantize_tensor_int4(w, group_size=8), QTensor4)
    assert not _is_quantized(w)
    assert not _is_quantized({"q": w})
