"""Resilience plane: deterministic fault injection + the recovery paths it
exercises (docs/RESILIENCE.md).

Covers: injector determinism and inertness-when-off, request-poison quarantine
vs engine-fatal crash-only restart (queued work preserved, no-token requests
re-submitted, streams past first delta failed cleanly), the restart circuit
(degraded engine -> EngineUnavailable -> HTTP 503 + Retry-After, /healthz
status + loop heartbeat), provider failover with per-backend circuit breakers,
and the HTTP client's connection-error/503/Retry-After retry policy.

Everything runs on CPU with tiny random models and exact fire-on-Nth (or
armed) fault schedules — no sleep-and-hope timing, no network.
"""

import asyncio
import time
from email.utils import format_datetime

import pytest

import jax

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.serving import (
    ByteTokenizer,
    EngineUnavailable,
    FaultInjected,
    FaultInjector,
    GenerationEngine,
    ModelRegistry,
    RequestPoisoned,
)
from django_assistant_bot_tpu.serving.faults import (
    global_injector,
    reset_global_injector,
    set_global_injector,
)
from django_assistant_bot_tpu.serving.server import create_app


def _tiny_engine(seed=1, faults=None, **kw):
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.key(seed))
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 64)
    return GenerationEngine(cfg, params, ByteTokenizer(), faults=faults, **kw)


# --------------------------------------------------------------- the injector
def test_injector_fire_on_is_exact():
    inj = FaultInjector({"tick_raise": {"fire_on": [2, 5]}})
    pattern = [inj.should_fire("tick_raise") for _ in range(6)]
    assert pattern == [False, True, False, False, True, False]
    assert inj.stats()["tick_raise"] == {"calls": 6, "fires": 2}


def test_injector_every_and_max_fires():
    inj = FaultInjector({"slow_tick": {"every": 3, "max_fires": 2, "delay_s": 0.0}})
    pattern = [inj.should_fire("slow_tick") for _ in range(12)]
    assert pattern == [False, False, True, False, False, True] + [False] * 6


def test_injector_probability_deterministic_per_seed():
    spec = {"conn_reset": {"p": 0.3}}
    # same seed -> identical pattern over many calls
    i1, i2 = FaultInjector(spec, seed=7), FaultInjector(spec, seed=7)
    p1 = [i1.should_fire("conn_reset") for _ in range(200)]
    p2 = [i2.should_fire("conn_reset") for _ in range(200)]
    assert p1 == p2
    assert 20 < sum(p1) < 120  # the stream actually fires at roughly p
    # a different seed produces a different pattern
    i3 = FaultInjector(spec, seed=8)
    assert [i3.should_fire("conn_reset") for _ in range(200)] != p1


def test_injector_site_isolation():
    """One site's call pattern must not perturb another's draws."""
    solo = FaultInjector({"timeout": {"p": 0.5}}, seed=3)
    both = FaultInjector({"timeout": {"p": 0.5}, "http_5xx": {"p": 0.5}}, seed=3)
    pattern_solo = []
    pattern_both = []
    for _ in range(100):
        pattern_solo.append(solo.should_fire("timeout"))
        both.should_fire("http_5xx")  # interleaved draws on the other site
        pattern_both.append(both.should_fire("timeout"))
    assert pattern_solo == pattern_both


def test_injector_rejects_unknown_sites_and_bad_specs():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultInjector({"tick_rise": 0.5})  # typo must not silently no-op
    with pytest.raises(ValueError, match="unknown keys"):
        FaultInjector({"tick_raise": {"fire_after": 3}})
    with pytest.raises(ValueError, match="probability"):
        FaultInjector({"tick_raise": 1.5})
    assert FaultInjector.from_spec(None) is None
    assert FaultInjector.from_spec({}) is None


def test_injector_window_models_link_state():
    """start_after_s/duration_s model a PARTITION: the site holds for the
    whole window (elapsed from first consult, on the injectable clock) and
    releases after — max_fires never truncates a window."""
    t = [0.0]
    inj = FaultInjector(
        {"net_partition": {"start_after_s": 5.0, "duration_s": 3.0, "max_fires": 1}},
        clock=lambda: t[0],
    )
    assert not inj.should_fire("net_partition")  # stamps first consult at 0
    t[0] = 4.9
    assert not inj.should_fire("net_partition")
    for now in (5.0, 6.5, 7.9):  # window holds, max_fires=1 notwithstanding
        t[0] = now
        assert inj.should_fire("net_partition")
    t[0] = 8.0  # heal: start_after + duration reached
    assert not inj.should_fire("net_partition")
    # a window needs a duration — a partition that never heals is a typo
    with pytest.raises(ValueError, match="duration_s"):
        FaultInjector({"net_partition": {"start_after_s": 1.0}})


def test_injector_edges_scope_sites_to_keys():
    """A spec's edges list scopes the site to those consult keys; other
    edges never fire (and edges must be a list, not a bare string)."""
    inj = FaultInjector({"net_drop": {"fire_on": [1, 2], "edges": ["r->a"]}})
    assert not inj.should_fire("net_drop", "r->b")
    assert inj.should_fire("net_drop", "r->a")
    assert inj.should_fire("net_drop", "r->a")
    assert not inj.should_fire("net_drop", "r->a")
    with pytest.raises(ValueError, match="edges"):
        FaultInjector({"net_drop": {"edges": "r->a"}})


def test_injector_per_edge_streams_deterministic():
    """Each edge draws from its own str-seeded RNG: the same seed replays
    the same per-edge schedule regardless of how OTHER edges' consults
    interleave — what makes a two-process chaos bench replayable."""
    spec = {"net_drop": {"p": 0.5}}
    i1, i2 = FaultInjector(spec, seed=5), FaultInjector(spec, seed=5)
    pa = [i1.should_fire("net_drop", "x->a") for _ in range(100)]
    pb = []
    for _ in range(100):
        i2.should_fire("net_drop", "x->b")  # interleaved other-edge consults
        pb.append(i2.should_fire("net_drop", "x->a"))
    assert pa == pb
    # distinct edges follow distinct (still deterministic) schedules
    i3 = FaultInjector(spec, seed=5)
    assert [i3.should_fire("net_drop", "x->b") for _ in range(100)] != pa


def test_injector_arm_with_key_and_edge_stats():
    """arm(site, key=...) auto-registers the site and arms ONE edge's
    substate; the edge appears as a site[key] row in stats() — the chaos
    bench's injected-vs-rejected accounting reads those rows."""
    inj = FaultInjector({})
    inj.arm("net_corrupt", 2, key="probe")
    assert not inj.should_fire("net_corrupt", "other")  # other edges inert
    assert inj.should_fire("net_corrupt", "probe")
    assert inj.should_fire("net_corrupt", "probe")
    assert not inj.should_fire("net_corrupt", "probe")
    st = inj.stats()
    assert st["net_corrupt[probe]"] == {"calls": 3, "fires": 2}
    assert st["net_corrupt[other]"]["fires"] == 0


def test_injector_env_gate(monkeypatch):
    reset_global_injector()
    try:
        monkeypatch.delenv("DABT_FAULTS", raising=False)
        assert global_injector() is None
        reset_global_injector()
        monkeypatch.setenv("DABT_FAULTS", '{"http_5xx": {"fire_on": [1]}}')
        monkeypatch.setenv("DABT_FAULT_SEED", "42")
        inj = global_injector()
        assert inj is not None and inj.seed == 42
        assert inj.should_fire("http_5xx") is True
        assert global_injector() is inj  # cached, not re-parsed per call
    finally:
        reset_global_injector()


def test_engine_inert_without_faults(monkeypatch):
    """The disabled path must be a bare `is None` check: with no injector
    configured, NO FaultInjector method is ever entered on the serve path."""

    def trip(self, site):
        raise AssertionError(f"injector consulted on a fault-free engine: {site}")

    monkeypatch.setattr(FaultInjector, "should_fire", trip)
    eng = _tiny_engine().start()
    try:
        assert eng._faults is None
        r = eng.submit([1, 2, 3], max_tokens=5, temperature=0.0).result(timeout=120)
        assert len(r.token_ids) == 5
        assert eng.poisoned_requests == 0 and eng.engine_restarts == 0
    finally:
        eng.stop()


# ------------------------------------------------- quarantine vs engine-fatal
def test_tick_raise_mid_trace_recovers_without_failing_queued():
    """Engine-fatal fault with queued work: the crash-only restart re-submits
    the (token-less) in-flight request and leaves queued requests untouched —
    every future completes, one restart recorded."""
    inj = FaultInjector({})
    eng = _tiny_engine(faults=inj, max_slots=1).start()
    try:
        inj.arm("tick_raise")
        futs = [
            eng.submit([1, 2, 3 + i], max_tokens=5, temperature=0.0)
            for i in range(3)
        ]
        results = [f.result(timeout=120) for f in futs]
        assert all(len(r.token_ids) == 5 for r in results)
        assert eng.engine_restarts == 1
        sup = eng.supervision_stats()
        assert sup["restarted_requests_resubmitted"] == 1
        assert sup["restarted_requests_failed"] == 0
        assert sup["healthy"] is True
    finally:
        eng.stop()


def test_tick_raise_restart_rebuilds_paged_pool_and_keeps_serving():
    """Chaos on the paged KV plane (docs/KV_PAGING.md): an engine-fatal fault
    while pages are allocated AND a prefix is registered — the crash-only
    restart resets the allocator (every page free, registry empty, block
    tables unallocated), salvaged work replays onto fresh pages, and prefix
    sharing works again after recovery."""
    inj = FaultInjector({})
    eng = _tiny_engine(
        faults=inj, max_slots=2, max_seq_len=64,
        prefix_cache_size=4, prefix_min_tokens=8,
    ).start()
    assert eng.paged
    prefix = list(range(1, 13))  # 12 tokens >= prefix_min_tokens
    try:
        eng.submit(
            prefix + [20], max_tokens=3, temperature=0.0, prefix_len=len(prefix)
        ).result(timeout=120)
        assert eng.kv_stats()["kv_shared_pages"] > 0
        inj.arm("tick_raise")
        futs = [
            eng.submit(
                prefix + [30 + i], max_tokens=4, temperature=0.0,
                prefix_len=len(prefix),
            )
            for i in range(3)
        ]
        results = [f.result(timeout=120) for f in futs]
        assert all(len(r.token_ids) == 4 for r in results)
        assert eng.engine_restarts == 1
        # the pool survived the crash in a clean state and re-registered the
        # prefix from post-restart traffic
        deadline = time.monotonic() + 10
        while eng.kv_stats()["kv_pages_used"] > eng.kv_stats()["kv_shared_pages"]:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        st = eng.kv_stats()
        assert st["kv_pages_used"] == st["kv_shared_pages"] > 0
        assert eng.supervision_stats()["healthy"] is True
    finally:
        eng.stop()


def test_nan_logits_quarantines_one_slot_keeps_batch_alive():
    """Request-poison: garbage sampled ids fail ONE co-batched request; its
    batch-mate keeps decoding to a normal finish.  No engine restart."""
    inj = FaultInjector({})
    eng = _tiny_engine(faults=inj, max_slots=2).start()
    try:
        futs = [
            eng.submit([1, 2, 3], max_tokens=48, temperature=0.0),
            eng.submit([4, 5, 6], max_tokens=48, temperature=0.0),
        ]
        deadline = time.monotonic() + 30
        while eng.num_active < 2 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert eng.num_active == 2
        inj.arm("nan_logits")
        outcomes = []
        for f in futs:
            try:
                outcomes.append(("ok", f.result(timeout=120)))
            except RequestPoisoned as e:
                outcomes.append(("poisoned", e))
        kinds = sorted(k for k, _ in outcomes)
        assert kinds == ["ok", "poisoned"]
        ok = next(r for k, r in outcomes if k == "ok")
        assert len(ok.token_ids) == 48
        assert eng.poisoned_requests == 1
        assert eng.engine_restarts == 0  # quarantine, not restart
    finally:
        eng.stop()


def test_detok_raise_quarantines_request_engine_keeps_serving():
    inj = FaultInjector({})
    eng = _tiny_engine(faults=inj).start()
    try:
        inj.arm("detok_raise")
        fut = eng.submit([1, 2, 3], max_tokens=4, temperature=0.0)
        with pytest.raises(FaultInjected, match="detok_raise"):
            fut.result(timeout=120)
        assert eng.poisoned_requests == 1
        r = eng.submit([1, 2, 3], max_tokens=4, temperature=0.0).result(timeout=120)
        assert len(r.token_ids) == 4
    finally:
        eng.stop()


def test_restart_fails_stream_past_first_delta_but_preserves_queued():
    """A streamed request that already emitted deltas cannot be replayed (the
    client would see divergent text) — on restart it fails cleanly; a queued
    request rides through untouched."""
    inj = FaultInjector({})
    eng = _tiny_engine(faults=inj, max_slots=1, max_seq_len=128).start()

    async def go():
        agen = eng.generate_stream("hello", max_tokens=64, temperature=0.0)
        first = await agen.__anext__()
        assert first.token_id is not None
        # now a queued request behind the 1-slot stream, then the fatal fault
        queued = eng.submit([9, 8, 7], max_tokens=4, temperature=0.0)
        inj.arm("tick_raise")
        with pytest.raises(FaultInjected):
            async for _ in agen:
                pass
        return queued

    try:
        queued = asyncio.run(go())
        assert len(queued.result(timeout=120).token_ids) == 4
        assert eng.engine_restarts == 1
        # the streamed request was NOT re-submitted (it was past first delta)
        assert eng.supervision_stats()["restarted_requests_resubmitted"] == 0
    finally:
        eng.stop()


def test_persistent_fault_trips_circuit_submit_fast_fails():
    """max_restarts restarts inside the window open the circuit: the engine
    goes degraded and submit() fails synchronously with EngineUnavailable
    carrying a Retry-After hint."""
    inj = FaultInjector({"tick_raise": {"every": 1}})  # every tick dies
    eng = _tiny_engine(
        faults=inj,
        max_slots=1,
        max_restarts=2,
        restart_window_s=60.0,
        restart_backoff_s=0.005,
        restart_backoff_max_s=0.02,
        degraded_cooldown_s=600.0,  # long: the trip itself is the assertion
        max_request_restarts=1,
    ).start()
    try:
        fut = eng.submit([1, 2, 3], max_tokens=4, temperature=0.0)
        deadline = time.monotonic() + 60
        while not eng.degraded() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.degraded()
        assert eng.circuit_trips == 1
        with pytest.raises(FaultInjected):
            fut.result(timeout=120)  # exhausted its max_request_restarts
        with pytest.raises(EngineUnavailable) as ei:
            eng.submit([4, 5], max_tokens=2)
        assert ei.value.retry_after_s > 0
        assert eng.supervision_stats()["healthy"] is False
    finally:
        eng.stop()


def test_circuit_half_open_recovers_after_cooldown():
    """Once the fault stops firing, the cooldown expiry half-opens the circuit
    and the engine serves again."""
    inj = FaultInjector({"tick_raise": {"every": 1, "max_fires": 3}})
    eng = _tiny_engine(
        faults=inj,
        max_slots=1,
        max_restarts=2,
        restart_backoff_s=0.005,
        restart_backoff_max_s=0.02,
        degraded_cooldown_s=0.2,
        max_request_restarts=0,
    ).start()
    try:
        fut = eng.submit([1, 2, 3], max_tokens=4, temperature=0.0)
        with pytest.raises(FaultInjected):
            fut.result(timeout=120)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                r = eng.submit([1, 2, 3], max_tokens=4, temperature=0.0).result(
                    timeout=120
                )
                break
            except (EngineUnavailable, FaultInjected):
                time.sleep(0.05)
        else:
            pytest.fail("engine never recovered after the fault stopped")
        assert len(r.token_ids) == 4
        assert not eng.degraded()
    finally:
        eng.stop()


# ------------------------------------------------------- HTTP surface mapping
@pytest.fixture()
def http_registry():
    registry = ModelRegistry.from_config(
        {"tiny-chat": {"kind": "decoder", "tiny": True, "max_slots": 2,
                       "max_seq_len": 64}}
    )
    yield registry
    registry.stop()


def test_healthz_degraded_and_503_mapping(http_registry):
    eng = http_registry.get_generator("tiny-chat")

    async def go(client):
        resp = await client.get("/healthz")
        data = await resp.json()
        assert data["status"] == "ok"
        sup = data["generators"]["tiny-chat"]["supervision"]
        assert sup["healthy"] is True
        assert "loop_heartbeat_age_s" in sup
        assert sup["engine_restarts"] == 0

        # trip the circuit: /dialog/ must map EngineUnavailable -> 503
        eng._degraded_until = time.monotonic() + 30.0
        resp = await client.post(
            "/dialog/",
            json={"model": "tiny-chat",
                  "messages": [{"role": "user", "content": "hi"}],
                  "max_tokens": 2},
        )
        assert resp.status == 503
        assert int(resp.headers["Retry-After"]) >= 1
        assert "degraded" in (await resp.json())["detail"]
        # streaming requests fast-fail with the same mapping
        resp = await client.post(
            "/dialog/",
            json={"model": "tiny-chat", "stream": True,
                  "messages": [{"role": "user", "content": "hi"}],
                  "max_tokens": 2},
        )
        assert resp.status == 503

        resp = await client.get("/healthz")
        data = await resp.json()
        assert data["status"] == "degraded"
        assert data["generators"]["tiny-chat"]["supervision"]["degraded"] is True
        eng._degraded_until = None

        # wedged-loop detection: a heartbeat older than the threshold flips
        # status even though cached stats still look green
        eng.heartbeat_degraded_s = 1e-9
        resp = await client.get("/healthz")
        assert (await resp.json())["status"] == "degraded"
        eng.heartbeat_degraded_s = 30.0

    _run_with_client(http_registry, go)


def _run_with_client(registry, go):
    from aiohttp.test_utils import TestClient, TestServer

    async def main():
        client = TestClient(TestServer(create_app(registry)))
        await client.start_server()
        try:
            await go(client)
        finally:
            await client.close()

    asyncio.run(main())


# ------------------------------------------------------------------- failover
class _StubProvider:
    """Scripted backend: each call pops an outcome — an Exception to raise or
    a text to answer."""

    def __init__(self, outcomes):
        self.outcomes = list(outcomes)
        self.calls = 0
        self.calls_attempts = []

    @property
    def context_size(self):
        return 1000

    def calculate_tokens(self, text):
        return len(text)

    async def get_response(self, messages, max_tokens=1024, json_format=False):
        from django_assistant_bot_tpu.ai.domain import AIResponse

        self.calls += 1
        out = self.outcomes.pop(0) if self.outcomes else "default"
        if isinstance(out, Exception):
            raise out
        return AIResponse(result=out, usage=None)

    async def stream_response(self, messages, max_tokens=1024, json_format=False):
        from django_assistant_bot_tpu.ai.providers.base import AIStreamChunk

        resp = await self.get_response(messages, max_tokens, json_format)
        mid = max(1, len(resp.result) // 2)
        yield AIStreamChunk(delta=resp.result[:mid])
        if resp.result == "die-mid-stream":
            raise RuntimeError("backend died mid-stream")
        yield AIStreamChunk(delta=resp.result[mid:])
        yield AIStreamChunk(done=True, response=resp)


def _chain(*provs, clock=None, **kw):
    from django_assistant_bot_tpu.ai.providers.failover import FailoverProvider

    kw.setdefault("backoff_s", 0.0)
    if clock is not None:
        kw["clock"] = clock
    return FailoverProvider(list(provs), names=[f"b{i}" for i in range(len(provs))], **kw)


def test_failover_chain_ordering_and_breaker():
    from django_assistant_bot_tpu.ai.providers.failover import AllBackendsFailed

    now = [0.0]
    bad = _StubProvider([RuntimeError("down")] * 10)
    good = _StubProvider(["answer-1", "answer-2", "answer-3"])
    fp = _chain(bad, good, clock=lambda: now[0],
                breaker_threshold=1, breaker_reset_s=100.0)

    async def go():
        r1 = await fp.get_response([{"role": "user", "content": "q"}])
        assert r1.result == "answer-1"
        assert fp.breaker_states() == {"b0": "open", "b1": "closed"}
        assert fp.calls_attempts[-1] == 2  # tried bad, then good
        # circuit open: the dead backend is skipped entirely
        r2 = await fp.get_response([{"role": "user", "content": "q"}])
        assert r2.result == "answer-2"
        assert bad.calls == 1
        assert fp.calls_attempts[-1] == 1
        # cooldown elapses -> half-open probe hits the bad backend once,
        # fails, and re-opens
        now[0] += 101.0
        r3 = await fp.get_response([{"role": "user", "content": "q"}])
        assert r3.result == "answer-3"
        assert bad.calls == 2
        assert fp.breaker_states()["b0"] == "open"
        # every backend down -> AllBackendsFailed naming each error
        dead = _chain(_StubProvider([RuntimeError("x")] * 5),
                      _StubProvider([RuntimeError("y")] * 5))
        with pytest.raises(AllBackendsFailed, match="b1"):
            await dead.get_response([{"role": "user", "content": "q"}])

    asyncio.run(go())


def test_breaker_cancelled_probe_releases_slot():
    """A half-open probe whose caller is cancelled must free the probe slot
    (neither success nor failure) — otherwise the backend blocks forever."""
    from django_assistant_bot_tpu.ai.providers.failover import CircuitBreaker

    now = [0.0]
    br = CircuitBreaker(failure_threshold=1, reset_timeout_s=10.0, clock=lambda: now[0])
    br.record_failure()
    assert br.state == "open" and not br.allow()
    now[0] += 11.0
    assert br.allow()  # admitted as the probe
    assert not br.allow()  # one probe at a time
    br.release_probe()  # probe's caller was cancelled mid-flight
    assert br.allow()  # the next request may probe
    br.record_success()
    assert br.state == "closed"


def test_failover_breaker_closes_after_successful_probe():
    now = [0.0]
    flaky = _StubProvider([RuntimeError("down"), "recovered", "recovered-2"])
    good = _StubProvider(["fallback"] * 5)
    fp = _chain(flaky, good, clock=lambda: now[0],
                breaker_threshold=1, breaker_reset_s=50.0)

    async def go():
        assert (await fp.get_response([])).result == "fallback"
        now[0] += 51.0
        assert (await fp.get_response([])).result == "recovered"
        assert fp.breaker_states()["b0"] == "closed"
        assert (await fp.get_response([])).result == "recovered-2"

    asyncio.run(go())


def test_failover_streaming_before_first_delta_only():
    bad = _StubProvider([RuntimeError("down")])
    good = _StubProvider(["streamed answer"])
    fp = _chain(bad, good)

    async def collect(provider):
        deltas, final = [], None
        async for c in provider.stream_response([{"role": "user", "content": "q"}]):
            if c.done:
                final = c.response
            else:
                deltas.append(c.delta)
        return deltas, final

    async def go():
        deltas, final = await collect(fp)
        assert "".join(deltas) == "streamed answer"
        assert final.result == "streamed answer"
        # past the first delta the response is committed: a mid-stream death
        # surfaces to the consumer instead of silently switching backends
        mid = _chain(_StubProvider(["die-mid-stream"]), good)
        with pytest.raises(RuntimeError, match="mid-stream"):
            await collect(mid)

    asyncio.run(go())


class _HangingStreamProvider:
    """First stream call hangs before its first delta (cancellation is the
    only way out); later calls stream normally.  The half-open-probe shape:
    a recovering backend that stalls its probe request."""

    def __init__(self):
        self.calls = 0
        self.calls_attempts = []

    @property
    def context_size(self):
        return 1000

    def calculate_tokens(self, text):
        return len(text)

    async def get_response(self, messages, max_tokens=1024, json_format=False):
        raise NotImplementedError

    async def stream_response(self, messages, max_tokens=1024, json_format=False):
        from django_assistant_bot_tpu.ai.domain import AIResponse
        from django_assistant_bot_tpu.ai.providers.base import AIStreamChunk

        self.calls += 1
        if self.calls == 1:
            await asyncio.Event().wait()  # hang until cancelled
        yield AIStreamChunk(delta="recovered")
        yield AIStreamChunk(
            done=True, response=AIResponse(result="recovered", usage=None)
        )


def test_failover_streaming_cancelled_half_open_probe_releases_slot():
    """Satellite of the PR 5 review fix, extended to the STREAMING path under
    concurrent consumers: the one half-open probe stream hangs pre-first-delta
    and is cancelled — the probe slot must free so the next concurrent stream
    can probe the backend (without the fix the breaker blocks forever)."""
    from django_assistant_bot_tpu.ai.providers.failover import AllBackendsFailed

    now = [0.0]
    prov = _HangingStreamProvider()
    fp = _chain(prov, clock=lambda: now[0], breaker_threshold=1,
                breaker_reset_s=10.0)

    async def consume():
        deltas = []
        async for c in fp.stream_response([{"role": "user", "content": "q"}]):
            if not c.done:
                deltas.append(c.delta)
        return deltas

    async def go():
        fp._breakers[0].record_failure()
        assert fp.breaker_states()["b0"] == "open"
        now[0] += 11.0  # cooldown elapsed: next caller is THE probe
        t1 = asyncio.create_task(consume())
        await asyncio.sleep(0.01)  # t1 claimed the probe and hangs
        # a concurrent stream cannot enter: the probe slot is held
        with pytest.raises(AllBackendsFailed, match="circuit open"):
            await consume()
        t1.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t1
        # the cancelled probe released its slot: the next stream probes,
        # commits, and closes the circuit
        assert await consume() == ["recovered"]
        assert fp.breaker_states()["b0"] == "closed"

    asyncio.run(go())


class _ParkedAwaitable:
    """Yields once and parks — lets a test drive an async generator by hand
    (no event loop) to a suspension point inside a backend await."""

    def __await__(self):
        yield self


def test_failover_streaming_abandoned_probe_releases_slot_on_generator_exit():
    """aclose() on the failover stream while it is suspended at the backend
    await delivers GeneratorExit — NOT CancelledError — at the await point;
    the probe slot must free on that path too (the streaming extension of the
    cancelled-probe fix: a consumer that abandons the generator, e.g. the SSE
    handler's finally-aclose after a disconnect, must not wedge the breaker)."""
    now = [0.0]

    class _Parked(_HangingStreamProvider):
        async def stream_response(self, messages, max_tokens=1024, json_format=False):
            self.calls += 1
            await _ParkedAwaitable()
            yield None  # pragma: no cover - never reached

    fp = _chain(_Parked(), clock=lambda: now[0], breaker_threshold=1,
                breaker_reset_s=10.0)
    br = fp._breakers[0]
    br.record_failure()
    now[0] += 11.0
    agen = fp.stream_response([{"role": "user", "content": "q"}])
    step = agen.__anext__()
    step.send(None)  # drive to the backend await: the probe slot is claimed
    assert br._probing is True
    # finalizing the abandoned consumer coroutine delivers GeneratorExit AT
    # the backend await point (what coroutine cleanup does for a consumer
    # that vanished without cancelling) — the handler must free the slot
    with pytest.raises(GeneratorExit):
        step.throw(GeneratorExit)
    assert br._probing is False  # slot released — the next request may probe
    assert br.allow() is True
    br.release_probe()


def test_failover_model_routing():
    from django_assistant_bot_tpu.ai.providers.failover import FailoverProvider
    from django_assistant_bot_tpu.ai.services.ai_service import get_ai_provider

    fp = get_ai_provider("failover:test:a|test:b")
    assert isinstance(fp, FailoverProvider)
    assert fp.breaker_states() == {"test:a": "closed", "test:b": "closed"}

    async def go():
        r = await fp.get_response([{"role": "user", "content": "ping"}])
        assert r.result == "echo: ping"

    asyncio.run(go())
    with pytest.raises(ValueError):
        get_ai_provider("failover:")


# ------------------------------------------------- HTTP client retry policy
def test_parse_retry_after_formats():
    from datetime import datetime, timedelta, timezone

    from django_assistant_bot_tpu.ai.providers.http_service import parse_retry_after

    assert parse_retry_after("2.5") == 2.5
    assert parse_retry_after("0") == 0.0
    assert parse_retry_after(None) is None
    assert parse_retry_after("soon") is None
    future = datetime.now(timezone.utc) + timedelta(seconds=30)
    got = parse_retry_after(format_datetime(future, usegmt=True))
    assert got is not None and 25.0 < got <= 31.0
    past = datetime.now(timezone.utc) - timedelta(seconds=30)
    assert parse_retry_after(format_datetime(past, usegmt=True)) == 0.0


def test_post_retries_connection_errors_and_503(monkeypatch):
    """Injected conn_reset then http_5xx: the idempotent POST retries both and
    lands on the real (healthy) server; non-idempotent requests surface the
    connection error immediately."""
    import aiohttp
    from aiohttp import web as aioweb
    from aiohttp.test_utils import TestClient, TestServer

    from django_assistant_bot_tpu.ai.providers import http_service

    monkeypatch.setattr(http_service, "RETRY_BACKOFF_BASE_S", 0.01)
    hits = {"n": 0}

    async def handler(request):
        hits["n"] += 1
        return aioweb.json_response({"ok": True})

    app = aioweb.Application()
    app.router.add_post("/echo", handler)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            async with aiohttp.ClientSession() as session:
                # attempt 1: conn_reset fires (http_5xx never consulted);
                # attempt 2: conn_reset is past its schedule, http_5xx's FIRST
                # consultation fires; attempt 3 reaches the healthy server
                inj = FaultInjector(
                    {"conn_reset": {"fire_on": [1]}, "http_5xx": {"fire_on": [1]}}
                )
                set_global_injector(inj)
                resp = await http_service._post_with_shed_retry(
                    session, str(client.make_url("/echo")), {"x": 1}
                )
                assert (await resp.json()) == {"ok": True}
                assert hits["n"] == 1  # two injected failures, one real hit
                assert inj.stats()["conn_reset"]["fires"] == 1
                assert inj.stats()["http_5xx"]["fires"] == 1

                # non-idempotent: a connection error must NOT be retried
                set_global_injector(
                    FaultInjector({"conn_reset": {"fire_on": [1]}})
                )
                with pytest.raises(ConnectionResetError):
                    await http_service._post_with_shed_retry(
                        session, str(client.make_url("/echo")), {"x": 2}, idempotent=False
                    )
                assert hits["n"] == 1
        finally:
            set_global_injector(None)
            reset_global_injector()
            await client.close()

    asyncio.run(go())


def test_post_retries_real_503_with_http_date_retry_after(monkeypatch):
    """A real 503 + HTTP-date Retry-After (RFC 9110) is honored, then the
    recovered server answers; a 400 never retries."""
    from datetime import datetime, timezone

    from aiohttp import ClientResponseError, ClientSession
    from aiohttp import web as aioweb
    from aiohttp.test_utils import TestClient, TestServer

    from django_assistant_bot_tpu.ai.providers import http_service

    monkeypatch.setattr(http_service, "RETRY_BACKOFF_BASE_S", 0.01)
    hits = {"flaky": 0, "bad": 0}

    async def flaky(request):
        hits["flaky"] += 1
        if hits["flaky"] == 1:
            return aioweb.json_response(
                {"detail": "degraded"},
                status=503,
                headers={
                    "Retry-After": format_datetime(
                        datetime.now(timezone.utc), usegmt=True
                    )
                },
            )
        return aioweb.json_response({"ok": True})

    async def bad(request):
        hits["bad"] += 1
        return aioweb.json_response({"detail": "nope"}, status=400)

    app = aioweb.Application()
    app.router.add_post("/flaky", flaky)
    app.router.add_post("/bad", bad)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            async with ClientSession() as session:
                resp = await http_service._post_with_shed_retry(
                    session, str(client.make_url("/flaky")), {}
                )
                assert (await resp.json()) == {"ok": True}
                assert hits["flaky"] == 2
                with pytest.raises(ClientResponseError):
                    await http_service._post_with_shed_retry(
                        session, str(client.make_url("/bad")), {}
                    )
                assert hits["bad"] == 1  # 4xx is not retriable
        finally:
            await client.close()

    asyncio.run(go())
