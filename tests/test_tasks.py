"""Task plane: dispatch, retries, lease reclaim, groups/chords, eager mode, beat.

The reference tests its Celery path by invoking task bodies directly (SURVEY.md
§4); here the broker is in-process sqlite so the REAL dispatch path runs in tests.
"""

import time

import pytest

from django_assistant_bot_tpu.conf import settings
from django_assistant_bot_tpu.tasks import Beat, TaskRecord, Worker, group, task

calls = []


@task(queue="query", max_retries=2, retry_delay=0.0)
def add_task(a, b):
    calls.append(("add", a, b))
    return a + b


@task(queue="processing", max_retries=2, retry_delay=0.0)
def flaky_task(fail_times):
    calls.append(("flaky",))
    if len([c for c in calls if c == ("flaky",)]) <= fail_times:
        raise RuntimeError("boom")
    return "ok"


@task(queue="processing")
def member_task(n):
    calls.append(("member", n))


@task(queue="processing")
def finalize_task():
    calls.append(("finalize",))


@task(queue="query")
async def async_task(x):
    calls.append(("async", x))
    return x * 2


@pytest.fixture(autouse=True)
def _fresh(tmp_db):
    calls.clear()
    yield


def test_delay_and_worker_executes():
    rec = add_task.delay(2, 3)
    assert rec.status == "pending"
    n = Worker(["query"]).run_until_idle()
    assert n == 1
    rec.refresh()
    assert rec.status == "done" and rec.result == 5
    assert calls == [("add", 2, 3)]


def test_async_task_body():
    async_task.delay(21)
    Worker(["query"]).run_until_idle()
    assert calls == [("async", 21)]


def test_retry_then_success():
    rec = flaky_task.delay(2)
    w = Worker(["processing"])
    for _ in range(5):
        w.run_until_idle()
    rec.refresh()
    assert rec.status == "done" and rec.result == "ok"
    assert len(calls) == 3  # 2 failures + 1 success


def test_retries_exhausted_marks_failed():
    rec = flaky_task.delay(99)
    w = Worker(["processing"])
    for _ in range(6):
        w.run_until_idle()
    rec.refresh()
    assert rec.status == "failed"
    assert "boom" in rec.error
    assert len(calls) == 3  # initial + 2 retries


def test_lease_reclaim_on_worker_death():
    rec = add_task.delay(1, 1)
    # simulate a worker that claimed the row then died: lease in the past
    w = Worker(["query"], lease_s=-1.0)
    claimed = w.claim()
    assert claimed.id == rec.id
    rec.refresh()
    assert rec.status == "running"
    # another worker's poll reclaims and executes it
    n = Worker(["query"]).run_until_idle()
    assert n == 1
    rec.refresh()
    assert rec.status == "done"


def test_group_chord_fires_once_after_all_members():
    group(
        [(member_task, (i,), {}) for i in range(3)],
        chord=(finalize_task, (), {}),
    )
    w = Worker(["processing"])
    w.run_until_idle()
    # chord enqueued after last member; drain again
    w.run_until_idle()
    members = [c for c in calls if c[0] == "member"]
    finals = [c for c in calls if c[0] == "finalize"]
    assert len(members) == 3 and len(finals) == 1
    # finalize ran after every member
    assert calls.index(finals[0]) > max(calls.index(m) for m in members)


def test_eager_mode_runs_inline():
    with settings.override(TASK_ALWAYS_EAGER=True):
        rec = add_task.delay(4, 5)
    assert rec is None
    assert calls == [("add", 4, 5)]
    assert TaskRecord.objects.count() == 0


def test_queue_isolation():
    add_task.delay(1, 2)
    member_task.delay(7)
    Worker(["query"]).run_until_idle()
    assert ("add", 1, 2) in calls and ("member", 7) not in calls
    Worker(["processing"]).run_until_idle()
    assert ("member", 7) in calls


_contention_lock = __import__("threading").Lock()
contention_runs = []


@task(queue="query")
def contention_task(n):
    with _contention_lock:
        contention_runs.append(n)


def test_multi_worker_write_contention_exactly_once():
    """The sqlite substrate under the reference's Postgres+Redis deployment shape:
    several producers enqueue while several multi-thread workers claim from the
    same database file.  WAL + busy-timeout + the atomic claim UPDATE must yield
    each task to exactly one worker with no lost or duplicated executions."""
    import threading

    contention_runs.clear()
    N_PRODUCERS, PER_PRODUCER = 3, 40
    total = N_PRODUCERS * PER_PRODUCER

    workers = [Worker(["query"], concurrency=2, poll_s=0.01).start() for _ in range(2)]
    try:
        producers = [
            threading.Thread(
                target=lambda base: [
                    contention_task.delay(base + i) for i in range(PER_PRODUCER)
                ],
                args=(p * PER_PRODUCER,),
            )
            for p in range(N_PRODUCERS)
        ]
        for t in producers:
            t.start()
        for t in producers:
            t.join()
        deadline = time.time() + 30
        while time.time() < deadline and len(contention_runs) < total:
            time.sleep(0.05)
    finally:
        for w in workers:
            w.stop()

    assert sorted(contention_runs) == list(range(total))  # no loss, no duplicates
    records = TaskRecord.objects.filter(name__contains="contention_task").all()
    assert len(records) == total
    assert all(r.status == "done" and r.attempts == 1 for r in records)


def test_beat_enqueues_on_cadence():
    beat = Beat().add(add_task, 1000.0, 1, 1)
    now = time.monotonic()
    assert beat.tick(now) == 1  # fires immediately
    assert beat.tick(now + 1) == 0  # not due
    assert beat.tick(now + 1001) == 1
    assert TaskRecord.objects.filter(name=add_task.name).count() == 2
