"""Task plane: dispatch, retries, lease reclaim, groups/chords, eager mode, beat.

The reference tests its Celery path by invoking task bodies directly (SURVEY.md
§4); here the broker is in-process sqlite so the REAL dispatch path runs in tests.

Exactly-once-effect coverage (docs/RESILIENCE.md "Task plane"): error
taxonomy (permanent vs transient vs RetryLater), dead-letter queue + CLI,
full-jitter backoff, lease heartbeats + ownership-guarded transitions, the
worker-loss attempt-budget boundary, graceful drain, queue stats/metrics.
"""

import datetime as dt
import random
import threading
import time

import pytest

from django_assistant_bot_tpu.conf import settings
from django_assistant_bot_tpu.tasks import (
    Beat,
    PermanentTaskError,
    RetryLater,
    TaskRecord,
    Worker,
    backoff_delay,
    group,
    queue_stats,
    task,
)


class FakeClock:
    """Injectable wall clock for lease/reclaim/backoff determinism.

    Starts slightly AHEAD of real wall time so rows enqueued with real-clock
    etas (Task.delay) are due immediately under the fake clock."""

    def __init__(self, t: float = None):
        self.t = time.time() + 60.0 if t is None else t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt_s: float) -> None:
        self.t += dt_s


class FakeWorkerLost(RuntimeError):
    """Duck-typed stand-in for FaultInjected(site='task_worker_lost') — the
    worker-death simulation without importing the serving package."""

    site = "task_worker_lost"


calls = []


@task(queue="query", max_retries=2, retry_delay=0.0)
def add_task(a, b):
    calls.append(("add", a, b))
    return a + b


@task(queue="processing", max_retries=2, retry_delay=0.0)
def flaky_task(fail_times):
    calls.append(("flaky",))
    if len([c for c in calls if c == ("flaky",)]) <= fail_times:
        raise RuntimeError("boom")
    return "ok"


@task(queue="processing")
def member_task(n):
    calls.append(("member", n))


@task(queue="processing")
def finalize_task():
    calls.append(("finalize",))


@task(queue="query")
async def async_task(x):
    calls.append(("async", x))
    return x * 2


@pytest.fixture(autouse=True)
def _fresh(tmp_db):
    calls.clear()
    yield


def test_delay_and_worker_executes():
    rec = add_task.delay(2, 3)
    assert rec.status == "pending"
    n = Worker(["query"]).run_until_idle()
    assert n == 1
    rec.refresh()
    assert rec.status == "done" and rec.result == 5
    assert calls == [("add", 2, 3)]


def test_async_task_body():
    async_task.delay(21)
    Worker(["query"]).run_until_idle()
    assert calls == [("async", 21)]


def test_retry_then_success():
    rec = flaky_task.delay(2)
    w = Worker(["processing"])
    for _ in range(5):
        w.run_until_idle()
    rec.refresh()
    assert rec.status == "done" and rec.result == "ok"
    assert len(calls) == 3  # 2 failures + 1 success


def test_retries_exhausted_dead_letters():
    rec = flaky_task.delay(99)
    w = Worker(["processing"])
    for _ in range(6):
        w.run_until_idle()
    rec.refresh()
    assert rec.status == "dead"
    assert rec.error_kind == "transient_exhausted"
    assert rec.dead_at is not None
    assert "boom" in rec.error
    assert len(calls) == 3  # initial + 2 retries
    assert w.stats()["dead_lettered"] == 1


def test_lease_reclaim_on_worker_death():
    rec = add_task.delay(1, 1)
    # simulate a worker that claimed the row then died: lease in the past
    w = Worker(["query"], lease_s=-1.0)
    claimed = w.claim()
    assert claimed.id == rec.id
    rec.refresh()
    assert rec.status == "running"
    # another worker's poll reclaims and executes it
    n = Worker(["query"]).run_until_idle()
    assert n == 1
    rec.refresh()
    assert rec.status == "done"


def test_group_chord_fires_once_after_all_members():
    group(
        [(member_task, (i,), {}) for i in range(3)],
        chord=(finalize_task, (), {}),
    )
    w = Worker(["processing"])
    w.run_until_idle()
    # chord enqueued after last member; drain again
    w.run_until_idle()
    members = [c for c in calls if c[0] == "member"]
    finals = [c for c in calls if c[0] == "finalize"]
    assert len(members) == 3 and len(finals) == 1
    # finalize ran after every member
    assert calls.index(finals[0]) > max(calls.index(m) for m in members)


def test_eager_mode_runs_inline():
    with settings.override(TASK_ALWAYS_EAGER=True):
        rec = add_task.delay(4, 5)
    assert rec is None
    assert calls == [("add", 4, 5)]
    assert TaskRecord.objects.count() == 0


def test_queue_isolation():
    add_task.delay(1, 2)
    member_task.delay(7)
    Worker(["query"]).run_until_idle()
    assert ("add", 1, 2) in calls and ("member", 7) not in calls
    Worker(["processing"]).run_until_idle()
    assert ("member", 7) in calls


_contention_lock = __import__("threading").Lock()
contention_runs = []


@task(queue="query")
def contention_task(n):
    with _contention_lock:
        contention_runs.append(n)


def test_multi_worker_write_contention_exactly_once():
    """The sqlite substrate under the reference's Postgres+Redis deployment shape:
    several producers enqueue while several multi-thread workers claim from the
    same database file.  WAL + busy-timeout + the atomic claim UPDATE must yield
    each task to exactly one worker with no lost or duplicated executions."""
    import threading

    contention_runs.clear()
    N_PRODUCERS, PER_PRODUCER = 3, 40
    total = N_PRODUCERS * PER_PRODUCER

    workers = [Worker(["query"], concurrency=2, poll_s=0.01).start() for _ in range(2)]
    try:
        producers = [
            threading.Thread(
                target=lambda base: [
                    contention_task.delay(base + i) for i in range(PER_PRODUCER)
                ],
                args=(p * PER_PRODUCER,),
            )
            for p in range(N_PRODUCERS)
        ]
        for t in producers:
            t.start()
        for t in producers:
            t.join()
        deadline = time.time() + 30
        while time.time() < deadline and len(contention_runs) < total:
            time.sleep(0.05)
    finally:
        for w in workers:
            w.stop()

    assert sorted(contention_runs) == list(range(total))  # no loss, no duplicates
    records = TaskRecord.objects.filter(name__contains="contention_task").all()
    assert len(records) == total
    assert all(r.status == "done" and r.attempts == 1 for r in records)


def test_beat_enqueues_on_cadence():
    beat = Beat().add(add_task, 1000.0, 1, 1)
    now = time.monotonic()
    assert beat.tick(now) == 1  # fires immediately
    assert beat.tick(now + 1) == 0  # not due
    assert beat.tick(now + 1001) == 1
    assert TaskRecord.objects.filter(name=add_task.name).count() == 2


# ------------------------------------------------------------- error taxonomy
@task(queue="tax", max_retries=5, retry_delay=0.0)
def permanent_task():
    calls.append(("permanent",))
    raise PermanentTaskError("this row will never exist")


@task(queue="tax", max_retries=3, retry_delay=0.0)
def flood_task():
    calls.append(("flood",))
    if len([c for c in calls if c == ("flood",)]) == 1:
        raise RetryLater(30.0, "platform says wait")
    return "ok"


def test_permanent_error_dead_letters_without_retry_burn():
    """Permanent failures skip the whole retry budget: one execution, DLQ."""
    rec = permanent_task.delay()
    w = Worker(["tax"])
    for _ in range(3):
        w.run_until_idle()
    rec.refresh()
    assert rec.status == "dead" and rec.error_kind == "permanent"
    assert rec.attempts == 1 and len(calls) == 1
    assert "never exist" in rec.error


def test_unknown_task_dead_letters():
    rec = TaskRecord.objects.create(queue="tax", name="nowhere.no_such_task", eta=None)
    Worker(["tax"]).run_until_idle()
    rec.refresh()
    assert rec.status == "dead" and rec.error_kind == "unknown_task"
    assert "unknown task" in rec.error


def test_retry_later_honors_platform_delay():
    """RetryLater(30) re-schedules at exactly clock+30 (the platform's
    pacing, not the backoff curve) and does not run before the eta — driven
    end to end on the worker's injectable clock."""
    clk = FakeClock()
    rec = flood_task.delay()
    w = Worker(["tax"], clock=clk)
    w.run_until_idle()
    rec.refresh()
    assert rec.status == "pending" and len(calls) == 1
    eta_ts = dt.datetime.fromisoformat(rec.eta).timestamp()
    assert abs(eta_ts - (clk() + 30.0)) < 1e-3
    w.run_until_idle()  # not due yet
    assert len(calls) == 1
    clk.advance(29.0)
    w.run_until_idle()  # still not due
    assert len(calls) == 1
    clk.advance(2.0)
    w.run_until_idle()
    rec.refresh()
    assert rec.status == "done" and rec.result == "ok"


def test_backoff_full_jitter_capped():
    rng = random.Random(0)
    # attempt 1: uniform in [0, base]
    ds = [backoff_delay(60.0, 1, rng=rng) for _ in range(200)]
    assert all(0.0 <= d <= 60.0 for d in ds)
    assert max(ds) > 30.0  # actually jittered, not collapsed
    # deep attempts: ceiling is the cap, not base * 2^n
    ds = [backoff_delay(60.0, 20, cap_s=900.0, rng=rng) for _ in range(200)]
    assert all(0.0 <= d <= 900.0 for d in ds)
    assert max(ds) > 600.0
    # zero base (tests / immediate-retry tasks) stays zero
    assert backoff_delay(0.0, 3, rng=rng) == 0.0


# --------------------------------------------------- worker-loss budget boundary
loss_runs = []


@task(queue="loss", max_retries=2, retry_delay=0.0)
def lossy_task():
    loss_runs.append(1)
    raise FakeWorkerLost()


@task(queue="loss", max_retries=2, retry_delay=0.0)
def mixed_loss_task():
    loss_runs.append(1)
    if len(loss_runs) == 1:
        raise FakeWorkerLost()
    raise RuntimeError("boom after the loss")


def _drive_losses(rec, w, clk, rounds=8):
    for _ in range(rounds):
        w.run_one()
        clk.advance(w.lease_s + 1.0)  # expire whatever lease the "death" left
    rec.refresh()
    return rec


def test_worker_loss_budget_is_exactly_initial_plus_retries():
    """Pure worker loss: exactly 1 + max_retries executions, then the DLQ —
    and the exhausted row dead-letters AT RECLAIM (no extra claim cycle)."""
    loss_runs.clear()
    clk = FakeClock()
    rec = lossy_task.delay()
    w = Worker(["loss"], lease_s=10.0, heartbeat_s=0, clock=clk)
    _drive_losses(rec, w, clk)
    assert len(loss_runs) == 3  # 1 initial + 2 retries, not one more
    assert rec.status == "dead" and rec.error_kind == "worker_lost"
    assert rec.attempts == 3  # the DLQ transition consumed NO extra attempt
    s = w.stats()
    assert s["worker_lost_aborts"] == 3
    assert s["reclaimed_leases"] == 2  # losses 1..2 requeued; loss 3 dead at reclaim
    assert s["dead_lettered"] == 1


def test_worker_loss_mixed_with_normal_failures_shares_budget():
    loss_runs.clear()
    clk = FakeClock()
    rec = mixed_loss_task.delay()
    w = Worker(["loss"], lease_s=10.0, heartbeat_s=0, clock=clk)
    _drive_losses(rec, w, clk)
    assert len(loss_runs) == 3
    assert rec.status == "dead" and rec.error_kind == "transient_exhausted"


def test_worker_loss_zero_retries_edge():
    loss_runs.clear()

    @task(queue="loss", max_retries=0, retry_delay=0.0, name="loss.zero")
    def zero_retry_lossy():
        loss_runs.append(1)
        raise FakeWorkerLost()

    clk = FakeClock()
    rec = zero_retry_lossy.delay()
    w = Worker(["loss"], lease_s=10.0, heartbeat_s=0, clock=clk)
    _drive_losses(rec, w, clk, rounds=4)
    assert len(loss_runs) == 1
    assert rec.status == "dead" and rec.error_kind == "worker_lost"


# --------------------------------------------------------- heartbeats + leases
def test_lease_heartbeat_outlives_short_lease():
    """A task running LONGER than its lease is not double-executed: the
    executing worker renews the lease on a heartbeat, so a concurrent worker
    never reclaims it (the seed plane double-executed here)."""
    ran = []

    @task(queue="hb", name="hb.slow")
    def slow_hb_task():
        ran.append(1)
        time.sleep(2.2)
        return "slow done"

    rec = slow_hb_task.delay()
    w = Worker(["hb"], lease_s=1.0, heartbeat_s=0.25)
    rival = Worker(["hb"], lease_s=1.0, heartbeat_s=0.25)
    th = threading.Thread(target=w.run_one)
    th.start()
    try:
        # let w win the initial claim before the rival starts poaching
        deadline = time.time() + 4.0
        while time.time() < deadline:
            rec.refresh()
            if rec.status == "running":
                break
            time.sleep(0.02)
        assert rec.status == "running"
        stolen = 0
        while th.is_alive() and time.time() < deadline:
            if rival.claim() is not None:
                stolen += 1
            time.sleep(0.1)
    finally:
        th.join(timeout=10)
    rec.refresh()
    assert stolen == 0  # the heartbeat kept the lease warm the whole run
    assert ran == [1]
    assert rec.status == "done" and rec.result == "slow done"
    assert w.stats()["heartbeats"] >= 2


def test_lost_lease_completion_is_discarded():
    """A worker that lost its lease mid-run must not clobber the reclaiming
    owner's state with its late completion (ownership-guarded transitions)."""
    gate = threading.Event()
    started = threading.Event()

    @task(queue="steal", name="steal.gated")
    def gated_task():
        started.set()
        gate.wait(10)
        return "late"

    rec = gated_task.delay()
    w = Worker(["steal"], lease_s=300.0, heartbeat_s=0)
    th = threading.Thread(target=w.run_one)
    th.start()
    try:
        # wait for the task BODY, not just status=="running": the worker does an
        # ownership-guarded attempts write between claim and execution, and a
        # thief installed inside that window is counted leases_lost (the worker
        # never runs the body), not completions_discarded
        assert started.wait(5.0)
        rec.refresh()
        assert rec.status == "running"
        # simulate a reclaim: another worker now owns the row
        TaskRecord.objects.filter(id=rec.id).update(lease_owner="thief")
    finally:
        gate.set()
        th.join(timeout=10)
    rec.refresh()
    assert rec.lease_owner == "thief" and rec.status == "running"
    assert rec.result is None  # the late "done" write was discarded
    assert w.stats()["completions_discarded"] == 1


# ------------------------------------------------------------------ drain/stop
def test_drain_finishes_inflight_and_stops_claiming():
    gate = threading.Event()
    finished = []

    @task(queue="drainq", name="drainq.slow")
    def drain_slow_task():
        gate.wait(10)
        finished.append(1)

    drain_slow_task.delay()
    for i in range(3):
        add_task.delay(i, i)  # queued behind, on another queue name? no: drainq only
    pending_before = TaskRecord.objects.filter(status="pending").count()
    w = Worker(["drainq"], poll_s=0.01).start()
    deadline = time.time() + 5.0
    while not TaskRecord.objects.filter(status="running").count() and time.time() < deadline:
        time.sleep(0.02)
    result: list = []
    t = threading.Thread(target=lambda: result.append(w.drain(timeout_s=10.0)))
    t.start()
    time.sleep(0.3)
    assert not result  # drain WAITS for the in-flight task
    gate.set()
    t.join(timeout=10)
    assert result == [True]
    assert finished == [1]
    w.stop(timeout_s=1.0)
    # the add_task rows (other queue) were never claimed by this worker
    assert TaskRecord.objects.filter(status="pending").count() == pending_before - 1


def test_release_claim_returns_row_to_pending():
    rec = add_task.delay(5, 6)
    w = Worker(["query"])
    claimed = w.claim()
    assert claimed.id == rec.id
    w._release_claim(claimed)
    rec.refresh()
    assert rec.status == "pending" and rec.lease_owner is None
    # and it still executes normally afterwards
    Worker(["query"]).run_until_idle()
    rec.refresh()
    assert rec.status == "done"


# ------------------------------------------------------------- chords with DLQ
@task(queue="processing")
def poison_member(n):
    calls.append(("poison", n))
    raise PermanentTaskError("bad member")


def test_legacy_failed_rows_migrate_and_never_block_chords():
    """A DB written by the pre-DLQ plane may hold terminal status='failed'
    rows: they must count as settled for their chord and surface in the DLQ
    (claim()'s one-shot migration), not zombie forever."""
    records = group(
        [(member_task, (1,), {}), (member_task, (2,), {})],
        chord=(finalize_task, (), {}),
    )
    # simulate the old plane having exhausted member 1 before the upgrade
    TaskRecord.objects.filter(id=records[0].id).update(status="failed")
    w = Worker(["processing"])
    for _ in range(3):
        w.run_until_idle()
    finals = [c for c in calls if c[0] == "finalize"]
    assert len(finals) == 1  # the legacy-failed member did not wedge the chord
    legacy = TaskRecord.objects.get(id=records[0].id)
    assert legacy.status == "dead"  # migrated: visible to dlq list/requeue
    assert legacy.error_kind == "transient_exhausted"


def test_chord_fires_once_when_member_dead_letters():
    group(
        [
            (member_task, (1,), {}),
            (poison_member, (2,), {}),
            (member_task, (3,), {}),
        ],
        chord=(finalize_task, (), {}),
    )
    w = Worker(["processing"])
    for _ in range(3):
        w.run_until_idle()
    finals = [c for c in calls if c[0] == "finalize"]
    assert len(finals) == 1  # dead member counts as settled; chord fires once
    dead = TaskRecord.objects.filter(status="dead").all()
    assert len(dead) == 1 and dead[0].error_kind == "permanent"


# ----------------------------------------------------------- stats + DLQ CLI
def test_queue_stats_shape():
    add_task.delay(1, 1)
    permanent_task.delay()
    Worker(["tax"]).run_until_idle()
    stats = queue_stats()
    assert stats["dlq_size"] == 1
    assert stats["queues"]["query"]["pending"] == 1
    assert stats["queues"]["query"]["oldest_pending_age_s"] is not None
    assert stats["queues"]["query"]["oldest_pending_age_s"] >= 0.0
    assert stats["queues"]["tax"]["dead"] == 1


def test_dlq_cli_list_requeue_purge(capsys):
    from types import SimpleNamespace

    from django_assistant_bot_tpu.cli import queue_cmd

    rec = permanent_task.delay()
    Worker(["tax"]).run_until_idle()
    rec.refresh()
    assert rec.status == "dead"

    def ns(**kw):
        base = dict(
            action="dlq", subaction="list", queue=None, id=None, status=None, all=False
        )
        base.update(kw)
        return SimpleNamespace(**base)

    assert queue_cmd.run(ns()) == 0
    out = capsys.readouterr().out
    assert "permanent" in out and "permanent_task" in out

    # requeue needs --id or --all
    assert queue_cmd.run(ns(subaction="requeue")) == 1
    assert queue_cmd.run(ns(subaction="requeue", id=rec.id)) == 0
    rec.refresh()
    assert rec.status == "pending" and rec.attempts == 0 and rec.error_kind is None

    Worker(["tax"]).run_until_idle()  # it is permanent: dead again
    rec.refresh()
    assert rec.status == "dead"
    assert queue_cmd.run(ns(subaction="purge")) == 0
    assert TaskRecord.objects.filter(status="dead").count() == 0


# -------------------------------------------------------- chaos sites + metrics
def test_task_raise_site_retries_through_backoff():
    from django_assistant_bot_tpu.serving.faults import (
        FaultInjector,
        reset_global_injector,
        set_global_injector,
    )

    inj = FaultInjector({"task_raise": {"fire_on": [1]}})
    set_global_injector(inj)
    try:
        rec = add_task.delay(20, 22)
        w = Worker(["query"])
        w.run_until_idle()
        w.run_until_idle()
        rec.refresh()
        assert rec.status == "done" and rec.result == 42
        assert rec.attempts == 2  # injected fault burned exactly one attempt
        assert inj.stats()["task_raise"]["fires"] == 1
    finally:
        reset_global_injector()


def test_injected_worker_lost_site_abandons_then_recovers():
    from django_assistant_bot_tpu.serving.faults import (
        FaultInjector,
        reset_global_injector,
        set_global_injector,
    )

    inj = FaultInjector({"task_worker_lost": {"fire_on": [1]}})
    set_global_injector(inj)
    clk = FakeClock()
    try:
        rec = add_task.delay(7, 8)
        w = Worker(["query"], lease_s=10.0, heartbeat_s=0, clock=clk)
        w.run_one()
        rec.refresh()
        assert rec.status == "running"  # abandoned with its lease intact
        clk.advance(11.0)
        w.run_one()
        rec.refresh()
        assert rec.status == "done" and rec.result == 15
        assert w.stats()["worker_lost_aborts"] == 1
        assert w.stats()["reclaimed_leases"] == 1
    finally:
        reset_global_injector()


def test_task_plane_metrics_render_and_parse():
    from types import SimpleNamespace

    from django_assistant_bot_tpu.serving import obs

    add_task.delay(1, 2)
    permanent_task.delay()
    w = Worker(["query", "tax"])
    w.run_until_idle()
    assert w.register_metrics()
    try:
        text = obs.render_prometheus(
            SimpleNamespace(generators={}, autoscalers={}, embedders={})
        )
        fams = obs.parse_prometheus_text(text)
        assert "dabt_queue_depth" in fams
        assert "dabt_queue_dlq_size" in fams
        dlq = [v for n, _, v in fams["dabt_queue_dlq_size"]["samples"]]
        assert dlq == [1.0]
        done = [v for n, _, v in fams["dabt_queue_done_total"]["samples"]]
        assert done == [1.0]
        assert "dabt_queue_dead_letters_total" in fams
    finally:
        obs.set_task_plane_provider(None)


def test_dead_letter_records_and_dumps_flight_event():
    class MiniFlight:
        def __init__(self):
            self.events = []
            self.dumps = []

        def record(self, event, **fields):
            self.events.append((event, fields))

        def dump(self, reason, **context):
            self.dumps.append((reason, context))

    flight = MiniFlight()
    rec = permanent_task.delay()
    Worker(["tax"], flight=flight).run_until_idle()
    rec.refresh()
    assert rec.status == "dead"
    kinds = [(e, f.get("kind")) for e, f in flight.events]
    assert ("task_dead_letter", "permanent") in kinds
    # a dead letter is a crash artifact: the ring is flushed to disk
    assert len(flight.dumps) == 1
    reason, ctx = flight.dumps[0]
    assert reason == "task_dead_letter" and ctx["task_id"] == rec.id


def test_heartbeat_stops_at_max_task_lifetime():
    """A HUNG body must not keep its lease alive forever: past
    max_task_lifetime_s the heartbeat stands down, the lease lapses, and a
    rival worker can reclaim — the pre-heartbeat bound, restored."""
    gate = threading.Event()

    @task(queue="hang", name="hang.stuck")
    def stuck_task():
        gate.wait(15)
        return "zombie result"

    rec = stuck_task.delay()
    w = Worker(["hang"], lease_s=0.5, heartbeat_s=0.1, max_task_lifetime_s=0.2)
    rival = Worker(["hang"], lease_s=300.0, heartbeat_s=0)
    th = threading.Thread(target=w.run_one)
    th.start()
    try:
        # heartbeats cap at ~0.2s, the last renewed lease lapses by ~0.8s
        deadline = time.time() + 6.0
        reclaimed = None
        while time.time() < deadline and reclaimed is None:
            time.sleep(0.15)
            reclaimed = rival.claim()
        assert reclaimed is not None and reclaimed.id == rec.id
        assert w.stats()["heartbeats_capped"] == 1
    finally:
        gate.set()
        th.join(timeout=10)
    # the zombie's completion was discarded (rival owns the lease)
    rec.refresh()
    assert rec.lease_owner == rival.worker_id
    assert rec.result is None
    assert w.stats()["completions_discarded"] == 1
