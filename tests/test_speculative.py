"""Tree-verified speculative decoding: drafter/acceptance semantics, engine
equivalence, paged byte-identity, chaos, and the adaptive controller.

The non-negotiable property is IDENTICAL greedy output with speculation on vs
off — speculation may only change how fast tokens arrive, never which tokens.
On the f32 CPU mesh that equality is exact (property-tested below across
ragged batches, mixed greedy/sampled rows and no-match rows); the bf16 MXU
near-tie caveat lives in docs/SPECULATIVE.md.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from django_assistant_bot_tpu.models import DecoderConfig, llama
from django_assistant_bot_tpu.ops.speculative import (
    SpecController,
    accept_tree,
    breakeven_accept_rate,
    build_prompt_lookup_draft,
    build_tree_draft,
    default_rungs,
    make_tree_spec,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(7))
    return cfg, params


def _prefill_into(cfg, params, prompt, batch=2, max_len=64):
    cache = llama.init_cache(cfg, batch=batch, max_len=max_len, dtype=jnp.float32)
    lengths = jnp.asarray([prompt.shape[1]], jnp.int32)
    logits, ks, vs = llama.prefill(params, cfg, jnp.asarray(prompt), lengths)
    cache = llama.insert_sequences(
        cache, ks, vs, lengths, jnp.asarray([0], jnp.int32)
    )
    return int(jnp.argmax(logits[0])), cache


def _greedy_reference(cfg, params, prompt, n_new):
    tok, cache = _prefill_into(cfg, params, prompt)
    got = [tok]
    tokens = jnp.zeros((2,), jnp.int32)
    active = jnp.asarray([True, False])
    for _ in range(n_new - 1):
        tokens = tokens.at[0].set(got[-1])
        logits, cache = llama.decode_step(params, cfg, tokens, cache, active=active)
        got.append(int(jnp.argmax(logits[0])))
    return got


def _run_tree(cfg, params, cache, tree_tokens, spec, temps=None):
    """verify_tree_step + accept_tree on a [2, T] batch (row 1 inert)."""
    depths = jnp.asarray(spec.depths)
    anc = jnp.asarray(spec.anc_mask)
    logits, tks, tvs = llama.verify_tree_step(
        params, cfg, jnp.asarray(tree_tokens, jnp.int32), cache, depths, anc
    )
    out, n_new, bonus, path_idx, _ = accept_tree(
        logits,
        jnp.asarray(tree_tokens, jnp.int32),
        spec,
        jax.random.key(0),
        temperature=temps if temps is not None else jnp.zeros((2,)),
        top_k=50,
        top_p=jnp.ones((2,)),
    )
    return logits, tks, tvs, out, n_new, bonus, path_idx


# ------------------------------------------------------------------ tree spec
def test_make_tree_spec_layout():
    spec = make_tree_spec(3, 4)
    assert spec.size == 1 + 3 * 4
    assert spec.depths[0] == 0 and spec.parent[0] == 0
    for n in range(3):
        nodes = spec.branch_nodes[n]
        assert spec.parent[nodes[0]] == 0  # depth-1 nodes hang off the root
        for d in range(1, 4):
            assert spec.parent[nodes[d]] == nodes[d - 1]
            assert spec.depths[nodes[d]] == d + 1
        # ancestor chain: every node sees the root, itself, and its branch
        # prefix — and nothing from other branches
        for d in range(4):
            t = nodes[d]
            anc = set(np.nonzero(spec.anc_mask[t])[0].tolist())
            assert anc == {0, *nodes[: d + 1].tolist()}


# ------------------------------------------------------------------- drafter
def test_build_tree_draft_branches_dedup_and_fallbacks():
    """Branches are distinct bigram continuations most-recent-first; duplicate
    first tokens dedup to the most recent occurrence; one spare branch takes
    the unigram; unfilled branches draft rejectable tail garbage."""
    # row 0: bigram (7, 8) occurs thrice; two of the continuations start with
    # 50 (positions 1 and 8 — dedup keeps position 8's), one with 40 (pos 4)
    hist0 = [9, 7, 8, 50, 7, 8, 40, 9, 7, 8, 50, 61, 2, 9, 7, 8, 0, 0, 0, 0]
    #        0  1  2   3  4  5   6  7  8  9  10  11 12 13 14 15  (pending 8 @15)
    # row 1: no bigram for (5, 9); unigram 9 at pos 2 -> draft follows it
    hist1 = [4, 5, 9, 70, 71, 72, 6, 5, 9, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0]
    hist = jnp.asarray([hist0, hist1], jnp.int32)
    lengths = jnp.asarray([15, 8], jnp.int32)
    tokens = jnp.asarray([8, 9], jnp.int32)
    draft = np.asarray(build_tree_draft(hist, lengths, tokens, 3, 3))
    # branch 0: most recent distinct bigram hit (pos 8) -> [50, 61, 2]
    assert draft[0, 0].tolist() == [50, 61, 2]
    # branch 1: next most recent distinct (pos 4) -> [40, 9, 7]
    assert draft[0, 1].tolist() == [40, 9, 7]
    # branch 2: only 2 distinct continuations exist; no unigram strictly
    # before the tail that isn't part of a bigram hit... row 0 has unigram 8
    # at positions 2/5/9 -> fallback branch follows the last one (pos 9)
    assert draft[0, 2].tolist() == [50, 61, 2] or draft[0, 2][0] == hist0[10]
    # row 1: no bigram anywhere -> branch 0 is the unigram continuation
    assert draft[1, 0].tolist() == [70, 71, 72]


def test_width1_tree_matches_linear_prompt_lookup():
    """The width-1 tree IS the old single-candidate prompt-lookup draft."""
    hist = jnp.asarray(
        [[1, 7, 8, 50, 60, 61, 2, 3, 7, 8, 0, 0, 0, 0, 0, 0]], jnp.int32
    )
    lengths = jnp.asarray([9], jnp.int32)
    tokens = jnp.asarray([8], jnp.int32)
    lin = np.asarray(build_prompt_lookup_draft(hist, lengths, tokens, 3))
    tre = np.asarray(build_tree_draft(hist, lengths, tokens, 1, 3))[:, 0]
    assert lin.tolist() == tre.tolist() == [[50, 60, 61]]


# ------------------------------------------------------------- verify/accept
def test_tree_accepts_oracle_branch_at_any_position(tiny):
    """The true greedy continuation planted in a NON-FIRST branch (garbage in
    the others) must be fully accepted, with the correct bonus token."""
    cfg, params = tiny
    prompt = np.array([[1, 5, 9, 17, 3]], np.int32)
    K, N = 3, 3
    ref = _greedy_reference(cfg, params, prompt, K + 2)
    tok, cache = _prefill_into(cfg, params, prompt)
    assert tok == ref[0]
    spec = make_tree_spec(N, K)
    tree = np.zeros((2, spec.size), np.int32)
    tree[0, 0] = ref[0]
    tree[0, spec.branch_nodes[0]] = [499, 498, 497]  # garbage branch
    tree[0, spec.branch_nodes[1]] = ref[1 : K + 1]  # the oracle branch
    tree[0, spec.branch_nodes[2]] = [3, 499, 3]
    _, tks, tvs, out, n_new, bonus, path_idx = _run_tree(
        cfg, params, cache, tree, spec
    )
    assert int(n_new[0]) == K + 1  # every oracle draft accepted + bonus
    assert np.asarray(out)[0, : K + 1].tolist() == ref[1 : K + 2]
    assert int(bonus[0]) == ref[K + 1]
    # the commit path is root + the winning (oracle) branch
    assert np.asarray(path_idx)[0].tolist() == [0, *spec.branch_nodes[1]]


def test_tree_rejects_garbage_and_cache_stays_sound(tiny):
    """All-garbage trees accept nothing; position-0 output equals the plain
    step's, and after committing the path the cache supports continued plain
    decoding that tracks the non-speculative reference exactly."""
    cfg, params = tiny
    prompt = np.array([[2, 11, 4, 30]], np.int32)
    n_total = 6
    ref = _greedy_reference(cfg, params, prompt, n_total)
    tok, cache = _prefill_into(cfg, params, prompt)
    K, N = 3, 2
    spec = make_tree_spec(N, K)
    tree = np.full((2, spec.size), 499, np.int32)
    tree[0, 0] = tok
    tree[1, :] = 0
    _, tks, tvs, out, n_new, bonus, path_idx = _run_tree(
        cfg, params, cache, tree, spec
    )
    assert int(n_new[0]) == 1
    assert int(out[0, 0]) == ref[1]
    cache = llama.commit_tree_path(cache, tks, tvs, path_idx)
    cache = cache._replace(
        lengths=cache.lengths.at[0].set(int(cache.lengths[0]) + 1)
    )
    got = [tok, int(out[0, 0])]
    tokens = jnp.zeros((2,), jnp.int32)
    active = jnp.asarray([True, False])
    for _ in range(n_total - 2):
        tokens = tokens.at[0].set(got[-1])
        lg, cache = llama.decode_step(params, cfg, tokens, cache, active=active)
        got.append(int(jnp.argmax(lg[0])))
    assert got == ref


def test_accept_tree_sampled_rows_take_position_zero():
    """temperature>0 rows never accept drafts (n_new==1) and their token is a
    valid sample of position-0 logits."""
    V = 32
    spec = make_tree_spec(2, 3)
    logits = jnp.full((1, spec.size, V), -30.0)
    logits = logits.at[0, 0, 5].set(10.0)  # position-0 mass on token 5
    tree = jnp.full((1, spec.size), 5, jnp.int32)
    out, n_new, bonus, _, _ = accept_tree(
        logits,
        tree,
        spec,
        jax.random.key(2),
        temperature=jnp.asarray([0.7]),
        top_k=10,
        top_p=jnp.asarray([0.9]),
    )
    assert int(n_new[0]) == 1
    assert int(out[0, 0]) == 5 and int(bonus[0]) == 5


def test_verify_tree_is_read_only_wrt_cache(tiny):
    """The tree verify must not mutate the cache — the accepted-path commit
    is the ONLY write (what lets the paged layout carry speculation)."""
    cfg, params = tiny
    prompt = np.array([[1, 5, 9, 17, 3]], np.int32)
    tok, cache = _prefill_into(cfg, params, prompt)
    k_before = np.asarray(cache.k)
    spec = make_tree_spec(2, 2)
    tree = np.zeros((2, spec.size), np.int32)
    tree[0, 0] = tok
    llama.verify_tree_step(
        params, cfg, jnp.asarray(tree), cache,
        jnp.asarray(spec.depths), jnp.asarray(spec.anc_mask),
    )
    assert np.array_equal(k_before, np.asarray(cache.k))


# ---------------------------------------------------------------- controller
def test_controller_upshift_downshift_under_forced_accept_rates():
    ctl = SpecController(
        rungs=default_rungs(4, 6), probe_every=8, explore_every=1000
    )
    # measured costs: wide trees are expensive, narrow ones cheap
    ctl.note_cost((4, 6), 3.0)
    ctl.note_cost((2, 6), 2.0)
    ctl.note_cost((1, 6), 1.5)
    ctl.note_cost((1, 3), 1.2)
    # force per-rung acceptance: the wide tree's extra candidates land
    # (p ~ 1.0) while the single branch only half-lands — the width pays
    # its 2x cost premium and the controller UPSHIFTS to it
    for _ in range(50):
        ctl.note_tick(accepted=6, depth=6, rung=(4, 6))
        ctl.note_tick(accepted=3, depth=6, rung=(2, 6))
        ctl.note_tick(accepted=3, depth=6, rung=(1, 6))
        ctl.note_tick(accepted=2, depth=3, rung=(1, 3))
    assert ctl.rung() == (4, 6)
    assert not ctl.disabled
    # the wide tree's acceptance collapses while the single branch keeps
    # half-landing: DOWNSHIFT off the wide rung
    for _ in range(50):
        ctl.note_tick(accepted=0, depth=6, rung=(4, 6))
    rung = ctl.rung()
    assert rung is not None and rung != (4, 6)
    # every rung collapses: disable entirely — below breakeven, a verify
    # forward can never pay for itself
    for r in [(2, 6), (1, 6)]:
        for _ in range(80):
            ctl.note_tick(accepted=0, depth=6, rung=r)
    for _ in range(80):
        ctl.note_tick(accepted=0, depth=3, rung=(1, 3))
    assert ctl.rung() is None
    assert ctl.disabled
    stats = ctl.stats()
    assert stats["spec_auto_disabled"] is True


def test_controller_explores_wider_rung_periodically():
    ctl = SpecController(rungs=[(4, 4), (1, 4)], explore_every=5)
    ctl.note_cost((4, 4), 2.0)
    ctl.note_cost((1, 4), 1.2)
    # wide rung measured bad, narrow rung good -> narrow is the workhorse
    for _ in range(60):
        ctl.note_tick(accepted=0, depth=4, rung=(4, 4))
        ctl.note_tick(accepted=3, depth=4, rung=(1, 4))
    picks = [ctl.rung() for _ in range(10)]
    assert picks.count((4, 4)) == 2  # one exploration tick per explore_every
    assert all(p in ((1, 4), (4, 4)) for p in picks)


def test_controller_probes_while_disabled_and_reenables():
    ctl = SpecController(rungs=[(1, 4)], probe_every=5)
    ctl.note_cost((1, 4), 2.0)
    for _ in range(100):
        ctl.note_tick(accepted=0, depth=4)
    assert ctl.rung() is None and ctl.disabled
    # plain ticks until the probe cadence elapses, then one speculative probe
    fired = [ctl.rung() for _ in range(5)]
    assert fired[:4] == [None] * 4
    assert fired[4] == (1, 4)
    # probe evidence of a workload shift (context-quoting traffic arrived)
    for _ in range(60):
        ctl.note_tick(accepted=4, depth=4)
    assert ctl.rung() == (1, 4)
    assert not ctl.disabled


def test_breakeven_accept_rate_math():
    assert breakeven_accept_rate(1.0, 6) == 0.0
    assert breakeven_accept_rate(0.5, 6) == 0.0
    assert breakeven_accept_rate(8.0, 6) == 1.0
    p = breakeven_accept_rate(2.0, 6)
    assert 0.0 < p < 1.0
    # the expected tokens/tick at the breakeven rate equals the cost ratio
    e = (1 - p ** 7) / (1 - p)
    assert abs(e - 2.0) < 1e-6
    # deeper trees break even at lower acceptance
    assert breakeven_accept_rate(2.0, 12) < p


def test_default_rungs_ladder():
    assert default_rungs(4, 6) == [(4, 6), (2, 6), (1, 6), (1, 3)]
    assert default_rungs(1, 1) == [(1, 1)]


# ---------------------------------------------------------------- engine level
def _spec_engine(cfg, params, tok, *, spec, mesh=None, **kw):
    from django_assistant_bot_tpu.serving import GenerationEngine

    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("prefix_cache_size", 0)
    if spec:
        # probe_every=1: the controller may disable on low acceptance but
        # then re-probes EVERY tick, so the speculative path (and its paged
        # commits) stays exercised for the whole equivalence run
        kw.setdefault("spec_probe_every", 1)
    return GenerationEngine(
        cfg, params, tok, mesh=mesh, speculative=spec, **kw
    )


def _run_engine(eng, jobs, timeout=600):
    eng.start()
    try:
        futs = [
            eng.submit(ids, max_tokens=mt, temperature=t) for ids, mt, t in jobs
        ]
        out = [f.result(timeout=timeout).token_ids for f in futs]
        stats = eng.tick_stats()
    finally:
        eng.stop(drain_timeout_s=60.0)
    return out, stats


def test_spec_engine_greedy_equivalence_property():
    """Pinned-seed equivalence property on the default (paged) plane, no
    mesh: ragged prompts (repetitive / quoting / no-match), mixed greedy and
    sampled rows, several seeds — greedy outputs must be identical with
    speculation on vs off, and the speculative engine must report the paged
    layout as effective."""
    from django_assistant_bot_tpu.serving import ByteTokenizer

    tok = ByteTokenizer()
    cfg = DecoderConfig.tiny()
    for seed in (0, 3):
        params = llama.init(cfg, jax.random.PRNGKey(seed))
        prompts = [
            "abc abc abc abc abc abc",
            "the cat sat on the mat the cat sat on the",
            "xyz",
            "quote me: pay invoices in the portal. pay invoices in the",
        ]
        # greedy rows interleaved with one sampled row (index 2)
        jobs = [
            (tok.encode(p), 16, 0.0 if i != 2 else 0.9)
            for i, p in enumerate(prompts)
        ]
        plain, _ = _run_engine(
            _spec_engine(cfg, params, tok, spec=0, lookahead=1, burst=4), jobs
        )
        spec, stats = _run_engine(
            _spec_engine(cfg, params, tok, spec=4, spec_width=2, lookahead=1),
            jobs,
        )
        for i in range(len(jobs)):
            if jobs[i][2] == 0.0:  # greedy rows: identical token ids
                assert spec[i] == plain[i], (seed, i)
            else:  # sampled rows: just complete within bounds
                assert 1 <= len(spec[i]) <= 16
        assert stats["spec_drafted"] > 0
        assert stats["kv"]["kv_layout_effective"] == "paged"


def test_spec_engine_paged_vs_legacy_byte_identity():
    """The same speculative workload on the paged plane and the legacy slot
    cache must produce identical greedy tokens — the paged tree commit is a
    layout change, never a numerics change.  (The legacy arm pins
    decode_kv_chunk to the paged arm's page size so any plain fallback ticks
    run the byte-identical chunked read, per the PR 6 contract.)"""
    from django_assistant_bot_tpu.serving import ByteTokenizer

    tok = ByteTokenizer()
    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(11))
    jobs = [
        (tok.encode("ab ab ab ab ab ab ab"), 12, 0.0),
        (tok.encode("context: x y z. context: x y"), 12, 0.0),
    ]
    paged_eng = _spec_engine(
        cfg, params, tok, spec=3, spec_width=2, max_seq_len=128,
        decode_kv_chunk=32, kv_layout="paged",
    )
    page = paged_eng.kv_page_size
    assert paged_eng.paged and page == 32
    paged, pstats = _run_engine(paged_eng, jobs)
    legacy, _ = _run_engine(
        _spec_engine(
            cfg, params, tok, spec=3, spec_width=2, max_seq_len=128,
            decode_kv_chunk=page, kv_layout="legacy",
        ),
        jobs,
    )
    assert paged == legacy
    assert pstats["kv"]["kv_layout_requested"] == "paged"
    assert pstats["kv"]["kv_layout_effective"] == "paged"
    assert pstats["spec_drafted"] > 0


def test_spec_k_bounded_against_max_seq_len():
    """An oversized K must fail at engine construction with a clear error,
    not crash opaquely inside the jitted tick (r5 review finding)."""
    from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(5))
    with pytest.raises(ValueError, match="speculative=40 .*too large"):
        GenerationEngine(
            cfg, params, ByteTokenizer(), max_slots=2, max_seq_len=64,
            speculative=40,
        )


# --------------------------------------------------------------------- chaos
def test_tick_raise_mid_verify_restart_leaves_page_pool_clean():
    """An engine-fatal fault fired during a speculative verify dispatch:
    crash-only restart must reset the page plane (every page back on the
    free list, block tables unallocated) and the salvaged/token-less
    requests must still complete on the rebuilt pool."""
    from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine
    from django_assistant_bot_tpu.serving.faults import FaultInjector

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(9))
    tok = ByteTokenizer()
    inj = FaultInjector({})
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=96, speculative=3,
        spec_width=2, spec_probe_every=1, prefix_cache_size=0, faults=inj,
    )
    assert eng.paged
    eng.start()
    try:
        # let the engine go live, then arm: the NEXT dispatch — a speculative
        # verify tick for the in-flight request — raises mid-verify
        f0 = eng.submit(tok.encode("ab ab ab ab"), max_tokens=6, temperature=0.0)
        f0.result(timeout=120)
        inj.arm("tick_raise")
        futs = [
            eng.submit(tok.encode("cd cd cd cd"), max_tokens=6, temperature=0.0)
            for _ in range(2)
        ]
        done = 0
        for f in futs:
            try:
                r = f.result(timeout=120)
                assert len(r.token_ids) >= 1
                done += 1
            except RuntimeError:
                pass  # past-first-token requests fail cleanly on restart
        assert done >= 1
        assert eng.engine_restarts == 1
        assert eng.healthy()
        # pool clean on the LIVE engine: every page back on the free list,
        # every block table unallocated — the restart (and per-finish frees)
        # leaked nothing, no shutdown sweep involved
        kv = eng.kv_stats()
        assert kv["kv_pages_used"] == 0
        assert kv["kv_pages_free"] == eng._kv_pool.n_pages
        assert all(not pages for pages in eng._slot_pages)
    finally:
        eng.stop(drain_timeout_s=60.0)


def test_nan_logits_quarantine_frees_spec_slot_pages():
    """A poisoned speculative tick quarantines ONE slot: its pages return to
    the pool while the batch keeps decoding."""
    from django_assistant_bot_tpu.serving import ByteTokenizer, GenerationEngine
    from django_assistant_bot_tpu.serving.faults import FaultInjector

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(10))
    tok = ByteTokenizer()
    inj = FaultInjector({})
    # lookahead=0: every tick is processed the iteration it issues, so the
    # armed fault deterministically lands on the NEW wave's first live tick
    # (with a pipeline it can fire on a stale-epoch ref of the finished
    # warm request and poison nobody)
    eng = GenerationEngine(
        cfg, params, tok, max_slots=2, max_seq_len=96, speculative=3,
        spec_width=2, spec_probe_every=1, prefix_cache_size=0, faults=inj,
        lookahead=0,
    ).start()
    try:
        f0 = eng.submit(tok.encode("ab ab ab ab"), max_tokens=8, temperature=0.0)
        f0.result(timeout=120)
        inj.arm("nan_logits")
        futs = [
            eng.submit(tok.encode("ef ef ef ef"), max_tokens=8, temperature=0.0)
            for _ in range(2)
        ]
        outcomes = []
        for f in futs:
            try:
                outcomes.append(("ok", len(f.result(timeout=120).token_ids)))
            except Exception as e:
                outcomes.append(("poisoned", type(e).__name__))
        assert ("poisoned", "RequestPoisoned") in outcomes
        assert any(kind == "ok" for kind, _ in outcomes)
        assert eng.poisoned_requests == 1
        assert eng.engine_restarts == 0  # quarantine, not a restart
    finally:
        eng.stop(drain_timeout_s=60.0)
    kv = eng.kv_stats()
    assert kv["kv_pages_used"] == 0


# ----------------------------------------------------------------- slow suite
@pytest.mark.slow
def test_spec_engine_greedy_bit_identical_and_accepts(mesh8):
    """The speculative engine must produce BIT-IDENTICAL greedy output to the
    plain engine on the f32 CPU mesh, and on a repetitive prompt it must
    actually accept drafts (the counters prove the fast path ran, not a
    silent fallback).  Previously xfail'd: the old linear verify program let
    the SPMD partitioner sequence-shard its K+1 dim, which this jaxlib
    miscompiles (input tokens doubled across the seq axis); the tree verify
    forward pins that dim replicated — root-caused and fixed, so this
    passes on its merits."""
    from django_assistant_bot_tpu.parallel import shard_pytree
    from django_assistant_bot_tpu.serving import ByteTokenizer

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(3))
    with mesh8:
        params = shard_pytree(params, llama.logical_axes(cfg), mesh8)
    tok = ByteTokenizer()
    prompts = [
        "abc abc abc abc abc abc",
        "the cat sat on the mat the cat sat on the",
        "xyz",
    ]
    jobs = [(tok.encode(p), 24, 0.0) for p in prompts]

    plain, _ = _run_engine(
        _spec_engine(cfg, params, tok, spec=0, mesh=mesh8, lookahead=1, burst=4),
        jobs,
    )
    spec, stats = _run_engine(
        _spec_engine(cfg, params, tok, spec=5, spec_width=2, mesh=mesh8,
                     lookahead=1),
        jobs,
    )
    assert spec == plain  # speculation must never change greedy output
    assert stats["spec_drafted"] > 0
    # a tiny random model still loops enough for SOME acceptance on these
    # prompts; zero would mean the draft path is broken end to end
    assert stats["spec_accepted"] > 0, stats


@pytest.mark.slow
def test_spec_engine_mixed_temperature_batch_and_json_rejected(mesh8):
    """Sampled requests ride the same spec ticks (one token per tick) and
    json_format is rejected up front."""
    from django_assistant_bot_tpu.parallel import shard_pytree
    from django_assistant_bot_tpu.serving import ByteTokenizer

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(4))
    with mesh8:
        params = shard_pytree(params, llama.logical_axes(cfg), mesh8)
    tok = ByteTokenizer()
    eng = _spec_engine(
        cfg, params, tok, spec=4, spec_width=2, mesh=mesh8, max_seq_len=64
    ).start()
    try:
        with pytest.raises(ValueError, match="speculative"):
            eng.submit(tok.encode("x"), max_tokens=4, json_format=True)
        futs = [
            eng.submit(tok.encode("ab ab ab ab"), max_tokens=10, temperature=t)
            for t in (0.0, 0.9, 0.0)
        ]
        results = [f.result(timeout=600) for f in futs]
        assert all(len(r.token_ids) >= 1 for r in results)
        assert all(r.completion_tokens <= 10 for r in results)
    finally:
        eng.stop(drain_timeout_s=60.0)


@pytest.mark.slow
def test_spec_engine_with_prefix_cache_matches_plain(mesh8):
    """Speculation composed with the prefix KV cache (the production RAG
    combination: shared context prefix + greedy answer) must still match the
    plain engine's greedy output bit-for-bit on the f32 mesh, and the prefix
    cache must actually hit.  Previously xfail'd — same partitioner root
    cause as test_spec_engine_greedy_bit_identical_and_accepts."""
    from django_assistant_bot_tpu.parallel import shard_pytree
    from django_assistant_bot_tpu.serving import ByteTokenizer

    cfg = DecoderConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(6))
    with mesh8:
        params = shard_pytree(params, llama.logical_axes(cfg), mesh8)
    tok = ByteTokenizer()
    shared = "context: pay invoices in the portal. " * 2
    prompts = [shared + "q1?", shared + "q2 about invoices?"]
    # the byte tokenizer has no merges: [bos] + bytes(shared) is exactly the
    # shared leading block of both prompts
    plen = len(tok.encode(shared))

    def run(spec):
        eng = _spec_engine(
            cfg, params, tok, spec=spec, spec_width=2, mesh=mesh8,
            max_slots=2, max_seq_len=160, prefix_cache_size=4,
            prefix_min_tokens=8,
        ).start()
        try:
            outs = []
            for p in prompts:  # sequential: turn 2 hits turn 1's prefix
                f = eng.submit(
                    tok.encode(p), max_tokens=16, temperature=0.0,
                    prefix_len=plen,
                )
                outs.append(f.result(timeout=600).token_ids)
            hits = eng.prefix_hits
            stats = eng.tick_stats()
        finally:
            eng.stop(drain_timeout_s=60.0)
        return outs, hits, stats

    plain, _, _ = run(0)
    spec, hits, stats = run(5)
    assert spec == plain
    assert hits >= 1  # the shared context block was reused from the cache
    # the spec path must have actually run (not a silent plain fallback)
    assert stats.get("spec_drafted", 0) > 0, stats


def test_healthz_carries_spec_gauges():
    """/healthz exposes the adaptive controller per generator (accept EMA,
    rung, auto/load-disable) so operators can tell a disabled mechanism from
    a broken one without shelling into tick_stats."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from django_assistant_bot_tpu.serving.registry import ModelRegistry, ModelSpec
    from django_assistant_bot_tpu.serving.server import create_app

    registry = ModelRegistry(
        {
            "tiny-spec": ModelSpec(
                name="tiny-spec", kind="decoder", tiny=True, max_slots=2,
                max_seq_len=256, speculative=3, spec_width=2,
            )
        }
    )

    async def drive():
        client = TestClient(TestServer(create_app(registry)))
        await client.start_server()
        try:
            r = await client.get("/healthz")
            body = await r.json()
            g = body["generators"]["tiny-spec"]
            spec = g["spec"]
            for key in (
                "spec_accept_rate", "spec_accept_ema", "spec_rung_accept_emas",
                "spec_tree_width", "spec_tree_depth", "spec_auto_disabled",
                "spec_load_disabled", "spec_skipped_load", "spec_skipped_accept",
            ):
                assert key in spec, key
            assert g["kv"]["kv_layout_effective"] == "paged"
            # the scheduler's stats carry the same gauge (bind_spec): load-
            # vs acceptance-disable side by side where queue pressure lives
            assert "spec_disabled" in g["sched"]
        finally:
            await client.close()

    try:
        asyncio.new_event_loop().run_until_complete(drive())
    finally:
        registry.stop()
